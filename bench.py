"""Benchmark driver — prints ONE JSON line with the headline metric.

Headline: Yahoo Streaming Benchmark (YSB) throughput in tuples/sec on one chip —
the north-star metric of BASELINE.json. The pipeline is the full YSB chain
(event source -> filter(1/3) -> campaign join -> keyed tumbling TB window count ->
device reduce sink) compiled as ONE XLA program per micro-batch, with event
generation fused on device (the reference replays an in-memory dataset from its
source threads; data never leaves the chip here either).

vs_baseline compares against the reference CUDA backend's best published number,
16.6 M tuples/s stateless MapGPU (BASELINE.md; the keyed-stateful CUDA peak is
11.8 M t/s) — the bar the TPU backend must beat. Secondary metrics (stateless
map+filter config, per-step latency ~ p99 window-result latency bound) go to stderr.
"""

import json
import os
import sys
import time

BATCH = int(os.environ.get("WF_BENCH_BATCH", 1 << 20))
STEPS = int(os.environ.get("WF_BENCH_STEPS", 40))
BASELINE_TPS = 16.6e6

# ---------------------------------------------------------------------------
# Capture persistence — outage-proofing the round's perf evidence.
#
# The tunneled dev chip has gone down mid-session in two of three rounds,
# erasing otherwise-green captures (r01, r03). Every successful measurement is
# therefore persisted immediately (number + UTC timestamp + device fingerprint
# + methodology tag) to bench_captures/last_good.json; when the device is
# unreachable at capture time, main() degrades to emitting the last good
# headline marked "stale": true alongside the diagnostic, instead of rc=2 and
# nothing.
# ---------------------------------------------------------------------------
CAPTURE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "bench_captures", "last_good.json")


def _utcnow() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _device_fingerprint() -> str:
    """Device string if a backend is already up; never initializes one (a
    fingerprint attempt must not itself hang — this environment's
    sitecustomize pre-imports jax, and the first devices() call on a dead
    tunnel blocks forever, so "jax imported" alone is NOT safe to query)."""
    mod = sys.modules.get("jax")
    if mod is None:
        return "unknown (jax not initialized)"
    try:
        from jax._src import xla_bridge
        if not xla_bridge._backends:          # nothing initialized yet
            # a CPU-pinned process can't hang on the tunnel: initializing the
            # backend for the fingerprint is safe (fixes the r04 capture that
            # stamped itself "unknown (no backend initialized)")
            if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
                return str(mod.devices()[0])
            return "unknown (no backend initialized)"
        return str(mod.devices()[0])          # cached list — no device I/O
    except Exception:  # noqa: BLE001 — fingerprinting must never kill a capture
        return "unknown (device query failed)"


def _load_store() -> dict:
    try:
        with open(CAPTURE_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"captures": {}, "headline": None}


def _save_store(store: dict) -> None:
    os.makedirs(os.path.dirname(CAPTURE_PATH), exist_ok=True)
    tmp = CAPTURE_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(store, f, indent=1, sort_keys=True)
    os.replace(tmp, CAPTURE_PATH)


def _stamp(payload: dict, methodology: str) -> dict:
    return dict(payload, ts=_utcnow(), device=_device_fingerprint(),
                methodology=methodology)


def record(name: str, payload: dict, methodology: str = "in-session") -> None:
    """Persist one successful measurement under ``name`` (atomic replace)."""
    store = _load_store()
    store.setdefault("captures", {})[name] = _stamp(payload, methodology)
    _save_store(store)


def record_headline(headline: dict, methodology: str = "driver-capture") -> None:
    store = _load_store()
    store["headline"] = _stamp(headline, methodology)
    _save_store(store)


def emit_stale_headline(diagnostic: str) -> int:
    """Device unreachable: print the last good headline marked stale (rc=0) so
    the round's evidence degrades to "stale but real" instead of "absent";
    rc=2 only when no good capture has ever been persisted."""
    store = _load_store()
    head = store.get("headline")
    print(f"DEVICE UNREACHABLE: {diagnostic}\n"
          f"(a 4KB device_put+sync failed — the tunnel/chip is down, not the "
          f"framework; rerun when the link recovers)", file=sys.stderr)
    if not head:
        return 2
    out = {k: head[k] for k in ("metric", "value", "unit", "vs_baseline")}
    out["stale"] = True
    out["captured_at"] = head.get("ts")
    out["captured_on"] = head.get("device")
    out["methodology"] = head.get("methodology")
    out["staleness_reason"] = "device unreachable at capture time"
    print(f"emitting last good capture from {head.get('ts')} "
          f"({head.get('methodology')}, {head.get('device')}) marked stale",
          file=sys.stderr)
    print(json.dumps(out))
    return 0


# Roofline peaks: overridable because the fingerprint string does not encode
# the SKU's datasheet. Defaults = TPU v5e (819 GB/s HBM, 197 bf16 TFLOP/s).
HBM_PEAK_GBPS = float(os.environ.get("WF_HBM_PEAK_GBPS", 819))
PEAK_TFLOPS = float(os.environ.get("WF_PEAK_TFLOPS", 197))


def _arg_specs(args):
    """ShapeDtypeStruct skeleton of ``args`` — captured BEFORE a donating loop
    runs (metadata only), usable for lowering AFTER it. One implementation,
    shared with the hermetic perf gate."""
    from windflow_tpu.analysis.perfgate import _arg_specs as impl
    return impl(args)


def _roofline(step_jitted, args, step_s):
    """Roofline utilization for one compiled step (VERDICT r05 ask #7):
    XLA's own cost model (``compiled.cost_analysis()``) supplies bytes
    accessed + FLOPs per step; divided by the measured step time and the
    device peaks that yields achieved GB/s / GFLOP/s and utilization
    percentages — "device-bound" as a number, not prose.

    Called AFTER the timed loop (with ``_arg_specs`` captured beforehand): the
    AOT lower().compile() needed to read the cost model is a second compile of
    the same program, and on the flaky tunneled link that must not sit between
    the healthcheck and the measurement — if the link dies here, the
    throughput number has already landed."""
    try:
        from windflow_tpu.analysis.perfgate import _cost_of
        cost = _cost_of(step_jitted.lower(*args).compile())
        flops, bts = cost["flops"], cost["bytes_accessed"]
    except Exception as e:  # noqa: BLE001 — cost model is backend-dependent
        return {"error": f"cost_analysis unavailable: {e}"}
    gbps = bts / step_s / 1e9
    gfls = flops / step_s / 1e9
    out = {
        "bytes_per_step": bts,
        "flops_per_step": flops,
        "achieved_hbm_gbps": round(gbps, 2),
        "hbm_utilization_pct": round(100 * gbps / HBM_PEAK_GBPS, 2),
        "achieved_gflops": round(gfls, 2),
        "mxu_utilization_pct": round(100 * gfls / (PEAK_TFLOPS * 1e3), 3),
        "peaks": {"hbm_gbps": HBM_PEAK_GBPS, "tflops": PEAK_TFLOPS},
    }
    if gbps > HBM_PEAK_GBPS:
        # cost_analysis() counts LOGICAL tensor traffic; when the step is fast
        # enough that the implied bandwidth exceeds the physical peak, most of
        # that traffic stayed in VMEM/fused registers and never touched HBM.
        # Flag it so nobody publishes a >100% "utilization" as a measurement.
        out["model_overcount"] = ("bytes-accessed is XLA's logical cost model; "
                                  "implied bandwidth exceeds the HBM peak, so "
                                  "the working set is VMEM-resident/fused — "
                                  "not a bandwidth measurement")
    return out


def _chain_metrics(chain, step_s: float = None, capacity: int = None) -> dict:
    """Graph-level metrics snapshot of one bench chain — attached to every
    persisted capture so BENCH_r*.json carry per-stage evidence (operator
    structure, routing, counters, service-time percentiles) instead of one
    opaque number. The cursor loop bypasses ``chain.push``, so the measured
    per-step time is fed to the entry op's Stats_Record first — the same
    attribution convention as CompiledChain.push (ONE fused program, one
    launch sample credited to the entry op).

    ``stage_costs`` rides along: per-operator XLA cost-analysis rows
    (flops / bytes accessed, ``analysis/perfgate.py::stage_costs``) — the
    device-free half of the evidence, so a tunnel-down round still records
    WHICH stage a cost change landed in."""
    from windflow_tpu.observability import MetricsRegistry
    if step_s is not None and chain.ops:
        chain.ops[0].get_StatsRecords()[0].record_launch(step_s)
    reg = MetricsRegistry("bench")
    reg.register_chain("chain", chain)
    snap = reg.snapshot()
    try:
        from windflow_tpu.analysis.perfgate import stage_costs
        snap["stage_costs"] = stage_costs(chain, capacity or BATCH)
    except Exception as e:  # noqa: BLE001 — cost rows must never kill a capture
        snap["stage_costs"] = [{"error": f"{type(e).__name__}: {e}"}]
    return snap


def _cursor_bench(chain, src, batch: int = None):
    """The one recipe for a timed chain bench: shared device-cursor step +
    lowering specs (a ShapeDtypeStruct cursor spec — no device array is
    materialized over the flaky link just to read a shape)."""
    import jax
    import jax.numpy as jnp
    from windflow_tpu.benchmarks import device_cursor_step
    step = device_cursor_step(chain, src, batch or BATCH)
    specs = _arg_specs((tuple(chain.states),
                        jax.ShapeDtypeStruct((), jnp.int32)))
    return step, specs


def _bench_loop(step, states, n_steps, reps: int = 1):
    """Time ``n_steps`` async-dispatched steps of a device-cursor step
    (``step(states, cur) -> (states, cur + batch, out)`` — see
    ``windflow_tpu.benchmarks.device_cursor_step``); with ``reps`` > 1 return
    the median rep (dispatch-pipelining jitter on the tunneled link is large
    when steps are fast). The caller's source must cover reps*n_steps+1
    batches. The cursor stays on device, so no bench row carries a per-step
    host-scalar upload."""
    import jax
    import jax.numpy as jnp
    cur = jnp.asarray(0, jnp.int32)
    # warmup/compile
    states, cur, out = step(states, cur)
    jax.block_until_ready(out)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(n_steps):
            states, cur, out = step(states, cur)
            # async dispatch: the host enqueues step i+1 while the device runs i
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2], states


def _health_compile_stats(steps: int = 8, batch: int = 4096) -> dict:
    """Hermetic compile-ledger stats for the trend (device-free, the
    ``cost`` convention): drive a small YSB chain through the real
    ``CompiledChain.push`` path with a private health ledger active and
    report compiles per driven step — the dispatch-amortization /
    trace-stability number ``bench_trend.py`` renders as its
    compiles/step column, moving even in tunnel-down rounds.  An
    unexpected-retrace count other than zero here means a warm executable
    recompiled mid-drive — a perf regression no throughput row would
    attribute."""
    from windflow_tpu.benchmarks import ysb
    from windflow_tpu.observability import device_health as _dh
    from windflow_tpu.runtime.pipeline import CompiledChain
    panes_per_batch = max(batch // (ysb.EVENTS_PER_TICK * ysb.WIN_LEN), 1) + 1
    src = ysb.make_source(total=(steps + 1) * batch)
    ops = ysb.make_ops(pane_capacity=2 * panes_per_batch + 2,
                       max_wins=panes_per_batch + 64)
    prev = _dh.get_active()
    led = _dh.HealthLedger(cost_analysis=False)   # counters only: fast
    _dh.set_active(led)
    try:
        chain = CompiledChain(ops, src.payload_spec(), batch_capacity=batch,
                              event_time=False)
        n = 0
        for b in src.batches(batch):
            if n >= steps:
                break
            chain.push(b)
            n += 1
    finally:
        _dh.set_active(prev)
    return {"compiles": led.traces,
            "retraces_unexpected": led.retraces_unexpected,
            "steps": n,
            "compiles_per_step": round(led.traces / max(n, 1), 4)}


def _shard_recovery_stats(shards: int = 4, total_batches: int = 24,
                          batch: int = 4096) -> dict:
    """Hermetic shard-local-recovery numbers for the trend (device-free,
    the ``cost``/``health`` convention): drive a small YSB chain through
    the SHARDED supervisor with one injected ``shard.kill``, and report the
    killed shard's measured restore+replay duration (``last_recovery_s``
    off the shard report) plus the byte-identity verdict vs an unsharded
    run — the per-shard-recovery-time column ``bench_trend.py`` renders,
    moving even in tunnel-down rounds."""
    import numpy as np
    from windflow_tpu.benchmarks import ysb
    from windflow_tpu.operators.sink import Sink
    from windflow_tpu.runtime.faults import (FaultInjector, FaultPlan,
                                             FaultSpec)
    from windflow_tpu.runtime.supervisor import SupervisedPipeline

    panes_per_batch = max(batch // (ysb.EVENTS_PER_TICK * ysb.WIN_LEN), 1) + 1

    def run(n_shards, faults=None):
        got = []

        def cb(view):
            if view is None:
                return
            got.extend(zip(view["key"].tolist(), view["id"].tolist()))
        src = ysb.make_source(total=total_batches * batch)
        ops = ysb.make_ops(pane_capacity=2 * panes_per_batch + 2,
                           max_wins=panes_per_batch + 64)
        p = SupervisedPipeline(src, ops, Sink(cb), batch_size=batch,
                               checkpoint_every=4, max_restarts=4,
                               backoff_base=0.0, shards=n_shards,
                               # hermetic drill: a caller's WF_RESHARD must
                               # not leak a live reshard into the recovery
                               # timing (the perfgate event_time=False rule)
                               reshard=False,
                               # ownership follows the WINDOW key (the
                               # ysb_rekey campaign), not the ingest key
                               shard_key=lambda t:
                                   t.ad_id // ysb.ADS_PER_CAMPAIGN,
                               faults=faults)
        p.run()
        return sorted(got), p

    oracle, _ = run(1)
    kill = FaultInjector(FaultPlan(
        [FaultSpec("shard.kill", where={"shard": shards // 2},
                   max_fires=1)], seed=7))
    sharded, p = run(shards, faults=kill)
    rep = p.shard_report()
    killed = rep[shards // 2]
    return {"shards": int(shards),
            "recovery_ms": round(killed["last_recovery_s"] * 1e3, 3),
            "killed_restarts": killed["restarts"],
            "kill_exact": sharded == oracle}


def _slo_stats(total_batches: int = 48, batch: int = 4096) -> dict:
    """Hermetic SLO-engine numbers for the trend (device-free, the
    ``health``/``shard`` convention): drive a small YSB chain through a
    monitored run with the default-shaped SLO spec set active at a fast
    Reporter cadence, and report the worst burn rate + page count off the
    final snapshot's ``slo`` section — the pages/run column
    ``bench_trend.py`` renders beside compiles/step.  A healthy engine run
    pages zero times; a nonzero count here means the default objectives no
    longer hold on the bench box (a latency/drop regression no throughput
    row would attribute)."""
    import json as _json
    import tempfile
    from windflow_tpu.benchmarks import ysb
    from windflow_tpu.observability import MonitoringConfig
    from windflow_tpu.operators.sink import Sink
    from windflow_tpu.runtime.pipeline import Pipeline

    panes_per_batch = max(batch // (ysb.EVENTS_PER_TICK * ysb.WIN_LEN), 1) + 1
    with tempfile.TemporaryDirectory(prefix="wf_bench_slo_") as mon:
        cfg = MonitoringConfig(out_dir=mon, interval_s=0.05, slo=True,
                               e2e_sample_every=1)
        src = ysb.make_source(total=total_batches * batch)
        ops = ysb.make_ops(pane_capacity=2 * panes_per_batch + 2,
                           max_wins=panes_per_batch + 64)
        Pipeline(src, ops, Sink(lambda v: None), batch_size=batch,
                 monitoring=cfg).run()
        # worst burn over the WHOLE series, not the final tick: a mid-run
        # burn that recovered before the run ended would read as ~0 off
        # snapshot.json alone (pages are cumulative, so the last section
        # carries the run total)
        secs = []
        with open(os.path.join(mon, "snapshots.jsonl")) as f:
            for line in f:
                s = _json.loads(line).get("slo")
                if s:
                    secs.append(s)
        if not secs:
            with open(os.path.join(mon, "snapshot.json")) as f:
                secs = [_json.load(f).get("slo") or {}]
    worst = 0.0
    pages = 0
    for row in secs[-1].values():
        pages += int(row.get("pages", 0))
    for sec in secs:
        for row in sec.values():
            worst = max(worst, row.get("burn_fast", 0.0),
                        row.get("burn_slow", 0.0))
    return {"slos": len(secs[-1]), "worst_burn": round(worst, 4),
            "pages": pages}


def bench_ysb():
    import jax
    import jax.numpy as jnp
    from windflow_tpu.benchmarks import ysb
    from windflow_tpu.runtime.pipeline import CompiledChain

    # pane ring: one batch spans BATCH/EVENTS_PER_TICK time units =
    # BATCH/(EVENTS_PER_TICK*WIN_LEN) panes; hold 2 batches + the window span
    panes_per_batch = BATCH // (ysb.EVENTS_PER_TICK * ysb.WIN_LEN) + 1
    src = ysb.make_source(total=(STEPS + 2) * BATCH)
    ops = ysb.make_ops(pane_capacity=2 * panes_per_batch + 2,
                       max_wins=panes_per_batch + 64)
    chain = CompiledChain(ops, src.payload_spec(), batch_capacity=BATCH,
                          event_time=False)

    step, specs = _cursor_bench(chain, src)
    dt, _ = _bench_loop(step, tuple(chain.states), STEPS)
    roof = _roofline(step, specs, dt / STEPS)
    return STEPS * BATCH / dt, dt / STEPS, roof, _chain_metrics(chain, dt / STEPS)


def bench_ysb_wmr(map_parallelism: int = 4):
    """YSB with the Win_MapReduce window stage — the reference's other
    headline YSB pipeline (``src/yahoo_test_cpu/test_ysb_wmr.cpp``: each
    window's content partitioned over MAP workers, partial counts combined by
    REDUCE). Same source/filter/join prefix as bench_ysb.

    Geometry is WMR-appropriate, not Key_FFAT's: Win_MapReduce rides the
    gather-based Win_Seq engine whose TB emission gathers the FULL per-key
    ring per fired window (L = tb_capacity) and whose fired-window budget W is
    SHARED across all keys — at the FFAT bench's win_len=100 that is ~105k
    fired windows x the ring per batch, infeasible by design (WMR is the
    reference's pattern for FEW, LARGE windows; per-pane counting is what
    Key_FFAT is for). win_len = 1000 ticks gives ~1 window/key/batch:
    W = num_keys * (windows/batch + margin), ring = 8192 > per-key window
    span (~3.3k tuples) + one batch of arrivals (~3.5k).

    The run self-checks exactness: the summed window counts must cover the
    views of every COMPLETED window; a mis-sized budget (deferral collapse or
    ring overwrite) undercounts and raises instead of reporting a degenerate
    pipeline's throughput."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from windflow_tpu.benchmarks import ysb
    from windflow_tpu.operators.sink import ReduceSink
    from windflow_tpu.runtime.pipeline import CompiledChain

    WIN_LEN = 1000                       # ticks; 10x the FFAT bench's windows
    wins_per_batch = BATCH // (ysb.EVENTS_PER_TICK * WIN_LEN) + 1
    src = ysb.make_source(total=(STEPS + 2) * BATCH)
    ops = ysb.make_ops_wmr(win_len=WIN_LEN,
                           map_parallelism=map_parallelism,
                           max_wins=ysb.N_CAMPAIGNS * (wins_per_batch + 2),
                           tb_capacity=8192)
    ops.append(ReduceSink(lambda t: t.data, name="wmr_total"))
    chain = CompiledChain(ops, src.payload_spec(), batch_capacity=BATCH,
                          event_time=False)

    step, specs = _cursor_bench(chain, src)
    dt, states = _bench_loop(step, tuple(chain.states), STEPS)
    # exactness self-check: every window whose span is fully delivered AND
    # past the flush horizon must have fired with its full count. After
    # n_batches = STEPS+1 (incl. warmup), ticks delivered = n*BATCH/RATE;
    # completed windows cover ticks [0, floor(.../WIN_LEN)*WIN_LEN); views in
    # that range = ceil(ticks*RATE/3) (every 3rd global index is a view).
    total = int(np.asarray(jax.tree.leaves(states[-1])[0]))
    ticks = (STEPS + 1) * BATCH // ysb.EVENTS_PER_TICK
    complete_ticks = (ticks // WIN_LEN - 1) * WIN_LEN   # -1: delay horizon
    expect_min = (complete_ticks * ysb.EVENTS_PER_TICK + 2) // 3
    if total < expect_min:
        raise RuntimeError(
            f"bench_ysb_wmr undercounted: {total} < {expect_min} views over "
            f"completed windows — budget/ring mis-sized, refusing to report "
            f"a degenerate pipeline")
    roof = _roofline(step, specs, dt / STEPS)
    return STEPS * BATCH / dt, dt / STEPS, roof, _chain_metrics(chain, dt / STEPS)


def bench_nexmark(batch: int = None, steps: int = None):
    """The Nexmark-class query suite (``windflow_tpu/nexmark``): tuples/s
    per query over the names.py::NEXMARK_QUERIES registry, each chain
    compiled + driven with the same device-cursor step discipline as
    bench_ysb. Smaller default batch than the headline: the join/session
    state machinery is [C, A]-quadratic in places, and the suite's job is
    the per-query TREND (bench_trend.py renders the rows beside YSB), not
    a memory-bandwidth headline. ``WF_BENCH_NEXMARK_EVENTS`` overrides the
    per-query event budget."""
    import jax
    from windflow_tpu.benchmarks import device_cursor_step
    from windflow_tpu.nexmark import QUERIES, make_query
    from windflow_tpu.runtime.pipeline import CompiledChain

    batch = int(batch or min(BATCH, 1 << 14))
    steps = int(steps or min(STEPS, 20))
    budget = os.environ.get("WF_BENCH_NEXMARK_EVENTS", "")
    total = int(budget) if budget else (steps + 2) * batch
    rows = {}
    for name in QUERIES:
        src, ops = make_query(name, total)
        chain = CompiledChain(ops, src.payload_spec(), batch_capacity=batch,
                              event_time=False)
        step = device_cursor_step(chain, src, batch)
        dt, _ = _bench_loop(step, tuple(chain.states), steps)
        rows[name] = {"tps": steps * batch / dt, "step_s": dt / steps,
                      "batch": batch}
        # e2e event-time p99 per query: a SHORT separate pass with the
        # event-time histograms compiled in (the timed row above stays the
        # exact monitoring-off program) — the max per-(operator, stream)
        # observed-lateness p99, in event-time ticks.  bench_trend.py
        # renders the column beside the per-query throughput.
        rows[name]["event_time_p99"] = _nexmark_event_time_p99(
            name, total, batch, min(steps, 5))
    # the tiered-state acceptance row: the q3 stream-table join at 100x the
    # per-batch key space with a FIXED hot table (windflow_tpu/state two-tier
    # layer) — the ROADMAP-3 claim measured: overflow_drops stays 0 while
    # cold keys spill to host and re-admit on probe miss, with a bounded
    # per-step p99 (the drive loop runs chain.push so the async spill
    # maintenance runs exactly as in production)
    rows["q3_enrich_join_100x"] = _bench_nexmark_tiered_100x(batch, steps)
    return rows


def _bench_nexmark_tiered_100x(batch: int, steps: int) -> dict:
    import time as _time
    import jax
    import numpy as np
    from windflow_tpu.nexmark import make_query
    from windflow_tpu.runtime.pipeline import CompiledChain
    b = min(int(batch), 1024)       # the [R, K] resolve compare is quadratic
    hot = 4 * b                     # clears the WF114 admission reserve (3b)
    keys = 100 * b                  # 100x the per-batch working set
    n_steps = max(4, min(steps, 12))
    total = keys + n_steps * b      # definition prefix + probe traffic
    src, ops = make_query("q3_enrich_join", total, n_auctions=keys,
                          num_slots=hot, tiered=dict())
    chain = CompiledChain(ops, src.payload_spec(), batch_capacity=b,
                          event_time=False)
    times = []
    for bt in src.batches(b):
        t0 = _time.perf_counter()
        out = chain.push(bt)
        jax.block_until_ready(out)
        times.append(_time.perf_counter() - t0)
    st = chain.states[0]
    timed = sorted(times[1:])       # drop the compile step
    p99 = timed[min(len(timed) - 1, int(0.99 * len(timed)))]
    n = len(times)
    spills = int(np.asarray(st["spills"]))
    readmits = int(np.asarray(st["readmits"]))
    return {
        "tps": n * b / sum(times),
        "step_s": sum(timed) / max(1, len(timed)),
        "p99_step_s": p99,
        "batch": b, "keys": keys, "hot_capacity": hot, "batches": n,
        "overflow_drops": int(np.asarray(st["dropped"])),
        "state_spills": spills, "state_readmits": readmits,
        "spills_per_step": round(spills / n, 2),
        "readmits_per_step": round(readmits / n, 2),
        "cold_keys": ops[0]._tier.store.key_count(),
    }


def _nexmark_event_time_p99(name, total, batch, steps):
    """Max observed-lateness p99 (ticks) across one query's stateful
    operators after ``steps`` batches with event-time monitoring compiled
    in; None when the query has no lateness surface."""
    from windflow_tpu.benchmarks import device_cursor_step
    from windflow_tpu.nexmark import make_query
    from windflow_tpu.runtime.pipeline import CompiledChain
    src, ops = make_query(name, total)
    chain = CompiledChain(ops, src.payload_spec(), batch_capacity=batch,
                          event_time=True)
    step = device_cursor_step(chain, src, batch)
    states = tuple(chain.states)
    import jax.numpy as jnp
    cur = jnp.asarray(0, jnp.int32)
    for _ in range(int(steps)):
        states, cur, _out = step(states, cur)
    chain.states = list(states)
    p99 = None
    for op, st in zip(chain.ops, chain.states):
        try:
            sec = op.event_time_stats(st)
        except Exception:   # noqa: BLE001 — bench telemetry is advisory
            continue
        for summ in ((sec or {}).get("lateness") or {}).values():
            if summ.get("total"):
                p99 = max(p99 or 0, summ["p99"])
    return p99


def bench_stateless():
    """Config 2 of BASELINE.json: Source->Map->Filter->Sink micro-batch."""
    import jax
    import jax.numpy as jnp
    from windflow_tpu.operators.map import Map
    from windflow_tpu.operators.filter import Filter
    from windflow_tpu.operators.sink import ReduceSink
    from windflow_tpu.operators.source import DeviceSource
    from windflow_tpu.runtime.pipeline import CompiledChain

    src = DeviceSource(lambda i: {"v": (i % 1000).astype(jnp.float32)},
                       total=(STEPS + 2) * BATCH, num_keys=512)
    ops = [Map(lambda t: {"v": t.v * 2.0 + 1.0}),
           Filter(lambda t: t.v > 100.0),
           ReduceSink(lambda t: t.v)]
    chain = CompiledChain(ops, src.payload_spec(), batch_capacity=BATCH,
                          event_time=False)

    step, specs = _cursor_bench(chain, src)
    dt, _ = _bench_loop(step, tuple(chain.states), STEPS)
    roof = _roofline(step, specs, dt / STEPS)
    return STEPS * BATCH / dt, dt / STEPS, roof, _chain_metrics(chain, dt / STEPS)


def bench_keyed_cb():
    """Config 3: Key_Farm/Win_SeqFFAT keyed count-based sliding-window sum."""
    import jax
    import jax.numpy as jnp
    from windflow_tpu.operators.source import DeviceSource
    from windflow_tpu.operators.win_patterns import Key_FFAT
    from windflow_tpu.operators.window import WindowSpec
    from windflow_tpu.runtime.pipeline import CompiledChain

    K = 512
    reps = 3
    src = DeviceSource(lambda i: {"v": (i % 97).astype(jnp.float32)},
                       total=(reps * STEPS + 2) * BATCH, num_keys=K)
    op = Key_FFAT(lambda t: t.v, jnp.add,
                  spec=WindowSpec(1024, 512), num_keys=K)
    chain = CompiledChain([op], src.payload_spec(), batch_capacity=BATCH,
                          event_time=False)

    step, specs = _cursor_bench(chain, src)
    dt, _ = _bench_loop(step, tuple(chain.states), STEPS, reps=reps)
    roof = _roofline(step, specs, dt / STEPS)
    return STEPS * BATCH / dt, dt / STEPS, roof, _chain_metrics(chain, dt / STEPS)


def measure_floor():
    """The host<->device synchronization floor of THIS environment, measured so
    latency numbers decompose honestly. On the tunneled dev chip the first D2H
    fetch switches the link into real-transfer mode whose round trip is ~67 ms
    (measured below); on a local PJRT host the same probe reads ~0.1 ms. Every
    latency we report includes this floor — the device-side component is
    (raw - rtt)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    x = jnp.zeros((16,))
    _ = np.asarray(x)                     # enter real-transfer mode
    f = jax.jit(lambda x: x + 1.0)
    jax.block_until_ready(f(x))
    rtt = []
    for _ in range(20):
        t0 = time.perf_counter()
        jax.block_until_ready(f(x))
        rtt.append(time.perf_counter() - t0)
    rtt.sort()
    big = jax.device_put(np.zeros(1 << 20, np.float32))
    jax.block_until_ready(big)
    t0 = time.perf_counter()
    _ = np.asarray(big)
    d2h_s = time.perf_counter() - t0
    return {"sync_rtt_ms": rtt[len(rtt) // 2] * 1e3,
            "d2h_mbps": 4.0 / d2h_s}


def bench_latency_curve(batches=(4096, 16384, 65536, 262144), steps: int = 80,
                        depth: int = 2):
    """Per-window-result latency, measured the reference's way
    (``ysb_nodes.hpp:200-216``): emission timestamp -> host receipt, per result.

    A batch's tuples are "emitted" when the batch is submitted (ship_time); its
    window results are received when their async D2H copy lands on the host
    (receipt_time). The loop runs PIPELINED with ``depth`` batches in flight
    (bounded-queue backpressure — the reference's FF_BOUNDED_BUFFER role): the
    device computes batch i while results of batch i-depth are harvested, so
    latency ~= depth * step_time + transfer, not a blocking sync per batch.
    Window results ship as ONE packed [4, W] i32 array (key, wid, count, valid)
    to cost a single transfer per batch."""
    import jax
    import jax.numpy as jnp
    from windflow_tpu.benchmarks import ysb
    from windflow_tpu.runtime.async_sink import AsyncResultShipper
    from windflow_tpu.runtime.pipeline import CompiledChain

    out_rows = []
    for batch in batches:
        panes_per_batch = max(batch // (ysb.EVENTS_PER_TICK * ysb.WIN_LEN), 1) + 1
        src = ysb.make_source(total=(steps + 4) * batch)
        ops = ysb.make_ops(pane_capacity=2 * panes_per_batch + 2,
                           max_wins=panes_per_batch + 64)
        chain = CompiledChain(ops, src.payload_spec(), batch_capacity=batch,
                              event_time=False)

        # device-resident cursor, advanced in-program: a per-step host-scalar
        # upload would sit INSIDE every latency sample (RTT-class through the
        # tunnel) and under-pipeline the curve
        from windflow_tpu.benchmarks import device_cursor_step
        step = device_cursor_step(
            chain, src, batch,
            out_fn=lambda b: jnp.stack([b.key, b.id,
                                        jnp.asarray(b.payload, jnp.int32),
                                        b.valid.astype(jnp.int32)]))
        states = tuple(chain.states)
        cur = jnp.asarray(0, jnp.int32)
        states, cur, packed = step(states, cur)
        jax.block_until_ready(packed)                     # compile outside timing

        shipper = AsyncResultShipper(depth=depth)
        lat = []
        n_results = 0
        t_wall0 = time.perf_counter()
        for i in range(1, steps + 1):
            states, cur, packed = step(states, cur)       # async dispatch
            shipper.ship(packed, tag=i)
            for rec in shipper.harvest():                 # blocks only past depth
                lat.append(rec.receipt_time - rec.ship_time)
                n_results += int((rec.value[3] > 0).sum())
        for rec in shipper.drain():
            lat.append(rec.receipt_time - rec.ship_time)
            n_results += int((rec.value[3] > 0).sum())
        t_wall = time.perf_counter() - t_wall0
        lat.sort()
        out_rows.append({
            "batch": batch,
            "p50_ms": lat[len(lat) // 2] * 1e3,
            "p99_ms": lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1e3,
            "tput_mtps": steps * batch / t_wall / 1e6,
            "step_ms": t_wall / steps * 1e3,
            "results": n_results,
        })
    return out_rows


def bench_adaptive(total_batches: int = 240, base_batch: int = None):
    """Closed-loop capacity autotuning through the real Pipeline driver: a
    stateless map+filter chain starts at ``base_batch`` and the control
    plane's hill-climber converges on the ladder rung this device actually
    sustains best; the winning plan persists to ``bench_captures/tuning.json``
    so the next run (and any supervised run of the same chain) warm-starts
    there. Returns end-to-end tuples/s, the chosen capacity, and the
    controller's own per-rung rate table — the closed-loop convergence
    evidence, next to the fixed-ladder sweep for the same shapes."""
    import jax.numpy as jnp
    import windflow_tpu as wf
    from windflow_tpu import control as wfcontrol
    from windflow_tpu.operators.source import DeviceSource

    base = base_batch or max(BATCH // 4, 1 << 12)
    cache_path = os.path.join(os.path.dirname(CAPTURE_PATH), "tuning.json")
    cfg = wf.ControlConfig(autotune=True, ladder_up=2, ladder_down=2,
                           decide_every=6, settle_batches=2,
                           cache_path=cache_path)
    src = DeviceSource(lambda i: {"v": (i % 1000).astype(jnp.float32)},
                       total=total_batches * base, num_keys=512)
    pipe = wf.Pipeline(src, [wf.Map(lambda t: {"v": t.v * 2.0 + 1.0}),
                             wf.Filter(lambda t: t.v > 100.0),
                             wf.ReduceSink(lambda t: t.v)],
                       batch_size=base, control=cfg)
    t0 = time.perf_counter()
    pipe.run()
    dt = time.perf_counter() - t0
    ctl = wfcontrol.counters()
    return {
        "tps": total_batches * base / dt,
        "base_capacity": base,
        "chosen_capacity": wfcontrol.gauges().get("chosen_capacity"),
        "capacity_switches": ctl["capacity_switches"],
        "tuning_decisions": ctl["tuning_decisions"],
        "cache_path": cache_path,
        "metrics": _chain_metrics(pipe.chain, capacity=base),
    }


def bench_dispatch(total_batches: int = 96, base_batch: int = None,
                   k: int = None):
    """Scan dispatch through the real Pipeline driver: the SAME chain driven
    per-batch (dispatch off) and K-fused (``dispatch=k``), launch counts read
    from the entry op's own Stats_Record (``num_kernels`` vs
    ``batches_received`` — the attribution CompiledChain.push_many makes: K
    batches, ONE kernel). The dispatch-amortization evidence next to the
    throughput it buys; ``launches_per_batch`` rides in the headline so
    ``bench_trend.py``'s launches/step column moves every round."""
    import jax.numpy as jnp
    import windflow_tpu as wf
    from windflow_tpu.operators.source import DeviceSource

    base = base_batch or max(BATCH // 4, 1 << 12)
    k = k or int(os.environ.get("WF_DISPATCH_K", "8") or "8")

    def run(dispatch):
        src = DeviceSource(lambda i: {"v": (i % 1000).astype(jnp.float32)},
                           total=total_batches * base, num_keys=512)
        pipe = wf.Pipeline(src, [wf.Map(lambda t: {"v": t.v * 2.0 + 1.0}),
                                 wf.Filter(lambda t: t.v > 100.0),
                                 wf.ReduceSink(lambda t: t.v)],
                           batch_size=base, dispatch=dispatch)
        t0 = time.perf_counter()
        pipe.run()
        dt = time.perf_counter() - t0
        rec = pipe.chain.ops[0].get_StatsRecords()[0]
        return {"tps": round(total_batches * base / dt),
                "batches": rec.batches_received,
                "launches": rec.num_kernels,
                "launches_per_batch": round(rec.num_kernels
                                            / max(rec.batches_received, 1), 4)}

    fused = run(k)
    per_batch = run(False)
    return {
        "dispatch_k": k, "base_capacity": base,
        "fused": fused, "per_batch": per_batch,
        "speedup": round(fused["tps"] / max(per_batch["tps"], 1), 3),
    }


def bench_keyed_stateful(num_keys: int):
    """MapGPU-stateful analogue (BASELINE.md rows 3-5): keyed map with a per-key
    running state folded in stream order (the reference keeps a per-key device
    scratch, wf/map_gpu_node.hpp:216-222). Sweep num_keys to reproduce the
    1-key serialization floor / 500-key peak / 10k-key curve."""
    import jax
    import jax.numpy as jnp
    from windflow_tpu.operators.accumulator import Accumulator
    from windflow_tpu.operators.sink import ReduceSink
    from windflow_tpu.operators.source import DeviceSource
    from windflow_tpu.runtime.pipeline import CompiledChain

    reps = 3
    src = DeviceSource(lambda i: {"v": (i % 1000).astype(jnp.float32)},
                       total=(reps * STEPS + 2) * BATCH, num_keys=num_keys)
    # per-key running state folded in stream order: the associative formulation
    # (segmented prefix scan + HBM carry table) — the TPU-native equivalent of the
    # reference's sequential per-key scratch update; no serialization floor at K=1
    ops = [Accumulator(lambda t: t.data["v"], init_value=0.0,
                       num_keys=max(num_keys, 8)),
           ReduceSink(lambda t: t.data)]
    chain = CompiledChain(ops, src.payload_spec(), batch_capacity=BATCH,
                          event_time=False)

    step, _ = _cursor_bench(chain, src)
    dt, _ = _bench_loop(step, tuple(chain.states), STEPS, reps=reps)
    return STEPS * BATCH / dt, dt / STEPS


def bench_scatter(fanout: int, variant: str = "sort"):
    """Keyed-scatter emitter analogue (BASELINE.md row 9, scattering study):
    partition each batch into per-destination sub-batches on device. Two
    formulations, A/B'd like the reference's own scattering study
    (``src/GPU_Tests/scattering``): ``sort`` = stable argsort grouping,
    ``onehot`` = sort-free one-hot-cumsum ranks."""
    import jax
    import jax.numpy as jnp
    from windflow_tpu.ops.compaction import (partition_by_destination,
                                             partition_by_destination_onehot)

    part = (partition_by_destination if variant == "sort"
            else partition_by_destination_onehot)
    cap = 2 * BATCH // fanout

    @jax.jit
    def step(carry, start):
        i = start + jnp.arange(BATCH, dtype=jnp.int32)
        key = (i.astype(jnp.uint32) * jnp.uint32(2654435761) % 10007).astype(jnp.int32)
        dest = key % fanout
        valid = jnp.ones((BATCH,), jnp.bool_)
        gather_idx, out_valid = part(dest, valid, fanout, cap)
        v = (i % 1000).astype(jnp.float32)
        sub = jnp.take(v, gather_idx)              # [fanout, cap] sub-batch payloads
        # carry the sum so step N+1 data-depends on step N: the final
        # block_until_ready then bounds ALL steps, not just the last
        return carry + jnp.sum(jnp.where(out_valid, sub, 0.0))

    carry = step(jnp.float32(0), 0)
    jax.block_until_ready(carry)
    times = []
    pos = 1
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(STEPS):
            carry = step(carry, pos * BATCH)
            pos += 1
        jax.block_until_ready(carry)
        times.append(time.perf_counter() - t0)
    dt = sorted(times)[1]
    return STEPS * BATCH / dt, dt / STEPS


def bench_ordering_overhead(total: int = 200_000, batch: int = 4096):
    """DETERMINISTIC-vs-DEFAULT merge throughput (the Ordering_Node's hot-path
    cost — reference inserts an Ordering_Node before each replica in
    DETERMINISTIC mode, ``wf/pipegraph.hpp:1197-1199``). Two sources -> merge ->
    map -> reduce, identical streams, both modes; returns
    (default_tps, deterministic_tps, ratio)."""
    import jax.numpy as jnp
    import windflow_tpu as wf
    from windflow_tpu.basic import Mode
    from windflow_tpu.runtime.pipegraph import PipeGraph

    def run(mode):
        g = PipeGraph("ord", mode=mode, batch_size=batch)
        sa = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=total,
                       num_keys=8, ts_fn=lambda i: 2 * i, name="a")
        sb = wf.Source(lambda i: {"v": -i.astype(jnp.float32)}, total=total,
                       num_keys=8, ts_fn=lambda i: 2 * i + 1, name="b")
        pa, pb = g.add_source(sa), g.add_source(sb)
        m = pa.merge(pb)
        m.add(wf.Map(lambda t: {"v": t.v * 2.0}))
        m.add(wf.ReduceSink(lambda t: t.v, name="out"))
        t0 = time.perf_counter()
        res = g.run()
        dt = time.perf_counter() - t0
        return 2 * total / dt, float(res["out"])

    # warm BOTH modes' compile caches (the Ordering_Node's jitted cores are
    # module-level and shared across instances, so a warmup graph's traces
    # carry over to the timed run)
    run(Mode.DEFAULT)
    run(Mode.DETERMINISTIC)
    d_tps, d_sum = run(Mode.DEFAULT)
    o_tps, o_sum = run(Mode.DETERMINISTIC)
    assert d_sum == o_sum, (d_sum, o_sum)   # ordering must not change the sum
    return d_tps, o_tps, o_tps / d_tps


def measure_h2d_bandwidth(mb: int = 64, streams: int = 4):
    """Aggregate host->device transfer bandwidth (MB/s): ``streams`` concurrent
    device_put transfers, the way the prefetch path issues them. Incompressible
    (random) payload — a tunneled link may compress; constants would flatter it."""
    import jax
    import numpy as np
    rng = np.random.default_rng(7)
    bufs = [rng.random(((mb // streams) << 18,), np.float32)
            for _ in range(2 * streams)]
    jax.block_until_ready([jax.device_put(b) for b in bufs[:streams]])  # warm path
    t0 = time.perf_counter()
    jax.block_until_ready([jax.device_put(b) for b in bufs[streams:]])
    n_bytes = sum(b.nbytes for b in bufs[streams:])
    return n_bytes / 1e6 / (time.perf_counter() - t0)     # MB/s (1e6 bytes)


def bench_ingest():
    """Ingest-inclusive YSB: host-resident numpy events -> prefetch thread with
    overlapped device_put (double buffering, the reference GPU path's pinned
    cudaMemcpyAsync protocol) -> full YSB chain. The reference's cost model is
    per-tuple host ingest (``wf/source.hpp:184``); its in-memory dataset replay is
    mirrored by pre-generated host chunks. Returns (tuples/s, s/step,
    transport-ceiling tuples/s derived from measured H2D bandwidth)."""
    import jax
    import numpy as np
    from windflow_tpu.benchmarks import ysb
    from windflow_tpu.operators.source import GeneratorSource
    from windflow_tpu.runtime.pipeline import CompiledChain

    B = 1 << 18
    steps = 24
    # host event chunks: ad_id/event_type payload + campaign key + event ts
    chunks = []
    for s in range(steps):
        i = np.arange(s * B, (s + 1) * B, dtype=np.int64)
        chunks.append((
            {"ad_id": ((i * 7919) % ysb.N_ADS).astype(np.int32),
             "event_type": (i % 3).astype(np.int32)},
            ((i * 7919) % ysb.N_ADS % ysb.N_CAMPAIGNS).astype(np.int32),
            (i // ysb.EVENTS_PER_TICK).astype(np.int32)))
    bytes_per_tuple = 4 + 4 + 4 + 4 + 4 + 1      # payload + key + ts + id + valid

    src = GeneratorSource(lambda: iter(chunks),
                          {"ad_id": jax.ShapeDtypeStruct((), "int32"),
                           "event_type": jax.ShapeDtypeStruct((), "int32")},
                          name="ysb_host_source")
    panes_per_batch = B // (ysb.EVENTS_PER_TICK * ysb.WIN_LEN) + 1
    ops = ysb.make_ops(pane_capacity=2 * panes_per_batch + 2,
                       max_wins=panes_per_batch + 64)
    chain = CompiledChain(ops, src.payload_spec(), batch_capacity=B,
                          event_time=False)

    # warmup/compile on the first chunk
    warm = next(iter(src.batches(B)))
    jax.block_until_ready(chain.push(warm).valid)

    t0 = time.perf_counter()
    out = None
    for b in src.batches_prefetched(B, depth=4):
        out = chain.push(b)
    jax.block_until_ready(out.valid)
    dt = time.perf_counter() - t0
    h2d_mbps = measure_h2d_bandwidth()
    ceiling_tps = h2d_mbps * 1e6 / bytes_per_tuple
    return steps * B / dt, dt / steps, ceiling_tps, bytes_per_tuple


def bench_ingest_decomposition(n: int = 1 << 20, reps: int = 7):
    """Split the ingest path into separately-measured terms so the ingest story
    is arithmetic over constants, not an assertion (VERDICT r03 #5):

    1. host framing — AoS record buffer -> SoA columns (``wf_unpack_records``)
       and key hashing (``wf_hash_int_keys``), in ns/tuple and GB/s; this is
       the reference's per-tuple Source cost model (``wf/source.hpp:184``) paid
       once per batch instead of per tuple;
    2. transfer — ``device_put`` of the framed columns on THIS backend (the
       tunnel's 30-80 MB/s, or a real host's multi-GB/s DMA);
    3. chain — the on-device compute, measured separately by bench_ysb.

    The ingest-inclusive ceiling is min(framing, transfer) by construction
    (prefetch overlaps them); the returned dict carries each term."""
    import jax
    import numpy as np
    from windflow_tpu.native import (hash_keys_native, native_available,
                                     unpack_records)

    rec_dt = np.dtype([("ad_id", "<i4"), ("event_type", "<i4"), ("ts", "<i4")])
    rng = np.random.default_rng(3)
    buf = np.empty(n, rec_dt)
    buf["ad_id"] = rng.integers(0, 100000, n, dtype=np.int32)
    buf["event_type"] = rng.integers(0, 3, n, dtype=np.int32)
    buf["ts"] = np.arange(n, dtype=np.int32)

    def _median(fn):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2]

    frame_s = _median(lambda: unpack_records(buf))
    cols = unpack_records(buf)
    hash_s = (_median(lambda: hash_keys_native(cols["ad_id"], 10007))
              if native_available() else float("nan"))

    # transfer: the framed columns, H2D, this backend
    put = lambda: jax.block_until_ready(
        [jax.device_put(c) for c in cols.values()])
    put()                                         # warm the path
    xfer_s = _median(put)
    col_bytes = sum(c.nbytes for c in cols.values())

    framing_tps = n / (frame_s + (0 if hash_s != hash_s else hash_s))
    xfer_tps = n / xfer_s
    return {
        "native": bool(native_available()),
        "framing_ns_per_tuple": frame_s / n * 1e9,
        "framing_gbps": buf.nbytes / frame_s / 1e9,
        "hash_ns_per_tuple": hash_s / n * 1e9,
        "transfer_mbps": col_bytes / xfer_s / 1e6,
        "bytes_per_tuple": buf.nbytes // n,
        "host_framing_tps": framing_tps,
        "transfer_tps": xfer_tps,
        "ingest_ceiling_tps": min(framing_tps, xfer_tps),
    }


def bench_drive_loop(batches=(4096, 262144, 1 << 20),
                     total_tuples: int = 1 << 22):
    """Host-side cost of the Python drive loop, per batch (VERDICT r05 ask #5).

    Every fresh PipeGraph re-traces its user lambdas, so timing one run times
    compilation. Instead each batch size runs the SAME graph shape at two
    stream lengths N1 < N2: both pay the identical compile cost C, so the
    steady-state per-batch driver wall time is (t2-t1)/(N2-N1), compile
    cancelled. Subtracting the bare pre-jitted step loop's per-batch time
    (device dispatch only, measured warm) leaves ``driver_us_per_batch`` — the
    Python loop's own cost. Rows feed BASELINE.md's decision on moving the
    steady-state loop behind the native layer (SURVEY §7: Python as toolchain,
    not data path)."""
    import jax
    import jax.numpy as jnp
    import windflow_tpu as wf
    from windflow_tpu.operators.source import DeviceSource
    from windflow_tpu.runtime.pipeline import CompiledChain
    from windflow_tpu.runtime.pipegraph import PipeGraph

    rows = []
    for B in batches:
        n1 = max(total_tuples // B // 4, 4)
        n2 = max(total_tuples // B, 4 * n1)

        def run_graph(n_batches):
            g = PipeGraph("drv", batch_size=B)
            (g.add_source(wf.Source(lambda i: {"v": (i % 97).astype(jnp.float32)},
                                    total=n_batches * B, num_keys=8))
             .add(wf.Map(lambda t: {"v": t.v * 2.0 + 1.0}))
             .add(wf.ReduceSink(lambda t: t.v, name="out")))
            t0 = time.perf_counter()
            g.run()
            return time.perf_counter() - t0

        # Pilot-size the row to a wall-clock budget: through the tunneled dev
        # chip a push can cost 1-3 x ~65 ms RTT, and the r05 capture lost its
        # whole 2400 s isolation slot to the batch=4096 row. The subtraction
        # estimate works at any n1 < n2 — only noise changes — so shrink the
        # stream counts until the driven batches plus per-run compile overhead
        # fit the budget, and record the applied scaling for honesty. The
        # per-batch pilot estimate is a WARM DIFFERENCE (two post-warmup runs
        # at different lengths) so the fresh-graph compile/trace cost — which
        # every run pays equally and the subtraction cancels — does not
        # masquerade as per-batch cost and over-shrink the row.
        pilot_a = run_graph(4)                # warms persistent XLA caches
        pilot_a = min(pilot_a, run_graph(4))
        # pilot_b: min-of-2 like pilot_a — a single noisy run on the tunneled
        # link can come in FASTER than pilot_a, and the old negative delta
        # clamped to per_batch_est=1e-7 concluded ~zero cost, skipped scaling,
        # and burned the whole isolation slot (ADVICE r05 #3)
        pilot_b = min(run_graph(12), run_graph(12))
        budget_s = float(os.environ.get("WF_DRIVE_LOOP_BUDGET_S", 240))
        pilot_failed = (pilot_b - pilot_a) <= 0.0
        if pilot_failed:
            # estimate failed (noise >= signal): conservative default — charge
            # the WHOLE warm pilot as per-batch cost so the budget check
            # over-protects the slot instead of under-protecting it
            per_batch_est = max(pilot_a / 4, 1e-7)
        else:
            per_batch_est = (pilot_b - pilot_a) / 8
        overhead_est = max(pilot_a - 4 * per_batch_est, 0.0)  # compile+trace
        n2_orig = n2
        spend = 5 * overhead_est + per_batch_est * (4 * n2 + 2 * n1)
        if spend > budget_s:
            scale = max(budget_s - 5 * overhead_est, 0.0) \
                / max(per_batch_est * (4 * n2 + 2 * n1), 1e-9)
            n1 = max(4, int(n1 * scale))
            n2 = max(4 * n1, int(n2 * scale))
        t1 = min(run_graph(n1) for _ in range(2))
        t2 = min(run_graph(n2) for _ in range(2))
        per_batch_s = max(t2 - t1, 0.0) / (n2 - n1)

        # bare loop: same ops, pre-jitted, no driver
        src = DeviceSource(lambda i: {"v": (i % 97).astype(jnp.float32)},
                           total=(n2 + 2) * B, num_keys=8)
        ops = [wf.Map(lambda t: {"v": t.v * 2.0 + 1.0}),
               wf.ReduceSink(lambda t: t.v, name="out")]
        chain = CompiledChain(ops, src.payload_spec(), batch_capacity=B,
                              event_time=False)

        # bare loop carries a DEVICE cursor exactly like the driven path
        # (operators/source.py::batches) — if it uploaded a host int per step
        # the ~0.1 ms H2D would no longer cancel in the subtraction and
        # driver_us_per_batch would read low by that amount
        from windflow_tpu.benchmarks import device_cursor_step
        step = device_cursor_step(chain, src, B)
        states_b = tuple(chain.states)
        cur = jnp.asarray(0, jnp.int32)
        states_b, cur, out = step(states_b, cur)      # warm/compile
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(n2 - n1):
            states_b, cur, out = step(states_b, cur)
        jax.block_until_ready(out)
        bare_s = time.perf_counter() - t0

        step_us = bare_s / (n2 - n1) * 1e6
        drv_us = per_batch_s * 1e6 - step_us
        rows.append({
            "batch": B, "n1": n1, "n2": n2,
            "pilot_estimate_failed": pilot_failed,
            "scaled_for_budget": (round(n2 / n2_orig, 4)
                                  if n2 < n2_orig else None),
            "driver_wall_us_per_batch": round(per_batch_s * 1e6, 1),
            "step_us_per_batch": round(step_us, 1),
            "driver_us_per_batch": round(max(drv_us, 0.0), 1),
            "driver_overhead_pct": round(100 * max(drv_us, 0.0)
                                         / max(step_us, 1e-9), 1),
        })
    return rows


def bench_framing_scaling(n: int = 1 << 22, workers=(1, 2, 4, 8), reps: int = 5):
    """Multi-core host framing sweep (VERDICT r05 ask #6): sharded AoS->SoA
    transpose (``parallel_unpack``) vs worker count — the reference's 1-14
    source-thread sweep applied to framing. On a single-core container the
    curve is flat by construction; the row set records the container's core
    count so the number reads honestly."""
    import numpy as np
    from windflow_tpu.native import (hardware_concurrency, native_available,
                                     parallel_unpack)

    rec_dt = np.dtype([("ad_id", "<i4"), ("event_type", "<i4"), ("ts", "<i4")])
    rng = np.random.default_rng(5)
    buf = np.empty(n, rec_dt)
    buf["ad_id"] = rng.integers(0, 100000, n, dtype=np.int32)
    buf["event_type"] = rng.integers(0, 3, n, dtype=np.int32)
    buf["ts"] = np.arange(n, dtype=np.int32)

    rows = []
    for w in workers:
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            parallel_unpack(buf, workers=w)
            ts.append(time.perf_counter() - t0)
        dt = sorted(ts)[len(ts) // 2]
        rows.append({"workers": w, "ns_per_tuple": round(dt / n * 1e9, 2),
                     "tps": round(n / dt), "gbps": round(buf.nbytes / dt / 1e9, 2)})
    return {"native": bool(native_available()),
            "host_cores": hardware_concurrency(),
            "rows": rows,
            "speedup_at_max": round(rows[-1]["tps"] / rows[0]["tps"], 2)}


def bench_pallas_ab(shapes=((4096, 512), (1024, 1024), (8192, 256)),
                    iters: int = 30):
    """A/B the Pallas masked window reduce (ops/pallas_kernels.py — the
    ComputeBatch_Kernel analogue's inner aggregation) against the XLA
    formulation at fired-window-batch shapes [W, L]. Returns rows of
    (W, L, xla_us, pallas_us). The winner belongs in the data path; the loser's
    existence is only justified by this number."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from windflow_tpu.ops import pallas_kernels as pk

    rows = []
    for W, L in shapes:
        vals = jnp.asarray(np.random.default_rng(0).random((W, L), np.float32))
        mask = jnp.asarray(np.random.default_rng(1).random((W, L)) < 0.7)

        xla = jax.jit(pk._xla_masked_sum)
        jax.block_until_ready(xla(vals, mask))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = xla(vals, mask)
        jax.block_until_ready(out)
        xla_us = (time.perf_counter() - t0) / iters * 1e6

        pallas_us = None
        if pk.HAVE_PALLAS and W % pk.ROW_TILE == 0 and L % 128 == 0:
            try:
                # time the Pallas program itself — masked_window_reduce would
                # silently substitute the XLA fallback on any compile failure
                # and corrupt the A/B
                jax.block_until_ready(pk._pallas_masked_sum(vals, mask))
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = pk._pallas_masked_sum(vals, mask)
                jax.block_until_ready(out)
                pallas_us = (time.perf_counter() - t0) / iters * 1e6
            except Exception as e:          # noqa: BLE001 — report, don't die
                pallas_us = f"failed: {e}"
        rows.append((W, L, xla_us, pallas_us))
    return rows


def bench_native_ring(n: int = 200_000, capacity: int = 1024):
    """Host-side SPSC ring throughput (tokens/s) across two pinned threads —
    the FastFlow-role substrate under the threaded driver
    (``native/spsc_queue.cpp``; reference L0, lock-free SPSC queues). Each
    token stands for a micro-batch handle, so sustaining ~1M tokens/s carries
    ~1T tuples/s of stream at 1M-tuple batches — the ring is never the
    bottleneck. Runs entirely on the host (no device needed)."""
    import threading
    from windflow_tpu.native import SPSCQueue, pin_thread

    q = SPSCQueue(capacity)
    sentinel = object()

    def producer():
        pin_thread(0)
        for i in range(n):
            q.push(i)
        q.push(sentinel)

    got = []

    def consumer():
        pin_thread(1)
        c = 0
        while True:
            ok, item = q.pop(spin=1024)
            if not ok:
                continue
            if item is sentinel:
                break
            c += 1
        got.append(c)

    t0 = time.perf_counter()
    tp = threading.Thread(target=producer)
    tc = threading.Thread(target=consumer)
    tc.start(); tp.start(); tp.join(); tc.join()
    dt = time.perf_counter() - t0
    assert got[0] == n
    return n / dt, dt


def _run_isolated(call: str, timeout_s: int = 2400):
    """Run ``bench.<call>`` in a FRESH subprocess and return its result.

    Measured (r03): merely constructing one chain can flip this tunnel's
    runtime into a mode where an unrelated, already-warmed executable's
    dispatch goes from 0.14 ms to 63 ms per step — identical HLO, same
    process (the YSB chain construction + any later Key_FFAT loop reproduces
    it deterministically; interleaving runs does not). Numbers taken after
    other benches in one process measure that mode, not the framework, so
    every WF_BENCH_ALL sub-bench runs in its own process."""
    import subprocess
    code = (f"import bench, json; r = bench.{call}; "
            f"print('WFRESULT ' + json.dumps(r))")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=timeout_s,
                          cwd=os.path.dirname(os.path.abspath(__file__)))
    for line in proc.stdout.splitlines():
        if line.startswith("WFRESULT "):
            return json.loads(line[len("WFRESULT "):])
    raise RuntimeError(f"isolated bench {call!r} failed (rc={proc.returncode}):\n"
                       f"{proc.stderr[-2000:]}")


def _device_healthcheck(timeout_s: int = 180) -> None:
    """Fail fast when the device link is wedged instead of hanging for the
    harness's whole timeout (tiny H2D+sync in a killable subprocess). On
    failure, degrade to the last persisted good capture marked stale (rc=0);
    rc=2 only if no good capture exists."""
    import subprocess
    code = ("import numpy as np, jax; "
            "x = jax.device_put(np.random.rand(4096).astype(np.float32)); "
            "jax.block_until_ready(x); print('ok')")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=timeout_s)
        if proc.returncode == 0 and "ok" in proc.stdout:
            return
        msg = proc.stderr[-2000:]
    except subprocess.TimeoutExpired:
        msg = f"device probe did not finish within {timeout_s}s"
    sys.exit(emit_stale_headline(msg))


def main():
    import jax
    _device_healthcheck()
    dev = jax.devices()[0]
    print(f"device: {dev}", file=sys.stderr)

    # ORDER MATTERS on the tunneled dev chip: the first D2H fetch (measure_floor /
    # the latency curves) flips the link into real-transfer mode, after which
    # EVERY dispatch pays the ~60-70 ms tunnel round trip (measured; see
    # BASELINE.md). The r03 WF_BENCH_ALL capture that ran the keyed benches after
    # the latency curves recorded 64 ms/step for a program the fresh link runs in
    # 0.13 ms. So: all throughput benches and the Pallas A/B run BEFORE the first
    # D2H; the floor + latency curves go last.
    #
    # The headline is recorded the moment YSB lands, and secondary-bench
    # failures degrade (stderr warning, headline still printed) instead of
    # crashing: the tunnel dying MID-run must not erase a fresh YSB number
    # (it erased the whole r03 capture).
    try:
        ysb_tps, ysb_step_s, ysb_roof, ysb_metrics = bench_ysb()
    except Exception as e:  # noqa: BLE001 — device death mid-run
        import traceback
        traceback.print_exc()
        sys.exit(emit_stale_headline(
            f"bench_ysb failed after a passing healthcheck: {e}"))
    record("ysb", {"tps": ysb_tps, "step_s": ysb_step_s, "batch": BATCH,
                   "roofline": ysb_roof, "metrics": ysb_metrics})
    if "error" not in ysb_roof:
        print(f"YSB roofline: {ysb_roof['achieved_hbm_gbps']} GB/s HBM "
              f"({ysb_roof['hbm_utilization_pct']}% of peak), "
              f"{ysb_roof['achieved_gflops']} GFLOP/s "
              f"({ysb_roof['mxu_utilization_pct']}% of MXU peak)",
              file=sys.stderr)
    headline = {
        "metric": "YSB tuples/sec/chip",
        "value": round(ysb_tps),
        "unit": "tuples/s",
        "vs_baseline": round(ysb_tps / BASELINE_TPS, 3),
    }
    if "error" not in ysb_roof:
        # XLA logical cost per step rides in the headline so BENCH_r*.json
        # rounds carry the device-free trajectory (bench_trend.py renders
        # these columns; the hermetic perf gate pins the same numbers)
        headline["cost"] = {"flops_per_step": ysb_roof["flops_per_step"],
                            "bytes_per_step": ysb_roof["bytes_per_step"]}
    try:
        # compile-ledger column (device-free, like `cost`): compiles per
        # driven step through the real push path + unexpected retraces
        headline["health"] = _health_compile_stats()
    except Exception as e:  # noqa: BLE001 — a trend column must never
        #                     block the headline
        print(f"health compile stats unavailable: {e}", file=sys.stderr)
    try:
        # shard-local recovery column (device-free, like `health`): a
        # kill-one-shard drill through the sharded supervisor — recovery
        # duration + the byte-identity verdict ride every capture
        headline["shard"] = _shard_recovery_stats()
    except Exception as e:  # noqa: BLE001 — a trend column must never
        #                     block the headline
        print(f"shard recovery stats unavailable: {e}", file=sys.stderr)
    try:
        # SLO-engine column (device-free, like `health`): worst burn rate +
        # page count of the default spec set over a short monitored run
        headline["slo"] = _slo_stats()
    except Exception as e:  # noqa: BLE001 — a trend column must never
        #                     block the headline
        print(f"slo stats unavailable: {e}", file=sys.stderr)
    record_headline(headline)
    try:
        _secondary_benches(ysb_tps, ysb_step_s, headline)
    except Exception as e:  # noqa: BLE001 — keep the fresh headline
        import traceback
        traceback.print_exc()
        print(f"secondary benches died mid-run ({e}); the headline below is "
              f"from THIS run's YSB capture and remains valid", file=sys.stderr)
    print(json.dumps(headline))


def capture_stateless_isolated():
    """Run bench_stateless in its own process and persist the capture — the
    ONE recipe for this row (bench runs and the probe watcher both call it).
    In-session it would run right after YSB and measure the same-process
    dispatch degradation (r03 finding), not the program: the 2026-07-31
    in-session capture read 1.83 ms/step at 0.07% HBM utilization for a
    map+filter whose traffic bound is ~50 us."""
    sl_tps, sl_step_s, sl_roof, sl_metrics = _run_isolated("bench_stateless()")
    record("stateless", {"tps": sl_tps, "step_s": sl_step_s, "batch": BATCH,
                         "roofline": sl_roof, "metrics": sl_metrics},
           methodology="isolated-subprocess")
    return sl_tps, sl_step_s, sl_roof


def _secondary_benches(ysb_tps, ysb_step_s, headline=None):
    sl_tps, sl_step_s, sl_roof = capture_stateless_isolated()
    print(f"YSB: {ysb_tps/1e6:.2f} M tuples/s ({ysb_step_s*1e3:.2f} ms/step, "
          f"batch={BATCH})", file=sys.stderr)
    print(f"stateless map+filter: {sl_tps/1e6:.2f} M tuples/s "
          f"({sl_step_s*1e3:.2f} ms/step; roofline "
          f"{sl_roof.get('hbm_utilization_pct', '?')}% HBM)", file=sys.stderr)
    # scan dispatch: driver-level, so it runs isolated like the other driver
    # benches; its launches/batch number ALSO rides the headline `dispatch`
    # record (re-persisted) so BENCH_r*.json rounds carry the
    # dispatch-amortization trajectory next to the cost columns
    dd = _run_isolated("bench_dispatch()")
    record("dispatch", dd, methodology="isolated-subprocess")
    if headline is not None:
        headline["dispatch"] = {
            "k": dd["dispatch_k"],
            "launches_per_step": dd["fused"]["launches_per_batch"],
        }
        record_headline(headline)
    print(f"scan dispatch (K={dd['dispatch_k']}): "
          f"{dd['fused']['tps']/1e6:.2f} M tuples/s fused "
          f"({dd['fused']['launches_per_batch']:.3f} launches/batch) vs "
          f"{dd['per_batch']['tps']/1e6:.2f} M per-batch "
          f"({dd['speedup']:.2f}x)", file=sys.stderr)
    nx = _run_isolated("bench_nexmark()")
    record("nexmark", nx, methodology="isolated-subprocess")
    if headline is not None:
        headline["nexmark"] = {q: round(r["tps"], 1) for q, r in nx.items()}
        # e2e event-time p99 per query (ticks) — the bench_trend.py
        # event-time column; queries without a lateness surface omit
        headline["nexmark_event_time"] = {
            q: r["event_time_p99"] for q, r in nx.items()
            if r.get("event_time_p99") is not None}
        # tiered-state movement of the 100x-keys acceptance row — the
        # bench_trend.py spill-rate column (moves even in tunnel-down
        # rounds: the spill protocol is host+CPU-measurable)
        t100 = nx.get("q3_enrich_join_100x")
        if t100 is not None:
            headline["nexmark_tiered"] = {
                "keys": t100.get("keys"),
                "hot_capacity": t100.get("hot_capacity"),
                "overflow_drops": t100.get("overflow_drops"),
                "spills_per_step": t100.get("spills_per_step"),
                "readmits_per_step": t100.get("readmits_per_step"),
                "p99_step_ms": round(1e3 * t100.get("p99_step_s", 0.0), 3),
            }
        record_headline(headline)
    for q, r in sorted(nx.items()):
        et = (f", et-p99={r['event_time_p99']}"
              if r.get("event_time_p99") is not None else "")
        print(f"nexmark {q}: {r['tps']/1e6:.2f} M tuples/s "
              f"({r['step_s']*1e3:.2f} ms/step, batch={r['batch']}{et})",
              file=sys.stderr)
    kc_tps, kc_step, kc_roof, kc_metrics = _run_isolated("bench_keyed_cb()")
    record("keyed_cb", {"tps": kc_tps, "step_s": kc_step, "roofline": kc_roof,
                        "metrics": kc_metrics},
           methodology="isolated-subprocess")
    print(f"keyed CB sliding windows (K=512, w=1024 s=512): "
          f"{kc_tps/1e6:.2f} M tuples/s ({kc_step*1e3:.2f} ms/step)",
          file=sys.stderr)
    from windflow_tpu.native import (hardware_concurrency, native_available,
                                     queue_selfbench)
    if native_available():
        ring_tps = queue_selfbench()
        print(f"native SPSC ring (raw, C threads): {ring_tps/1e6:.1f} M tokens/s "
              f"on {hardware_concurrency()} core(s) — each token is a micro-batch "
              f"handle", file=sys.stderr)
    else:
        print("native SPSC ring: skipped (native library unavailable)",
              file=sys.stderr)
    if os.environ.get("WF_BENCH_ALL"):
        py_tps, _ = bench_native_ring(200_000)
        print(f"SPSC ring through the Python binding: {py_tps/1e6:.2f} M "
              f"handles/s (per-handle ctypes cost; the raw ring above is the "
              f"C-side number)", file=sys.stderr)
        for k in (1, 500, 10000):
            ks_tps, ks_step = _run_isolated(f"bench_keyed_stateful({k})")
            record(f"keyed_stateful_k{k}", {"tps": ks_tps, "step_s": ks_step},
                   methodology="isolated-subprocess")
            print(f"keyed-stateful map (K={k}): {ks_tps/1e6:.2f} M tuples/s "
                  f"({ks_step*1e3:.2f} ms/step)  [CUDA bar: 0.44-0.64M @1, "
                  f"11.8M @500, 10M @10k]", file=sys.stderr)
        ad = _run_isolated("bench_adaptive()")
        record("adaptive", ad, methodology="isolated-subprocess")
        print(f"adaptive capacity autotune: {ad['tps']/1e6:.2f} M tuples/s, "
              f"base {ad['base_capacity']} -> chosen "
              f"{ad['chosen_capacity']} "
              f"({ad['capacity_switches']} switches, "
              f"{ad['tuning_decisions']} decisions; plan cached at "
              f"{ad['cache_path']})", file=sys.stderr)
        wm_tps, wm_step, wm_roof, wm_metrics = _run_isolated("bench_ysb_wmr()")
        record("ysb_wmr", {"tps": wm_tps, "step_s": wm_step,
                           "roofline": wm_roof, "metrics": wm_metrics},
               methodology="isolated-subprocess")
        print(f"YSB Win_MapReduce variant (M=4): {wm_tps/1e6:.2f} M tuples/s "
              f"({wm_step*1e3:.2f} ms/step)", file=sys.stderr)
        od_tps, oo_tps, oratio = _run_isolated("bench_ordering_overhead()")
        record("ordering_overhead", {"default_tps": od_tps,
                                     "deterministic_tps": oo_tps,
                                     "ratio": oratio},
               methodology="isolated-subprocess")
        print(f"DETERMINISTIC merge overhead: {od_tps/1e6:.2f} M t/s DEFAULT vs "
              f"{oo_tps/1e6:.2f} M t/s DETERMINISTIC ({oratio:.2f}x)",
              file=sys.stderr)
        for n in (2, 4, 8, 16):
            sc_tps, sc_step = _run_isolated(f"bench_scatter({n}, 'sort')")
            oh_tps, oh_step = _run_isolated(f"bench_scatter({n}, 'onehot')")
            record(f"scatter_fanout{n}",
                   {"sort_tps": sc_tps, "sort_step_s": sc_step,
                    "onehot_tps": oh_tps, "onehot_step_s": oh_step},
                   methodology="isolated-subprocess")
            print(f"keyed scatter fan-out={n}: sort {sc_tps/1e6:.2f} M tuples/s "
                  f"({sc_step*1e3:.2f} ms/step) vs one-hot {oh_tps/1e6:.2f} M "
                  f"({oh_step*1e3:.2f} ms/step)  [CUDA bar: 1.6M @2 -> "
                  f"0.2-0.7M @16]", file=sys.stderr)

    ab_rows = bench_pallas_ab()
    record("pallas_ab", {"rows": [list(r) for r in ab_rows]})
    for W, L, xla_us, pallas_us in ab_rows:
        p = (f"{pallas_us:.1f} us" if isinstance(pallas_us, float)
             else str(pallas_us))
        print(f"masked window reduce A/B [{W},{L}]: XLA {xla_us:.1f} us vs "
              f"Pallas {p}", file=sys.stderr)

    if os.environ.get("WF_BENCH_ALL"):
        # H2D-heavy; isolated like the rest
        in_tps, in_step, in_ceiling, in_bpt = _run_isolated("bench_ingest()")
        record("ingest", {"tps": in_tps, "step_s": in_step,
                          "transport_ceiling_tps": in_ceiling,
                          "bytes_per_tuple": in_bpt},
               methodology="isolated-subprocess")
        dec = _run_isolated("bench_ingest_decomposition()")
        record("ingest_decomposition", dec, methodology="isolated-subprocess")
        fs = _run_isolated("bench_framing_scaling()")
        record("framing_scaling", fs, methodology="isolated-subprocess")
        print(f"host framing scaling ({fs['host_cores']} core(s)): " +
              ", ".join(f"{r['workers']}w={r['tps']/1e6:.0f}M t/s"
                        for r in fs["rows"]) +
              f" (speedup {fs['speedup_at_max']}x; flat on a 1-core container)",
              file=sys.stderr)
        dl = _run_isolated("bench_drive_loop()")
        record("drive_loop", {"rows": dl}, methodology="isolated-subprocess")
        print("Python drive-loop cost (driver-vs-bare, per batch):",
              file=sys.stderr)
        for r in dl:
            print(f"  batch={r['batch']:7d}: step {r['step_us_per_batch']:8.1f} "
                  f"us  driver +{r['driver_us_per_batch']:8.1f} us "
                  f"({r['driver_overhead_pct']:.0f}%)", file=sys.stderr)
        print(f"ingest decomposition: framing {dec['framing_ns_per_tuple']:.1f} "
              f"ns/tuple ({dec['framing_gbps']:.2f} GB/s), hash "
              f"{dec['hash_ns_per_tuple']:.1f} ns/tuple, transfer "
              f"{dec['transfer_mbps']:.0f} MB/s -> ingest ceiling "
              f"{dec['ingest_ceiling_tps']/1e6:.1f} M t/s "
              f"(host framing alone: {dec['host_framing_tps']/1e6:.1f} M t/s)",
              file=sys.stderr)
        print(f"ingest-inclusive YSB (host numpy -> prefetch/device_put overlap "
              f"-> full chain): {in_tps/1e6:.2f} M tuples/s ({in_step*1e3:.2f} "
              f"ms/step); measured H2D transport ceiling "
              f"{in_ceiling/1e6:.2f} M t/s at {in_bpt} B/tuple "
              f"[CUDA bar: 16.6M]", file=sys.stderr)

    floor = measure_floor()
    record("floor", floor)
    print(f"environment floor: sync round trip {floor['sync_rtt_ms']:.2f} ms, "
          f"D2H {floor['d2h_mbps']:.1f} MB/s  (tunnel artifact — local PJRT "
          f"measures ~0.1 ms; all latencies below INCLUDE this floor)",
          file=sys.stderr)
    for depth, tag in ((2, "latency-oriented"), (12, "throughput-oriented")):
        curve = bench_latency_curve(depth=depth)
        record(f"latency_curve_depth{depth}", {"rows": curve})
        print(f"window-result latency curve (emission->host receipt, pipelined "
              f"depth={depth}, {tag}):", file=sys.stderr)
        for r in curve:
            dev_p99 = max(r["p99_ms"] - floor["sync_rtt_ms"], r["step_ms"])
            print(f"  batch={r['batch']:6d}: p50 {r['p50_ms']:7.2f} ms  "
                  f"p99 {r['p99_ms']:7.2f} ms  @ {r['tput_mtps']:6.1f} M t/s  "
                  f"(step {r['step_ms']:.2f} ms; device-side p99 bound "
                  f"~{dev_p99:.2f} ms)", file=sys.stderr)


if __name__ == "__main__":
    main()
