"""The Yahoo Streaming Benchmark pipeline at example scale.

EventSource -> Filter(view events) -> campaign join (device table lookup) ->
KeyBy(campaign) -> per-campaign tumbling time window counting views -> sink.
The flagship macro-benchmark (bench.py runs it at 1M-tuple batches on TPU);
this example runs it small and checks the window counts against an oracle.
"""
import _common
_common.select_backend()

import numpy as np
import windflow_tpu as wf
from windflow_tpu.benchmarks import ysb

TOTAL = 40_000
results = []

def sink(view):
    if view is None:
        return
    results.extend(zip(view["key"].tolist(), view["id"].tolist(),
                       np.asarray(view["payload"]).tolist()))

src = ysb.make_source(total=TOTAL)
wf.Pipeline(src, ysb.make_ops(), wf.Sink(sink), batch_size=4096).run()

# oracle: replay the generator's arithmetic on the host
views = [i for i in range(TOTAL) if (i % 3) == 0]
expect = {}
for i in views:
    camp = (i * 7919) % ysb.N_ADS // ysb.ADS_PER_CAMPAIGN
    win = (i // ysb.EVENTS_PER_TICK) // ysb.WIN_LEN
    expect[(camp, win)] = expect.get((camp, win), 0) + 1
got = {(k, w): int(c) for k, w, c in results}
assert got == expect, "window counts diverge from the oracle"
print(f"YSB example OK: {len(got)} windows over {len(set(k for k,_ in got))} campaigns")
