"""Streaming word-count: the hello-world of stream processing.

FlatMap (line -> words, static max_fanout) -> per-key rolling count
(Accumulator, KEYBY routing) -> host sink. Runs on CPU or TPU unchanged.

Counterpart of the reference's basic graph tests (src/graph_test) in spirit:
a tiny end-to-end PipeGraph with a self-checking result.
"""
import _common
_common.select_backend()

import jax.numpy as jnp
import numpy as np
import windflow_tpu as wf

# synthetic "documents": each source item i carries 3 word ids drawn from a
# zipf-ish table; the FlatMap ships one tuple per word
VOCAB = 50

def make_words(i):
    return {"w": jnp.stack([(i * 7) % VOCAB, (i * 13) % VOCAB, (i * 29) % VOCAB])}

def split_words(t, shipper):
    for j in range(3):
        shipper.push({"word": t.w[j]})

counts = {}

def sink(view):
    if view is None:
        return
    for k, v in zip(view["key"].tolist(), np.asarray(view["payload"]).tolist()):
        counts[k] = v            # rolling count per word id

TOTAL = 3000
g = wf.PipeGraph("wordcount", batch_size=256)
(g.add_source(wf.Source(make_words, total=TOTAL))
 .add(wf.FlatMap(split_words, max_fanout=3))
 .add(wf.Map(lambda t: {"one": jnp.ones((), jnp.int32), "word": t.word}))
 .add(wf.KeyBy(lambda t: t.word, num_keys=VOCAB))
 .add(wf.Accumulator(lambda t: t.data["one"], init_value=0, num_keys=VOCAB))
 .add_sink(wf.Sink(sink)))
g.run()

expect = {}
for i in range(TOTAL):
    for w in ((i * 7) % VOCAB, (i * 13) % VOCAB, (i * 29) % VOCAB):
        expect[w] = expect.get(w, 0) + 1
got = {k: int(v) for k, v in counts.items()}
assert got == expect, "word counts diverge from the oracle"
print(f"wordcount OK: {len(got)} words, {sum(got.values())} total")
