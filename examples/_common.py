"""Shared example bootstrap: repo-root import path + backend selection."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def select_backend(virtual_devices: int = 0) -> None:
    """``WF_CPU=1`` forces the CPU backend (config-update form — the env-var
    form is overridden by preloaded TPU plugins and can hang on a wedged device
    link); anything else uses the session's accelerator. When forcing CPU,
    ``virtual_devices`` requests an N-device virtual mesh."""
    if os.environ.get("WF_CPU", "") in ("", "0"):
        return
    if virtual_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={virtual_devices}"
            ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
