"""Fault recovery with an O(1) seekable source, and lossless routing under
deliberate overflow — the two r05 hardening contracts, end to end.

1. A SupervisedPipeline takes injected device faults mid-stream and recovers
   from the last aligned checkpoint. The source's ``it_factory`` declares a
   ``from_batch`` parameter, so restart resumes AT the committed chunk index
   (the factory owns the cursor — here plain arithmetic, in production a file
   offset) instead of replaying the stream. Output must be exactly-once,
   bit-identical to a fault-free run.

2. A Standard_Emitter with a per-destination budget far below one skewed
   key's share must deliver EVERY tuple anyway: overflowing lanes are
   re-partitioned in further passes (the blocking bounded-queue backpressure
   of the reference's FF_BOUNDED_BUFFER — it blocks, it never drops).
"""
import _common
_common.select_backend()

import numpy as np
import jax
import jax.numpy as jnp
import windflow_tpu as wf
from windflow_tpu.basic import routing_modes_t, win_type_t
from windflow_tpu.batch import Batch
from windflow_tpu.operators.source import GeneratorSource
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.parallel.emitters import Standard_Emitter
from windflow_tpu.runtime.supervisor import SupervisedPipeline

TOTAL, BATCH, K = 2000, 100, 4

# ---- 1. supervised recovery through the seekable-source cursor --------------


def factory(from_batch=0):
    """Chunk k is pure arithmetic on k — seeking is O(1). The supervisor calls
    factory(from_batch=committed_chunk) on restart."""
    def gen():
        for s in range(from_batch * BATCH, TOTAL, BATCH):
            ids = np.arange(s, s + BATCH, dtype=np.int32)
            yield ({"v": ((ids * 7) % 31).astype(np.float32)}, ids % K, ids)
    return gen()


def build(sink_cb, **kw):
    src = GeneratorSource(factory, {"v": jnp.zeros((), jnp.float32)})
    op = wf.Win_Seq(lambda wid, it: it.sum("v"),
                    WindowSpec(25, 25, win_type_t.TB), num_keys=K)
    return SupervisedPipeline(src, [op], wf.Sink(sink_cb),
                              batch_size=BATCH, **kw)


def collect(results):
    def cb(view):
        if view is None:
            return
        results.extend(zip(view["key"].tolist(), view["id"].tolist(),
                           np.asarray(view["payload"]).tolist()))
    return cb


golden = []
build(collect(golden)).run()

got = []
p = build(collect(got), checkpoint_every=3, max_restarts=5)
inner, fail_at = p.chain.push, {5, 11}
calls = [0]


def flaky(batch):
    calls[0] += 1
    if calls[0] in fail_at:
        raise RuntimeError(f"injected device fault at push #{calls[0]}")
    return inner(batch)


p.chain.push = flaky
p.run()
assert p.restarts == 2, p.restarts
assert sorted(got) == sorted(golden) and golden, "lost/duplicated results"
print(f"recovery: {p.restarts} faults recovered, "
      f"{len(got)} window results exactly-once, O(1) resume")

# ---- 2. lossless routing under overflow -------------------------------------

rng = np.random.default_rng(3)
C = 256
keys = np.where(rng.random(C) < 0.6, 0, rng.integers(0, 32, C)).astype(np.int32)
valid = rng.random(C) < 0.9
b = Batch(key=jnp.asarray(keys), id=jnp.arange(C, dtype=jnp.int32),
          ts=jnp.zeros(C, jnp.int32),
          payload={"v": jnp.arange(C, dtype=jnp.float32)},
          valid=jnp.asarray(valid))
em = Standard_Emitter(4, routing_modes_t.KEYBY, capacity_per_dest=8)
outs = em.route(b)
delivered = []
for d, ob in enumerate(outs):
    ob = jax.tree.map(np.asarray, ob)
    assert np.all(ob.key[ob.valid] % 4 == d)
    delivered.extend(ob.payload["v"][ob.valid].tolist())
want = [float(i) for i, ok in enumerate(valid) if ok]
assert sorted(delivered) == sorted(want)
print(f"backpressure: {len(want)} tuples through a budget of 8/dest in "
      f"{em.overflow_rounds + 1} passes, zero loss")
print("OK")
