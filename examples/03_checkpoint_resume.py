"""Checkpoint mid-stream, 'crash', restore into a NEW process-fresh pipeline,
and continue — ending bit-identical to an uninterrupted run.

The reference has no checkpointing (state dies with the process,
SURVEY §5); here every operator's state is a pytree, so save/restore is
np.savez of the chain (runtime/checkpoint.py). The same mechanism powers
supervised exactly-once recovery (SupervisedPipeline) and elastic mesh
rescaling.
"""
import _common
_common.select_backend()

import os

import tempfile
import jax.numpy as jnp
import numpy as np
import windflow_tpu as wf
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.basic import win_type_t

TOTAL, BATCH, K = 4000, 256, 8

def make_chain():
    src = wf.Source(lambda i: {"v": ((i * 13) % 23).astype(jnp.float32)},
                    total=TOTAL, num_keys=K)
    op = wf.Key_FFAT(lambda t: t.v, jnp.add,
                     spec=WindowSpec(64, 32, win_type_t.CB), num_keys=K)
    chain = wf.CompiledChain([op], src.payload_spec(), batch_capacity=BATCH)
    return src, chain

def collect(out, batch):
    v = np.asarray(batch.valid)
    out.extend(zip(np.asarray(batch.key)[v].tolist(),
                   np.asarray(batch.id)[v].tolist(),
                   np.asarray(batch.payload)[v].tolist()))

# ---- golden: uninterrupted run
src, chain = make_chain()
golden = []
for b in src.batches(BATCH):
    collect(golden, chain.push(b))
for fb in chain.flush():
    collect(golden, fb)

# ---- interrupted run: checkpoint at the half-way batch, then "crash"
src, chain = make_chain()
part1, seen = [], 0
ckpt = os.path.join(tempfile.mkdtemp(), "chain.npz")
for b in src.batches(BATCH):
    collect(part1, chain.push(b))
    seen += BATCH
    if seen >= TOTAL // 2:
        wf.save_chain(chain, ckpt, meta={"position": seen})
        break
del chain                                  # the "crash"

# ---- resume: fresh chain, restore state, fast-forward the source
src2, chain2 = make_chain()
meta = wf.load_chain(chain2, ckpt)
pos = meta["position"]
part2 = []
it = src2.batches(BATCH)
for _ in range(pos // BATCH):          # replayable source: skip committed batches
    next(it)
for b in it:
    collect(part2, chain2.push(b))
for fb in chain2.flush():
    collect(part2, fb)

assert sorted(part1 + part2) == sorted(golden), "resume diverged from golden run"
print(f"checkpoint/resume OK: {len(part1)}+{len(part2)} window results == "
      f"{len(golden)} golden")
