"""The serving front door, end to end: Nexmark bid records arrive over a
REAL socket as WFS1 frames, are admitted under per-tenant budgets, flow
through the compiled Q1 currency-conversion query, and the graph is
hot-swapped mid-stream — all without dropping or reordering a single
committed tuple.

1. Socket ingest + zero-downtime swap: two tenants stream binary bid
   chunks through a ``SocketSource``; halfway in, a wire ``swap`` frame cuts
   the runtime over to a registered twin graph (same math — so the output
   must stay byte-identical to a plain in-process ``RecordSource`` oracle,
   REGARDLESS of which batch the cutover lands on). The swap is warmed
   before cutover and journaled as a ``graph_swap`` span.

2. Tenant isolation: a noisy tenant with a tight deterministic bucket is
   shed under ITS budget while the quiet tenant — same socket, same run —
   is never shed and every one of its bids reaches the sink.
"""
import _common
_common.select_backend()

import json
import os
import shutil
import tempfile

import numpy as np

import windflow_tpu as wf
from windflow_tpu.nexmark.queries import EURO_DEN, EURO_NUM
from windflow_tpu.serving import RecordClient, ServingRuntime, SocketSource

BATCH = 50
N_AUCTIONS = 8
#: the bid stream's wire schema — one fixed record dtype, keyed by auction
DT = np.dtype([("auction", np.int32), ("ts", np.int64),
               ("price", np.int32)])


def make_chunks(n_chunks, base_price):
    out = []
    for i in range(n_chunks):
        ids = np.arange(i * BATCH, (i + 1) * BATCH)
        rec = np.zeros(BATCH, dtype=DT)
        rec["auction"] = (ids * 2477) % N_AUCTIONS
        rec["ts"] = ids
        rec["price"] = base_price + (ids * 7919) % 9000 + 100
        out.append(rec)
    return out


def q1_ops():
    """Nexmark Q1: per-bid dollar -> euro currency projection (the auction
    id rides the batch's key lane — RecordSource pulled it out of the
    payload as key_field)."""
    return [wf.Map(lambda t: {"euro": (t.price * EURO_NUM) // EURO_DEN},
                   name="nexmark_currency")]


def collect(acc):
    def cb(view):
        if view is not None:
            acc.extend(zip(view["id"].tolist(),
                           np.asarray(view["payload"]["euro"]).tolist()))
    return cb


def serve(tenants, chunks, tenant_of, *, swap_at=None, eos_tenant="a"):
    """Stand up a ServingRuntime on an ephemeral loopback port, stream the
    chunks through a RecordClient, return (results, runtime, mon_dir)."""
    mon_dir = tempfile.mkdtemp(prefix="wf_example_serve_")
    got = []
    src = SocketSource("tcp://127.0.0.1:0", DT, key_field="auction",
                       ts_field="ts", num_keys=N_AUCTIONS,
                       replay=len(chunks) + 8)
    rt = ServingRuntime(src, q1_ops(), wf.Sink(collect(got)),
                        batch_size=BATCH, serving={"tenants": tenants},
                        monitoring=mon_dir)
    rt.register_graph("q1_v2", q1_ops())      # the swap candidate (twin math)
    src.start()                               # .endpoint now has the real port
    thread = rt.run_background()
    client = RecordClient(src.endpoint)
    for i, chunk in enumerate(chunks):
        client.send(chunk.tobytes(), tenant=tenant_of[i])
        if swap_at is not None and i == swap_at:
            client.send_swap("q1_v2")         # hot-swap, from the wire
    client.send_eos(eos_tenant)
    client.close()
    thread.join(timeout=60.0)
    assert not thread.is_alive(), "serving drive did not reach EOS"
    if rt.background_error is not None:
        raise rt.background_error
    return got, rt, mon_dir


# ---- 1. socket ingest + zero-downtime hot-swap ------------------------------

chunks = make_chunks(40, base_price=0)
tenant_of = ["a" if i % 2 == 0 else "b" for i in range(len(chunks))]

# oracle: the SAME bids through a plain in-process RecordSource pipeline
oracle = []
wf.Pipeline(wf.RecordSource(lambda: iter(chunks), DT, key_field="auction",
                            ts_field="ts", num_keys=N_AUCTIONS),
            q1_ops(), wf.Sink(collect(oracle)), batch_size=BATCH).run()

got, rt, mon_dir = serve([{"id": "a"}, {"id": "b"}], chunks, tenant_of,
                         swap_at=len(chunks) // 2)
assert rt.swaps_applied == 1 and rt.graph_label == "q1_v2", (
    rt.swaps_applied, rt.graph_label)
assert sorted(got) == sorted(oracle) and oracle, \
    "serving output diverged from the RecordSource oracle across the swap"

# query the service the way an operator would: the monitoring snapshot
snap = json.load(open(os.path.join(mon_dir, "snapshot.json")))
sv = snap["serving"]
assert sv["graph"] == "q1_v2" and sv["swaps_applied"] == 1
shutil.rmtree(mon_dir, ignore_errors=True)
print(f"hot-swap: {len(got)} Q1 results over tcp, swap to {sv['graph']!r} "
      f"mid-stream, byte-identical to the oracle")

# ---- 2. noisy-tenant isolation ----------------------------------------------

# quiet bids carry prices >= 100_000 so their euro results are recognizable
# in the shared sink; noisy gets a tight deterministic bucket (burst = 1
# batch, refill 10 tuples per offered batch) and MUST shed — quiet never.
quiet_chunks = make_chunks(20, base_price=100_000)
noisy_chunks = make_chunks(20, base_price=0)
mixed, tenant_of = [], []
for q, n in zip(quiet_chunks, noisy_chunks):
    mixed += [q, n]
    tenant_of += ["quiet", "noisy"]

got, rt, mon_dir = serve(
    [{"id": "quiet"},
     {"id": "noisy", "refill_per_batch": 10.0, "burst": float(BATCH)}],
    mixed, tenant_of, eos_tenant="quiet")
rows = rt.serving_section()["tenants"]
assert rows["noisy"]["shed"] > 0, rows
assert rows["quiet"]["shed"] == 0 and rows["quiet"]["shed_tuples"] == 0, rows
quiet_floor = (100_000 * EURO_NUM) // EURO_DEN
quiet_out = [e for _, e in got if e >= quiet_floor]
want = sum(len(c) for c in quiet_chunks)
assert len(quiet_out) == want, (len(quiet_out), want)
shutil.rmtree(mon_dir, ignore_errors=True)
print(f"isolation: noisy shed {rows['noisy']['shed']} batches under its own "
      f"budget; quiet delivered {len(quiet_out)}/{want}, zero shed")
print("OK")
