"""Multi-chip execution: the same keyed-window pipeline sharded over a device
mesh — batch axis on ``dp`` (operator replication), key-state tables on ``key``
(Key_Farm whole-key ownership) — and verified oracle-identical to the
single-device run.

Run with real chips, or anywhere with a virtual mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 WF_CPU=1 \
        python examples/04_multichip.py
"""
import _common
_common.select_backend(virtual_devices=8)

import sys

import jax
import jax.numpy as jnp
import numpy as np
import windflow_tpu as wf
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.basic import win_type_t

TOTAL, BATCH, K = 8000, 512, 16

def make_chain():
    src = wf.Source(lambda i: {"v": ((i * 7) % 31).astype(jnp.float32)},
                    total=TOTAL, num_keys=K)
    op = wf.Key_FFAT(lambda t: t.v, jnp.add,
                     spec=WindowSpec(50, 25, win_type_t.TB), num_keys=K)
    return src, wf.CompiledChain([op], src.payload_spec(), batch_capacity=BATCH)

def run(sharded):
    src, chain = make_chain()
    if sharded:
        n = min(8, jax.device_count())
        mesh = wf.make_mesh_2d((2, n // 2), axes=("dp", "key"))
        chain = wf.ShardedChain(chain, mesh, axis="dp", key_axis="key")
    out = []
    for b in src.batches(BATCH):
        ob = chain.push(b)
        v = np.asarray(ob.valid)
        out.extend(zip(np.asarray(ob.key)[v].tolist(),
                       np.asarray(ob.id)[v].tolist(),
                       np.asarray(ob.payload)[v].tolist()))
    for fb in (chain.flush() or []):
        v = np.asarray(fb.valid)
        out.extend(zip(np.asarray(fb.key)[v].tolist(),
                       np.asarray(fb.id)[v].tolist(),
                       np.asarray(fb.payload)[v].tolist()))
    return sorted(out)

if jax.device_count() < 2:
    print("multichip example needs >= 2 devices: run with real chips or\n"
          "  WF_CPU=1 python examples/04_multichip.py   (8-device virtual mesh)")
    sys.exit(1)

single = run(sharded=False)
multi = run(sharded=True)
assert single == multi and single, "sharded run diverged from single-device oracle"
print(f"multichip OK: {len(multi)} window results identical on the "
      f"{min(8, jax.device_count())}-device mesh")
