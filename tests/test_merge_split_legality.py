"""Merge/split semantic parity with the reference.

Legality (wf/pipegraph.hpp:2992-3026 entry checks; :813-965 structural cases):
illegal topologies must raise; the reference merge_test/split_test DAG shapes
(src/merge_test/test_merge_{1..4}.cpp, src/split_test/test_split_{1..5}.cpp)
must match dense oracles at multiple batch sizes under both drivers."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.runtime.pipegraph import PipeGraph


def src(total=90, mod=7, name="s"):
    return wf.Source(lambda i: {"v": (i % mod).astype(jnp.float32)}, total=total,
                     num_keys=2, name=name)


def collector(acc):
    def cb(view):
        if view is None:
            return
        p = view["payload"]
        leaf = p["v"] if isinstance(p, dict) else p
        acc.extend(np.asarray(leaf).tolist())
    return wf.Sink(cb)


# ---------------- legality rejections ------------------------------------------

def test_merge_self_rejected():
    g = PipeGraph()
    a = g.add_source(src())
    with pytest.raises(RuntimeError, match="merged with itself"):
        a.merge(a)


def test_merge_foreign_pipe_rejected():
    g1, g2 = PipeGraph(), PipeGraph()
    a = g1.add_source(src())
    b = g2.add_source(src())
    with pytest.raises(RuntimeError, match="does not belong"):
        a.merge(b)


def test_merge_already_merged_rejected():
    g = PipeGraph()
    a, b, c = (g.add_source(src(name=n)) for n in "abc")
    a.merge(b)
    with pytest.raises(RuntimeError, match="already been merged"):
        a.merge(c)


def test_merge_split_pipe_rejected():
    g = PipeGraph()
    a = g.add_source(src()).split(lambda t: t.v % 2 == 0, 2)
    b = g.add_source(src(name="b"))
    with pytest.raises(RuntimeError, match="split MultiPipe cannot be merged"):
        a.merge(b)


def test_merge_sunk_pipe_rejected():
    g = PipeGraph()
    a = g.add_source(src()).add_sink(collector([]))
    b = g.add_source(src(name="b"))
    with pytest.raises(RuntimeError, match="sink"):
        b.merge(a)


def test_merge_noncontiguous_siblings_rejected():
    g = PipeGraph()
    s = g.add_source(src()).split(lambda t: jnp.int32(t.v) % 3, 3)
    with pytest.raises(RuntimeError, match="contiguous"):
        s.select(0).merge(s.select(2))


def test_merge_mixed_root_and_branch_rejected():
    g = PipeGraph()
    s = g.add_source(src()).split(lambda t: jnp.int32(t.v) % 2, 2)
    b = g.add_source(src(name="b"))
    with pytest.raises(RuntimeError, match="not supported"):
        s.select(0).merge(b)


def test_merge_branches_of_different_splits_rejected():
    g = PipeGraph()
    s1 = g.add_source(src(name="s1")).split(lambda t: jnp.int32(t.v) % 2, 2)
    s2 = g.add_source(src(name="s2")).split(lambda t: jnp.int32(t.v) % 2, 2)
    with pytest.raises(RuntimeError, match="different split parents"):
        s1.select(0).merge(s2.select(0))


def test_merge_contiguous_siblings_legal():
    g = PipeGraph()
    s = g.add_source(src()).split(lambda t: jnp.int32(t.v) % 3, 3)
    s.select(0).merge(s.select(1))     # contiguous: legal (merge-partial)


def test_merge_whole_subtree_legal():
    g = PipeGraph()
    s = g.add_source(src()).split(lambda t: jnp.int32(t.v) % 3, 3)
    s.select(0).merge(s.select(1), s.select(2))   # merge-full


# ---------------- reference DAG shapes with dense oracles -----------------------

def vals(total=90, mod=7):
    return [float(i % mod) for i in range(total)]


@pytest.mark.parametrize("batch_size,threaded", [(32, False), (77, False),
                                                 (45, True)])
def test_merge_three_roots_shape(batch_size, threaded):
    """test_merge_2.cpp: three source pipelines merged into one (merge-ind)."""
    g = PipeGraph(batch_size=batch_size)
    a = g.add_source(src(name="a")).add(wf.Map(lambda t: {"v": t.v + 1}))
    b = g.add_source(src(mod=5, name="b")).add(wf.Map(lambda t: {"v": t.v + 2}))
    c = (g.add_source(src(mod=3, name="c"))
         .add(wf.Filter(lambda t: t.v > 0))
         .add(wf.Map(lambda t: {"v": t.v * 2})))
    out = []
    a.merge(b, c).add(wf.Map(lambda t: {"v": t.v * 10})).add_sink(collector(out))
    g.run(threaded=threaded)
    want = ([10 * (v + 1) for v in vals()] + [10 * (v + 2) for v in vals(mod=5)]
            + [10 * (v * 2) for v in vals(mod=3) if v > 0])
    assert sorted(out) == sorted(want)


@pytest.mark.parametrize("batch_size,threaded", [(32, False), (60, True)])
def test_merge_of_merged_shape(batch_size, threaded):
    """test_merge_3/4.cpp: a merged pipe (extended by an operator) merged again
    with a third root — merge-ind over a merged result."""
    g = PipeGraph(batch_size=batch_size)
    a = g.add_source(src(name="a"))
    b = g.add_source(src(mod=5, name="b"))
    m1 = a.merge(b).add(wf.Filter(lambda t: t.v % 2 == 0))
    c = g.add_source(src(mod=3, name="c"))
    out = []
    m1.merge(c).add(wf.Map(lambda t: {"v": t.v + 100})).add_sink(collector(out))
    g.run(threaded=threaded)
    want = ([v + 100 for v in vals() + vals(mod=5) if v % 2 == 0]
            + [v + 100 for v in vals(mod=3)])
    assert sorted(out) == sorted(want)


@pytest.mark.parametrize("batch_size,threaded", [(32, False), (45, False),
                                                 (60, True)])
def test_split_then_partial_merge_shape(batch_size, threaded):
    """test_split_3.cpp topology + merge-partial: split into 3 predicate
    branches, rejoin the two contiguous ones, third sinks alone."""
    g = PipeGraph(batch_size=batch_size)
    s = g.add_source(src()).split(lambda t: jnp.int32(t.v) % 3, 3)
    rejoined, solo = [], []
    (s.select(0).merge(s.select(1))
     .add(wf.Map(lambda t: {"v": t.v * 10})).add_sink(collector(rejoined)))
    s.select(2).add_sink(collector(solo))
    g.run(threaded=threaded)
    want_rejoin = [v * 10 for v in vals() if int(v) % 3 in (0, 1)]
    want_solo = [v for v in vals() if int(v) % 3 == 2]
    assert sorted(rejoined) == sorted(want_rejoin)
    assert sorted(solo) == sorted(want_solo)


@pytest.mark.parametrize("batch_size", [32, 64])
def test_nested_split_with_window_leaf_shape(batch_size):
    """test_split_4/5.cpp: a nested split whose leaf is a keyed windowed
    pattern (KF) while the sibling leaf is a plain sink and the other outer
    branch runs a FlatMap (bool split routes False->0, True->1)."""
    g = PipeGraph(batch_size=batch_size)
    s0 = g.add_source(src(total=120)).split(lambda t: t.v % 2 == 0, 2)
    # select(1): even v -> Map(+1) makes odd w in {1,3,5,7}; inner split on
    # (w//2)%2 puts {1,5} on branch 0 and {3,7} on branch 1
    inner = (s0.select(1).add(wf.Map(lambda t: {"v": t.v + 1}))
             .split(lambda t: jnp.int32(t.v) // 2 % 2, 2))
    win_out, plain_out, fm_out = [], [], []
    (inner.select(1)
     .add(wf.Key_FFAT(lambda t: t.v, jnp.add,
                      spec=WindowSpec(4, 4, win_type_t.CB), num_keys=2))
     .add_sink(collector(win_out)))
    inner.select(0).add_sink(collector(plain_out))
    (s0.select(0)
     .add(wf.FlatMap(lambda t, sh: sh.push({"v": t.v * 2}), max_fanout=1))
     .add_sink(collector(fm_out)))
    g.run()

    # oracle
    stream = vals(120)
    per_key = {}
    for i, v in enumerate(stream):
        if v % 2 == 0:
            w = v + 1
            if int(w) // 2 % 2 == 1:
                per_key.setdefault(i % 2, []).append(w)
    want_win = []
    for k, xs in per_key.items():
        full = len(xs) - len(xs) % 4
        want_win.extend(sum(xs[j:j + 4]) for j in range(0, full, 4))
        if xs[full:]:
            want_win.append(sum(xs[full:]))   # EOS flush of the partial window
    assert sorted(win_out) == sorted(float(x) for x in want_win) and win_out
    want_plain = [v + 1 for v in stream if v % 2 == 0 and int(v + 1) // 2 % 2 == 0]
    assert sorted(plain_out) == sorted(want_plain)
    want_fm = [v * 2 for v in stream if v % 2 == 1]
    assert sorted(fm_out) == sorted(want_fm)


@pytest.mark.parametrize("threaded", [False, True])
def test_partial_merge_chain_absorbs_merged_sibling(threaded):
    """4-branch split; merge(b0,b1) is merge-partial; merging that RESULT with
    b2 is still partial (covers {0,1,2} — the absorbed sibling is itself a
    merged pipe, not a split branch); the app tree must track the replacement
    so the final sink composition runs. Dense oracle under both drivers."""
    g = PipeGraph(batch_size=32)
    mp = g.add_source(wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=200))
    mp.split(lambda t: (t.v % 4).astype(jnp.int32), 4)
    def mk(m):
        return wf.Map(lambda t: {"v": t.v * m})
    b = [mp.select(i).chain(mk(10 ** i)) for i in range(4)]
    m01 = b[0].merge(b[1])
    m012 = m01.merge(b[2])
    assert m01._merge_parent is mp and m01._covers_idx == (0, 1)
    assert m012._merge_parent is mp and m012._covers_idx == (0, 1, 2)
    # app tree: children of mp's node are now [m012's leaf, b3's leaf]
    node = g._node_of(mp)
    assert [c.mp for c in node.children] == [m012, b[3]]
    m012.add(wf.ReduceSink(lambda t: t.v, name="m"))
    b[3].add(wf.ReduceSink(lambda t: t.v, name="r3"))
    res = {k: int(v) for k, v in g.run(threaded=threaded).items()}
    expect_m = sum(v * 10 ** (v % 4) for v in range(200) if v % 4 < 3)
    assert res["m"] == expect_m
    assert res["r3"] == sum(v * 1000 for v in range(200) if v % 4 == 3)
