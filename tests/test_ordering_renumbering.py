"""Ordering_Node modes and the DETERMINISTIC broadcast+renumbering case.

Reference: ``wf/ordering_node.hpp:47-287`` (ID/TS/TS_RENUMBERING release,
renumbering at ``:218,257``) and the count-based-windows-after-shuffle rule at
``wf/pipegraph.hpp:1954-1957`` — a CB windowed operator downstream of a
DETERMINISTIC merge must see tuples in deterministic (ts) arrival order with
progressive ids, or the per-key window contents depend on merge scheduling.
"""

import numpy as np
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import Mode, ordering_mode_t, win_type_t
from windflow_tpu.batch import Batch, CTRL_DTYPE
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.parallel.ordering import Ordering_Node
from windflow_tpu.runtime.pipegraph import PipeGraph


def mk_batch(ids, ts=None, vals=None):
    ids = np.asarray(ids, np.int32)
    ts = ids if ts is None else np.asarray(ts, np.int32)
    vals = ids.astype(np.float32) if vals is None else np.asarray(vals, np.float32)
    return Batch(key=jnp.zeros(len(ids), CTRL_DTYPE), id=jnp.asarray(ids),
                 ts=jnp.asarray(ts), payload={"v": jnp.asarray(vals)},
                 valid=jnp.ones(len(ids), bool))


def drain(node, pushes):
    """Push (channel, batch) pairs then flush; return the released id sequence."""
    out = []

    def take(b):
        if b is None:
            return
        v = np.asarray(b.valid)
        out.extend(np.asarray(b.id)[v].tolist())

    for ch, b in pushes:
        take(node.push(ch, b))
    take(node.flush())
    return out


def test_ordering_node_id_mode_low_watermark():
    node = Ordering_Node(2, ordering_mode_t.ID)
    rel = node.push(0, mk_batch([3, 1, 5]))
    assert rel is None or not bool(np.asarray(rel.valid).any())  # ch1 has no wm yet
    rel = node.push(1, mk_batch([2, 4]))
    # low watermark = min(max ids) = min(5, 4) = 4 -> ids <= 4 release, sorted
    got = np.asarray(rel.id)[np.asarray(rel.valid)].tolist()
    assert got == [1, 2, 3, 4]
    final = drain(node, [])
    assert final == [5]


def test_ordering_node_ts_mode_interleave():
    node = Ordering_Node(2, ordering_mode_t.TS)
    got = drain(node, [(0, mk_batch([0, 1], ts=[0, 20])),
                       (1, mk_batch([10, 11], ts=[10, 30])),
                       (0, mk_batch([2], ts=[40])),
                       (1, mk_batch([12], ts=[50]))])
    # ids in ts order: ts 0,10,20,30,40,50 -> ids 0,10,1,11,2,12
    assert got == [0, 10, 1, 11, 2, 12]


def test_ordering_node_ts_renumbering_progressive_ids():
    node = Ordering_Node(2, ordering_mode_t.TS_RENUMBERING)
    got = drain(node, [(0, mk_batch([100, 200], ts=[5, 15])),
                       (1, mk_batch([300, 400], ts=[10, 20]))])
    # renumbered: progressive ids 0..n-1 in ts order regardless of original ids
    assert got == [0, 1, 2, 3]


def test_ordering_node_equal_ts_ties_are_deterministic():
    # equal (ts, id) pairs on both channels: channel index is the final tiebreak,
    # so release order never depends on push interleaving
    def payload_seq(pushes):
        node = Ordering_Node(2, ordering_mode_t.TS)
        out = []
        for ch, b in pushes:
            r = node.push(ch, b)
            if r is not None:
                out.extend(np.asarray(r.payload["v"])[np.asarray(r.valid)].tolist())
        r = node.flush()
        if r is not None:
            out.extend(np.asarray(r.payload["v"])[np.asarray(r.valid)].tolist())
        return out

    b0 = mk_batch([0, 1], ts=[5, 5], vals=[10.0, 11.0])
    b1 = mk_batch([0, 1], ts=[5, 5], vals=[20.0, 21.0])
    a = payload_seq([(0, b0), (1, b1)])
    b = payload_seq([(1, b1), (0, b0)])
    assert a == b == [10.0, 20.0, 11.0, 21.0]   # (ts, id, channel) total order


def test_unbalanced_merge_releases_early_in_push_driver():
    """A short source exhausting must stop gating (and hoarding) the long one."""
    g = PipeGraph("unbal", batch_size=16, mode=Mode.DETERMINISTIC)
    sa = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=16, num_keys=1,
                   ts_fn=lambda i: i, name="short")
    sb = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=512, num_keys=1,
                   ts_fn=lambda i: i, name="long")
    pa, pb = g.add_source(sa), g.add_source(sb)
    m = pa.merge(pb)
    seen = []
    m.add(wf.Map(lambda t: {"v": t.v})).add_sink(
        wf.Sink(lambda v: v is not None and seen.extend(
            np.asarray(v["payload"]["v"]).tolist())))
    g.run()
    # all 528 tuples arrive; the Ordering_Node did not hold the long tail hostage
    assert len(seen) == 528
    node = m._ordering
    assert node is not None and node._pending is None


def test_ordering_node_channel_eos_unblocks():
    node = Ordering_Node(2, ordering_mode_t.TS)
    held = node.push(0, mk_batch([1, 2], ts=[1, 2]))          # ch1 silent: held
    assert held is None or not bool(np.asarray(held.valid).any())
    assert node.last_release_count == 0
    rel = node.close_channel(1)                               # ch1 EOS: stops gating
    got = np.asarray(rel.id)[np.asarray(rel.valid)].tolist()
    # ts=1 < ch0's watermark (2) releases; ts=2 == the watermark is a potential
    # tie (ch0 may still deliver more ts=2) and stays held until ch0 closes
    assert got == [1]
    rel2 = node.close_channel(0)
    got2 = np.asarray(rel2.id)[np.asarray(rel2.valid)].tolist()
    assert got2 == [2]


def test_long_stream_backlog_stays_bounded():
    """Soak: 200 alternating pushes through one Ordering_Node. The retained
    pool's capacity must stay bounded by ~2x the held-back backlog (pow2 trim),
    NOT grow with stream length — the memory guarantee that makes DETERMINISTIC
    mode usable on unbounded streams."""
    from windflow_tpu.parallel.ordering import Ordering_Node
    B = 1024
    node = Ordering_Node(2, ordering_mode_t.TS)
    released = 0
    max_cap = 0
    for i in range(200):
        ch = i % 2
        ids = np.arange(i * B, (i + 1) * B, dtype=np.int32)
        b = Batch(key=jnp.zeros(B, jnp.int32), id=jnp.asarray(ids),
                  ts=jnp.asarray(2 * ids + ch),
                  payload={"v": jnp.zeros(B, jnp.float32)},
                  valid=jnp.ones(B, bool))
        out = node.push(ch, b)
        if out is not None:
            released += node.last_release_count
        if node._pending is not None:
            max_cap = max(max_cap, node._pending.capacity)
    tail = node.flush()
    if tail is not None:
        released += node.last_release_count
    assert released == 200 * B                  # nothing lost
    # the two channels interleave tightly: backlog is ~1 batch; the pool must
    # never have grown beyond a few batches' pow2 envelope
    assert max_cap <= 8 * B, max_cap


K = 2


def run_cb(batch_size, swap=False, threaded=False):
    """CB windows downstream of a DETERMINISTIC merge (renumbering case)."""
    g = PipeGraph("det_cb", batch_size=batch_size, mode=Mode.DETERMINISTIC)
    sa = wf.Source(lambda i: {"v": (i % 5).astype(jnp.float32)}, total=100,
                   num_keys=K, ts_fn=lambda i: 2 * i, name="even_ts")
    sb = wf.Source(lambda i: {"v": (i % 7).astype(jnp.float32)}, total=100,
                   num_keys=K, ts_fn=lambda i: 2 * i + 1, name="odd_ts")
    pa, pb = g.add_source(sa), g.add_source(sb)
    m = pb.merge(pa) if swap else pa.merge(pb)
    out = []

    def cb(view):
        if view is None:
            return
        out.extend((int(k), int(w), round(float(r), 4)) for k, w, r in
                   zip(view["key"].tolist(), view["id"].tolist(),
                       np.asarray(view["payload"]).tolist()))

    m.add(wf.Win_Seq(lambda wid, it: it.sum("v"),
                     WindowSpec(10, 10, win_type_t.CB),
                     num_keys=K)).add_sink(wf.Sink(cb))
    g.run(threaded=threaded)
    return sorted(out)


def cb_oracle():
    """Per-key ts-ordered arrival stream chunked into CB windows of 10."""
    per_key = {k: [] for k in range(K)}
    rows = []
    for i in range(100):
        rows.append((2 * i, i % K, i % 5))
        rows.append((2 * i + 1, i % K, i % 7))
    for ts, k, v in sorted(rows):
        per_key[k].append(v)
    want = []
    for k, vs in per_key.items():
        for w in range(0, -(-len(vs) // 10)):
            want.append((k, w, round(float(sum(vs[10 * w:10 * w + 10])), 4)))
    return sorted(want)


@pytest.mark.parametrize("batch_size", [32, 77, 200])
def test_deterministic_cb_windows_after_merge(batch_size):
    assert run_cb(batch_size) == cb_oracle()


def test_deterministic_cb_invariant_operand_order_and_driver():
    base = run_cb(50)
    assert run_cb(50, swap=True) == base
    assert run_cb(50, threaded=True) == base
    assert run_cb(80, swap=True, threaded=True) == base
