"""Randomized absolute-oracle fuzz of the CB window engine: 12 random
(win_len, slide, keys, batch, reducer) configurations checked against a
pure-Python windowing reference (per-key arrival positions, sliding windows
[w*s, w*s+L), EOS flush of non-empty partial windows) — the strongest §4
evidence: not just invariance, absolute semantics."""

import numpy as np
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.operators.window import WindowSpec

RNG = np.random.default_rng(42)
CASES = []
for _ in range(12):
    L = int(RNG.integers(2, 24))
    CASES.append((L, int(RNG.integers(1, L + 1)), int(RNG.integers(1, 6)),
                  int(RNG.integers(16, 120)), RNG.choice(["sum", "max"])))


def py_oracle(total, K, L, S, red):
    per_key = {}
    for i in range(total):
        per_key.setdefault(i % K, []).append(float((i * 17) % 23))
    out = []
    for k, xs in per_key.items():
        n = len(xs)
        w = 0
        while w * S < n:                        # windows with any content
            seg = xs[w * S: w * S + L]
            if seg:
                out.append((k, w, float(sum(seg) if red == "sum" else max(seg))))
            w += 1
    return sorted(out)


@pytest.mark.parametrize("L,S,K,batch,red", CASES)
def test_cb_windows_absolute_oracle(L, S, K, batch, red):
    total = 10 * max(L, batch) // 2 + 37        # odd, spans many windows
    src = wf.Source(lambda i: {"v": ((i * 17) % 23).astype(jnp.float32)},
                    total=total, num_keys=K)
    fn = (lambda wid, it: it.sum("v")) if red == "sum" else \
         (lambda wid, it: it.max("v"))
    got = []

    def cb(view):
        if view is None:
            return
        got.extend((int(k), int(w), float(r)) for k, w, r in
                   zip(view["key"].tolist(), view["id"].tolist(),
                       np.asarray(view["payload"]).tolist()))

    wf.Pipeline(src, [wf.Win_Seq(fn, WindowSpec(L, S, win_type_t.CB),
                                 num_keys=K)],
                wf.Sink(cb), batch_size=batch).run()
    assert sorted(got) == py_oracle(total, K, L, S, red), \
        f"L={L} S={S} K={K} batch={batch} {red}"


TB_CASES = []
for _ in range(8):
    L = int(RNG.integers(2, 30))
    TB_CASES.append((L, int(RNG.integers(1, L + 1)), int(RNG.integers(1, 5)),
                     int(RNG.integers(16, 100)), int(RNG.integers(1, 5))))


def py_oracle_tb(total, K, L, S, rate):
    """TB windows over monotone event time ts = i // rate: window w covers
    [w*S, w*S+L); every non-empty window eventually emits (fired or flushed)."""
    per_key = {}
    for i in range(total):
        per_key.setdefault(i % K, []).append((i // rate, float((i * 17) % 23)))
    out = []
    for k, tv in per_key.items():
        max_ts = max(t for t, _ in tv)
        w = 0
        while w * S <= max_ts:
            seg = [v for t, v in tv if w * S <= t < w * S + L]
            if seg:
                out.append((k, w, float(sum(seg))))
            w += 1
    return sorted(out)


@pytest.mark.parametrize("L,S,K,batch,rate", TB_CASES)
def test_tb_windows_absolute_oracle(L, S, K, batch, rate):
    total = 6 * max(L * rate, batch) + 29
    src = wf.Source(lambda i: {"v": ((i * 17) % 23).astype(jnp.float32)},
                    total=total, num_keys=K, ts_fn=lambda i: i // rate)
    got = []

    def cb(view):
        if view is None:
            return
        got.extend((int(k), int(w), float(r)) for k, w, r in
                   zip(view["key"].tolist(), view["id"].tolist(),
                       np.asarray(view["payload"]).tolist()))

    wf.Pipeline(src, [wf.Win_Seq(lambda wid, it: it.sum("v"),
                                 WindowSpec(L, S, win_type_t.TB),
                                 num_keys=K, tb_capacity=4 * total)],
                wf.Sink(cb), batch_size=batch).run()
    assert sorted(got) == py_oracle_tb(total, K, L, S, rate), \
        f"L={L} S={S} K={K} batch={batch} rate={rate}"
