"""Randomized absolute-oracle fuzz of the CB window engine: 12 random
(win_len, slide, keys, batch, reducer) configurations checked against a
pure-Python windowing reference (per-key arrival positions, sliding windows
[w*s, w*s+L), EOS flush of non-empty partial windows) — the strongest §4
evidence: not just invariance, absolute semantics."""

import numpy as np
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.operators.window import WindowSpec

RNG = np.random.default_rng(42)
CASES = []
for _ in range(12):
    L = int(RNG.integers(2, 24))
    CASES.append((L, int(RNG.integers(1, L + 1)), int(RNG.integers(1, 6)),
                  int(RNG.integers(16, 120)), RNG.choice(["sum", "max"])))


def py_oracle(total, K, L, S, red):
    per_key = {}
    for i in range(total):
        per_key.setdefault(i % K, []).append(float((i * 17) % 23))
    out = []
    for k, xs in per_key.items():
        n = len(xs)
        w = 0
        while w * S < n:                        # windows with any content
            seg = xs[w * S: w * S + L]
            if seg:
                out.append((k, w, float(sum(seg) if red == "sum" else max(seg))))
            w += 1
    return sorted(out)


@pytest.mark.parametrize("L,S,K,batch,red", CASES)
def test_cb_windows_absolute_oracle(L, S, K, batch, red):
    total = 10 * max(L, batch) // 2 + 37        # odd, spans many windows
    src = wf.Source(lambda i: {"v": ((i * 17) % 23).astype(jnp.float32)},
                    total=total, num_keys=K)
    fn = (lambda wid, it: it.sum("v")) if red == "sum" else \
         (lambda wid, it: it.max("v"))
    got = []

    def cb(view):
        if view is None:
            return
        got.extend((int(k), int(w), float(r)) for k, w, r in
                   zip(view["key"].tolist(), view["id"].tolist(),
                       np.asarray(view["payload"]).tolist()))

    wf.Pipeline(src, [wf.Win_Seq(fn, WindowSpec(L, S, win_type_t.CB),
                                 num_keys=K)],
                wf.Sink(cb), batch_size=batch).run()
    assert sorted(got) == py_oracle(total, K, L, S, red), \
        f"L={L} S={S} K={K} batch={batch} {red}"


TB_CASES = []
for _ in range(8):
    L = int(RNG.integers(2, 30))
    TB_CASES.append((L, int(RNG.integers(1, L + 1)), int(RNG.integers(1, 5)),
                     int(RNG.integers(16, 100)), int(RNG.integers(1, 5))))


def py_oracle_tb(total, K, L, S, rate):
    """TB windows over monotone event time ts = i // rate: window w covers
    [w*S, w*S+L); every non-empty window eventually emits (fired or flushed)."""
    per_key = {}
    for i in range(total):
        per_key.setdefault(i % K, []).append((i // rate, float((i * 17) % 23)))
    out = []
    for k, tv in per_key.items():
        max_ts = max(t for t, _ in tv)
        w = 0
        while w * S <= max_ts:
            seg = [v for t, v in tv if w * S <= t < w * S + L]
            if seg:
                out.append((k, w, float(sum(seg))))
            w += 1
    return sorted(out)


@pytest.mark.parametrize("L,S,K,batch,rate", TB_CASES)
def test_tb_windows_absolute_oracle(L, S, K, batch, rate):
    total = 6 * max(L * rate, batch) + 29
    src = wf.Source(lambda i: {"v": ((i * 17) % 23).astype(jnp.float32)},
                    total=total, num_keys=K, ts_fn=lambda i: i // rate)
    got = []

    def cb(view):
        if view is None:
            return
        got.extend((int(k), int(w), float(r)) for k, w, r in
                   zip(view["key"].tolist(), view["id"].tolist(),
                       np.asarray(view["payload"]).tolist()))

    wf.Pipeline(src, [wf.Win_Seq(lambda wid, it: it.sum("v"),
                                 WindowSpec(L, S, win_type_t.TB),
                                 num_keys=K, tb_capacity=4 * total)],
                wf.Sink(cb), batch_size=batch).run()
    assert sorted(got) == py_oracle_tb(total, K, L, S, rate), \
        f"L={L} S={S} K={K} batch={batch} rate={rate}"


OOO_CASES = []
for _ in range(6):
    L = int(RNG.integers(4, 20))
    OOO_CASES.append((L, int(RNG.integers(1, L + 1)), int(RNG.integers(1, 4)),
                      int(RNG.integers(20, 90)), int(RNG.integers(0, 12)),
                      int(RNG.integers(1, 10))))


def py_oracle_tb_ooo(total, K, L, S, delay, jitter, batch):
    """Exact batch-level TB oracle with out-of-order ts + lateness: per key,
    insert (dropping tuples below the purge horizon next_win*S), advance the
    watermark on inserted tuples only, then fire windows with
    hi = (wm - delay - L)//S + 1; EOS flushes windows up to wm//S + 1.
    Mirrors Win_Seq._insert/_fired_range semantics (wf/window.hpp Triggerer_TB
    incl. triggering_delay; OLD drops wf/win_seq.hpp:338-345)."""
    ts_of = lambda i: max(0, i - (i * 7) % (jitter + 1))
    arch = {k: [] for k in range(K)}
    wm = {k: -1 for k in range(K)}
    nw = {k: 0 for k in range(K)}
    out = []

    def fire(k, hi):
        for w in range(nw[k], max(hi, nw[k])):
            seg = [v for t, v in arch[k] if w * S <= t < w * S + L]
            if seg:
                out.append((k, w, float(sum(seg))))
        nw[k] = max(hi, nw[k])

    for s in range(0, total, batch):
        touched = set()
        for i in range(s, min(s + batch, total)):
            k, t = i % K, ts_of(i)
            if t >= nw[k] * S:                       # OLD drop below horizon
                arch[k].append((t, float((i * 17) % 23)))
                wm[k] = max(wm[k], t)
            touched.add(k)
        for k in touched:
            fire(k, (wm[k] - delay - L) // S + 1)
    for k in range(K):
        if arch[k] and wm[k] >= 0:
            fire(k, wm[k] // S + 1)
    return sorted(out)


@pytest.mark.parametrize("L,S,K,batch,delay,jitter", OOO_CASES)
def test_tb_out_of_order_lateness_oracle(L, S, K, batch, delay, jitter):
    total = 5 * max(L, batch) + 31
    src = wf.Source(lambda i: {"v": ((i * 17) % 23).astype(jnp.float32)},
                    total=total, num_keys=K,
                    ts_fn=lambda i: jnp.maximum(0, i - (i * 7) % (jitter + 1)))
    got = []

    def cb(view):
        if view is None:
            return
        got.extend((int(k), int(w), float(r)) for k, w, r in
                   zip(view["key"].tolist(), view["id"].tolist(),
                       np.asarray(view["payload"]).tolist()))

    wf.Pipeline(src, [wf.Win_Seq(lambda wid, it: it.sum("v"),
                                 WindowSpec(L, S, win_type_t.TB, delay=delay),
                                 num_keys=K, tb_capacity=4 * total,
                                 max_wins=512)],
                wf.Sink(cb), batch_size=batch).run()
    want = py_oracle_tb_ooo(total, K, L, S, delay, jitter, batch)
    assert sorted(got) == want, \
        f"L={L} S={S} K={K} batch={batch} delay={delay} jitter={jitter}"
