"""Order-correctness of the FFAT pane-sharing engine with a NON-commutative
associative combine (2x2 matrix product). The reference FlatFAT maintains prefix and
suffix partials precisely so that non-commutative combines associate in stream order
(wf/flatfat.hpp:80-133); here order is preserved because pane partials are gathered in
logical pane order and reduced with an order-preserving tree (_tree_reduce)."""

import numpy as np
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.operators.win_seqffat import Win_SeqFFAT


def matmul2(a, b):
    """Associative, non-commutative: 2x2 matrix product along trailing dims."""
    return jnp.einsum("...ij,...jk->...ik", a, b)


def lift(t):
    # tuple value v -> [[1, v], [0, 1]] (shear matrices compose non-commutatively
    # only if mixed; use rotation-ish asymmetric form to expose ordering bugs)
    v = t.v
    one = jnp.ones_like(v)
    zero = jnp.zeros_like(v)
    return jnp.stack([jnp.stack([one, v]), jnp.stack([v * 0.5, one])])


def test_ffat_noncommutative_matches_sequential():
    total, K, L, S = 96, 2, 8, 4
    spec = WindowSpec(L, S, win_type_t.CB)
    op = Win_SeqFFAT(lift, matmul2, spec=spec,
                     identity=jnp.eye(2, dtype=jnp.float32), num_keys=K, name="mm")

    src = wf.Source(lambda i: {"v": (i % 5).astype(jnp.float32) * 0.1},
                    total=total, num_keys=K)
    got = {}

    def cb(view):
        if view is None:
            return
        for k, w, m in zip(view["key"].tolist(), view["id"].tolist(),
                           np.asarray(view["payload"])):
            got[(k, w)] = m

    wf.Pipeline(src, [op], wf.Sink(cb), batch_size=32).run()

    # sequential oracle
    per_key = {k: [] for k in range(K)}
    for i in range(total):
        per_key[i % K].append((i % 5) * 0.1)
    for k, vals in per_key.items():
        n = len(vals)
        hi = (n - 1) // S + 1
        for w in range(hi):
            content = vals[w * S: w * S + L]
            m = np.eye(2, dtype=np.float32)
            for v in content:
                m = m @ np.array([[1, v], [v * 0.5, 1]], np.float32)
            np.testing.assert_allclose(got[(k, w)], m, rtol=1e-4, atol=1e-5)
