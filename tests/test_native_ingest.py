"""Native AoS<->SoA ingest + key hashing (windflow_tpu/native/ingest.cpp): parity
with the Python reference implementations, and RecordSource end-to-end through a
keyed windowed pipeline."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.batch import hash_key_to_slot, _fnv1a
from windflow_tpu.native import (unpack_records, pack_records, hash_keys_native,
                                 native_available)
from windflow_tpu.operators.window import WindowSpec

DT = np.dtype([("key", "i4"), ("ts", "i8"), ("v", "f4"), ("vec", "f4", (3,)),
               ("tag", "S8")])


def make_records(n, seed=0):
    rng = np.random.default_rng(seed)
    rec = np.zeros(n, DT)
    rec["key"] = rng.integers(0, 57, n)
    rec["ts"] = np.arange(n) * 3
    rec["v"] = rng.random(n).astype(np.float32)
    rec["vec"] = rng.random((n, 3)).astype(np.float32)
    rec["tag"] = [f"k{i % 7}".encode() for i in range(n)]
    return rec


def test_native_library_builds():
    assert native_available(), "libwfnative.so must build in this image"


def test_unpack_pack_roundtrip_all_field_widths():
    rec = make_records(500)
    cols = unpack_records(rec)
    for f in DT.names:
        np.testing.assert_array_equal(cols[f], rec[f], err_msg=f)
        assert cols[f].flags["C_CONTIGUOUS"]
    back = pack_records(cols, DT)
    assert np.array_equal(back, rec)


@pytest.mark.parametrize("workers", [1, 2, 3, 8])
def test_parallel_unpack_matches_single_pass(workers):
    """Sharded framing (parallel_unpack): identical columns to the single native
    pass for every worker count, including worker counts that don't divide the
    row count and structured subdtype fields."""
    from windflow_tpu.native import parallel_unpack
    rec = make_records(1001)
    want = unpack_records(rec)
    got = parallel_unpack(rec, workers=workers)
    assert set(got) == set(want)
    for f in want:
        assert got[f].shape == want[f].shape
        assert (got[f] == want[f]).all(), f


def test_parallel_unpack_tiny_and_empty():
    from windflow_tpu.native import parallel_unpack
    for n in (0, 1, 3):
        rec = make_records(max(n, 1))[:n]
        got = parallel_unpack(np.ascontiguousarray(rec), workers=4)
        want = unpack_records(np.ascontiguousarray(rec))
        for f in want:
            assert (got[f] == want[f]).all()


def test_record_source_parallel_framing_end_to_end():
    """framing_workers > 1 must not change the stream: same batches as the
    single-pass source, including control fields from record fields."""
    rec = make_records(500)
    DT2 = np.dtype([("key", "i4"), ("ts", "i8"), ("v", "f4")])
    r2 = np.zeros(500, DT2)
    for f in DT2.names:
        r2[f] = rec[f]

    def chunks():
        for s in range(0, 500, 100):
            yield r2[s:s + 100]

    def drain(workers):
        src = wf.RecordSource(chunks, DT2, key_field="key", ts_field="ts",
                              num_keys=8, framing_workers=workers)
        out = []
        for b in src.batches(100):
            v = np.asarray(b.valid)
            out.extend(zip(np.asarray(b.key)[v].tolist(),
                           np.asarray(b.ts)[v].tolist(),
                           np.asarray(b.payload["v"])[v].tolist()))
        return out

    assert drain(1) == drain(4)


def test_record_source_cursor_resume():
    """RecordSource shares the host-source cursor contract: resume from a
    commit-time token reproduces the exact remaining stream (ids included)."""
    rec = make_records(600)
    DT2 = np.dtype([("key", "i4"), ("v", "f4")])
    r2 = np.zeros(600, DT2)
    r2["key"], r2["v"] = rec["key"], rec["v"]
    opens = []

    def chunks(from_batch=0):
        opens.append(from_batch)
        def gen():
            for s in range(from_batch * 100, 600, 100):
                yield r2[s:s + 100]
        return gen()

    src = wf.RecordSource(chunks, DT2, key_field="key", num_keys=8)
    it = src.batches(100)
    first3 = [jax.tree.map(np.asarray, next(it)) for _ in range(3)]
    tok = src.cursor()
    assert tok == {"batch": 3, "next_id": 300}
    rest_a = [jax.tree.map(np.asarray, b) for b in it]

    src2 = wf.RecordSource(chunks, DT2, key_field="key", num_keys=8)
    rest_b = [jax.tree.map(np.asarray, b)
              for b in src2.batches(100, cursor=tok)]
    assert opens[-1] == 3                     # factory seeked, not replayed
    assert len(rest_a) == len(rest_b) == 3
    for a, b in zip(rest_a, rest_b):
        assert (a.id == b.id).all() and (a.key == b.key).all()
        assert (a.payload["v"] == b.payload["v"]).all()


def test_unpack_noncontiguous_falls_back():
    rec = make_records(200)[::2]                # strided view
    cols = unpack_records(rec)
    for f in DT.names:
        np.testing.assert_array_equal(cols[f], rec[f], err_msg=f)


@pytest.mark.parametrize("num_slots", [7, 64, 977])
def test_hash_parity_int_bytes_unicode(num_slots):
    ints = np.asarray([0, 1, -5, 2**31 - 1, -2**31, 123456789], np.int64)
    got = hash_keys_native(ints, num_slots)
    want = [(int(k) & 0xFFFFFFFFFFFFFFFF) * 2654435761 % (1 << 64) % num_slots
            for k in ints]
    np.testing.assert_array_equal(got, want)

    tags = np.asarray([b"alpha", b"beta", b"x", b""], "S8")
    got = hash_keys_native(tags, num_slots)
    want = [_fnv1a(t) % num_slots for t in [b"alpha", b"beta", b"x", b""]]
    np.testing.assert_array_equal(got, want)

    names = np.asarray(["user_1", "user_22", "", "éclair"])
    got = hash_keys_native(names, num_slots)
    want = [_fnv1a(s.encode()) % num_slots for s in names.tolist()]
    np.testing.assert_array_equal(got, want)


def test_hash_parity_embedded_nul_bytes():
    # numpy bytes items strip only TRAILING NULs; embedded NULs are key content
    # and must hash identically in both paths
    tags = np.asarray([b"a\x00b", b"a\x00c", b"a"], "S8")
    got = hash_keys_native(tags, 97)
    want = [_fnv1a(t) % 97 for t in [b"a\x00b", b"a\x00c", b"a"]]
    np.testing.assert_array_equal(got, want)
    assert got[0] != got[1]                     # distinct keys must not merge


def test_pack_records_rejects_mismatched_columns():
    cols = {"key": np.arange(10, dtype=np.int32),
            "ts": np.arange(5, dtype=np.int64)}
    dt = np.dtype([("key", "i4"), ("ts", "i8")])
    with pytest.raises(ValueError, match="ts"):
        pack_records(cols, dt)


def test_record_source_rejects_string_payload_field():
    dt = np.dtype([("key", "i4"), ("tag", "S8")])
    with pytest.raises(TypeError, match="tag"):
        wf.RecordSource(lambda: iter(()), dt, key_field="key")


def test_hash_key_to_slot_uses_native_path_consistently():
    # the public API must give identical slots whether or not native is loaded
    arr = np.asarray([f"sensor-{i}" for i in range(50)])
    slots = hash_key_to_slot(arr, 16)
    want = np.asarray([_fnv1a(s.encode()) % 16 for s in arr.tolist()], np.int32)
    np.testing.assert_array_equal(slots, want)


def test_record_source_end_to_end_keyed_window():
    total, chunk, K = 240, 60, 8
    rec = make_records(total, seed=3)
    rec["ts"] = np.arange(total)                # monotone event time

    def chunks():
        for s in range(0, total, chunk):
            yield rec[s:s + chunk]

    src = wf.RecordSource(chunks, DT, key_field="tag", ts_field="ts", num_keys=K)
    results = []

    def cb(view):
        if view is None:
            return
        for k, w, r in zip(view["key"].tolist(), view["id"].tolist(),
                           np.asarray(view["payload"]).tolist()):
            results.append((int(k), int(w), round(float(r), 4)))

    op = wf.Win_Seq(lambda wid, it: it.sum("v"), WindowSpec(20, 20, win_type_t.TB),
                    num_keys=K)
    wf.Pipeline(src, [op], wf.Sink(cb), batch_size=64).run()

    # dense oracle on the host
    want = {}
    slots = hash_key_to_slot(rec["tag"], K)
    for i in range(total):
        wid = int(rec["ts"][i]) // 20
        kslot = int(slots[i])
        want[(kslot, wid)] = round(want.get((kslot, wid), 0.0)
                                   + float(rec["v"][i]), 4)
    got = {(k, w): r for k, w, r in results}
    assert set(got) == set(want)
    for kk in want:
        assert abs(got[kk] - want[kk]) < 1e-3, (kk, got[kk], want[kk])
