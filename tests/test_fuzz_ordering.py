"""Randomized interleaving fuzz for Ordering_Node against an exact host oracle.

The reference's guarantee (wf/ordering_node.hpp:79-94): whatever the
interleaving of per-channel deliveries, the released stream is the global
(ts, id)-sorted merge, each tuple exactly once, and no tuple is released
before the low-watermark proves nothing smaller can still arrive. Channels
are internally ordered (the reference's standing assumption); batch sizes,
delivery interleavings, gaps, and per-channel rates are all randomized."""

import numpy as np
import jax.numpy as jnp
import pytest

from windflow_tpu.basic import ordering_mode_t
from windflow_tpu.batch import Batch
from windflow_tpu.parallel.ordering import Ordering_Node, WM_NONE

def make_batch(keys, ids, ts, vals):
    n = len(ids)
    return Batch(key=jnp.asarray(keys, jnp.int32), id=jnp.asarray(ids, jnp.int32),
                 ts=jnp.asarray(ts, jnp.int32),
                 payload={"v": jnp.asarray(vals, jnp.float32)},
                 valid=jnp.ones(n, bool))


def drain(out, acc):
    if out is None:
        return
    v = np.asarray(out.valid)
    acc.extend(zip(np.asarray(out.ts)[v].tolist(), np.asarray(out.id)[v].tolist(),
                   np.asarray(out.payload["v"])[v].tolist()))


@pytest.mark.parametrize("trial", range(8))
def test_fuzz_interleaved_channels_release_global_sorted_merge(trial):
    rng = np.random.default_rng(100 + trial)
    n_ch = int(rng.integers(2, 5))
    # per-channel streams: sorted ts with random gaps/duplicates; globally unique ids
    streams, uid = [], 0
    for c in range(n_ch):
        n = int(rng.integers(5, 60))
        ts = np.cumsum(rng.integers(0, 4, n)).astype(np.int32)  # non-decreasing
        ids = np.arange(uid, uid + n, dtype=np.int32)
        uid += n
        streams.append([(int(t), int(i)) for t, i in zip(ts, ids)])

    node = Ordering_Node(n_ch, ordering_mode_t.TS)
    released = []
    cursors = [0] * n_ch
    while any(cursors[c] < len(streams[c]) for c in range(n_ch)):
        c = int(rng.integers(0, n_ch))
        if cursors[c] >= len(streams[c]):
            continue
        take = int(rng.integers(1, 9))
        chunk = streams[c][cursors[c]:cursors[c] + take]
        cursors[c] += take
        ts = [t for t, _ in chunk]
        ids = [i for _, i in chunk]
        # released prefix must never exceed the provable low-watermark
        out = node.push(c, make_batch([0] * len(ids), ids, ts, ids))
        before = len(released)
        drain(out, released)
        wms = [w for w in np.asarray(node._wm_dev).tolist()
               if w != int(WM_NONE)]
        if len(wms) == node.n_inputs and len(released) > before:
            low = min(wms)
            assert all(t <= low for t, _, _ in released[before:])
    for c in range(n_ch):
        drain(node.close_channel(c), released)
    drain(node.flush(), released)

    everything = [(t, i, float(i)) for s in streams for t, i in s]
    # exact oracle: stable global sort by (ts, id)
    assert released == sorted(everything, key=lambda x: (x[0], x[1]))


@pytest.mark.parametrize("mode", [ordering_mode_t.ID, ordering_mode_t.TS_RENUMBERING])
def test_fuzz_other_modes(mode):
    rng = np.random.default_rng(7)
    n_ch = 3
    streams, uid = [], 0
    for c in range(n_ch):
        n = int(rng.integers(10, 40))
        ts = np.cumsum(rng.integers(0, 3, n)).astype(np.int32)
        ids = np.arange(uid, uid + n, dtype=np.int32)
        uid += n
        streams.append([(int(t), int(i)) for t, i in zip(ts, ids)])
    node = Ordering_Node(n_ch, mode)
    released = []
    cursors = [0] * n_ch
    while any(cursors[c] < len(streams[c]) for c in range(n_ch)):
        c = int(rng.integers(0, n_ch))
        if cursors[c] >= len(streams[c]):
            continue
        take = int(rng.integers(1, 6))
        chunk = streams[c][cursors[c]:cursors[c] + take]
        cursors[c] += take
        drain(node.push(c, make_batch([0] * len(chunk),
                                      [i for _, i in chunk],
                                      [t for t, _ in chunk],
                                      [i for _, i in chunk])), released)
    for c in range(n_ch):
        drain(node.close_channel(c), released)
    drain(node.flush(), released)
    everything = [(t, i, float(i)) for s in streams for t, i in s]
    if mode == ordering_mode_t.ID:
        # ID mode: global sort by id (each channel's ids ascend)
        assert [i for _, i, _ in released] == sorted(i for _, i, _ in everything)
    else:
        # TS_RENUMBERING: ts-sorted payload order + progressive released ids
        assert [v for _, _, v in released] == [
            v for _, _, v in sorted(everything, key=lambda x: (x[0], x[1]))]
        assert [i for _, i, _ in released] == list(range(len(everything)))


def test_flush_releases_max_sentinel_ts():
    """EOS must release tuples whose ts sits AT the dtype maximum: mid-stream
    that value is indistinguishable from the invalid-lane sentinel, so flush
    releases valid lanes unconditionally instead of via a watermark compare
    (review-caught data-loss regression of the tie fix)."""
    top = int(np.iinfo(np.int32).max)
    node = Ordering_Node(2, ordering_mode_t.TS)
    released = []
    drain(node.push(0, make_batch([0, 0], [1, 2], [5, top], [1.0, 2.0])), released)
    drain(node.close_channel(1), released)
    drain(node.close_channel(0), released)
    drain(node.flush(), released)
    assert [i for _, i, _ in released] == [1, 2]
