"""Capture persistence: a tunnel outage must degrade the round's perf evidence
to "stale but real" instead of "absent" (VERDICT r03 item 2).

No device needed: exercises the store round-trip and the stale-emission path
with CAPTURE_PATH pointed at a temp file.
"""

import json
import sys

import bench


def _point_store_at(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "CAPTURE_PATH",
                        str(tmp_path / "captures" / "last_good.json"))


def test_record_round_trip(tmp_path, monkeypatch):
    _point_store_at(tmp_path, monkeypatch)
    bench.record("ysb", {"tps": 1.27e8, "step_s": 8.2e-3, "batch": 1 << 20},
                 methodology="test")
    bench.record("stateless", {"tps": 5e8, "step_s": 2.1e-3, "batch": 1 << 20})
    store = bench._load_store()
    assert store["captures"]["ysb"]["tps"] == 1.27e8
    assert store["captures"]["ysb"]["methodology"] == "test"
    assert "ts" in store["captures"]["ysb"]
    assert "device" in store["captures"]["stateless"]
    # updating one key preserves the other
    bench.record("ysb", {"tps": 2e8, "step_s": 5e-3, "batch": 1 << 20})
    store = bench._load_store()
    assert store["captures"]["ysb"]["tps"] == 2e8
    assert store["captures"]["stateless"]["tps"] == 5e8


def test_stale_emission_with_good_capture(tmp_path, monkeypatch, capsys):
    _point_store_at(tmp_path, monkeypatch)
    bench.record_headline({"metric": "YSB tuples/sec/chip", "value": 127000000,
                           "unit": "tuples/s", "vs_baseline": 7.651},
                          methodology="test-capture")
    rc = bench.emit_stale_headline("probe timed out")
    assert rc == 0
    out = capsys.readouterr()
    line = [ln for ln in out.out.splitlines() if ln.startswith("{")][-1]
    payload = json.loads(line)
    assert payload["stale"] is True
    assert payload["value"] == 127000000
    assert payload["metric"] == "YSB tuples/sec/chip"
    assert payload["staleness_reason"] == "device unreachable at capture time"
    assert payload["methodology"] == "test-capture"
    assert "DEVICE UNREACHABLE" in out.err


def test_stale_emission_without_capture_is_rc2(tmp_path, monkeypatch, capsys):
    _point_store_at(tmp_path, monkeypatch)
    rc = bench.emit_stale_headline("probe timed out")
    assert rc == 2
    out = capsys.readouterr()
    assert not [ln for ln in out.out.splitlines() if ln.startswith("{")]


def test_committed_seed_store_is_valid():
    """The committed seed (r03 session capture) must parse and carry the
    honesty markers the stale path forwards."""
    store = bench._load_store()
    head = store.get("headline")
    assert head and head["metric"] == "YSB tuples/sec/chip"
    assert "methodology" in head and "device" in head and "ts" in head


def test_fingerprint_never_initializes_jax(monkeypatch):
    monkeypatch.delitem(sys.modules, "jax", raising=False)
    assert bench._device_fingerprint() == "unknown (jax not initialized)"
