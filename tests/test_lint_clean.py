"""Pillar-2 gate: the framework invariant linter runs over THIS repository in
tier-1 and fails on any finding not suppressed by ``analysis/baseline.json``
— a regression gate, fast and CPU-only (the rules are stdlib ``ast``).

Also unit-tests each rule against seeded fixture trees (every ``WF2xx`` code
fires on a minimal violation and is silenced by its annotation), and pins the
CLI's exit-code contract (0 clean / 1 findings / 2 internal error)."""

import json
import os
import subprocess
import sys
import textwrap

from windflow_tpu.analysis import lint

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ the repo gate


def test_repo_lints_clean_against_baseline():
    """THE gate: any new violation in windflow_tpu/ fails tier-1 with
    file:line and code; pre-existing findings stay suppressed."""
    fresh, suppressed = lint.lint_repo(ROOT)
    assert not fresh, (
        "new wf-lint findings (fix them, annotate with the wf-lint grammar "
        "where legitimate, or — for genuinely pre-existing debt — run "
        "scripts/wf_lint.py --update-baseline):\n"
        + "\n".join(x.render() for x in fresh))


def test_baseline_contains_only_real_findings():
    """Every baseline entry still matches a live finding (count-aware) — a
    stale entry means debt was paid off; shrink the baseline so it cannot
    mask a future regression at the same (code, path, text)."""
    findings = lint.run_lint(ROOT)
    live: dict = {}
    for x in findings:
        live[x.key()] = live.get(x.key(), 0) + 1
    base = lint.load_baseline(lint.baseline_path(lint.LintConfig(root=ROOT)))
    stale = sorted(k for k, n in base.items() if n > live.get(k, 0))
    assert not stale, (
        f"stale baseline entries (regenerate with scripts/wf_lint.py "
        f"--update-baseline): {stale}")


def test_metrics_module_is_clean():
    """Satellite pin: observability/metrics.py carries zero findings — its
    donated/abstract-state except was narrowed to the concrete JAX errors."""
    findings = lint.run_lint(ROOT)
    mine = [x for x in findings
            if x.path == "windflow_tpu/observability/metrics.py"]
    assert not mine, "\n".join(x.render() for x in mine)


def test_both_pillars_run_in_tier1():
    """Pillar-1 presence in this gate file too: the canonical YSB pipeline
    validates clean (the per-code suite is tests/test_analysis_validate.py)."""
    import windflow_tpu as wf
    from windflow_tpu.analysis import validate
    from windflow_tpu.benchmarks import ysb
    p = wf.Pipeline(ysb.make_source(total=8192), list(ysb.make_ops()),
                    wf.Sink(lambda view: None), batch_size=1024)
    report = validate(p)
    assert report.ok, str(report)


# ----------------------------------------------------------- rule fixtures


_NAMES_PY = textwrap.dedent('''\
    JOURNAL_EVENTS = ("good_event",)
    RECOVERY_COUNTERS = ("good_counter",)
    CONTROL_COUNTERS = ("good_control",)
    CONTROL_GAUGES = ("good_gauge",)
''')

_ENV_DOC = textwrap.dedent('''\
    # flags
    | flag | read at | where | meaning |
    |---|---|---|---|
    | `WF_DOCUMENTED` | run time | somewhere | fine. |
    | `WF_NO_TIME` | whenever | somewhere | row lacks a read-time word. |
''')


def _mini_repo(tmp_path, module_src, module_rel="windflow_tpu/mod.py"):
    """A minimal repo skeleton the rules can run against."""
    (tmp_path / "windflow_tpu" / "observability").mkdir(parents=True)
    (tmp_path / "windflow_tpu" / "analysis").mkdir(parents=True)
    (tmp_path / "docs").mkdir()
    (tmp_path / "scripts").mkdir()
    (tmp_path / "windflow_tpu" / "observability" / "names.py").write_text(
        _NAMES_PY)
    (tmp_path / "docs" / "ENV_FLAGS.md").write_text(_ENV_DOC)
    mod = tmp_path / module_rel
    mod.parent.mkdir(parents=True, exist_ok=True)
    mod.write_text(textwrap.dedent(module_src))
    return lint.LintConfig(
        root=str(tmp_path),
        deterministic_modules=(module_rel,),
    )


def _codes(findings):
    return sorted(x.code for x in findings)


def test_wf200_parse_error(tmp_path):
    cfg = _mini_repo(tmp_path, "def broken(:\n")
    assert "WF200" in _codes(lint.run_lint(cfg=cfg))


def test_wf200_non_utf8_file_is_a_finding_not_a_crash(tmp_path):
    cfg = _mini_repo(tmp_path, "pass\n")
    (tmp_path / "windflow_tpu" / "latin.py").write_bytes(
        b"# -*- coding: latin-1 -*-\nx = '\xe9'\n")
    hits = [x for x in lint.run_lint(cfg=cfg) if x.code == "WF200"]
    assert len(hits) == 1 and "UTF-8" in hits[0].message


def test_wf201_undocumented_env_read(tmp_path):
    cfg = _mini_repo(tmp_path, '''
        import os
        X = os.environ.get("WF_UNDOCUMENTED", "")
        Y = os.environ.get("WF_DOCUMENTED", "")
    ''')
    findings = lint.run_lint(cfg=cfg)
    assert [x.code for x in findings if "WF_UNDOCUMENTED" in x.message] \
        == ["WF201"]
    assert not [x for x in findings if "WF_DOCUMENTED`" in x.message]


def test_wf202_row_without_read_time(tmp_path):
    cfg = _mini_repo(tmp_path, "pass\n")
    hits = [x for x in lint.run_lint(cfg=cfg) if x.code == "WF202"]
    assert len(hits) == 1 and "WF_NO_TIME" in hits[0].message
    assert hits[0].path == "docs/ENV_FLAGS.md"


def test_wf210_wall_clock_in_deterministic_module(tmp_path):
    cfg = _mini_repo(tmp_path, '''
        import time, random
        def bad():
            return time.time(), time.monotonic(), random.random()
        def ok():
            return time.time()      # wf-lint: allow[wall-clock]
    ''')
    hits = [x for x in lint.run_lint(cfg=cfg) if x.code == "WF210"]
    assert len(hits) == 3, hits
    # outside the deterministic module list, wall clocks are fine
    cfg2 = _mini_repo(tmp_path / "b", '''
        import time
        def fine():
            return time.time()
    ''')
    cfg2.deterministic_modules = ()
    assert "WF210" not in _codes(lint.run_lint(cfg=cfg2))


def test_wf210_aliased_imports_do_not_escape(tmp_path):
    """`import time as _t` / `from time import monotonic` / `from random
    import random as r` must be flagged like the literal spellings."""
    cfg = _mini_repo(tmp_path, '''
        import time as _t
        from time import monotonic
        from random import random as r
        def bad():
            return _t.time(), monotonic(), r()
        def ok():
            return _t.perf_counter()     # wf-lint: allow[wall-clock]
    ''')
    hits = [x for x in lint.run_lint(cfg=cfg) if x.code == "WF210"]
    assert len(hits) == 3, hits


def test_wf241_aliased_imports_do_not_escape(tmp_path):
    """Any import spelling of the counter emitters is resolved: the typo'd
    name is flagged wherever bump() came from."""
    cfg = _mini_repo(tmp_path, '''
        from .runtime import faults as flt
        from .runtime.faults import bump
        def f():
            flt.bump("typo_a")
            bump("typo_b")
            bump("good_counter")
    ''')
    hits = sorted(x.message for x in lint.run_lint(cfg=cfg)
                  if x.code == "WF241")
    assert len(hits) == 2 and "typo_a" in hits[0] and "typo_b" in hits[1]


def test_wf220_guarded_attribute_outside_lock(tmp_path):
    cfg = _mini_repo(tmp_path, '''
        import threading
        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []          # wf-lint: guarded-by[_lock]
                self.items.append(0)     # __init__ is exempt
            def good(self):
                with self._lock:
                    return len(self.items)
            def bad(self):
                return len(self.items)
            def annotated(self):
                return self.items        # wf-lint: allow[unguarded]
    ''')
    hits = [x for x in lint.run_lint(cfg=cfg) if x.code == "WF220"]
    assert len(hits) == 1 and "Box.bad" in hits[0].message


def test_wf220_nested_closure_under_lock_is_not_lock_held(tmp_path):
    """A lambda/def DEFINED inside `with self._lock:` runs later, unlocked —
    a deferred callback touching the guarded attribute must still be
    flagged."""
    cfg = _mini_repo(tmp_path, '''
        import threading
        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []          # wf-lint: guarded-by[_lock]
            def deferred(self):
                with self._lock:
                    return lambda: self.items.pop(0)
    ''')
    hits = [x for x in lint.run_lint(cfg=cfg) if x.code == "WF220"]
    assert len(hits) == 1 and "Box.deferred" in hits[0].message


def test_wf220_trailing_annotation_does_not_leak_to_next_line(tmp_path):
    cfg = _mini_repo(tmp_path, '''
        import threading
        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.a = []              # wf-lint: guarded-by[_lock]
                self.b = 0
            def touch_b(self):
                return self.b            # b is NOT guarded
    ''')
    assert "WF220" not in _codes(lint.run_lint(cfg=cfg))


def test_wf230_broad_except(tmp_path):
    cfg = _mini_repo(tmp_path, '''
        def f():
            try:
                pass
            except Exception:
                return 1                 # swallowed: finding
        def g():
            try:
                pass
            except Exception:            # noqa: BLE001 — rationale given
                return 2
        def h():
            try:
                pass
            except BaseException:
                raise                    # cleanup re-raise: exempt
        def i():
            try:
                pass
            except ValueError:
                return 4                 # concrete: fine
        def j():
            try:
                pass
            except Exception:            # noqa
                return 5                 # bare noqa, no code: still a finding
        def k():
            try:
                pass
            except Exception:            # noqa: E501
                return 6                 # unrelated code: still a finding
    ''')
    hits = [x for x in lint.run_lint(cfg=cfg) if x.code == "WF230"]
    assert len(hits) == 3 and all(x.severity == "warning" for x in hits)


def test_baseline_counts_do_not_mask_new_duplicates(tmp_path):
    """A baseline holding ONE `except Exception:` in a file must not also
    suppress a newly added second with identical source text."""
    cfg = _mini_repo(tmp_path, '''
        def f():
            try:
                pass
            except Exception:
                return 1
    ''')
    one = [x for x in lint.run_lint(cfg=cfg) if x.code == "WF230"]
    bpath = tmp_path / "b.json"
    lint.save_baseline(str(bpath), one)
    cfg2 = _mini_repo(tmp_path / "dup", '''
        def f():
            try:
                pass
            except Exception:
                return 1
        def g():
            try:
                pass
            except Exception:
                return 1
    ''')
    two = [x for x in lint.run_lint(cfg=cfg2) if x.code == "WF230"]
    assert len(two) == 2 and two[0].key() == two[1].key()
    fresh = lint.apply_baseline(two, lint.load_baseline(str(bpath)))
    assert len(fresh) == 1, "second identical violation must stay fresh"


def test_wf240_unregistered_journal_event(tmp_path):
    cfg = _mini_repo(tmp_path, '''
        from .observability import journal as _journal
        def f():
            _journal.record("good_event", x=1)
            _journal.record("typo_event", x=1)
    ''')
    hits = [x for x in lint.run_lint(cfg=cfg) if x.code == "WF240"]
    assert len(hits) == 1 and "typo_event" in hits[0].message


def test_wf241_unregistered_counter(tmp_path):
    cfg = _mini_repo(tmp_path, '''
        from . import faults as _faults
        def f():
            _faults.bump("good_counter")
            _faults.bump("typo_counter")
    ''')
    hits = [x for x in lint.run_lint(cfg=cfg) if x.code == "WF241"]
    assert len(hits) == 1 and "typo_counter" in hits[0].message


def test_wf250_unregistered_kernel_name(tmp_path):
    """Literal kernel/impl names at register_kernel/resolve_impl call sites
    are gated against names.py::KERNELS / KERNEL_IMPLS — any spelling
    (module function or registry method)."""
    cfg = _mini_repo(tmp_path, '''
        from .ops.registry import register_kernel, resolve_impl

        register_kernel("good_kernel", "good_impl", reference=True)
        register_kernel("typo_kernel", "good_impl")
        register_kernel("good_kernel", "typo_impl")

        def f(REGISTRY):
            resolve_impl("good_kernel")
            return REGISTRY.resolve_impl("typo_kernel2", spec_key="s")
    ''')
    (tmp_path / "windflow_tpu" / "observability" / "names.py").write_text(
        _NAMES_PY + 'KERNELS = ("good_kernel",)\n'
                    'KERNEL_IMPLS = ("good_impl",)\n')
    hits = [x for x in lint.run_lint(cfg=cfg) if x.code == "WF250"]
    msgs = "\n".join(x.message for x in hits)
    assert len(hits) == 3, msgs
    assert "typo_kernel" in msgs and "typo_impl" in msgs \
        and "typo_kernel2" in msgs


def test_wf250_silent_without_kernel_registry(tmp_path):
    """A minimal tree whose names.py predates the kernel registry (no
    KERNELS tuple) lints clean — the rule has nothing to check against."""
    cfg = _mini_repo(tmp_path, '''
        from .ops.registry import resolve_impl
        def f():
            return resolve_impl("anything_goes")
    ''')
    assert not [x for x in lint.run_lint(cfg=cfg) if x.code == "WF250"]


def test_baseline_suppression_roundtrip(tmp_path):
    cfg = _mini_repo(tmp_path, '''
        def f():
            try:
                pass
            except Exception:
                return 1
    ''')
    findings = lint.run_lint(cfg=cfg)
    assert "WF230" in _codes(findings)
    bpath = tmp_path / "windflow_tpu" / "analysis" / "baseline.json"
    lint.save_baseline(str(bpath), findings)
    fresh = lint.apply_baseline(findings, lint.load_baseline(str(bpath)))
    assert fresh == []
    # a NEW finding (different source text) is not suppressed
    cfg2 = _mini_repo(tmp_path / "n", '''
        def g():
            try:
                pass
            except BaseException:
                return 9
    ''')
    findings2 = lint.run_lint(cfg=cfg2)
    assert lint.apply_baseline(findings2, lint.load_baseline(str(bpath)))


def test_env_override_baseline_path(tmp_path, monkeypatch):
    """WF_LINT_BASELINE (docs/ENV_FLAGS.md) redirects the suppression set."""
    alt = tmp_path / "alt_baseline.json"
    monkeypatch.setenv("WF_LINT_BASELINE", str(alt))
    cfg = lint.LintConfig(root=str(tmp_path))
    assert lint.baseline_path(cfg) == str(alt)
    monkeypatch.delenv("WF_LINT_BASELINE")
    assert lint.baseline_path(cfg).endswith(
        os.path.join("analysis", "baseline.json"))


# ------------------------------------------------------------- CLI contract


def _run_cli(*args, env=None):
    e = dict(os.environ)
    if env:
        e.update(env)
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "wf_lint.py"), *args],
        capture_output=True, text=True, timeout=120, env=e)


def test_cli_exit_0_on_clean_gate():
    proc = _run_cli()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout


def test_cli_exit_1_on_findings_and_json_format(tmp_path):
    """A seeded violation → exit 1, --format=json machine-readable. (Pinned
    against a fixture repo, NOT the live baseline debt — paying that debt
    off must not break this contract test.)"""
    _mini_repo(tmp_path, '''
        def f():
            try:
                pass
            except Exception:
                return 1
    ''')
    proc = _run_cli("--format=json", "--no-baseline", "--root",
                    str(tmp_path))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert any(x["code"] == "WF230" for x in data["findings"])
    assert {"code", "path", "line", "severity"} <= set(data["findings"][0])


def test_cli_exit_2_on_internal_error(tmp_path):
    """A root without the names registry breaks the linter itself → exit 2
    (never confuse a broken gate with a clean one)."""
    (tmp_path / "windflow_tpu").mkdir()
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "ENV_FLAGS.md").write_text(_ENV_DOC)
    proc = _run_cli("--root", str(tmp_path))
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "internal error" in proc.stderr


def test_cli_exit_2_on_missing_explicit_baseline(tmp_path):
    """An explicit WF_LINT_BASELINE pointing nowhere is a broken gate (exit
    2), not an empty baseline resurfacing old debt as 'fresh'."""
    proc = _run_cli(env={"WF_LINT_BASELINE": str(tmp_path / "typo.json")})
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "missing baseline" in proc.stderr


def test_cli_update_baseline_roundtrip(tmp_path):
    """--update-baseline writes the current findings; the next gate run is
    clean against it."""
    bpath = tmp_path / "baseline.json"
    proc = _run_cli("--update-baseline",
                    env={"WF_LINT_BASELINE": str(bpath)})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(bpath.read_text())
    assert isinstance(data["findings"], list)
    proc2 = _run_cli(env={"WF_LINT_BASELINE": str(bpath)})
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr


# ------------------------------------- WF30x registration (progcheck codes)
# The device-program analyzer (analysis/progcheck.py) emits WF300-WF305 at
# TRACE time, not parse time — but the codes live in the one shared RULES
# table so --select/--ignore/--explain speak a single grammar across
# wf_lint and wf_progcheck.


def test_wf30x_registered_in_rules():
    """All six progcheck codes are registered, with the severity split the
    analyzer documents: replay-visible determinism breaks and buffer
    aliasing are errors; advisory rankings are warnings."""
    for code in ("WF300", "WF301", "WF302", "WF303", "WF304", "WF305"):
        assert code in lint.RULES, code
        severity, summary = lint.RULES[code]
        assert severity in ("error", "warning") and summary
    assert lint.RULES["WF300"][0] == "error"
    assert lint.RULES["WF301"][0] == "error"
    assert lint.RULES["WF304"][0] == "error"
    assert lint.RULES["WF302"][0] == "warning"
    assert lint.RULES["WF303"][0] == "warning"
    assert lint.RULES["WF305"][0] == "warning"


def test_progcheck_doc_covers_every_wf30x_code():
    """--explain's long-form block comes from progcheck.py's docstring,
    read via ast WITHOUT importing it (progcheck imports JAX; lint.py must
    stay loadable-by-path on a jax-less box).  Every registered WF30x code
    must have a row there or --explain prints an empty block."""
    doc = lint.progcheck_doc()
    for code in [c for c in lint.RULES if c.startswith("WF30")]:
        assert code in doc, f"{code} missing from progcheck.py docstring"


def test_cli_explain_wf30x_without_jax(tmp_path):
    """wf_lint --explain WF30x works on a box where importing jax is
    poisoned — the docstring is read textually, never imported."""
    d = tmp_path / "nojax"
    d.mkdir()
    (d / "jax.py").write_text("raise ImportError('explain must not import "
                              "jax')\n")
    for code in ("WF300", "WF305"):
        proc = _run_cli("--explain", code, env={"PYTHONPATH": str(d)})
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert code in proc.stdout
    # and the block carries the rule's story, not just the RULES row
    proc = _run_cli("--explain", "WF302", env={"PYTHONPATH": str(d)})
    assert "dispatch_ratio" in proc.stdout


def test_cli_family_token_wf30x():
    """The family grammar extends to WF3xx: WF30x resolves through RULES
    (the lint passes never emit those codes, so the select runs clean);
    an unregistered family like WF39x stays a broken invocation (exit 2),
    never a silent no-op."""
    proc = _run_cli("--select", "WF30x", "--no-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = _run_cli("--select", "WF39x")
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "unknown rule family" in proc.stderr
