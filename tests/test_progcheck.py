"""Device-program analyzer (analysis/progcheck.py, WF3xx): each rule pinned
by a minimally-broken program fixture plus its clean sibling, the recursive
sub-jaxpr walker, the canonical fingerprint's contract (pure function of the
program, address-free, change-sensitive), the rationale-required baseline
gate, the validate() integration, and the CLI's 0/1/2 exit contract
(including exit 2 WITHOUT a traceback on a box with no JAX — the one wf_*
CLI that genuinely needs it)."""

import json
import os
import subprocess
import sys

import pytest
import jax
import jax.numpy as jnp
from jax.experimental import io_callback

import windflow_tpu as wf
from windflow_tpu.analysis import progcheck as pc
from windflow_tpu.analysis.validate import validate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
S = jax.ShapeDtypeStruct
F8 = S((8,), jnp.float32)
I8 = S((8,), jnp.int32)


def prog(fn, *args, k=1, replay=False, shards=1):
    """A fixture Program: trace ``fn`` abstractly, wrap with the given
    execution context."""
    return pc.Program(target="fx", kind="step",
                      closed=jax.make_jaxpr(fn)(*args), capacity=8,
                      k=k, shards=shards, replay=replay)


def codes(p):
    return [x.code for x in pc.analyze_program(p)]


# ------------------------------------------------------------ the rules


def test_wf300_float_scatter_add_under_replay():
    bad = prog(lambda v, i: jnp.zeros(16, jnp.float32).at[i].add(v),
               F8, I8, replay=True)
    assert codes(bad) == ["WF300"]


def test_wf300_clean_siblings():
    unique = prog(lambda v, i: jnp.zeros(16, jnp.float32)
                  .at[i].add(v, unique_indices=True), F8, I8, replay=True)
    integer = prog(lambda v, i: jnp.zeros(16, jnp.int32).at[i].add(v),
                   I8, I8, replay=True)
    no_replay = prog(lambda v, i: jnp.zeros(16, jnp.float32).at[i].add(v),
                     F8, I8, replay=False)
    assert codes(unique) == []
    assert codes(integer) == []
    assert "WF300" not in codes(no_replay)


def test_wf301_unordered_io_callback():
    def cb(x):
        return x
    bad = prog(lambda x: io_callback(cb, F8, x, ordered=False), F8)
    ok = prog(lambda x: io_callback(cb, F8, x, ordered=True), F8)
    assert codes(bad) == ["WF301"]
    # the ordered sibling clears WF301 but still counts as host-sync
    assert codes(ok) == ["WF302"]


def test_wf301_unordered_debug_callback():
    bad = prog(lambda x: (jax.debug.print("v={v}", v=x[0]), x)[1], F8)
    ok = prog(lambda x: (jax.debug.print("v={v}", v=x[0], ordered=True),
                         x)[1], F8)
    assert codes(bad) == ["WF301"]
    assert codes(ok) == ["WF302"]


def test_wf302_names_the_callback_and_ranks_fusion():
    def resolve_miss(x):
        return x
    p = prog(lambda x: io_callback(resolve_miss, F8, x, ordered=True), F8)
    [f] = pc.analyze_program(p)
    assert f.code == "WF302"
    assert "resolve_miss" in f.message
    assert "dispatch_ratio" in f.message


def test_wf303_weak_typed_program_input():
    bad = pc.Program(target="fx", kind="step",
                     closed=jax.make_jaxpr(lambda x: x * 2)(3.0),
                     capacity=8)
    ok = prog(lambda x: x * 2, F8)
    assert codes(bad) == ["WF303"]
    assert codes(ok) == []


def test_wf304_donated_input_read_after_donation():
    g = jax.jit(lambda x: x + 1, donate_argnums=0)
    bad = prog(lambda x: g(x) + x, F8)     # x read AFTER g donates it
    ok = prog(lambda x: g(x) * 2, F8)
    assert codes(bad) == ["WF304"]
    assert codes(ok) == []


def test_wf305_float_reduction_under_composition():
    under_k = prog(lambda v: jnp.sum(v), F8, k=2)
    under_shards = prog(lambda v: jnp.sum(v), F8, shards=2)
    integer = prog(lambda v: jnp.sum(v), I8, k=2)
    solo = prog(lambda v: jnp.sum(v), F8, k=1)
    exact_max = prog(lambda v: jnp.max(v), F8, k=2)
    assert codes(under_k) == ["WF305"]
    assert codes(under_shards) == ["WF305"]
    assert codes(integer) == []
    assert codes(solo) == []
    assert codes(exact_max) == []          # max is associative-exact


def test_walker_recurses_into_scan_and_cond():
    """A violation INSIDE a scan body / cond branch is found, and the
    finding's text names the nesting path."""
    def body(c, v):
        return c, jnp.sum(v)               # float reduce inside the scan
    bad = prog(lambda vs: jax.lax.scan(body, 0.0, vs),
               S((4, 8), jnp.float32), k=2)
    hits = [f for f in pc.analyze_program(bad) if f.code == "WF305"]
    assert hits and any("scan" in f.text for f in hits)

    def branch(x):
        return jnp.sum(x)
    bad2 = prog(lambda p, x: jax.lax.cond(p, branch, lambda x: x[0], x),
                S((), jnp.bool_), F8, k=2)
    hits2 = [f for f in pc.analyze_program(bad2) if f.code == "WF305"]
    assert hits2 and any("cond" in f.text for f in hits2)


# ------------------------------------------------------- the fingerprint


def _q1_chain():
    from windflow_tpu.nexmark import queries as q
    src, ops = q.make_query("q1_currency", total=512)
    return pc._mk_chain(src, ops, 64)


def test_fingerprint_deterministic_in_process():
    chain = _q1_chain()
    assert pc.step_fingerprint(chain, 64) == pc.step_fingerprint(chain, 64)
    # a fresh identical chain traces to the same program
    assert pc.step_fingerprint(_q1_chain(), 64) == \
        pc.step_fingerprint(chain, 64)


def test_fingerprint_stable_across_processes():
    """The acceptance pin: a pure function of the jaxpr — no ids, no
    addresses — so a second interpreter computes the same hex digest."""
    chain = _q1_chain()
    here = pc.step_fingerprint(chain, 64)
    script = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from windflow_tpu.analysis import progcheck as pc\n"
        "from windflow_tpu.nexmark import queries as q\n"
        "src, ops = q.make_query('q1_currency', total=512)\n"
        "print(pc.step_fingerprint(pc._mk_chain(src, ops, 64), 64))\n")
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, cwd=REPO,
                          env={**os.environ, "JAX_PLATFORMS": "cpu"},
                          timeout=300)
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == here


def test_fingerprint_sensitive_to_program_change():
    a = pc.program_fingerprint(jax.make_jaxpr(lambda x: x * 2)(F8))
    b = pc.program_fingerprint(jax.make_jaxpr(lambda x: x * 3)(F8))
    c = pc.program_fingerprint(jax.make_jaxpr(lambda x: x + 2)(F8))
    assert len({a, b, c}) == 3


def test_fingerprint_ignores_callback_addresses():
    """Two distinct-but-identical callback closures repr with different
    0x addresses; the canonical form must hash them alike (qualname, not
    identity)."""
    def make(tag):
        def cb(x):
            return x
        return jax.make_jaxpr(
            lambda x: io_callback(cb, F8, x, ordered=True))(F8)
    assert pc.program_fingerprint(make("a")) == \
        pc.program_fingerprint(make("b"))


def test_fingerprint_distinguishes_const_values():
    """Constant VALUES are part of the program: two chains differing only
    in a baked-in table must not collide."""
    t1 = jnp.arange(8, dtype=jnp.float32)
    t2 = jnp.arange(8, dtype=jnp.float32) * 2
    a = pc.program_fingerprint(jax.make_jaxpr(lambda x: x + t1)(F8))
    b = pc.program_fingerprint(jax.make_jaxpr(lambda x: x + t2)(F8))
    assert a != b


# --------------------------------------------------------------- baseline


def test_baseline_requires_rationale(tmp_path):
    path = str(tmp_path / "b.json")
    entry = {"code": "WF305", "path": "fx/step", "text": "t",
             "message": "m", "rationale": ""}
    with open(path, "w") as f:
        json.dump({"findings": [entry]}, f)
    counts, problems = pc.load_baseline(path)
    assert counts == {}                    # an unargued entry suppresses NOTHING
    assert len(problems) == 1
    entry["rationale"] = "per-batch fold, grouping invariant in K"
    with open(path, "w") as f:
        json.dump({"findings": [entry]}, f)
    counts, problems = pc.load_baseline(path)
    assert counts == {("WF305", "fx/step", "t"): 1} and problems == []


def test_update_baseline_preserves_written_rationales(tmp_path):
    path = str(tmp_path / "b.json")
    f1 = pc.Finding("WF305", "warning", "fx/step", 1, "m", "t")
    pc.save_baseline(path, [f1])
    data = json.load(open(path))
    assert data["findings"][0]["rationale"] == ""
    data["findings"][0]["rationale"] = "argued"
    with open(path, "w") as f:
        json.dump(data, f)
    # rewrite with the same finding still present plus a new one
    f2 = pc.Finding("WF300", "error", "fx/step", 2, "m2", "t2")
    pc.save_baseline(path, [f1, f2])
    by_code = {e["code"]: e for e in json.load(open(path))["findings"]}
    assert by_code["WF305"]["rationale"] == "argued"
    assert by_code["WF300"]["rationale"] == ""


def test_repo_baseline_every_entry_has_rationale():
    """The acceptance gate: zero unexplained entries in the checked-in
    baseline."""
    counts, problems = pc.load_baseline(pc.baseline_path())
    assert problems == []
    assert sum(counts.values()) > 0        # the first audit WAS recorded


def test_apply_baseline_is_count_aware():
    f = pc.Finding("WF305", "warning", "fx/step", 1, "m", "t")
    g = pc.Finding("WF305", "warning", "fx/step", 2, "m", "t")
    counts = {("WF305", "fx/step", "t"): 1}
    fresh = pc.apply_baseline([f, g], counts)
    assert len(fresh) == 1                 # the duplicate is NOT masked


# ------------------------------------------------- validate() integration


def _tiered_q3_pipeline():
    from windflow_tpu.nexmark import queries as q
    src, ops = q.q3_enrich_join(512, num_slots=512, tiered=True)
    return wf.Pipeline(src, ops, wf.Sink(lambda v: None), batch_size=64)


def test_validate_surfaces_progcheck_findings():
    """The tiered host exchange (io_callback, ordered) surfaces as WF302
    through validate() — the repo baseline keys on audit-target labels,
    not driver labels, so a driver validation sees it fresh."""
    r = validate(_tiered_q3_pipeline())
    assert "WF302" in r.codes()
    assert r.ok                            # warning, not error


def test_validate_progcheck_kwarg_and_env_gate(monkeypatch):
    p = _tiered_q3_pipeline()
    r = validate(p, progcheck=False)
    assert not any(c.startswith("WF3") for c in r.codes())
    monkeypatch.setenv("WF_PROGCHECK", "0")
    r = validate(p)
    assert not any(c.startswith("WF3") for c in r.codes())


def test_validate_clean_chain_stays_clean():
    src = wf.Source(lambda i: {"v": (i % 97).astype(jnp.int32)}, total=256,
                    num_keys=4)
    p = wf.Pipeline(src, [wf.Map(lambda t: {"v": t.v * 2})],
                    wf.Sink(lambda v: None), batch_size=64)
    r = validate(p)
    assert not any(c.startswith("WF3") for c in r.codes())


def test_validate_supervised_flags_replay_rules():
    """A float scatter-add chain under a SUPERVISED validation trips WF300
    (replay context), and stays quiet under plain pipeline validation."""
    src = wf.Source(lambda i: {"v": ((i * 13) % 23).astype(jnp.float32)},
                    total=240, num_keys=3)
    from windflow_tpu.operators.window import WindowSpec
    from windflow_tpu.basic import win_type_t
    op = wf.Key_FFAT(lambda t: t.v, jnp.add,
                     spec=WindowSpec(8, 2, win_type_t.CB), num_keys=3)
    p = wf.Pipeline(src, [op], wf.Sink(lambda v: None), batch_size=48)
    assert "WF300" in validate(p, supervised=True).codes()
    assert "WF300" not in validate(p).codes()


# ------------------------------------------------------------- the CLI


def _run_cli(*args, env=None):
    e = {**os.environ, "JAX_PLATFORMS": "cpu"}
    if env:
        e.update(env)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "wf_progcheck.py"),
         *args], capture_output=True, text=True, cwd=REPO, env=e,
        timeout=600)


def _poisoned_jax_dir(tmp_path):
    d = tmp_path / "nojax"
    d.mkdir()
    (d / "jax.py").write_text("raise ImportError('no jax here')\n")
    return str(d)


def test_cli_exit_2_without_jax_no_traceback(tmp_path):
    proc = _run_cli(env={"PYTHONPATH": _poisoned_jax_dir(tmp_path)})
    assert proc.returncode == 2
    assert "Traceback" not in proc.stderr
    assert "JAX is not importable" in proc.stderr


def test_cli_explain_works_without_jax(tmp_path):
    proc = _run_cli("--explain", "WF304",
                    env={"PYTHONPATH": _poisoned_jax_dir(tmp_path)})
    assert proc.returncode == 0
    assert "WF304" in proc.stdout and "donated" in proc.stdout


def test_cli_explain_unknown_code_exit_2():
    proc = _run_cli("--explain", "WF999")
    assert proc.returncode == 2


def test_cli_family_token_and_bad_tokens():
    proc = _run_cli("--select", "WF30x", "--targets", "examples")
    assert proc.returncode == 0, proc.stderr
    for tok in ("WF999", "x", "Wx"):
        proc = _run_cli("--select", tok, "--targets", "examples")
        assert proc.returncode == 2, tok


def test_cli_refuses_partial_baseline_update():
    proc = _run_cli("--update-baseline", "--select", "WF305")
    assert proc.returncode == 2
    assert "refusing" in proc.stderr


def test_cli_unknown_target_exit_2():
    proc = _run_cli("--targets", "nope")
    assert proc.returncode == 2
    assert "unknown audit target" in proc.stderr


def test_cli_gate_clean_and_rationale_gate(tmp_path):
    """The examples family is clean against the repo baseline (exit 0
    with the multichip WF300/WF305 entries suppressed); pointing the gate
    at a rationale-less baseline flips it to exit 1."""
    proc = _run_cli("--targets", "examples", "--format=json")
    assert proc.returncode == 0, proc.stderr + proc.stdout
    out = json.loads(proc.stdout)
    assert out["findings"] == [] and out["baseline_problems"] == []

    stripped = json.load(open(pc.baseline_path()))
    for e in stripped["findings"]:
        e["rationale"] = ""
    bad = tmp_path / "no_rationale.json"
    bad.write_text(json.dumps(stripped))
    proc = _run_cli("--targets", "examples", "--baseline", str(bad))
    assert proc.returncode == 1
    assert "WITHOUT a rationale" in proc.stdout


def test_cli_fingerprints_flag():
    proc = _run_cli("--targets", "examples", "--fingerprints",
                    "--format=json")
    assert proc.returncode == 0, proc.stderr
    rows = json.loads(proc.stdout)["fingerprints"]
    assert rows and all(len(r["fingerprint"]) == 64 for r in rows)


# ------------------------------------------------- audit-surface tracing


@pytest.mark.parametrize("target", sorted(pc.AUDIT_TARGETS))
def test_audit_targets_trace(target):
    """Every registered audit family traces abstractly (zero device) and
    analyzes without error — the CLI's whole-repo run can never rot."""
    programs = pc.AUDIT_TARGETS[target]()
    assert programs
    findings = pc.analyze_programs(programs)
    # every finding the audit produces is suppressed by an ARGUED baseline
    counts, problems = pc.load_baseline(pc.baseline_path())
    assert problems == []
    assert pc.apply_baseline(findings, counts) == []


def test_wf115_pairing_demo_no_order_variant_reductions():
    """ROADMAP item 1 evidence (the satellite demo, pinned): the
    currently-forbidden dispatch K>1 x tiered-state pairing has NO
    order-variant float reductions in its fused scan program — the exact
    record the next composition arc needs. Only the designed tiered host
    exchange (WF302) appears."""
    from windflow_tpu.nexmark import queries as q
    src, ops = q.q3_enrich_join(512, tiered=True)
    chain = pc._mk_chain(src, ops, 64)
    programs = pc.chain_programs(chain, capacity=64, k=4, replay=True,
                                 target="demo:q3_tiered_k4")
    findings = pc.analyze_programs(programs)
    assert [f.code for f in findings] == ["WF302", "WF302"]
    assert not [f for f in findings if f.code == "WF305"]
