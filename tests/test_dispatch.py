"""Scan dispatch (runtime/dispatch.py + CompiledChain.push_many): K batches
fused into ONE compiled lax.scan program are byte-identical to K sequential
pushes across all four drivers — including under FaultPlan restart with
mid-accumulator checkpoints, partial tails < K at EOS, the K=1 degenerate
rung, and the rebatcher interaction — and the Ordering_Node's async counts
readback preserves every release byte-for-byte."""

import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu import control as wfcontrol
from windflow_tpu.basic import Mode, win_type_t
from windflow_tpu.batch import stack_batches, unstack_batches
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.runtime import faults as faults_mod
from windflow_tpu.runtime.dispatch import (DispatchConfig,
                                           MicrobatchAccumulator,
                                           build_k_ladder)
from windflow_tpu.runtime.faults import FaultPlan, FaultSpec
from windflow_tpu.runtime.pipegraph import PipeGraph
from windflow_tpu.runtime.pipeline import CompiledChain
from windflow_tpu.runtime.supervisor import (SupervisedPipeline,
                                             run_graph_supervised)
from windflow_tpu.runtime.threaded import ThreadedPipeline

from test_mp_matrix import CASES, K as MP_K, TOTAL as MP_TOTAL  # noqa: F401

TOTAL, NKEYS = 240, 3


@pytest.fixture(autouse=True)
def _clean_state():
    faults_mod.set_active(None)
    faults_mod.reset_counters()
    wfcontrol.reset()
    yield
    faults_mod.set_active(None)
    wfcontrol.reset()


def mk_source(total=TOTAL, name="src"):
    return wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
                     total=total, num_keys=NKEYS, name=name)


def collect(acc):
    def cb(view):
        if view is None:
            return
        acc.extend(zip(view["id"].tolist(),
                       np.asarray(view["payload"]["v"]).tolist()))
    return cb


def win_collect(acc):
    def cb(view):
        if view is None:
            return
        acc.extend(zip(view["key"].tolist(), view["id"].tolist(),
                       np.asarray(view["payload"]).tolist()))
    return cb


# ------------------------------------------------------- stack / unstack


def test_stack_unstack_roundtrip_byte_exact():
    batches = list(mk_source(64).batches(16))
    stacked = stack_batches(batches)
    assert jax.tree.leaves(stacked)[0].shape[0] == len(batches)
    back = unstack_batches(stacked)
    assert len(back) == len(batches)
    for a, b in zip(batches, back):
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_stack_batches_rejects_mixed_capacity_and_empty():
    b16 = next(iter(mk_source(32).batches(16)))
    b8 = next(iter(mk_source(32).batches(8)))
    with pytest.raises(ValueError, match="mixed capacities"):
        stack_batches([b16, b8])
    with pytest.raises(ValueError, match="at least one"):
        stack_batches([])


# ----------------------------------------------------------- accumulator


class _FakeBatch:
    def __init__(self, capacity):
        self.capacity = capacity


def test_accumulator_groups_by_k_and_flushes_on_capacity_switch():
    acc = MicrobatchAccumulator(3)
    out = []
    for _ in range(5):
        out += acc.feed(_FakeBatch(16))
    assert [len(g) for g in out] == [3]
    # capacity switch flushes the partial run FIRST, then buffers the new
    groups = acc.feed(_FakeBatch(8))
    assert [len(g) for g in groups] == [2]
    assert [b.capacity for b in groups[0]] == [16, 16]
    assert len(acc) == 1
    assert [b.capacity for b in acc.drain()] == [8]
    assert acc.drain() == []


def test_accumulator_linger_and_set_k_fake_clock():
    now = {"t": 0.0}
    acc = MicrobatchAccumulator(4, linger_s=0.5, clock=lambda: now["t"])
    assert not acc.expired()
    acc.feed(_FakeBatch(16))
    assert not acc.expired()
    now["t"] = 0.6
    assert acc.expired()
    assert len(acc.take()) == 1
    assert not acc.expired()          # empty: never expired
    acc.set_k(2)
    assert acc.feed(_FakeBatch(16)) == []
    assert len(acc.feed(_FakeBatch(16))[0]) == 2
    acc.feed(_FakeBatch(16))
    acc.clear()
    assert len(acc) == 0 and acc.drain() == []


def test_dispatch_config_resolve_forms(monkeypatch):
    monkeypatch.delenv("WF_DISPATCH", raising=False)
    monkeypatch.delenv("WF_DISPATCH_K", raising=False)
    assert DispatchConfig.resolve(None) is None
    assert DispatchConfig.resolve(False) is None
    assert DispatchConfig.resolve(0) is None      # int 0 == the '0' spelling
    assert DispatchConfig.resolve(True).k == 8
    assert DispatchConfig.resolve(6).k == 6
    assert DispatchConfig.resolve({"k": 3, "linger_s": 0.0}).linger_s == 0.0
    cfg = DispatchConfig(k=5)
    assert DispatchConfig.resolve(cfg) is cfg
    monkeypatch.setenv("WF_DISPATCH", "0")
    assert DispatchConfig.resolve(None) is None
    monkeypatch.setenv("WF_DISPATCH", "4")
    assert DispatchConfig.resolve(None).k == 4
    monkeypatch.setenv("WF_DISPATCH", json.dumps({"k": 2, "prewarm": False}))
    r = DispatchConfig.resolve(None)
    assert (r.k, r.prewarm) == (2, False)
    monkeypatch.setenv("WF_DISPATCH", "1")
    monkeypatch.setenv("WF_DISPATCH_K", "16")
    assert DispatchConfig.resolve(None).k == 16
    assert DispatchConfig.resolve(4).k == 16      # K env wins whenever on
    with pytest.raises(ValueError):
        DispatchConfig(k=0)
    with pytest.raises(ValueError):
        DispatchConfig(linger_s=-1)


def test_build_k_ladder():
    assert build_k_ladder(1) == [1]
    assert build_k_ladder(8) == [1, 2, 4, 8]
    assert build_k_ladder(6) == [1, 2, 4, 6]
    with pytest.raises(ValueError):
        build_k_ladder(0)


# ------------------------------------------------------------- push_many


def _win_ops():
    return [wf.Map(lambda t: {"v": t.v * 2.0}),
            wf.Win_Seq(lambda wid, it: it.sum("v"),
                       WindowSpec(10, 10, win_type_t.TB), num_keys=NKEYS)]


def _batches_equal(a, b):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_push_many_byte_identical_to_sequential_push():
    src = mk_source(128)
    seq = CompiledChain(_win_ops(), src.payload_spec(), batch_capacity=16)
    fused = CompiledChain(_win_ops(), src.payload_spec(), batch_capacity=16)
    batches = list(mk_source(128).batches(16))
    outs_seq = [seq.push(b) for b in batches]
    outs_fused = fused.push_many(batches)
    assert len(outs_fused) == len(outs_seq)
    for a, b in zip(outs_seq, outs_fused):
        _batches_equal(a, b)
    for sa, sb in zip(seq.states, fused.states):
        _batches_equal(sa, sb)
    # K=1 degenerates to push (same executable, same path)
    one = fused.push_many([batches[0]])
    assert len(one) == 1


def test_push_many_stats_k_batches_one_kernel():
    src = mk_source(96)
    chain = CompiledChain(_win_ops(), src.payload_spec(), batch_capacity=16)
    batches = list(mk_source(96).batches(16))
    chain.push_many(batches)
    rec = chain.ops[0].get_StatsRecords()[0]
    assert rec.batches_received == len(batches)
    assert rec.num_kernels == 1               # ONE launch for K batches
    assert rec.bytes_received > 0


def test_warm_scan_touches_no_state():
    src = mk_source(64)
    chain = CompiledChain(_win_ops(), src.payload_spec(), batch_capacity=16)
    before = [jax.tree.map(np.asarray, s) for s in chain.states]
    chain.warm_scan(4, 16)
    chain.warm_scan(1, 16)                    # degenerate delegates to warm
    for a, b in zip(before, chain.states):
        _batches_equal(a, b)
    assert ("scan", 0) in chain._steps


# ------------------------------------------------------- Pipeline driver


def _run_pipeline(dispatch=None, total=TOTAL, batch=16, **kw):
    got = []
    wf.Pipeline(mk_source(total), [wf.Map(lambda t: {"v": t.v * 3.0}),
                                   wf.Win_Seq(lambda wid, it: it.sum("v"),
                                              WindowSpec(12, 6, win_type_t.CB),
                                              num_keys=NKEYS)],
                wf.Sink(win_collect(got)), batch_size=batch,
                dispatch=dispatch, **kw).run()
    return got


def test_pipeline_dispatch_byte_identical_with_partial_tail():
    plain = _run_pipeline(None)
    # 15 batches at K=4: three full groups + a 3-batch tail at EOS
    assert _run_pipeline(4) == plain
    assert _run_pipeline(1) == plain          # K=1 degenerate pass-through
    # one giant partial group (prewarm off: the K=32 executable would be
    # traced but never run — the stream holds only 15 batches)
    assert _run_pipeline({"k": 32, "prewarm": False}) == plain


@pytest.mark.parametrize("name", sorted(CASES))
def test_mp_matrix_case_dispatch_byte_identical(name):
    def run(dispatch):
        src = wf.Source(lambda i: {"v": ((i * 13) % 23).astype(jnp.float32)},
                        total=MP_TOTAL, num_keys=MP_K)
        results = []

        def cb(view):
            if view is None:
                return
            for k, w, r in zip(view["key"].tolist(), view["id"].tolist(),
                               np.asarray(view["payload"]).tolist()):
                results.append((k, w, round(float(r), 3)))
        ops = CASES[name]()
        if not isinstance(ops, (list, tuple)):
            ops = [ops]
        wf.Pipeline(src, list(ops), wf.Sink(cb), batch_size=40,
                    dispatch=dispatch).run()
        return results

    assert run(3) == run(None)


def test_pipeline_dispatch_with_rebatcher_byte_identical(tmp_path):
    cfg = wf.ControlConfig(autotune=True, ladder_up=1, ladder_down=1,
                           decide_every=4, settle_batches=1,
                           cache_path=str(tmp_path / "tuning.json"))
    plain = _run_pipeline(None)
    got = _run_pipeline(4, control=cfg)
    # capacity rungs are wall-clock hill-climb decisions, so WHERE the
    # rebatcher re-slices (and therefore which batch a window fires in —
    # the sink interleaving) is not replay-pinned between two runs; the
    # window RESULTS are lane-exact invariant (the PR 3 contract), and the
    # accumulator flushes short at every capacity switch rather than mix
    # shapes
    assert sorted(got) == sorted(plain)
    # the K tuner rode along: its gauge is published
    assert wfcontrol.gauges().get("dispatch_k") is not None


def test_pipeline_dispatch_ysb_all_subsystems(tmp_path):
    from windflow_tpu.benchmarks import ysb
    ysb_total = 3000

    def run(dispatch, **kw):
        results = []

        def cb(view):
            if view is None:
                return
            for k, w, c in zip(view["key"].tolist(), view["id"].tolist(),
                               np.asarray(view["payload"]).tolist()):
                results.append((int(k), int(w), int(c)))
        wf.Pipeline(ysb.make_source(ysb_total), ysb.make_ops(),
                    wf.Sink(cb), batch_size=256, dispatch=dispatch,
                    **kw).run()
        return results

    plain = run(None)
    assert sum(c for _, _, c in plain) == ysb.oracle_totals(ysb_total)
    cfg = wf.ControlConfig(autotune=False, admission=True,
                           refill_per_batch=10**9)
    got = run(4, monitoring=str(tmp_path / "mon"),
              trace=str(tmp_path / "tr"), control=cfg)
    assert got == plain
    # the fused launches journaled (sampled at launch 2 with >= 2 groups)
    events = [json.loads(ln)
              for ln in open(tmp_path / "mon" / "events.jsonl")]
    fused = [e for e in events if e.get("event") == "dispatch_fused"]
    assert fused and all(e["k"] > 1 for e in fused)


def test_pipeline_dispatch_trace_ids_identical(tmp_path):
    from windflow_tpu.observability import tracing

    def ids(dispatch, d):
        _run_pipeline(dispatch, trace=str(tmp_path / d))
        recs, _ = tracing.load_flight(str(tmp_path / d))
        return ([r["tid"] for r in recs if r["kind"] == "ingest"],
                sorted({(r["tid"], r["kind"], r["stage"]) for r in recs
                        if r["stage"] == "chain"}))

    plain_ids, plain_spans = ids(None, "off")
    fused_ids, fused_spans = ids(4, "on")
    assert fused_ids == plain_ids             # minted at ingest, positional
    assert fused_spans == plain_spans         # per-batch spans synthesized


# ------------------------------------------------------- threaded driver


def _run_threaded(dispatch=None, **kw):
    got = []
    ThreadedPipeline(mk_source(480),
                     [[wf.Map(lambda t: {"v": t.v * 3})],
                      [wf.Map(lambda t: {"v": t.v + 1})]],
                     wf.Sink(collect(got)), batch_size=16, pin=False,
                     dispatch=dispatch, **kw).run()
    return got


def test_threaded_dispatch_byte_identical():
    plain = _run_threaded(None)
    assert sorted(_run_threaded(4)) == sorted(plain)
    # generous linger: groups mostly fill; tiny linger: mostly flush short —
    # results identical either way
    assert sorted(_run_threaded({"k": 4, "linger_s": 0.0})) == sorted(plain)


def test_threaded_dispatch_under_fault_drain():
    plain = _run_threaded(None)
    got = []
    plan = FaultPlan([FaultSpec("queue.stall", kind="stall", stall_s=0.3,
                                where={"stage": "seg0", "pos": 3})])
    ThreadedPipeline(mk_source(480),
                     [[wf.Map(lambda t: {"v": t.v * 3})],
                      [wf.Map(lambda t: {"v": t.v + 1})]],
                     wf.Sink(collect(got)), batch_size=16, pin=False,
                     dispatch=4, faults=plan).run()
    assert sorted(got) == sorted(plain)


# ------------------------------------------------------- PipeGraph driver


def _graph(win_sink, plain_sink, mode=Mode.DEFAULT, **kw):
    g = PipeGraph("disp", batch_size=40, mode=mode, **kw)
    a = g.add_source(wf.Source(lambda i: {"v": (i % 9).astype(jnp.float32)},
                               total=TOTAL, num_keys=NKEYS, name="a"))
    b = g.add_source(wf.Source(lambda i: {"v": (i % 7).astype(jnp.float32)},
                               total=TOTAL // 2, num_keys=NKEYS, name="b",
                               ts_fn=lambda i: i * 2))
    m = a.merge(b).split(lambda t: t.v % 2 == 0, 2)
    (m.select(1).add(wf.Win_Seq(lambda wid, it: it.sum("v"),
                                WindowSpec(12, 12, win_type_t.CB),
                                num_keys=NKEYS))
     .add_sink(wf.Sink(win_sink)))
    m.select(0).add_sink(wf.Sink(plain_sink))
    return g


def _run_graph(mode=Mode.DEFAULT, supervised=False, **kw):
    wins, plains = [], []
    g = _graph(win_collect(wins), collect(plains), mode=mode,
               **({} if supervised else kw))
    if supervised:
        run_graph_supervised(g, checkpoint_every=3, **kw)
    else:
        g.run()
    return wins, plains


def test_pipegraph_dispatch_byte_identical_both_modes():
    for mode in (Mode.DEFAULT, Mode.DETERMINISTIC):
        w0, p0 = _run_graph(mode)
        w1, p1 = _run_graph(mode, dispatch=4)
        assert (w1, p1) == (w0, p0), mode


def test_pipegraph_threaded_dispatch_identical():
    # the threaded graph driver fuses per pipe-thread (ring-dry linger, EOS
    # tail) — same results as the per-batch threaded run, thread interleave
    # aside; DETERMINISTIC keeps the Ordering_Node's async readback in play.
    # In DEFAULT mode the merge interleave is timing-dependent (window
    # CONTENT varies run to run, dispatch or not), so only interleave-
    # insensitive aggregates compare; DETERMINISTIC releases in ts order, so
    # the window multiset is exact.
    for mode in (Mode.DEFAULT, Mode.DETERMINISTIC):
        wins, plains = [], []
        _graph(win_collect(wins), collect(plains), mode=mode).run(
            threaded=True)
        w1, p1 = [], []
        _graph(win_collect(w1), collect(p1), mode=mode,
               dispatch={"k": 4, "linger_s": 0.0}).run(threaded=True)
        assert sorted(p1) == sorted(plains), mode
        if mode == Mode.DETERMINISTIC:
            assert sorted(w1) == sorted(wins), mode
        else:
            assert round(sum(v for _, _, v in w1), 3) == \
                round(sum(v for _, _, v in wins), 3)
            assert len(w1) == len(wins)


# ------------------------------------------------------ supervised driver


def test_supervised_dispatch_byte_identical_mid_accumulator_checkpoint():
    oracle = []
    SupervisedPipeline(mk_source(), [wf.Map(lambda t: {"v": t.v * 2})],
                       wf.Sink(collect(oracle)), batch_size=16).run()
    # checkpoint_every=5 with K=4: commits land MID-accumulator, forcing the
    # partial-group flush; with faults, restores clear + replay re-feeds
    for faults in (None,
                   FaultPlan([FaultSpec("chain.step", at=[2, 7]),
                              FaultSpec("checkpoint.save", kind="torn",
                                        at=[1])])):
        got = []
        sp = SupervisedPipeline(mk_source(), [wf.Map(lambda t: {"v": t.v * 2})],
                                wf.Sink(collect(got)), batch_size=16,
                                checkpoint_every=5, dispatch=4, faults=faults,
                                backoff_base=0.001, backoff_cap=0.02)
        sp.run()
        assert got == oracle, f"faults={faults is not None}"
        if faults is not None:
            assert sp.restarts >= 1


def test_supervised_dispatch_windowed_chain_under_faults():
    oracle = []
    src = mk_source()
    op = wf.Win_Seq(lambda wid, it: it.sum("v"),
                    WindowSpec(10, 10, win_type_t.TB), num_keys=NKEYS)
    SupervisedPipeline(src, [op], wf.Sink(win_collect(oracle)),
                       batch_size=16).run()
    got = []
    op2 = wf.Win_Seq(lambda wid, it: it.sum("v"),
                     WindowSpec(10, 10, win_type_t.TB), num_keys=NKEYS)
    plan = FaultPlan([FaultSpec("chain.step", at=[4]),
                      FaultSpec("source.next", at=[9])])
    sp = SupervisedPipeline(mk_source(), [op2], wf.Sink(win_collect(got)),
                            batch_size=16, checkpoint_every=3, dispatch=4,
                            faults=plan, backoff_base=0.001, backoff_cap=0.02)
    sp.run()
    assert got == oracle
    assert sp.restarts >= 1


def test_supervised_dispatch_poison_quarantines_exact_batch():
    # a deterministic poison inside a fused group: the group failure is only
    # attributable to its head, so the replay DEGRADES to per-batch through
    # the failed range — the failure re-manifests at its true position and
    # quarantine dead-letters exactly the poison batch, never a group-mate
    # (and the restart budget is spent like the per-batch path, not once per
    # innocent head)
    from windflow_tpu.runtime.faults import DeadLetterQueue
    oracle = []
    SupervisedPipeline(mk_source(), [wf.Map(lambda t: {"v": t.v * 2})],
                       wf.Sink(collect(oracle)), batch_size=16).run()
    plan = FaultPlan([FaultSpec("chain.step", where={"pos": 4})])
    got, dlq = [], DeadLetterQueue()
    sp = SupervisedPipeline(mk_source(), [wf.Map(lambda t: {"v": t.v * 2})],
                            wf.Sink(collect(got)), batch_size=16,
                            checkpoint_every=5, dispatch=4, faults=plan,
                            dead_letter=dlq, poison_threshold=3,
                            backoff_base=0.001, backoff_cap=0.02)
    sp.run()
    assert [e["pos"] for e in dlq.entries] == [4]
    # every batch except the quarantined one delivered (16 tuples skipped)
    skipped = {i for i in range(64, 80)}      # batch 4 of 16-tuple batches
    assert got == [t for t in oracle if t[0] not in skipped]


def test_graph_supervised_dispatch_byte_identical_under_faults():
    # DETERMINISTIC mode: the fused root pushes drive the Ordering_Node's
    # async counts readback under checkpoint/restore too
    for mode in (Mode.DEFAULT, Mode.DETERMINISTIC):
        w0, p0 = _run_graph(mode)
        plan = FaultPlan([FaultSpec("chain.step", at=[3])])
        w1, p1 = _run_graph(mode, supervised=True, dispatch=4, faults=plan,
                            backoff_base=0.001, backoff_cap=0.02)
        assert (w1, p1) == (w0, p0), mode


def test_graph_supervised_dispatch_with_step_timeout():
    # fused compute AND per-batch delivery both run under the step watchdog
    # (a generous timeout: nothing fires, results identical)
    w0, p0 = _run_graph(Mode.DETERMINISTIC)
    w1, p1 = _run_graph(Mode.DETERMINISTIC, supervised=True, dispatch=4,
                        step_timeout=30.0)
    assert (w1, p1) == (w0, p0)


# ------------------------------------------------- ordering async readback


from test_ordering_renumbering import mk_batch as _mk_ord  # noqa: E402


def _mk_ord_batch(ids, ts):
    return _mk_ord(ids, ts=ts)


def test_ordering_async_readback_identical_to_settled():
    """Deferred counts settle (the async hot path) releases EXACTLY what an
    eagerly-settled node releases, over a randomized two-channel sweep."""
    from windflow_tpu.parallel.ordering import Ordering_Node, ordering_mode_t

    def run(eager, seed):
        rng = np.random.default_rng(seed)
        node = Ordering_Node(2, ordering_mode_t.TS)
        out = []
        t = [0, 0]
        for _ in range(12):
            ch = int(rng.integers(0, 2))
            n = int(rng.integers(1, 5))
            ts = sorted(int(t[ch] + x) for x in rng.integers(0, 9, n))
            t[ch] = ts[-1]
            rel = node.push(ch, _mk_ord_batch(list(range(n)), ts))
            if eager:
                node.settle()         # the seed behavior: block every push
            cnt = node.last_release_count
            if rel is not None and cnt:
                v = np.asarray(rel.ts)[:cnt].tolist()
                out.extend(v)
        for ch in range(2):
            rel = node.close_channel(ch)
            if rel is not None and node.last_release_count:
                out.extend(np.asarray(rel.ts)[:node.last_release_count]
                           .tolist())
        rel = node.flush()
        if rel is not None and node.last_release_count:
            out.extend(np.asarray(rel.ts)[:node.last_release_count].tolist())
        return out

    for seed in range(3):
        assert run(False, seed) == run(True, seed), seed


def test_ordering_push_returns_empty_release_not_stale():
    from windflow_tpu.parallel.ordering import Ordering_Node, ordering_mode_t
    node = Ordering_Node(2, ordering_mode_t.TS)
    rel = node.push(0, _mk_ord_batch([1, 2], [1, 2]))
    # ch1 silent: nothing releasable — the async contract returns a batch
    # with zero valid lanes (or None), never stale data
    assert node.last_release_count == 0
    if rel is not None:
        assert int(np.asarray(jnp.sum(rel.valid))) >= 0
    rel2 = node.push(1, _mk_ord_batch([3], [5]))
    assert node.last_release_count > 0
    got = np.asarray(rel2.ts)[:node.last_release_count].tolist()
    # ch0's ts=1 sits strictly below the low watermark (min(2, 5) = 2);
    # ts=2 == the watermark is a potential duplicate and stays held
    assert got == [1]


# --------------------------------------------------------- autotuner K


def test_dispatch_k_autotuner_ladder_and_cache(tmp_path):
    cache = str(tmp_path / "tuning.json")
    cfg = wf.ControlConfig(autotune=True, ladder_up=0, ladder_down=0,
                           decide_every=2, settle_batches=0,
                           cache_path=cache)
    plain = _run_pipeline(None, total=480)
    got = _run_pipeline({"k": 4, "autotune_k": True}, total=480, control=cfg)
    assert got == plain
    assert wfcontrol.gauges()["dispatch_k"] in (1, 2, 4)
    assert os.path.exists(cache)
    # the K plan lives under its own namespaced key, beside (never clobbering)
    # the capacity plan for the same chain
    assert (wfcontrol.dispatch_tuning_key("sig", "pay", "cpu")
            != wfcontrol.tuning_key("sig", "pay", "cpu"))


def test_dispatch_gauges_registered():
    from windflow_tpu.observability.names import (CONTROL_GAUGES,
                                                  JOURNAL_EVENTS,
                                                  PERF_PROXY_FAMILIES)
    assert "dispatch_k" in CONTROL_GAUGES
    assert "dispatch_linger_depth" in CONTROL_GAUGES
    assert "dispatch_fused" in JOURNAL_EVENTS
    assert "dispatch" in PERF_PROXY_FAMILIES
