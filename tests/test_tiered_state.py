"""Tiered keyed state (``windflow_tpu/state``) correctness.

The contract under test: with ``tiered=`` on, a TINY hot table produces
results byte-identical to an untiered table big enough for the whole key
space — across all four drivers, the full Nexmark query set, FaultPlan
chaos with checkpoints landing mid-spill (restore discards in-flight
spills, replay re-derives them), and the ``.npz`` checkpoint layer; the
OFF path is byte-for-byte today's state pytrees; the 100x-key-space
acceptance workload completes with ``overflow_drops == 0``; and the
WF114 validator, HostStore, fleet merge, and ``wf_state.py`` tier
surfaces hold their pins."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.nexmark import make_query
from windflow_tpu.operators.join import IntervalJoin, StreamTableJoin
from windflow_tpu.operators.rank import Distinct, TopN
from windflow_tpu.operators.session import SessionWindow
from windflow_tpu.operators.source import DeviceSource
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.runtime.faults import FaultPlan, FaultSpec
from windflow_tpu.state import HostStore, TierConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# a spill-forcing stream-table join workload: 300 keys through a hot table
# that clears the WF114 reserve (batch 50 + pending 100) but holds only a
# fraction of the key space
N_KEYS, TOTAL, BATCH = 300, 1000, 50
HOT = 192


def _enrich_src():
    def gen(i):
        is_def = i < N_KEYS
        k = jnp.where(is_def, i, (i * 2477) % N_KEYS)
        return {"side": jnp.where(is_def, 1, 0).astype(jnp.int32),
                "k": k.astype(jnp.int32),
                "cat": jnp.where(is_def, (i * 13) % 7, 0).astype(jnp.int32)}
    return DeviceSource(
        gen, total=TOTAL,
        key_fn=lambda i: jnp.where(i < N_KEYS, i, (i * 2477) % N_KEYS),
        ts_fn=lambda i: i // 8)


def _stj(slots, tiered):
    return StreamTableJoin(lambda t: t.side == 1, lambda t: t.k,
                           lambda t: {"category": t.cat},
                           num_slots=slots, tiered=tiered)


def _run_stj(slots, tiered, driver="plain", faults=None, ckpt=2):
    op = _stj(slots, tiered)
    rows = []

    def cb(v):
        if v is None:
            return
        rows.extend(zip(v["key"].tolist(), v["id"].tolist(),
                        v["ts"].tolist(),
                        np.asarray(v["payload"]["category"]).tolist()))
    sink = wf.Sink(cb)
    if driver == "plain":
        wf.Pipeline(_enrich_src(), [op], sink, batch_size=BATCH).run()
    elif driver == "threaded":
        wf.ThreadedPipeline(_enrich_src(), [[op]], sink,
                            batch_size=BATCH).run()
    elif driver == "supervised":
        wf.SupervisedPipeline(_enrich_src(), [op], sink, batch_size=BATCH,
                              checkpoint_every=ckpt, max_restarts=8,
                              backoff_base=0.001, backoff_cap=0.01,
                              faults=faults).run()
    elif driver == "graph-supervised":
        g = wf.PipeGraph(batch_size=BATCH)
        mp = g.add_source(_enrich_src())
        mp.add(op)
        mp.add_sink(sink)
        g.run_supervised(checkpoint_every=ckpt, max_restarts=8,
                         backoff_base=0.001, backoff_cap=0.01,
                         faults=faults)
    return rows, op


# ------------------------------------------------- OFF path is unchanged


def test_tiered_off_state_pytree_unchanged():
    """tiered=None must build EXACTLY today's state pytrees — no tier
    fields, no geometry change (the perf-gate pins depend on it)."""
    spec = {"side": jax.ShapeDtypeStruct((), jnp.int32),
            "k": jax.ShapeDtypeStruct((), jnp.int32),
            "cat": jax.ShapeDtypeStruct((), jnp.int32)}
    st = _stj(64, None).init_state(spec)
    assert set(st) == {"key", "val", "ver", "vid", "vseq", "used",
                       "pkey", "pval", "pts", "pid", "pseq", "pok",
                       "wm", "seq", "version", "dropped"}
    s = SessionWindow(lambda t: {"n": jnp.ones((), jnp.int32)},
                      WindowSpec.session(3), num_keys=32)
    assert "hkey" not in s.init_state(spec)
    t = TopN(lambda t: t.k, 2, num_keys=32)
    assert set(t.init_state(spec)) == {"score", "tid", "evict", "eos"}
    ij = IntervalJoin(lambda t: t.side == 1, 0, 4)
    ij.bind_geometry(64)
    assert "lokey" not in ij.init_state(spec)


def test_env_resolution(monkeypatch):
    assert TierConfig.resolve(None) is None
    assert TierConfig.resolve(False) is None
    monkeypatch.setenv("WF_STATE_TIERED", "0")
    assert TierConfig.resolve(None) is None
    monkeypatch.setenv("WF_STATE_TIERED", "1")
    assert TierConfig.resolve(None) == TierConfig()
    monkeypatch.setenv("WF_STATE_TIERED", '{"readmit_rows": 4}')
    assert TierConfig.resolve(None).readmit_rows == 4
    monkeypatch.setenv("WF_STATE_HOT_CAPACITY", "4096")
    assert TierConfig.resolve(None).hot_capacity == 4096
    assert TierConfig.resolve(True).hot_capacity == 4096
    monkeypatch.setenv("WF_STATE_TIERED", "not-a-config")
    with pytest.raises(ValueError):
        TierConfig.resolve(None)


# ------------------------- tiny hot table == big untiered table (4 drivers)


@pytest.mark.parametrize("driver", ["plain", "threaded", "supervised",
                                    "graph-supervised"])
def test_tiered_equals_untiered_big_table_all_drivers(driver):
    ref, _ = _run_stj(4096, None, driver)
    got, op = _run_stj(HOT, dict(), driver)
    assert got == ref
    # the hot table really is too small: spills and readmissions flowed
    assert op._tier.store.counters()["state_spills"] > 0
    assert op._tier.store.key_count() > 0


def test_tiered_zero_movement_when_hot_table_fits():
    """A hot table that holds the whole key space never touches the cold
    tier — tiering on a fitting workload is the off path plus bookkeeping."""
    ref, _ = _run_stj(4096, None)
    got, op = _run_stj(4096, dict())
    assert got == ref
    c = op._tier.store.counters()
    assert c["state_spills"] == 0 and c["state_readmits"] == 0


# --------------------------------- the full Nexmark query set, tiered on/off


def _run_nexmark(name, tiered, total=400, batch=50):
    src, ops = make_query(name, total, **(
        {"tiered": tiered} if tiered is not None else {}))
    out = []

    def cb(v):
        if v is None:
            return
        keys = v["key"].tolist()
        ids_ = v["id"].tolist()
        ts = v["ts"].tolist()
        flat = [np.asarray(leaf).tolist()
                for leaf in jax.tree.leaves(v["payload"])]
        out.extend(zip(keys, ids_, ts, *flat))
    wf.Pipeline(src, ops, wf.Sink(cb), batch_size=batch).run()
    return out


@pytest.mark.parametrize("name", ["q3_enrich_join", "q4_interval_join",
                                  "q5_session", "q6_topn", "q7_distinct"])
def test_nexmark_query_tiered_on_off_identical(name):
    """Every stateful Nexmark query, tiered-on vs tiered-off. The hot
    capacity covers the query's key space here, so the results must agree
    as SETS OF ROWS exactly (sorted: the session/top-N slot directories
    emit in admission order rather than key order)."""
    off = sorted(_run_nexmark(name, None))
    on = sorted(_run_nexmark(name, dict(hot_capacity=256)))
    assert on == off


# --------------------------------------------- chaos: checkpoint mid-spill


@pytest.mark.chaos
def test_chaos_checkpoint_mid_spill_byte_identical():
    """FaultPlan restarts with checkpoints landing while spills are in
    flight (checkpoint_every=2 against per-push spill traffic): the
    restore discards the in-flight copy, replay re-derives it, and the
    output stream is byte-identical to the fault-free run — with the tiny
    hot table still matching the big untiered reference."""
    ref, _ = _run_stj(4096, None, "supervised")
    clean, _ = _run_stj(HOT, dict(), "supervised")
    plan = FaultPlan([FaultSpec(site="chain.step", at=(2, 7, 11))])
    chaos, op = _run_stj(HOT, dict(), "supervised", faults=plan)
    assert clean == ref
    assert chaos == ref
    assert op._tier.store.counters()["state_spills"] > 0


@pytest.mark.chaos
def test_chaos_graph_driver_mid_spill():
    ref, _ = _run_stj(4096, None, "graph-supervised")
    plan = FaultPlan([FaultSpec(site="chain.step", at=(3, 9))])
    chaos, op = _run_stj(HOT, dict(), "graph-supervised", faults=plan)
    assert chaos == ref
    assert op._tier.store.counters()["state_spills"] > 0


# --------------------------------------------------- .npz checkpoint layer


def test_npz_checkpoint_roundtrip_carries_cold_tier(tmp_path):
    from windflow_tpu.runtime.checkpoint import load_chain, save_chain
    from windflow_tpu.runtime.pipeline import CompiledChain
    src = _enrich_src()

    def mk():
        op = _stj(HOT, dict())
        return CompiledChain([op], src.payload_spec(),
                             batch_capacity=BATCH), op
    chain, op = mk()
    for b in _enrich_src().batches(BATCH):
        chain.push(b)
    assert op._tier.store.key_count() > 0
    path = str(tmp_path / "ck.npz")
    save_chain(chain, path)
    chain2, op2 = mk()
    load_chain(chain2, path)
    assert op2._tier.store.key_count() == op._tier.store.key_count()
    for a, b in zip(jax.tree.leaves(chain.states),
                    jax.tree.leaves(chain2.states)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # both continue identically
    nb = next(_enrich_src().batches(BATCH))
    o1, o2 = chain.push(nb), chain2.push(nb)
    for a, b in zip(jax.tree.leaves(o1), jax.tree.leaves(o2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_pre_tiering_checkpoint_restores_into_tiered_chain(tmp_path):
    """A checkpoint written by an UNTIERED chain restores into a TIERED
    chain of the same geometry: leaves match BY KEY PATH (the tier fields
    interleave into the dict flatten order, so a positional restore would
    misassign arrays), tier fields keep their fresh init, and the cold
    tier starts empty."""
    from windflow_tpu.runtime.checkpoint import load_chain, save_chain
    from windflow_tpu.runtime.pipeline import CompiledChain
    src = _enrich_src()
    chain = CompiledChain([_stj(HOT, None)], src.payload_spec(),
                          batch_capacity=BATCH)
    chain.push(next(_enrich_src().batches(BATCH)))
    path = str(tmp_path / "old.npz")
    save_chain(chain, path)
    assert not [k for k in np.load(path).files if k.startswith("tier")]
    op2 = _stj(HOT, dict())
    chain2 = CompiledChain([op2], src.payload_spec(), batch_capacity=BATCH)
    load_chain(chain2, path)
    # shared fields restored exactly, by name
    for f in ("key", "used", "ver", "wm", "version", "dropped"):
        assert np.array_equal(np.asarray(chain.states[0][f]),
                              np.asarray(chain2.states[0][f])), f
    # tier fields stay fresh; the cold tier is empty
    assert int(np.asarray(chain2.states[0]["ocnt"])) == 0
    assert int(np.asarray(chain2.states[0]["spills"])) == 0
    assert op2._tier.store.key_count() == 0


def test_legacy_positional_checkpoint_refused_for_tiered_chain(tmp_path):
    """A checkpoint file with NO leaf-path metadata (a pre-PR-11 save)
    cannot restore into a tiered chain — positional matching would
    silently misassign fields, so the restore refuses loudly."""
    import json as _json
    from windflow_tpu.runtime import checkpoint as ck
    from windflow_tpu.runtime.pipeline import CompiledChain
    src = _enrich_src()
    chain = CompiledChain([_stj(HOT, None)], src.payload_spec(),
                          batch_capacity=BATCH)
    chain.push(next(_enrich_src().batches(BATCH)))
    # write a legacy-format file: strip the path map from the meta
    arrays = ck._flatten(chain.states)
    meta = {ck._META_SHA: ck._digest_map(arrays)}
    raw = ck._to_npz_bytes(ck._serialize(arrays, meta))
    path = str(tmp_path / "legacy.npz")
    ck._atomic_write_bytes(path, raw)
    chain2 = CompiledChain([_stj(HOT, dict())], src.payload_spec(),
                           batch_capacity=BATCH)
    with pytest.raises(KeyError):
        ck.load_chain(chain2, path)
    # ... but it still restores fine into an untiered chain (positional)
    chain3 = CompiledChain([_stj(HOT, None)], src.payload_spec(),
                           batch_capacity=BATCH)
    ck.load_chain(chain3, path)
    for a, b in zip(jax.tree.leaves(chain.states),
                    jax.tree.leaves(chain3.states)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


# ----------------------------------------------------------- WF114 pins


def test_wf114_undersized_hot_table_is_an_error():
    from windflow_tpu.analysis import validate
    src, ops = make_query("q3_enrich_join", 400, n_auctions=300,
                          num_slots=64, tiered=dict())
    p = wf.Pipeline(src, ops, wf.Sink(lambda v: None), batch_size=64)
    rep = validate(p)
    assert any(d.code == "WF114" and d.severity == "error"
               for d in rep.diagnostics)
    with pytest.raises(Exception):
        rep.raise_if_errors()


def test_wf114_clean_when_sized_and_blockable():
    from windflow_tpu.analysis import validate
    # batch 128 + pending 256 = 384 (3 x 128: blockable), hot 1024 > 384
    src, ops = make_query("q3_enrich_join", 800, n_auctions=600,
                          num_slots=1024, tiered=dict())
    p = wf.Pipeline(src, ops, wf.Sink(lambda v: None), batch_size=128)
    assert not [d for d in validate(p).diagnostics if d.code == "WF114"]


def test_wf114_nonblockable_width_warns():
    from windflow_tpu.analysis import validate
    src, ops = make_query("q3_enrich_join", 400, n_auctions=300,
                          num_slots=1024, tiered=dict())
    p = wf.Pipeline(src, ops, wf.Sink(lambda v: None), batch_size=50)
    found = [d for d in validate(p).diagnostics if d.code == "WF114"]
    assert found and all(d.severity == "warning" for d in found)


def test_wf114_sequence_tracing_under_supervision():
    from windflow_tpu.analysis import validate
    from windflow_tpu.observability import TraceConfig
    src, ops = make_query("q3_enrich_join", 800, n_auctions=600,
                          num_slots=1024, tiered=dict())
    sp = wf.SupervisedPipeline(src, ops, wf.Sink(lambda v: None),
                               batch_size=128)
    rep = validate(sp, trace=TraceConfig(ids="sequence"))
    assert any(d.code == "WF114" and d.severity == "error"
               for d in rep.diagnostics)


def test_wf114_wall_clock_admission_under_supervision():
    from windflow_tpu.analysis import validate
    from windflow_tpu.control import ControlConfig
    src, ops = make_query("q3_enrich_join", 800, n_auctions=600,
                          num_slots=1024, tiered=dict())
    sp = wf.SupervisedPipeline(src, ops, wf.Sink(lambda v: None),
                               batch_size=128)
    rep = validate(sp, control=ControlConfig(admission=True, rate_tps=1e6))
    assert any(d.code == "WF114" and d.severity == "error"
               for d in rep.diagnostics)


def test_wf114_absent_when_untiered():
    from windflow_tpu.analysis import validate
    src, ops = make_query("q3_enrich_join", 400)
    p = wf.Pipeline(src, ops, wf.Sink(lambda v: None), batch_size=50)
    assert not [d for d in validate(p).diagnostics if d.code == "WF114"]


# ------------------------------------------------------- HostStore units


def test_host_store_lww_by_version_triplet():
    hs = HostStore("t", {"v": np.int32})
    hs.upsert([7], [5], [1], [0], {"v": np.asarray([10])})
    # an OLDER spill must not roll the row back
    hs.upsert([7], [4], [9], [9], {"v": np.asarray([11])})
    found, meta, cols = hs.lookup(np.asarray([7]), np.asarray([True]))
    assert found[0] and cols["v"][0] == 10 and tuple(meta[0]) == (5, 1, 0)
    # a NEWER spill wins
    hs.upsert([7], [6], [0], [0], {"v": np.asarray([12])})
    _, _, cols = hs.lookup(np.asarray([7]), np.asarray([True]))
    assert cols["v"][0] == 12
    assert hs.key_count() == 1


def test_host_store_multimap_fetch_and_compaction():
    hs = HostStore("a", {"ts": np.int32, "p": np.int32}, unique=False)
    z = np.zeros(3, np.int64)
    hs.append([1, 1, 2], z, z, z, {"ts": np.asarray([5, 9, 7]),
                                   "p": np.asarray([50, 90, 70])})
    mask, _m, cols = hs.fetch_multi(np.asarray([1, 2]),
                                    np.asarray([True, True]), 4)
    assert mask[0].sum() == 2 and mask[1].sum() == 1
    assert sorted(cols["ts"][0][mask[0]].tolist()) == [5, 9]
    # rows stay (fetch is read-only: the one-tier rule)
    assert len(hs) == 3
    # frontier compaction retires rows below the bound
    assert hs.compact_below("ts", 7) == 1
    assert len(hs) == 2 and hs.counters()["state_compactions"] == 1


def test_host_store_manifest_roundtrip():
    hs = HostStore("t", {"v": np.int32})
    hs.upsert([3, 9], [1, 2], [0, 0], [0, 0],
              {"v": np.asarray([30, 90])})
    man = hs.manifest()
    hs2 = HostStore("t", {"v": np.int32})
    hs2.restore(man)
    assert hs2.key_count() == 2
    assert hs2.counters() == hs.counters()
    _, _, cols = hs2.lookup(np.asarray([9]), np.asarray([True]))
    assert cols["v"][0] == 90


def test_host_store_pop_keys_sorted_and_removing():
    hs = HostStore("t", {"v": np.int32})
    hs.upsert([9, 3, 5], [1, 1, 1], [0, 0, 0], [0, 0, 0],
              {"v": np.asarray([1, 2, 3])})
    keys, cols = hs.pop_keys(2)
    assert keys.tolist() == [3, 5]
    assert hs.key_count() == 1


# --------------------------------------------- fleet merge + CLI surfaces


def test_merge_snapshots_folds_tier_gauges_max_counters_sum():
    from windflow_tpu.observability.device_health import merge_snapshots
    mk = lambda hot, spills: {
        "graph": "g", "operators": [{
            "name": "join", "event_time": {
                "watermark_ts": 5,
                "tier": {"hot_used": hot, "hot_pct": hot / 2.56,
                         "cold_keys": 10 * hot,
                         "state_spills": spills, "state_readmits": 2,
                         "state_compactions": 1}}}]}
    out = merge_snapshots([mk(100, 7), mk(80, 5)], hosts=["a", "b"])
    t = out["operators"][0]["event_time"]["tier"]
    assert t["hot_used"] == 100 and t["cold_keys"] == 1000   # max
    assert t["state_spills"] == 12 and t["state_readmits"] == 4   # sum
    assert t["state_compactions"] == 2


def _fake_monitoring_dir(tmp_path):
    snap = {"graph": "g", "operators": [{
        "name": "join", "event_time": {
            "watermark_ts": 9, "occupancy_pct": 91.0,
            "tier": {"hot_slots": 256, "hot_used": 200, "hot_pct": 78.1,
                     "outbox_depth": 3, "cold_keys": 5000,
                     "cold_rows": 5000, "state_spills": 640,
                     "state_readmits": 120, "state_compactions": 7}}}]}
    d = tmp_path / "mon"
    d.mkdir()
    (d / "snapshot.json").write_text(json.dumps(snap))
    (d / "snapshots.jsonl").write_text(json.dumps(snap) + "\n")
    (d / "events.jsonl").write_text("")
    return d


def test_wf_state_cli_tier_section_and_risk_threshold(tmp_path):
    d = _fake_monitoring_dir(tmp_path)
    script = os.path.join(REPO, "scripts", "wf_state.py")
    r = subprocess.run([sys.executable, script, "--monitoring-dir", str(d),
                       "--report", "tier"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "tiered state" in r.stdout and "cold-keys" in r.stdout
    assert "[OVERFLOW-RISK]" not in r.stdout       # 78.1 < default 80
    r2 = subprocess.run([sys.executable, script, "--monitoring-dir", str(d),
                        "--report", "tier", "--risk-threshold", "70"],
                        capture_output=True, text=True)
    assert r2.returncode == 0 and "[OVERFLOW-RISK]" in r2.stdout
    rj = subprocess.run([sys.executable, script, "--monitoring-dir", str(d),
                        "--json"], capture_output=True, text=True)
    assert rj.returncode == 0
    out = json.loads(rj.stdout)
    assert out["tier"]["join"]["state_spills"] == 640
    bad = subprocess.run([sys.executable, script, "--monitoring-dir",
                          str(d), "--risk-threshold", "0"],
                         capture_output=True, text=True)
    assert bad.returncode == 2


def test_wf_health_cli_names_tier_tables(tmp_path):
    snap = {"graph": "g",
            "health": {"devices": [{"device": "cpu:0", "kind": "cpu"}],
                       "state_bytes": {"join": 123456}},
            "operators": [{
                "name": "join", "event_time": {"tier": {
                    "hot_slots": 256, "hot_used": 250, "hot_pct": 97.7,
                    "cold_keys": 9000, "state_spills": 11,
                    "state_readmits": 5, "state_compactions": 0}}}]}
    d = tmp_path / "mon"
    d.mkdir()
    (d / "snapshot.json").write_text(json.dumps(snap))
    (d / "snapshots.jsonl").write_text(json.dumps(snap) + "\n")
    (d / "events.jsonl").write_text("")
    script = os.path.join(REPO, "scripts", "wf_health.py")
    r = subprocess.run([sys.executable, script, "--monitoring-dir", str(d)],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "tiered tables" in r.stdout and "hot=250/256" in r.stdout


# --------------------------------------------------- per-operator parity


def test_distinct_tiered_equals_big_table():
    def run(slots, tiered):
        src = DeviceSource(
            lambda i: {"v": ((i * 2477) % 700).astype(jnp.int32)},
            total=4096, key_fn=lambda i: (i * 2477) % 700,
            ts_fn=lambda i: i // 8)
        op = Distinct(lambda t: t.v, num_slots=slots, tiered=tiered)
        rows = []

        def cb(v):
            if v is None:
                return
            rows.extend(zip(v["key"].tolist(), v["id"].tolist()))
        wf.Pipeline(src, [op], wf.Sink(cb), batch_size=256).run()
        return rows, op
    ref, _ = run(4096, None)
    got, op = run(512, dict())
    assert got == ref
    assert op._tier.store.counters()["state_spills"] > 0


def test_topn_tiered_final_leaderboards_match():
    def run(slots, tiered):
        src = DeviceSource(
            lambda i: {"price": ((i * 7919) % 997).astype(jnp.int32)},
            total=4096, key_fn=lambda i: (i * 2477) % 700,
            ts_fn=lambda i: i // 8)
        op = TopN(lambda t: t.price, 3, num_keys=slots, tiered=tiered)
        final = {}

        def cb(v):
            if v is None:
                return
            for k, r, i, s in zip(
                    v["key"].tolist(),
                    np.asarray(v["payload"]["rank"]).tolist(),
                    v["id"].tolist(),
                    np.asarray(v["payload"]["score"]).tolist()):
                final[(k, r)] = (i, s)
        wf.Pipeline(src, [op], wf.Sink(cb), batch_size=256).run()
        return final, op
    ref, _ = run(1024, None)
    got, op = run(400, dict())
    assert got == ref
    assert op._tier.store.counters()["state_spills"] > 0


def test_session_tiered_equals_big_table():
    def run(slots, tiered):
        src = DeviceSource(
            lambda i: {"v": jnp.ones((), jnp.int32)}, total=4096,
            key_fn=lambda i: (i % 37) * 17 + (i // 37) % 25
            + ((i // 641) * 40) % 600,
            ts_fn=lambda i: i // 4)
        op = SessionWindow(lambda t: {"n": jnp.ones((), jnp.int32)},
                           WindowSpec.session(3, delay=2),
                           num_keys=slots, tiered=tiered)
        rows = []

        def cb(v):
            if v is None:
                return
            rows.extend(zip(v["key"].tolist(), v["id"].tolist(),
                            np.asarray(v["payload"]["start"]).tolist(),
                            np.asarray(v["payload"]["end"]).tolist(),
                            np.asarray(v["payload"]["n"]).tolist()))
        wf.Pipeline(src, [op], wf.Sink(cb), batch_size=256).run()
        return sorted(rows), op
    ref, _ = run(2048, None)
    got, op = run(300, dict())
    assert got == ref
    assert op._tier.store.counters()["state_spills"] > 0


def test_interval_join_tiered_recovers_ring_overwrites():
    def run(archive, tiered):
        def gen(i):
            is_open = (i % 8) == 0
            a = jnp.where(is_open, (i // 8) % 64, (i * 2477) % 64)
            return {"side": jnp.where(is_open, 1, 0).astype(jnp.int32),
                    "a": a.astype(jnp.int32)}

        def key(i):
            is_open = (i % 8) == 0
            return jnp.where(is_open, (i // 8) % 64, (i * 2477) % 64)
        src = DeviceSource(gen, total=4096, key_fn=key,
                           ts_fn=lambda i: i // 8)
        op = IntervalJoin(lambda t: t.side == 1, 0, 300, archive=archive,
                          max_matches=96, tiered=tiered,
                          emit=lambda l, r: {"lid": l.id, "rid": r.id})
        rows = []

        def cb(v):
            if v is None:
                return
            rows.extend(zip(np.asarray(v["payload"]["lid"]).tolist(),
                            np.asarray(v["payload"]["rid"]).tolist()))
        wf.Pipeline(src, [op], wf.Sink(cb), batch_size=256).run()
        return sorted(rows), op
    ref, _ = run(8192, None)          # big ring: nothing ever overwritten
    got, op = run(256, dict())        # tiny ring + cold tier
    lost, _ = run(256, None)          # tiny ring untiered: drops pairs
    assert got == ref
    assert len(lost) < len(ref)
    assert op._tier_l.store.counters()["state_spills"] > 0


# -------------------------------------------------- telemetry registration


def test_tier_counters_published_and_registered():
    from windflow_tpu.observability.names import (JOURNAL_EVENTS,
                                                  STAGE_COUNTERS,
                                                  STAGE_GAUGES)
    for n in ("state_spills", "state_readmits", "state_compactions"):
        assert n in STAGE_COUNTERS
    for n in ("tier_hot_used", "tier_cold_keys"):
        assert n in STAGE_GAUGES
    for n in ("spill", "readmit"):
        assert n in JOURNAL_EVENTS
    _, op = _run_stj(HOT, dict())
    sc = op.stage_counters()
    assert sc["state_spills"] > 0
    assert "tier_hot_used" in sc and "tier_cold_keys" in sc
    sec = None
    # event-time section carries the tier sub-dict even with monitoring off
    # (the snapshot-time read path)
    from windflow_tpu.runtime.pipeline import CompiledChain
    src = _enrich_src()
    op2 = _stj(HOT, dict())
    chain = CompiledChain([op2], src.payload_spec(), batch_capacity=BATCH)
    for b in _enrich_src().batches(BATCH):
        chain.push(b)
    sec = op2.event_time_stats(chain.states[0])
    assert sec["tier"]["hot_slots"] == HOT
    assert sec["tier"]["state_spills"] > 0


def test_count_drops_rejects_unregistered_names():
    from windflow_tpu.ops.lookup import count_drops
    with pytest.raises(ValueError):
        count_drops(jnp.asarray(0), "not_a_counter", 1)
    assert int(count_drops(jnp.asarray(1), "overflow_drops", 2)) == 3


def test_ttl_compaction_retires_cold_rows_end_to_end():
    """With builds spread through the stream (the watermark keeps
    advancing), cold rows older than the TTL retire from the host store
    on the maintain cadence — and retirement never changes results (the
    retention bound only drops rows no admissible probe can need... here
    the stale keys are simply never probed again)."""
    def gen(i):
        # a rolling build frontier: every 4th event (re)defines a key from
        # a sliding window, the rest probe only RECENT keys
        is_def = (i % 4) == 0
        k = jnp.where(is_def, (i // 4) % 500, ((i // 8) + i % 3) % 500)
        return {"side": jnp.where(is_def, 1, 0).astype(jnp.int32),
                "k": k.astype(jnp.int32),
                "cat": (i % 7).astype(jnp.int32)}
    src = DeviceSource(gen, total=8000,
                       key_fn=lambda i: jnp.where(
                           (i % 4) == 0, (i // 4) % 500,
                           ((i // 8) + i % 3) % 500),
                       ts_fn=lambda i: i // 4)
    op = StreamTableJoin(lambda t: t.side == 1, lambda t: t.k,
                         lambda t: {"category": t.cat}, num_slots=256,
                         tiered=dict(ttl=200, compact_every=4))
    wf.Pipeline(src, [op], wf.Sink(lambda v: None), batch_size=64).run()
    c = op._tier.store.counters()
    assert c["state_spills"] > 0
    assert c["state_compactions"] > 0


# ---------------------------------------------- the 100x acceptance (slow)


@pytest.mark.slow
def test_100x_key_space_zero_overflow_drops_and_exact():
    """THE acceptance workload: the Nexmark stream-table join at 100x the
    per-batch key space with a fixed hot table — completes with
    ``overflow_drops == 0`` and byte-identical results to an untiered
    table sized for the whole key space."""
    batch = 64
    hot = 4 * batch
    keys = 100 * batch
    total = keys + 20 * batch

    def run(num_slots, tiered):
        src, ops = make_query("q3_enrich_join", total, n_auctions=keys,
                              num_slots=num_slots, tiered=tiered)
        rows = []

        def cb(v):
            if v is None:
                return
            rows.extend(zip(v["key"].tolist(), v["id"].tolist(),
                            np.asarray(v["payload"]["category"]).tolist()))
        wf.Pipeline(src, ops, wf.Sink(cb), batch_size=batch).run()
        return rows, ops[0]
    ref, _ = run(keys + 64, None)
    got, op = run(hot, dict())
    assert got == ref
    import numpy as _np
    # read the drop counter off the op's published stage counters
    assert op.stage_counters()["overflow_drops"] == 0
    assert op._tier.store.key_count() > hot      # genuinely cold-resident
    assert op.stage_counters()["state_spills"] > 0
