"""withBatch / withDevice / withOpt builder hints and chain() outcome recording.

VERDICT r03 items 6/7: the GPU builders' device parameters
(``wf/builders_gpu.hpp:115-130``) must not be silently-dropped decoration —
withBatch is a micro-batch capacity ceiling honored by Pipeline/PipeGraph
batch-size resolution, withDevice places the fused chain's states on a chosen
``jax.Device``, and chain() records its chainability outcome instead of
computing it into a dead ``pass``.
"""

import jax
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import opt_level_t
from windflow_tpu.runtime.builders import (Map_Builder, ReduceSink_Builder,
                                           Source_Builder)
from windflow_tpu.runtime.pipegraph import PipeGraph
from windflow_tpu.runtime.pipeline import CompiledChain, Pipeline, resolve_batch_hint


def _src(total=300):
    return (Source_Builder(lambda i: {"v": i.astype(jnp.int32)})
            .withName("src").withTotal(total).withKeys(4).build())


def test_with_batch_sets_pipeline_batch_size():
    m = Map_Builder(lambda t: {"v": t.v * 2}).withBatch(64).build()
    rs = ReduceSink_Builder(lambda t: t.v).withName("s").build()
    p = Pipeline(_src(), [m, rs])
    assert p.batch_size == 64
    res = p.run()
    assert int(res["s"]) == sum(i * 2 for i in range(300))


def test_with_batch_min_over_chain_and_explicit_wins():
    m1 = Map_Builder(lambda t: {"v": t.v}).withBatch(128).build()
    m2 = Map_Builder(lambda t: {"v": t.v}).withBatch(32).build()
    assert resolve_batch_hint([m1, m2]) == 32
    p = Pipeline(_src(), [m1, m2])
    assert p.batch_size == 32          # a fused chain can't exceed any ceiling
    p2 = Pipeline(_src(), [Map_Builder(lambda t: {"v": t.v}).withBatch(32).build()],
                  batch_size=100)
    assert p2.batch_size == 100        # explicit batch_size wins over hints


def test_with_batch_flows_through_pipegraph():
    m = Map_Builder(lambda t: {"v": t.v * 3}).withBatch(56).build()
    rs = ReduceSink_Builder(lambda t: t.v).withName("total").build()
    g = PipeGraph("hints")
    g.add_source(_src()).chain(m).add(rs)
    res = g.run()
    assert g.batch_size == 56
    assert int(res["total"]) == sum(i * 3 for i in range(300))


def test_with_batch_rejects_nonpositive():
    with pytest.raises(ValueError, match="withBatch"):
        Map_Builder(lambda t: {"v": t.v}).withBatch(0)


def test_with_device_places_chain_state_and_output():
    dev = jax.devices()[3]
    m = Map_Builder(lambda t: {"v": t.v + 1}).withDevice(dev).build()
    assert m._device is dev
    src = _src(100)
    chain = CompiledChain([m], src.payload_spec(), batch_capacity=50)
    assert chain.device is dev
    out = chain.push(src.make_batch(jnp.asarray(0, jnp.int32), 50))
    assert all(leaf.devices() == {dev} for leaf in jax.tree.leaves(out))
    chain.reset_states()
    for st in chain.states:
        assert all(leaf.devices() == {dev} for leaf in jax.tree.leaves(st)
                   if hasattr(leaf, "devices"))


def test_conflicting_with_device_hints_raise():
    m1 = Map_Builder(lambda t: {"v": t.v}).withDevice(jax.devices()[1]).build()
    m2 = Map_Builder(lambda t: {"v": t.v}).withDevice(jax.devices()[2]).build()
    with pytest.raises(ValueError, match="conflicting withDevice"):
        CompiledChain([m1, m2], _src().payload_spec(), batch_capacity=32)


def test_with_opt_recorded_on_operator():
    m = Map_Builder(lambda t: {"v": t.v}).withOpt(opt_level_t.LEVEL2).build()
    assert m._opt_level == opt_level_t.LEVEL2
    with pytest.raises(ValueError):
        Map_Builder(lambda t: {"v": t.v}).withOpt(99)


def test_chain_outcome_recorded_and_rendered():
    g = PipeGraph("chainrec", batch_size=64)
    m = Map_Builder(lambda t: {"v": t.v * 2}).withName("dbl").build()
    acc = wf.Accumulator(lambda t: t.data["v"], init_value=0, num_keys=8,
                         name="acc")
    rs = wf.ReduceSink(lambda t: t.data, name="out")
    g.add_source(_src()).chain(m).chain(acc).add(rs)
    assert m._chained is True                     # FORWARD: queue-free fusion
    assert acc._chained is False                  # KEYBY: fell back to add
    dot = g.dump_DOTGraph()
    assert "dbl (chained)" in dot
    assert "acc (keyby)" in dot
    res = g.run()
    # Accumulator emits the per-key RUNNING sum per tuple (rolling reduce);
    # key = i % 4 (DeviceSource default)
    running = {k: 0 for k in range(4)}
    expect = 0
    for i in range(300):
        running[i % 4] += 2 * i
        expect += running[i % 4]
    assert int(res["out"]) == expect
