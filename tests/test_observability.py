"""Tests for the graph-level telemetry subsystem (windflow_tpu/observability):
registry aggregation math, log-bucket percentiles vs a numpy oracle, reporter
lifecycle (no thread leak), journal schema round-trip, topology export for a
merge/split graph, monitoring end-to-end through every driver, and the
OLD-drop counter under per-key skew > delay (VERDICT r05 item 6)."""

import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import Mode, win_type_t
from windflow_tpu.observability import (LogHistogram, MetricsRegistry,
                                        MonitoringConfig, Reporter,
                                        EventJournal, read_journal,
                                        topology_dot, topology_json)
from windflow_tpu.observability import journal as wfjournal


# ------------------------------------------------------------- LogHistogram

def test_log_histogram_percentiles_against_numpy_oracle():
    rng = np.random.default_rng(7)
    # log-uniform latencies spanning 3 decades (10 us .. 10 ms)
    samples = 10 ** rng.uniform(-5, -2, size=2000)
    h = LogHistogram()
    for s in samples:
        h.record(float(s))
    assert h.count == len(samples)
    np.testing.assert_allclose(h.sum, samples.sum(), rtol=1e-9)
    for q in (50, 95, 99):
        oracle = np.percentile(samples, q)
        got = h.percentile(q)
        # bucket growth is sqrt(2): the reported percentile must be within one
        # bucket of the true one
        assert oracle / 2**0.5 <= got <= oracle * 2**0.5, (q, got, oracle)


def test_log_histogram_edge_cases():
    h = LogHistogram()
    assert h.percentile(50) == 0.0 and h.count == 0
    h.record(0.0)            # below the first bound: lands in bucket 0
    h.record(1e9)            # beyond the last bound: overflow bucket
    assert h.count == 2
    assert h.percentile(99) == 1e9          # overflow reports the true max
    summ = h.summary_us()
    assert summ["samples"] == 2 and summ["max"] == 1e15


def test_log_histogram_prometheus_buckets_cumulative():
    h = LogHistogram()
    for s in (1e-5, 1e-4, 1e-3):
        h.record(s)
    buckets = h.prometheus_buckets()
    assert buckets[-1][0] == float("inf") and buckets[-1][1] == 3
    counts = [c for _, c in buckets]
    assert counts == sorted(counts)          # cumulative = monotone


# ---------------------------------------------------------- registry math

def _linear_graph(monitoring=False, total=256, batch=32):
    g = wf.PipeGraph("agg", batch_size=batch, monitoring=monitoring)
    out = []
    (g.add_source(wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=total,
                            name="gen"))
     .add(wf.Map(lambda t: {"v": t.v * 2}, name="dbl"))
     .add_sink(wf.Sink(lambda v: out.append(v), name="snk")))
    return g, out


@pytest.fixture(scope="module")
def ran_linear_graph():
    """One completed linear graph shared by the read-only registry/reporter/
    topology tests (each builds its own registry; the graph is only read)."""
    g, out = _linear_graph()
    g.run()
    return g


def test_registry_aggregates_graph_counters(ran_linear_graph):
    g = ran_linear_graph
    reg = MetricsRegistry("agg")
    reg.register_graph(g)
    snap = reg.snapshot()
    rows = {r["name"]: r for r in snap["operators"]}
    assert set(rows) == {"gen", "dbl", "snk"}
    # sink saw every live tuple; the chain op counted its 8 batches
    assert rows["snk"]["inputs_received"] == 256
    assert rows["dbl"]["batches_received"] == 8
    assert rows["dbl"]["num_kernels"] == 8
    # totals = per-operator sums
    assert snap["totals"]["inputs_received"] == sum(
        r["inputs_received"] for r in snap["operators"])
    # second snapshot derives rates from the delta (no progress -> 0)
    snap2 = reg.snapshot()
    rows2 = {r["name"]: r for r in snap2["operators"]}
    assert rows2["snk"]["rate_in_tps"] == 0.0


def test_registry_aggregates_across_replicas():
    """Replica counters sum: a parallelism-3 operator with per-replica records
    contributes the sum, not replica 0."""
    op = wf.Map(lambda t: {"v": t.v}, name="m", parallelism=3)
    for i, rec in enumerate(op.get_StatsRecords()):
        rec.inputs_received = 10 * (i + 1)       # 10+20+30
    reg = MetricsRegistry("reps")
    reg.register_operator(op)
    snap = reg.snapshot()
    row = snap["operators"][0]
    assert row["replicas"] == 3
    assert row["inputs_received"] == 60


def test_stats_record_service_histogram_and_dict():
    from windflow_tpu.stats import Stats_Record
    rec = Stats_Record("op")
    rec.record_launch(0.001)
    rec.record_launch(0.004)
    d = rec.as_dict()
    assert d["service_time_us"]["samples"] == 2
    assert d["service_time_us"]["p99"] >= 3000
    assert "tuples_dropped_old" in d


def test_prometheus_exposition_names(ran_linear_graph):
    g = ran_linear_graph
    reg = MetricsRegistry("promg")
    reg.register_graph(g)
    reg.record_e2e(0.002)
    text = reg.to_prometheus()
    assert 'windflow_inputs_received_total{graph="promg",operator="snk"} 256' \
        in text
    assert "# TYPE windflow_service_time_seconds histogram" in text
    assert 'windflow_e2e_latency_seconds_count{graph="promg"} 1' in text
    # histogram buckets carry le labels ending at +Inf
    assert 'le="+Inf"' in text


# ----------------------------------------------------------- reporter

def test_reporter_start_stop_no_thread_leak(tmp_path, ran_linear_graph):
    g = ran_linear_graph
    reg = MetricsRegistry("rep")
    reg.register_graph(g)
    before = threading.active_count()
    rep = Reporter(reg, str(tmp_path), interval_s=0.05)
    rep.start()
    assert rep.running
    import time
    time.sleep(0.2)                        # a few ticks
    rep.stop()
    assert not rep.running
    assert threading.active_count() == before
    # artifacts exist and parse
    snap = json.loads((tmp_path / "snapshot.json").read_text())
    assert snap["graph"] == "rep" and snap["operators"]
    lines = (tmp_path / "snapshots.jsonl").read_text().splitlines()
    assert len(lines) >= 1
    assert (tmp_path / "metrics.prom").read_text().startswith("# TYPE")
    # stop() is idempotent
    rep.stop()
    assert threading.active_count() == before


# ----------------------------------------------------------- journal

def test_journal_schema_round_trip(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    j = EventJournal(path)
    j.event("custom", foo=1, bar="x")
    with j.span("work", item=3):
        j.event("inner")
    j.close()
    evs = read_journal(path)
    assert [e["event"] for e in evs] == ["custom", "work", "inner", "work"]
    for e in evs:
        assert isinstance(e["t"], float) and isinstance(e["wall"], float)
    # monotonic timestamps are ordered
    ts = [e["t"] for e in evs]
    assert ts == sorted(ts)
    begin, end = evs[1], evs[3]
    assert begin["phase"] == "begin" and end["phase"] == "end"
    assert begin["span"] == end["span"] and end["dur_s"] >= 0
    assert begin["item"] == 3


def test_journal_span_records_error(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    j = EventJournal(path)
    with pytest.raises(ValueError):
        with j.span("boom"):
            raise ValueError("x")
    j.close()
    evs = read_journal(path)
    assert evs[-1]["phase"] == "end" and evs[-1]["error"] == "ValueError"


def test_journal_span_error_field_collision(tmp_path):
    """A span opened WITH an 'error' field (supervisor restore spans carry the
    error being recovered from) that then raises must not die on a duplicate
    kwarg: the end record carries the in-span failure, overriding."""
    path = str(tmp_path / "ev.jsonl")
    j = EventJournal(path)
    with pytest.raises(RuntimeError):
        with j.span("restore", error="OrigError"):
            raise RuntimeError("boom")
    j.close()
    evs = read_journal(path)
    assert evs[0]["error"] == "OrigError"
    assert evs[1]["phase"] == "end" and evs[1]["error"] == "RuntimeError"


def test_module_level_journal_noop_when_inactive():
    assert wfjournal.get_active() is None
    wfjournal.record("nothing", x=1)        # must not raise
    with wfjournal.span("nothing"):
        pass


# ------------------------------------------------- topology export

def _split_merge_graph(monitoring=False):
    g = wf.PipeGraph("topo", batch_size=32, monitoring=monitoring)
    p = g.add_source(wf.Source(lambda i: {"v": i.astype(jnp.float32)},
                               total=128, num_keys=4, name="gen"))
    p.split(lambda t: (t.key % 2).astype(jnp.int32), 2)
    a = p.select(0).add(wf.Map(lambda t: {"v": t.v + 1.0}, name="inc"))
    b = p.select(1).add(wf.Map(lambda t: {"v": t.v - 1.0}, name="dec"))
    m = a.merge(b)
    m.add(wf.ReduceSink(lambda t: t.v, name="tot"))
    return g


def test_topology_export_merge_split_graph():
    g = _split_merge_graph()
    g.run()
    topo = topology_json(g)
    assert len(topo["nodes"]) == 4           # root + 2 branches + merged
    kinds = sorted(e["kind"] for e in topo["edges"])
    assert kinds == ["merge", "merge", "split", "split"]
    # app tree: merge-full absorbed both branch subtrees; the merged pipe is a
    # new root beside the (now child-less) split root (wf/pipegraph.hpp:846-858)
    assert len(topo["app_tree"]) == 2
    assert all(r["children"] == [] for r in topo["app_tree"])
    merged_idx = next(i for i, n in enumerate(topo["nodes"])
                      if any(o["name"] == "tot" for o in n["ops"]))
    assert {r["pipe"] for r in topo["app_tree"]} == {0, merged_idx}
    dot = topology_dot(g)
    assert dot.startswith("digraph") and "split" in dot and "merge" in dot
    # every node id renders
    for i in range(4):
        assert f"mp{i}" in dot


def test_topology_rates_annotated_from_snapshot(ran_linear_graph):
    g = ran_linear_graph
    reg = MetricsRegistry("topo2")
    reg.register_graph(g)
    snap = reg.snapshot()
    topo = topology_json(g, snap)
    node = topo["nodes"][0]
    ops = {o["name"]: o for o in node["ops"]}
    assert "rate_in_tps" in ops["dbl"]
    assert topo["totals"]["inputs_received"] > 0


# ------------------------------------ monitoring end-to-end (drivers)

def test_pipegraph_monitoring_artifacts(tmp_path):
    cfg = MonitoringConfig(out_dir=str(tmp_path), interval_s=0.05,
                           e2e_sample_every=2)
    g, out = _linear_graph(monitoring=cfg)
    g.run()
    files = set(os.listdir(tmp_path))
    assert {"snapshot.json", "snapshots.jsonl", "metrics.prom",
            "events.jsonl", "topology.dot", "topology.json"} <= files
    snap = json.loads((tmp_path / "snapshot.json").read_text())
    rows = {r["name"]: r for r in snap["operators"]}
    assert rows["snk"]["inputs_received"] == 256
    assert snap["e2e_latency_us"]["samples"] >= 1
    assert snap["e2e_latency_us"]["p50"] > 0
    # journal closed and reset
    assert wfjournal.get_active() is None
    evs = read_journal(str(tmp_path / "events.jsonl"))
    names = {e["event"] for e in evs}
    assert "monitoring_start" in names and "monitoring_end" in names
    assert "eos_flush" in names


def test_pipeline_monitoring_via_env(tmp_path, monkeypatch):
    monkeypatch.setenv("WF_MONITORING", str(tmp_path))
    monkeypatch.setenv("WF_MONITORING_INTERVAL", "0.05")
    out = []
    src = wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=128,
                    name="gen")
    wf.Pipeline(src, [wf.Map(lambda t: {"v": t.v * 2}, name="dbl")],
                wf.Sink(lambda v: out.append(v), name="snk"),
                batch_size=32).run()
    assert (tmp_path / "snapshot.json").exists()
    topo = json.loads((tmp_path / "topology.json").read_text())
    assert topo["pipeline"] is True
    assert [s["name"] for s in topo["stages"]] == ["gen", "dbl", "snk"]


def test_monitoring_off_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("WF_MONITORING", raising=False)
    g, _ = _linear_graph()
    g.run()
    assert g._monitor is None
    # '0' and '' also mean off (the WF_ORDERING_SKIP_SORTED convention)
    for v in ("", "0"):
        monkeypatch.setenv("WF_MONITORING", v)
        assert MonitoringConfig.resolve(None) is None
    monkeypatch.setenv("WF_MONITORING", "1")
    assert MonitoringConfig.resolve(None) is not None


def test_supervised_graph_journal_has_checkpoint_span(tmp_path):
    cfg = MonitoringConfig(out_dir=str(tmp_path), interval_s=0.05)
    g = wf.PipeGraph("sup", batch_size=32, monitoring=cfg)
    (g.add_source(wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=256,
                            name="gen"))
     .add(wf.Map(lambda t: {"v": t.v + 1}, name="inc"))
     .add(wf.ReduceSink(lambda t: t.v, name="tot")))
    g.run_supervised(checkpoint_every=4)
    evs = read_journal(str(tmp_path / "events.jsonl"))
    cks = [e for e in evs if e["event"] == "checkpoint"]
    assert len(cks) >= 2                       # at least one interval + EOS
    begins = [e for e in cks if e["phase"] == "begin"]
    ends = [e for e in cks if e["phase"] == "end"]
    assert len(begins) == len(ends)
    assert all("dur_s" in e for e in ends)
    assert {e["span"] for e in begins} == {e["span"] for e in ends}


def test_threaded_driver_queue_gauges(tmp_path):
    cfg = MonitoringConfig(out_dir=str(tmp_path), interval_s=0.05)
    g = wf.PipeGraph("thr", mode=Mode.DETERMINISTIC, batch_size=64,
                     monitoring=cfg)
    sa = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=256,
                   num_keys=4, ts_fn=lambda i: 2 * i, name="a")
    sb = wf.Source(lambda i: {"v": -i.astype(jnp.float32)}, total=256,
                   num_keys=4, ts_fn=lambda i: 2 * i + 1, name="b")
    pa, pb = g.add_source(sa), g.add_source(sb)
    m = pa.merge(pb)
    m.add(wf.Map(lambda t: {"v": t.v * 2.0}, name="x2"))
    m.add(wf.ReduceSink(lambda t: t.v, name="out"))
    g.run(threaded=True)
    snap = json.loads((tmp_path / "snapshot.json").read_text())
    # one gauge per dataflow edge: 2 source rings + 2 merge rings
    assert set(snap["queues"]) == {"src->0", "src->2", "0->1", "2->1"}
    evs = read_journal(str(tmp_path / "events.jsonl"))
    names = {e["event"] for e in evs}
    assert "eos_propagate" in names
    assert "ordering_flush" in names or "ordering_close_channel" in names


def test_watermark_gauge_for_tb_window(tmp_path):
    cfg = MonitoringConfig(out_dir=str(tmp_path), interval_s=10.0)
    g = wf.PipeGraph("wm", batch_size=64, monitoring=cfg)
    op = wf.Win_SeqFFAT(lambda t: 1, jnp.add,
                        spec=wf.WindowSpec(8, 8, win_type_t.TB),
                        num_keys=4, name="tbwin")
    (g.add_source(wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=512,
                            num_keys=4, name="gen"))
     .add(op)
     .add(wf.ReduceSink(lambda t: t.data, name="tot")))
    g.run()
    snap = json.loads((tmp_path / "snapshot.json").read_text())
    rows = {r["name"]: r for r in snap["operators"]}
    wmg = rows["tbwin"]["watermark"]
    assert wmg["watermark_ts"] == 511
    assert wmg["fire_frontier_ts"] >= 0
    assert wmg["lag_ts"] >= 0


# ---------------------------------- OLD-drop counter (VERDICT r05 item 6)

def test_global_time_straggler_drops_counted_fuzz():
    """Per-key skew > delay under global_time TB windows DROPS the laggard
    key's tuples (the docstring used to claim skew only delays firing); the
    device counter must equal a host oracle across fuzzed skews."""
    from windflow_tpu.batch import Batch
    rng = np.random.default_rng(11)
    for trial in range(4):
        K, C = 4, 64
        win = 8
        op = wf.Win_SeqFFAT(lambda t: 1, jnp.add,
                            spec=wf.WindowSpec(win, win, win_type_t.TB),
                            num_keys=K, pane_capacity=64, name="g")
        assert op.global_time
        st = op.init_state({"v": jax.ShapeDtypeStruct((), jnp.float32)})
        skew = int(rng.integers(2 * win, 6 * win))   # > delay (=0) + win
        dropped_oracle = 0
        horizon_pane = 0                             # first un-fired pane
        step = jax.jit(op.apply)
        for b in range(3):
            key = rng.integers(0, K, C).astype(np.int32)
            base = b * 2 * win
            # key 0 lags `skew` behind the global clock; others advance it
            ts = np.where(key == 0, np.maximum(base - skew, 0),
                          base + rng.integers(0, 2 * win, C)).astype(np.int32)
            batch = Batch(key=jnp.asarray(key),
                          id=jnp.arange(C, dtype=jnp.int32),
                          ts=jnp.asarray(ts),
                          payload={"v": jnp.ones(C, jnp.float32)},
                          valid=jnp.ones(C, bool))
            pane = ts // op.pane_len
            dropped_oracle += int((pane < horizon_pane).sum())
            st, out = step(st, batch)
            # replay the engine's frontier arithmetic on the host
            wm = int(np.asarray(st.wm))
            hi = max((wm - op.spec.delay - op.spec.win_len)
                     // op.spec.slide + 1, 0)
            horizon_pane = int(np.asarray(st.next_win)) * op.spanes
            assert int(np.asarray(st.next_win)) <= hi or hi == 0
        got = int(np.asarray(st.dropped_old))
        assert got == dropped_oracle, (trial, got, dropped_oracle)
        assert got > 0, "fuzz must actually exercise the drop path"
        # and the counter surfaces through Stats_Record / collect_stats
        op.collect_stats(st)
        assert op.get_StatsRecords()[0].tuples_dropped_old == got


def test_per_key_tb_straggler_drops_counted():
    """The per-key-watermark path (global_time=False) drops tuples behind the
    per-key fired frontier; dropped_old counts them too."""
    from windflow_tpu.batch import Batch
    K, C, win = 2, 32, 4
    op = wf.Win_SeqFFAT(lambda t: 1, jnp.add,
                        spec=wf.WindowSpec(win, win, win_type_t.TB),
                        num_keys=K, pane_capacity=64, global_time=False,
                        name="pk")
    st = op.init_state({"v": jax.ShapeDtypeStruct((), jnp.float32)})
    step = jax.jit(op.apply)

    def mk(ts):
        ts = np.asarray(ts, np.int32)
        n = len(ts)
        pad = C - n
        return Batch(key=jnp.asarray(np.pad(np.zeros(n, np.int32), (0, pad))),
                     id=jnp.arange(C, dtype=jnp.int32),
                     ts=jnp.asarray(np.pad(ts, (0, pad))),
                     payload={"v": jnp.ones(C, jnp.float32)},
                     valid=jnp.asarray([True] * n + [False] * pad))

    st, _ = step(st, mk(np.arange(4 * win)))     # fires windows 0..2 on key 0
    assert int(np.asarray(st.next_win)[0]) > 0
    st, _ = step(st, mk([0, 1, 2]))              # stragglers behind frontier
    assert int(np.asarray(st.dropped_old)) == 3


def test_cb_windows_never_count_drops():
    from windflow_tpu.batch import Batch
    op = wf.Win_SeqFFAT(lambda t: 1, jnp.add,
                        spec=wf.WindowSpec(4, 4, win_type_t.CB),
                        num_keys=2, pane_capacity=64, name="cb")
    st = op.init_state({"v": jax.ShapeDtypeStruct((), jnp.float32)})
    b = Batch(key=jnp.zeros(16, jnp.int32), id=jnp.arange(16, dtype=jnp.int32),
              ts=jnp.zeros(16, jnp.int32),
              payload={"v": jnp.ones(16, jnp.float32)},
              valid=jnp.ones(16, bool))
    st, _ = jax.jit(op.apply)(st, b)
    st, _ = jax.jit(op.apply)(st, b)
    assert int(np.asarray(st.dropped_old)) == 0
