"""Tests for keyed operators: Accumulator (rolling reduce) and KeyedMap (stateful map).

Oracle: sequential per-key python fold over the same stream — the reference's
result-invariance-under-parallelism property (src/graph_test/test_graph_1.cpp:77-87)
restated as invariance under batching."""

import numpy as np
import jax.numpy as jnp

import windflow_tpu as wf


def test_accumulator_rolling_sum_per_key():
    total, K = 300, 5
    outputs = []

    def cb(view):
        if view is None:
            return
        for k, v in zip(view["key"].tolist(), view["payload"].tolist()):
            outputs.append((k, v))

    src = wf.Source(lambda i: {"v": (i % 7).astype(jnp.float32)},
                    total=total, num_keys=K)
    acc = wf.Accumulator(lambda t: t.v, init_value=0.0, num_keys=K)
    sink = wf.Sink(cb)
    wf.Pipeline(src, [acc], sink, batch_size=64).run()

    # sequential oracle
    run = {k: 0.0 for k in range(K)}
    expect = []
    for i in range(total):
        k = i % K
        run[k] += float(i % 7)
        expect.append((k, run[k]))
    assert len(outputs) == total
    # per-key sequences must match exactly in order
    got_by_key = {k: [v for kk, v in outputs if kk == k] for k in range(K)}
    exp_by_key = {k: [v for kk, v in expect if kk == k] for k in range(K)}
    for k in range(K):
        np.testing.assert_allclose(got_by_key[k], exp_by_key[k], rtol=1e-5)


def test_accumulator_invariant_under_batch_size():
    total, K = 211, 3
    finals = []
    for bs in (32, 211, 512):
        src = wf.Source(lambda i: {"v": jnp.ones((), jnp.float32)},
                        total=total, num_keys=K)
        acc = wf.Accumulator(lambda t: t.v, num_keys=K)
        p = wf.Pipeline(src, [acc], batch_size=bs)
        p.run()
        finals.append(np.asarray(p.chain.states[0]))
    for f in finals[1:]:
        np.testing.assert_allclose(f, finals[0])
    # per-key counts of i % K over range(total)
    expect = np.asarray([len([i for i in range(total) if i % K == k]) for k in range(K)],
                        np.float32)
    np.testing.assert_allclose(finals[0], expect)


def test_accumulator_custom_combine_max():
    total, K = 100, 4
    src = wf.Source(lambda i: {"v": ((i * 37) % 91).astype(jnp.float32)},
                    total=total, num_keys=K)
    acc = wf.Accumulator(lambda t: t.v, combine=jnp.maximum, identity=-1e30,
                         init_value=-1e30, num_keys=K)
    p = wf.Pipeline(src, [acc], batch_size=33)
    p.run()
    got = np.asarray(p.chain.states[0])
    expect = np.full(K, -1e30, np.float32)
    for i in range(total):
        expect[i % K] = max(expect[i % K], float((i * 37) % 91))
    np.testing.assert_allclose(got, expect)


def test_keyed_map_stateful_counter():
    """Stateful map: per-key monotonically increasing counter attached to each tuple —
    the reference fork's keyed MapGPU semantics (wf/map_gpu_node.hpp:216-222)."""
    total, K = 120, 4

    def f(t, st):
        new = st + 1
        return {"n": new}, new

    src = wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=total, num_keys=K)
    km = wf.KeyedMap(f, init_state_value=jnp.zeros((), jnp.int32), num_keys=K)
    outputs = []

    def cb(view):
        if view is None:
            return
        outputs.extend(zip(view["key"].tolist(), view["payload"]["n"].tolist()))

    wf.Pipeline(src, [km], wf.Sink(cb), batch_size=32).run()
    by_key = {}
    for k, n in outputs:
        by_key.setdefault(k, []).append(n)
    for k, ns in by_key.items():
        assert ns == list(range(1, len(ns) + 1)), f"key {k}: {ns[:10]}"
