"""Adaptive control plane (windflow_tpu/control/): deterministic fake-clock
controller-decision tests, the controller-on/off byte-identity regression on
mp-matrix workloads, the synthetic-overload bounded-backlog demonstration,
and the controller x fault-injection chaos interaction."""

import json
import os
import threading
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.batch import Batch, concat_batches, split_batch
from windflow_tpu.control import (AdmissionController, BackpressureGovernor,
                                  CapacityAutotuner, ControlConfig,
                                  PositionBucket, Rebatcher, TokenBucket,
                                  TuningCache, build_ladder)
from windflow_tpu.control import _state as control_state
from windflow_tpu.observability import MetricsRegistry
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.operators.win_patterns import Key_FFAT, Pane_Farm
from windflow_tpu.operators.win_seq import Win_Seq
from windflow_tpu.runtime.faults import FaultInjector, FaultPlan, FaultSpec
from windflow_tpu.runtime.threaded import ThreadedPipeline


@pytest.fixture(autouse=True)
def _fresh_counters():
    control_state.reset()
    yield
    control_state.reset()


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _mkbatch(n, start=0, ts=None):
    i = np.arange(start, start + n, dtype=np.int32)
    return Batch(key=jnp.asarray(i % 4), id=jnp.asarray(i),
                 ts=jnp.asarray(ts if ts is not None else i),
                 payload={"v": jnp.asarray(i, jnp.float32)},
                 valid=jnp.ones(n, bool))


# ---------------------------------------------------------------- primitives

def test_split_concat_roundtrip():
    b = _mkbatch(32)
    parts = split_batch(b, 8)
    assert len(parts) == 4 and all(p.capacity == 8 for p in parts)
    back = parts[0]
    for p in parts[1:]:
        back = concat_batches(back, p)
    for leaf_a, leaf_b in zip(jax.tree.leaves(b), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(leaf_a), np.asarray(leaf_b))
    with pytest.raises(ValueError):
        split_batch(b, 5)                    # 5 does not divide 32


def test_build_ladder_divisibility_and_bounds():
    assert build_ladder(64, up=2, down=2) == [16, 32, 64, 128, 256]
    # odd base: no down rungs (cannot slice exactly)
    assert build_ladder(40, up=1, down=3) == [10, 20, 40, 80]
    assert build_ladder(24, up=0, down=5, min_capacity=8) == [12, 24]
    assert 7 not in build_ladder(7, up=0, down=3)[:-1]


def test_rebatcher_up_down_and_drain():
    rb = Rebatcher(8)
    b0, b1, b2 = _mkbatch(8), _mkbatch(8, 8), _mkbatch(8, 16)
    assert rb.feed(b0) == [b0]               # target == base: passthrough
    rb.set_target(16)
    assert rb.feed(b1) == []                 # buffering toward 16
    out = rb.feed(b2)
    assert len(out) == 1 and out[0].capacity == 16
    np.testing.assert_array_equal(np.asarray(out[0].id), np.arange(8, 24))
    rb.set_target(4)
    out = rb.feed(_mkbatch(8, 24))
    assert [o.capacity for o in out] == [4, 4]
    rb.set_target(16)
    assert rb.feed(_mkbatch(8, 32)) == []
    tail = rb.drain()                        # EOS: partial buffer at base cap
    assert len(tail) == 1 and tail[0].capacity == 8
    with pytest.raises(ValueError):
        rb.set_target(12)                    # neither multiple nor divisor


# ------------------------------------------------------- admission (fake clock)

def test_token_bucket_fake_clock_shed_pattern():
    clk = FakeClock()
    adm = AdmissionController(TokenBucket(rate=10.0, burst=20.0, clock=clk),
                              "drop_newest")
    b = _mkbatch(10)
    decisions = []
    for _ in range(6):
        decisions.append(bool(adm.offer(b)))
        clk.advance(0.5)                     # +5 tokens per offer
    # burst 20: admit (10 left), +5 admit (5), +5 admit (0), +5 shed,
    # +5 admit (0), +5 shed — the exact refill arithmetic, no timing slack
    assert decisions == [True, True, True, False, True, False]
    assert adm.shed == 2 and adm.admitted == 4
    c = control_state.counters()
    assert c["shed_batches"] == 2 and c["shed_tuples"] == 20
    assert c["admitted_batches"] == 4


def test_position_bucket_is_deterministic():
    def pattern():
        adm = AdmissionController(PositionBucket(refill_per_batch=6, burst=10),
                                  "drop_newest")
        return [bool(adm.offer(_mkbatch(10))) for _ in range(8)]
    assert pattern() == pattern()
    assert pattern().count(False) > 0        # it does shed at this rate


def test_drop_oldest_ts_sheds_stale_holds_fresh():
    clk = FakeClock()
    adm = AdmissionController(TokenBucket(rate=0.0, burst=10.0, clock=clk),
                              "drop_oldest_ts", hold_max=2)
    b0, b1, b2, b3 = (_mkbatch(10, 100 * k) for k in range(4))
    assert adm.offer(b0) == [b0]             # burst covers the first
    assert adm.offer(b1) == []               # held
    assert adm.offer(b2) == []               # held (2 = hold_max)
    assert adm.offer(b3) == []               # overflow: b1 (oldest ts) shed
    assert adm.shed == 1
    held_ids = [int(np.asarray(b.id)[0]) for b, *_ in adm.held]
    assert held_ids == [200, 300]            # stale dropped, fresh kept
    drained = adm.drain()                    # EOS admits the bounded tail
    assert [int(np.asarray(b.id)[0]) for b in drained] == [200, 300]


def test_admission_state_roundtrip():
    adm = AdmissionController(PositionBucket(4, 12), "drop_newest")
    for k in range(5):
        adm.offer(_mkbatch(8, 8 * k))
    st = adm.state()
    adm2 = AdmissionController(PositionBucket(4, 12), "drop_newest")
    adm2.set_state(st)
    a = [bool(adm.offer(_mkbatch(8, 99))) for _ in range(6)]
    b = [bool(adm2.offer(_mkbatch(8, 99))) for _ in range(6)]
    assert a == b                            # replayed decisions identical


# ------------------------------------------------------ autotuner (fake clock)

RATES = {16: 1000.0, 32: 3000.0, 64: 5000.0, 128: 9000.0, 256: 7000.0}


def _drive_tuner(tuner, clk, rates, max_batches=500):
    """Feed on_batch with a synthetic per-rung service rate until converged."""
    for _ in range(max_batches):
        cap = tuner.capacity
        clk.advance(cap / rates[cap])        # one batch takes cap/rate secs
        tuner.on_batch(cap)
        if tuner.converged:
            return
    raise AssertionError("tuner did not converge")


def test_hill_climb_converges_to_best_rung(tmp_path):
    clk = FakeClock()
    cache = TuningCache(str(tmp_path / "tune.json"))
    tuner = CapacityAutotuner(sorted(RATES), start_capacity=64,
                              decide_every=4, settle_batches=1,
                              clock=clk, cache=cache, cache_key="k1")
    _drive_tuner(tuner, clk, RATES)
    assert tuner.capacity == 128             # the synthetic optimum
    best_rate = max(tuner.plan()["rates"].values())
    # the acceptance bound: converged rung within 10% of the best measured
    assert tuner.plan()["rates"][tuner.capacity] >= 0.9 * best_rate
    saved = json.load(open(cache.path))["k1"]
    assert saved["capacity"] == 128


def test_cache_warm_start_begins_at_optimum(tmp_path):
    cache = TuningCache(str(tmp_path / "tune.json"))
    cache.put("k1", {"capacity": 128, "tps": 9000.0})
    tuner = CapacityAutotuner(sorted(RATES), start_capacity=64,
                              cache=cache, cache_key="k1")
    # warm start: already converged AT the cached rung, zero exploration
    assert tuner.converged and tuner.capacity == 128
    assert tuner.on_batch(128) is None
    assert control_state.counters()["tuning_cache_hits"] == 1


def test_tuner_never_retraces_unknown_rungs():
    clk = FakeClock()
    tuner = CapacityAutotuner([32, 64, 128], start_capacity=32,
                              decide_every=2, settle_batches=0, clock=clk)
    seen = set()
    for _ in range(200):
        seen.add(tuner.capacity)
        clk.advance(1.0)
        tuner.on_batch(tuner.capacity)
        if tuner.converged:
            break
    assert seen <= {32, 64, 128}             # only ladder rungs ever actuated


# ------------------------------------------------------------------ governor

def test_governor_throttles_until_low_watermark():
    gov = BackpressureGovernor(high_watermark=0.5, low_watermark=0.25,
                               poll_s=0.001)
    depth = [8]
    gov.watch("edge", lambda: depth[0], capacity=8)   # hi=4, lo=2
    released = []

    def drainer():
        time.sleep(0.05)
        depth[0] = 2                         # drain to the low watermark
        released.append(gov.pause_event.is_set())

    t = threading.Thread(target=drainer)
    t.start()
    waited = gov.throttle()
    t.join()
    assert waited > 0 and gov.throttles == 1
    assert released == [True]                # pause hook was set while waiting
    assert not gov.pause_event.is_set()      # and cleared after release
    assert gov.throttle() == 0.0             # below hi: fast path
    c = control_state.counters()
    assert c["throttle_events"] == 1 and c["throttle_seconds"] > 0


def test_governor_stop_unblocks():
    gov = BackpressureGovernor(high_watermark=0.5, low_watermark=0.25)
    gov.watch("edge", lambda: 8, capacity=8)  # permanently over-high
    t = threading.Thread(target=gov.throttle)
    t.start()
    time.sleep(0.02)
    gov.stop()
    t.join(timeout=5)
    assert not t.is_alive()


def test_prefetch_pause_event_suspends_worker():
    pulled = [0]

    def it():
        for s in range(20):
            pulled[0] += 1
            yield {"v": np.full(4, s, np.float32)}

    from windflow_tpu.operators.source import GeneratorSource
    src = GeneratorSource(it, {"v": jax.ShapeDtypeStruct((), jnp.float32)})
    pause = threading.Event()
    pause.set()
    batches = src.batches_prefetched(4, depth=1, pause_event=pause)
    time.sleep(0.1)
    assert pulled[0] <= 1                    # paused before pulling ahead
    pause.clear()
    assert len(list(batches)) == 20 and pulled[0] == 20


# --------------------------------------------------- config / env resolution

def test_wf_control_env_resolution(monkeypatch):
    monkeypatch.delenv("WF_CONTROL", raising=False)
    assert ControlConfig.resolve(None) is None          # off by default
    assert ControlConfig.resolve(False) is None
    monkeypatch.setenv("WF_CONTROL", "0")
    assert ControlConfig.resolve(None) is None
    monkeypatch.setenv("WF_CONTROL", "1")
    assert ControlConfig.resolve(None) is not None
    monkeypatch.setenv("WF_CONTROL",
                       '{"admission": true, "rate_tps": 123.0, '
                       '"shed_policy": "drop_oldest_ts"}')
    cfg = ControlConfig.resolve(None)
    assert cfg.rate_tps == 123.0 and cfg.shed_policy == "drop_oldest_ts"
    with pytest.raises(ValueError):
        ControlConfig(shed_policy="nope")
    with pytest.raises(ValueError):
        ControlConfig(high_watermark=0.2, low_watermark=0.5)


def test_per_edge_queue_capacities_and_exposure():
    src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=64)
    tp = ThreadedPipeline(
        src, [[wf.Map(lambda t: {"v": t.v})], [wf.Map(lambda t: {"v": t.v})]],
        wf.Sink(lambda v: None), batch_size=16, pin=False,
        queue_capacity={"src->seg0": 2, "seg1->sink": 32})
    assert tp.edge_names == ["src->seg0", "seg0->seg1", "seg1->sink"]
    assert tp.edge_capacities == {"src->seg0": 2, "seg0->seg1": 8,
                                  "seg1->sink": 32}
    assert set(tp.queue_depths()) == set(tp.edge_names)
    # callable form + registry exposure of capacity alongside depth
    tp2 = ThreadedPipeline(
        src, [[wf.Map(lambda t: {"v": t.v})]], None, batch_size=16, pin=False,
        queue_capacity=lambda name, i: 4 + i)
    assert tp2.edge_capacities == {"src->seg0": 4, "seg0->sink": 5}
    reg = MetricsRegistry("t")
    for name, q in zip(tp2.edge_names, tp2.queues):
        reg.attach_queue_gauge(name, q.size,
                               capacity=tp2.edge_capacities[name])
    snap = reg.snapshot()
    assert snap["queue_capacity"] == tp2.edge_capacities
    assert "windflow_queue_capacity" in reg.to_prometheus(snap)


# ------------------------------------------- regression: byte-identical on/off

TOTAL, K = 240, 3

MP_CASES = {
    "win_seq_tb": lambda: [Win_Seq(lambda wid, it: it.sum("v"),
                                   WindowSpec(12, 6, win_type_t.TB),
                                   num_keys=K)],
    "key_ffat_cb": lambda: [Key_FFAT(lambda t: t.v, jnp.add,
                                     spec=WindowSpec(8, 2, win_type_t.CB),
                                     num_keys=K)],
    # Pane_Farm compiles two Win_Seq engines per ladder rung — the heaviest
    # case rides the slow tier; the two above keep the gather + FFAT engines
    # in tier-1
    "pf_chained": lambda: [wf.Map(lambda t: {"v": t.v * 2.0}),
                           Pane_Farm(lambda pid, it: it.sum("v"),
                                     lambda wid, it: it.sum(),
                                     WindowSpec(9, 3, win_type_t.CB),
                                     num_keys=K)],
}

MP_PARAMS = [pytest.param(c, marks=pytest.mark.slow) if c == "pf_chained"
             else c for c in sorted(MP_CASES)]


def _run_mp_case(make_ops, control):
    src = wf.Source(lambda i: {"v": ((i * 13) % 23).astype(jnp.float32)},
                    total=TOTAL, num_keys=K)
    results = []

    def cb(view):
        if view is None:
            return
        for k, w, r in zip(view["key"].tolist(), view["id"].tolist(),
                           np.asarray(view["payload"]).tolist()):
            results.append((k, w, round(float(r), 3)))

    wf.Pipeline(src, make_ops(), wf.Sink(cb), batch_size=16,
                control=control).run()
    return sorted(results)


@pytest.mark.parametrize("case", MP_PARAMS)
def test_controller_on_off_byte_identical(case):
    """The mp-matrix invariance property, under the control plane: the
    autotuner's mid-stream rung switches (forced by a tiny decide window)
    must not change a single result."""
    off = _run_mp_case(MP_CASES[case], control=False)
    on = _run_mp_case(MP_CASES[case],
                      ControlConfig(autotune=True, decide_every=2,
                                    settle_batches=0, admission=False,
                                    ladder_up=1, ladder_down=0))
    assert on == off and len(off) > 0
    # and the controller really did actuate (otherwise this test is vacuous)
    assert control_state.counters()["capacity_switches"] > 0


def test_control_off_is_default_and_inert(monkeypatch):
    monkeypatch.delenv("WF_CONTROL", raising=False)
    src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=64)
    p = wf.Pipeline(src, [wf.Map(lambda t: {"v": t.v})],
                    wf.Sink(lambda v: None), batch_size=16)
    assert p._control is None and p._ladder is None
    p.run()
    c = control_state.counters()
    assert not any(c.values())               # zero controller activity


# ----------------------------------------- overload: bounded vs pegged backlog

def _overload_run(control):
    """Fast source, slow sink (the synthetic overload); samples ring depth."""
    got, max_depth = [], [0]
    src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=50 * 32)
    tp = ThreadedPipeline(
        src, [[wf.Map(lambda t: {"v": t.v})]],
        wf.Sink(lambda v: (time.sleep(0.004),
                           got.extend(np.asarray(v["payload"]["v"]).tolist()))
                if v is not None else None),
        batch_size=32, pin=False, queue_capacity=8, control=control)
    stop = threading.Event()

    def watch():
        while not stop.is_set():
            max_depth[0] = max(max_depth[0], *tp.queue_depths().values())
            time.sleep(0.0005)

    w = threading.Thread(target=watch)
    w.start()
    tp.run()
    stop.set()
    w.join()
    return got, max_depth[0], tp


def test_overload_bounded_with_control_pegged_without():
    # control ON: admission sheds + governor keeps depth below the high
    # watermark (hi = 0.5 * 8 = 4)
    on_cfg = ControlConfig(autotune=False, backpressure=True,
                           high_watermark=0.5, low_watermark=0.25,
                           admission=True, rate_tps=3000.0, burst_tuples=64.0)
    got_on, depth_on, _tp = _overload_run(on_cfg)
    c = control_state.counters()
    # hi + 1: the governor admits one push after each release, and the
    # sampling probe can race a concurrent push/pop by one slot — bounded at
    # the watermark, not pegged at ring capacity, is the property
    assert depth_on <= 5, f"rings exceeded the high watermark: {depth_on}"
    assert c["shed_batches"] > 0 and c["throttle_events"] >= 0
    assert len(got_on) < 50 * 32             # load was genuinely shed
    # the evidence shows up in the snapshot AND the Prometheus exposition
    reg = MetricsRegistry("overload")
    snap = reg.snapshot()
    assert snap["control"]["counters"]["shed_batches"] > 0
    prom = reg.to_prometheus(snap)
    assert "windflow_control_shed_batches_total" in prom
    assert "windflow_control_throttle_events_total" in prom
    # control OFF: the ring pegs at/over the watermark (implicit blocking
    # backpressure only — the backlog signal nobody sees)
    control_state.reset()
    got_off, depth_off, _tp = _overload_run(False)
    assert len(got_off) == 50 * 32           # nothing shed...
    assert depth_off > 4                     # ...but the ring filled past hi
    assert not any(control_state.counters().values())


# --------------------------------------------- chaos: controller x fault plan

def _sup_control(batch):
    return ControlConfig(autotune=False, backpressure=False, admission=True,
                         refill_per_batch=0.75 * batch,
                         burst_tuples=2.0 * batch)


def _run_supervised(faults=None, batch=16):
    out = []
    src = wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
                    total=TOTAL, num_keys=4)
    op = Win_Seq(lambda wid, it: it.sum("v"),
                 WindowSpec(10, 10, win_type_t.TB), num_keys=4)
    wf.SupervisedPipeline(
        src, [op],
        wf.Sink(lambda v: v is not None and out.extend(
            zip(v["key"].tolist(), v["id"].tolist(),
                np.asarray(v["payload"]).round(3).tolist()))),
        batch_size=batch, checkpoint_every=3, max_restarts=8,
        backoff_base=0.001, backoff_cap=0.01, faults=faults,
        control=_sup_control(batch)).run()
    return sorted(out)


@pytest.mark.chaos
def test_supervised_admission_replays_shed_decisions_under_faults():
    """Controller active under FaultPlan injection: the deterministic
    positional bucket + snapshot/restore makes shed decisions part of the
    replayed stream — outputs match the fault-free controlled run exactly,
    and the run terminates (no backoff livelock)."""
    baseline = _run_supervised()
    t0 = time.monotonic()
    faulted = _run_supervised(FaultInjector(FaultPlan(
        [FaultSpec("source.next", p=0.06), FaultSpec("chain.step", p=0.10),
         FaultSpec("sink.consume", p=0.10)], seed=11)))
    assert faulted == baseline and len(baseline) > 0
    assert time.monotonic() - t0 < 120       # terminated, no livelock
    assert control_state.counters()["shed_batches"] > 0


@pytest.mark.chaos
def test_graph_supervised_admission_under_faults():
    from windflow_tpu.runtime.pipegraph import PipeGraph

    def run(faults=None):
        got = []
        g = PipeGraph("ctl", batch_size=12)
        a = g.add_source(wf.Source(lambda i: {"v": (i % 9).astype(jnp.float32)},
                                   total=144, num_keys=3, name="a"))
        b = g.add_source(wf.Source(lambda i: {"v": (i % 7).astype(jnp.float32)},
                                   total=72, num_keys=3, name="b"))
        (a.merge(b)
         .add(wf.Map(lambda t: {"v": t.v + 1.0}))
         .add_sink(wf.Sink(lambda v: v is not None and got.extend(
             zip(v["key"].tolist(), v["id"].tolist(),
                 np.asarray(v["payload"]["v"]).tolist())))))
        g.run_supervised(checkpoint_every=3, max_restarts=8,
                         backoff_base=0.001, backoff_cap=0.01, faults=faults,
                         control=_sup_control(12))
        return sorted(got)

    baseline = run()
    faulted = run(FaultInjector(FaultPlan(
        [FaultSpec("chain.step", p=0.08), FaultSpec("sink.consume", p=0.08)],
        seed=5)))
    assert faulted == baseline and len(baseline) > 0


def test_supervised_rejects_nondeterministic_admission():
    src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=32)
    with pytest.raises(ValueError, match="refill_per_batch"):
        wf.SupervisedPipeline(
            src, [wf.Map(lambda t: {"v": t.v})], batch_size=16,
            control=ControlConfig(admission=True, rate_tps=100.0))
    with pytest.raises(ValueError, match="drop_newest"):
        wf.SupervisedPipeline(
            src, [wf.Map(lambda t: {"v": t.v})], batch_size=16,
            control=ControlConfig(admission=True, refill_per_batch=8.0,
                                  shed_policy="drop_oldest_ts"))


def test_supervised_warm_starts_from_tuning_cache(tmp_path):
    """A plan persisted by a live Pipeline run is consumed read-only by the
    supervised driver: same chain signature -> start at the tuned capacity."""
    from windflow_tpu.control import (chain_signature, device_kind,
                                      payload_signature, tuning_key)
    cache_path = str(tmp_path / "tune.json")
    src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=64)
    ops = [wf.Map(lambda t: {"v": t.v * 2.0})]
    key = tuning_key(chain_signature(ops),
                     payload_signature(src.payload_spec()), device_kind())
    TuningCache(cache_path).put(key, {"capacity": 32, "tps": 1.0})
    sp = wf.SupervisedPipeline(
        src, ops, batch_size=16,
        control=ControlConfig(autotune=True, cache_path=cache_path))
    assert sp.batch_size == 32               # warm-started at the cached rung
    assert control_state.counters()["tuning_cache_hits"] == 1


# -------------------------------------------------- sweep: the adaptive row

def test_sweep_adaptive_rows_and_warm_start(tmp_path):
    from windflow_tpu.benchmarks.sweep import render_markdown, run_adaptive
    cache = str(tmp_path / "tune.json")
    rows = run_adaptive(batches=(128, 256), keyset=(4,),
                        names=("map_stateless",), steps=2, cache_path=cache)
    assert len(rows) == 1
    name, cap, keys, tps = rows[0]
    assert name.endswith("(adaptive)") and cap in (128, 256) and tps > 0
    # second run warm-starts at the cached rung (no re-exploration)
    control_state.reset()
    rows2 = run_adaptive(batches=(128, 256), keyset=(4,),
                         names=("map_stateless",), steps=2, cache_path=cache)
    assert rows2[0][1] == cap                # same rung, straight away
    assert control_state.counters()["tuning_cache_hits"] == 1
    assert control_state.counters()["capacity_switches"] == 0
    md = render_markdown(rows + rows2, "cpu-test")
    assert "(adaptive)" in md
