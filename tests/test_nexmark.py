"""Nexmark suite correctness: every query passes its dense oracle (exact
expected outputs, the ``test_ysb.py`` style) invariant under batch size; the
interval-join and session queries are byte-identical across the plain /
threaded / supervised drivers, under FaultPlan injection with mid-upsert
checkpoints (both supervised drivers), and under fused scan dispatch
(``WF_DISPATCH``); the join-table state replays byte-identically through a
restart that lands between an upsert's ingestion and its watermark
application."""

import numpy as np
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.nexmark import QUERIES, make_query, oracles
from windflow_tpu.operators.join import StreamTableJoin
from windflow_tpu.runtime.faults import FaultPlan, FaultSpec

TOTAL = 400


def pay(v, f):
    return np.asarray(v["payload"][f]).tolist()


def ids(v, f):
    return np.asarray(v[f]).tolist()


ROW_FNS = {
    "q1_currency": lambda v: list(zip(ids(v, "id"), pay(v, "auction"),
                                      pay(v, "euro"))),
    "q2_selection": lambda v: list(zip(ids(v, "id"), pay(v, "auction"),
                                       pay(v, "price"))),
    "q3_enrich_join": lambda v: list(zip(ids(v, "id"), pay(v, "auction"),
                                         pay(v, "category"),
                                         pay(v, "price"))),
    "q4_interval_join": lambda v: list(zip(pay(v, "auction"),
                                           pay(v, "open_ts"),
                                           pay(v, "bid_ts"),
                                           pay(v, "price"))),
    "q5_session": lambda v: list(zip(
        ids(v, "key"), ids(v, "id"), pay(v, "start"), pay(v, "end"),
        pay(v, "n"),
        [int(x) for x in np.asarray(v["payload"]["agg"]["bids"])],
        [int(x) for x in np.asarray(v["payload"]["agg"]["spend"])])),
    "q7_distinct": lambda v: list(zip(ids(v, "id"), pay(v, "auction"))),
}


def run_query(name, batch, driver="plain", **kw):
    src, ops = make_query(name, TOTAL)
    rows = []
    rowfn = ROW_FNS[name]

    def cb(view):
        if view is None:
            return
        rows.extend(rowfn(view))
    sink = wf.Sink(cb)
    if driver == "plain":
        wf.Pipeline(src, ops, sink, batch_size=batch, **kw).run()
    elif driver == "threaded":
        wf.ThreadedPipeline(src, [ops], sink, batch_size=batch, **kw).run()
    elif driver == "supervised":
        wf.SupervisedPipeline(src, ops, sink, batch_size=batch,
                              backoff_base=0.001, backoff_cap=0.01,
                              **kw).run()
    elif driver == "graph-supervised":
        g = wf.PipeGraph(batch_size=batch)
        mp = g.add_source(src)
        for op in ops:
            mp.add(op)
        mp.add_sink(sink)
        g.run_supervised(checkpoint_every=2, backoff_base=0.001,
                         backoff_cap=0.01, **kw)
    return rows


# --------------------------------------------------------- dense oracles

@pytest.mark.parametrize("batch", [32, 64, 100, TOTAL])
@pytest.mark.parametrize("name", ["q1_currency", "q2_selection",
                                  "q3_enrich_join", "q4_interval_join",
                                  "q5_session", "q7_distinct"])
def test_query_matches_dense_oracle(name, batch):
    got = sorted(run_query(name, batch))
    want = oracles.ORACLES[name](TOTAL)
    assert got == want


@pytest.mark.parametrize("batch", [64, 100])
def test_topn_matches_dense_oracle(batch):
    src, ops = make_query("q6_topn", TOTAL)
    final = {}

    def cb(view):
        if view is None:
            return
        for k, r, i, s in zip(view["key"].tolist(),
                              np.asarray(view["payload"]["rank"]).tolist(),
                              view["id"].tolist(),
                              np.asarray(view["payload"]["score"]).tolist()):
            final[(k, r)] = (i, s)
    wf.Pipeline(src, ops, wf.Sink(cb), batch_size=batch).run()
    got = sorted((k, r, i, s) for (k, r), (i, s) in final.items())
    assert got == oracles.q6_topn(TOTAL)


def test_every_registered_query_has_oracle_and_rowfn_coverage():
    assert set(oracles.ORACLES) == set(QUERIES)
    assert set(ROW_FNS) | {"q6_topn"} == set(QUERIES)


def test_queries_match_names_registry():
    from windflow_tpu.observability.names import NEXMARK_QUERIES
    assert QUERIES == NEXMARK_QUERIES


# -------------------------------------- cross-driver / chaos byte-identity

@pytest.mark.parametrize("name", ["q4_interval_join", "q5_session"])
def test_join_and_session_byte_identical_across_drivers(name):
    base = run_query(name, 50)
    assert run_query(name, 50, "threaded") == base
    assert run_query(name, 50, "supervised") == base
    assert run_query(name, 50, "graph-supervised") == base


@pytest.mark.chaos
@pytest.mark.parametrize("name", ["q4_interval_join", "q5_session",
                                  "q3_enrich_join"])
def test_join_session_byte_identical_under_faultplan(name):
    base = run_query(name, 50)
    plan = FaultPlan([FaultSpec("chain.step", at=[3, 5])], seed=3)
    got = run_query(name, 50, "supervised", checkpoint_every=2, faults=plan)
    assert got == base
    got_g = run_query(name, 50, "graph-supervised", faults=plan)
    assert got_g == base


@pytest.mark.chaos
def test_join_table_replay_with_mid_upsert_checkpoint():
    """A restart landing while upserts are still parked in the pending ring
    (delay > 0) must replay the join-table state byte-identically: the
    checkpoint carries the ring, the watermark, and the arrival-seq stamp."""
    def gen(i):
        is_def = (i % 4) == 0
        return {"side": jnp.where(is_def, 1, 0).astype(jnp.int32),
                "k": ((i // 4) % 8).astype(jnp.int32),
                "val": (i * 10).astype(jnp.int32)}
    mk = lambda: wf.Source(gen, total=160, num_keys=8,
                           key_fn=lambda i: (i // 4) % 8,
                           ts_fn=lambda i: i // 4)
    op = lambda: StreamTableJoin(
        lambda t: t.side == 1, lambda t: t.k, lambda t: {"jv": t.val},
        num_slots=16, delay=3, emit_misses=True)

    def run(faults=None):
        rows = []

        def cb(view):
            if view is None:
                return
            rows.extend(zip(view["id"].tolist(),
                            np.asarray(view["payload"]["jv"]).tolist()))
        wf.SupervisedPipeline(mk(), [op()], wf.Sink(cb), batch_size=16,
                              checkpoint_every=2, backoff_base=0.001,
                              backoff_cap=0.01, faults=faults).run()
        return rows

    base = run()
    # fault after the 3rd chain step: checkpoint at step 2 holds a pending
    # ring mid-flight (delay=3 keeps recent upserts unapplied)
    got = run(FaultPlan([FaultSpec("chain.step", at=[3])], seed=11))
    assert got == base


# ------------------------------------------------------ fused dispatch

@pytest.mark.parametrize("name", ["q3_enrich_join", "q4_interval_join",
                                  "q5_session"])
def test_join_and_session_byte_identical_under_wf_dispatch(name, monkeypatch):
    base = run_query(name, 50)
    assert run_query(name, 50, dispatch=4) == base
    monkeypatch.setenv("WF_DISPATCH", "1")
    monkeypatch.setenv("WF_DISPATCH_K", "3")
    assert run_query(name, 50) == base


# ------------------------------------------------------------- wiring

def test_sweep_run_nexmark_rows():
    from windflow_tpu.benchmarks.sweep import run_nexmark
    rows = run_nexmark(batches=(64,), steps=2)
    assert len(rows) == len(QUERIES)
    assert all(tps > 0 for _, _, _, tps in rows)
    names = {n for n, _, _, _ in rows}
    assert names == {f"nexmark:{q}" for q in QUERIES}


def test_validate_clean_on_every_query():
    from windflow_tpu.analysis import validate
    for name in QUERIES:
        src, ops = make_query(name, TOTAL)
        rep = validate(wf.Pipeline(src, ops, wf.Sink(lambda v: None),
                                   batch_size=64))
        assert rep.ok, f"{name}: {rep}"
