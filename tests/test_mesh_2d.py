"""2-D mesh execution (dp x key): batch capacity sharded over ``dp`` while
keyed state tables shard over ``key`` — the dp x ep layout. Oracle: identical
results to single-device; evidence: state table and batch live on different
mesh axes."""

import numpy as np
import jax
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.batch import Batch
from windflow_tpu.operators.win_patterns import Key_FFAT
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.parallel import make_mesh_2d, ShardedChain
from windflow_tpu.runtime.pipeline import CompiledChain


def _batches(total, C, K):
    out = []
    for s in range(0, total, C):
        n = min(C, total - s)
        ids = np.arange(s, s + C, dtype=np.int32)
        out.append(Batch(
            key=jnp.asarray(ids % K), id=jnp.asarray(ids), ts=jnp.asarray(ids),
            payload={"v": jnp.asarray((ids % 13).astype(np.float32))},
            valid=jnp.asarray(np.arange(C) < n)))
    return out


def _collect(outs):
    acc = []
    for o in outs:
        o = jax.tree.map(np.asarray, o)
        v = o.valid
        acc.extend(zip(o.key[v].tolist(), o.id[v].tolist(),
                       np.asarray(jax.tree.leaves(o.payload)[0])[v].tolist()))
    return sorted(acc)


def test_dp_x_key_mesh_matches_single_device():
    K = 16                       # multiple of the 4-way key axis
    spec = WindowSpec(20, 20, win_type_t.CB)
    batches = _batches(400, 80, K)

    def build():
        return CompiledChain(
            [Key_FFAT(lambda t: t.v, jnp.add, spec=spec, num_keys=K)],
            {"v": jax.ShapeDtypeStruct((), jnp.float32)}, batch_capacity=80)

    chain = build()
    single = _collect([chain.push(b) for b in batches] + chain.flush())

    mesh = make_mesh_2d((2, 4), axes=("dp", "key"))
    chain2 = build()
    sc = ShardedChain(chain2, mesh, axis="dp", key_axis="key")
    multi = _collect([sc.push(b) for b in batches] + sc.flush())
    assert single == multi and len(single) > 0

    # the key-state table is partitioned over the key axis (4-way), replicated
    # over dp; pick a [K,...] leaf and check its shard layout
    leaves = [l for l in jax.tree.leaves(chain2.states[0])
              if getattr(l, "ndim", 0) >= 1 and l.shape[0] == K]
    assert leaves, "no key-table state leaves found"
    shards = leaves[0].addressable_shards
    assert len(shards) == 8
    assert all(s.data.shape[0] == K // 4 for s in shards)   # key-axis 4-way
