"""The toggle-OFF program-identity gate (ISSUE 19 satellite): every
host-side observability/serving toggle must leave the compiled step program
*structurally identical* — not "results equal", the PROGRAM equal — across
the whole Nexmark query set.

One table-driven test replaces the per-PR ad-hoc HLO-text pins
(test_device_health/test_fleet/test_slo ``test_off_path_hlo_identical``):
each toggle row builds the same chain under its env set and asserts
``program_fingerprint`` equality against the no-env baseline.  The
fingerprint is the canonical structural hash of the traced jaxpr
(``analysis/progcheck.py``) — stable across processes, so these pins are
comparable between CI runs, not just within one.

``event_time`` is the one GEOMETRY-BINDING toggle (ON adds lateness
histograms to operator state, changing the program by design); its row
pins the OFF resolution under ``WF_MONITORING=1`` — the regression that
actually bites (monitoring on silently flipping event-time state in)."""

import pytest
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.analysis import progcheck as pc
from windflow_tpu.nexmark import queries as q
from windflow_tpu.observability import device_health as dh

#: every env var any toggle row touches — cleared for the baseline build
_TOGGLE_ENVS = ("WF_MONITORING", "WF_MONITORING_HEALTH",
                "WF_MONITORING_EVENT_TIME", "WF_SLO", "WF_TELEMETRY",
                "WF_REMEDIATION", "WF_SERVE", "WF_PROFILE")

#: toggle -> env set; ``health`` additionally activates a live
#: HealthLedger around build+trace (the ledger hooks chain tracing)
TOGGLES = {
    "monitoring": {"WF_MONITORING": "1"},
    "health": {"WF_MONITORING": "1", "WF_MONITORING_HEALTH": "1"},
    "event_time": {"WF_MONITORING": "1", "WF_MONITORING_EVENT_TIME": "0"},
    "slo": {"WF_MONITORING": "1", "WF_SLO": "1"},
    "telemetry": {"WF_MONITORING": "1",
                  "WF_TELEMETRY": "tcp://127.0.0.1:9"},
    "remediation": {"WF_MONITORING": "1", "WF_SLO": "1",
                    "WF_REMEDIATION": "1"},
    "serving": {"WF_MONITORING": "1", "WF_SERVE": "1"},
    "profile": {"WF_MONITORING": "1", "WF_SLO": "1", "WF_PROFILE": "1"},
}


def _fingerprint(query: str) -> str:
    """Build the query's chain UNDER THE CURRENT ENV (CompiledChain
    consults the monitoring envs at construction) and fingerprint its
    per-push step program."""
    src, ops = q.make_query(query, total=512)
    chain = pc._mk_chain(src, ops, 64)
    return pc.step_fingerprint(chain, 64)


@pytest.mark.parametrize("query", sorted(q.QUERIES))
def test_toggles_off_program_identical(query, monkeypatch):
    for env in _TOGGLE_ENVS:
        monkeypatch.delenv(env, raising=False)
    base = _fingerprint(query)
    for name, envs in TOGGLES.items():
        for env in _TOGGLE_ENVS:
            monkeypatch.delenv(env, raising=False)
        for k, v in envs.items():
            monkeypatch.setenv(k, v)
        if name == "health":
            # a LIVE ledger during build+trace: its trace hooks ride the
            # jit path, the abstract trace here must stay untouched either
            # way (the ledger-observes-jit pin lives in test_device_health)
            led = dh.HealthLedger(cost_analysis=False)
            dh.set_active(led)
            try:
                fp = _fingerprint(query)
            finally:
                dh.set_active(None)
        else:
            fp = _fingerprint(query)
        assert fp == base, (
            f"{query}: toggle {name!r} changed the compiled step program "
            f"(fingerprint {fp[:16]} != baseline {base[:16]}) — host-side "
            f"toggles must be byte-for-byte OFF the device path")


def test_event_time_on_changes_program(monkeypatch):
    """The counter-pin that keeps the gate honest: event_time ON is
    geometry-binding (lateness histograms enter operator state), so its
    fingerprint MUST differ — if it ever stops differing, the gate above
    is vacuous."""
    for env in _TOGGLE_ENVS:
        monkeypatch.delenv(env, raising=False)
    base = _fingerprint("q5_session")
    monkeypatch.setenv("WF_MONITORING", "1")
    monkeypatch.setenv("WF_MONITORING_EVENT_TIME", "1")
    assert _fingerprint("q5_session") != base


def test_scan_program_toggle_off_identical(monkeypatch):
    """The K-fused scan program rides the same gate: monitoring on must
    not perturb the fused dispatch path either."""
    for env in _TOGGLE_ENVS:
        monkeypatch.delenv(env, raising=False)

    def scan_fp():
        src, ops = q.make_query("q1_currency", total=512)
        chain = pc._mk_chain(src, ops, 64)
        return pc.program_fingerprint(pc.trace_scan(chain, 4, 64))

    base = scan_fp()
    monkeypatch.setenv("WF_MONITORING", "1")
    monkeypatch.setenv("WF_SLO", "1")
    assert scan_fp() == base
