"""Real 2-process multihost validation (VERDICT r03 item 7): localhost
coordinator, two OS processes, CPU backend, DCN×ICI mesh.

Un-quarantined by the shard-local supervision layer (ROADMAP item 1 /
ISSUE 13): each process now supervises its slice of a 4-shard
``ShardedSupervisor`` layout over the same logical stream — per-shard
recovery domains with a shard-kill drill, NO cross-process collectives —
so a real multi-process code path is exercised (and asserted against an
unsharded single-process oracle) even on jaxlib builds whose CPU backend
cannot run cross-process computations. The ``keyed_all_to_all`` collective
part still runs where the platform supports it (the driver reports
``COLLECTIVES-UNSUPPORTED`` otherwise — reported, not skipped); only a
platform that cannot even form the coordination service skips.

(The single-process fallback paths are covered by tests/test_multihost.py.)
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRIVER = os.path.join(REPO, "tests", "multihost_driver.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


#: stderr signatures of a platform where 2-process jax.distributed cannot
#: even initialize (no coordination service) — the only remaining skip;
#: cross-process COMPUTATION gaps are handled inside the driver now
#: (COLLECTIVES-UNSUPPORTED), because the shard-supervision part needs no
#: collectives at all.
_PLATFORM_SIGNATURES = (
    "DEADLINE_EXCEEDED",
    "failed to connect to all addresses",
    "coordination service",
)


def _platform_unusable(outs):
    """A platform-capability line to skip on — ONLY when EVERY failing
    process matches a signature. A real bug in one process cascades into a
    coordination failure in its peer (which DOES look platform-shaped), so
    one matching process must never be enough: any failing process without
    a signature means a genuine regression and the test still fails."""
    failing = [(rc, err) for rc, _out, err in outs if rc != 0]
    if not failing:
        return None
    first = None
    for _rc, err in failing:
        line = next((ln.strip() for sig in _PLATFORM_SIGNATURES
                     for ln in err.splitlines() if sig in ln), None)
        if line is None:
            return None                   # a non-platform failure: real bug
        first = first or line
    return first


def _shard_oracle():
    """The unsharded single-process oracle of the driver's part-1 workload
    (same source/window/geometry): count + the driver's digest."""
    import jax.numpy as jnp
    import windflow_tpu as wf
    from windflow_tpu.basic import win_type_t
    from windflow_tpu.operators.window import WindowSpec
    from windflow_tpu.runtime.supervisor import SupervisedPipeline
    got = []

    def cb(view):
        if view is None:
            return
        got.extend(zip(view["key"].tolist(), view["id"].tolist(),
                       np.asarray(view["payload"]).tolist()))
    SupervisedPipeline(
        wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
                  total=240, num_keys=8),
        [wf.Win_Seq(lambda wid, it: it.sum("v"),
                    WindowSpec(10, 10, win_type_t.TB), num_keys=8)],
        wf.Sink(cb), batch_size=30, checkpoint_every=2).run()
    digest = sum((k + 1) * 1_000_003 + (i + 1) * 31 + int(v * 7)
                 for k, i, v in got) % (1 << 31)
    return len(got), digest


def test_two_process_shard_supervision_and_collectives():
    coordinator = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PYTHONPATH")}
    procs = [subprocess.Popen(
                 [sys.executable, DRIVER, coordinator, "2", str(i)],
                 cwd=REPO, env=env, stdout=subprocess.PIPE,
                 stderr=subprocess.PIPE, text=True)
             for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=540)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    unusable = _platform_unusable(outs)
    if unusable is not None:
        pytest.skip(f"multihost 2-proc cannot form a coordination service "
                    f"on this platform: {unusable!r} (non-platform failures "
                    f"still fail this test)")
    for rc, out, err in outs:
        assert rc == 0, f"driver failed (rc={rc}):\n{err[-3000:]}"
        assert "SHARD-OK" in out, out

    # -- part 1 (always): shard-local supervision across the boundary -----
    # each process supervised its own shard slice with a shard-kill drill;
    # the union of both processes' result multisets must equal the
    # unsharded single-process oracle — no key lost, none duplicated
    counts, digests, ranges = [], [], []
    for _rc, out, _err in outs:
        parts = out.split("SHARD-OK ")[1].split()
        counts.append(int(parts[0]))
        digests.append(int(parts[1]))
        ranges.append(parts[2])
        assert "restarts=1" in out, out   # the kill drill recovered locally
    assert sorted(ranges) == ["range=0:2", "range=2:4"], ranges
    oracle_n, oracle_digest = _shard_oracle()
    assert sum(counts) == oracle_n, (counts, oracle_n)
    assert sum(digests) % (1 << 31) == oracle_digest, (digests,
                                                       oracle_digest)

    # -- part 2 (platform-dependent): collectives over DCN -----------------
    if any("COLLECTIVES-UNSUPPORTED" in out for _rc, out, _err in outs):
        # reported, NOT skipped: the multi-process path was exercised above;
        # this platform's CPU backend simply cannot run cross-process
        # computations (the old quarantine signature, now contained)
        return
    for rc, out, err in outs:
        assert "MULTIHOST-OK" in out, out
        assert "LOSSLESS-OK" in out, out
    # both processes together received all 64 rows x 4 dp replicas; each
    # process reports its local share
    counts = [int(out.split("MULTIHOST-OK ")[1].split()[0])
              for _, out, _ in outs]
    assert sum(counts) == 64 * 4, counts
    # lossless exchange under total key skew: every process computes the same
    # GLOBAL delivered count (16, each row once), over more than one round
    # (the blocking-bounded-queue path), across the real process boundary
    lcounts = [int(out.split("LOSSLESS-OK ")[1].split()[0])
               for _, out, _ in outs]
    rounds = [int(out.split("rounds=")[1].split()[0]) for _, out, _ in outs]
    assert lcounts == [16, 16], lcounts
    assert all(r > 1 for r in rounds), rounds
