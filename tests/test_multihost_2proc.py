"""Real 2-process multihost validation (VERDICT r03 item 7): localhost
coordinator, two OS processes, CPU backend, DCN×ICI mesh, keyed_all_to_all
ACROSS the process boundary. Green without a TPU.

(The single-process fallback paths are covered by tests/test_multihost.py; this
file is the one that makes the DCN axis more than prose.)
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRIVER = os.path.join(REPO, "tests", "multihost_driver.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_keyed_all_to_all():
    coordinator = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PYTHONPATH")}
    procs = [subprocess.Popen(
                 [sys.executable, DRIVER, coordinator, "2", str(i)],
                 cwd=REPO, env=env, stdout=subprocess.PIPE,
                 stderr=subprocess.PIPE, text=True)
             for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=540)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"driver failed (rc={rc}):\n{err[-3000:]}"
        assert "MULTIHOST-OK" in out, out
        assert "LOSSLESS-OK" in out, out
    # both processes together received all 64 rows x 4 dp replicas; each
    # process reports its local share
    counts = [int(out.split("MULTIHOST-OK ")[1].split()[0])
              for _, out, _ in outs]
    assert sum(counts) == 64 * 4, counts
    # lossless exchange under total key skew: every process computes the same
    # GLOBAL delivered count (16, each row once), over more than one round
    # (the blocking-bounded-queue path), across the real process boundary
    lcounts = [int(out.split("LOSSLESS-OK ")[1].split()[0])
               for _, out, _ in outs]
    rounds = [int(out.split("rounds=")[1].split()[0]) for _, out, _ in outs]
    assert lcounts == [16, 16], lcounts
    assert all(r > 1 for r in rounds), rounds
