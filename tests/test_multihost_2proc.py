"""Real 2-process multihost validation (VERDICT r03 item 7): localhost
coordinator, two OS processes, CPU backend, DCN×ICI mesh, keyed_all_to_all
ACROSS the process boundary. Green without a TPU.

(The single-process fallback paths are covered by tests/test_multihost.py; this
file is the one that makes the DCN axis more than prose.)
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRIVER = os.path.join(REPO, "tests", "multihost_driver.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


#: stderr signatures of a PLATFORM that cannot run 2-process collectives at
#: all (vs a real regression in our code): jaxlib builds where cross-process
#: computations are unimplemented on the CPU backend, or a coordination
#: service that cannot form. Matching failures SKIP with the reason —
#: keeping tier-1 green until ROADMAP item 1 (elastic multi-host scale-out)
#: lands the real multi-host story; anything else still FAILS.
_PLATFORM_SIGNATURES = (
    "Multiprocess computations aren't implemented",
    "DEADLINE_EXCEEDED",
    "failed to connect to all addresses",
    "coordination service",
)


def _platform_unusable(outs):
    """A platform-capability line to skip on — ONLY when EVERY failing
    process matches a signature. A real bug in one process cascades into a
    coordination failure in its peer (which DOES look platform-shaped), so
    one matching process must never be enough: any failing process without
    a signature means a genuine regression and the test still fails."""
    failing = [(rc, err) for rc, _out, err in outs if rc != 0]
    if not failing:
        return None
    first = None
    for _rc, err in failing:
        line = next((ln.strip() for sig in _PLATFORM_SIGNATURES
                     for ln in err.splitlines() if sig in ln), None)
        if line is None:
            return None                   # a non-platform failure: real bug
        first = first or line
    return first


def test_two_process_keyed_all_to_all():
    coordinator = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PYTHONPATH")}
    procs = [subprocess.Popen(
                 [sys.executable, DRIVER, coordinator, "2", str(i)],
                 cwd=REPO, env=env, stdout=subprocess.PIPE,
                 stderr=subprocess.PIPE, text=True)
             for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=540)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    unusable = _platform_unusable(outs)
    if unusable is not None:
        pytest.skip(f"multihost 2-proc unusable on this platform: "
                    f"{unusable!r} (quarantined until ROADMAP item 1 lands "
                    f"shard-local multi-host recovery; non-platform "
                    f"failures still fail this test)")
    for rc, out, err in outs:
        assert rc == 0, f"driver failed (rc={rc}):\n{err[-3000:]}"
        assert "MULTIHOST-OK" in out, out
        assert "LOSSLESS-OK" in out, out
    # both processes together received all 64 rows x 4 dp replicas; each
    # process reports its local share
    counts = [int(out.split("MULTIHOST-OK ")[1].split()[0])
              for _, out, _ in outs]
    assert sum(counts) == 64 * 4, counts
    # lossless exchange under total key skew: every process computes the same
    # GLOBAL delivered count (16, each row once), over more than one round
    # (the blocking-bounded-queue path), across the real process boundary
    lcounts = [int(out.split("LOSSLESS-OK ")[1].split()[0])
               for _, out, _ in outs]
    rounds = [int(out.split("rounds=")[1].split()[0]) for _, out, _ in outs]
    assert lcounts == [16, 16], lcounts
    assert all(r > 1 for r in rounds), rounds
