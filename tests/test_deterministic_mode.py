"""Mode.DETERMINISTIC (reference: Ordering_Node before each replica + broadcast
renumbering, wf/pipegraph.hpp:1197-1248): merged streams produce identical windowed
results regardless of merge operand order, batch size, or driver (push vs threaded)."""

import numpy as np
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import Mode, win_type_t
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.runtime.pipegraph import PipeGraph

K = 2


def run(batch_size, swap=False, threaded=False):
    # two sources covering interleaved ts ranges (even/odd ticks)
    g = PipeGraph("det", batch_size=batch_size, mode=Mode.DETERMINISTIC)
    sa = wf.Source(lambda i: {"v": (i % 5).astype(jnp.float32)}, total=120,
                   num_keys=K, ts_fn=lambda i: 2 * i, name="even_ts")
    sb = wf.Source(lambda i: {"v": (i % 7).astype(jnp.float32)}, total=120,
                   num_keys=K, ts_fn=lambda i: 2 * i + 1, name="odd_ts")
    pa, pb = g.add_source(sa), g.add_source(sb)
    m = pb.merge(pa) if swap else pa.merge(pb)
    out = []

    def cb(view):
        if view is None:
            return
        out.extend((int(k), int(w), round(float(r), 4)) for k, w, r in
                   zip(view["key"].tolist(), view["id"].tolist(),
                       np.asarray(view["payload"]).tolist()))

    m.add(wf.Win_Seq(lambda wid, it: it.sum("v"),
                     WindowSpec(30, 30, win_type_t.TB, delay=60),
                     num_keys=K)).add_sink(wf.Sink(cb))
    g.run(threaded=threaded)
    return sorted(out)


def oracle():
    want = {}
    for i in range(120):
        for ts, v in ((2 * i, i % 5), (2 * i + 1, i % 7)):
            k = i % K
            w = ts // 30
            want[(k, w)] = round(want.get((k, w), 0.0) + v, 4)
    return sorted((k, w, r) for (k, w), r in want.items())


@pytest.mark.parametrize("batch_size", [32, 77, 240])
def test_deterministic_merge_matches_oracle(batch_size):
    assert run(batch_size) == oracle()


def test_deterministic_invariant_under_operand_order_and_driver():
    base = run(60)
    assert run(60, swap=True) == base
    assert run(60, threaded=True) == base
    assert run(90, swap=True, threaded=True) == base
