"""The full user-function signature surface, deduced — one test per accepted
flavour per operator, plus rejection messages carrying the catalogue
(reference: wf/meta.hpp:49-877 static dispatch, /root/reference/API)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.meta import SignatureError, classify_source_flavour, \
    classify_window_flavour
from windflow_tpu.operators.window import WindowSpec


def run_pipeline(src, ops, batch_size=32):
    out = []

    def cb(view):
        if view is None:
            return
        v = view["payload"]
        leaf = v["v"] if isinstance(v, dict) else v
        out.extend(np.asarray(leaf).tolist())

    wf.Pipeline(src, ops, wf.Sink(cb), batch_size=batch_size).run()
    return out


def _src(total=96):
    return wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=total,
                     num_keys=2)


# ---- MAP: in-place vs non-in-place (wf/map.hpp:64-74) --------------------------

def test_map_non_in_place():
    got = run_pipeline(_src(), [wf.Map(lambda t: {"v": t.v * 2})])
    assert got == [2.0 * i for i in range(96)]


def test_map_in_place():
    def f(t):
        t.v = t.v * 2          # void(tuple_t&): mutate, return nothing
    got = run_pipeline(_src(), [wf.Map(f)])
    assert got == [2.0 * i for i in range(96)]


def test_map_in_place_new_field():
    def f(t):
        t.w = t.v + 1          # in-place maps may add payload fields
    src = _src(32)
    p = wf.Pipeline(src, [wf.Map(f)], batch_size=32)
    outs = p.chain.push(next(iter(src.batches(32))))
    assert set(outs.payload.keys()) == {"v", "w"}


def test_map_control_fields_read_only_in_place():
    def f(t):
        t.key = t.key + 1
    with pytest.raises(Exception, match="read-only"):
        run_pipeline(_src(32), [wf.Map(f)])


# ---- FILTER: predicate vs optional (wf/filter.hpp:63-76) -----------------------

def test_filter_predicate():
    got = run_pipeline(_src(), [wf.Filter(lambda t: t.v % 2 == 0)])
    assert got == [float(i) for i in range(0, 96, 2)]


def test_filter_optional_transforming():
    # std::optional<result_t>(const tuple_t&): transform + keep flag in one fn
    got = run_pipeline(_src(), [wf.Filter(lambda t: ({"v": t.v * 10},
                                                     t.v % 3 == 0))])
    assert got == [10.0 * i for i in range(0, 96, 3)]


def test_filter_bad_tuple_rejected():
    with pytest.raises(SignatureError, match="FILTER"):
        run_pipeline(_src(32), [wf.Filter(lambda t: (t.v, t.v, t.v))])


# ---- SOURCE: itemized vs loop (wf/meta.hpp:49-88) ------------------------------

def test_source_itemized_flavour():
    assert classify_source_flavour(lambda i: {"v": i}) == (False, False)
    assert classify_source_flavour(lambda i, ctx: {"v": i}) == (False, True)


def test_source_loop_flavour():
    def f(i, shipper):
        shipper.push({"v": i.astype(jnp.float32)})
        shipper.push({"v": (i + 100).astype(jnp.float32)}, when=i % 2 == 0)
    src = wf.Source(f, total=8, max_fanout=2)
    got = sorted(run_pipeline(src, [wf.Map(lambda t: {"v": t.v})], batch_size=8))
    want = sorted([float(i) for i in range(8)] +
                  [float(i + 100) for i in range(0, 8, 2)])
    assert got == want


def test_source_bad_signature_rejected():
    # 3 positional params whose 2nd is not a shipper: matches no flavour
    with pytest.raises(SignatureError, match="SOURCE"):
        wf.Source(lambda i, extra_thing, more: {"v": i}, total=8)


def test_window_rich_flavours_run():
    spec = WindowSpec(8, 8, win_type_t.CB)
    seen = []

    def rich_noninc(wid, it, ctx):
        seen.append(ctx)
        return it.sum("v")

    got = run_pipeline(_src(), [wf.Win_Seq(rich_noninc, spec, num_keys=2)])
    assert len(got) == 12 and seen and seen[0].getParallelism() == 1

    def rich_inc(wid, t, acc, ctx):
        return acc + t.v

    inc = run_pipeline(_src(), [wf.Win_Seq(rich_inc, spec,
                                           init_acc=jnp.float32(0), num_keys=2)])
    assert sorted(inc) == sorted(got)


# ---- WINDOW: non-incremental vs incremental deduced ----------------------------

def test_window_flavour_classifier():
    assert classify_window_flavour(lambda wid, it: it.sum()) == (False, False)
    assert classify_window_flavour(lambda wid, it, ctx: it.sum()) == (False, True)
    assert classify_window_flavour(lambda wid, t, acc: acc + t.v) == (True, False)
    with pytest.raises(SignatureError, match="WIN_FARM|KEY_FARM"):
        classify_window_flavour(lambda a, b, c, d, e: None)


def test_win_seq_deduces_incremental():
    spec = WindowSpec(8, 8, win_type_t.CB)
    inc = wf.Win_Seq(lambda wid, t, acc: acc + t.v, spec, init_acc=jnp.float32(0),
                     num_keys=2)
    noninc = wf.Win_Seq(lambda wid, it: it.sum("v"), spec, num_keys=2)
    assert inc.incremental and not noninc.incremental
    a = run_pipeline(_src(), [inc])
    b = run_pipeline(_src(), [noninc])
    assert sorted(a) == sorted(b) and len(a) == 12


def test_win_seq_incremental_requires_init_acc():
    with pytest.raises(ValueError, match="init_acc"):
        wf.Win_Seq(lambda wid, t, acc: acc + t.v,
                   WindowSpec(8, 8, win_type_t.CB), num_keys=2)


def test_flavour_warning_on_unrecognized_context_name():
    from windflow_tpu.meta import FlavourWarning, classify_map
    with pytest.warns(FlavourWarning, match="RuntimeContext"):
        assert classify_map(lambda t, environment: t) is True


def test_flavour_warning_on_ambiguous_source_second_param():
    from windflow_tpu.meta import FlavourWarning, classify_source_flavour
    with pytest.warns(FlavourWarning, match="LOOP source"):
        assert classify_source_flavour(lambda i, sender: None) == (False, True)
    # recognized names stay silent
    import warnings as w
    with w.catch_warnings():
        w.simplefilter("error")
        assert classify_source_flavour(lambda i, shipper: None) == (True, False)
        assert classify_source_flavour(lambda i, ctx: i) == (False, True)


def test_flavour_warning_on_contextish_window_param():
    from windflow_tpu.meta import FlavourWarning, classify_window_flavour
    with pytest.warns(FlavourWarning, match="INCREMENTAL"):
        assert classify_window_flavour(
            lambda wid, t, my_ctx: t) == (True, False)
