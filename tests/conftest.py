"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding correctness is validated on
``xla_force_host_platform_device_count=8`` CPU devices (same XLA partitioner as TPU).

The session environment pins JAX_PLATFORMS to the single real TPU chip and a
sitecustomize pre-imports jax, so plain env manipulation is too late — instead force
the platform through jax.config before any backend is initialized.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) >= 8, "tests need the 8-device virtual CPU mesh"


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 gate "
        "(-m 'not slow')")
    config.addinivalue_line(
        "markers", "chaos: seeded fault-injection tests "
        "(runtime/faults.py harness); fast ones stay in tier-1")
