"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding correctness is validated on
``xla_force_host_platform_device_count=8`` CPU devices (same XLA partitioner as TPU).
Must run before the first ``import jax`` in any test module.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
