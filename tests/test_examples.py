"""Every example under examples/ must run green (CPU backend, subprocess) —
they are the user-facing counterpart of the reference's src/ test programs
and each self-checks against an oracle."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(f for f in os.listdir(os.path.join(REPO, "examples"))
                  if f.endswith(".py") and not f.startswith("_"))


@pytest.mark.parametrize("name", EXAMPLES)
def test_example_runs(name):
    env = dict(os.environ, WF_CPU="1")
    proc = subprocess.run([sys.executable, os.path.join(REPO, "examples", name)],
                          capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, f"{name} failed:\n{proc.stdout}\n{proc.stderr}"
    assert "OK" in proc.stdout
