"""Sink(async_depth=N): overlapped D2H result delivery — same callbacks, same
order, EOS drains; plus Ordering_Node pow-2 padding keeps release semantics."""

import numpy as np
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.basic import ordering_mode_t
from windflow_tpu.batch import Batch, CTRL_DTYPE
from windflow_tpu.parallel.ordering import Ordering_Node


def _run(async_depth):
    src = wf.Source(lambda i: {"v": (i % 9).astype(jnp.float32)}, total=200,
                    num_keys=2)
    got = []
    eos = []

    def cb(view):
        if view is None:
            eos.append(True)
            return
        got.extend(view["payload"]["v"].tolist())

    wf.Pipeline(src, [wf.Map(lambda t: {"v": t.v * 3})],
                wf.Sink(cb, async_depth=async_depth), batch_size=32).run()
    assert eos == [True]
    return got


def test_async_sink_matches_sync_in_order():
    assert _run(0) == _run(3)


def test_ordering_node_odd_capacity_padding():
    node = Ordering_Node(2, ordering_mode_t.TS)

    def mk(ids):
        ids = np.asarray(ids, np.int32)
        return Batch(key=jnp.zeros(len(ids), CTRL_DTYPE), id=jnp.asarray(ids),
                     ts=jnp.asarray(ids), payload={"v": jnp.asarray(ids, jnp.float32)},
                     valid=jnp.ones(len(ids), bool))

    out = []
    # per-channel ts are monotone across pushes (FIFO channels — the reference's
    # per-channel maxs[] assumption); capacities are odd and growing -> padded
    for ch, ids in ((0, [3, 1, 7]), (1, [2, 5]), (0, [9, 11, 13, 15, 17]),
                    (1, [6, 8, 10])):
        r = node.push(ch, mk(ids))
        if r is not None:
            out.extend(np.asarray(r.id)[np.asarray(r.valid)].tolist())
    r = node.flush()
    if r is not None:
        out.extend(np.asarray(r.id)[np.asarray(r.valid)].tolist())
    assert out == sorted(out)
    assert sorted(out) == [1, 2, 3, 5, 6, 7, 8, 9, 10, 11, 13, 15, 17]
