"""Event-time observability: telemetry-on byte-identity across all four
drivers (plain / threaded / supervised / graph-supervised, under FaultPlan
restarts and fused ``WF_DISPATCH``), the watermark/occupancy/lateness
snapshot + Prometheus + topology surfaces, ``recommend_delay`` driving a
skewed stream's OLD drops to zero end-to-end through ``wf_state.py``, the
fused-dispatch trace apportionment, and the ``wf_state.py`` 0/2 exit
contract without JAX."""

import json
import os
import subprocess
import sys

import numpy as np
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.nexmark import make_query
from windflow_tpu.observability import MonitoringConfig, event_time as et
from windflow_tpu.runtime.faults import FaultPlan, FaultSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WF_STATE = os.path.join(REPO, "scripts", "wf_state.py")

TOTAL = 300
I32 = jnp.int32


def run_query(name, driver="plain", monitoring=False, **kw):
    src, ops = make_query(name, TOTAL)
    rows = []

    def cb(view):
        if view is None:
            return
        rows.append((np.asarray(view["key"]).tolist(),
                     np.asarray(view["id"]).tolist(),
                     np.asarray(view["ts"]).tolist()))
    sink = wf.Sink(cb)
    if driver == "plain":
        wf.Pipeline(src, ops, sink, batch_size=64, monitoring=monitoring,
                    **kw).run()
    elif driver == "threaded":
        # ThreadedPipeline has no monitoring= kwarg: env-driven (the caller
        # monkeypatches WF_MONITORING/WF_MONITORING_EVENT_TIME)
        wf.ThreadedPipeline(src, [ops], sink, batch_size=64, **kw).run()
    elif driver == "supervised":
        wf.SupervisedPipeline(src, ops, sink, batch_size=64,
                              checkpoint_every=2, backoff_base=0.001,
                              backoff_cap=0.01, **kw).run()
    elif driver == "graph-supervised":
        g = wf.PipeGraph(batch_size=64, monitoring=monitoring)
        mp = g.add_source(src)
        for op in ops:
            mp.add(op)
        mp.add_sink(sink)
        g.run_supervised(checkpoint_every=2, backoff_base=0.001,
                         backoff_cap=0.01, **kw)
    return rows


def _cfg(tmp_path, sub="mon"):
    return MonitoringConfig(out_dir=str(tmp_path / sub), event_time=True,
                            interval_s=30.0)


def _snapshot(tmp_path, sub="mon"):
    with open(tmp_path / sub / "snapshot.json") as f:
        return json.load(f)


# ------------------------------------------------- bucket math / device unit

def test_bucket_math_host_device_agree():
    import jax
    vals = [0, 1, 2, 3, 4, 7, 8, 100, 1023, 1024, (1 << 30) + 5]
    wm = 1 << 30
    ts = jnp.asarray([wm - v for v in vals], I32)
    hist = et.lateness_update(et.lateness_init(), wm, ts,
                              jnp.ones((len(vals),), jnp.bool_))
    counts = np.asarray(jax.device_get(hist))
    want = np.zeros(et.NB, np.int64)
    for v in vals:
        want[et.bucket_of(v)] += 1
    assert counts.tolist() == want.tolist()


def test_lateness_update_respects_mask():
    hist = et.lateness_update(et.lateness_init(), 10,
                              jnp.asarray([0, 5, 10], I32),
                              jnp.asarray([False, True, False]))
    counts = np.asarray(hist)
    assert counts.sum() == 1 and counts[et.bucket_of(5)] == 1


def test_recommend_delay_quantiles():
    counts = [0] * et.NB
    counts[0] = 90                       # 90 on-time
    counts[3] = 9                        # 9 in [4, 7]
    counts[5] = 1                        # 1 in [16, 31]
    assert et.recommend_delay(counts, 0.50) == 0
    assert et.recommend_delay(counts, 0.99) == 7
    assert et.recommend_delay(counts, 1.0) == 31
    assert et.recommend_delay([0] * et.NB, 0.99) == 0
    s = et.summarize(counts)
    assert s["total"] == 100 and s["p99"] == 7 and s["max"] == 31
    assert s["recommend_delay_p99"] == 7


def test_bucket_upper_covers_bucket():
    for v in (0, 1, 2, 3, 8, 100, 12345):
        assert et.bucket_upper(et.bucket_of(v)) >= v


# ------------------------------------------ telemetry-on byte-identity

@pytest.mark.parametrize("name", ["q3_enrich_join", "q4_interval_join",
                                  "q5_session"])
def test_event_time_on_is_byte_identical_plain(name, tmp_path):
    base = run_query(name)
    assert run_query(name, monitoring=_cfg(tmp_path)) == base


def test_event_time_on_byte_identical_across_all_four_drivers(
        tmp_path, monkeypatch):
    name = "q5_session"
    base = run_query(name)
    assert run_query(name, monitoring=_cfg(tmp_path, "plain")) == base
    assert run_query(name, "graph-supervised",
                     monitoring=_cfg(tmp_path, "graph")) == base
    # threaded + supervised resolve the toggle from the env
    monkeypatch.setenv("WF_MONITORING", str(tmp_path / "env"))
    monkeypatch.setenv("WF_MONITORING_EVENT_TIME", "1")
    assert run_query(name, "threaded") == base
    assert run_query(name, "supervised") == base


@pytest.mark.chaos
@pytest.mark.parametrize("name", ["q4_interval_join", "q5_session"])
def test_event_time_on_byte_identical_under_faultplan(name, tmp_path,
                                                      monkeypatch):
    base = run_query(name)
    plan = FaultPlan([FaultSpec("chain.step", at=[3, 5])], seed=7)
    monkeypatch.setenv("WF_MONITORING", str(tmp_path / "sup"))
    monkeypatch.setenv("WF_MONITORING_EVENT_TIME", "1")
    assert run_query(name, "supervised", faults=plan) == base
    monkeypatch.delenv("WF_MONITORING")
    monkeypatch.delenv("WF_MONITORING_EVENT_TIME")
    assert run_query(name, "graph-supervised",
                     monitoring=_cfg(tmp_path, "graph"),
                     faults=plan) == base


def test_event_time_on_byte_identical_under_wf_dispatch(tmp_path):
    name = "q3_enrich_join"
    base = run_query(name)
    assert run_query(name, monitoring=_cfg(tmp_path), dispatch=4) == base


# -------------------------------------------------- snapshot surfaces

#: stateful event-time operators per query -> section keys the snapshot
#: must carry (the watermark/occupancy/lateness acceptance surface)
_SECTION_KEYS = {
    "q3_enrich_join": {"watermark_ts", "occupancy_pct", "pending_depth",
                       "lateness"},
    "q4_interval_join": {"watermark_ts", "l_fill_pct", "r_fill_pct",
                         "evict_frontier_l_ts", "lateness"},
    "q5_session": {"watermark_ts", "open_sessions", "occupancy_pct",
                   "lateness"},
    "q6_topn": {"occupancy_pct", "topn_evictions"},
    "q7_distinct": {"watermark_ts", "occupancy_pct", "pending_depth"},
}


@pytest.mark.parametrize("name", sorted(_SECTION_KEYS))
def test_every_stateful_query_snapshot_carries_event_time_sections(
        name, tmp_path):
    run_query(name, monitoring=_cfg(tmp_path))
    snap = _snapshot(tmp_path)
    secs = {r["name"]: r["event_time"] for r in snap["operators"]
            if "event_time" in r}
    assert secs, f"{name}: no event_time sections in snapshot"
    merged = set()
    for sec in secs.values():
        merged |= set(sec)
    missing = _SECTION_KEYS[name] - merged
    assert not missing, f"{name}: missing {missing} in {merged}"
    # graph-level frontier whenever any op carries a watermark
    if any("watermark_ts" in sec for sec in secs.values()):
        assert "min_watermark_ts" in snap.get("event_time", {})


def test_stage_counters_in_rows_and_prometheus(tmp_path):
    run_query("q5_session", monitoring=_cfg(tmp_path))
    snap = _snapshot(tmp_path)
    row = [r for r in snap["operators"]
           if r["name"] == "nexmark_session"][0]
    assert row["counters"]["sessions_closed"] > 0
    with open(tmp_path / "mon" / "metrics.prom") as f:
        prom = f.read()
    assert "# HELP windflow_stage_sessions_closed_total" in prom
    assert "# TYPE windflow_stage_sessions_closed_total counter" in prom
    assert 'windflow_stage_sessions_closed_total{graph=' in prom
    assert "# TYPE windflow_event_time_watermark gauge" in prom
    assert "# HELP windflow_event_time_lateness_p99" in prom
    assert "windflow_event_time_min_watermark" in prom


def test_stage_counters_reject_unregistered_names():
    op = wf.SessionWindow(lambda t: t.key,
                          wf.WindowSpec.session(2), num_keys=4)
    with pytest.raises(ValueError, match="STAGE_COUNTERS"):
        op._publish_stage_counters({"not_a_registered_name": 1})


def test_event_time_names_registered():
    from windflow_tpu.observability.names import (
        EVENT_TIME_GAUGES, JOURNAL_EVENTS, STAGE_COUNTERS, STAGE_GAUGES)
    assert "lateness_drop" in JOURNAL_EVENTS
    for n in ("sessions_closed", "topn_evictions", "match_drops",
              "arch_drops", "overflow_drops", "old_drops"):
        assert n in STAGE_COUNTERS
    assert "join_table_version" in STAGE_GAUGES
    for n in ("watermark", "lateness_p99", "min_watermark", "skew"):
        assert n in EVENT_TIME_GAUGES


def test_off_path_state_is_unchanged():
    """event_time off must leave the state pytrees byte-for-byte today's —
    the zero-added-device-work contract the perf-gate pins enforce."""
    src, ops = make_query("q3_enrich_join", TOTAL)
    from windflow_tpu.runtime.pipeline import CompiledChain
    chain = CompiledChain(ops, src.payload_spec(), batch_capacity=64)
    assert "lat_hist" not in chain.states[0]
    src2, ops2 = make_query("q3_enrich_join", TOTAL)
    chain2 = CompiledChain(ops2, src2.payload_spec(), batch_capacity=64,
                           event_time=True)
    assert "lat_hist" in chain2.states[0]
    # the toggle must not stick to reused operator instances: rebuilding an
    # OFF chain over the same ops drops the histograms again
    chain3 = CompiledChain(ops2, src2.payload_spec(), batch_capacity=64,
                           event_time=False)
    assert "lat_hist" not in chain3.states[0]
    # and the perf-gate/bench builders stay hermetic under the env toggle
    import os
    os.environ["WF_MONITORING"], os.environ["WF_MONITORING_EVENT_TIME"] = \
        "1", "1"
    try:
        from windflow_tpu.analysis.perfgate import _build_mp_matrix
        chain4 = _build_mp_matrix()[0]
        assert not chain4.event_time
    finally:
        del os.environ["WF_MONITORING"]
        del os.environ["WF_MONITORING_EVENT_TIME"]


# --------------------------------------- graph topology: edge skew export

def test_graph_edge_skew_in_snapshot_and_topology(tmp_path):
    mon = _cfg(tmp_path)
    g = wf.PipeGraph(batch_size=32, monitoring=mon)
    mk = lambda: wf.Source(lambda i: {"side": (i % 2).astype(I32),
                                      "v": (i * 1).astype(I32)},
                           total=128, num_keys=4, ts_fn=lambda i: i // 2)
    a, b = g.add_source(mk()), g.add_source(mk())
    m = a.join_with(b, wf.IntervalJoin(lambda t: t.side == 1, 0, 4))
    m.add_sink(wf.Sink(lambda v: None))
    g.run()
    snap = _snapshot(tmp_path)
    assert "event_time" in snap
    assert "min_watermark_ts" in snap["event_time"]
    from windflow_tpu.observability import topology_dot, topology_json
    tj = topology_json(g, snap)
    skews = snap["event_time"].get("edge_skew_ts")
    if skews:      # present when both endpoint pipes carry watermarks
        assert any("watermark_skew_ts" in e for e in tj["edges"])
        assert "skew=" in topology_dot(g, snap)


# ------------------------ lateness forensics: recommend_delay -> zero drops

LAG = 5


def _skewed_source():
    """Two keys sharing one event clock, key 1 lagging LAG ticks behind —
    the cross-key skew that makes a global-time TB window drop OLD."""
    return wf.Source(lambda i: {"v": jnp.ones((), I32)}, total=256,
                     num_keys=2, key_fn=lambda i: i % 2,
                     ts_fn=lambda i: jnp.where(
                         i % 2 == 0, i // 2,
                         jnp.maximum(i // 2 - LAG, 0)))


def _run_skewed_window(delay, monitoring=False):
    spec = wf.WindowSpec(4, 4, wf.win_type_t.TB, delay)
    op = wf.Win_SeqFFAT(lambda t: 1, jnp.add, spec=spec, num_keys=2,
                        name="skewed_win")
    wf.Pipeline(_skewed_source(), [op], wf.Sink(lambda v: None),
                batch_size=32, monitoring=monitoring).run()
    return op


def test_recommend_delay_drives_old_drops_to_zero_via_wf_state(tmp_path):
    """THE acceptance loop: a skewed stream drops OLD at delay=0; the
    wf_state.py lateness report recommends a delay; applying it drives
    ``tuples_dropped_old`` to zero."""
    mon = str(tmp_path / "skew")
    op = _run_skewed_window(0, MonitoringConfig(out_dir=mon,
                                                event_time=True,
                                                interval_s=30.0))
    assert op.get_StatsRecords()[0].tuples_dropped_old > 0
    out = subprocess.run(
        [sys.executable, WF_STATE, "--monitoring-dir", mon,
         "--q", "1.0", "--json"],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    data = json.loads(out.stdout)
    rec = data["recommendations"]["skewed_win/in"]["recommend_delay"]
    assert rec >= LAG
    op2 = _run_skewed_window(rec, MonitoringConfig(
        out_dir=str(tmp_path / "skew2"), event_time=True, interval_s=30.0))
    assert op2.get_StatsRecords()[0].tuples_dropped_old == 0


def test_lateness_drop_journal_events(tmp_path):
    mon = str(tmp_path / "mon")
    _run_skewed_window(0, MonitoringConfig(out_dir=mon, event_time=True,
                                           interval_s=30.0))
    from windflow_tpu.observability import read_journal
    events = read_journal(os.path.join(mon, "events.jsonl"))
    drops = [e for e in events if e["event"] == "lateness_drop"]
    assert drops, "no lateness_drop events journaled"
    assert drops[0]["op"] == "skewed_win"
    assert drops[0]["kind"] == "old_drops"
    assert sum(e["n"] for e in drops) == drops[-1]["total"]


def test_session_lateness_section_recommends_covering_delay(tmp_path):
    run_query("q5_session", monitoring=_cfg(tmp_path))
    snap = _snapshot(tmp_path)
    sec = [r for r in snap["operators"]
           if r["name"] == "nexmark_session"][0]["event_time"]
    summ = sec["lateness"]["in"]
    assert summ["total"] > 0
    assert et.recommend_delay(summ["counts"], 1.0) >= summ["p99"]


# ------------------------------------------- wf_state.py CLI contract

def _poisoned_jax_dir(tmp_path):
    d = tmp_path / "nojax"
    d.mkdir(exist_ok=True)
    (d / "jax.py").write_text("raise ImportError('wf_state must not "
                              "import jax')\n")
    return str(d)


def test_wf_state_exit_0_and_report_without_jax(tmp_path):
    mon = str(tmp_path / "mon")
    _run_skewed_window(0, MonitoringConfig(out_dir=mon, event_time=True,
                                           interval_s=0.05))
    env = dict(os.environ, PYTHONPATH=_poisoned_jax_dir(tmp_path))
    out = subprocess.run([sys.executable, WF_STATE,
                          "--monitoring-dir", mon],
                         capture_output=True, text=True, env=env)
    assert out.returncode == 0, out.stderr
    assert "watermark propagation map" in out.stdout
    assert "state-pressure trends" in out.stdout
    assert "lateness report" in out.stdout
    assert "skewed_win" in out.stdout


def test_wf_state_exit_2_on_missing_inputs(tmp_path):
    env = dict(os.environ, PYTHONPATH=_poisoned_jax_dir(tmp_path))
    out = subprocess.run([sys.executable, WF_STATE, "--monitoring-dir",
                          str(tmp_path / "nope")],
                         capture_output=True, text=True, env=env)
    assert out.returncode == 2
    assert "cannot load snapshots" in out.stderr


def test_wf_state_exit_2_on_bad_quantile(tmp_path):
    out = subprocess.run([sys.executable, WF_STATE, "--q", "1.5"],
                         capture_output=True, text=True)
    assert out.returncode == 2


# ----------------------------------- fused-dispatch trace apportionment

def test_fused_spans_apportion_service_across_members():
    from windflow_tpu.observability.tracing import _batch_lifecycles
    recs = []
    # a fused group of 4: four spans over the SAME 8 ms launch, k-marked
    for i, tid in enumerate((11, 12, 13, 14)):
        recs.append({"t": 0.0 + i * 1e-6, "tid": tid, "stage": "chain",
                     "kind": "begin", "k": 4})
    for i, tid in enumerate((11, 12, 13, 14)):
        recs.append({"t": 0.008 + i * 1e-6, "tid": tid, "stage": "chain",
                     "kind": "end"})
    # an unfused span: full duration charged
    recs.append({"t": 0.020, "tid": 15, "stage": "chain", "kind": "begin"})
    recs.append({"t": 0.024, "tid": 15, "stage": "chain", "kind": "end"})
    lives = _batch_lifecycles(recs)
    for tid in (11, 12, 13, 14):
        assert lives[tid]["service"]["chain"] == pytest.approx(0.002,
                                                               rel=1e-3)
        assert lives[tid]["fused"] == 1
    assert lives[15]["service"]["chain"] == pytest.approx(0.004, rel=1e-6)
    assert lives[15]["fused"] == 0


def test_fused_push_marks_k_on_begin_records(tmp_path):
    from windflow_tpu.observability import TraceConfig, Tracer, tracing
    src, ops = make_query("q3_enrich_join", TOTAL)
    rows = []
    p = wf.Pipeline(src, ops, wf.Sink(lambda v: rows.append(1)),
                    batch_size=64,
                    trace=TraceConfig(out_dir=str(tmp_path / "tr")),
                    dispatch=4)
    p.run()
    records, meta = tracing.load_flight(str(tmp_path / "tr"))
    fused_begins = [r for r in records
                    if r["kind"] == "begin" and r.get("k")]
    assert fused_begins, "no k-marked begin records under dispatch=4"
    assert all(r["k"] > 1 for r in fused_begins)
    # chrome export annotates the fused spans
    trace = tracing.to_chrome_trace(records, [], meta)
    assert any(e.get("args", {}).get("fused_k")
               for e in trace["traceEvents"] if e["ph"] == "B")


def test_wf_trace_report_renders_lateness_drops(tmp_path):
    from windflow_tpu.observability import TraceConfig, tracing
    mon = str(tmp_path / "mon")
    spec = wf.WindowSpec(4, 4, wf.win_type_t.TB, 0)
    op = wf.Win_SeqFFAT(lambda t: 1, jnp.add, spec=spec, num_keys=2,
                        name="skewed_win")
    wf.Pipeline(_skewed_source(), [op], wf.Sink(lambda v: None),
                batch_size=32,
                monitoring=MonitoringConfig(out_dir=mon, event_time=True,
                                            interval_s=30.0),
                trace=TraceConfig(out_dir=str(tmp_path / "tr"))).run()
    from windflow_tpu.observability import read_journal
    records, meta = tracing.load_flight(str(tmp_path / "tr"))
    events = read_journal(os.path.join(mon, "events.jsonl"))
    report = tracing.critical_path_report(records, events, None, meta)
    assert "event-time drops" in report
    assert "skewed_win" in report and "old_drops" in report
