"""Win_Seq tests: CB and TB sliding/tumbling windows, keyed, with EOS flush.

Oracle: pure-python window computation over the same stream (reference pattern:
result invariance vs a sequential run, src/mp_test_cpu suite semantics)."""

import numpy as np
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.operators.win_seq import Win_Seq
from windflow_tpu.basic import win_type_t


def run_pipeline(total, K, spec, win_fn, batch_size, **kw):
    src = wf.Source(lambda i: {"v": (i // K).astype(jnp.float32)},
                    total=total, num_keys=K)
    ws = Win_Seq(win_fn, spec, num_keys=K, **kw)
    results = []

    def cb(view):
        if view is None:
            return
        for k, w, r in zip(view["key"].tolist(), view["id"].tolist(),
                           np.asarray(view["payload"]).tolist()):
            results.append((k, w, r))

    wf.Pipeline(src, [ws], wf.Sink(cb), batch_size=batch_size).run()
    return sorted(results)


def oracle_cb(total, K, L, S, agg=sum, flush=True):
    """Python oracle: key k receives values i//K for i = k, k+K, k+2K, ..."""
    per_key = {k: [] for k in range(K)}
    for i in range(total):
        per_key[i % K].append(float(i // K))
    out = []
    for k, vals in per_key.items():
        n = len(vals)
        hi = (n - 1) // S + 1 if (flush and n > 0) else max(0, (n - L) // S + 1)
        for w in range(hi):
            content = vals[w * S: w * S + L]
            if content:
                out.append((k, w, agg(content)))
    return sorted(out)


def test_cb_tumbling_sum():
    spec = WindowSpec(win_len=4, slide=4, wtype=win_type_t.CB)
    got = run_pipeline(160, 2, spec, lambda wid, it: it.sum("v"), batch_size=32)
    assert got == oracle_cb(160, 2, 4, 4)


def test_cb_sliding_sum():
    spec = WindowSpec(win_len=6, slide=2, wtype=win_type_t.CB)
    got = run_pipeline(200, 3, spec, lambda wid, it: it.sum("v"), batch_size=64)
    assert got == oracle_cb(200, 3, 6, 2)


def test_cb_invariance_under_batch_size():
    spec = WindowSpec(win_len=5, slide=3, wtype=win_type_t.CB)
    runs = [run_pipeline(121, 4, spec, lambda wid, it: it.sum("v"), bs)
            for bs in (16, 64, 121)]
    assert runs[0] == runs[1] == runs[2]
    assert runs[0] == oracle_cb(121, 4, 5, 3)


def test_cb_incremental_fold():
    spec = WindowSpec(win_len=4, slide=4, wtype=win_type_t.CB)
    fold = lambda wid, t, acc: acc + t.v
    got = run_pipeline(96, 2, spec, fold, batch_size=24,
                       incremental=True, init_acc=jnp.zeros((), jnp.float32))
    assert got == oracle_cb(96, 2, 4, 4)


def test_cb_max_window():
    spec = WindowSpec(win_len=8, slide=8, wtype=win_type_t.CB)
    got = run_pipeline(128, 2, spec, lambda wid, it: it.max("v"), batch_size=32)
    assert got == oracle_cb(128, 2, 8, 8, agg=max)


def test_tb_tumbling_sum():
    # ts = global index i; key = i % K; window [w*8, w*8+8) per key
    total, K, L, S = 160, 2, 8, 8
    spec = WindowSpec(win_len=L, slide=S, wtype=win_type_t.TB)
    got = run_pipeline(total, K, spec, lambda wid, it: it.sum("v"), batch_size=40)
    # oracle over timestamps
    per_key = {k: [] for k in range(K)}
    for i in range(total):
        per_key[i % K].append((i, float(i // K)))   # (ts, v)
    expect = []
    for k, tuples in per_key.items():
        max_ts = max(t for t, _ in tuples)
        for w in range(max_ts // S + 1):
            content = [v for t, v in tuples if w * S <= t < w * S + L]
            if content:
                expect.append((k, w, sum(content)))
    assert got == sorted(expect)


def test_tb_sliding_with_lateness():
    """Out-of-order timestamps within the lateness allowance land in their windows."""
    total, K, L, S, delay = 120, 1, 10, 5, 16
    spec = WindowSpec(win_len=L, slide=S, wtype=win_type_t.TB, delay=delay)
    # scramble ts mildly: ts = i + (3 - i%7 scaled) stays within lateness
    def src_fn(i):
        return {"v": i.astype(jnp.float32)}
    src = wf.Source(src_fn, total=total, num_keys=K,
                    ts_fn=lambda i: i + (i % 3) * 2 - 2)
    ws = Win_Seq(lambda wid, it: it.sum("v"), spec, num_keys=K,
                 archive_capacity=256)
    results = []

    def cb(view):
        if view is None:
            return
        for w, r in zip(view["id"].tolist(), np.asarray(view["payload"]).tolist()):
            results.append((w, r))

    wf.Pipeline(src, [ws], wf.Sink(cb), batch_size=30).run()
    ts_of = [i + (i % 3) * 2 - 2 for i in range(total)]
    max_ts = max(ts_of)
    expect = []
    for w in range(max_ts // S + 1):
        content = [float(i) for i in range(total) if w * S <= ts_of[i] < w * S + L]
        if content:
            expect.append((w, sum(content)))
    assert sorted(results) == sorted(expect)


def test_iterable_positional_access():
    """at/[]/first/last (reference wf/iterable.hpp begin/end/at/operator[])."""
    import windflow_tpu as wf
    from windflow_tpu.operators.win_seq import Win_Seq

    results = []

    def win_fn(wid, it):
        # span = last.v - first.v; mid = it[1].v (second live tuple)
        return it.last().v - it.first().v + 100.0 * it[1].v

    src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=40, num_keys=1)

    def cb(view):
        if view is None:
            return
        results.extend(zip(view["id"].tolist(),
                           np.asarray(view["payload"]).tolist()))

    wf.Pipeline(src, [Win_Seq(win_fn, WindowSpec(8, 8, win_type_t.CB),
                              num_keys=1)], wf.Sink(cb), batch_size=16).run()
    got = dict(results)
    for w in range(5):
        base = w * 8.0
        want = (base + 7) - base + 100.0 * (base + 1)
        assert abs(got[w] - want) < 1e-3, (w, got[w], want)


def test_vector_payload_windows():
    """Tuples carrying vector payloads (e.g. embeddings): windowed reduction is
    element-wise over the trailing dims, both non-incremental and incremental."""
    import windflow_tpu as wf
    src = lambda: wf.Source(
        lambda i: {"emb": (i % 5).astype(jnp.float32) * jnp.ones(4)},
        total=96, num_keys=2)

    def run(op):
        out = []
        def cb(view):
            if view is None:
                return
            out.extend(map(tuple, np.asarray(view["payload"]).tolist()))
        wf.Pipeline(src(), [op], wf.Sink(cb), batch_size=32).run()
        return sorted(out)

    spec = WindowSpec(8, 8, win_type_t.CB)
    noninc = run(wf.Win_Seq(lambda wid, it: it.sum("emb"), spec, num_keys=2))
    inc = run(wf.Win_Seq(lambda wid, t, acc: acc + t.emb, spec,
                         init_acc=jnp.zeros(4), num_keys=2))
    assert noninc == inc and len(noninc) == 12
    per_key = {0: [], 1: []}
    for i in range(96):
        per_key[i % 2].append(float(i % 5))
    want = sorted(tuple([sum(xs[j:j + 8])] * 4)
                  for xs in per_key.values() for j in range(0, len(xs), 8))
    assert noninc == want
