"""Multi-device tests on the virtual 8-CPU mesh: sharded execution must produce the
same results as single-device (the reference oracle: result invariance under
parallelism degree, src/graph_test/test_graph_1.cpp:77-87 — here invariance under
sharding), plus emitter/ordering unit tests."""

import numpy as np
import jax
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.basic import routing_modes_t, ordering_mode_t
from windflow_tpu.parallel import (make_mesh, ShardedChain, shard_batch,
                                   Standard_Emitter, Broadcast_Emitter,
                                   Splitting_Emitter, Tree_Emitter, Ordering_Node)
from windflow_tpu.runtime.pipeline import CompiledChain
from windflow_tpu.batch import Batch
from windflow_tpu.operators.win_patterns import Key_FFAT
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.basic import win_type_t


def test_eight_devices_available():
    assert len(jax.devices()) >= 8


def _run_chain(chain_factory, batches, sharded):
    src_spec = {"v": jax.ShapeDtypeStruct((), jnp.float32)}
    chain = CompiledChain(chain_factory(), src_spec, batch_capacity=batches[0].capacity)
    if sharded:
        mesh = make_mesh(8)
        sc = ShardedChain(chain, mesh)
        outs = [sc.push(b) for b in batches]
        outs += sc.flush()
    else:
        outs = [chain.push(b) for b in batches]
        outs += chain.flush()
    acc = []
    for o in outs:
        o = jax.tree.map(np.asarray, o)
        v = o.valid
        acc.extend(zip(o.key[v].tolist(), o.id[v].tolist(),
                       np.asarray(jax.tree.leaves(o.payload)[0])[v].tolist()))
    return sorted(acc)


def _batches(total, C, K):
    rng = np.random.default_rng(0)
    out = []
    for s in range(0, total, C):
        n = min(C, total - s)
        ids = np.arange(s, s + C, dtype=np.int32)
        out.append(Batch(
            key=jnp.asarray(ids % K),
            id=jnp.asarray(ids),
            ts=jnp.asarray(ids),
            payload={"v": jnp.asarray((ids % 13).astype(np.float32))},
            valid=jnp.asarray(np.arange(C) < n),
        ))
    return out


def test_sharded_keyed_window_matches_single_device():
    K = 16  # multiple of 8 devices
    spec = WindowSpec(20, 20, win_type_t.CB)
    factory = lambda: [Key_FFAT(lambda t: t.v, jnp.add, spec=spec, num_keys=K)]
    batches = _batches(400, 80, K)
    single = _run_chain(factory, batches, sharded=False)
    multi = _run_chain(factory, batches, sharded=True)
    assert single == multi and len(single) > 0


def test_standard_emitter_keyby_partition():
    b = _batches(64, 64, 8)[0]
    em = Standard_Emitter(4, routing_modes_t.KEYBY)
    outs = em.route(b)
    seen = []
    for d, ob in enumerate(outs):
        ob = jax.tree.map(np.asarray, ob)
        for k in ob.key[ob.valid].tolist():
            assert k % 4 == d
            seen.append(k)
    assert len(seen) == 64


def test_standard_emitter_overflow_is_lossless():
    """A capacity_per_dest smaller than one destination's share must NOT drop
    tuples: the emitter multi-passes the residue (bounded-queue backpressure —
    the reference's FF_BOUNDED_BUFFER blocks, it never loses a tuple)."""
    rng = np.random.default_rng(11)
    C = 64
    # heavy skew: key 0 gets ~70% of the batch, far past a 4-lane budget
    keys = np.where(rng.random(C) < 0.7, 0, rng.integers(0, 16, C)).astype(np.int32)
    valid = rng.random(C) < 0.9
    b = Batch(key=jnp.asarray(keys), id=jnp.arange(C, dtype=jnp.int32),
              ts=jnp.zeros(C, jnp.int32),
              payload={"v": jnp.arange(C, dtype=jnp.float32)},
              valid=jnp.asarray(valid))
    em = Standard_Emitter(4, routing_modes_t.KEYBY, capacity_per_dest=4)
    outs = em.route(b)
    assert em.overflow_rounds > 0               # the skew actually overflowed
    got = []
    for d, ob in enumerate(outs):
        ob = jax.tree.map(np.asarray, ob)
        live_k = ob.key[ob.valid]
        assert np.all(live_k % 4 == d)          # routing stayed correct
        got.extend((int(k), float(v)) for k, v in zip(live_k, ob.payload["v"][ob.valid]))
    want = [(int(k), float(i)) for i, (k, ok) in enumerate(zip(keys, valid)) if ok]
    assert sorted(got) == sorted(want)          # every live tuple delivered once


def test_standard_emitter_overflow_fuzz():
    """Randomized conservation under arbitrary skew/capacity (overflow fuzz)."""
    rng = np.random.default_rng(23)
    for trial in range(10):
        C = int(rng.integers(8, 128))
        n_dest = int(rng.integers(2, 6))
        cap = int(rng.integers(1, 8))
        keys = rng.integers(0, max(1, int(rng.integers(1, 12))), C).astype(np.int32)
        valid = rng.random(C) < 0.85
        b = Batch(key=jnp.asarray(keys), id=jnp.arange(C, dtype=jnp.int32),
                  ts=jnp.zeros(C, jnp.int32),
                  payload={"v": jnp.arange(C, dtype=jnp.float32)},
                  valid=jnp.asarray(valid))
        outs = Standard_Emitter(n_dest, routing_modes_t.KEYBY,
                                capacity_per_dest=cap).route(b)
        got = []
        for d, ob in enumerate(outs):
            ob = jax.tree.map(np.asarray, ob)
            got.extend(float(v) for v in ob.payload["v"][ob.valid])
        want = [float(i) for i, ok in enumerate(valid) if ok]
        assert sorted(got) == sorted(want), (trial, C, n_dest, cap)


def test_broadcast_and_tree_emitter():
    b = _batches(32, 32, 4)[0]
    tree = Tree_Emitter(Broadcast_Emitter(2),
                        [Standard_Emitter(2, routing_modes_t.KEYBY),
                         Standard_Emitter(2, routing_modes_t.KEYBY)])
    outs = tree.route(b)
    assert len(outs) == 4
    tot = sum(int(np.asarray(o.valid).sum()) for o in outs if o is not None)
    assert tot == 64  # each tuple duplicated by the broadcast root


def test_ordering_node_ts_merge():
    node = Ordering_Node(2, ordering_mode_t.TS)
    def mk(ts_list):
        n = len(ts_list)
        ids = np.arange(n, dtype=np.int32)
        return Batch(key=jnp.zeros(n, jnp.int32), id=jnp.asarray(ids),
                     ts=jnp.asarray(np.asarray(ts_list, np.int32)),
                     payload={"v": jnp.zeros(n, jnp.float32)},
                     valid=jnp.ones(n, bool))
    released = []
    for ch, b in [(0, mk([5, 1, 9])), (1, mk([4, 2, 7]))]:
        out = node.push(ch, b)
        if out is not None:
            o = jax.tree.map(np.asarray, out)
            released.extend(o.ts[o.valid].tolist())
    tail = node.flush()
    if tail is not None:
        o = jax.tree.map(np.asarray, tail)
        released.extend(o.ts[o.valid].tolist())
    assert released == sorted(released) == [1, 2, 4, 5, 7, 9]


def test_standard_emitter_partition_variants_agree():
    """The sort-based and one-hot KEYBY partitions must route identically
    (same sub-batch membership AND stable within-destination order)."""
    b = _batches(96, 96, 8)[0]
    outs_s = Standard_Emitter(4, routing_modes_t.KEYBY, partition="sort").route(b)
    outs_o = Standard_Emitter(4, routing_modes_t.KEYBY, partition="onehot").route(b)
    for os_, oo in zip(outs_s, outs_o):
        os_, oo = jax.tree.map(np.asarray, os_), jax.tree.map(np.asarray, oo)
        assert (os_.valid == oo.valid).all()
        assert (os_.id[os_.valid] == oo.id[oo.valid]).all()
        assert (os_.key[os_.valid] == oo.key[oo.valid]).all()


def test_fuzz_sharded_chain_random_geometry():
    """Randomized op x mesh-layout x geometry: the ShardedChain must be
    oracle-identical to the single-device run for key-axis, dp-axis, and
    2-D dp x key layouts at arbitrary window specs and batch sizes."""
    from windflow_tpu.parallel.mesh import make_mesh_2d

    rng = np.random.default_rng(23)
    for trial in range(4):
        slide = int(rng.integers(2, 6))
        win = slide * int(rng.integers(1, 4))
        wt = win_type_t.CB if trial % 2 == 0 else win_type_t.TB
        K = 8 * int(rng.integers(1, 3))             # divisible by the key axis
        total = int(rng.integers(100, 300))
        bs = 8 * int(rng.integers(4, 12))           # divisible by dp axis
        spec = WindowSpec(win, slide, wt)

        def collect(ob, acc):
            o = jax.tree.map(np.asarray, ob)
            v = o.valid
            acc.extend(zip(o.key[v].tolist(), o.id[v].tolist(),
                           np.asarray(jax.tree.leaves(o.payload)[0])[v].tolist()))

        def results(layout):
            src = wf.Source(lambda i: {"v": ((i * 11) % 17).astype(jnp.float32)},
                            total=total, num_keys=K)
            chain = CompiledChain([Key_FFAT(lambda t: t.v, jnp.add, spec=spec,
                                            num_keys=K)],
                                  src.payload_spec(), batch_capacity=bs)
            if layout == "key":
                chain = ShardedChain(chain, make_mesh(8, axis="key"), axis="key",
                                     key_axis="key")
            elif layout == "dp":
                chain = ShardedChain(chain, make_mesh(8, axis="dp"), axis="dp")
            elif layout == "2d":
                chain = ShardedChain(chain, make_mesh_2d((2, 4),
                                                         axes=("dp", "key")),
                                     axis="dp", key_axis="key")
            out = []
            for b in src.batches(bs):
                collect(chain.push(b), out)
            for fb in chain.flush():
                collect(fb, out)
            return sorted(out)

        oracle = results("single")
        assert oracle, f"trial {trial}: no windows fired"
        for layout in ("key", "dp", "2d"):
            got = results(layout)
            assert got == oracle, (
                f"trial {trial}: layout={layout} diverges at spec=({win},{slide},"
                f"{wt}) K={K} total={total} bs={bs}")
