"""Per-batch causal tracing: deterministic trace ids across every driver and
under supervised restart, flight-recorder mechanics, histogram exemplars, the
Chrome trace-event export schema (wf_trace.py end-to-end), the critical-path
report's restart/shed attribution on a chaos run, the WF108 validator check,
the buffered EventJournal mode, and xprof_trace session hardening."""

import json
import os
import subprocess
import sys
import threading

import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.batch import trace_meta
from windflow_tpu.observability import (EventJournal, LogHistogram,
                                        TraceConfig, Tracer, read_journal)
from windflow_tpu.observability import tracing
from windflow_tpu.runtime.faults import FaultPlan, FaultSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TOTAL, BATCH = 256, 32


def _source():
    return wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=TOTAL,
                     name="gen")


def _ops():
    return [wf.Map(lambda t: {"v": t.v * 2}, name="dbl")]


def _cfg(tmp_path, sub, **kw):
    kw.setdefault("run_id", "t")
    return TraceConfig(out_dir=str(tmp_path / sub), **kw)


def _ingest_ids(trace_dir):
    recs, meta = tracing.load_flight(str(trace_dir))
    return [r["tid"] for r in recs if r["kind"] == "ingest"], recs, meta


def _assert_no_orphan_begins(recs):
    open_b = {}
    for r in recs:
        k = (r["tid"], r["stage"])
        if r["kind"] == "begin":
            open_b[k] = open_b.get(k, 0) + 1
        elif r["kind"] == "end":
            open_b[k] = open_b.get(k, 0) - 1
    orphans = {k: v for k, v in open_b.items() if v}
    assert not orphans, orphans


# ------------------------------------------------------------ id minting

def test_mint_trace_id_pure_and_decodable():
    a = tracing.mint_trace_id("run", 0, 7)
    assert a == tracing.mint_trace_id("run", 0, 7)      # pure
    assert tracing.trace_pos(a) == 7
    assert a != tracing.mint_trace_id("run", 1, 7)      # stream-namespaced
    assert a != tracing.mint_trace_id("other", 0, 7)    # run-namespaced


def test_trace_config_resolve_conventions(monkeypatch):
    assert TraceConfig.resolve(False) is None
    monkeypatch.delenv("WF_TRACE", raising=False)
    assert TraceConfig.resolve(None) is None            # off by default
    monkeypatch.setenv("WF_TRACE", "0")
    assert TraceConfig.resolve(None) is None
    monkeypatch.setenv("WF_TRACE", "1")
    assert TraceConfig.resolve(None).out_dir == "wf_trace"
    monkeypatch.setenv("WF_TRACE", "/tmp/x")
    assert TraceConfig.resolve(None).out_dir == "/tmp/x"
    monkeypatch.setenv("WF_TRACE_SAMPLE", "16")
    assert TraceConfig.resolve(True).sample_every == 16
    with pytest.raises(ValueError):
        TraceConfig(sample_every=0)
    with pytest.raises(ValueError):
        TraceConfig(ids="wall-clock")


# --------------------------------------------- determinism across drivers

def test_trace_ids_identical_across_drivers(tmp_path):
    """The SAME workload under Pipeline / ThreadedPipeline / PipeGraph (push
    and threaded) mints byte-identical ingest id sequences."""
    wf.Pipeline(_source(), _ops(), wf.Sink(lambda v: None), batch_size=BATCH,
                trace=_cfg(tmp_path, "p")).run()
    ids_p, recs_p, _ = _ingest_ids(tmp_path / "p")

    wf.ThreadedPipeline(_source(), [_ops()], wf.Sink(lambda v: None),
                        batch_size=BATCH, pin=False,
                        trace=_cfg(tmp_path, "tp")).run()
    ids_t, recs_t, _ = _ingest_ids(tmp_path / "tp")

    g = wf.PipeGraph("g", batch_size=BATCH, trace=_cfg(tmp_path, "g"))
    g.add_source(_source()).add(_ops()[0]).add_sink(wf.Sink(lambda v: None))
    g.run()
    ids_g, _, _ = _ingest_ids(tmp_path / "g")

    g2 = wf.PipeGraph("g2", batch_size=BATCH, trace=_cfg(tmp_path, "gt"))
    g2.add_source(_source()).add(_ops()[0]).add_sink(wf.Sink(lambda v: None))
    g2.run(threaded=True)
    ids_gt, _, _ = _ingest_ids(tmp_path / "gt")

    assert len(ids_p) == TOTAL // BATCH
    assert ids_p == ids_t == ids_g == ids_gt
    _assert_no_orphan_begins(recs_p)
    _assert_no_orphan_begins(recs_t)
    # the threaded driver records the full causal chain: ring enqueue/
    # dequeue around every hop
    kinds = {r["kind"] for r in recs_t}
    assert {"ingest", "enq", "deq", "begin", "end"} <= kinds


def test_trace_ids_stable_under_supervised_restart(tmp_path):
    """A FaultPlan restart replays positions — the replayed batches re-mint
    the SAME ids (dedup == fault-free sequence), no orphan begin-spans
    survive recovery, and every service-histogram exemplar is a minted id."""
    wf.Pipeline(_source(), _ops(), wf.Sink(lambda v: None), batch_size=BATCH,
                trace=_cfg(tmp_path, "ref")).run()
    ids_ref, _, _ = _ingest_ids(tmp_path / "ref")

    plan = FaultPlan(seed=7, faults=[FaultSpec(site="chain.step",
                                               kind="error", at=[4])])
    sp = wf.SupervisedPipeline(_source(), _ops(), wf.Sink(lambda v: None),
                               batch_size=BATCH, checkpoint_every=2,
                               faults=plan, trace=_cfg(tmp_path, "sup"))
    sp.run()
    assert sp.restarts >= 1
    ids_sup, recs, meta = _ingest_ids(tmp_path / "sup")
    assert len(ids_sup) > len(ids_ref)          # replay re-ingested batches
    dedup = list(dict.fromkeys(ids_sup))
    assert dedup == ids_ref
    _assert_no_orphan_begins(recs)
    minted = set(ids_sup)
    for op in sp.chain.ops:
        for rec in op.get_StatsRecords():
            for ex in rec.service_hist.exemplars.values():
                assert ex in minted             # exemplar ids stable


def test_supervised_rejects_sequence_ids(tmp_path):
    sp = wf.SupervisedPipeline(_source(), _ops(), batch_size=BATCH,
                               trace=_cfg(tmp_path, "seq", ids="sequence"))
    with pytest.raises(ValueError, match="position"):
        sp.run()


def test_sampling_is_positional(tmp_path):
    wf.Pipeline(_source(), _ops(), wf.Sink(lambda v: None), batch_size=BATCH,
                trace=_cfg(tmp_path, "s", sample_every=4)).run()
    _, recs, meta = _ingest_ids(tmp_path / "s")
    poss = [r["pos"] for r in recs if r["kind"] == "ingest"]
    assert poss == [0, 4]
    assert meta["minted"] == 2
    # untraced batches leave NO records at all
    assert {tracing.trace_pos(r["tid"]) for r in recs
            if r["tid"]} == {0, 4}


def test_tracing_off_leaves_no_state(tmp_path):
    """Off (the default): no active tracer, no sidecar attr on batches, no
    output files — the hot path is today's exact code."""
    out = []
    wf.Pipeline(_source(), _ops(), wf.Sink(lambda v: out.append(v)),
                batch_size=BATCH).run()
    assert tracing.get_active() is None
    assert not (tmp_path / "wf_trace").exists()
    b = next(iter(_source().batches(BATCH)))
    assert trace_meta(b) is None
    assert tracing.tid_of(b) is None


def test_results_identical_with_tracing_on(tmp_path):
    import numpy as np
    ref, traced = [], []
    wf.Pipeline(_source(), _ops(),
                wf.Sink(lambda v: ref.append(v)), batch_size=BATCH).run()
    wf.Pipeline(_source(), _ops(),
                wf.Sink(lambda v: traced.append(v)), batch_size=BATCH,
                trace=_cfg(tmp_path, "same")).run()
    assert len(ref) == len(traced)
    for a, b in zip(ref, traced):
        if a is None or b is None:
            assert a is b
            continue
        np.testing.assert_array_equal(np.asarray(a["payload"]["v"]),
                                      np.asarray(b["payload"]["v"]))


# --------------------------------------------------------- flight recorder

def test_flight_recorder_ring_wraps_bounded():
    tr = Tracer(TraceConfig(out_dir="/tmp/unused", ring_capacity=8,
                            run_id="w"), "w")
    class B:                                  # any object takes the sidecar
        pass
    for i in range(50):
        b = B()
        tr.ingest(b, i)
    recs = tr.records()
    assert len(recs) == 8                     # bounded
    assert [r["pos"] for r in recs] == list(range(42, 50))   # newest kept
    assert tr.meta()["dropped"] == 42


def test_flight_recorder_per_thread_segments():
    tr = Tracer(TraceConfig(out_dir="/tmp/unused", run_id="mt"), "mt")
    class B:
        pass
    def work(stream):
        for i in range(20):
            b = B()
            tr.ingest(b, i, stream=stream)
    ts = [threading.Thread(target=work, args=(s,)) for s in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    recs = tr.records()
    assert len(recs) == 80
    assert len({r["thread"] for r in recs}) == 4
    assert [r["t"] for r in recs] == sorted(r["t"] for r in recs)


def test_abort_open_closes_spans_with_reason():
    tr = Tracer(TraceConfig(out_dir="/tmp/unused", run_id="a"), "a")
    class B:
        pass
    b = B()
    tr.ingest(b, 0)
    span = tr.service(b, "chain")
    assert span is not None
    assert tr.abort_open("restore") == 1
    span.done()                               # late done after abort: no-op
    recs = tr.records()
    ends = [r for r in recs if r["kind"] == "end"]
    assert len(ends) == 1 and ends[0]["aborted"] == "restore"
    _assert_no_orphan_begins(recs)


def test_abort_open_sweeps_dead_worker_segments():
    """A step_timeout watchdog worker that died mid-span (graph supervisor
    with a timeout runs the push in a transient thread): after the join, the
    driver-thread abort_open closes the dead thread's spans too — but never
    touches a LIVE foreign thread's open spans."""
    tr = Tracer(TraceConfig(out_dir="/tmp/unused", run_id="dw"), "dw")
    class B:
        pass
    def worker():
        b = B()
        tr.ingest(b, 0)
        tr.service(b, "pipe0")                # dies without done()
    t = threading.Thread(target=worker)
    t.start()
    t.join()
    gate = threading.Event()
    def live_worker():
        b = B()
        tr.ingest(b, 1)
        tr.service(b, "pipe1")
        gate.wait(5.0)
    lt = threading.Thread(target=live_worker)
    lt.start()
    import time as _t
    for _ in range(100):                      # wait for live span to open
        if any(s.open_spans and s.owner is lt for s in tr._segments):
            break
        _t.sleep(0.01)
    assert tr.abort_open("restore") == 1      # dead worker swept, live kept
    gate.set()
    lt.join()
    recs = tr.records()
    aborted = [r for r in recs if r.get("aborted")]
    assert len(aborted) == 1 and aborted[0]["stage"] == "pipe0"


# ------------------------------------------------------ histogram exemplars

def test_log_histogram_exemplars():
    h = LogHistogram()
    for i, s in enumerate((1e-5, 1e-5, 1e-3)):
        h.record(s, exemplar=100 + i)
    # p50 falls in the 10us bucket (last exemplar there: 101), p99 in the
    # 1ms bucket (exemplar 102)
    assert h.exemplar(50) == 101
    assert h.exemplar(99) == 102
    assert h.summary_us()["p99_exemplar"] == 102
    h2 = LogHistogram()
    h2.record(1e-4)                           # no exemplar passed
    assert h2.exemplar(99) is None
    assert "p99_exemplar" not in h2.summary_us()


def test_snapshot_p99_exemplar_names_a_minted_batch(tmp_path):
    mon = str(tmp_path / "mon")
    wf.Pipeline(_source(), _ops(), wf.Sink(lambda v: None), batch_size=BATCH,
                monitoring=mon, trace=_cfg(tmp_path, "ex")).run()
    snap = json.load(open(os.path.join(mon, "snapshot.json")))
    ids, _, _ = _ingest_ids(tmp_path / "ex")
    ex = snap["e2e_latency_us"].get("p99_exemplar")
    assert ex is not None and ex in set(ids)


# ------------------------------------- Chrome export + wf_trace.py smoke

def _validate_chrome_trace(trace):
    assert "traceEvents" in trace and isinstance(trace["traceEvents"], list)
    stacks = {}
    last_ts = None
    for e in trace["traceEvents"]:
        for key in ("ph", "ts", "pid", "tid", "name"):
            assert key in e, (key, e)
        assert e["ts"] >= 0
        if last_ts is not None:
            assert e["ts"] >= last_ts         # monotonic export order
        last_ts = e["ts"]
        if e["ph"] == "B":
            stacks.setdefault((e["pid"], e["tid"]), []).append(e)
        elif e["ph"] == "E":
            assert stacks.get((e["pid"], e["tid"])), \
                f"E without B on track {e}"
            stacks[(e["pid"], e["tid"])].pop()
    dangling = {k: v for k, v in stacks.items() if v}
    assert not dangling, f"unmatched B events: {dangling}"


def test_wf_trace_cli_end_to_end(tmp_path):
    """Tier-1 smoke: run a small traced+monitored graph, then drive
    scripts/wf_trace.py over the artifacts and validate the export against
    the Chrome trace-event schema (required keys, monotonic ts, matched
    B/E pairs)."""
    mon = str(tmp_path / "mon")
    td = tmp_path / "tr"
    g = wf.PipeGraph("smoke", batch_size=BATCH, monitoring=mon,
                     trace=_cfg(tmp_path, "tr"))
    g.add_source(_source()).add(_ops()[0]).add_sink(wf.Sink(lambda v: None))
    g.run(threaded=True)
    out = tmp_path / "trace.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "wf_trace.py"),
         "--trace-dir", str(td), "--monitoring-dir", mon,
         "--out", str(out), "--report"],
        capture_output=True, text=True, cwd=str(tmp_path))
    assert r.returncode == 0, r.stderr
    assert "wrote" in r.stdout and "windflow trace report" in r.stdout
    _validate_chrome_trace(json.load(open(out)))


def test_wf_trace_cli_missing_inputs_exit_2(tmp_path):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "wf_trace.py"),
         "--trace-dir", str(tmp_path / "nope")],
        capture_output=True, text=True)
    assert r.returncode == 2
    assert "cannot load flight recorder" in r.stderr


@pytest.mark.chaos
def test_report_attributes_restart_and_shed(tmp_path):
    """Acceptance: a supervised chaos run (one injected restart + admission
    shedding) — the report attributes the affected batches to restart/shed
    phases and its p99 exemplar matches the snapshot histogram bucket."""
    mon = str(tmp_path / "mon")
    g = wf.PipeGraph("chaos", batch_size=BATCH, monitoring=mon,
                     trace=_cfg(tmp_path, "tr"),
                     control=dict(autotune=False, backpressure=False,
                                  admission=True, refill_per_batch=24.0,
                                  burst_tuples=40.0))
    g.add_source(_source()).add(_ops()[0]).add_sink(wf.Sink(lambda v: None))
    plan = FaultPlan(seed=3, faults=[FaultSpec(site="chain.step",
                                               kind="error", at=[3])])
    g.run_supervised(checkpoint_every=4, faults=plan)
    assert g.supervised_restarts >= 1

    recs, meta = tracing.load_flight(str(tmp_path / "tr"))
    events = read_journal(os.path.join(mon, "events.jsonl"))
    snap = json.load(open(os.path.join(mon, "snapshot.json")))
    rep = tracing.critical_path_report(recs, events, snap, meta)
    assert "RESTART-AFFECTED" in rep
    assert "restart/restore" in rep
    # the deterministic position bucket shed batches; the journal names them
    shed = sorted(e["pos"] for e in events if e["event"] == "shed")
    assert shed and f"shed" in rep
    for p in shed:
        assert str(p) in rep
    # p99 exemplar line present and consistent with the snapshot
    ex = snap["e2e_latency_us"].get("p99_exemplar")
    assert ex is not None
    assert f"{int(ex):#x}" in rep
    _assert_no_orphan_begins(recs)
    # journal shed events carry the shed positions; the trace ids decode
    # back to positions, closing the loop
    ids, _, _ = _ingest_ids(tmp_path / "tr")
    assert set(shed) <= {tracing.trace_pos(t) for t in ids}


# ---------------------------------------------------------- WF108 validator

def test_validator_wf108_sequence_ids_under_supervision(tmp_path):
    from windflow_tpu.analysis import validate
    sp = wf.SupervisedPipeline(_source(), _ops(), batch_size=BATCH,
                               trace=TraceConfig(ids="sequence"))
    rep = validate(sp)
    assert "WF108" in rep.codes()
    assert any("sequence" in d.message for d in rep.errors)
    # position ids (the default) are clean
    sp2 = wf.SupervisedPipeline(_source(), _ops(), batch_size=BATCH,
                                trace=TraceConfig())
    assert "WF108" not in validate(sp2).codes()
    # live drivers may use sequence ids
    p = wf.Pipeline(_source(), _ops(), wf.Sink(lambda v: None),
                    batch_size=BATCH, trace=TraceConfig(ids="sequence"))
    assert "WF108" not in validate(p).codes()
    # explicit trace= override wins over the stored argument
    assert "WF108" in validate(p, supervised=True,
                               trace=TraceConfig(ids="sequence")).codes()


def test_validator_wf108_bad_env_sample(monkeypatch):
    from windflow_tpu.analysis import validate
    monkeypatch.setenv("WF_TRACE", "1")
    monkeypatch.setenv("WF_TRACE_SAMPLE", "zero")
    p = wf.Pipeline(_source(), _ops(), wf.Sink(lambda v: None),
                    batch_size=BATCH)
    rep = validate(p)
    assert "WF108" in rep.codes()


# ------------------------------------------------- EventJournal flush modes

def test_journal_buffered_mode_flushes_on_interval_and_close(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = EventJournal(path, flush_interval=10)
    for i in range(4):
        j.event("launch", push=i)
    # buffered: nothing hit the disk yet (4 < 10, no error events)
    assert os.path.getsize(path) == 0
    j.close()                                 # close always flushes
    assert len(read_journal(path)) == 4

    path2 = str(tmp_path / "j2.jsonl")
    j2 = EventJournal(path2, flush_interval=3)
    for i in range(3):
        j2.event("launch", push=i)
    assert len(read_journal(path2)) == 3      # interval crossed -> flushed
    j2.close()


def test_journal_buffered_mode_flushes_errors_immediately(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = EventJournal(path, flush_interval=1000)
    j.event("launch", push=0)
    assert os.path.getsize(path) == 0
    j.event("restart_exhausted", error="Boom")
    # an error-carrying record flushes the buffered tail immediately
    assert len(read_journal(path)) == 2
    j.close()


def test_journal_default_stays_per_event(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = EventJournal(path)
    j.event("launch", push=0)
    assert len(read_journal(path)) == 1       # visible without close
    j.close()


# ------------------------------------------------- xprof session hardening

def test_xprof_trace_nested_session_clear_error(tmp_path, monkeypatch):
    import windflow_tpu.stats as stats
    calls = []
    monkeypatch.setattr("jax.profiler.start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr("jax.profiler.stop_trace",
                        lambda: calls.append(("stop",)))
    with stats.xprof_trace(str(tmp_path / "a")):
        with pytest.raises(RuntimeError, match="already active"):
            with stats.xprof_trace(str(tmp_path / "b")):
                pass
    # the guard cleared: a fresh session opens fine afterwards
    with stats.xprof_trace(str(tmp_path / "c")):
        pass
    assert calls == [("start", str(tmp_path / "a")), ("stop",),
                     ("start", str(tmp_path / "c")), ("stop",)]


def test_xprof_trace_external_session_chained_error(tmp_path, monkeypatch):
    import windflow_tpu.stats as stats

    def boom(d):
        raise RuntimeError("Only one profile may be run at a time.")
    monkeypatch.setattr("jax.profiler.start_trace", boom)
    with pytest.raises(RuntimeError, match="another profiler session") as ei:
        with stats.xprof_trace(str(tmp_path / "x")):
            pass
    assert isinstance(ei.value.__cause__, RuntimeError)
    # the guard did not latch: a later (now-working) session is allowed
    monkeypatch.setattr("jax.profiler.start_trace", lambda d: None)
    monkeypatch.setattr("jax.profiler.stop_trace", lambda: None)
    with stats.xprof_trace(str(tmp_path / "y")):
        pass


# ------------------------------------------------------- bench_trend smoke

def test_bench_trend_reports_failed_rounds(tmp_path):
    """The r01-style failed round (rc=1, parsed=null) is REPORTED, never
    silently skipped; regressions flag against best-so-far."""
    rounds = [
        {"n": 1, "rc": 1, "tail": "Traceback ...\nRuntimeError: boom",
         "parsed": None},
        {"n": 2, "rc": 0, "tail": "",
         "parsed": {"metric": "m", "value": 100.0, "unit": "t/s",
                    "vs_baseline": 1.0}},
        {"n": 3, "rc": 0, "tail": "",
         "parsed": {"metric": "m", "value": 80.0, "unit": "t/s",
                    "vs_baseline": 0.8}},
        {"n": 4, "rc": 0, "tail": "stale capture",
         "parsed": {"metric": "m", "value": 120.0, "unit": "t/s",
                    "stale": True, "staleness_reason": "device down"}},
    ]
    for r in rounds:
        (tmp_path / f"BENCH_r{r['n']:02d}.json").write_text(json.dumps(r))
    (tmp_path / "MULTICHIP_r01.json").write_text(json.dumps(
        {"n_devices": 8, "rc": 124, "ok": False, "skipped": False,
         "tail": ""}))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_trend.py"),
         "--root", str(tmp_path)], capture_output=True, text=True)
    assert r.returncode == 1                  # one regressed round
    out = r.stdout
    assert "| r01 | FAILED" in out and "rc=1" in out and "boom" in out
    assert "| r02 | BEST" in out
    assert "| r03 | REGRESSED" in out and "below best-so-far" in out
    assert "| r04 | STALE" in out            # stale never sets the best
    assert "| r01 | FAILED | 8 | rc=124 (timeout)" in out


def test_bench_trend_on_this_repo_exits_clean():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_trend.py")],
        capture_output=True, text=True)
    assert r.returncode in (0, 1)             # real rounds may regress
    assert "| r01 | FAILED" in r.stdout       # the rc=1 round is visible
