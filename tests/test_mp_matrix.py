"""The mp_test matrix, TPU edition: {Win_Seq, Win_Farm, Key_Farm, Key_FFAT,
Pane_Farm, Win_MapReduce} × {CB, TB} × randomized geometry.

The reference's 36-test mp_test_cpu suite re-runs each topology with random
parallelism degrees in [1,9] and asserts the sink total is invariant
(src/graph_test/test_graph_1.cpp:77-87). The TPU analogue of "parallelism degree" is
execution geometry: batch size and window budgets. Each case runs the same stream
under randomized geometries and asserts identical window results."""

import numpy as np
import pytest
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.operators.win_seq import Win_Seq
from windflow_tpu.operators.win_patterns import (Win_Farm, Key_Farm, Key_FFAT,
                                                 Pane_Farm, Win_MapReduce)

TOTAL, K = 240, 3
rng = np.random.default_rng(7)


def run_case(make_op, batch_size):
    src = wf.Source(lambda i: {"v": ((i * 13) % 23).astype(jnp.float32)},
                    total=TOTAL, num_keys=K)
    results = []

    def cb(view):
        if view is None:
            return
        for k, w, r in zip(view["key"].tolist(), view["id"].tolist(),
                           np.asarray(view["payload"]).tolist()):
            results.append((k, w, round(float(r), 3)))

    ops = make_op()
    if not isinstance(ops, (list, tuple)):
        ops = [ops]
    wf.Pipeline(src, list(ops), wf.Sink(cb), batch_size=batch_size).run()
    return sorted(results)


CASES = {
    "win_seq_cb": lambda: Win_Seq(lambda wid, it: it.sum("v"),
                                  WindowSpec(8, 4, win_type_t.CB), num_keys=K),
    "win_seq_tb": lambda: Win_Seq(lambda wid, it: it.sum("v"),
                                  WindowSpec(12, 6, win_type_t.TB), num_keys=K),
    "win_farm_cb": lambda: Win_Farm(lambda wid, it: it.sum("v"),
                                    WindowSpec(10, 5, win_type_t.CB),
                                    parallelism=4, num_keys=K),
    "key_farm_cb": lambda: Key_Farm(lambda wid, it: it.max("v"),
                                    WindowSpec(6, 3, win_type_t.CB),
                                    parallelism=3, num_keys=K),
    "key_ffat_cb": lambda: Key_FFAT(lambda t: t.v, jnp.add,
                                    spec=WindowSpec(8, 2, win_type_t.CB),
                                    num_keys=K),
    "key_ffat_tb": lambda: Key_FFAT(lambda t: t.v, jnp.add,
                                    spec=WindowSpec(10, 5, win_type_t.TB),
                                    num_keys=K),
    "pane_farm_cb": lambda: Pane_Farm(lambda pid, it: it.sum("v"),
                                      lambda wid, it: it.sum(),
                                      WindowSpec(9, 3, win_type_t.CB), num_keys=K),
    "wmr_cb": lambda: Win_MapReduce(lambda wid, it: it.sum("v"),
                                    lambda wid, it: it.sum(),
                                    WindowSpec(8, 8, win_type_t.CB),
                                    map_parallelism=2, num_keys=K),
    "win_farm_tb": lambda: Win_Farm(lambda wid, it: it.sum("v"),
                                    WindowSpec(12, 4, win_type_t.TB),
                                    parallelism=4, num_keys=K),
    "key_farm_tb": lambda: Key_Farm(lambda wid, it: it.max("v"),
                                    WindowSpec(10, 5, win_type_t.TB),
                                    parallelism=3, num_keys=K),
    "pane_farm_tb": lambda: Pane_Farm(lambda pid, it: it.sum("v"),
                                      lambda wid, it: it.sum(),
                                      WindowSpec(12, 4, win_type_t.TB), num_keys=K),
    "wmr_tb": lambda: Win_MapReduce(lambda wid, it: it.sum("v"),
                                    lambda wid, it: it.sum(),
                                    WindowSpec(12, 12, win_type_t.TB),
                                    map_parallelism=3, num_keys=K),
    "nested_wf_pf_cb": lambda: Win_Farm(
        Pane_Farm(lambda pid, it: it.sum("v"), lambda wid, it: it.sum(),
                  WindowSpec(9, 3, win_type_t.CB), num_keys=K), parallelism=2),
    "nested_kf_wmr_cb": lambda: Key_Farm(
        Win_MapReduce(lambda wid, it: it.sum("v"), lambda wid, it: it.sum(),
                      WindowSpec(8, 8, win_type_t.CB), map_parallelism=2,
                      num_keys=K), parallelism=2),
    # remaining reference nesting combos (test_mp_wf+wmr_*.cpp, test_mp_kf+pf_*.cpp)
    "nested_wf_wmr_cb": lambda: Win_Farm(
        Win_MapReduce(lambda wid, it: it.sum("v"), lambda wid, it: it.sum(),
                      WindowSpec(8, 8, win_type_t.CB), map_parallelism=2,
                      num_keys=K), parallelism=2),
    "nested_kf_pf_cb": lambda: Key_Farm(
        Pane_Farm(lambda pid, it: it.sum("v"), lambda wid, it: it.sum(),
                  WindowSpec(9, 3, win_type_t.CB), num_keys=K), parallelism=2),
    "nested_wf_pf_tb": lambda: Win_Farm(
        Pane_Farm(lambda pid, it: it.sum("v"), lambda wid, it: it.sum(),
                  WindowSpec(12, 4, win_type_t.TB), num_keys=K), parallelism=2),
    # chaining variants (test_mp_*_chaining.cpp): stateless ops fused ahead of
    # the windowed pattern — one compiled program, same results
    "kf_cb_chaining": lambda: [wf.Map(lambda t: {"v": t.v + 1.0}),
                               wf.Filter(lambda t: t.v > 2.0),
                               Key_Farm(lambda wid, it: it.max("v"),
                                        WindowSpec(6, 3, win_type_t.CB),
                                        parallelism=3, num_keys=K)],
    "pf_tb_chaining": lambda: [wf.Map(lambda t: {"v": t.v * 2.0}),
                               Pane_Farm(lambda pid, it: it.sum("v"),
                                         lambda wid, it: it.sum(),
                                         WindowSpec(12, 4, win_type_t.TB),
                                         num_keys=K)],
    "wmr_cb_chaining": lambda: [wf.Filter(lambda t: t.v % 2 == 0),
                                Win_MapReduce(lambda wid, it: it.sum("v"),
                                              lambda wid, it: it.sum(),
                                              WindowSpec(8, 8, win_type_t.CB),
                                              map_parallelism=2, num_keys=K)],
    # remaining nested TB combos (test_mp_kf+pf_tb.cpp, test_mp_kf+wmr_tb.cpp,
    # test_mp_wf+wmr_tb.cpp)
    "nested_kf_pf_tb": lambda: Key_Farm(
        Pane_Farm(lambda pid, it: it.sum("v"), lambda wid, it: it.sum(),
                  WindowSpec(12, 4, win_type_t.TB), num_keys=K), parallelism=2),
    "nested_kf_wmr_tb": lambda: Key_Farm(
        Win_MapReduce(lambda wid, it: it.sum("v"), lambda wid, it: it.sum(),
                      WindowSpec(12, 12, win_type_t.TB), map_parallelism=2,
                      num_keys=K), parallelism=2),
    "nested_wf_wmr_tb": lambda: Win_Farm(
        Win_MapReduce(lambda wid, it: it.sum("v"), lambda wid, it: it.sum(),
                      WindowSpec(12, 12, win_type_t.TB), map_parallelism=3,
                      num_keys=K), parallelism=2),
    # remaining chaining combos (test_mp_wf_cb_chaining.cpp, kf_tb_chaining,
    # pf_cb_chaining, wmr_tb_chaining)
    "wf_cb_chaining": lambda: [wf.Map(lambda t: {"v": t.v + 0.5}),
                               Win_Farm(lambda wid, it: it.sum("v"),
                                        WindowSpec(10, 5, win_type_t.CB),
                                        parallelism=4, num_keys=K)],
    "kf_tb_chaining": lambda: [wf.Filter(lambda t: t.v != 3.0),
                               Key_Farm(lambda wid, it: it.max("v"),
                                        WindowSpec(10, 5, win_type_t.TB),
                                        parallelism=3, num_keys=K)],
    "pf_cb_chaining": lambda: [wf.Map(lambda t: {"v": t.v * 3.0}),
                               Pane_Farm(lambda pid, it: it.sum("v"),
                                         lambda wid, it: it.sum(),
                                         WindowSpec(9, 3, win_type_t.CB),
                                         num_keys=K)],
    "wmr_tb_chaining": lambda: [wf.Filter(lambda t: t.v > 1.0),
                                Win_MapReduce(lambda wid, it: it.sum("v"),
                                              lambda wid, it: it.sum(),
                                              WindowSpec(12, 12, win_type_t.TB),
                                              map_parallelism=2, num_keys=K)],
    # _2 geometry variants (the reference's *_tb_2 files re-run with a second
    # window/slide pair)
    "win_seq_tb_2": lambda: Win_Seq(lambda wid, it: it.sum("v"),
                                    WindowSpec(20, 4, win_type_t.TB), num_keys=K),
    "key_farm_tb_2": lambda: Key_Farm(lambda wid, it: it.max("v"),
                                      WindowSpec(15, 5, win_type_t.TB),
                                      parallelism=3, num_keys=K),
    "pane_farm_tb_2": lambda: Pane_Farm(lambda pid, it: it.sum("v"),
                                        lambda wid, it: it.sum(),
                                        WindowSpec(16, 4, win_type_t.TB),
                                        num_keys=K),
    "wmr_tb_2": lambda: Win_MapReduce(lambda wid, it: it.sum("v"),
                                      lambda wid, it: it.sum(),
                                      WindowSpec(18, 18, win_type_t.TB),
                                      map_parallelism=3, num_keys=K),
}


@pytest.mark.parametrize("case", sorted(CASES))
def test_result_invariance_under_geometry(case):
    make_op = CASES[case]
    sizes = sorted(set([int(rng.integers(16, 120)), 60, TOTAL]))
    runs = [run_case(make_op, bs) for bs in sizes]
    assert runs[0], f"{case}: produced no windows"
    for r, bs in zip(runs[1:], sizes[1:]):
        assert r == runs[0], f"{case}: results differ at batch_size={bs}"


STRING_OPS = {
    "kf_ffat": lambda: Key_FFAT(lambda t: t.v, jnp.add,
                                spec=WindowSpec(8, 4, win_type_t.CB), num_keys=8),
    "key_farm": lambda: Key_Farm(lambda wid, it: it.max("v"),
                                 WindowSpec(6, 3, win_type_t.CB),
                                 parallelism=3, num_keys=8),
    "win_farm": lambda: Win_Farm(lambda wid, it: it.sum("v"),
                                 WindowSpec(10, 5, win_type_t.CB),
                                 parallelism=4, num_keys=8),
    "pane_farm": lambda: Pane_Farm(lambda pid, it: it.sum("v"),
                                   lambda wid, it: it.sum(),
                                   WindowSpec(9, 3, win_type_t.CB), num_keys=8),
    "wmr": lambda: Win_MapReduce(lambda wid, it: it.sum("v"),
                                 lambda wid, it: it.sum(),
                                 WindowSpec(8, 8, win_type_t.CB),
                                 map_parallelism=2, num_keys=8),
}


@pytest.mark.parametrize("op_name", sorted(STRING_OPS))
def test_string_keyed_windows(op_name):
    """The *_string variants (mp_common_string.hpp: kf/pf/wf/wmr over
    string-keyed tuples): non-integer keys hashed to slots at ingest
    (hash(key) % n); window results invariant under batch size and consistent
    per logical key."""
    import jax
    from windflow_tpu.operators.source import GeneratorSource

    names = np.array(["alpha", "beta", "gamma"])

    def run(bs):
        def it():
            for s in range(0, TOTAL, 60):
                i = np.arange(s, s + 60, dtype=np.int32)
                yield ({"v": ((i * 13) % 23).astype(np.float32)},
                       names[i % 3], i)
        src = GeneratorSource(it, {"v": jax.ShapeDtypeStruct((), jnp.float32)},
                              num_keys=8)
        results = []

        def cb(view):
            if view is None:
                return
            results.extend((int(k), int(w), round(float(r), 3))
                           for k, w, r in zip(view["key"].tolist(),
                                              view["id"].tolist(),
                                              np.asarray(view["payload"]).tolist()))
        wf.Pipeline(src, [STRING_OPS[op_name]()],
                    wf.Sink(cb), batch_size=bs).run()
        return sorted(results)

    a, b = run(60), run(120)
    assert a == b and a
    assert len({k for k, _, _ in a}) == 3       # three logical keys, hashed slots
