"""The mp_test matrix, TPU edition: {Win_Seq, Win_Farm, Key_Farm, Key_FFAT,
Pane_Farm, Win_MapReduce} × {CB, TB} × randomized geometry.

The reference's 36-test mp_test_cpu suite re-runs each topology with random
parallelism degrees in [1,9] and asserts the sink total is invariant
(src/graph_test/test_graph_1.cpp:77-87). The TPU analogue of "parallelism degree" is
execution geometry: batch size and window budgets. Each case runs the same stream
under randomized geometries and asserts identical window results."""

import numpy as np
import pytest
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.operators.win_seq import Win_Seq
from windflow_tpu.operators.win_patterns import (Win_Farm, Key_Farm, Key_FFAT,
                                                 Pane_Farm, Win_MapReduce)

TOTAL, K = 240, 3
rng = np.random.default_rng(7)


def run_case(make_op, batch_size):
    src = wf.Source(lambda i: {"v": ((i * 13) % 23).astype(jnp.float32)},
                    total=TOTAL, num_keys=K)
    results = []

    def cb(view):
        if view is None:
            return
        for k, w, r in zip(view["key"].tolist(), view["id"].tolist(),
                           np.asarray(view["payload"]).tolist()):
            results.append((k, w, round(float(r), 3)))

    wf.Pipeline(src, [make_op()], wf.Sink(cb), batch_size=batch_size).run()
    return sorted(results)


CASES = {
    "win_seq_cb": lambda: Win_Seq(lambda wid, it: it.sum("v"),
                                  WindowSpec(8, 4, win_type_t.CB), num_keys=K),
    "win_seq_tb": lambda: Win_Seq(lambda wid, it: it.sum("v"),
                                  WindowSpec(12, 6, win_type_t.TB), num_keys=K),
    "win_farm_cb": lambda: Win_Farm(lambda wid, it: it.sum("v"),
                                    WindowSpec(10, 5, win_type_t.CB),
                                    parallelism=4, num_keys=K),
    "key_farm_cb": lambda: Key_Farm(lambda wid, it: it.max("v"),
                                    WindowSpec(6, 3, win_type_t.CB),
                                    parallelism=3, num_keys=K),
    "key_ffat_cb": lambda: Key_FFAT(lambda t: t.v, jnp.add,
                                    spec=WindowSpec(8, 2, win_type_t.CB),
                                    num_keys=K),
    "key_ffat_tb": lambda: Key_FFAT(lambda t: t.v, jnp.add,
                                    spec=WindowSpec(10, 5, win_type_t.TB),
                                    num_keys=K),
    "pane_farm_cb": lambda: Pane_Farm(lambda pid, it: it.sum("v"),
                                      lambda wid, it: it.sum(),
                                      WindowSpec(9, 3, win_type_t.CB), num_keys=K),
    "wmr_cb": lambda: Win_MapReduce(lambda wid, it: it.sum("v"),
                                    lambda wid, it: it.sum(),
                                    WindowSpec(8, 8, win_type_t.CB),
                                    map_parallelism=2, num_keys=K),
    "win_farm_tb": lambda: Win_Farm(lambda wid, it: it.sum("v"),
                                    WindowSpec(12, 4, win_type_t.TB),
                                    parallelism=4, num_keys=K),
    "key_farm_tb": lambda: Key_Farm(lambda wid, it: it.max("v"),
                                    WindowSpec(10, 5, win_type_t.TB),
                                    parallelism=3, num_keys=K),
    "pane_farm_tb": lambda: Pane_Farm(lambda pid, it: it.sum("v"),
                                      lambda wid, it: it.sum(),
                                      WindowSpec(12, 4, win_type_t.TB), num_keys=K),
    "wmr_tb": lambda: Win_MapReduce(lambda wid, it: it.sum("v"),
                                    lambda wid, it: it.sum(),
                                    WindowSpec(12, 12, win_type_t.TB),
                                    map_parallelism=3, num_keys=K),
    "nested_wf_pf_cb": lambda: Win_Farm(
        Pane_Farm(lambda pid, it: it.sum("v"), lambda wid, it: it.sum(),
                  WindowSpec(9, 3, win_type_t.CB), num_keys=K), parallelism=2),
    "nested_kf_wmr_cb": lambda: Key_Farm(
        Win_MapReduce(lambda wid, it: it.sum("v"), lambda wid, it: it.sum(),
                      WindowSpec(8, 8, win_type_t.CB), map_parallelism=2,
                      num_keys=K), parallelism=2),
}


@pytest.mark.parametrize("case", sorted(CASES))
def test_result_invariance_under_geometry(case):
    make_op = CASES[case]
    sizes = sorted(set([int(rng.integers(16, 120)), 60, TOTAL]))
    runs = [run_case(make_op, bs) for bs in sizes]
    assert runs[0], f"{case}: produced no windows"
    for r, bs in zip(runs[1:], sizes[1:]):
        assert r == runs[0], f"{case}: results differ at batch_size={bs}"
