"""Native runtime tests: SPSC queue correctness under concurrency + threaded
pipeline end-to-end equivalence with the sequential Pipeline."""

import threading

import numpy as np
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.native import SPSCQueue, native_available, hardware_concurrency
from windflow_tpu.runtime.threaded import ThreadedPipeline


def test_native_lib_builds():
    # the toolchain is part of the image; the native ring must be available
    assert native_available()
    assert hardware_concurrency() >= 1


def test_spsc_queue_ordered_transfer():
    q = SPSCQueue(64)
    N = 10_000
    out = []

    def consumer():
        for _ in range(N):
            ok, item = q.pop()
            assert ok
            out.append(item)

    t = threading.Thread(target=consumer)
    t.start()
    for i in range(N):
        q.push(("item", i))
    t.join()
    assert [x[1] for x in out] == list(range(N))


def test_spsc_queue_backpressure():
    q = SPSCQueue(4)
    for i in range(4):
        q.push(i, spin=1)
    assert q.size() >= 4  # full; further pushes would spin (bounded buffer)


def test_threaded_pipeline_matches_sequential():
    total = 2000
    src = wf.Source(lambda i: {"v": (i % 11).astype(jnp.float32)},
                    total=total, num_keys=4)
    seg1 = [wf.Map(lambda t: {"v": t.v * 2.0})]
    seg2 = [wf.Filter(lambda t: t.v > 4.0),
            wf.ReduceSink(lambda t: t.v, name="total")]
    tp = ThreadedPipeline(src, [seg1, seg2], batch_size=128, pin=False)
    res = tp.run()
    expect = sum(v * 2.0 for v in (i % 11 for i in range(total)) if v * 2.0 > 4.0)
    np.testing.assert_allclose(float(res["total"]), expect)


def test_threaded_pipeline_with_windows():
    total, K = 600, 3
    src = wf.Source(lambda i: {"v": (i // K).astype(jnp.float32)},
                    total=total, num_keys=K)
    from windflow_tpu.operators.win_patterns import Key_FFAT
    from windflow_tpu.operators.window import WindowSpec
    got = []

    def cb(view):
        if view is None:
            return
        got.extend(zip(view["key"].tolist(), view["id"].tolist(),
                       np.asarray(view["payload"]).tolist()))

    ff = Key_FFAT(lambda t: t.v, jnp.add, spec=WindowSpec(10, 10), num_keys=K)
    tp = ThreadedPipeline(src, [[ff]], wf.Sink(cb), batch_size=100, pin=False)
    tp.run()
    expect = []
    for k in range(K):
        vals = [float(i // K) for i in range(total) if i % K == k]
        for w in range((len(vals) - 1) // 10 + 1):
            expect.append((k, w, sum(vals[w * 10:(w + 1) * 10])))
    assert sorted(got) == sorted(expect)


def test_queue_selfbench_moves_tokens():
    """The raw C selfbench must complete and report sane throughput (> 1 M
    tokens/s even single-core — short spins + yield batch the handoff)."""
    from windflow_tpu.native import native_available, queue_selfbench
    if not native_available():
        import pytest
        pytest.skip("native library unavailable")
    tps = queue_selfbench(200_000, 1024)
    assert tps > 1e6
