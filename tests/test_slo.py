"""SLO engine (PR 15): burn-rate alerting, the OK->WARN->PAGE state
machine, automatic incident forensic bundles, Reporter retention, the
wf_slo.py CLI contract, and the off-path hermeticity pins (slo= on vs off
byte-identical across all four drivers; compiled programs untouched)."""

import json
import os
import shutil
import subprocess
import sys

import numpy as np
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.nexmark import make_query
from windflow_tpu.observability import (MonitoringConfig, set_journal,
                                        device_health as dh,
                                        slo_engine as slo)
from windflow_tpu.runtime.faults import (FaultPlan, FaultSpec,
                                         reset_counters)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WF_SLO_CLI = os.path.join(REPO, "scripts", "wf_slo.py")
WF_HEALTH_CLI = os.path.join(REPO, "scripts", "wf_health.py")
WF_STATE_CLI = os.path.join(REPO, "scripts", "wf_state.py")

TOTAL = 300


@pytest.fixture(autouse=True)
def _clean_hooks():
    yield
    dh.set_active(None)
    set_journal(None)


def _poisoned_jax_dir(tmp_path):
    d = tmp_path / "nojax"
    d.mkdir(exist_ok=True)
    (d / "jax.py").write_text("raise ImportError('wf_slo must not import "
                              "jax')\n")
    return str(d)


def _lat_spec(**kw):
    base = dict(name="latency", signal="e2e_p99_ms", target=30.0,
                objective=0.5, fast_window=3, slow_window=6,
                warn_burn=1.0, page_burn=2.0)
    base.update(kw)
    return slo.SLOSpec(**base)


def _snap_p99(p99_ms, samples=5):
    """Synthetic snapshot carrying one windowed e2e latency observation."""
    return {"graph": "t", "operators": [],
            "e2e_latency_us": {"p99": p99_ms * 1e3, "p99_tick": p99_ms * 1e3,
                               "samples": samples, "samples_tick": samples}}


# ------------------------------------------------------- registry lockstep


def test_slo_gauges_registry_lockstep():
    from windflow_tpu.observability.metrics import _SLO_HELP
    from windflow_tpu.observability.names import SLO_GAUGES
    assert set(_SLO_HELP) == set(SLO_GAUGES)


def test_slo_events_registered():
    from windflow_tpu.observability.names import JOURNAL_EVENTS
    assert "slo_page" in JOURNAL_EVENTS
    assert "slo_recover" in JOURNAL_EVENTS
    from windflow_tpu.observability.names import RECOVERY_COUNTERS
    assert "recovery_seconds" in RECOVERY_COUNTERS


# --------------------------------------------------------- spec resolution


def test_resolve_specs_forms(tmp_path):
    assert slo.resolve_specs(None) is None
    assert slo.resolve_specs(False) is None
    assert slo.resolve_specs("") is None
    assert slo.resolve_specs("0") is None
    assert [s.name for s in slo.resolve_specs(True)] == \
        [s.name for s in slo.default_specs()]
    assert [s.name for s in slo.resolve_specs("1")] == \
        [s.name for s in slo.default_specs()]
    inline = '[{"name": "x", "signal": "drop_ratio", "target": 0.5}]'
    specs = slo.resolve_specs(inline)
    assert specs[0].name == "x" and specs[0].signal == "drop_ratio"
    p = tmp_path / "specs.json"
    p.write_text(json.dumps({"specs": [{"name": "y",
                                        "signal": "recovery_s",
                                        "target": 2.0}]}))
    assert slo.resolve_specs(str(p))[0].name == "y"
    specs = slo.resolve_specs([_lat_spec(), {"name": "z",
                                             "signal": "retrace_rate",
                                             "target": 0.0}])
    assert [s.name for s in specs] == ["latency", "z"]
    with pytest.raises(ValueError):
        slo.resolve_specs('{"specs": 17}')
    with pytest.raises(ValueError):
        slo.resolve_specs([{"name": "q", "signal": "drop_ratio",
                            "target": 1, "bogus_field": 2}])
    with pytest.raises(ValueError):
        slo.resolve_specs([3])


def test_monitoring_config_env_resolution(monkeypatch):
    monkeypatch.setenv("WF_MONITORING", "1")
    monkeypatch.setenv("WF_SLO", "1")
    assert MonitoringConfig.resolve(None).slo is True
    monkeypatch.setenv("WF_SLO", "0")
    assert MonitoringConfig.resolve(None).slo is False
    monkeypatch.setenv("WF_SLO", '[{"name":"a","signal":"drop_ratio",'
                                 '"target":1}]')
    cfg = MonitoringConfig.resolve(None)
    assert slo.resolve_specs(cfg.slo)[0].name == "a"
    monkeypatch.setenv("WF_SLO_COOLDOWN_S", "7.5")
    monkeypatch.setenv("WF_SLO_MAX_INCIDENTS", "3")
    monkeypatch.setenv("WF_SNAPSHOT_KEEP", "11")
    cfg = MonitoringConfig.resolve(None)
    assert cfg.slo_cooldown_s == 7.5
    assert cfg.slo_max_incidents == 3
    assert cfg.snapshot_keep == 11
    monkeypatch.setenv("WF_SNAPSHOT_KEEP", "0")
    assert MonitoringConfig.resolve(None).snapshot_keep is None
    monkeypatch.setenv("WF_SNAPSHOT_KEEP", "-2")
    with pytest.raises(ValueError):
        MonitoringConfig.resolve(None)


def test_spec_problems():
    assert slo.spec_problems(_lat_spec()) == []
    assert any("unknown signal" in p for p in
               slo.spec_problems(_lat_spec(signal="nope")))
    assert any("fast_window" in p for p in
               slo.spec_problems(_lat_spec(fast_window=6, slow_window=6)))
    assert any("objective" in p for p in
               slo.spec_problems(_lat_spec(objective=1.0)))
    assert any("warn_burn" in p for p in
               slo.spec_problems(_lat_spec(warn_burn=3.0, page_burn=2.0)))
    assert any("mode" in p for p in
               slo.spec_problems(_lat_spec(mode="sideways")))
    with pytest.raises(ValueError):
        slo.SLOEngine([_lat_spec(signal="nope")], out_dir=None)
    with pytest.raises(ValueError):
        slo.SLOEngine([_lat_spec(), _lat_spec()], out_dir=None)  # dup name


# ------------------------------------------------- burn / state machine


def test_transient_spike_warns_sustained_burn_pages():
    """THE multi-window contract: a spike that fills only the fast window
    WARNs and clears; a burn sustained across the slow window PAGEs."""
    eng = slo.SLOEngine([_lat_spec()], out_dir=None, journal=False)
    for _ in range(6):
        eng.observe(_snap_p99(1.0))
    assert eng.report()["latency"]["state"] == "ok"
    # 2-tick transient spike: fast window (3) burns, slow window (6) does
    # not reach page_burn -> WARN, never PAGE
    states = []
    for _ in range(2):
        states.append(eng.observe(_snap_p99(500.0))["slo"]["latency"]
                      ["state"])
    assert states[-1] == "warn"
    for _ in range(4):
        states.append(eng.observe(_snap_p99(1.0))["slo"]["latency"]
                      ["state"])
    assert states[-1] == "ok"
    assert "page" not in states
    # sustained: every tick violating -> both windows saturate -> PAGE
    for _ in range(6):
        st = eng.observe(_snap_p99(500.0))["slo"]["latency"]["state"]
    assert st == "page"
    rep = eng.report()["latency"]
    assert rep["pages"] == 1 and rep["burning"]
    # sticky until the FAST window is clean, then OK + slo_recover
    st = eng.observe(_snap_p99(500.0))["slo"]["latency"]["state"]
    assert st == "page"
    for _ in range(3):
        st = eng.observe(_snap_p99(1.0))["slo"]["latency"]["state"]
    assert st == "ok"
    trs = [(t["from"], t["to"]) for t in eng.report()["latency"]
           ["transitions"]]
    assert ("ok", "warn") in trs and ("page", "ok") in trs


def test_signal_absent_does_not_advance_window():
    """None observations (sub-system off / no traffic) neither violate nor
    clear — the SLO idles in its current state."""
    eng = slo.SLOEngine([_lat_spec()], out_dir=None, journal=False)
    for _ in range(8):
        eng.observe(_snap_p99(500.0))
    assert eng.report()["latency"]["state"] == "page"
    for _ in range(10):
        eng.observe({"graph": "t", "operators": [],
                     "e2e_latency_us": {"p99": 1.0, "samples": 5,
                                        "samples_tick": 0,
                                        "p99_tick": 0.0}})
    assert eng.report()["latency"]["state"] == "page"


def test_min_mode_signal_hbm_headroom():
    spec = slo.SLOSpec("headroom", "hbm_headroom_pct", target=20.0,
                       objective=0.5, fast_window=2, slow_window=4)
    eng = slo.SLOEngine([spec], out_dir=None, journal=False)

    def snap(pct):
        return {"graph": "t", "operators": [],
                "health": {"devices": [{"device": "d0",
                                        "bytes_limit": 100,
                                        "headroom_bytes": int(pct)}]}}
    for _ in range(4):
        eng.observe(snap(50))
    assert eng.report()["headroom"]["state"] == "ok"
    for _ in range(4):
        eng.observe(snap(5))
    assert eng.report()["headroom"]["state"] == "page"


def test_drop_ratio_differences_cumulative_counters():
    spec = slo.SLOSpec("drops", "drop_ratio", target=0.1, objective=0.5,
                       fast_window=2, slow_window=4)
    eng = slo.SLOEngine([spec], out_dir=None, journal=False)

    def snap(dropped, offered):
        return {"graph": "t",
                "operators": [{"name": "op", "inputs_received": offered,
                               "counters": {"overflow_drops": dropped}}],
                "totals": {"tuples_dropped_old": 0}}
    eng.observe(snap(0, 100))
    row = eng.observe(snap(0, 200))["slo"]["drops"]
    assert row["signal"] == 0.0
    # 50 new drops over 100 new offered = 0.5 per-tick ratio, even though
    # the cumulative ratio is only 50/300
    row = eng.observe(snap(50, 300))["slo"]["drops"]
    assert row["signal"] == pytest.approx(0.5)


# --------------------------------------------------- incident forensics


def test_page_capture_cooldown_and_cap(tmp_path):
    """Rate limit under a page storm: one bundle per cooldown window, a
    hard cap per run, every suppression counted — and every bundle commits
    via manifest-last."""
    clock = {"t": 0.0}
    eng = slo.SLOEngine([_lat_spec(fast_window=2, slow_window=4)],
                        out_dir=str(tmp_path), cooldown_s=60.0,
                        max_incidents=2, journal=False,
                        clock=lambda: clock["t"])

    def page_cycle():
        for _ in range(4):
            eng.observe(_snap_p99(500.0))
        for _ in range(2):
            eng.observe(_snap_p99(1.0))

    page_cycle()                      # page 1: captured
    page_cycle()                      # page 2: inside cooldown -> suppressed
    bundles, torn = slo.list_incidents(str(tmp_path))
    assert len(bundles) == 1 and not torn
    assert eng.incidents_suppressed == 1
    clock["t"] = 120.0                # past cooldown
    page_cycle()                      # page 3: captured (cap = 2 reached)
    clock["t"] = 300.0
    page_cycle()                      # page 4: over max_incidents
    bundles, _ = slo.list_incidents(str(tmp_path))
    assert len(bundles) == 2
    assert eng.report()["latency"]["pages"] == 4
    assert eng.incidents_suppressed == 2
    man = bundles[-1]
    assert man["slo"] == "latency" and not man["missing"]
    for fname in man["files"]:
        assert os.path.getsize(os.path.join(man["path"], fname)) > 0
    burn = json.load(open(os.path.join(man["path"], "burn.json")))
    assert burn["slo"] == "latency" and burn["timeline"]
    cfgj = json.load(open(os.path.join(man["path"], "config.json")))
    assert "env" in cfgj


def test_torn_bundle_detected(tmp_path):
    eng = slo.SLOEngine([_lat_spec(fast_window=2, slow_window=4)],
                        out_dir=str(tmp_path), journal=False,
                        clock=lambda: 0.0)
    for _ in range(4):
        eng.observe(_snap_p99(500.0))
    bundles, torn = slo.list_incidents(str(tmp_path))
    assert len(bundles) == 1 and not torn
    # a crash mid-capture = bundle directory without a committed manifest
    os.unlink(os.path.join(bundles[0]["path"], "manifest.json"))
    bundles, torn = slo.list_incidents(str(tmp_path))
    assert not bundles and len(torn) == 1
    summ = slo.incidents_summary(str(tmp_path))
    assert summ["count"] == 0 and summ["torn"] == 1


# --------------------------------------------- THE chaos acceptance loop


def _chaos_run(mon, trace_dir):
    """queue.stall chaos through the monitored threaded driver: a stalled
    phase that saturates both burn windows, then a healthy tail the fast
    window recovers on."""
    spec = [{"name": "latency", "signal": "e2e_p99_ms", "target": 30.0,
             "objective": 0.5, "fast_window": 3, "slow_window": 6,
             "warn_burn": 1.0, "page_burn": 2.0}]
    cfg = MonitoringConfig(out_dir=mon, interval_s=0.02, slo=spec,
                           e2e_sample_every=1)
    plan = FaultPlan([
        FaultSpec("queue.stall", kind="stall", stall_s=0.05,
                  at=list(range(6, 60))),
        FaultSpec("queue.stall", kind="stall", stall_s=0.002,
                  at=list(range(60, 500))),
    ], seed=3)
    src = wf.Source(lambda i: {"v": i.astype(jnp.float32)},
                    total=420 * 32, num_keys=4)
    rows = []
    from windflow_tpu.observability import TraceConfig
    tp = wf.ThreadedPipeline(
        src, [[wf.Map(lambda t: {"v": t.v * 2})]],
        wf.Sink(lambda v: rows.append(0) if v is not None else None),
        batch_size=32, queue_capacity=2, faults=plan, monitoring=cfg,
        trace=TraceConfig(out_dir=trace_dir))
    tp.run()
    return rows


def test_acceptance_queue_stall_pages_and_recovers(tmp_path):
    """THE acceptance loop: an injected queue.stall drives the latency SLO
    OK -> WARN -> PAGE, exactly one cooldown-limited bundle lands with a
    schema-valid Chrome trace + journal tail, and recovery flips
    PAGE -> OK — with the wf_slo.py exit contract 1-on-burning /
    0-after-recovery over the same artifacts."""
    mon = str(tmp_path / "mon")
    rows = _chaos_run(mon, str(tmp_path / "trace"))
    assert len(rows) == 420            # every batch delivered

    series = [json.loads(l) for l in open(os.path.join(mon,
                                                       "snapshots.jsonl"))]
    states = [s["slo"]["latency"]["state"] for s in series if "slo" in s]
    # strictly OK -> WARN -> PAGE -> OK, in order
    assert states[0] == "ok"
    i_warn = states.index("warn")
    i_page = states.index("page")
    assert i_warn < i_page
    assert states[-1] == "ok"
    assert "page" not in states[states.index("ok", i_page):]

    ev = [json.loads(l) for l in open(os.path.join(mon, "events.jsonl"))]
    assert [e["event"] for e in ev if e["event"].startswith("slo_")] == \
        ["slo_page", "slo_recover"]

    # exactly ONE committed bundle (cooldown-limited), fully valid
    bundles, torn = slo.list_incidents(mon)
    assert len(bundles) == 1 and not torn
    man = bundles[0]
    assert man["slo"] == "latency" and not man["missing"]
    assert {"sections.json", "burn.json", "journal_tail.jsonl",
            "trace.json", "config.json"} <= set(man["files"])
    # schema-valid Chrome trace: event list with matched B/E pairs
    chrome = json.load(open(os.path.join(man["path"], "trace.json")))
    evs = chrome["traceEvents"]
    assert isinstance(evs, list) and evs
    b = sum(1 for e in evs if e["ph"] == "B")
    e_ = sum(1 for e in evs if e["ph"] == "E")
    assert b == e_ and b > 0
    assert all("ts" in e for e in evs)
    # journal tail parses line-by-line
    tail = [json.loads(l) for l in
            open(os.path.join(man["path"], "journal_tail.jsonl"))]
    assert tail and all("event" in e for e in tail)
    sections = json.load(open(os.path.join(man["path"], "sections.json")))
    assert sections["slo"]["latency"]["state"] == "page"

    # wf_slo exit contract over the SAME artifacts: a prefix ending inside
    # the burn exits 1; the full recovered series exits 0 — both without
    # jax on the path
    burn_dir = tmp_path / "burnwin"
    burn_dir.mkdir()
    lines = open(os.path.join(mon, "snapshots.jsonl")).readlines()
    with open(burn_dir / "snapshots.jsonl", "w") as f:
        f.writelines(lines[:i_page + 2])
    specf = tmp_path / "spec.json"
    specf.write_text(json.dumps([{
        "name": "latency", "signal": "e2e_p99_ms", "target": 30.0,
        "objective": 0.5, "fast_window": 3, "slow_window": 6,
        "warn_burn": 1.0, "page_burn": 2.0}]))
    env = dict(os.environ, PYTHONPATH=_poisoned_jax_dir(tmp_path))
    out = subprocess.run([sys.executable, WF_SLO_CLI, "--monitoring-dir",
                          str(burn_dir), "--specs", str(specf)],
                         capture_output=True, text=True, env=env)
    assert out.returncode == 1, out.stderr
    assert "BURNING" in out.stdout
    out = subprocess.run([sys.executable, WF_SLO_CLI, "--monitoring-dir",
                          mon, "--specs", str(specf), "--json"],
                         capture_output=True, text=True, env=env)
    assert out.returncode == 0, out.stderr
    data = json.loads(out.stdout)
    assert data["burning"] == []
    assert data["report"]["latency"]["pages"] == 1
    assert len(data["incidents"]) == 1

    # the sibling CLIs cross-reference the forensics
    for cli in (WF_HEALTH_CLI, WF_STATE_CLI):
        out = subprocess.run([sys.executable, cli, "--monitoring-dir", mon,
                              "--json"],
                             capture_output=True, text=True, env=env)
        assert out.returncode == 0, out.stderr
        inc = json.loads(out.stdout)["incidents"]
        assert inc["count"] == 1
        assert inc["last"]["slo"] == "latency"
        out = subprocess.run([sys.executable, cli, "--monitoring-dir", mon,
                              "--report", "incidents"],
                             capture_output=True, text=True, env=env)
        assert out.returncode == 0
        assert "triggered by SLO 'latency'" in out.stdout


# ------------------------------------------------ off-path hermeticity


def run_q3(driver="plain", monitoring=False):
    """The Nexmark enrich-join through one of the four drivers (the
    test_device_health acceptance workload), sink rows returned."""
    src, ops = make_query("q3_enrich_join", TOTAL)
    rows = []

    def cb(view):
        if view is None:
            return
        rows.append((np.asarray(view["key"]).tolist(),
                     np.asarray(view["id"]).tolist(),
                     np.asarray(view["ts"]).tolist()))
    sink = wf.Sink(cb)
    if driver == "plain":
        wf.Pipeline(src, ops, sink, batch_size=64,
                    monitoring=monitoring).run()
    else:
        g = wf.PipeGraph(batch_size=64, monitoring=monitoring)
        mp = g.add_source(src)
        for op in ops:
            mp.add(op)
        mp.add_sink(sink)
        if driver == "graph":
            g.run()
        elif driver == "graph-threaded":
            g.run(threaded=True)
        elif driver == "graph-supervised":
            g.run_supervised(checkpoint_every=2, backoff_base=0.001,
                             backoff_cap=0.01)
    return rows


@pytest.mark.parametrize("driver", ["plain", "graph", "graph-threaded",
                                    "graph-supervised"])
def test_slo_on_results_byte_identical(tmp_path, driver):
    """slo= on must not change a single result byte through any of the four
    drivers — the engine is Reporter-thread work only."""
    base = run_q3(driver)
    cfg = MonitoringConfig(out_dir=str(tmp_path / f"m-{driver}"),
                           interval_s=30.0, slo=True)
    on = run_q3(driver, monitoring=cfg)
    assert on == base


# WF_SLO's program-identity pin (formerly an ad-hoc HLO-text comparison
# here) lives in the shared toggle-OFF fingerprint gate:
# tests/test_program_fingerprint.py, TOGGLES["slo"].


# ------------------------------------------------- windowed e2e latency


def test_e2e_p99_tick_windows_per_snapshot():
    """The per-tick e2e percentile reads ONLY the samples recorded since
    the previous snapshot — the recovery signal the cumulative p99 cannot
    provide."""
    from windflow_tpu.observability import MetricsRegistry
    reg = MetricsRegistry("t")
    for _ in range(20):
        reg.record_e2e(0.500)
    s1 = reg.snapshot()
    assert "samples_tick" not in s1["e2e_latency_us"]   # no prev tick yet
    for _ in range(20):
        reg.record_e2e(0.001)
    s2 = reg.snapshot()
    e2e = s2["e2e_latency_us"]
    assert e2e["samples_tick"] == 20
    # cumulative p99 still remembers the slow phase; the tick p99 is fast
    assert e2e["p99"] > 100e3
    assert e2e["p99_tick"] < 10e3
    s3 = reg.snapshot()
    assert s3["e2e_latency_us"]["samples_tick"] == 0


# --------------------------------------------------- reporter retention


def test_snapshot_keep_rotation(tmp_path):
    from windflow_tpu.observability import MetricsRegistry, Reporter
    reg = MetricsRegistry("t")
    rep = Reporter(reg, str(tmp_path), interval_s=30.0, snapshot_keep=5)
    # amortized rotation: the file is bounded at 2N-1 lines (trim back to
    # N once it reaches 2N — trimming every tick past N would rewrite the
    # whole series per second on a long-running service), and every trim
    # keeps the NEWEST ticks
    for i in range(1, 25):
        rep.emit()
        n = len(open(tmp_path / "snapshots.jsonl").readlines())
        assert n <= 2 * 5 - 1
        # exact sawtooth: grows to 2N-1, trims to N on the 2N-th append
        assert n == (i if i < 10 else 5 + (i - 10) % 5)
    lines = open(tmp_path / "snapshots.jsonl").readlines()
    kept = [json.loads(l) for l in lines]
    assert all(s["graph"] == "t" for s in kept)
    ticks = [s["uptime_s"] for s in kept]
    assert ticks == sorted(ticks)
    # a fresh reporter over the same dir resumes the line count: keeps the
    # bound, never re-grows past 2N-1
    rep2 = Reporter(reg, str(tmp_path), interval_s=30.0, snapshot_keep=5)
    for _ in range(12):
        rep2.emit()
    assert len(open(tmp_path / "snapshots.jsonl").readlines()) <= 2 * 5 - 1
    # unlimited default: no rotation
    rep3 = Reporter(reg, str(tmp_path / "unl"), interval_s=30.0)
    for _ in range(8):
        rep3.emit()
    assert len(open(tmp_path / "unl" / "snapshots.jsonl").readlines()) == 8


def test_reporter_survives_engine_failure(tmp_path, capsys):
    """A broken signal extractor must not kill the tick — but the engine
    whose whole job is alerting must not die SILENTLY either: the snapshot
    records the error + count and the FIRST failure warns on stderr."""
    from windflow_tpu.observability import MetricsRegistry, Reporter

    class _Boom:
        def observe(self, snap):
            raise RuntimeError("bad extractor")

    reg = MetricsRegistry("t")
    rep = Reporter(reg, str(tmp_path), interval_s=30.0, slo_engine=_Boom())
    rep.emit()
    rep.emit()
    assert rep.slo_errors == 2
    with open(tmp_path / "snapshot.json") as f:
        snap = json.load(f)
    assert snap["slo_error"]["count"] == 2
    assert "RuntimeError" in snap["slo_error"]["error"]
    err = capsys.readouterr().err
    assert err.count("burn-rate alerting is degraded") == 1


# ------------------------------------------------------- fleet federation


def test_merge_snapshots_folds_slo_sections():
    a = {"graph": "g", "operators": [],
         "slo": {"latency": {"state": "ok", "code": 0, "burn_fast": 0.2,
                             "burn_slow": 0.1, "signal": 5.0,
                             "target": 30.0, "pages": 0}}}
    b = {"graph": "g", "operators": [],
         "slo": {"latency": {"state": "page", "code": 2, "burn_fast": 3.0,
                             "burn_slow": 2.5, "signal": 80.0,
                             "target": 30.0, "pages": 2}}}
    c = {"graph": "g", "operators": [],
         "slo": {"latency": {"state": "warn", "code": 1, "burn_fast": 1.5,
                             "burn_slow": 0.5, "signal": 40.0,
                             "target": 30.0, "pages": 1}}}
    m = dh.merge_snapshots([a, b, c], hosts=["h0", "h1", "h2"])
    row = m["slo"]["latency"]
    assert row["state"] == "page" and row["code"] == 2    # worst state wins
    assert row["worst_host"] == "h1"
    assert row["burn_fast"] == 3.0 and row["burn_slow"] == 2.5   # MAX
    assert row["pages"] == 3
    assert row["pages_by_host"] == {"h1": 2, "h2": 1}     # host-tagged
    assert row["signal"] == 80.0                  # the worst host's value
    # min-sense signal: the paging host's LOW value must win — a blanket
    # MAX would report the HEALTHIEST host's headroom on a paging row
    d = {"graph": "g", "operators": [],
         "slo": {"headroom": {"state": "page", "code": 2, "burn_fast": 4.0,
                              "burn_slow": 3.0, "signal": 3.0,
                              "target": 10.0, "pages": 1}}}
    e = {"graph": "g", "operators": [],
         "slo": {"headroom": {"state": "ok", "code": 0, "burn_fast": 0.0,
                              "burn_slow": 0.0, "signal": 85.0,
                              "target": 10.0, "pages": 0}}}
    row2 = dh.merge_snapshots([d, e], hosts=["h0", "h1"])["slo"]["headroom"]
    assert row2["signal"] == 3.0 and row2["worst_host"] == "h0"
    assert row2["burn_fast"] == 4.0 and row2["state"] == "page"


# ------------------------------------------- supervisor recovery surface


def test_recovery_seconds_counter_from_restore(tmp_path):
    reset_counters()
    src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=16 * 32,
                    num_keys=4)
    got = []
    p = wf.SupervisedPipeline(
        src, [wf.Map(lambda t: {"v": t.v * 2})],
        wf.Sink(lambda v: got.append(0) if v is not None else None),
        batch_size=32, checkpoint_every=4, max_restarts=3,
        backoff_base=0.0,
        faults=FaultPlan([FaultSpec("chain.step", at=[5])], seed=1))
    p.run()
    from windflow_tpu.runtime import faults as _faults
    c = _faults.counters()
    assert c["restarts"] >= 1
    assert c["recovery_seconds"] > 0.0


# ------------------------------------------------------------ WF116 pins


def test_wf116_env_on_monitoring_off(monkeypatch):
    src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=256,
                    num_keys=4)
    p = wf.Pipeline(src, [wf.Map(lambda t: {"v": t.v})],
                    wf.Sink(lambda v: None), batch_size=64)
    from windflow_tpu.analysis import validate
    monkeypatch.setenv("WF_SLO", "1")
    r = validate(p)
    assert "WF116" in r.codes() and r.errors
    monkeypatch.setenv("WF_MONITORING", "1")
    r = validate(p)
    assert "WF116" not in r.codes()


@pytest.mark.parametrize("bad,frag", [
    ([{"name": "x", "signal": "nope", "target": 1}], "unknown signal"),
    ([{"name": "x", "signal": "e2e_p99_ms", "target": 1,
       "fast_window": 8, "slow_window": 4}], "fast_window"),
    ([{"name": "x", "signal": "e2e_p99_ms", "target": 1},
      {"name": "x", "signal": "drop_ratio", "target": 1}], "duplicate"),
    ("[not json", "does not resolve"),
])
def test_wf116_bad_specs(bad, frag):
    src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=256,
                    num_keys=4)
    from windflow_tpu.analysis import validate
    cfg = MonitoringConfig(slo=bad)
    p = wf.Pipeline(src, [wf.Map(lambda t: {"v": t.v})],
                    wf.Sink(lambda v: None), batch_size=64, monitoring=cfg)
    r = validate(p)
    msgs = [d.message for d in r.diagnostics if d.code == "WF116"]
    assert msgs and any(frag in m for m in msgs), msgs


def test_wf116_in_explain_rules():
    from windflow_tpu.analysis.lint import RULES
    assert "WF116" in RULES and RULES["WF116"][0] == "error"


# ------------------------------------------------------------ CLI pins


def test_wf_slo_exit_2_contracts(tmp_path):
    env = dict(os.environ, PYTHONPATH=_poisoned_jax_dir(tmp_path))
    out = subprocess.run([sys.executable, WF_SLO_CLI, "--monitoring-dir",
                          str(tmp_path / "nope")],
                         capture_output=True, text=True, env=env)
    assert out.returncode == 2
    assert "cannot load snapshots" in out.stderr
    # malformed spec set is a usage error, not a crash
    mon = tmp_path / "m"
    mon.mkdir()
    (mon / "snapshots.jsonl").write_text(
        json.dumps({"graph": "t", "operators": []}) + "\n")
    out = subprocess.run([sys.executable, WF_SLO_CLI, "--monitoring-dir",
                          str(mon), "--specs", "[notjson"],
                         capture_output=True, text=True, env=env)
    assert out.returncode == 2
    assert "cannot resolve" in out.stderr
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps([{"name": "x", "signal": "nope",
                                "target": 1}]))
    out = subprocess.run([sys.executable, WF_SLO_CLI, "--monitoring-dir",
                          str(mon), "--specs", str(bad)],
                         capture_output=True, text=True, env=env)
    assert out.returncode == 2
    assert "WF116" in out.stderr
    # an EMPTY spec set is unusable input (2), never "burning" (1): an
    # automation caller must not read an empty spec file as an incident
    out = subprocess.run([sys.executable, WF_SLO_CLI, "--monitoring-dir",
                          str(mon), "--specs", "[]"],
                         capture_output=True, text=True, env=env)
    assert out.returncode == 2
    assert "empty" in out.stderr
    # duplicate SLO names are a spec typo (2), never "burning" (1)
    dup = json.dumps([{"name": "a", "signal": "e2e_p99_ms", "target": 10},
                      {"name": "a", "signal": "e2e_p99_ms", "target": 20}])
    out = subprocess.run([sys.executable, WF_SLO_CLI, "--monitoring-dir",
                          str(mon), "--specs", dup],
                         capture_output=True, text=True, env=env)
    assert out.returncode == 2
    assert "duplicate" in out.stderr


# ------------------------------------------------------------- bench row


def test_bench_slo_stats():
    sys.path.insert(0, REPO)
    try:
        import bench
        row = bench._slo_stats(total_batches=10, batch=2048)
    finally:
        sys.path.remove(REPO)
    assert row["slos"] == len(slo.default_specs())
    assert row["pages"] == 0
    assert row["worst_burn"] >= 0.0
