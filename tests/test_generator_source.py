"""GeneratorSource (host ingestion) + arbitrary-key hashing — the reference's
string-keyed tuple tests (mp_test_cpu *_str variants) hash user keys to replica
slots; here arbitrary keys hash to key slots at ingest."""

import numpy as np
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.batch import hash_key_to_slot


def test_generator_source_end_to_end():
    K = 4

    def gen():
        rng = np.random.default_rng(0)
        for chunk in range(5):
            n = 40 + chunk
            vals = rng.normal(size=n).astype(np.float32)
            keys = rng.integers(0, K, n).astype(np.int32)
            yield ({"v": vals}, keys, np.arange(n) + chunk * 100)

    spec = {"v": jnp.zeros((), jnp.float32)}
    src = wf.GeneratorSource(gen, spec, name="ingest")
    rsink = wf.ReduceSink(lambda t: jnp.ones((), jnp.int32), name="n")
    res = wf.Pipeline(src, [rsink], batch_size=64).run()
    assert int(res["n"]) == sum(40 + c for c in range(5))


def test_hash_key_to_slot_strings():
    slots = [hash_key_to_slot(k, 8) for k in ("alpha", "beta", "gamma", "alpha")]
    assert all(0 <= s < 8 for s in slots)
    assert slots[0] == slots[3]          # deterministic
    arr = hash_key_to_slot(np.asarray([10, 11, 10], np.int64), 4)
    assert arr[0] == arr[2] and 0 <= int(arr[1]) < 4


def test_hash_key_scalar_array_agree():
    # the scalar and vectorized paths must route a key identically (one key's
    # state must never split across slots)
    for n in (3, 5, 7, 8, 1000):
        for k in (0, 2, 3, 10, 12345, 2**40 + 7):
            assert hash_key_to_slot(k, n) == int(
                hash_key_to_slot(np.asarray([k], np.int64), n)[0]), (k, n)
    # string scalar vs string array; bytes hash like their utf-8 string
    arr = hash_key_to_slot(np.asarray(["alpha", "beta"]), 8)
    assert int(arr[0]) == hash_key_to_slot("alpha", 8)
    assert int(arr[1]) == hash_key_to_slot("beta", 8)
    assert hash_key_to_slot(b"alpha", 8) == hash_key_to_slot("alpha", 8)
    barr = hash_key_to_slot(np.asarray([b"alpha", b"beta"]), 8)
    assert barr.tolist() == arr.tolist()
    # float keys are rejected, not truncated
    import pytest
    with pytest.raises(TypeError, match="float"):
        hash_key_to_slot(np.asarray([1.2, 1.9]), 4)
    # object arrays of (big) ints agree with the scalar int path
    big = 2 ** 70 + 3
    oarr = hash_key_to_slot(np.asarray([big, 5], dtype=object), 8)
    assert int(oarr[0]) == hash_key_to_slot(big, 8)
    assert int(oarr[1]) == hash_key_to_slot(5, 8)


def test_generator_source_string_keys():
    """mp_test *_str parity: string-keyed tuples hashed to slots at ingest."""
    K = 8
    names = np.asarray(["alpha", "beta", "gamma", "delta"])

    def gen():
        for chunk in range(4):
            n = 32
            vals = np.ones(n, np.float32)
            keys = names[np.arange(n) % 4]
            yield ({"v": vals}, keys, np.arange(n) + chunk * n)

    spec = {"v": jnp.zeros((), jnp.float32)}
    src = wf.GeneratorSource(gen, spec, num_keys=K, name="ingest_str")
    acc = wf.Accumulator(lambda t: t.v, num_keys=K)
    seen = {}

    def cb(view):
        if view is None:
            return
        for k, r in zip(view["key"].tolist(),
                        np.asarray(view["payload"]).tolist()):
            seen[k] = max(seen.get(k, 0.0), float(r))

    wf.Pipeline(src, [acc], wf.Sink(cb), batch_size=32).run()
    # 4 distinct string keys -> at most 4 slots, each accumulating 32 ones
    assert sum(seen.values()) == 128.0
    assert len(seen) == len({hash_key_to_slot(s, K) for s in names.tolist()})


def test_generator_source_rejects_raw_string_keys():
    def gen():
        yield ({"v": np.ones(4, np.float32)}, np.asarray(["a", "b", "a", "b"]),
               np.arange(4))

    src = wf.GeneratorSource(gen, {"v": jnp.zeros((), jnp.float32)})
    rsink = wf.ReduceSink(lambda t: jnp.ones((), jnp.int32), name="n")
    import pytest
    with pytest.raises(TypeError, match="num_keys"):
        wf.Pipeline(src, [rsink], batch_size=8).run()


def test_nesting_rejects_extra_args():
    import pytest
    from windflow_tpu.basic import win_type_t
    from windflow_tpu.operators.window import WindowSpec
    from windflow_tpu.runtime.builders import KeyFarm_Builder

    spec = WindowSpec(6, 2, win_type_t.CB)
    pf = wf.Pane_Farm(lambda p, i: i.sum("v"), lambda w, i: i.sum(), spec,
                      num_keys=3)
    with pytest.raises(TypeError, match="nesting accepts only"):
        wf.Win_Farm(pf, WindowSpec(99, 1, win_type_t.CB), parallelism=2)
    with pytest.raises(TypeError, match="num_keys"):
        wf.Key_Farm(pf, num_keys=77)
    with pytest.raises(TypeError, match="withCB/TBWindows"):
        KeyFarm_Builder(pf).withCBWindows(10, 10).build()
    with pytest.raises(TypeError, match="num_keys"):
        KeyFarm_Builder(pf).withKeys(9).build()     # extras rejected by the ctor
