"""GeneratorSource (host ingestion) + arbitrary-key hashing — the reference's
string-keyed tuple tests (mp_test_cpu *_str variants) hash user keys to replica
slots; here arbitrary keys hash to key slots at ingest."""

import numpy as np
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.batch import hash_key_to_slot


def test_generator_source_end_to_end():
    K = 4

    def gen():
        rng = np.random.default_rng(0)
        for chunk in range(5):
            n = 40 + chunk
            vals = rng.normal(size=n).astype(np.float32)
            keys = rng.integers(0, K, n).astype(np.int32)
            yield ({"v": vals}, keys, np.arange(n) + chunk * 100)

    spec = {"v": jnp.zeros((), jnp.float32)}
    src = wf.GeneratorSource(gen, spec, name="ingest")
    rsink = wf.ReduceSink(lambda t: jnp.ones((), jnp.int32), name="n")
    res = wf.Pipeline(src, [rsink], batch_size=64).run()
    assert int(res["n"]) == sum(40 + c for c in range(5))


def test_hash_key_to_slot_strings():
    slots = [hash_key_to_slot(k, 8) for k in ("alpha", "beta", "gamma", "alpha")]
    assert all(0 <= s < 8 for s in slots)
    assert slots[0] == slots[3]          # deterministic
    arr = hash_key_to_slot(np.asarray([10, 11, 10], np.int64), 4)
    assert arr[0] == arr[2] and 0 <= int(arr[1]) < 4
