"""Checkpoint/resume: a windowed pipeline interrupted mid-stream and resumed from an
.npz checkpoint must produce the same results as an uninterrupted run (a capability
the reference lacks entirely — SURVEY §5 'Checkpoint/resume: absent')."""

import numpy as np
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.operators.win_patterns import Key_FFAT
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.runtime.pipeline import CompiledChain
from windflow_tpu.runtime.checkpoint import save_chain, load_chain


def _collect(outs):
    acc = []
    for o in outs:
        import jax
        o = jax.tree.map(np.asarray, o)
        v = o.valid
        acc.extend(zip(o.key[v].tolist(), o.id[v].tolist(),
                       np.asarray(o.payload)[v].tolist()))
    return sorted(acc)


def test_checkpoint_resume_windowed(tmp_path):
    total, K, C = 600, 3, 64
    src = wf.Source(lambda i: {"v": (i % 9).astype(jnp.float32)},
                    total=total, num_keys=K)
    mk = lambda: [Key_FFAT(lambda t: t.v, jnp.add, spec=WindowSpec(20, 20),
                           num_keys=K)]
    batches = list(src.batches(C))

    # uninterrupted run
    c0 = CompiledChain(mk(), src.payload_spec(), batch_capacity=C)
    outs = [c0.push(b) for b in batches] + c0.flush()
    expect = _collect(outs)

    # run half, checkpoint, restore into a FRESH chain, run the rest
    half = len(batches) // 2
    c1 = CompiledChain(mk(), src.payload_spec(), batch_capacity=C)
    outs_a = [c1.push(b) for b in batches[:half]]
    ckpt = str(tmp_path / "state.npz")
    save_chain(c1, ckpt, meta={"next_batch": half})
    c2 = CompiledChain(mk(), src.payload_spec(), batch_capacity=C)
    meta = load_chain(c2, ckpt)
    assert meta["next_batch"] == half
    outs_b = [c2.push(b) for b in batches[half:]] + c2.flush()
    assert _collect(outs_a + outs_b) == expect
