"""Checkpoint/resume: a windowed pipeline interrupted mid-stream and resumed from an
.npz checkpoint must produce the same results as an uninterrupted run (a capability
the reference lacks entirely — SURVEY §5 'Checkpoint/resume: absent')."""

import numpy as np
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.operators.win_patterns import Key_FFAT
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.runtime.pipeline import CompiledChain
from windflow_tpu.runtime.checkpoint import save_chain, load_chain


def _collect(outs):
    acc = []
    for o in outs:
        import jax
        o = jax.tree.map(np.asarray, o)
        v = o.valid
        acc.extend(zip(o.key[v].tolist(), o.id[v].tolist(),
                       np.asarray(o.payload)[v].tolist()))
    return sorted(acc)


def test_checkpoint_resume_windowed(tmp_path):
    total, K, C = 600, 3, 64
    src = wf.Source(lambda i: {"v": (i % 9).astype(jnp.float32)},
                    total=total, num_keys=K)
    mk = lambda: [Key_FFAT(lambda t: t.v, jnp.add, spec=WindowSpec(20, 20),
                           num_keys=K)]
    batches = list(src.batches(C))

    # uninterrupted run
    c0 = CompiledChain(mk(), src.payload_spec(), batch_capacity=C)
    outs = [c0.push(b) for b in batches] + c0.flush()
    expect = _collect(outs)

    # run half, checkpoint, restore into a FRESH chain, run the rest
    half = len(batches) // 2
    c1 = CompiledChain(mk(), src.payload_spec(), batch_capacity=C)
    outs_a = [c1.push(b) for b in batches[:half]]
    ckpt = str(tmp_path / "state.npz")
    save_chain(c1, ckpt, meta={"next_batch": half})
    c2 = CompiledChain(mk(), src.payload_spec(), batch_capacity=C)
    meta = load_chain(c2, ckpt)
    assert meta["next_batch"] == half
    outs_b = [c2.push(b) for b in batches[half:]] + c2.flush()
    assert _collect(outs_a + outs_b) == expect


def test_checkpoint_rescale_across_meshes(tmp_path):
    """Elastic rescaling: a pipeline checkpointed while sharded over 8 devices
    restores onto a 4-device mesh (and vice versa) and continues bit-identically
    — checkpoints store unsharded state; ShardedChain re-places it on load."""
    import jax
    from windflow_tpu.parallel import make_mesh, ShardedChain

    total, K, C = 480, 8, 96
    src = wf.Source(lambda i: {"v": (i % 11).astype(jnp.float32)},
                    total=total, num_keys=K)
    mk = lambda: [Key_FFAT(lambda t: t.v, jnp.add, spec=WindowSpec(16, 16),
                           num_keys=K)]
    batches = list(src.batches(C))

    c0 = CompiledChain(mk(), src.payload_spec(), batch_capacity=C)
    expect = _collect([c0.push(b) for b in batches] + c0.flush())

    half = len(batches) // 2
    c8 = CompiledChain(mk(), src.payload_spec(), batch_capacity=C)
    s8 = ShardedChain(c8, make_mesh(8))
    outs_a = [s8.push(b) for b in batches[:half]]
    ckpt = str(tmp_path / "rescale.npz")
    save_chain(c8, ckpt, meta={"next_batch": half})

    c4 = CompiledChain(mk(), src.payload_spec(), batch_capacity=C)
    meta = load_chain(c4, ckpt)
    s4 = ShardedChain(c4, make_mesh(4))      # HALF the devices
    assert meta["next_batch"] == half
    outs_b = [s4.push(b) for b in batches[half:]] + s4.flush()
    assert _collect(outs_a + outs_b) == expect

    # key table re-placed over the 4-device mesh
    leaves = [l for l in jax.tree.leaves(c4.states[0])
              if getattr(l, "ndim", 0) >= 1 and l.shape[0] == K]
    assert leaves and len({s.device for s in leaves[0].addressable_shards}) == 4
