"""Checkpoint/resume: a windowed pipeline interrupted mid-stream and resumed from an
.npz checkpoint must produce the same results as an uninterrupted run (a capability
the reference lacks entirely — SURVEY §5 'Checkpoint/resume: absent')."""

import numpy as np
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.operators.win_patterns import Key_FFAT
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.runtime.pipeline import CompiledChain
from windflow_tpu.runtime.checkpoint import save_chain, load_chain


def _collect(outs):
    acc = []
    for o in outs:
        import jax
        o = jax.tree.map(np.asarray, o)
        v = o.valid
        acc.extend(zip(o.key[v].tolist(), o.id[v].tolist(),
                       np.asarray(o.payload)[v].tolist()))
    return sorted(acc)


def test_checkpoint_resume_windowed(tmp_path):
    total, K, C = 600, 3, 64
    src = wf.Source(lambda i: {"v": (i % 9).astype(jnp.float32)},
                    total=total, num_keys=K)
    mk = lambda: [Key_FFAT(lambda t: t.v, jnp.add, spec=WindowSpec(20, 20),
                           num_keys=K)]
    batches = list(src.batches(C))

    # uninterrupted run
    c0 = CompiledChain(mk(), src.payload_spec(), batch_capacity=C)
    outs = [c0.push(b) for b in batches] + c0.flush()
    expect = _collect(outs)

    # run half, checkpoint, restore into a FRESH chain, run the rest
    half = len(batches) // 2
    c1 = CompiledChain(mk(), src.payload_spec(), batch_capacity=C)
    outs_a = [c1.push(b) for b in batches[:half]]
    ckpt = str(tmp_path / "state.npz")
    save_chain(c1, ckpt, meta={"next_batch": half})
    c2 = CompiledChain(mk(), src.payload_spec(), batch_capacity=C)
    meta = load_chain(c2, ckpt)
    assert meta["next_batch"] == half
    outs_b = [c2.push(b) for b in batches[half:]] + c2.flush()
    assert _collect(outs_a + outs_b) == expect


def test_checkpoint_rescale_across_meshes(tmp_path):
    """Elastic rescaling: a pipeline checkpointed while sharded over 8 devices
    restores onto a 4-device mesh (and vice versa) and continues bit-identically
    — checkpoints store unsharded state; ShardedChain re-places it on load."""
    import jax
    from windflow_tpu.parallel import make_mesh, ShardedChain

    total, K, C = 480, 8, 96
    src = wf.Source(lambda i: {"v": (i % 11).astype(jnp.float32)},
                    total=total, num_keys=K)
    mk = lambda: [Key_FFAT(lambda t: t.v, jnp.add, spec=WindowSpec(16, 16),
                           num_keys=K)]
    batches = list(src.batches(C))

    c0 = CompiledChain(mk(), src.payload_spec(), batch_capacity=C)
    expect = _collect([c0.push(b) for b in batches] + c0.flush())

    half = len(batches) // 2
    c8 = CompiledChain(mk(), src.payload_spec(), batch_capacity=C)
    s8 = ShardedChain(c8, make_mesh(8))
    outs_a = [s8.push(b) for b in batches[:half]]
    ckpt = str(tmp_path / "rescale.npz")
    save_chain(c8, ckpt, meta={"next_batch": half})

    c4 = CompiledChain(mk(), src.payload_spec(), batch_capacity=C)
    meta = load_chain(c4, ckpt)
    s4 = ShardedChain(c4, make_mesh(4))      # HALF the devices
    assert meta["next_batch"] == half
    outs_b = [s4.push(b) for b in batches[half:]] + s4.flush()
    assert _collect(outs_a + outs_b) == expect

    # key table re-placed over the 4-device mesh
    leaves = [l for l in jax.tree.leaves(c4.states[0])
              if getattr(l, "ndim", 0) >= 1 and l.shape[0] == K]
    assert leaves and len({s.device for s in leaves[0].addressable_shards}) == 4


def test_load_chain_legacy_checkpoint_missing_trailing_leaves(tmp_path):
    """A checkpoint written before a state dataclass grew a trailing field
    (Win_SeqFFAT.dropped_old) restores with the missing leaves at their
    freshly-initialized values instead of raising KeyError."""
    import numpy as np
    import windflow_tpu as wf
    from windflow_tpu.basic import win_type_t
    from windflow_tpu.operators.source import DeviceSource
    from windflow_tpu.runtime.pipeline import CompiledChain

    def mk_chain():
        src = DeviceSource(lambda i: {"v": (i % 7).astype(jnp.float32)},
                           total=512, num_keys=4)
        op = wf.Win_SeqFFAT(lambda t: 1, jnp.add,
                            spec=wf.WindowSpec(8, 8, win_type_t.TB),
                            num_keys=4, pane_capacity=64)
        return src, CompiledChain([op], src.payload_spec(), batch_capacity=64)

    src, c1 = mk_chain()
    for b in src.batches(64):
        c1.push(b)
        break
    ckpt = str(tmp_path / "legacy.npz")
    save_chain(c1, ckpt, meta={"v": 1})
    # simulate the pre-dropped_old format: strip the trailing leaf
    data = dict(np.load(ckpt))
    n_leaves = len([k for k in data if k.startswith("op0_leaf")])
    del data[f"op0_leaf{n_leaves - 1}"]
    np.savez(ckpt, **data)

    _, c2 = mk_chain()
    meta = load_chain(c2, ckpt)
    assert meta == {"v": 1}
    st = c2.states[0]
    assert int(np.asarray(st.dropped_old)) == 0          # defaulted, not KeyError
    np.testing.assert_array_equal(np.asarray(st.cnt),
                                  np.asarray(c1.states[0].cnt))


def test_load_chain_gap_in_leaves_still_raises(tmp_path):
    """Only a missing TRAILING suffix is tolerated (legacy grown field); a gap
    — missing leaf with later leaves present — is a mismatched/truncated
    checkpoint and must stay a loud error, not a silent partial restore."""
    import numpy as np
    import pytest
    import windflow_tpu as wf
    from windflow_tpu.operators.source import DeviceSource
    from windflow_tpu.runtime.pipeline import CompiledChain

    from windflow_tpu.basic import win_type_t
    src = DeviceSource(lambda i: {"v": (i % 7).astype(jnp.float32)},
                       total=512, num_keys=4)
    op = wf.Win_SeqFFAT(lambda t: 1, jnp.add,
                        spec=wf.WindowSpec(8, 8, win_type_t.TB),
                        num_keys=4, pane_capacity=64)
    chain = CompiledChain([op], src.payload_spec(), batch_capacity=64)
    ckpt = str(tmp_path / "gap.npz")
    save_chain(chain, ckpt)
    data = dict(np.load(ckpt))
    assert "op0_leaf2" in data          # multi-leaf state: gap constructible
    del data["op0_leaf0"]               # drop leaf 0, keep later leaves
    np.savez(ckpt, **data)
    with pytest.raises(KeyError, match="missing op0_leaf0"):
        load_chain(chain, ckpt)


def test_save_load_path_without_npz_suffix(tmp_path):
    """np.savez appends .npz when the suffix is missing; the pre-fix code
    resolved the path only on save, so save_chain('ckpt') + load_chain('ckpt')
    disagreed. Both now resolve through checkpoint.resolve_path."""
    import windflow_tpu as wf
    src = wf.Source(lambda i: {"v": (i % 5).astype(jnp.float32)},
                    total=128, num_keys=2)
    mk = lambda: CompiledChain(
        [Key_FFAT(lambda t: t.v, jnp.add, spec=WindowSpec(8, 8), num_keys=2)],
        src.payload_spec(), batch_capacity=32)
    c1 = mk()
    for b in src.batches(32):
        c1.push(b)
        break
    stem = str(tmp_path / "ckpt")             # NO .npz suffix
    written = save_chain(c1, stem, meta={"k": 9})
    assert written.endswith("ckpt.npz")
    c2 = mk()
    assert load_chain(c2, stem) == {"k": 9}   # same suffix-free path


def test_checksum_detects_corruption(tmp_path):
    """Flipped bytes inside a stored array fail the per-array sha256 and raise
    CheckpointCorrupt instead of silently restoring garbage."""
    import pytest
    from windflow_tpu.runtime.checkpoint import CheckpointCorrupt
    import windflow_tpu as wf
    src = wf.Source(lambda i: {"v": (i % 5).astype(jnp.float32)},
                    total=256, num_keys=2)
    mk = lambda: CompiledChain(
        [Key_FFAT(lambda t: t.v, jnp.add, spec=WindowSpec(8, 8), num_keys=2)],
        src.payload_spec(), batch_capacity=64)
    c1 = mk()
    for b in src.batches(64):
        c1.push(b)
    ckpt = str(tmp_path / "c.npz")
    save_chain(c1, ckpt)
    data = dict(np.load(ckpt))
    key = next(k for k in data if k.startswith("op0_leaf")
               and data[k].size > 4 and data[k].dtype.kind == "f")
    data[key] = data[key].copy()
    data[key].flat[1] += 1234.5               # bit rot
    np.savez(ckpt, **data)
    with pytest.raises(CheckpointCorrupt, match="sha256"):
        load_chain(mk(), ckpt)


def test_lineage_falls_back_to_newest_valid(tmp_path):
    """keep=K lineage: a torn/corrupt NEWEST checkpoint restores from the
    previous valid one (journaled fallback); when every entry is bad the
    restore fails loudly."""
    import pytest
    from windflow_tpu.runtime import faults as faults_mod
    from windflow_tpu.runtime.checkpoint import (CheckpointCorrupt,
                                                 manifest_path, _read_manifest)
    import windflow_tpu as wf
    faults_mod.reset_counters()
    src = wf.Source(lambda i: {"v": (i % 5).astype(jnp.float32)},
                    total=256, num_keys=2)
    mk = lambda: CompiledChain(
        [Key_FFAT(lambda t: t.v, jnp.add, spec=WindowSpec(8, 8), num_keys=2)],
        src.payload_spec(), batch_capacity=64)
    c1 = mk()
    stem = str(tmp_path / "lin.npz")
    files = []
    for n, b in enumerate(src.batches(64)):
        c1.push(b)
        files.append(save_chain(c1, stem, meta={"n": n}, keep=2))
    man = _read_manifest(manifest_path(stem))
    assert len(man["entries"]) == 2           # pruned to keep
    import os
    assert not os.path.exists(files[0])       # oldest rotated out
    # torn newest: truncate to half
    raw = open(files[-1], "rb").read()
    open(files[-1], "wb").write(raw[:len(raw) // 2])
    c2 = mk()
    meta = load_chain(c2, stem)
    assert meta == {"n": len(files) - 2}      # previous commit restored
    ctr = faults_mod.counters()
    assert ctr["checkpoint_corrupt_skipped"] >= 1
    assert ctr["checkpoint_fallbacks"] >= 1
    # every entry torn -> loud failure
    raw2 = open(files[-2], "rb").read()
    open(files[-2], "wb").write(raw2[:len(raw2) // 2])
    with pytest.raises(CheckpointCorrupt, match="no valid checkpoint"):
        load_chain(mk(), stem)
