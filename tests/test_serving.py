"""Serving front-end (PR 18): WFS1 framing (resync on torn/garbage bytes,
length-lie rejection, endpoint grammar), SocketSource over a REAL TCP socket
(per-tenant seq dedup, peer-kill + overlap re-send degrading to replay, the
supervised replay ring's gap re-drive and loud under-sized refusal),
FileTailSource, ServingRuntime (tenant isolation — a noisy tenant sheds
under ITS bucket while the quiet tenant is never touched; live graph
hot-swap under load staying oracle-exact; wire-swap rejection), the WF119
validator + constructor mirror, the gauge/help lockstep, tenant-labelled
SLO signals, and the wf_serve CLI contract."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.analysis import validate
from windflow_tpu.observability import slo as slo_mod
from windflow_tpu.observability.names import (JOURNAL_EVENTS, SERVING_GAUGES,
                                              TENANT_GAUGES)
from windflow_tpu.observability import metrics as metrics_mod
from windflow_tpu.serving import (FileTailSource, RecordClient,
                                  RecordFrameDecoder, ServingConfig,
                                  ServingRuntime, SocketSource, TenantSpec,
                                  encode_record_frame)
from windflow_tpu.serving import framing as framing_mod
from windflow_tpu.serving.config import serving_problems
from windflow_tpu.serving.tenants import (build_registry, registry_problems,
                                          resolve_tenants)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BATCH = 32
DT = np.dtype([("key", np.int32), ("ts", np.int64), ("v", np.float32)])


def _chunks(n, base=0.0, batch=BATCH):
    out = []
    for i in range(n):
        rec = np.zeros(batch, dtype=DT)
        rec["key"] = np.arange(batch) % 4
        rec["ts"] = np.arange(i * batch, (i + 1) * batch)
        rec["v"] = base + np.arange(i * batch, (i + 1) * batch,
                                    dtype=np.float32)
        out.append(rec)
    return out


def _ops():
    return [wf.Map(lambda t: {"v": t.v * 2.0 + 1.0})]


def _collect(acc):
    def cb(view):
        if view is not None:
            acc.extend(zip(view["id"].tolist(),
                           np.asarray(view["payload"]["v"]).tolist()))
    return cb


def _oracle(chunks):
    out = []
    wf.Pipeline(wf.RecordSource(lambda: iter(chunks), DT, key_field="key",
                                ts_field="ts", num_keys=4),
                _ops(), wf.Sink(_collect(out)), batch_size=BATCH).run()
    return out


# ---------------------------------------------------------------- framing


def test_frame_roundtrip_byte_by_byte():
    rec = _chunks(1)[0].tobytes()
    wire = encode_record_frame(rec, tenant="a", seq=7)
    dec = RecordFrameDecoder()
    got = []
    for i in range(len(wire)):          # worst-case torn delivery
        got += dec.feed(wire[i:i + 1])
    assert len(got) == 1
    meta, blob = got[0]
    assert meta["tenant"] == "a" and meta["seq"] == 7 \
        and meta["kind"] == "data" and meta["nbytes"] == len(rec)
    assert blob == rec
    assert dec.frames_decoded == 1 and dec.frames_torn == 0


def test_decoder_resyncs_through_garbage_and_truncation():
    rec = _chunks(1)[0].tobytes()
    a = encode_record_frame(rec, tenant="a", seq=0)
    b = encode_record_frame(rec, tenant="b", seq=0)
    # garbage, an intact frame, a frame cut mid-payload, another intact one
    wire = b"NOT A FRAME " * 4 + a + b[:len(b) // 2] + a[:10] + b
    dec = RecordFrameDecoder()
    got = dec.feed(wire)
    assert [m["tenant"] for m, _ in got] == ["a", "b"]
    assert all(blob == rec for _, blob in got)
    assert dec.frames_torn >= 2


def test_decoder_rejects_lying_nbytes_then_recovers():
    rec = b"x" * 40
    liar = bytearray(encode_record_frame(rec, tenant="a", seq=0))
    # corrupt the meta's nbytes without touching the frame length
    liar = bytes(liar).replace(b'"nbytes": 40', b'"nbytes": 39')
    good = encode_record_frame(rec, tenant="b", seq=0)
    dec = RecordFrameDecoder()
    got = dec.feed(liar + good)
    assert [m["tenant"] for m, _ in got] == ["b"]
    assert dec.frames_torn == 1


def test_decoder_treats_non_numeric_meta_fields_as_torn():
    """A well-formed frame whose meta carries a non-numeric nbytes/seq is a
    TORN frame, never an exception out of feed() — one malicious frame must
    not kill the client connection loop (the resync contract)."""
    def raw(meta, records=b""):
        payload = json.dumps(meta).encode() + b"\n" + records
        return (framing_mod.MAGIC + b"%08x" % len(payload) + b"\n"
                + payload + b"\n")
    good = encode_record_frame(b"ok", tenant="b", seq=0)
    wire = (raw({"kind": "data", "nbytes": None, "seq": 0}, b"xyz")
            + raw({"kind": "data", "nbytes": "bogus", "seq": 0}, b"xyz")
            + raw({"kind": "data", "nbytes": 3, "seq": [1]}, b"xyz")
            + good)
    dec = RecordFrameDecoder()
    got = dec.feed(wire)                # must not raise
    assert [m["tenant"] for m, _ in got] == ["b"]
    assert dec.frames_torn == 3 and dec.frames_decoded == 1


def test_parse_endpoint_grammar():
    pe = framing_mod.parse_endpoint
    assert pe("tcp://127.0.0.1:9500") == ("tcp", "127.0.0.1", 9500)
    assert pe("127.0.0.1:0") == ("tcp", "127.0.0.1", 0)
    assert pe("unix:///tmp/wf.sock") == ("unix", "/tmp/wf.sock")
    assert pe("unix:/tmp/wf.sock") == ("unix", "/tmp/wf.sock")
    for bad in ("", "tcp://nohost", "tcp://h:notaport", "tcp://h:99999",
                "unix://"):
        with pytest.raises(ValueError):
            pe(bad)


# ----------------------------------------------------------- socket source


def _drain(src, out):
    """Consume src.batches on this thread into out (chunk value lists)."""
    for b in src.batches(BATCH):
        v = np.asarray(b.payload["v"])[np.asarray(b.valid)]
        out.append((src.last_tenant, v.tolist()))


def test_socket_source_dedup_torn_and_eos():
    chunks = _chunks(3)
    src = SocketSource("tcp://127.0.0.1:0", DT, key_field="key",
                       ts_field="ts", num_keys=4).start()
    client = RecordClient(src.endpoint)
    client.send(chunks[0].tobytes(), tenant="a")
    client.send_garbage(b"GARBAGE IN THE STREAM " * 3)
    client.send(chunks[1].tobytes(), tenant="b")
    client.send(chunks[0].tobytes(), tenant="a", seq=0)   # dup: dropped
    client.send(chunks[2].tobytes(), tenant="a")
    client.send_eos("a")
    client.close()
    got = []
    _drain(src, got)
    src.close()
    assert [t for t, _ in got] == ["a", "b", "a"]
    assert got[0][1] == chunks[0]["v"].tolist()
    assert got[2][1] == chunks[2]["v"].tolist()
    assert src.frames_dup == 1 and src.frames_torn >= 1
    assert src.clients_seen == 1


def test_peer_kill_overlap_resend_degrades_to_replay():
    chunks = _chunks(6)
    src = SocketSource("tcp://127.0.0.1:0", DT, key_field="key",
                       ts_field="ts", num_keys=4, replay=32).start()
    client = RecordClient(src.endpoint)
    sent = []
    for c in chunks[:3]:
        sent.append((client.send(c.tobytes(), tenant="a"), c.tobytes()))
    client.kill()
    # wait for the killed connection's thread to finish draining
    last = -1
    for _ in range(100):
        cur = src.frames_decoded + src.frames_torn + src.frames_dup
        if cur == last:
            break
        last = cur
        time.sleep(0.05)
    client.reconnect()
    for seq, blob in sent:              # unacked-tail re-send: all overlap
        client.send(blob, tenant="a", seq=seq)
    for c in chunks[3:]:
        client.send(c.tobytes(), tenant="a")
    client.send_eos("a")
    client.close()
    got = []
    _drain(src, got)
    src.close()
    assert [v for _, v in got] == [c["v"].tolist() for c in chunks]
    assert src.frames_dup >= 1          # the overlap was deduped, not lost


def test_replay_ring_resume_redrives_gap_and_refuses_undersized():
    chunks = _chunks(5)
    src = SocketSource("tcp://127.0.0.1:0", DT, key_field="key",
                       ts_field="ts", num_keys=4, replay=8).start()
    client = RecordClient(src.endpoint)
    for c in chunks:
        client.send(c.tobytes(), tenant="a")
    client.send_eos("a")
    client.close()
    # let all frames land in the ring before resuming
    for _ in range(200):
        with src._lock:
            if src._next_chunk == len(chunks):
                break
        time.sleep(0.01)
    got = [rec["v"].tolist() for rec in src._chunks_from_ring(from_batch=2)]
    assert got == [c["v"].tolist() for c in chunks[2:]]
    src.close()

    tight = SocketSource("tcp://127.0.0.1:0", DT, key_field="key",
                         ts_field="ts", num_keys=4, replay=2).start()
    client = RecordClient(tight.endpoint)
    for c in chunks:
        client.send(c.tobytes(), tenant="a")
    client.send_eos("a")
    client.close()
    for _ in range(200):
        with tight._lock:
            if tight._next_chunk == len(chunks):
                break
        time.sleep(0.01)
    with pytest.raises(RuntimeError, match="replay ring starts at"):
        next(tight._chunks_from_ring(from_batch=1))
    tight.close()


def test_file_tail_source_follows_appends(tmp_path):
    chunks = _chunks(4)
    path = str(tmp_path / "records.bin")
    open(path, "wb").close()

    def writer():
        with open(path, "ab") as f:
            for c in chunks:
                f.write(c.tobytes())
                f.flush()
                time.sleep(0.02)
        open(path + ".eos", "w").close()

    t = threading.Thread(target=writer)
    t.start()
    got = []
    src = FileTailSource(path, DT, batch_records=BATCH, key_field="key",
                         ts_field="ts", num_keys=4, poll_s=0.005)
    wf.Pipeline(src, _ops(), wf.Sink(_collect(got)), batch_size=BATCH).run()
    t.join()
    assert sorted(got) == sorted(_oracle(chunks)) and got


# ---------------------------------------------------------------- runtime


def _serve(tmp_path, tenants, chunks, tenant_of, *, swap=None,
           eos_tenant="a", register=("v2",)):
    mon = str(tmp_path / "mon")
    got = []
    src = SocketSource("tcp://127.0.0.1:0", DT, key_field="key",
                       ts_field="ts", num_keys=4, replay=len(chunks) + 8)
    rt = ServingRuntime(src, _ops(), wf.Sink(_collect(got)),
                        batch_size=BATCH, serving={"tenants": tenants},
                        monitoring=mon)
    for label in register:
        rt.register_graph(label, _ops())
    src.start()
    thread = rt.run_background()
    client = RecordClient(src.endpoint)
    for i, c in enumerate(chunks):
        client.send(c.tobytes(), tenant=tenant_of[i])
        if swap is not None and i == swap[0]:
            client.send_swap(swap[1])
    client.send_eos(eos_tenant)
    client.close()
    thread.join(timeout=60.0)
    assert not thread.is_alive()
    if rt.background_error is not None:
        raise rt.background_error
    return got, rt, mon


def test_live_swap_under_load_is_oracle_exact(tmp_path):
    chunks = _chunks(16)
    tenant_of = ["a" if i % 2 == 0 else "b" for i in range(len(chunks))]
    got, rt, mon = _serve(tmp_path, [{"id": "a"}, {"id": "b"}], chunks,
                          tenant_of, swap=(len(chunks) // 2, "v2"))
    assert rt.swaps_applied == 1 and rt.graph_label == "v2"
    assert sorted(got) == sorted(_oracle(chunks)) and got
    # the cutover is a journaled graph_swap with warm-before-cut recorded
    events = [json.loads(line)
              for line in open(os.path.join(mon, "events.jsonl"))]
    swaps = [e for e in events
             if e.get("event") == "graph_swap" and e.get("applied")]
    assert len(swaps) == 1
    assert swaps[0]["warmed"] is True and swaps[0]["carried_state"] is True
    # and the snapshot's serving section reflects the post-cut world
    snap = json.load(open(os.path.join(mon, "snapshot.json")))
    assert snap["serving"]["graph"] == "v2"
    assert snap["serving"]["swaps_applied"] == 1
    assert set(snap["serving"]["tenants"]) == {"a", "b"}


def test_wire_swap_to_unregistered_graph_is_rejected(tmp_path):
    chunks = _chunks(4)
    got, rt, _ = _serve(tmp_path, [{"id": "a"}], chunks, ["a"] * 4,
                        swap=(1, "nope"))
    assert rt.swaps_rejected == 1 and rt.swaps_applied == 0
    assert rt.graph_label != "nope"
    assert sorted(got) == sorted(_oracle(chunks))   # traffic unharmed


def test_noisy_tenant_sheds_under_its_own_bucket_only(tmp_path):
    quiet = _chunks(12, base=10_000.0)
    noisy = _chunks(12, base=0.0)
    mixed, tenant_of = [], []
    for q, n in zip(quiet, noisy):
        mixed += [q, n]
        tenant_of += ["quiet", "noisy"]
    got, rt, _ = _serve(
        tmp_path,
        [{"id": "quiet"},
         {"id": "noisy", "refill_per_batch": 4.0, "burst": float(BATCH)}],
        mixed, tenant_of, eos_tenant="quiet")
    rows = rt.serving_section()["tenants"]
    assert rows["noisy"]["shed"] > 0 and rows["noisy"]["shed_tuples"] > 0
    # the isolation contract: the quiet tenant NEVER sheds — its
    # drop_ratio signal stays exactly 0 while its neighbor burns
    assert rows["quiet"]["shed"] == 0 and rows["quiet"]["shed_tuples"] == 0
    quiet_vals = [v for _, v in got if v >= 2 * 10_000]
    assert len(quiet_vals) == sum(len(c) for c in quiet)


def test_drop_oldest_ts_held_batches_are_not_counted_shed():
    """shed_tuples follows the controller's own shed ledger: an empty
    offer() return under drop_oldest_ts means HELD (admitted later by
    drain), not shed — only a hold_max overflow sheds, and exactly that
    batch's capacity is counted."""
    class _B:
        capacity = BATCH
    reg = build_registry(
        [{"id": "a", "refill_per_batch": 1.0, "burst": float(BATCH),
          "shed_policy": "drop_oldest_ts"}], base_capacity=BATCH)
    assert reg.offer("a", _B())         # burst affords the first batch
    for _ in range(2):                  # held (hold_max=2), NOT shed
        assert reg.offer("a", _B()) == []
        assert reg.counters()["a"]["shed_tuples"] == 0
    assert reg.offer("a", _B()) == []   # overflow: oldest held batch sheds
    row = reg.counters()["a"]
    assert row["shed"] == 1 and row["shed_tuples"] == BATCH
    assert len(reg.drain()) == 2        # the held tail admits at EOS
    row = reg.counters()["a"]
    assert row["offered"] == 4 and row["admitted"] == 3
    assert row["shed"] == 1 and row["shed_tuples"] == BATCH
    # the totals ride the registry snapshot across a supervised restore
    reg2 = build_registry(
        [{"id": "a", "refill_per_batch": 1.0, "burst": float(BATCH),
          "shed_policy": "drop_oldest_ts"}], base_capacity=BATCH)
    reg2.set_state(reg.state())
    assert reg2.counters()["a"]["shed_tuples"] == BATCH


def test_registry_scale_rate_targets_one_tenant():
    reg = build_registry(
        [{"id": "a", "refill_per_batch": 8.0}, {"id": "b"}],
        base_capacity=BATCH)
    out = reg.scale_rate("a", 0.5)
    assert out["tenant"] == "a"
    with pytest.raises(ValueError):
        reg.scale_rate("b", 0.5)        # declared but rate-unlimited
    with pytest.raises(ValueError):
        reg.scale_rate("ghost", 0.5)


# ------------------------------------------------------ config + validator


def test_serving_config_resolve_grammar(monkeypatch, tmp_path):
    monkeypatch.delenv("WF_SERVE", raising=False)
    assert ServingConfig.resolve(None) is None
    assert ServingConfig.resolve(False) is None
    assert ServingConfig.resolve(True).replay == 256
    assert ServingConfig.resolve("tcp://h:5").endpoint == "tcp://h:5"
    assert ServingConfig.resolve('{"replay": 9}').replay == 9
    p = tmp_path / "s.json"
    p.write_text('{"endpoint": "tcp://h:5", "swap_warm": false}')
    cfg = ServingConfig.resolve(str(p))
    assert cfg.endpoint == "tcp://h:5" and cfg.swap_warm is False
    monkeypatch.setenv("WF_SERVE", "0")
    assert ServingConfig.resolve(None) is None
    monkeypatch.setenv("WF_SERVE", "1")
    assert ServingConfig.resolve(None) is not None
    monkeypatch.setenv("WF_SERVE_ENDPOINT", "tcp://e:7")
    assert ServingConfig.resolve(None).resolved_endpoint() == "tcp://e:7"


def test_serving_problems_catalogue(tmp_path):
    mon = str(tmp_path / "mon")
    ok = ServingConfig(tenants=[{"id": "a"}])
    assert serving_problems(ok, monitoring=mon) == []
    # monitoring off: the whole plane is unobservable
    assert any("monitoring" in p
               for p in serving_problems(ok, monitoring=None))
    # endpoint, replay, swap_warm, duplicate tenants, supervised wall-clock
    probs = serving_problems(
        ServingConfig(endpoint="not an endpoint", replay=0, swap_warm=False,
                      tenants=[{"id": "a"}, {"id": "a"},
                               {"id": "b", "rate_tps": 5.0}]),
        monitoring=mon, supervised=True)
    blob = "\n".join(probs)
    assert "unparseable serving endpoint" in blob
    assert "replay must be >= 1" in blob
    assert "swap_warm=false" in blob
    assert "duplicate tenant id" in blob.lower() or "duplicate" in blob
    assert "rate_tps" in blob           # wall-clock bucket under supervision
    # an SLO tenant label must name a declared tenant
    spec = slo_mod.SLOSpec("iso", "tenant_drop_ratio", target=0.1,
                           tenant="ghost")
    probs = serving_problems(ok, monitoring=mon, slo_specs=[spec])
    assert any("ghost" in p for p in probs)


def test_constructor_mirrors_wf119(tmp_path):
    mon = str(tmp_path / "mon")
    src = SocketSource("tcp://127.0.0.1:0", DT, key_field="key",
                       ts_field="ts", num_keys=4)
    with pytest.raises(ValueError, match="WF119"):
        ServingRuntime(src, _ops(), serving=True)           # monitoring off
    with pytest.raises(ValueError, match="WF119"):
        ServingRuntime(src, _ops(), monitoring=mon,
                       serving={"tenants": [{"id": "a"}, {"id": "a"}]})
    with pytest.raises(ValueError, match="WF119"):
        ServingRuntime(src, _ops(), monitoring=mon, supervised=True,
                       serving={"tenants": [{"id": "a", "rate_tps": 9.0}]})
    src.close()


def test_validator_reports_wf119(monkeypatch, tmp_path):
    mon = str(tmp_path / "mon")
    src = SocketSource("tcp://127.0.0.1:0", DT, key_field="key",
                       ts_field="ts", num_keys=4)
    rt = ServingRuntime(src, _ops(), wf.Sink(lambda v: None),
                        batch_size=BATCH, monitoring=mon,
                        serving={"tenants": [{"id": "a"}]})
    rt.register_graph("v2", _ops())
    report = validate(rt)               # a ServingRuntime validates directly
    assert "WF119" not in report.codes()
    assert not report.errors
    src.close()
    # classic drivers resolve the env exactly as the runtime would:
    # WF_SERVE on + monitoring off is flagged pre-run
    monkeypatch.setenv("WF_SERVE", "1")
    chunks = _chunks(2)
    p = wf.Pipeline(wf.RecordSource(lambda: iter(chunks), DT,
                                    key_field="key", ts_field="ts",
                                    num_keys=4),
                    _ops(), wf.Sink(lambda v: None), batch_size=BATCH)
    report = validate(p)
    assert "WF119" in report.codes()


def test_tenant_grammar_and_registry_problems():
    specs = resolve_tenants('[{"id": "a", "refill_per_batch": 2}]')
    assert specs[0].id == "a" and specs[0].refill_per_batch == 2.0
    assert resolve_tenants(None) is None
    # legality is registry_problems/build_registry territory, not resolve
    both = resolve_tenants([{"id": "a", "rate_tps": 1.0,
                             "refill_per_batch": 1.0}])
    assert any("mutually exclusive" in p for p in registry_problems(both))
    with pytest.raises(ValueError, match="WF119"):
        build_registry(both, base_capacity=BATCH)
    probs = registry_problems([TenantSpec("a", rate_tps=5.0)],
                              supervised=True)
    assert probs and "rate_tps" in probs[0]


# ----------------------------------------------------- observability glue


def test_gauge_help_lockstep():
    assert set(metrics_mod._SERVING_HELP) == set(SERVING_GAUGES)
    assert set(metrics_mod._TENANT_HELP) == set(TENANT_GAUGES)
    for ev in ("serving_start", "serving_end", "graph_swap"):
        assert ev in JOURNAL_EVENTS


def test_tenant_slo_signals_read_tenant_rows():
    def snap(offered, shed, shed_tuples):
        return {"serving": {"tenants": {"a": {"offered": offered,
                                              "shed": shed,
                                              "shed_tuples": shed_tuples}}}}
    fn, mode = slo_mod.TENANT_SIGNALS["tenant_drop_ratio"]
    assert mode == "max"
    assert fn(snap(10, 5, 160), snap(0, 0, 0), "a") == pytest.approx(0.5)
    assert fn(snap(10, 5, 160), snap(10, 5, 160), "a") is None  # no traffic
    assert fn(snap(10, 5, 160), snap(0, 0, 0), "ghost") is None
    fn2, _ = slo_mod.TENANT_SIGNALS["tenant_shed_tuples"]
    assert fn2(snap(10, 5, 160), snap(8, 3, 100), "a") == 60.0
    # a tenant signal without tenant= (and vice versa) is a spec problem
    bad = slo_mod.SLOSpec("x", "tenant_drop_ratio", target=0.1)
    assert any("tenant" in p for p in slo_mod.spec_problems(bad))
    bad2 = slo_mod.SLOSpec("y", "drop_ratio", target=0.1, tenant="a")
    assert any("tenant" in p for p in slo_mod.spec_problems(bad2))


# ------------------------------------------------------------------- CLI


def test_wf_serve_cli_contract(tmp_path):
    env = dict(os.environ)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "wf_serve.py"),
         "selftest"], capture_output=True, text=True, env=env, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "wf_serve.py"),
         "status", "--monitoring-dir", str(tmp_path / "nope")],
        capture_output=True, text=True, env=env, timeout=120)
    assert proc.returncode == 2


def test_wf_serve_status_renders_live_run(tmp_path):
    chunks = _chunks(4)
    got, rt, mon = _serve(tmp_path, [{"id": "a"}], chunks, ["a"] * 4)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "wf_serve.py"),
         "status", "--monitoring-dir", mon, "--json"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["tenants"]["a"]["offered"] == 4
