"""Unit coverage for the join/session/rank operator family: the versioned
JoinTable (last-writer-wins determinism, as-of-watermark reads, rollback
guard, overflow accounting), the registry-resolved probe path (oversize
tables route to the XLA reference instead of raising; Pallas-interpret
parity), the session triggerer, top-N eviction accounting, distinct
semantics, and the WF111/WF112 pre-run diagnostics."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.analysis import validate
from windflow_tpu.basic import win_type_t
from windflow_tpu.operators.join import IntervalJoin, StreamTableJoin
from windflow_tpu.operators.rank import TOPN_SENTINEL, Distinct, TopN
from windflow_tpu.operators.session import SessionWindow
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.ops.lookup import (JOIN_PROBE_MAX_ROWS, _join_probe_xla,
                                     join_probe, join_table_init,
                                     join_table_pending, join_table_probe,
                                     join_table_upsert)

I32 = jnp.int32
SPEC = {"v": jax.ShapeDtypeStruct((), I32)}


def up1(st, key, val, ts, tid, *, delay=0):
    return join_table_upsert(
        st, jnp.asarray([key], I32), {"v": jnp.asarray([val], I32)},
        jnp.asarray([ts], I32), jnp.asarray([tid], I32),
        jnp.ones(1, bool), delay=delay)


# ----------------------------------------------------------- JoinTable core

def test_join_table_duplicate_keys_last_writer_wins_by_event_time():
    st = join_table_init(8, 16, SPEC)
    st = join_table_upsert(
        st, jnp.asarray([1, 2, 1, 3], I32),
        {"v": jnp.asarray([10, 20, 11, 30], I32)},
        jnp.asarray([5, 5, 7, 5], I32), jnp.asarray([0, 1, 2, 3], I32),
        jnp.ones(4, bool))
    vals, hit = join_table_probe(st, jnp.asarray([1, 2, 3, 9], I32),
                                 jnp.ones(4, bool))
    assert np.asarray(hit).tolist() == [True, True, True, False]
    # key 1 took the ts=7 version, not the scatter-luck one
    assert np.asarray(vals["v"]).tolist() == [11, 20, 30, 0]
    assert int(np.asarray(st["version"])) == 3


def test_join_table_same_ts_ties_break_by_id():
    st = join_table_init(4, 8, SPEC)
    st = join_table_upsert(
        st, jnp.asarray([7, 7], I32), {"v": jnp.asarray([100, 200], I32)},
        jnp.asarray([3, 3], I32), jnp.asarray([9, 4], I32),
        jnp.ones(2, bool))
    vals, _ = join_table_probe(st, jnp.asarray([7], I32), jnp.ones(1, bool))
    assert int(np.asarray(vals["v"])[0]) == 100        # id 9 > id 4


def test_join_table_watermark_delay_gates_visibility():
    st = join_table_init(8, 16, SPEC)
    st = up1(st, 5, 99, 10, 0, delay=3)
    _, hit = join_table_probe(st, jnp.asarray([5], I32), jnp.ones(1, bool))
    assert not bool(np.asarray(hit)[0])
    assert int(np.asarray(join_table_pending(st))) == 1
    # watermark reaches ts + delay: the version becomes visible
    st = up1(st, 0, 0, 13, 1, delay=3)
    vals, hit = join_table_probe(st, jnp.asarray([5], I32),
                                 jnp.ones(1, bool))
    assert bool(np.asarray(hit)[0])
    assert int(np.asarray(vals["v"])[0]) == 99
    # the ts=13 upsert itself now parks behind the watermark
    assert int(np.asarray(join_table_pending(st))) == 1


def test_join_table_late_eligible_upsert_cannot_roll_back():
    st = join_table_init(8, 16, SPEC)
    st = up1(st, 4, 100, 10, 0)
    st = up1(st, 4, 50, 8, 1)          # older event time, arrives later
    vals, _ = join_table_probe(st, jnp.asarray([4], I32), jnp.ones(1, bool))
    assert int(np.asarray(vals["v"])[0]) == 100


def test_join_table_overflow_drops_are_counted():
    st = join_table_init(2, 2, SPEC)   # tiny table AND tiny ring
    st = join_table_upsert(
        st, jnp.asarray([1, 2, 3], I32),
        {"v": jnp.asarray([1, 2, 3], I32)},
        jnp.asarray([1, 1, 1], I32), jnp.asarray([0, 1, 2], I32),
        jnp.ones(3, bool))
    # ring capacity 2: third upsert dropped; table capacity 2 holds the rest
    assert int(np.asarray(st["dropped"])) >= 1
    _, hit = join_table_probe(st, jnp.asarray([1, 2], I32),
                              jnp.ones(2, bool))
    assert np.asarray(hit).tolist() == [True, True]


def test_join_table_state_is_checkpointable_pytree():
    st = join_table_init(4, 8, SPEC)
    st = up1(st, 1, 5, 2, 0)
    host = jax.tree.map(np.asarray, st)          # the supervisor snapshot
    back = jax.tree.map(jnp.asarray, host)
    vals, hit = join_table_probe(back, jnp.asarray([1], I32),
                                 jnp.ones(1, bool))
    assert bool(np.asarray(hit)[0]) and int(np.asarray(vals["v"])[0]) == 5


# -------------------------------------------------- registry probe contract

def test_join_probe_oversize_routes_to_xla_reference_not_raise():
    K = 2 * JOIN_PROBE_MAX_ROWS                  # beyond the Pallas envelope
    tk = jnp.arange(K, dtype=I32)
    tv = tk * 3
    probe = jnp.pad(jnp.asarray([5, K - 7, 123], I32), (0, 125))
    ok = jnp.arange(128) < 3
    got = join_probe(tk, tv, probe, ok, impl="pallas")
    ref = _join_probe_xla(tk, tv, probe, ok)
    assert np.array_equal(np.asarray(got[0]), np.asarray(ref[0]))
    assert np.array_equal(np.asarray(got[1]), np.asarray(ref[1]))


def test_join_table_probe_pallas_interpret_parity():
    st = join_table_init(512, 512, SPEC)
    rng = np.random.default_rng(0)
    keys = jnp.asarray(rng.permutation(4096)[:256].astype(np.int32))
    st = join_table_upsert(
        st, keys, {"v": keys * 7}, jnp.zeros(256, I32),
        jnp.arange(256, dtype=I32), jnp.ones(256, bool))
    probe = jnp.asarray(rng.integers(0, 4096, 128).astype(np.int32))
    ok = jnp.ones(128, bool)
    vx, hx = join_table_probe(st, probe, ok, impl="xla")
    vp, hp = join_table_probe(st, probe, ok, impl="pallas")
    assert np.array_equal(np.asarray(vx["v"]), np.asarray(vp["v"]))
    assert np.array_equal(np.asarray(hx), np.asarray(hp))


def test_join_table_probe_multi_column_single_contraction_parity():
    """Multi-column values probe the slot ONCE and gather each column —
    byte-identical to per-column probing."""
    spec2 = {"a": jax.ShapeDtypeStruct((), I32),
             "b": jax.ShapeDtypeStruct((), jnp.float32)}
    st = join_table_init(16, 16, spec2)
    keys = jnp.asarray([3, 9, 12], I32)
    st = join_table_upsert(
        st, keys, {"a": keys * 2, "b": keys.astype(jnp.float32) * 0.5},
        jnp.zeros(3, I32), jnp.arange(3, dtype=I32), jnp.ones(3, bool))
    probe = jnp.asarray([9, 4, 12, 3], I32)
    ok = jnp.ones(4, bool)
    vals, hit = join_table_probe(st, probe, ok)
    assert np.asarray(hit).tolist() == [True, False, True, True]
    assert np.asarray(vals["a"]).tolist() == [18, 0, 24, 6]
    assert np.asarray(vals["b"]).tolist() == [4.5, 0.0, 6.0, 1.5]


def test_interval_join_ts_extractors_batching_invariant():
    """With ts_l/ts_r payload extractors, the probing side's emit() ref
    carries the EXTRACTED event time — the emitted multiset (including the
    ts fields emit() reads) is identical whichever member arrived later."""
    def gen(i):
        is_l = (i % 8) == 0
        return {"side": jnp.where(is_l, 1, 0).astype(I32),
                "ev": (i // 4).astype(I32),
                "p": (i * 3).astype(I32)}
    def run(batch):
        src = wf.Source(gen, total=64, num_keys=1, key_fn=lambda i: i * 0,
                        ts_fn=lambda i: i // 4)
        op = IntervalJoin(lambda t: t.side == 1, 0, 2, max_matches=16,
                          ts_l=lambda t: t.ev, ts_r=lambda t: t.ev,
                          emit=lambda l, r: {"lt": l.ts, "rt": r.ts,
                                             "p": r.data["p"]})
        rows = []

        def cb(view):
            if view is None:
                return
            rows.extend(zip(np.asarray(view["payload"]["lt"]).tolist(),
                            np.asarray(view["payload"]["rt"]).tolist(),
                            np.asarray(view["payload"]["p"]).tolist()))
        wf.Pipeline(src, [op], wf.Sink(cb), batch_size=batch).run()
        return sorted(rows)
    a, b, c = run(8), run(16), run(64)
    assert a == b == c and a
    # every emitted lt/rt is an extracted event time (i // 4 domain)
    assert all(0 <= lt <= 16 and 0 <= rt <= 16 for lt, rt, _ in a)


# ------------------------------------------------------- operator semantics

def _tagged_source(total, defs):
    """side=1 definition events for the first ``defs`` indexes, bids after."""
    def gen(i):
        is_def = i < defs
        return {"side": jnp.where(is_def, 1, 0).astype(I32),
                "k": jnp.where(is_def, i % 4, (i * 3) % 4).astype(I32),
                "val": (i * 10).astype(I32)}
    return wf.Source(gen, total=total, num_keys=4,
                     key_fn=lambda i: jnp.where(i < defs, i % 4, (i * 3) % 4),
                     ts_fn=lambda i: i // 2)


def test_stream_table_join_left_join_emits_misses():
    src = _tagged_source(20, 2)        # only keys 0, 1 defined
    rows = []

    def cb(view):
        if view is None:
            return
        rows.extend(zip(view["id"].tolist(),
                        np.asarray(view["payload"]["k"]).tolist(),
                        np.asarray(view["payload"]["val"]).tolist()))
    op = StreamTableJoin(lambda t: t.side == 1, lambda t: t.k,
                         lambda t: {"jv": t.val}, num_slots=8,
                         emit_misses=True)
    wf.Pipeline(src, [op], wf.Sink(cb), batch_size=8).run()
    assert len(rows) == 18             # every probe lane, hit or miss


def test_interval_join_match_drops_counted_when_max_matches_too_small():
    def gen(i):
        return {"side": jnp.where(i == 0, 1, 0).astype(I32),
                "p": (i * 1).astype(I32)}
    src = wf.Source(gen, total=8, num_keys=1, key_fn=lambda i: i * 0,
                    ts_fn=lambda i: i * 0)       # everything at ts 0
    op = IntervalJoin(lambda t: t.side == 1, 0, 0, max_matches=2)
    chain = wf.CompiledChain([op], src.payload_spec(), batch_capacity=8)
    b = next(src.batches(8))
    chain.push(b)
    # the single open matches 7 same-tick bids; 2 kept, 5 counted dropped
    assert int(np.asarray(chain.states[0]["match_drops"])) == 5


def test_topn_eviction_counter_and_tie_break():
    src = wf.Source(lambda i: {"s": ((i * 7) % 50).astype(I32)},
                    total=40, num_keys=2, ts_fn=lambda i: i)
    op = TopN(lambda t: t.s, 2, num_keys=2)
    rows = {}

    def cb(view):
        if view is None:
            return
        for k, r, i, s in zip(view["key"].tolist(),
                              np.asarray(view["payload"]["rank"]).tolist(),
                              view["id"].tolist(),
                              np.asarray(view["payload"]["score"]).tolist()):
            rows[(k, r)] = (i, s)
    wf.Pipeline(src, [op], wf.Sink(cb), batch_size=10).run()
    want = {}
    per = {}
    for i in range(40):
        per.setdefault(i % 2, []).append((-((i * 7) % 50), i))
    for k, cands in per.items():
        for r, (ns, i) in enumerate(sorted(cands)[:2]):
            want[(k, r)] = (i, -ns)
    assert rows == want
    from windflow_tpu.control import _state as _cstate
    assert _cstate.counters().get("topn_evictions", 0) > 0


def test_topn_rejects_sentinel_score_domain():
    assert TOPN_SENTINEL == -(1 << 31) + 1       # documented domain floor


def test_distinct_in_batch_and_cross_batch_dedup():
    src = wf.Source(lambda i: {"d": (i % 3).astype(I32)}, total=30,
                    num_keys=1, ts_fn=lambda i: i)
    rows = []

    def cb(view):
        if view is None:
            return
        rows.extend(zip(view["id"].tolist(),
                        np.asarray(view["payload"]["d"]).tolist()))
    wf.Pipeline(src, [Distinct(lambda t: t.d, num_slots=8)],
                wf.Sink(cb), batch_size=7).run()
    assert sorted(rows) == [(0, 0), (1, 1), (2, 2)]


def test_session_old_events_dropped_and_counted():
    # key 0: ts 0,1 then a gap to ts 10,11 (first session closes on
    # in-batch evidence, floor=1) — then a straggler at ts 2 arrives in the
    # NEXT batch, inside the closed session's span: OLD, dropped, counted
    ts_tab = jnp.asarray([0, 1, 10, 11, 2, 12, 13, 14], I32)
    src = wf.Source(lambda i: {"v": jnp.ones((), I32)}, total=8,
                    num_keys=1, ts_fn=lambda i: ts_tab[i])
    op = SessionWindow(lambda t: t.v, WindowSpec.session(3), num_keys=1)
    rows = []

    def cb(view):
        if view is None:
            return
        rows.extend(zip(view["key"].tolist(), view["id"].tolist(),
                        np.asarray(view["payload"]["start"]).tolist(),
                        np.asarray(view["payload"]["end"]).tolist(),
                        np.asarray(view["payload"]["n"]).tolist()))
    wf.Pipeline(src, [op], wf.Sink(cb), batch_size=4).run()
    assert (0, 0, 0, 1, 2) in rows               # first session closed
    assert (0, 1, 10, 14, 5) in rows             # second session at EOS
    op.collect_stats(None)
    assert op.get_StatsRecords()[0].tuples_dropped_old == 1


def test_session_spec_requires_session_type():
    with pytest.raises(ValueError, match="session spec"):
        SessionWindow(lambda t: t.v, WindowSpec(10, 10, win_type_t.TB))


def test_windowspec_session_triggerer_is_gap_dependent():
    spec = WindowSpec.session(5, delay=2)
    assert spec.is_session and spec.gap == 5
    last = jnp.asarray([0, 10], I32)
    fired = spec.fired_session(last, jnp.asarray(8, I32))
    # wm 8, delay 2: horizon 6 — session ending at 0 fired (0+5 < 6),
    # session ending at 10 not
    assert np.asarray(fired).tolist() == [True, False]


# --------------------------------------------------------- WF111 / WF112

def _pipe(ops, ts_fn="yes"):
    src = wf.Source(lambda i: {"side": (i % 2).astype(I32),
                               "v": (i * 1).astype(I32)},
                    total=64, num_keys=4,
                    ts_fn=(lambda i: i // 4) if ts_fn else None)
    return wf.Pipeline(src, ops, wf.Sink(lambda v: None), batch_size=32)


def test_wf111_empty_match_window():
    rep = validate(_pipe([IntervalJoin(lambda t: t.side == 1, 5, 2)]))
    assert any(d.code == "WF111" and "empty" in d.message
               for d in rep.errors)
    assert rep.errors[0].hint


def test_wf111_bounds_incompatible_with_watermark_delay():
    rep = validate(_pipe([IntervalJoin(lambda t: t.side == 1, -10, -6,
                                       delay=2)]))
    assert any(d.code == "WF111" and "delay" in d.message
               for d in rep.errors)


def test_wf111_two_input_ts_dtype_disagreement():
    op = IntervalJoin(lambda t: t.side == 1, 0, 4,
                      ts_l=lambda t: t.v.astype(jnp.float32),
                      ts_r=lambda t: t.v)
    rep = validate(_pipe([op]))
    assert any(d.code == "WF111" and "dtype" in d.message
               for d in rep.errors)


def test_wf112_session_gap_under_cb_only_source():
    op = SessionWindow(lambda t: t.v, WindowSpec.session(3), num_keys=4)
    rep = validate(_pipe([op], ts_fn=None))
    assert any(d.code == "WF112" for d in rep.errors)


def test_wf112_record_source_without_ts_field():
    rec_dtype = np.dtype([("k", np.int32), ("v", np.float32)])
    src = wf.RecordSource(lambda: iter(()), rec_dtype, key_field="k",
                          num_keys=4)
    op = SessionWindow(lambda t: t.v, WindowSpec.session(3), num_keys=4)
    rep = validate(wf.Pipeline(src, [op], wf.Sink(lambda v: None),
                               batch_size=16))
    assert any(d.code == "WF112" for d in rep.errors)
    # ts_field present: clean
    rec2 = np.dtype([("k", np.int32), ("t", np.int32), ("v", np.float32)])
    src2 = wf.RecordSource(lambda: iter(()), rec2, key_field="k",
                           ts_field="t", num_keys=4)
    rep2 = validate(wf.Pipeline(src2, [SessionWindow(
        lambda t: t.v, WindowSpec.session(3), num_keys=4)],
        wf.Sink(lambda v: None), batch_size=16))
    assert "WF112" not in rep2.codes()
    # event time present: clean
    rep2 = validate(_pipe([SessionWindow(lambda t: t.v,
                                         WindowSpec.session(3),
                                         num_keys=4)]))
    assert "WF112" not in rep2.codes()


def test_wf111_wf112_clean_on_good_config():
    rep = validate(_pipe([IntervalJoin(lambda t: t.side == 1, 0, 4)]))
    assert "WF111" not in rep.codes() and "WF112" not in rep.codes()


def test_graph_join_with_traces_sources_through_merge():
    g = wf.PipeGraph(batch_size=32)
    mk = lambda: wf.Source(lambda i: {"side": (i % 2).astype(I32),
                                      "v": (i * 1).astype(I32)},
                           total=64, num_keys=4)
    a, b = g.add_source(mk()), g.add_source(mk())
    m = a.join_with(b, IntervalJoin(lambda t: t.side == 1, 5, 2))
    m.add_sink(wf.Sink(lambda v: None))
    rep = validate(g)
    assert any(d.code == "WF111" for d in rep.errors)


def test_join_with_rejects_non_join_operator():
    g = wf.PipeGraph(batch_size=32)
    mk = lambda: wf.Source(lambda i: {"v": (i * 1).astype(I32)}, total=8,
                           num_keys=2)
    a, b = g.add_source(mk()), g.add_source(mk())
    with pytest.raises(TypeError, match="join_with"):
        a.join_with(b, wf.Map(lambda t: {"v": t.v}))
