"""Runtime health layer (PR 11): HBM memory ledger, compile/retrace
telemetry, device-time attribution, fleet snapshot federation — plus the
off-path hermeticity contract (health off = byte-for-byte today's compiled
programs and results across all four drivers) and the wf_health.py CLI
exit/shape pins."""

import importlib.util
import json
import os
import time

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.nexmark import make_query
from windflow_tpu.observability import (EventJournal, MetricsRegistry,
                                        MonitoringConfig,
                                        device_health as dh,
                                        read_journal, set_journal)
from windflow_tpu.runtime.pipeline import CompiledChain

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TOTAL = 300
I32 = jnp.int32


@pytest.fixture(autouse=True)
def _clean_ledger():
    """No test may leak an active ledger/journal into the next."""
    yield
    dh.set_active(None)
    set_journal(None)


def _cfg(tmp_path, sub="mon", **kw):
    kw.setdefault("health", True)
    kw.setdefault("interval_s", 30.0)
    return MonitoringConfig(out_dir=str(tmp_path / sub), **kw)


def _snapshot(tmp_path, sub="mon"):
    with open(tmp_path / sub / "snapshot.json") as f:
        return json.load(f)


def run_q3(driver="plain", monitoring=False, **kw):
    """The Nexmark enrich-join (q3) through one of the four drivers,
    returning the sink rows — the acceptance workload of this layer."""
    src, ops = make_query("q3_enrich_join", TOTAL)
    rows = []

    def cb(view):
        if view is None:
            return
        rows.append((np.asarray(view["key"]).tolist(),
                     np.asarray(view["id"]).tolist(),
                     np.asarray(view["ts"]).tolist()))
    sink = wf.Sink(cb)
    if driver == "plain":
        wf.Pipeline(src, ops, sink, batch_size=64, monitoring=monitoring,
                    **kw).run()
    elif driver == "graph":
        g = wf.PipeGraph(batch_size=64, monitoring=monitoring)
        mp = g.add_source(src)
        for op in ops:
            mp.add(op)
        mp.add_sink(sink)
        g.run()
    elif driver == "graph-threaded":
        g = wf.PipeGraph(batch_size=64, monitoring=monitoring)
        mp = g.add_source(src)
        for op in ops:
            mp.add(op)
        mp.add_sink(sink)
        g.run(threaded=True)
    elif driver == "graph-supervised":
        g = wf.PipeGraph(batch_size=64, monitoring=monitoring)
        mp = g.add_source(src)
        for op in ops:
            mp.add(op)
        mp.add_sink(sink)
        g.run_supervised(checkpoint_every=2, backoff_base=0.001,
                         backoff_cap=0.01)
    return rows


def _small_chain(batch=64):
    src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=512,
                    num_keys=4)
    chain = CompiledChain([wf.Map(lambda t: {"v": t.v * 2})],
                          src.payload_spec(), batch_capacity=batch)
    return src, chain


# ------------------------------------------------------- registry lockstep


def test_health_gauges_registry_lockstep():
    from windflow_tpu.observability.metrics import _HEALTH_HELP
    from windflow_tpu.observability.names import HEALTH_GAUGES
    assert set(_HEALTH_HELP) == set(HEALTH_GAUGES)


# --------------------------------------------------------- snapshot shape


def test_health_off_no_section(tmp_path):
    run_q3(monitoring=_cfg(tmp_path, health=False))
    snap = _snapshot(tmp_path)
    assert "health" not in snap


def test_health_snapshot_journal_prometheus(tmp_path):
    """THE acceptance shape: a Nexmark join run's snapshot carries HBM
    devices + per-op state footprints, the journal records every compile
    with cause/key/duration/cost, and the Prometheus exposition renders
    the health gauges with HELP/TYPE."""
    run_q3(monitoring=_cfg(tmp_path))
    snap = _snapshot(tmp_path)
    h = snap["health"]
    assert h["devices"] and h["devices"][0]["device"].startswith("cpu")
    assert h["live_buffer_count"] > 0
    # the stateful join table shows up with a real footprint
    sb = h["state_bytes"]
    assert any(b > 0 for b in sb.values()), sb
    assert h["compile"]["compiles"] >= 1
    assert h["compile"]["retraces_unexpected"] == 0
    assert "chain" in h["device_time"]
    assert h["device_time"]["chain"]["samples"] >= 1
    ev = read_journal(str(tmp_path / "mon" / "events.jsonl"))
    comps = [e for e in ev if e["event"] == "compile"]
    assert len(comps) == h["compile"]["compiles"]
    for e in comps:
        assert e["cause"] in ("push", "push_many", "warm", "warm_scan",
                              "autotune_prewarm")
        assert e["kind"] in ("step", "scan")
        assert e["cache_key"] and e["compile_s"] > 0
        # AOT cost columns land on the CPU backend
        assert e["flops"] >= 0 and e["bytes_accessed"] > 0
        assert e["argument_bytes"] > 0
    assert h["executables"]                 # footprints folded in
    prom = open(tmp_path / "mon" / "metrics.prom").read()
    assert "# TYPE windflow_health_compiles gauge" in prom
    assert "windflow_health_state_bytes{" in prom
    assert "windflow_health_device_ms{" in prom
    # topology export carries the memory ledger annotations (pipeline
    # exports "stages"; a PipeGraph would export "nodes" with op lists)
    topo = json.load(open(tmp_path / "mon" / "topology.json"))
    assert "health" in topo
    assert any("state_bytes" in st for st in topo["stages"])


# ----------------------------------------------- compile/retrace ledger


def test_retrace_counters_and_detector(tmp_path):
    led = dh.HealthLedger(cost_analysis=False)
    dh.set_active(led)
    j = EventJournal(str(tmp_path / "events.jsonl"))
    set_journal(j)
    src, chain = _small_chain()
    b = next(iter(src.batches(64)))
    chain.push(b)
    assert (led.traces, led.retraces, led.retraces_unexpected) == (1, 0, 0)
    # forced re-trace via capacity change: the retrace counter fires
    chain.warm(128)
    assert (led.traces, led.retraces, led.retraces_unexpected) == (2, 1, 0)
    # a warm executable silently recompiled (cache cleared): UNEXPECTED
    chain._steps[0].clear_cache()
    chain.push(b)
    assert led.retraces_unexpected == 1
    j.close()
    ev = read_journal(str(tmp_path / "events.jsonl"))
    kinds = [(e["event"], e.get("cause"), e.get("retrace"),
              e.get("unexpected")) for e in ev
             if e["event"] in ("compile", "retrace_unexpected")]
    assert ("retrace_unexpected", "push", False, True) in kinds
    causes = [e["cause"] for e in ev if e["event"] == "compile"]
    assert causes == ["push", "warm", "push"]
    # same cache key for the unexpected retrace as the original compile
    comp_keys = [e["cache_key"] for e in ev if e["event"] == "compile"]
    assert comp_keys[0] == comp_keys[2]


def test_scan_compile_carries_k(tmp_path):
    led = dh.HealthLedger(cost_analysis=False)
    dh.set_active(led)
    j = EventJournal(str(tmp_path / "events.jsonl"))
    set_journal(j)
    src, chain = _small_chain()
    it = iter(src.batches(64))
    chain.push_many([next(it) for _ in range(4)])
    j.close()
    ev = read_journal(str(tmp_path / "events.jsonl"))
    scans = [e for e in ev if e["event"] == "compile" and e["kind"] == "scan"]
    assert len(scans) == 1
    assert scans[0]["k"] == 4 and scans[0]["capacity"] == 64
    assert scans[0]["cause"] == "push_many"


def test_autotune_prewarm_cause_overrides():
    led = dh.HealthLedger(cost_analysis=False)
    dh.set_active(led)
    _src, chain = _small_chain()
    with dh.cause("autotune_prewarm"):
        chain.warm(64)
    pend = []  # committed already by warm; check via the compile log
    sec = led.snapshot_section()
    assert sec["compile_log"][-1]["cause"] == "autotune_prewarm"
    assert not pend


def test_supervised_restore_clears_pending():
    led = dh.HealthLedger(cost_analysis=False)
    dh.set_active(led)
    led.note_trace("chain", 0, "step", "sig-abandoned")
    dh.clear_pending()
    led.commit_pending(1.0)         # nothing left to charge
    assert led.snapshot_section()["compile_log"] == []
    # the counters still saw the trace (it DID happen)
    assert led.traces == 1


def test_kernel_resolve_journaled(tmp_path):
    led = dh.HealthLedger(cost_analysis=False)
    dh.set_active(led)
    j = EventJournal(str(tmp_path / "events.jsonl"))
    set_journal(j)
    from windflow_tpu.ops import registry
    impl = registry.resolve_impl("lookup", spec_key="health-test")
    j.close()
    ev = read_journal(str(tmp_path / "events.jsonl"))
    res = [e for e in ev if e["event"] == "kernel_resolve"]
    assert res and res[0]["kernel"] == "lookup" and res[0]["impl"] == impl
    assert led.kernel_resolves == 1


# ------------------------------------------------ device-time attribution


def test_service_sampling_and_dispatch_bound():
    led = dh.HealthLedger(sample_every=2)
    # every Nth sampled point records: 1st no, 2nd yes, 3rd no, 4th yes
    assert [led.service_sample() for _ in range(4)] == [False, True,
                                                       False, True]
    led.note_service("pipe0", dispatch_s=0.004, device_s=0.005)
    led.note_service("pipe1", dispatch_s=0.001, device_s=0.020)
    sec = led.snapshot_section()
    assert sec["device_time"]["pipe0"]["dispatch_ratio"] == 0.8
    assert "pipe0" in sec["dispatch_bound"]          # >= 0.5: candidate
    assert "pipe1" not in sec["dispatch_bound"]      # 0.05: device-bound


def test_trace_report_renders_dispatch_bound():
    from windflow_tpu.observability.tracing import critical_path_report
    snap = {"health": {
        "device_time": {"pipe0": {"device_ms": 5.0, "dispatch_ms": 4.0,
                                  "samples": 3, "dispatch_ratio": 0.8}},
        "dispatch_bound": {"pipe0": 0.8},
        "compile": {"compiles": 2, "retraces": 1, "retraces_unexpected": 0,
                    "compile_s_total": 0.5},
    }}
    out = critical_path_report([], [], snap, None)
    assert "DISPATCH-BOUND" in out and "pipe0" in out
    assert "compile ledger: 2 compiles" in out


# ------------------------------------------------------- state footprints


def test_state_footprints_match_shapes():
    src, ops = make_query("q3_enrich_join", TOTAL)
    chain = CompiledChain(ops, src.payload_spec(), batch_capacity=64)
    fp = chain.state_footprints()
    for op, st in zip(chain.ops, chain.states):
        want = sum(
            int(np.prod(getattr(leaf, "shape", ()))
                * jnp.dtype(getattr(leaf, "dtype", "float32")).itemsize)
            for leaf in jax.tree.leaves(st))
        assert fp[op.getName()] == want
    assert sum(fp.values()) > 0


# -------------------------------------------------- off-path hermeticity


def test_ledger_observes_trace_off_path():
    """The ledger hooks are trace-time host side effects: lowering with the
    ledger active must be OBSERVED by it (traces recorded) while leaving
    the device program untouched.  Program identity itself is pinned by the
    shared toggle-OFF fingerprint gate (test_program_fingerprint.py); this
    keeps only the observes-the-trace half, which that gate cannot see."""
    src, chain = _small_chain()
    b = next(iter(src.batches(64)))
    led = dh.HealthLedger(cost_analysis=False)
    dh.set_active(led)
    chain._step_fn(0).lower(tuple(chain.states), b).as_text()
    dh.set_active(None)
    assert led.traces >= 1            # the hook DID observe the trace


@pytest.mark.parametrize("driver", ["plain", "graph", "graph-threaded",
                                    "graph-supervised"])
def test_health_on_results_byte_identical(tmp_path, driver, monkeypatch):
    """Mirror of PR 9's off-path pin: WF_MONITORING_HEALTH on must not
    change a single result byte through any of the four drivers."""
    base = run_q3(driver)
    monkeypatch.setenv("WF_MONITORING_HEALTH", "1")
    on = run_q3(driver, monitoring=_cfg(tmp_path, sub=f"m-{driver}"))
    assert on == base


def test_perfgate_builders_hermetic_under_env(monkeypatch):
    """The hermetic gate's chains must not consult the health env — pins
    byte-identical whatever the caller's environment says."""
    monkeypatch.setenv("WF_MONITORING", "1")
    monkeypatch.setenv("WF_MONITORING_HEALTH", "1")
    from windflow_tpu.analysis.perfgate import _build_mp_matrix
    chain = _build_mp_matrix()[0]
    # no ledger was activated (Monitor never ran), so nothing was recorded
    assert dh.get_active() is None
    assert not chain.event_time


# ---------------------------------------------------------- WF113 checks


def test_wf113_health_without_monitoring(monkeypatch):
    src, chain = _small_chain()
    p = wf.Pipeline(src, [wf.Map(lambda t: {"v": t.v})],
                    wf.Sink(lambda v: None), batch_size=64)
    from windflow_tpu.analysis import validate
    monkeypatch.setenv("WF_MONITORING_HEALTH", "1")
    r = validate(p)
    assert "WF113" in r.codes() and r.errors
    monkeypatch.setenv("WF_MONITORING", "1")
    r = validate(p)
    assert "WF113" not in r.codes()
    monkeypatch.setenv("WF_HEALTH_SAMPLE", "0")
    r = validate(p)
    assert "WF113" in r.codes()
    monkeypatch.setenv("WF_HEALTH_SAMPLE", "abc")
    r = validate(p)
    assert "WF113" in r.codes()
    monkeypatch.setenv("WF_HEALTH_SAMPLE", "4")
    r = validate(p)
    assert "WF113" not in r.codes()


# ----------------------------------------- reporter atomicity (satellite)


def test_reporter_never_serves_torn_files(tmp_path):
    """A reader polling snapshot.json / metrics.prom while the reporter
    rewrites them every 50 ms must never observe a torn (unparseable or
    empty) file — the tmp+fsync+os.replace contract."""
    from windflow_tpu.observability.reporter import Reporter
    reg = MetricsRegistry("torn-test", health=True)
    src, chain = _small_chain()
    reg.register_chain("chain", chain)
    rep = Reporter(reg, str(tmp_path), interval_s=0.05)
    rep.start()
    try:
        deadline = time.monotonic() + 0.6
        reads = 0
        while time.monotonic() < deadline:
            sj = tmp_path / "snapshot.json"
            if sj.exists():
                text = sj.read_text()
                assert text.strip(), "torn/empty snapshot.json served"
                json.loads(text)                      # must always parse
                reads += 1
            pm = tmp_path / "metrics.prom"
            if pm.exists():
                assert pm.read_text().strip(), "torn/empty metrics.prom"
    finally:
        rep.stop()
    assert reads > 0 and rep.ticks >= 2
    assert not list(tmp_path.glob("*.tmp*")), "tmp debris left behind"


def test_loader_tolerates_torn_jsonl(tmp_path):
    good = {"graph": "g", "operators": [], "totals": {}}
    with open(tmp_path / "snapshots.jsonl", "w") as f:
        f.write(json.dumps(good) + "\n")
        f.write('{"graph": "g", "oper')          # torn mid-append
    latest, series = dh.load_snapshots(str(tmp_path))
    assert latest == good and len(series) == 1
    with open(tmp_path / "events.jsonl", "w") as f:
        f.write(json.dumps({"event": "eos"}) + "\n")
        f.write('{"event": "comp')
    assert dh.load_journal(str(tmp_path)) == [{"event": "eos"}]


# ------------------------------------------------------- fleet federation


def _host_snap(wm, occ, compiles, tuples):
    return {
        "graph": "g", "wall_time": 1.0, "uptime_s": 2.0,
        "operators": [{"name": "join", "inputs_received": tuples,
                       "counters": {"overflow_drops": 1},
                       "service_time_us": {"p99": 100.0 * compiles,
                                           "samples": 4},
                       "event_time": {"watermark_ts": wm,
                                      "occupancy_pct": occ}}],
        "totals": {"inputs_received": tuples},
        "queues": {"src->0": occ},
        "recovery": {"restarts": 1},
        "control": {"counters": {"shed_batches": 2}},
        "e2e_latency_us": {"p99": 50.0, "samples": 3},
        "event_time": {"min_watermark_ts": wm,
                       "frontier_operator": "join",
                       "edge_skew_ts": {"0->1": wm}},
        "health": {
            "devices": [{"device": "tpu:0", "kind": "v5e",
                         "bytes_in_use": 10, "bytes_limit": 100,
                         "headroom_bytes": 90}],
            "state_bytes": {"join": 1000},
            "compile": {"compiles": compiles, "retraces": 0,
                        "retraces_unexpected": 0, "compile_s_total": 0.1},
            "device_time": {"pipe0": {"device_ms": 10.0, "dispatch_ms": 8.0,
                                      "samples": 2}},
        },
    }


def test_merge_snapshots_fleet_semantics():
    a, b = _host_snap(10, 40, 3, 100), _host_snap(7, 90, 2, 50)
    m = dh.merge_snapshots([a, b], hosts=["h0", "h1"])
    assert m["merged_from"] == 2
    assert [h["host"] for h in m["hosts"]] == ["h0", "h1"]
    # counters summed
    assert m["totals"]["inputs_received"] == 150
    op = m["operators"][0]
    assert op["inputs_received"] == 150
    assert op["counters"]["overflow_drops"] == 2
    # watermark frontier = MIN (slowest host), pressure = MAX (worst host)
    assert m["event_time"]["min_watermark_ts"] == 7
    assert m["event_time"]["frontier_host"] == "h1"
    assert op["event_time"]["watermark_ts"] == 7
    assert op["event_time"]["occupancy_pct"] == 90
    assert m["queues"]["src->0"] == 90
    # percentiles: worst host + summed samples
    assert op["service_time_us"]["p99"] == 300.0
    assert op["service_time_us"]["samples"] == 8
    # health: devices host-tagged, counters summed, ratio recomputed
    h = m["health"]
    assert {d["device"] for d in h["devices"]} == {"h0/tpu:0", "h1/tpu:0"}
    assert h["compile"]["compiles"] == 5
    assert h["state_bytes"]["join"] == 2000
    assert h["device_time"]["pipe0"]["samples"] == 4
    assert h["device_time"]["pipe0"]["dispatch_ratio"] == 0.8
    assert "pipe0" in h["dispatch_bound"]
    assert m["recovery"]["restarts"] == 2
    assert m["control"]["counters"]["shed_batches"] == 4


def test_merge_tolerates_partial_host():
    """A host whose snapshot is missing whole sections (torn mid-upgrade,
    or a seed-era emitter) still folds — the merge never KeyErrors, it
    just contributes nothing to the sections it lacks."""
    full = _host_snap(10, 40, 3, 100)
    partial = {"graph": "g", "operators": [
        {"name": "join", "inputs_received": 7}]}
    m = dh.merge_snapshots([full, partial], hosts=["h0", "h1"])
    assert m["merged_from"] == 2
    assert m["totals"]["inputs_received"] == 100      # full host only
    assert m["operators"][0]["inputs_received"] == 107
    assert m["queues"]["src->0"] == 40
    assert m["event_time"]["frontier_host"] == "h0"
    assert len(m["health"]["devices"]) == 1
    # and in the other order (partial host first sets the fold's seed)
    m2 = dh.merge_snapshots([partial, full], hosts=["h1", "h0"])
    assert m2["operators"][0]["inputs_received"] == 107


def test_merge_duplicate_host_tags_disambiguated():
    """Two --merge dirs with the same basename must not fold into one
    host's rows — duplicate tags get a #N suffix so host-tagged sections
    (devices, hosts) keep every host's data."""
    snaps = [_host_snap(10, 40, 1, 10), _host_snap(9, 50, 1, 20),
             _host_snap(8, 60, 1, 30)]
    m = dh.merge_snapshots(snaps, hosts=["mon", "mon", "mon"])
    assert [h["host"] for h in m["hosts"]] == ["mon", "mon#2", "mon#3"]
    assert {d["device"] for d in m["health"]["devices"]} == {
        "mon/tpu:0", "mon#2/tpu:0", "mon#3/tpu:0"}
    assert m["totals"]["inputs_received"] == 60


def test_merge_seed_era_schema_reads_as_zero():
    """Seed-era snapshots carry no schema field: they fold as version 0,
    and mixing them with stamped hosts flags — never silently folds —
    the disagreement."""
    old, new = _host_snap(1, 1, 1, 1), _host_snap(1, 1, 1, 1)
    new["schema"] = dh.SNAPSHOT_SCHEMA
    m = dh.merge_snapshots([old, new], hosts=["h0", "h1"])
    assert m["schema"] == dh.SNAPSHOT_SCHEMA
    assert m["schema_mismatch"] == {"h0": 0, "h1": dh.SNAPSHOT_SCHEMA}
    # an all-seed-era fleet agrees with itself: version 0, no flag
    m0 = dh.merge_snapshots([_host_snap(1, 1, 1, 1)] * 2,
                            hosts=["h0", "h1"])
    assert m0["schema"] == 0 and "schema_mismatch" not in m0


def test_merge_monitoring_dirs_torn_host(tmp_path):
    """A host dir whose snapshots.jsonl was torn mid-append (the host
    died writing) still merges: the torn tail is dropped by the loader,
    the series aligns to the shortest host, the journal concatenates."""
    for name, ticks, torn in (("ha", 3, False), ("hb", 2, True)):
        d = tmp_path / name
        d.mkdir()
        with open(d / "snapshots.jsonl", "w") as f:
            for i in range(ticks):
                s = _host_snap(10 + i, 40, 1, 10 * (i + 1))
                s["wall_time"] = float(i)
                f.write(json.dumps(s) + "\n")
            if torn:
                f.write('{"graph": "g", "oper')       # died mid-write
        with open(d / "events.jsonl", "w") as f:
            f.write(json.dumps({"event": "eos", "wall": float(ticks)})
                    + "\n")
    merged, series, journal = dh.merge_monitoring_dirs(
        [str(tmp_path / "ha"), str(tmp_path / "hb")])
    assert merged["merged_from"] == 2
    assert [h["host"] for h in merged["hosts"]] == ["ha", "hb"]
    assert len(series) == 2                           # min(3, 2 whole lines)
    assert merged["totals"]["inputs_received"] == 30 + 20
    assert [e["event"] for e in journal] == ["eos", "eos"]


def test_headroom_risk_flags():
    devs = [{"device": "tpu:0", "headroom_bytes": 5, "bytes_limit": 100},
            {"device": "tpu:1", "headroom_bytes": 50, "bytes_limit": 100},
            {"device": "cpu:0"}]
    assert dh.headroom_risks(devs) == ["tpu:0"]


# ------------------------------------------------------------ the CLIs


def _load_cli(name):
    spec = importlib.util.spec_from_file_location(
        f"wf_cli_{name}", os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_wf_health_cli_merge_and_exit_contract(tmp_path, capsys):
    """THE acceptance loop: a health-on join run, its artifacts duplicated
    as a second 'host', merged by wf_health.py --json — ledger + merged
    provenance render; missing inputs exit 2."""
    import shutil
    run_q3(monitoring=_cfg(tmp_path, sub="h0"))
    shutil.copytree(tmp_path / "h0", tmp_path / "h1")
    cli = _load_cli("wf_health")
    rc = cli.main(["--merge", str(tmp_path / "h0"), str(tmp_path / "h1"),
                   "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    data = json.loads(out)
    assert data["merged_from"] == 2
    assert [h["host"] for h in data["hosts"]] == ["h0", "h1"]
    h = data["health"]
    assert h["compile"]["compiles"] >= 2          # summed across hosts
    assert h["state_bytes"]
    assert len(h["devices"]) == 2 * len(jax.local_devices())
    # human report renders every section
    rc = cli.main(["--merge", str(tmp_path / "h0"), str(tmp_path / "h1")])
    out = capsys.readouterr().out
    assert rc == 0
    for want in ("HBM memory ledger", "compile/retrace ledger",
                 "device-time attribution", "state footprints"):
        assert want in out
    # single-dir mode + exit contract
    rc = cli.main(["--monitoring-dir", str(tmp_path / "h0")])
    assert rc == 0
    capsys.readouterr()
    rc = cli.main(["--monitoring-dir", str(tmp_path / "nope")])
    assert rc == 2


def test_wf_state_cli_merge(tmp_path, capsys):
    import shutil
    run_q3(monitoring=_cfg(tmp_path, sub="h0", event_time=True))
    shutil.copytree(tmp_path / "h0", tmp_path / "h1")
    cli = _load_cli("wf_state")
    rc = cli.main(["--merge", str(tmp_path / "h0"), str(tmp_path / "h1"),
                   "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    data = json.loads(out)
    assert data["merged_from"] == 2 and len(data["hosts"]) == 2


def test_bench_health_compile_stats():
    bench_dir = REPO
    spec = importlib.util.spec_from_file_location(
        "wf_bench_health", os.path.join(bench_dir, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    stats = mod._health_compile_stats(steps=3, batch=512)
    assert stats["steps"] == 3
    assert stats["compiles"] >= 1
    assert stats["retraces_unexpected"] == 0
    assert 0 < stats["compiles_per_step"] <= stats["compiles"]
    assert dh.get_active() is None                # ledger restored
