"""End-to-end tests of the minimum slice: Source -> Map -> Filter -> Sink.

Mirrors the reference oracle pattern (src/graph_test/test_graph_1.cpp:77-87): run the
same stream with different batch sizes / configurations and assert the sink total is
invariant — result invariance under execution geometry is the core property."""

import numpy as np
import jax.numpy as jnp

import windflow_tpu as wf


def _expected_sum(total):
    # source i -> value i; map v -> v*2+1; filter keeps even ids
    s = 0
    for i in range(total):
        if i % 2 == 0:
            s += i * 2 + 1
    return s


def build(total, batch_size):
    src = wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=total, num_keys=4)
    m = wf.Map(lambda t: {"v": t.v * 2 + 1})
    f = wf.Filter(lambda t: t.id % 2 == 0)
    rsink = wf.ReduceSink(lambda t: t.v.astype(jnp.int64)
                          if False else t.v.astype(jnp.int32))
    return wf.Pipeline(src, [m, f, rsink], batch_size=batch_size)


def test_map_filter_reduce_sum():
    total = 1000
    res = build(total, 128).run()
    assert int(res["reduce_sink"]) == _expected_sum(total)


def test_invariance_under_batch_size():
    total = 777  # non-multiple of batch size: exercises tail masking
    sums = []
    for bs in (64, 100, 777, 1024):
        res = build(total, bs).run()
        sums.append(int(res["reduce_sink"]))
    assert len(set(sums)) == 1
    assert sums[0] == _expected_sum(total)


def test_host_sink_receives_live_tuples_only():
    total = 100
    got = {"ids": [], "eos": 0}

    def cb(view):
        if view is None:
            got["eos"] += 1
            return
        got["ids"].extend(view["id"].tolist())

    src = wf.Source(lambda i: {"v": i * 1.0}, total=total)
    f = wf.Filter(lambda t: t.v < 10)
    sink = wf.Sink(cb)
    wf.Pipeline(src, [f], sink, batch_size=32).run()
    assert sorted(got["ids"]) == list(range(10))
    assert got["eos"] == 1


def test_flatmap_fanout():
    total = 50
    # each tuple emits v and -v (second push masked for odd ids)
    def fm(t, shipper):
        shipper.push({"v": t.v})
        shipper.push({"v": -t.v}, when=t.id % 2 == 0)

    src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=total)
    op = wf.FlatMap(fm, max_fanout=2)
    rsink = wf.ReduceSink(lambda t: jnp.ones((), jnp.int32))  # count outputs
    res = wf.Pipeline(src, [op, rsink], batch_size=16).run()
    assert int(res["reduce_sink"]) == total + total // 2


def test_filtermap_optional_variant():
    total = 60
    op = wf.FilterMap(lambda t: ({"w": t.v + 100.0}, t.v % 3 == 0))
    src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=total)
    rsink = wf.ReduceSink(lambda t: t.w)
    res = wf.Pipeline(src, [op, rsink], batch_size=25).run()
    expect = sum(v + 100.0 for v in range(total) if v % 3 == 0)
    np.testing.assert_allclose(float(res["reduce_sink"]), expect)


def test_rich_map_receives_context():
    total = 20
    seen = []

    def rich_map(t, ctx):
        seen.append(ctx.getParallelism())
        return {"v": t.v + ctx.getReplicaIndex()}

    src = wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=total)
    m = wf.Map(rich_map, parallelism=3)
    rsink = wf.ReduceSink(lambda t: t.v)
    res = wf.Pipeline(src, [m, rsink], batch_size=8).run()
    assert seen and seen[0] == 3
    assert int(res["reduce_sink"]) == sum(range(total))
