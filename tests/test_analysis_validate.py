"""Pillar-1 gate: ``analysis.validate`` passes every real topology shipped in
this repo (the examples' graphs, the mp_test matrix) with zero errors, and
every ``WF1xx`` diagnostic code fires on a minimally-broken graph — the
shift-left counterpart of discovering the same misconfiguration mid-stream."""

import jax.numpy as jnp
import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu import ControlConfig, FaultPlan
from windflow_tpu.analysis import ValidationError, validate
from windflow_tpu.basic import win_type_t
from windflow_tpu.benchmarks import ysb
from windflow_tpu.operators.source import GeneratorSource
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.runtime.supervisor import SupervisedPipeline

from test_mp_matrix import CASES, K, TOTAL  # noqa: F401 — topology fixtures


def _sink():
    return wf.Sink(lambda view: None)


def _src(total=200, num_keys=1):
    return wf.Source(lambda i: {"v": ((i * 13) % 23).astype(jnp.float32)},
                     total=total, num_keys=num_keys)


# ---------------------------------------------------- positive: repo graphs


def test_example_01_wordcount_graph_validates():
    """The graph of examples/01_wordcount.py, built but not run."""
    VOCAB = 50

    def make_words(i):
        return {"w": jnp.stack([(i * 7) % VOCAB, (i * 13) % VOCAB,
                                (i * 29) % VOCAB])}

    def split_words(t, shipper):
        for j in range(3):
            shipper.push({"word": t.w[j]})

    g = wf.PipeGraph("wordcount", batch_size=256)
    (g.add_source(wf.Source(make_words, total=3000))
     .add(wf.FlatMap(split_words, max_fanout=3))
     .add(wf.Map(lambda t: {"one": jnp.ones((), jnp.int32), "word": t.word}))
     .add(wf.KeyBy(lambda t: t.word, num_keys=VOCAB))
     .add(wf.Accumulator(lambda t: t.data["one"], init_value=0,
                         num_keys=VOCAB))
     .add_sink(_sink()))
    report = validate(g)
    assert report.ok, str(report)
    assert not report.warnings, str(report)


def test_example_02_ysb_pipeline_validates():
    """The YSB pipeline of examples/02_ysb_windows.py."""
    p = wf.Pipeline(ysb.make_source(total=40_000), list(ysb.make_ops()),
                    _sink(), batch_size=4096)
    report = validate(p)
    assert report.ok, str(report)


def test_example_03_checkpoint_chain_validates():
    """The raw CompiledChain of examples/03_checkpoint_resume.py."""
    src = _src(total=4000, num_keys=8)
    op = wf.Key_FFAT(lambda t: t.v, jnp.add,
                     spec=WindowSpec(64, 32, win_type_t.CB), num_keys=8)
    chain = wf.CompiledChain([op], src.payload_spec(), batch_capacity=256)
    report = validate(chain)
    assert report.ok, str(report)


def test_example_04_multichip_chain_validates():
    """The (unsharded) chain of examples/04_multichip.py — sharding wraps
    the same compiled chain, so its spec flow is the validated surface."""
    src = wf.Source(lambda i: {"v": ((i * 7) % 31).astype(jnp.float32)},
                    total=8000, num_keys=16)
    op = wf.Key_FFAT(lambda t: t.v, jnp.add,
                     spec=WindowSpec(50, 25, win_type_t.TB), num_keys=16)
    chain = wf.CompiledChain([op], src.payload_spec(), batch_capacity=512)
    report = validate(chain)
    assert report.ok, str(report)


def test_example_05_supervised_pipeline_validates():
    """The SupervisedPipeline of examples/05_recovery_and_backpressure.py."""
    TOT, BATCH, KK = 2000, 100, 4

    def factory(from_batch=0):
        def gen():
            for s in range(from_batch * BATCH, TOT, BATCH):
                ids = np.arange(s, s + BATCH, dtype=np.int32)
                yield ({"v": ((ids * 7) % 31).astype(np.float32)},
                       ids % KK, ids)
        return gen()

    src = GeneratorSource(factory, {"v": jnp.zeros((), jnp.float32)})
    op = wf.Win_Seq(lambda wid, it: it.sum("v"),
                    WindowSpec(25, 25, win_type_t.TB), num_keys=KK)
    sp = SupervisedPipeline(src, [op], _sink(), batch_size=BATCH)
    report = validate(sp)
    assert report.ok, str(report)


@pytest.mark.parametrize("case", sorted(CASES))
def test_mp_matrix_topologies_validate(case):
    """Every mp_test-matrix topology flows specs cleanly end to end."""
    src = _src(total=TOTAL, num_keys=K)
    ops = CASES[case]()
    if not isinstance(ops, (list, tuple)):
        ops = [ops]
    p = wf.Pipeline(src, list(ops), _sink(), batch_size=48)
    report = validate(p)
    assert report.ok, f"{case}:\n{report}"


def test_threaded_pipeline_with_window_validates():
    """A ThreadedPipeline containing a geometry-sensitive (windowed)
    operator validates clean — pins the validator against corrupting the
    already-bound segment chains (bind_geometry must NOT be re-invoked with
    validator-chosen values)."""
    src = _src(total=192, num_keys=K)
    win = wf.Win_Seq(lambda wid, it: it.sum("v"),
                     WindowSpec(12, 6, win_type_t.TB), num_keys=K)
    tp = wf.ThreadedPipeline(src, [[wf.Map(lambda t: {"v": t.v + 1.0})],
                                   [win]],
                             _sink(), batch_size=32, control=False)
    a_before = win.A
    report = validate(tp)
    assert report.ok, str(report)
    assert win.A == a_before, "validator re-bound an already-bound chain"
    assert any(d.where.startswith("seg") for d in report.diagnostics) \
        or not report.diagnostics


def test_split_merge_graph_validates():
    """A split/merge DAG (the PipeGraph-native shape) validates clean."""
    g = wf.PipeGraph("diamond", batch_size=64)
    mp = g.add_source(_src(total=400))
    mp.add(wf.Map(lambda t: {"v": t.v + 1.0}))
    mp.split(lambda t: (t.data["v"] > 10.0).astype(jnp.int32), 2)
    b0 = mp.select(0).add(wf.Map(lambda t: {"v": t.v * 2.0}))
    b1 = mp.select(1).add(wf.Map(lambda t: {"v": t.v * 3.0}))
    merged = b0.merge(b1)
    merged.add(wf.Filter(lambda t: t.v > 0.0)).add_sink(_sink())
    report = validate(g)
    assert report.ok, str(report)
    assert not report.warnings, str(report)


# ------------------------------------------------- negative: each code fires


def test_wf100_empty_graph():
    report = validate(wf.PipeGraph("empty"))
    assert [d.code for d in report.errors] == ["WF100"]


def test_wf100_unknown_object():
    report = validate(object())
    assert [d.code for d in report.errors] == ["WF100"]


def test_wf101_spec_mismatch_between_chained_operators():
    """The tentpole case: an operator destructures a field its upstream does
    not produce — caught pre-run with the operator path in the diagnostic."""
    g = wf.PipeGraph("broken", batch_size=64)
    (g.add_source(_src())
     .add(wf.Map(lambda t: {"x": t.v * 2.0}))       # renames v -> x
     .add(wf.Map(lambda t: {"y": t.v + 1.0}))       # still expects v: broken
     .add_sink(_sink()))
    report = validate(g)
    assert not report.ok
    [err] = report.errors
    assert err.code == "WF101"
    assert "ops[1]" in err.where
    assert "payload" in err.hint


def test_wf101_bad_split_function():
    g = wf.PipeGraph("badsplit", batch_size=64)
    mp = g.add_source(_src())
    mp.split(lambda t: (t.data["nope"] > 0).astype(jnp.int32), 2)
    for i in range(2):
        mp.select(i).add_sink(_sink())
    report = validate(g)
    assert "WF101" in report.codes()
    assert any(".split" in d.where for d in report.errors)


def test_wf102_weak_type_drift():
    """A Python-scalar payload leaf — the retrace hazard — warns, and names
    the leaf."""
    g = wf.PipeGraph("weak", batch_size=64)
    (g.add_source(_src())
     .add(wf.Map(lambda t: {"v": t.v, "c": 1.0}))   # weak f32 constant
     .add_sink(_sink()))
    report = validate(g)
    assert report.ok                                 # warning, not error
    [warn] = [d for d in report.diagnostics if d.code == "WF102"]
    assert "c" in warn.message


def test_wf103_fault_site_not_threaded_through_driver():
    plan = FaultPlan([{"site": "checkpoint.save", "at": [1]}])
    tp = wf.ThreadedPipeline(_src(), [[wf.Map(lambda t: {"v": t.v})]],
                             _sink(), batch_size=32, control=False)
    report = validate(tp, faults=plan)
    [d] = [d for d in report.diagnostics if d.code == "WF103"]
    assert d.severity == "warning" and "checkpoint.save" in d.message
    # the same site IS threaded under supervision: no WF103 there
    p = wf.Pipeline(_src(), [wf.Map(lambda t: {"v": t.v})], _sink(),
                    batch_size=32, control=False)
    assert "WF103" not in validate(p, faults=plan, supervised=True).codes()


def test_wf103_unparseable_plan_is_error():
    p = wf.Pipeline(_src(), [wf.Map(lambda t: {"v": t.v})], _sink(),
                    batch_size=32, control=False)
    report = validate(p, faults='{"faults": [{"site": "not.a.site"}]}')
    [d] = [d for d in report.diagnostics if d.code == "WF103"]
    assert d.severity == "error"


def test_wf104_watermarks_degenerate_on_tiny_ring():
    tp = wf.ThreadedPipeline(_src(), [[wf.Map(lambda t: {"v": t.v})]],
                             _sink(), batch_size=32, queue_capacity=1,
                             control=ControlConfig(backpressure=True,
                                                   autotune=False))
    report = validate(tp)
    hits = [d for d in report.diagnostics if d.code == "WF104"]
    assert hits and all("capacity 1" in d.message for d in hits)


def test_wf104_illegal_graph_edge_capacity_is_an_error():
    """queue_capacity resolving < 1 would ValueError mid-run(threaded=True);
    the validator surfaces it pre-run — but only under threaded=True, since
    the push driver never builds rings."""
    g = wf.PipeGraph("badcap", batch_size=64, queue_capacity=0)
    g.add_source(_src()).add_sink(_sink())
    [d] = [d for d in validate(g, threaded=True).diagnostics
           if d.code == "WF104"]
    assert d.severity == "error" and "queue_capacity" in d.where
    assert validate(g).ok, "push-driver validation must not check rings"


def test_wf104_clean_on_roomy_ring():
    tp = wf.ThreadedPipeline(_src(), [[wf.Map(lambda t: {"v": t.v})]],
                             _sink(), batch_size=32, queue_capacity=8,
                             control=ControlConfig(backpressure=True,
                                                   autotune=False))
    assert "WF104" not in validate(tp).codes()


def test_wf105_wall_clock_bucket_under_supervision():
    p = wf.Pipeline(_src(), [wf.Map(lambda t: {"v": t.v})], _sink(),
                    batch_size=32, control=False)
    cfg = ControlConfig(admission=True, rate_tps=100.0, autotune=False,
                        backpressure=False)
    report = validate(p, control=cfg, supervised=True)
    [d] = report.errors
    assert d.code == "WF105"
    # the deterministic bucket is legal under supervision
    det = ControlConfig(admission=True, refill_per_batch=32.0,
                        autotune=False, backpressure=False)
    assert validate(p, control=det, supervised=True).ok
    # and the wall-clock bucket is fine WITHOUT supervision
    assert validate(p, control=cfg).ok


def test_wf106_prefetch_exceeds_ring():
    tp = wf.ThreadedPipeline(_src(), [[wf.Map(lambda t: {"v": t.v})]],
                             _sink(), batch_size=32, queue_capacity=4,
                             prefetch=16, control=False)
    [d] = [d for d in validate(tp).diagnostics if d.code == "WF106"]
    assert "16" in d.message and d.severity == "warning"


def test_wf107_dangling_branch():
    g = wf.PipeGraph("dangle", batch_size=64)
    mp = g.add_source(_src())
    mp.split(lambda t: (t.data["v"] > 3).astype(jnp.int32), 2)
    mp.select(0).add_sink(_sink())
    mp.select(1).add(wf.Map(lambda t: {"v": t.v}))   # leaf, no sink
    [d] = [d for d in validate(g).diagnostics if d.code == "WF107"]
    assert d.severity == "warning"


def test_wf107_reduce_sink_is_a_real_terminal():
    """An in-graph ReduceSink terminates a branch — no dangling warning."""
    g = wf.PipeGraph("reduce", batch_size=64)
    (g.add_source(_src())
     .add(wf.ReduceSink(lambda t: t.v, name="total")))
    assert "WF107" not in validate(g).codes()


def test_raise_if_errors():
    g = wf.PipeGraph("broken", batch_size=64)
    (g.add_source(_src())
     .add(wf.Map(lambda t: {"y": t.nope}))
     .add_sink(_sink()))
    report = validate(g)
    with pytest.raises(ValidationError) as ei:
        report.raise_if_errors()
    assert "WF101" in str(ei.value)
    assert ei.value.report is report


def test_report_json_roundtrip():
    g = wf.PipeGraph("empty")
    j = validate(g).to_json()
    assert j["diagnostics"][0]["code"] == "WF100"
    assert j["target"].startswith("PipeGraph")


# ----------------------------------------------------------- WF110 dispatch


def test_wf110_sequence_ids_with_dispatch_under_supervision():
    from windflow_tpu.observability import TraceConfig
    p = wf.Pipeline(_src(), [wf.Map(lambda t: {"v": t.v})], _sink(),
                    batch_size=32, dispatch=4)
    rep = validate(p, supervised=True, trace=TraceConfig(ids="sequence"))
    assert {"WF108", "WF110"} <= set(rep.codes())
    [d] = [d for d in rep.diagnostics if d.code == "WF110"]
    assert d.severity == "error" and "sequence" in d.message
    # position ids (the default) are legal with dispatch under supervision
    assert "WF110" not in validate(
        p, supervised=True, trace=TraceConfig(ids="position")).codes()
    # and sequence ids are fine live (no supervision)
    assert "WF110" not in validate(
        p, trace=TraceConfig(ids="sequence")).codes()


def test_wf110_wall_clock_admission_with_dispatch_under_supervision():
    from windflow_tpu.control import ControlConfig
    p = wf.Pipeline(_src(), [wf.Map(lambda t: {"v": t.v})], _sink(),
                    batch_size=32, dispatch=8)
    cfg = ControlConfig(admission=True, rate_tps=50.0, autotune=False,
                        backpressure=False)
    rep = validate(p, control=cfg, supervised=True)
    codes = rep.codes()
    assert "WF105" in codes and "WF110" in codes      # both name the hazard
    det = ControlConfig(admission=True, refill_per_batch=32.0,
                        autotune=False, backpressure=False)
    assert "WF110" not in validate(p, control=det, supervised=True).codes()


def test_wf110_k_exceeds_ring_capacity_warns():
    tp = wf.ThreadedPipeline(_src(), [[wf.Map(lambda t: {"v": t.v})]],
                             _sink(), batch_size=32, queue_capacity=4,
                             dispatch=16, control=False)
    hits = [d for d in validate(tp).diagnostics if d.code == "WF110"]
    assert hits and all(d.severity == "warning" for d in hits)
    assert any("16" in d.message and "4" in d.message for d in hits)
    # K within every ring is clean
    tp2 = wf.ThreadedPipeline(_src(), [[wf.Map(lambda t: {"v": t.v})]],
                              _sink(), batch_size=32, queue_capacity=8,
                              dispatch=4, control=False)
    assert "WF110" not in validate(tp2).codes()


def test_wf110_unresolvable_config_is_an_error():
    p = wf.Pipeline(_src(), [wf.Map(lambda t: {"v": t.v})], _sink(),
                    batch_size=32, dispatch={"k": -2})
    [d] = [d for d in validate(p).diagnostics if d.code == "WF110"]
    assert d.severity == "error" and "resolve" in d.message


def test_wf110_k1_and_off_are_silent():
    p = wf.Pipeline(_src(), [wf.Map(lambda t: {"v": t.v})], _sink(),
                    batch_size=32, dispatch=1)
    assert "WF110" not in validate(p, supervised=True).codes()
    p2 = wf.Pipeline(_src(), [wf.Map(lambda t: {"v": t.v})], _sink(),
                    batch_size=32)
    assert "WF110" not in validate(p2, supervised=True).codes()


def test_wf110_explicit_dispatch_overrides_stored():
    p = wf.Pipeline(_src(), [wf.Map(lambda t: {"v": t.v})], _sink(),
                    batch_size=32)                     # no stored dispatch
    tp_cfg = {"k": 16}
    rep = validate(p, supervised=True, dispatch=tp_cfg,
                   control=wf.ControlConfig(admission=True, rate_tps=10.0,
                                            autotune=False,
                                            backpressure=False))
    assert "WF110" in rep.codes()
