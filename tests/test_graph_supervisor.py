"""Supervised PipeGraph execution (run_graph_supervised): injected failures on a
split+merge DAG recover from aligned checkpoints with exactly-once delivery on
every sink; budget exhaustion raises RestartExhausted."""

import numpy as np
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import Mode, win_type_t
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.runtime.pipegraph import PipeGraph
from windflow_tpu.runtime.supervisor import RestartExhausted

TOTAL, K = 360, 3


def build(win_sink, plain_sink, mode=Mode.DEFAULT):
    g = PipeGraph("sup", batch_size=40, mode=mode)
    a = g.add_source(wf.Source(lambda i: {"v": (i % 9).astype(jnp.float32)},
                               total=TOTAL, num_keys=K, name="a"))
    b = g.add_source(wf.Source(lambda i: {"v": (i % 7).astype(jnp.float32)},
                               total=TOTAL // 2, num_keys=K, name="b",
                               ts_fn=lambda i: i * 2))
    m = a.merge(b).split(lambda t: t.v % 2 == 0, 2)
    (m.select(1).add(wf.Win_Seq(lambda wid, it: it.sum("v"),
                                WindowSpec(12, 12, win_type_t.CB), num_keys=K))
     .add_sink(wf.Sink(win_sink)))
    m.select(0).add_sink(wf.Sink(plain_sink))
    return g


def collectors():
    wins, plains = [], []

    def win_cb(view):
        if view is None:
            return
        wins.extend(zip(view["key"].tolist(), view["id"].tolist(),
                        np.asarray(view["payload"]).tolist()))

    def plain_cb(view):
        if view is None:
            return
        plains.extend(zip(view["id"].tolist(),
                          np.asarray(view["payload"]["v"]).tolist()))

    return wins, plains, win_cb, plain_cb


def inject_failures(g, fail_at):
    orig = g._push
    n = {"c": 0}
    remaining = sorted(fail_at)

    def flaky(mp, batch):
        n["c"] += 1
        if remaining and n["c"] == remaining[0]:
            remaining.pop(0)
            raise RuntimeError(f"injected device fault at push #{n['c']}")
        return orig(mp, batch)

    g._push = flaky


def test_supervised_graph_no_failure_matches_plain():
    w0, p0, wc0, pc0 = collectors()
    build(wc0, pc0).run()
    w1, p1, wc1, pc1 = collectors()
    build(wc1, pc1).run_supervised(checkpoint_every=3)
    assert sorted(w1) == sorted(w0) and sorted(p1) == sorted(p0)
    assert len(w0) > 0 and len(p0) > 0


def test_supervised_graph_recovers_exactly_once():
    w0, p0, wc0, pc0 = collectors()
    build(wc0, pc0).run()

    w1, p1, wc1, pc1 = collectors()
    g = build(wc1, pc1)
    inject_failures(g, fail_at=[4, 9, 15])
    g.run_supervised(checkpoint_every=3, max_restarts=3)
    assert g.supervised_restarts == 3
    assert sorted(w1) == sorted(w0)         # no lost, duplicated, or torn results
    assert sorted(p1) == sorted(p0)


def test_supervised_graph_budget_exhaustion():
    w, p, wc, pc = collectors()
    g = build(wc, pc)
    inject_failures(g, fail_at=[2, 3, 4, 5, 6])     # 5 faults in one interval
    with pytest.raises(RestartExhausted):
        g.run_supervised(checkpoint_every=100, max_restarts=3)


def test_supervised_deterministic_merge_recovers():
    """DETERMINISTIC mode under supervision: Ordering_Node state (pending
    held-back batches, per-channel watermarks, renumber counter) snapshots and
    restores across injected failures; results equal the unsupervised run."""
    def build_det(sink_cb):
        g = PipeGraph("sup_det", batch_size=30, mode=Mode.DETERMINISTIC)
        a = g.add_source(wf.Source(lambda i: {"v": (i % 5).astype(jnp.float32)},
                                   total=120, num_keys=2, name="a",
                                   ts_fn=lambda i: 2 * i))
        b = g.add_source(wf.Source(lambda i: {"v": (i % 7).astype(jnp.float32)},
                                   total=120, num_keys=2, name="b",
                                   ts_fn=lambda i: 2 * i + 1))
        (a.merge(b)
         .add(wf.Win_Seq(lambda wid, it: it.sum("v"),
                         WindowSpec(30, 30, win_type_t.TB, delay=60),
                         num_keys=2))
         .add_sink(wf.Sink(sink_cb)))
        return g

    def collect(acc):
        def cb(view):
            if view is None:
                return
            acc.extend(zip(view["key"].tolist(), view["id"].tolist(),
                           np.asarray(view["payload"]).tolist()))
        return cb

    plain = []
    build_det(collect(plain)).run()

    sup = []
    g = build_det(collect(sup))
    inject_failures(g, fail_at=[3, 7])
    g.run_supervised(checkpoint_every=2, max_restarts=3)
    assert g.supervised_restarts == 2
    assert sorted(sup) == sorted(plain) and len(plain) > 0


def test_shims_uninstalled_even_when_recovery_fails():
    """The _CommitBufferSink output-commit shims must be removed by the
    ``finally`` in run_graph_supervised on EVERY exit path — after a
    RestartExhausted each pipe's sink is the original user Sink again."""
    w, p, wc, pc = collectors()
    g = build(wc, pc)
    sinks_before = [mp.sink for mp in g._all_pipes() if mp.sink is not None]
    inject_failures(g, fail_at=[2, 3, 4, 5, 6])
    with pytest.raises(RestartExhausted) as ei:
        g.run_supervised(checkpoint_every=100, max_restarts=3,
                         backoff_base=0.0)
    assert isinstance(ei.value.__cause__, RuntimeError)
    sinks_after = [mp.sink for mp in g._all_pipes() if mp.sink is not None]
    assert sinks_after == sinks_before
    assert all(isinstance(s, wf.Sink) for s in sinks_after)
