"""Threaded PipeGraph driver: pipeline-parallel execution over native SPSC edges
must produce identical results to the sequential push driver."""

import numpy as np
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.runtime.pipegraph import PipeGraph


def build(threaded):
    total = 300
    g = PipeGraph("t", batch_size=64)
    src = wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=total)
    mp = g.add_source(src)
    mp.split(lambda t: (t.v % 2).astype(jnp.int32), 2)
    b0 = mp.select(0).add(wf.Map(lambda t: {"v": t.v * 10}, name="m0"))
    b1 = mp.select(1).add(wf.Map(lambda t: {"v": t.v * 100}, name="m1"))
    merged = b0.merge(b1)
    merged.add(wf.ReduceSink(lambda t: t.v, name="sum"))
    return g.run(threaded=threaded)


def test_threaded_diamond_matches_sequential():
    seq = int(build(False)["sum"])
    thr = int(build(True)["sum"])
    assert seq == thr
    total = 300
    expect = sum(i * 10 for i in range(total) if i % 2 == 0) + \
        sum(i * 100 for i in range(total) if i % 2 == 1)
    assert seq == expect


def test_threaded_windowed_pipeline():
    total, K = 400, 2
    from windflow_tpu.operators.win_patterns import Key_FFAT
    from windflow_tpu.operators.window import WindowSpec
    got = []

    def cb(view):
        if view is None:
            return
        got.extend(zip(view["key"].tolist(), view["id"].tolist(),
                       np.asarray(view["payload"]).tolist()))

    g = PipeGraph("w", batch_size=80)
    src = wf.Source(lambda i: {"v": (i // K).astype(jnp.float32)},
                    total=total, num_keys=K)
    ff = Key_FFAT(lambda t: t.v, jnp.add, spec=WindowSpec(10, 10), num_keys=K)
    g.add_source(src).add(ff).add_sink(wf.Sink(cb))
    g.run(threaded=True)

    expect = []
    for k in range(K):
        vals = [float(i // K) for i in range(total) if i % K == k]
        for w in range((len(vals) - 1) // 10 + 1):
            expect.append((k, w, sum(vals[w * 10:(w + 1) * 10])))
    assert sorted(got) == sorted(expect)


def test_threaded_nested_split_and_3way_merge():
    """Threaded driver on the deeper graph_test shapes: nested split, 3-way
    merge covering the WHOLE outer split subtree (merge-full collapses it to
    the root pipe), then merge with an independent root (merge-ind)."""
    def build(threaded):
        g = PipeGraph("tg", batch_size=64)
        mp = g.add_source(wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=240,
                                    name="sa"))
        mp.split(lambda t: (t.v % 3 == 0).astype(jnp.int32), 2)
        b_rest, b_mul3 = mp.select(0), mp.select(1)
        b_mul3.add(wf.Map(lambda t: {"v": t.v * 1000}, name="mz"))
        b_rest.split(lambda t: (t.v % 3 - 1).astype(jnp.int32), 2)
        r1 = b_rest.select(0)
        r2 = b_rest.select(1).add(wf.Map(lambda t: {"v": t.v * 10}, name="m2"))
        ind = g.add_source(wf.Source(lambda i: {"v": (i + 900).astype(jnp.int32)},
                                     total=12, name="sb"))
        merged = r1.merge(r2, b_mul3).merge(ind)
        merged.add(wf.ReduceSink(lambda t: t.v, name="m"))
        return {k: int(v) for k, v in g.run(threaded=threaded).items()}

    seq, thr = build(False), build(True)
    assert seq == thr
    expect = (sum(i * 1000 for i in range(240) if i % 3 == 0)
              + sum(i for i in range(240) if i % 3 == 1)
              + sum(i * 10 for i in range(240) if i % 3 == 2)
              + sum(range(900, 912)))
    assert seq["m"] == expect


def test_nested_subtree_merge_stays_a_branch():
    """Merge-full of a NESTED subtree re-parents the merged pipe as a branch of
    the outer split (wf/pipegraph.hpp:822-846 Case 2.1), so merging it with an
    independent root must be rejected (get_MergedNodes2 LCA=root,
    wf/pipegraph.hpp:763-765) — while extending it with operators and a sink
    stays legal."""
    import pytest
    g = PipeGraph("tg2", batch_size=64)
    mp = g.add_source(wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=240,
                                name="sa"))
    mp.split(lambda t: (t.v % 3 == 0).astype(jnp.int32), 2)
    b_rest, b_mul3 = mp.select(0), mp.select(1)
    b_rest.split(lambda t: (t.v % 3 - 1).astype(jnp.int32), 2)
    merged = b_rest.select(0).merge(b_rest.select(1))
    ind = g.add_source(wf.Source(lambda i: {"v": (i + 900).astype(jnp.int32)},
                                 total=12, name="sb"))
    with pytest.raises(RuntimeError, match="not supported"):
        merged.merge(ind)
    ind.add(wf.ReduceSink(lambda t: t.v, name="i"))
    merged.add(wf.ReduceSink(lambda t: t.v, name="m"))
    b_mul3.add(wf.ReduceSink(lambda t: t.v, name="z"))
    res = g.run()
    assert int(res["z"]) == sum(i for i in range(240) if i % 3 == 0)
    assert int(res["m"]) == sum(i for i in range(240) if i % 3)
    assert int(res["i"]) == sum(range(900, 912))
