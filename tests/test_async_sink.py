"""AsyncResultShipper: overlapped device->host result shipping (the latency-path
sink; reference D2H overlap discipline, wf/win_seq_gpu.hpp:243-260,524)."""

import jax
import jax.numpy as jnp
import numpy as np

from windflow_tpu.runtime.async_sink import AsyncResultShipper


def test_ship_harvest_ordering_and_depth():
    sh = AsyncResultShipper(depth=2)
    f = jax.jit(lambda i: {"a": jnp.full((4,), i), "b": jnp.asarray(i * 2)})
    for i in range(5):
        sh.ship(f(i), tag=i)
    got = sh.harvest()                 # leaves 2 in flight
    assert [r.tag for r in got] == [0, 1, 2]
    assert len(sh) == 2
    rest = sh.drain()
    assert [r.tag for r in rest] == [3, 4]
    assert len(sh) == 0
    for r in got + rest:
        np.testing.assert_array_equal(r.value["a"], np.full((4,), r.tag))
        assert int(r.value["b"]) == r.tag * 2
        assert isinstance(r.value["a"], np.ndarray)
        assert r.receipt_time >= r.ship_time


def test_harvest_empty_and_keep_inflight():
    sh = AsyncResultShipper(depth=4)
    assert sh.harvest() == []
    sh.ship(jnp.zeros(3), tag="x")
    assert sh.harvest() == []          # still within depth
    [r] = sh.harvest(keep_inflight=0)
    assert r.tag == "x"
