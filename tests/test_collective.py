"""Explicit-collective patterns (parallel/collective.py) on the 8-device CPU mesh:
shard_map Win_MapReduce (psum combine over the partition axis), ring pane exchange
(ppermute halo), keyed all_to_all redistribution. Oracle: every collective result
must equal the single-device computation on the unsharded arrays."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from windflow_tpu.parallel.mesh import make_mesh
from windflow_tpu.parallel.collective import (wmr_map_reduce, ring_pane_windows,
                                              keyed_all_to_all)

MESH = 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(MESH, axis="part")


def test_wmr_psum_matches_local_sum(mesh):
    L = 64
    data = jnp.arange(L, dtype=jnp.float32) * 0.5
    valid = jnp.arange(L) % 5 != 0

    def map_fn(local, lv):
        return jnp.sum(jnp.where(lv, local, 0.0))

    f = jax.jit(wmr_map_reduce(map_fn, jnp.add, mesh, axis="part"))
    got = f(data, valid)
    want = jnp.sum(jnp.where(valid, data, 0.0))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_wmr_pmax_and_generic_combine(mesh):
    L = 64
    data = jnp.asarray(np.random.default_rng(0).normal(size=L), jnp.float32)
    valid = jnp.ones(L, bool)

    def map_fn(local, lv):
        return jnp.max(jnp.where(lv, local, -jnp.inf))

    got_max = jax.jit(wmr_map_reduce(map_fn, jnp.maximum, mesh, axis="part"))(data, valid)
    np.testing.assert_allclose(np.asarray(got_max), float(np.max(np.asarray(data))))

    # generic associative, non-commutative combine: 2x2 matrix product over
    # per-partition products (checks the all_gather + ordered tree fold path)
    mats = jnp.stack([jnp.eye(2) + 0.01 * jnp.asarray([[0, i], [i % 3, 0]], jnp.float32)
                      for i in range(16)])

    def map_mats(local, lv):
        res = jnp.eye(2)
        for i in range(local.shape[0]):
            res = res @ local[i]
        return res

    # jnp.dot is strictly pairwise (no batch polymorphism) — locks the
    # (partial, partial) -> partial contract of the generic combine
    f = jax.jit(wmr_map_reduce(map_mats, jnp.dot, mesh, axis="part"))
    got = f(mats, jnp.ones(16, bool))
    want = np.eye(2)
    for i in range(16):
        want = want @ np.asarray(mats[i])
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


def test_wmr_generic_combine_pytree_partials(mesh):
    # mean via (sum, count) pytree partials — the documented '(partial, partial) ->
    # partial, any pytree' contract of the generic combine path
    L = 64
    data = jnp.asarray(np.random.default_rng(3).normal(size=L), jnp.float32)
    valid = jnp.arange(L) % 3 != 0

    def map_fn(local, lv):
        return {"s": jnp.sum(jnp.where(lv, local, 0.0)),
                "n": jnp.sum(lv.astype(jnp.float32))}

    def combine(a, b):
        return {"s": a["s"] + b["s"], "n": a["n"] + b["n"]}

    got = jax.jit(wmr_map_reduce(map_fn, combine, mesh, axis="part"))(data, valid)
    want_s = float(jnp.sum(jnp.where(valid, data, 0.0)))
    want_n = float(jnp.sum(valid))
    np.testing.assert_allclose(float(got["s"]), want_s, rtol=1e-5)
    assert float(got["n"]) == want_n


@pytest.mark.parametrize("win_panes,slide_panes",
                         [(4, 2), (8, 4), (3, 1), (9, 3), (5, 3), (7, 5), (11, 2)])
def test_ring_pane_windows_matches_dense(win_panes, slide_panes):
    mesh = make_mesh(MESH, axis="win")
    Ptot = 64                                   # 8 panes per device
    panes = jnp.asarray(np.random.default_rng(1).normal(size=Ptot), jnp.float32)
    pane_valid = jnp.ones(Ptot, bool)
    f = jax.jit(ring_pane_windows(jnp.add, 0.0, mesh, win_panes=win_panes,
                                  slide_panes=slide_panes, axis="win"))
    res, valid = f(panes, pane_valid)
    res, valid = np.asarray(res).ravel(), np.asarray(valid).ravel()
    # dense single-device oracle: every full window starting at a multiple of slide
    # — the emitted set must not depend on the device count
    got = sorted(float(r) for r, v in zip(res, valid) if v)
    want = [float(np.sum(np.asarray(panes[s:s + win_panes])))
            for s in range(0, Ptot - win_panes + 1, slide_panes)]
    np.testing.assert_allclose(got, sorted(want), rtol=1e-5)


def test_keyed_all_to_all_ownership_and_conservation():
    mesh = make_mesh(MESH, axis="key")
    C = 128 * MESH
    rng = np.random.default_rng(2)
    keys = jnp.asarray(rng.integers(0, 57, C), jnp.int32)
    valid = jnp.asarray(rng.random(C) < 0.9)
    pay = {"v": jnp.arange(C, dtype=jnp.float32),
           "m": jnp.asarray(rng.normal(size=(C, 3)), jnp.float32)}
    f = jax.jit(keyed_all_to_all(mesh, axis="key", capacity=64))
    rk, rv, rp, n_left = f(keys, valid, pay)
    assert int(np.asarray(n_left).sum()) == 0      # capacity 64 is ample: complete
    rk, rv = np.asarray(rk), np.asarray(rv)
    rv_np = np.asarray(rp["v"])
    # every live row landed on its owner device
    per_dev = rk.shape[0] // MESH
    for d in range(MESH):
        sl = slice(d * per_dev, (d + 1) * per_dev)
        live = rk[sl][rv[sl]]
        assert np.all(live % MESH == d), f"device {d} received foreign keys"
    # conservation: the multiset of live (key, v) pairs is preserved
    want = sorted((int(k), float(v)) for k, v, ok in
                  zip(np.asarray(keys), np.asarray(pay["v"]), np.asarray(valid)) if ok)
    got = sorted((int(k), float(v)) for k, v, ok in zip(rk, rv_np, rv.ravel()) if ok)
    assert got == want
    # companion 2-D payload rides along consistently
    m = np.asarray(rp["m"])
    src_m = {float(v): np.asarray(pay["m"])[i] for i, v in enumerate(np.asarray(pay["v"]))}
    for i in range(rk.shape[0]):
        if rv.ravel()[i]:
            np.testing.assert_allclose(m[i], src_m[float(rv_np[i])])


def test_keyed_all_to_all_overflow_is_loud_not_silent():
    mesh = make_mesh(MESH, axis="key")
    C = 16 * MESH
    keys = jnp.zeros(C, jnp.int32)              # all rows -> device 0
    valid = jnp.ones(C, bool)
    pay = {"v": jnp.arange(C, dtype=jnp.float32)}
    f = jax.jit(keyed_all_to_all(mesh, axis="key", capacity=4))
    rk, rv, rp, n_left = f(keys, valid, pay)
    rv = np.asarray(rv).ravel()
    rk = np.asarray(rk)
    # capacity 4 per (src,dst) lane: device 0 receives at most 8*4 live rows
    assert rv.sum() == 4 * MESH
    assert np.all(rk[rv] == 0)
    # every row NOT delivered is accounted for: 16 live per source, 4 shipped
    n_left = np.asarray(n_left)
    assert n_left.shape == (MESH,)
    assert np.all(n_left == 12), n_left
    assert int(rv.sum()) + int(n_left.sum()) == C


def test_keyed_all_to_all_residue_identifies_left_rows():
    mesh = make_mesh(MESH, axis="key")
    C = 16 * MESH
    keys = jnp.zeros(C, jnp.int32)
    valid = jnp.ones(C, bool)
    pay = {"v": jnp.arange(C, dtype=jnp.float32)}
    f = jax.jit(keyed_all_to_all(mesh, axis="key", capacity=4, return_residue=True))
    rk, rv, rp, n_left, resid = f(keys, valid, pay)
    resid = np.asarray(resid)
    assert resid.shape == (C,)
    assert resid.sum() == int(np.asarray(n_left).sum())
    # delivered rows + residue rows partition the live set exactly
    delivered = sorted(float(v) for v, ok in
                       zip(np.asarray(rp["v"]).ravel(), np.asarray(rv).ravel()) if ok)
    left = sorted(float(v) for v, r in zip(np.asarray(pay["v"]), resid) if r)
    assert sorted(delivered + left) == [float(i) for i in range(C)]


def test_keyed_all_to_all_rejects_zero_capacity():
    import pytest
    mesh = make_mesh(MESH, axis="key")
    C = MESH * (MESH // 2)          # local rows < device count -> default cap 0
    keys = jnp.zeros(C, jnp.int32)
    valid = jnp.ones(C, bool)
    with pytest.raises(ValueError, match="capacity"):
        jax.jit(keyed_all_to_all(mesh, axis="key"))(
            keys, valid, {"v": jnp.zeros(C, jnp.float32)})


def test_keyed_all_to_all_lossless_delivers_everything():
    from windflow_tpu.parallel.collective import keyed_all_to_all_lossless
    mesh = make_mesh(MESH, axis="key")
    C = 16 * MESH
    rng = np.random.default_rng(7)
    # skewed keys: one hot key overflows its (src,dst) lane budget repeatedly
    keys = jnp.asarray(np.where(rng.random(C) < 0.7, 0, rng.integers(0, 29, C)),
                       jnp.int32)
    valid = jnp.asarray(rng.random(C) < 0.95)
    pay = {"v": jnp.arange(C, dtype=jnp.float32)}
    run = keyed_all_to_all_lossless(mesh, axis="key", capacity=3)
    rk, rv, rp, n_rounds = run(keys, valid, pay)
    assert n_rounds > 1                          # the skew actually forced rounds
    rk, rvm = np.asarray(rk), np.asarray(rv)
    # the multiset of live (key, v) pairs is fully preserved — nothing dropped
    want = sorted((int(k), float(v)) for k, v, ok in
                  zip(np.asarray(keys), np.asarray(pay["v"]), np.asarray(valid)) if ok)
    got = sorted((int(k), float(v)) for k, v, ok in
                 zip(rk, np.asarray(rp["v"]), rvm) if ok)
    assert got == want
