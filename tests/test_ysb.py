"""YSB (flagship macro-benchmark) correctness: the sum of all emitted window counts
must equal the number of view events in the stream (reference oracle: the sink
accumulates per-window counts, src/yahoo_test_cpu/test_ysb_kf.cpp), invariant under
batch size and across the KF (Key_FFAT) and WMR (Win_MapReduce) window variants."""

import re
import numpy as np
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.benchmarks import ysb

TOTAL = 3000        # 300 time units = 3 windows per campaign


def run_variant(make_ops_fn, batch_size, **kw):
    src = ysb.make_source(TOTAL)
    ops = make_ops_fn(**kw)
    results = []

    def cb(view):
        if view is None:
            return
        for k, w, c in zip(view["key"].tolist(), view["id"].tolist(),
                           np.asarray(view["payload"]).tolist()):
            results.append((int(k), int(w), int(c)))

    wf.Pipeline(src, ops, wf.Sink(cb), batch_size=batch_size).run()
    return sorted(results)


@pytest.mark.parametrize("batch_size", [256, 1000, TOTAL])
def test_ysb_kf_totals_match_oracle(batch_size):
    res = run_variant(ysb.make_ops, batch_size)
    assert res, "no window results emitted"
    assert sum(c for _, _, c in res) == ysb.oracle_totals(TOTAL)


def test_ysb_wmr_matches_kf_windows():
    kf = run_variant(ysb.make_ops, 500)
    wmr = run_variant(ysb.make_ops_wmr, 500, map_parallelism=2)
    assert kf == wmr
    wmr3 = run_variant(ysb.make_ops_wmr, 750, map_parallelism=3)
    assert kf == wmr3


def test_ysb_per_window_counts_against_dense_oracle():
    res = run_variant(ysb.make_ops, 512)
    want = {}
    for i in range(TOTAL):
        if i % 3 != 0:                          # filter: views only
            continue
        camp = (i * 7919) % ysb.N_ADS // ysb.ADS_PER_CAMPAIGN
        wid = (i // ysb.EVENTS_PER_TICK) // ysb.WIN_LEN
        want[(camp, wid)] = want.get((camp, wid), 0) + 1
    got = {(k, w): c for k, w, c in res}
    assert got == want


def _chain_step(batch_size, pane_capacity, max_wins, n_batches=4):
    """Shared harness: the YSB op chain compiled as one step function."""
    import jax.numpy as jnp
    from windflow_tpu.runtime.pipeline import CompiledChain

    src = ysb.make_source(total=n_batches * batch_size)
    ops = ysb.make_ops(pane_capacity=pane_capacity, max_wins=max_wins)
    chain = CompiledChain(ops, src.payload_spec(), batch_capacity=batch_size)

    def step(states, start):
        b = src.make_batch(jnp.asarray(start, jnp.int32), batch_size)
        states = list(states)
        for j, op in enumerate(chain.ops):
            states[j], b = op.apply(states[j], b)
        return tuple(states), jnp.sum(b.valid)

    return src, ops, chain, step


def test_count_lift_detected_inside_chain_trace():
    """Regression: _detect_count_lift runs INSIDE the chain's jit trace, where
    float() on a freshly created jnp constant raises ConcretizationTypeError
    unless evaluated under jax.ensure_compile_time_eval(). When the blanket
    except swallowed that, the YSB windowed-count chain silently took the
    serialized segment-sum fallback for its panes update — ~5.4 ms/step at 1M
    batch on-chip, the whole window-stage anomaly of BASELINE.md's ablation."""
    import jax

    _, ops, chain, step = _chain_step(2048, 16, 16)
    win = ops[-1]
    assert win.count_lift is None               # not yet traced
    out = jax.jit(step)(tuple(chain.states), 0)
    jax.block_until_ready(out[1])
    assert win.count_lift is True, \
        "count-lift fast path not detected under an ambient jit trace"


def _reachable_computations(hlo: str):
    """(names reachable from ENTRY via calls=/to_apply=, minus conditional
    branch computations) -> their bodies. Text-level HLO walk."""
    comps = {}
    for m in re.finditer(r"^(?:ENTRY )?%?([\w.\-]+)[^\n]*\{\n(.*?)^\}", hlo,
                         re.M | re.S):
        comps[m.group(1)] = m.group(2)
    entry_name = next(n for n in comps
                      if re.search(rf"^ENTRY %?{re.escape(n)}\b", hlo, re.M))
    seen, todo = set(), [entry_name]
    while todo:
        name = todo.pop()
        if name in seen or name not in comps:
            continue
        seen.add(name)
        body = comps[name]
        branch = set()
        for bm in re.finditer(r"branch_computations=\{([^}]*)\}", body):
            branch |= {b.strip().lstrip("%") for b in bm.group(1).split(",")}
        for cm in re.finditer(r"(?:calls|to_apply)=%([\w.\-]+)", body):
            if cm.group(1) not in branch:
                todo.append(cm.group(1))
    return {n: comps[n] for n in seen}


def test_ysb_chain_unconditional_path_has_no_scatter():
    """Structural lock on the count-lift fast path: no scatter opcode may be
    reachable from the compiled chain's ENTRY outside the locality cond's
    branch computations (where the exact fallback legitimately lives). A
    reachable scatter means the panes update regressed onto the serialized
    fallback (the r05 5.4 ms/step anomaly) — including the fused/renamed form
    a plain 'scatter not in ENTRY-text' check would miss."""
    import jax

    _, _, chain, step = _chain_step(4096, 32, 32)
    txt = (jax.jit(step)
           .lower(tuple(chain.states), 0).compile().as_text())
    offenders = {
        name: [l.strip() for l in body.splitlines() if "scatter(" in l]
        for name, body in _reachable_computations(txt).items()}
    offenders = {n: ls for n, ls in offenders.items() if ls}
    assert not offenders, (
        "scatter reachable outside the locality cond — the windowed-count "
        f"panes update fell off the histogram fast path: {offenders}")
