"""YSB (flagship macro-benchmark) correctness: the sum of all emitted window counts
must equal the number of view events in the stream (reference oracle: the sink
accumulates per-window counts, src/yahoo_test_cpu/test_ysb_kf.cpp), invariant under
batch size and across the KF (Key_FFAT) and WMR (Win_MapReduce) window variants."""

import numpy as np
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.benchmarks import ysb

TOTAL = 3000        # 300 time units = 3 windows per campaign


def run_variant(make_ops_fn, batch_size, **kw):
    src = ysb.make_source(TOTAL)
    ops = make_ops_fn(**kw)
    results = []

    def cb(view):
        if view is None:
            return
        for k, w, c in zip(view["key"].tolist(), view["id"].tolist(),
                           np.asarray(view["payload"]).tolist()):
            results.append((int(k), int(w), int(c)))

    wf.Pipeline(src, ops, wf.Sink(cb), batch_size=batch_size).run()
    return sorted(results)


@pytest.mark.parametrize("batch_size", [256, 1000, TOTAL])
def test_ysb_kf_totals_match_oracle(batch_size):
    res = run_variant(ysb.make_ops, batch_size)
    assert res, "no window results emitted"
    assert sum(c for _, _, c in res) == ysb.oracle_totals(TOTAL)


def test_ysb_wmr_matches_kf_windows():
    kf = run_variant(ysb.make_ops, 500)
    wmr = run_variant(ysb.make_ops_wmr, 500, map_parallelism=2)
    assert kf == wmr
    wmr3 = run_variant(ysb.make_ops_wmr, 750, map_parallelism=3)
    assert kf == wmr3


def test_ysb_per_window_counts_against_dense_oracle():
    res = run_variant(ysb.make_ops, 512)
    want = {}
    for i in range(TOTAL):
        if i % 3 != 0:                          # filter: views only
            continue
        camp = (i * 7919) % ysb.N_ADS // ysb.ADS_PER_CAMPAIGN
        wid = (i // ysb.EVENTS_PER_TICK) // ysb.WIN_LEN
        want[(camp, wid)] = want.get((camp, wid), 0) + 1
    got = {(k, w): c for k, w, c in res}
    assert got == want
