"""Pillar-3 gate: the whole-repo static concurrency analyzer
(``analysis/concurrency.py``, the WF26x family) runs as part of ``run_lint``
in tier-1 and must be clean against the baseline — plus per-rule minimal
fixture negatives for WF260–WF265, the annotation-grammar rejection cases,
role-inference through ``ThreadPoolExecutor.submit`` and an ``io_callback``
lambda, and the CLI contract (``--select``/``--ignore``/``--explain``,
exit codes under a poisoned-jax ``PYTHONPATH``)."""

import json
import os
import subprocess
import sys
import textwrap

from windflow_tpu.analysis import lint

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
conc = lint.concurrency_module()


# ------------------------------------------------------------ the repo gate


def test_repo_concurrency_pass_is_clean():
    """THE acceptance gate: zero un-baselined WF26x findings over this
    repository — every cross-thread contract is locked, annotated with a
    rationale, or was fixed in this PR."""
    fresh, _suppressed = lint.lint_repo(ROOT)
    mine = [x for x in fresh if x.code.startswith("WF26")]
    assert not mine, "\n".join(x.render() for x in mine)


def test_baselined_wf26x_entries_carry_a_rationale():
    """The audit contract: nothing from the concurrency pass may be banked
    in baseline.json without a written rationale — an entry without one is
    an unexplained suppression, which is exactly the convention debt this
    pass exists to kill."""
    path = lint.baseline_path(lint.LintConfig(root=ROOT))
    data = json.load(open(path)) if os.path.exists(path) else {}
    for e in data.get("findings", ()):
        if e["code"].startswith("WF26"):
            assert e.get("rationale", "").strip(), (
                f"baselined {e['code']} at {e['path']} has no rationale: "
                f"{e}")


def test_driver_only_contracts_are_annotation_enforced():
    """The three formerly docstring-only contracts are now declared in the
    checked annotation grammar (and the inference actually classifies them
    — their inferred roles stay inside the declared set)."""
    roles = conc.inferred_roles(ROOT)

    def roles_of(suffix):
        hits = {q: r for q, r in roles.items() if q.endswith(suffix)}
        assert hits, f"no function matching {suffix}"
        return set().union(*hits.values())

    assert roles_of("Ordering_Node.settle") <= {"driver", "stage"}
    assert roles_of("TieredTable.maintain") <= {"driver", "stage"}
    assert roles_of("MicrobatchAccumulator.feed") <= {"driver", "stage"}
    # and the spawned roles landed where the annotations say they do
    assert "reporter" in roles_of("Reporter._run")
    assert "watchdog" in roles_of("ThreadedPipeline._watchdog_body")
    assert "checkpoint-pool" in roles_of("checkpoint.py::save_states")
    assert "jax-callback" in roles_of("JoinTableTier.lookup_cb")


# ----------------------------------------------------------- rule fixtures


def _fixture(tmp_path, module_src, replay=False):
    """Minimal tree the concurrency pass can run against (it needs only
    ``windflow_tpu/``)."""
    pkg = tmp_path / "windflow_tpu"
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / "mod.py").write_text(textwrap.dedent(module_src))
    replay_modules = ("windflow_tpu/mod.py",) if replay else ()
    return conc.run_rules(str(tmp_path), ("windflow_tpu",),
                          replay_modules=replay_modules)


def _codes(findings):
    return sorted(d["code"] for d in findings)


_SETTLE_FROM_THREAD = '''
    import threading

    class Node:
        def settle(self):  # wf-lint: thread-role[driver]
            return 0

    class Driver:
        def __init__(self, node: Node):
            self._node = node
        def _body(self):
            self._node.settle()
        def run(self):
            t = threading.Thread(target=self._body)
            t.start()
            t.join()
'''


def test_wf261_settle_from_spawned_thread_fires(tmp_path):
    """THE acceptance fixture: a driver-thread-only settle() called from a
    spawned thread fails with WF261."""
    findings = _fixture(tmp_path, _SETTLE_FROM_THREAD)
    hits = [d for d in findings if d["code"] == "WF261"]
    assert len(hits) == 1, findings
    assert "settle" in hits[0]["message"]
    assert "'thread'" in hits[0]["message"]


def test_wf261_annotated_spawn_role_is_allowed(tmp_path):
    """The same shape with the spawn annotated as a driver loan (the
    call_with_timeout pattern) is clean."""
    findings = _fixture(tmp_path, '''
        import threading

        class Node:
            def settle(self):  # wf-lint: thread-role[driver]
                return 0

        class Driver:
            def __init__(self, node: Node):
                self._node = node
            def _body(self):
                self._node.settle()
            def run(self):
                t = threading.Thread(  # wf-lint: thread-role[driver]
                    target=self._body)
                t.start()
                t.join()
    ''')
    assert "WF261" not in _codes(findings)


def test_wf261_mixed_role_fallback_adds_no_phantom_edge(tmp_path):
    """Two same-named annotated methods with DIFFERENT role sets must not
    resolve by name alone — the union would smear one class's allowed
    roles into the stricter class and fire a spurious WF261 (review
    finding: fallback requires IDENTICAL declared sets)."""
    findings = _fixture(tmp_path, '''
        import threading

        class DriverOnly:
            def settle(self):  # wf-lint: thread-role[driver]
                return 0

        class StageSafe:
            def settle(self):  # wf-lint: thread-role[driver, stage]
                return 1

        def body(x):
            x.settle()

        def run(x):
            t = threading.Thread(  # wf-lint: thread-role[stage]
                target=body)
            t.start()
            t.join()
    ''')
    assert "WF261" not in _codes(findings)


def test_wf261_constructor_typed_local_resolves_precisely(tmp_path):
    """A local bound from a repo-class constructor resolves obj.m() even
    when the bare-name fallback would bail (multiple unannotated-mixed
    definitions) — review finding: the local-type map must actually feed
    call resolution."""
    findings = _fixture(tmp_path, '''
        import threading

        class Node:
            def settle(self):  # wf-lint: thread-role[driver]
                return 0

        class Unrelated:
            def settle(self):
                return 1

        def body():
            n = Node()
            n.settle()

        def run():
            t = threading.Thread(target=body)
            t.start()
            t.join()
    ''')
    hits = [d for d in findings if d["code"] == "WF261"]
    assert len(hits) == 1 and "Node.settle" in hits[0]["message"]


def test_wf261_pool_bound_by_plain_assignment(tmp_path):
    """An executor bound by plain assignment (not with-as) still seeds the
    checkpoint-pool role through .submit (review finding)."""
    findings = _fixture(tmp_path, '''
        from concurrent.futures import ThreadPoolExecutor

        class Node:
            def settle(self):  # wf-lint: thread-role[driver]
                return 0

        def step(node):
            return node.settle()

        def save_all(nodes):
            ex = ThreadPoolExecutor(2)
            try:
                return [ex.submit(step, n) for n in nodes]
            finally:
                ex.shutdown()
    ''')
    hits = [d for d in findings if d["code"] == "WF261"]
    assert len(hits) == 1 and "checkpoint-pool" in hits[0]["message"]


def test_wf261_role_inference_through_pool_submit(tmp_path):
    """ThreadPoolExecutor.submit seeds the checkpoint-pool role, and it
    propagates through the call graph into the constrained API."""
    findings = _fixture(tmp_path, '''
        from concurrent.futures import ThreadPoolExecutor

        class Node:
            def settle(self):  # wf-lint: thread-role[driver]
                return 0

        def save_one(node):
            return step(node)

        def step(node):
            return node.settle()

        def save_all(nodes):
            with ThreadPoolExecutor(max_workers=2) as ex:
                return list(ex.map(save_one, nodes))
    ''')
    hits = [d for d in findings if d["code"] == "WF261"]
    assert len(hits) == 1 and "checkpoint-pool" in hits[0]["message"]


def test_wf261_role_inference_through_io_callback_lambda(tmp_path):
    """A lambda passed to io_callback gets the jax-callback role; its calls
    propagate it into the constrained API."""
    findings = _fixture(tmp_path, '''
        from jax.experimental import io_callback

        class Tier:
            def fetch(self):  # wf-lint: thread-role[driver]
                return 0

        def probe(tier, shapes, keys):
            return io_callback(lambda k: tier.fetch(), shapes, keys,
                               ordered=True)
    ''')
    hits = [d for d in findings if d["code"] == "WF261"]
    assert len(hits) == 1 and "jax-callback" in hits[0]["message"]


def test_wf260_cross_role_attr_without_lock(tmp_path):
    findings = _fixture(tmp_path, '''
        import threading

        class Box:
            def __init__(self):
                self.items = []
            def _body(self):
                self.items.append(1)
            def run(self):
                t = threading.Thread(  # wf-lint: thread-role[stage]
                    target=self._body)
                t.start()
                return len(self.items)
    ''')
    hits = [d for d in findings if d["code"] == "WF260"]
    assert len(hits) == 1 and "Box.items" in hits[0]["message"]
    assert "stage" in hits[0]["message"]


def test_wf260_consistent_lock_is_clean(tmp_path):
    findings = _fixture(tmp_path, '''
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []
            def _body(self):
                with self._lock:
                    self.items.append(1)
            def run(self):
                t = threading.Thread(  # wf-lint: thread-role[stage]
                    target=self._body)
                t.start()
                with self._lock:
                    return len(self.items)
    ''')
    assert "WF260" not in _codes(findings)


def test_wf260_lock_held_by_caller_counts(tmp_path):
    """The must-held analysis: a private helper whose every call site holds
    the lock is treated as running under it."""
    findings = _fixture(tmp_path, '''
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []
            def _append(self, x):
                self.items.append(x)
            def _body(self):
                with self._lock:
                    self._append(1)
            def run(self):
                t = threading.Thread(  # wf-lint: thread-role[stage]
                    target=self._body)
                t.start()
                with self._lock:
                    self._append(2)
    ''')
    assert "WF260" not in _codes(findings)


def test_wf260_single_writer_annotation_suppresses(tmp_path):
    findings = _fixture(tmp_path, '''
        import threading

        class Box:
            def __init__(self):
                # stage body owns the list; driver reads post-join
                self.items = []          # wf-lint: single-writer[stage]
            def _body(self):
                self.items.append(1)
            def run(self):
                t = threading.Thread(  # wf-lint: thread-role[stage]
                    target=self._body)
                t.start()
                t.join()
                return len(self.items)
    ''')
    assert "WF260" not in _codes(findings)


def test_wf260_class_level_single_writer_covers_all_attrs(tmp_path):
    findings = _fixture(tmp_path, '''
        import threading

        class Ring:  # wf-lint: single-writer[stage]
            def __init__(self):
                self.buf = []
                self.idx = 0
            def _body(self):
                self.buf.append(1)
                self.idx += 1
            def run(self):
                t = threading.Thread(  # wf-lint: thread-role[stage]
                    target=self._body)
                t.start()
                return self.idx
    ''')
    assert "WF260" not in _codes(findings)


def test_wf260_threadsafe_primitive_attrs_exempt(tmp_path):
    findings = _fixture(tmp_path, '''
        import threading

        class Box:
            def __init__(self):
                self.stop = threading.Event()
            def _body(self):
                self.stop.set()
            def run(self):
                t = threading.Thread(  # wf-lint: thread-role[stage]
                    target=self._body)
                t.start()
                return self.stop.is_set()
    ''')
    assert "WF260" not in _codes(findings)


def test_wf262_unordered_io_callback_in_replay_module(tmp_path):
    findings = _fixture(tmp_path, '''
        from jax.experimental import io_callback

        def cb(k):
            return k

        def probe_missing(shapes, keys):
            return io_callback(cb, shapes, keys)

        def probe_false(shapes, keys):
            return io_callback(cb, shapes, keys, ordered=False)

        def probe_var(shapes, keys, flag):
            return io_callback(cb, shapes, keys, ordered=flag)

        def probe_ok(shapes, keys):
            return io_callback(cb, shapes, keys, ordered=True)

        def probe_allowed(shapes, keys):
            return io_callback(cb, shapes, keys)  # wf-lint: allow[unordered]
    ''', replay=True)
    hits = [d for d in findings if d["code"] == "WF262"]
    assert len(hits) == 3, findings


def test_wf262_unresolvable_callback(tmp_path):
    findings = _fixture(tmp_path, '''
        from jax.experimental import io_callback

        def probe(cb_factory, shapes, keys):
            return io_callback(cb_factory(), shapes, keys, ordered=True)
    ''', replay=True)
    hits = [d for d in findings if d["code"] == "WF262"]
    assert len(hits) == 1 and "resolve" in hits[0]["message"]


def test_wf262_scoped_to_replay_modules(tmp_path):
    findings = _fixture(tmp_path, '''
        from jax.experimental import io_callback

        def cb(k):
            return k

        def probe(shapes, keys):
            return io_callback(cb, shapes, keys)
    ''', replay=False)
    assert "WF262" not in _codes(findings)


def test_wf263_lock_order_cycle(tmp_path):
    findings = _fixture(tmp_path, '''
        import threading

        class AB:
            def __init__(self):
                self.lock_a = threading.Lock()
                self.lock_b = threading.Lock()
            def ab(self):
                with self.lock_a:
                    with self.lock_b:
                        return 1
            def ba(self):
                with self.lock_b:
                    with self.lock_a:
                        return 2
    ''')
    hits = [d for d in findings if d["code"] == "WF263"]
    assert len(hits) == 1 and "cycle" in hits[0]["message"]


def test_wf263_cycle_through_call_edge(tmp_path):
    findings = _fixture(tmp_path, '''
        import threading

        class AB:
            def __init__(self):
                self.lock_a = threading.Lock()
                self.lock_b = threading.Lock()
            def _take_b(self):
                with self.lock_b:
                    return 1
            def ab(self):
                with self.lock_a:
                    return self._take_b()
            def ba(self):
                with self.lock_b:
                    with self.lock_a:
                        return 2
    ''')
    assert "WF263" in _codes(findings)


def test_wf263_multi_item_with_statement_orders_locks(tmp_path):
    """`with self.a, self.b:` acquires a THEN b — the a->b edge must enter
    the graph so an opposite-order nested pair is a cycle (review
    finding)."""
    findings = _fixture(tmp_path, '''
        import threading

        class AB:
            def __init__(self):
                self.lock_a = threading.Lock()
                self.lock_b = threading.Lock()
            def ab(self):
                with self.lock_a, self.lock_b:
                    return 1
            def ba(self):
                with self.lock_b:
                    with self.lock_a:
                        return 2
    ''')
    hits = [d for d in findings if d["code"] == "WF263"]
    assert len(hits) == 1 and "cycle" in hits[0]["message"]


def test_multi_role_spawn_annotation_seeds_every_role(tmp_path):
    """A spawn annotated with two roles seeds BOTH (review finding: the
    tail must not silently drop) — and the spawn record duplication does
    not double-report WF264."""
    findings = _fixture(tmp_path, '''
        import threading

        class Node:
            def settle(self):  # wf-lint: thread-role[driver]
                return 0

        class Driver:
            def __init__(self, node: Node):
                self._node = node
            def _body(self):
                self._node.settle()
            def run(self):
                t = threading.Thread(  # wf-lint: thread-role[driver, stage]
                    target=self._body)
                t.start()
    ''')
    hits = [d for d in findings if d["code"] == "WF261"]
    assert len(hits) == 1 and "'stage'" in hits[0]["message"]
    assert len([d for d in findings if d["code"] == "WF264"]) == 1


def test_wf263_nested_order_consistent_is_clean(tmp_path):
    findings = _fixture(tmp_path, '''
        import threading

        class AB:
            def __init__(self):
                self.lock_a = threading.Lock()
                self.lock_b = threading.Lock()
            def ab(self):
                with self.lock_a:
                    with self.lock_b:
                        return 1
            def ab2(self):
                with self.lock_a:
                    with self.lock_b:
                        return 2
    ''')
    assert "WF263" not in _codes(findings)


def test_wf263_cross_function_self_reacquire(tmp_path):
    """Holding a plain Lock and calling a helper that re-takes it is a
    guaranteed deadlock even though the acquire lives in another function
    (review finding: the a==b case the cycle graph drops must be checked
    through the call graph); an RLock is fine."""
    findings = _fixture(tmp_path, '''
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
            def _helper(self):
                with self._lock:
                    return 1
            def outer(self):
                with self._lock:
                    return self._helper()

        class ReBox:
            def __init__(self):
                self._lock = threading.RLock()
            def _helper(self):
                with self._lock:
                    return 1
            def outer(self):
                with self._lock:
                    return self._helper()
    ''')
    hits = [d for d in findings if d["code"] == "WF263"]
    assert len(hits) == 1 and "re-acquires" in hits[0]["message"], findings
    assert "Box._helper" in hits[0]["message"] or "_helper" in \
        hits[0]["message"]


def test_wf261_pool_stored_on_self_attribute(tmp_path):
    """`self._pool = ThreadPoolExecutor(...)` + `self._pool.submit(...)`
    seeds the checkpoint-pool role like the local/with-as forms (review
    finding)."""
    findings = _fixture(tmp_path, '''
        from concurrent.futures import ThreadPoolExecutor

        class Node:
            def settle(self):  # wf-lint: thread-role[driver]
                return 0

        class Saver:
            def __init__(self, node: Node):
                self._pool = ThreadPoolExecutor(2)
                self._node = node
            def work(self):
                return self._node.settle()
            def save(self):
                return self._pool.submit(self.work)
    ''')
    hits = [d for d in findings if d["code"] == "WF261"]
    assert len(hits) == 1 and "checkpoint-pool" in hits[0]["message"]


def test_wf263_self_reacquire_of_plain_lock(tmp_path):
    findings = _fixture(tmp_path, '''
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
            def outer(self):
                with self._lock:
                    with self._lock:
                        return 1
    ''')
    hits = [d for d in findings if d["code"] == "WF263"]
    assert len(hits) == 1 and "re-acquiring" in hits[0]["message"]


def test_wf264_unjoined_non_daemon_thread(tmp_path):
    findings = _fixture(tmp_path, '''
        import threading

        def fire_and_forget(fn):
            t = threading.Thread(target=fn)
            t.start()
    ''')
    hits = [d for d in findings if d["code"] == "WF264"]
    assert len(hits) == 1


def test_wf264_not_suppressed_by_unrelated_join_names(tmp_path):
    """os.path.join / ', '.join are not thread joins — they must not
    satisfy the reachable-join() check (review finding)."""
    findings = _fixture(tmp_path, '''
        import os
        import threading

        def fire_and_forget(fn):
            p = os.path.join("a", "b")
            label = ", ".join(["x", "y"])
            t = threading.Thread(target=fn)
            t.start()
            return p, label
    ''')
    hits = [d for d in findings if d["code"] == "WF264"]
    assert len(hits) == 1, findings


def test_wf264_daemon_join_and_allow_are_clean(tmp_path):
    findings = _fixture(tmp_path, '''
        import threading

        def daemonized(fn):
            threading.Thread(target=fn, daemon=True).start()

        def joined(fn):
            t = threading.Thread(target=fn)
            t.start()
            t.join()

        def joined_in_class_method(fn):
            pass

        def allowed(fn):
            t = threading.Thread(target=fn)  # wf-lint: allow[unjoined]
            t.start()
    ''')
    assert "WF264" not in _codes(findings)


def test_wf265_annotation_grammar_rejection(tmp_path):
    findings = _fixture(tmp_path, '''
        import threading

        class Box:
            def __init__(self):
                self.items = []       # wf-lint: single-writer[gremlin]

            def work(self):  # wf-lint: thread-role[bogus-role]
                return self.items
    ''')
    hits = [d for d in findings if d["code"] == "WF265"]
    assert len(hits) == 2, findings
    assert all("unknown role" in d["message"] for d in hits)


def test_wf265_line_above_annotation_form(tmp_path):
    """The declaration-on-the-line-above form parses for thread-role too."""
    findings = _fixture(tmp_path, '''
        import threading

        class Node:
            # wf-lint: thread-role[driver]
            def settle(self):
                return 0

        class Driver:
            def __init__(self, node: Node):
                self._node = node
            def _body(self):
                self._node.settle()
            def run(self):
                threading.Thread(target=self._body).start()
    ''')
    assert "WF261" in _codes(findings)


def test_run_lint_includes_concurrency_findings(tmp_path):
    """The WF26x family rides run_lint/lint_repo (and therefore the shared
    baseline ratchet), not a separate entry point."""
    pkg = tmp_path / "windflow_tpu"
    (pkg / "observability").mkdir(parents=True)
    (pkg / "analysis").mkdir()
    (tmp_path / "docs").mkdir()
    (pkg / "observability" / "names.py").write_text(
        'JOURNAL_EVENTS = ()\nRECOVERY_COUNTERS = ()\n'
        'CONTROL_COUNTERS = ()\nCONTROL_GAUGES = ()\n')
    (tmp_path / "docs" / "ENV_FLAGS.md").write_text("# flags\n")
    (pkg / "mod.py").write_text(textwrap.dedent('''
        import threading

        def fire_and_forget(fn):
            t = threading.Thread(target=fn)
            t.start()
    '''))
    findings = lint.run_lint(cfg=lint.LintConfig(root=str(tmp_path)))
    assert "WF264" in [x.code for x in findings]
    # and the baseline ratchet suppresses it like any WF2xx finding
    bpath = tmp_path / "b.json"
    lint.save_baseline(str(bpath), findings)
    fresh = lint.apply_baseline(findings, lint.load_baseline(str(bpath)))
    assert fresh == []


# ------------------------------------------------------------- CLI contract


def _poisoned_jax_dir(tmp_path):
    d = tmp_path / "nojax"
    d.mkdir(exist_ok=True)
    (d / "jax.py").write_text("raise ImportError('wf_lint must not "
                              "import jax')\n")
    return str(d)


def _run_cli(*args, env=None):
    e = dict(os.environ)
    if env:
        e.update(env)
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "wf_lint.py"), *args],
        capture_output=True, text=True, timeout=120, env=e)


def test_cli_runs_concurrency_pass_by_default_without_jax(tmp_path):
    """The default wf_lint invocation includes the WF26x pass and exits 0
    on this repo even when importing jax is poisoned (the loadable-by-path
    contract)."""
    proc = _run_cli(env={"PYTHONPATH": _poisoned_jax_dir(tmp_path)})
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_select_family_and_exit_codes(tmp_path):
    """A seeded WF264 fixture exits 1 under --select WF264 (family syntax
    included) and 0 under --ignore WF264."""
    pkg = tmp_path / "fix" / "windflow_tpu"
    (pkg / "observability").mkdir(parents=True)
    (pkg / "observability" / "names.py").write_text(
        'JOURNAL_EVENTS = ()\nRECOVERY_COUNTERS = ()\n'
        'CONTROL_COUNTERS = ()\nCONTROL_GAUGES = ()\n')
    (tmp_path / "fix" / "docs").mkdir()
    (tmp_path / "fix" / "docs" / "ENV_FLAGS.md").write_text("# flags\n")
    (pkg / "mod.py").write_text(textwrap.dedent('''
        import threading
        def fire_and_forget(fn):
            t = threading.Thread(target=fn)
            t.start()
    '''))
    proc = _run_cli("--select", "WF26x", "--no-baseline",
                    "--root", str(tmp_path / "fix"))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "WF264" in proc.stdout
    proc = _run_cli("--ignore", "WF264", "--no-baseline",
                    "--root", str(tmp_path / "fix"))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_select_unknown_code_is_exit_2():
    proc = _run_cli("--select", "WF999")
    assert proc.returncode == 2, proc.stdout + proc.stderr


def test_cli_overbroad_family_token_is_exit_2():
    """`--ignore x` must not match every rule and turn the gate into a
    silent no-op (review finding: family prefix must be WF+digits)."""
    proc = _run_cli("--ignore", "x")
    assert proc.returncode == 2, proc.stdout + proc.stderr
    proc = _run_cli("--select", "Wx")
    assert proc.returncode == 2, proc.stdout + proc.stderr


def test_cli_refuses_partial_baseline_update():
    proc = _run_cli("--select", "WF26x", "--update-baseline")
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "partial baseline" in proc.stderr


def test_cli_explain_mode(tmp_path):
    proc = _run_cli("--explain", "WF261",
                    env={"PYTHONPATH": _poisoned_jax_dir(tmp_path)})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "WF261" in proc.stdout and "thread-role" in proc.stdout
    proc = _run_cli("--explain", "WF999")
    assert proc.returncode == 2
