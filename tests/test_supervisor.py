"""Failure detection + recovery (runtime/supervisor.py): injected step failures
must be recovered from the last aligned checkpoint with exactly-once sink delivery
(no duplicated, lost, or torn window results)."""

import numpy as np
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.runtime.supervisor import SupervisedPipeline, RestartExhausted

TOTAL, K = 400, 4


def build(sink_cb, **kw):
    src = wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
                    total=TOTAL, num_keys=K)
    op = wf.Win_Seq(lambda wid, it: it.sum("v"), WindowSpec(10, 10, win_type_t.TB),
                    num_keys=K)
    return SupervisedPipeline(src, [op], wf.Sink(sink_cb), batch_size=50, **kw)


def collect(results):
    def cb(view):
        if view is None:
            return
        results.extend(zip(view["key"].tolist(), view["id"].tolist(),
                           np.asarray(view["payload"]).tolist()))
    return cb


class Flaky:
    """Wraps chain.push to raise on chosen batch indices, once each."""

    def __init__(self, chain, fail_at):
        self.inner = chain.push
        self.count = 0                        # absolute push-call index
        self.fail_at = sorted(fail_at)

    def __call__(self, batch):
        self.count += 1
        if self.fail_at and self.count == self.fail_at[0]:
            self.fail_at.pop(0)
            raise RuntimeError(f"injected device fault at push #{self.count}")
        return self.inner(batch)


def test_no_failure_matches_plain_pipeline():
    plain, sup = [], []
    wf.Pipeline(wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
                          total=TOTAL, num_keys=K),
                [wf.Win_Seq(lambda wid, it: it.sum("v"),
                            WindowSpec(10, 10, win_type_t.TB), num_keys=K)],
                wf.Sink(collect(plain)), batch_size=50).run()
    build(collect(sup)).run()
    assert sorted(sup) == sorted(plain)


@pytest.mark.parametrize("fail_at", [[2], [3, 7], [1, 2, 3]])
def test_recovers_with_exactly_once_delivery(fail_at):
    oracle = []
    build(collect(oracle)).run()

    got = []
    p = build(collect(got), checkpoint_every=3, max_restarts=5)
    p.chain.push = Flaky(p.chain, fail_at)
    p.run()
    assert p.restarts == len(fail_at)
    assert sorted(got) == sorted(oracle), "results lost or duplicated on recovery"


def test_restart_budget_exhausts_on_permanent_failure():
    got = []
    p = build(collect(got), checkpoint_every=4, max_restarts=2)

    def always_fail(batch):
        raise RuntimeError("permanent fault")
    p.chain.push = always_fail
    with pytest.raises(RestartExhausted):
        p.run()


def test_seekable_recovery_is_o1_not_replay():
    """Seekable-source contract (VERDICT r04 weak #6): a restart at a large
    stream position must resume from the commit cursor in O(1), not re-iterate
    ``pos`` batches. DeviceSource seeks by index arithmetic — count the batches
    the source actually regenerates."""
    oracle = []
    build(collect(oracle)).run()

    got = []
    p = build(collect(got), checkpoint_every=2, max_restarts=3)
    made = []
    orig_batches = p.source.batches

    def counting_batches(batch_size, cursor=None):
        made.append(0)
        for b in orig_batches(batch_size, cursor=cursor):
            made[-1] += 1
            yield b
    p.source.batches = counting_batches
    p.chain.push = Flaky(p.chain, [7])        # fail late: committed pos >= 6
    p.run()
    assert p.restarts == 1
    assert sorted(got) == sorted(oracle)
    # TOTAL=400 / batch 50 = 8 batches. First open produced the first 7 pushes'
    # batches; the re-open must start AT the committed position (pos 6), i.e.
    # regenerate only 8 - 6 = 2, not re-iterate from zero.
    assert len(made) == 2
    assert made[1] == 2, f"re-open replayed {made[1]} batches (expected 2)"


def test_seekable_recovery_generator_source_cursor_factory():
    """GeneratorSource O(1) resume: an it_factory accepting from_batch is called
    with the committed chunk index, and progressive ids stay exact (window
    results identical to the no-failure run)."""
    opens = []

    def factory(from_batch=0):
        opens.append(from_batch)
        def gen():
            for s in range(from_batch * 50, TOTAL, 50):
                ids = np.arange(s, s + 50, dtype=np.int32)
                yield ({"v": (ids % 13).astype(np.float32)},
                       ids % K, ids)
        return gen()

    def mk(sink_cb, **kw):
        from windflow_tpu.operators.source import GeneratorSource
        src = GeneratorSource(factory, {"v": jnp.zeros((), jnp.float32)})
        op = wf.Win_Seq(lambda wid, it: it.sum("v"),
                        WindowSpec(10, 10, win_type_t.TB), num_keys=K)
        return SupervisedPipeline(src, [op], wf.Sink(sink_cb), batch_size=50, **kw)

    oracle = []
    mk(collect(oracle)).run()

    opens.clear()
    got = []
    p = mk(collect(got), checkpoint_every=2, max_restarts=3)
    p.chain.push = Flaky(p.chain, [7])
    p.run()
    assert p.restarts == 1
    assert sorted(got) == sorted(oracle)
    # the factory was re-opened WITH the committed chunk index, not from zero
    assert opens == [0, 6], opens


def test_spill_checkpoint_written(tmp_path):
    got = []
    path = str(tmp_path / "sup_ckpt.npz")
    p = build(collect(got), checkpoint_every=2, spill_path=path)
    p.run()
    import numpy as np
    data = np.load(path)
    assert "__meta__" in data


def test_budget_refills_on_commit_progress():
    """max_restarts bounds failures PER checkpoint interval: three faults in
    three different intervals recover with a budget of one, because each
    commit refills it."""
    oracle = []
    build(collect(oracle)).run()

    got = []
    p = build(collect(got), checkpoint_every=2, max_restarts=1)
    # pushes 2, 6, 10 land in distinct intervals (replays shift the counts:
    # each failure re-pushes the interval's batches before the next commit)
    p.chain.push = Flaky(p.chain, [2, 6, 10])
    p.run()
    assert p.restarts == 3
    assert sorted(got) == sorted(oracle)


def test_restart_exhausted_carries_cause():
    got = []
    p = build(collect(got), checkpoint_every=4, max_restarts=1)
    boom = RuntimeError("the real device fault")

    def always_fail(batch):
        raise boom
    p.chain.push = always_fail
    with pytest.raises(RestartExhausted) as ei:
        p.run()
    assert ei.value.__cause__ is boom


def test_reopen_source_fast_forwards_pre_cursor_signature():
    """A legacy/user source whose ``batches`` predates the cursor kwarg is
    detected via inspect.signature and fast-forwarded — not probed by calling
    it and swallowing TypeError."""
    from windflow_tpu.runtime.supervisor import _reopen_source

    class Legacy:
        def __init__(self):
            self.opens = 0

        def batches(self, batch_size):
            self.opens += 1
            for i in range(8):
                yield i

    src = Legacy()
    it = _reopen_source(src, 50, 3, cursor={"batch": 3})
    assert next(it) == 3 and src.opens == 1


def test_reopen_source_genuine_typeerror_propagates():
    """A TypeError raised BY a cursor-accepting source must propagate — the
    pre-fix ``except TypeError`` fallback silently masked it behind a
    from-zero replay (wrong data, no error)."""
    from windflow_tpu.runtime.supervisor import _reopen_source

    class Buggy:
        def batches(self, batch_size, cursor=None):
            raise TypeError("genuine bug inside the source")
            yield  # pragma: no cover

    with pytest.raises(TypeError, match="genuine bug"):
        it = _reopen_source(Buggy(), 50, 3, cursor={"batch": 3})
        next(it)
