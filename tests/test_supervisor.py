"""Failure detection + recovery (runtime/supervisor.py): injected step failures
must be recovered from the last aligned checkpoint with exactly-once sink delivery
(no duplicated, lost, or torn window results)."""

import numpy as np
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.runtime.supervisor import SupervisedPipeline, RestartExhausted

TOTAL, K = 400, 4


def build(sink_cb, **kw):
    src = wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
                    total=TOTAL, num_keys=K)
    op = wf.Win_Seq(lambda wid, it: it.sum("v"), WindowSpec(10, 10, win_type_t.TB),
                    num_keys=K)
    return SupervisedPipeline(src, [op], wf.Sink(sink_cb), batch_size=50, **kw)


def collect(results):
    def cb(view):
        if view is None:
            return
        results.extend(zip(view["key"].tolist(), view["id"].tolist(),
                           np.asarray(view["payload"]).tolist()))
    return cb


class Flaky:
    """Wraps chain.push to raise on chosen batch indices, once each."""

    def __init__(self, chain, fail_at):
        self.inner = chain.push
        self.count = 0                        # absolute push-call index
        self.fail_at = sorted(fail_at)

    def __call__(self, batch):
        self.count += 1
        if self.fail_at and self.count == self.fail_at[0]:
            self.fail_at.pop(0)
            raise RuntimeError(f"injected device fault at push #{self.count}")
        return self.inner(batch)


def test_no_failure_matches_plain_pipeline():
    plain, sup = [], []
    wf.Pipeline(wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
                          total=TOTAL, num_keys=K),
                [wf.Win_Seq(lambda wid, it: it.sum("v"),
                            WindowSpec(10, 10, win_type_t.TB), num_keys=K)],
                wf.Sink(collect(plain)), batch_size=50).run()
    build(collect(sup)).run()
    assert sorted(sup) == sorted(plain)


@pytest.mark.parametrize("fail_at", [[2], [3, 7], [1, 2, 3]])
def test_recovers_with_exactly_once_delivery(fail_at):
    oracle = []
    build(collect(oracle)).run()

    got = []
    p = build(collect(got), checkpoint_every=3, max_restarts=5)
    p.chain.push = Flaky(p.chain, fail_at)
    p.run()
    assert p.restarts == len(fail_at)
    assert sorted(got) == sorted(oracle), "results lost or duplicated on recovery"


def test_restart_budget_exhausts_on_permanent_failure():
    got = []
    p = build(collect(got), checkpoint_every=4, max_restarts=2)

    def always_fail(batch):
        raise RuntimeError("permanent fault")
    p.chain.push = always_fail
    with pytest.raises(RestartExhausted):
        p.run()


def test_spill_checkpoint_written(tmp_path):
    got = []
    path = str(tmp_path / "sup_ckpt.npz")
    p = build(collect(got), checkpoint_every=2, spill_path=path)
    p.run()
    import numpy as np
    data = np.load(path)
    assert "__meta__" in data
