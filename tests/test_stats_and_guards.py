"""Regression tests for honest accounting and safety guards (round-2 verdict #9):
num_kernels counts compiled launches (not operators), Win_Seq rejects an unbounded
default fired-window budget, KeyedMap's single-round fast path rejects same-key
duplicates instead of silently dropping updates."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.operators.map import KeyedMap
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.operators.win_seq import Win_Seq


def test_num_kernels_counts_launches_not_operators():
    src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=128, num_keys=2)
    ops = [wf.Map(lambda t: {"v": t.v + 1}),
           wf.Filter(lambda t: t.v >= 0),
           wf.Map(lambda t: {"v": t.v * 2})]
    p = wf.Pipeline(src, ops, wf.Sink(lambda v: None), batch_size=32)
    p.run()
    total_kernels = sum(op.get_StatsRecords()[0].num_kernels for op in ops)
    pushes = ops[0].get_StatsRecords()[0].batches_received
    assert pushes == 4                      # 128 tuples / batch 32
    # the 3-op chain is ONE fused program: one kernel per push, not one per op
    assert total_kernels == pushes
    # byte counters: 4 pushes x (key/id/ts i32 + v f32 + valid bool) x 32 lanes
    rec = ops[0].get_StatsRecords()[0]
    assert rec.bytes_received == 4 * 32 * (4 + 4 + 4 + 4 + 1)


def test_win_seq_default_budget_guard():
    op = Win_Seq(lambda wid, it: it.sum("v"), WindowSpec(1024, 1, win_type_t.CB),
                 num_keys=4)
    with pytest.raises(ValueError, match="max_wins"):
        op.out_capacity(65536)              # slide=1 @ 64k batch: [64k+, 1024] gather


def test_win_seqffat_default_budget_guard():
    from windflow_tpu.operators.win_seqffat import Win_SeqFFAT
    op = Win_SeqFFAT(lambda t: t.v, jnp.add,
                     spec=WindowSpec(4096, 1, win_type_t.CB), num_keys=4,
                     pane_capacity=8192)
    with pytest.raises(ValueError, match="max_wins"):
        op.out_capacity(1 << 20)


def test_win_seq_default_budget_ok_with_explicit_max_wins():
    op = Win_Seq(lambda wid, it: it.sum("v"), WindowSpec(1024, 1, win_type_t.CB),
                 num_keys=4, max_wins=128)
    assert op.out_capacity(65536) == 128


def _dup_batch():
    from windflow_tpu.batch import Batch
    return Batch(key=jnp.asarray([1, 1, 2], jnp.int32),     # duplicate key 1
                 id=jnp.arange(3, dtype=jnp.int32), ts=jnp.arange(3, dtype=jnp.int32),
                 payload={"v": jnp.asarray([1.0, 2.0, 3.0], jnp.float32)},
                 valid=jnp.ones(3, bool))


def test_keyed_map_folds_duplicates_in_order_even_unordered():
    # ordered=False no longer drops updates: duplicates take the in-order fallback
    op = KeyedMap(lambda t, s: ({"v": s + t.v}, s + t.v), jnp.float32(0),
                  num_keys=4, ordered=False)
    st = op.init_state({"v": jax.ShapeDtypeStruct((), jnp.float32)})
    st, out = jax.jit(op.apply)(st, _dup_batch())
    # key 1: running sums 1, then 1+2=3; key 2: 3
    np.testing.assert_allclose(np.asarray(out.payload["v"]), [1.0, 3.0, 3.0])
    np.testing.assert_allclose(float(st["tbl"][1]), 3.0)


def test_keyed_map_static_promise_violation_fails_loudly():
    op = KeyedMap(lambda t, s: ({"v": s + t.v}, s + t.v), jnp.float32(0),
                  num_keys=4, max_key_multiplicity=1)
    st = op.init_state({"v": jax.ShapeDtypeStruct((), jnp.float32)})
    with pytest.raises(Exception,
                       match="max_key_multiplicity|callback|CpuCallback"):
        _, out = jax.jit(op.apply)(st, _dup_batch())
        jax.block_until_ready(out.payload["v"])
        jax.effects_barrier()


def test_keyed_map_promise_violation_latched_to_flush():
    """The violation must be reported no later than EOS even if the async
    debug-callback report never surfaces: apply latches a device flag into the
    carried state and flush() raises on it synchronously."""
    op = KeyedMap(lambda t, s: ({"v": s + t.v}, s + t.v), jnp.float32(0),
                  num_keys=4, max_key_multiplicity=1)
    st = op.init_state({"v": jax.ShapeDtypeStruct((), jnp.float32)})
    # the async callback may surface during apply (eager backends) or the
    # latched flag raises at flush — either way the violation cannot reach EOS
    # unreported
    with pytest.raises(Exception,
                       match="max_key_multiplicity|callback|CpuCallback"):
        st, _ = jax.jit(op.apply)(st, _dup_batch())
        op.flush(st)


def test_keyed_map_flush_clean_when_promise_kept():
    op = KeyedMap(lambda t, s: ({"v": s + t.v}, s + t.v), jnp.float32(0),
                  num_keys=4, max_key_multiplicity=1)
    st = op.init_state({"v": jax.ShapeDtypeStruct((), jnp.float32)})
    from windflow_tpu.batch import Batch
    b = Batch(key=jnp.asarray([0, 1, 2], jnp.int32),
              id=jnp.arange(3, dtype=jnp.int32), ts=jnp.arange(3, dtype=jnp.int32),
              payload={"v": jnp.ones(3, jnp.float32)},
              valid=jnp.ones(3, bool))
    st, _ = jax.jit(op.apply)(st, b)
    st, out = op.flush(st)
    assert out is None


def test_keyed_map_fast_path_ok_without_duplicates():
    op = KeyedMap(lambda t, s: ({"v": s + t.v}, s + t.v), jnp.float32(0),
                  num_keys=4, ordered=False)
    st = op.init_state({"v": jax.ShapeDtypeStruct((), jnp.float32)})
    from windflow_tpu.batch import Batch
    b = Batch(key=jnp.asarray([0, 1, 2], jnp.int32),
              id=jnp.arange(3, dtype=jnp.int32), ts=jnp.arange(3, dtype=jnp.int32),
              payload={"v": jnp.ones(3, jnp.float32)},
              valid=jnp.ones(3, bool))
    _, out = jax.jit(op.apply)(st, b)
    np.testing.assert_allclose(np.asarray(out.payload["v"]), [1.0, 1.0, 1.0])


def test_xprof_trace_produces_a_capture(tmp_path):
    """wf.xprof_trace wraps a run in a JAX profiler capture (SURVEY §5 tracing:
    Xprof hooks beside the Stats_Record counters)."""
    import os
    import jax.numpy as jnp
    import windflow_tpu as wf

    logdir = str(tmp_path / "trace")
    with wf.xprof_trace(logdir):
        g = wf.PipeGraph("prof", batch_size=32)
        g.add_source(wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=64)) \
         .add(wf.ReduceSink(lambda t: t.v, name="s"))
        g.run()
    found = [os.path.join(r, f) for r, _, fs in os.walk(logdir) for f in fs]
    assert found, "profiler produced no capture files"


def test_pipegraph_dump_stats_writes_per_operator_logs(tmp_path):
    """PipeGraph.dump_stats: one JSON per operator replica under log_dir with
    live counters (TRACE_WINDFLOW analogue, wf/stats_record.hpp:109-155)."""
    import json

    g = wf.PipeGraph("stats", batch_size=32)
    (g.add_source(wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=96,
                            name="gen"))
     .add(wf.Map(lambda t: {"v": t.v * 2}, name="dbl"))
     .add(wf.ReduceSink(lambda t: t.v, name="tot")))
    g.run()
    paths = g.dump_stats(str(tmp_path))
    assert len(paths) >= 3
    names = set()
    for p in paths:
        with open(p) as f:
            rec = json.load(f)
        names.add(rec["operator"])
        assert rec["batches_received"] >= 1 or rec["operator"] == "gen"
    assert {"gen", "dbl", "tot"} <= names


def test_stats_service_times_and_transfer_bytes_populated():
    """Device counters carry real values under a real run, not dumped zeros
    (wf/stats_record.hpp:76-80: per-svc service time + H2D/D2H byte counts —
    VERDICT r04 missing #6): the chain samples service time every Nth push,
    the source counts framed H2D bytes, the sink counts D2H bytes."""
    import numpy as np
    from windflow_tpu.operators.source import GeneratorSource

    out = []

    def gen():
        for s in range(0, 640, 32):
            yield {"v": np.arange(s, s + 32, dtype=np.int32)}

    g = wf.PipeGraph("svc", batch_size=32)
    (g.add_source(GeneratorSource(gen, {"v": jnp.zeros((), jnp.int32)},
                                  name="gen"))
     .add(wf.Map(lambda t: {"v": t.v * 2}, name="dbl"))
     .add_sink(wf.Sink(lambda view: out.append(view), name="snk")))
    g.run()
    recs = {op.getName(): op.get_StatsRecords()[0] for op in g.listOperators()}
    # entry op of the chain: sampled service times (20 pushes, sample every 16)
    assert recs["dbl"].avg_service_time_us > 0.0
    assert recs["dbl"].num_kernels >= 20
    # host source framed batches and moved them H2D (a DeviceSource would — and
    # should — count zero: it generates inside the compiled program)
    assert recs["gen"].bytes_copied_hd > 0
    # sink pulled every result batch D2H
    assert recs["snk"].bytes_copied_dh > 0
    assert recs["snk"].inputs_received == 640
    assert len([v for v in out if v is not None]) == 20
