"""Tests for the parallel window patterns: Key_Farm, Win_Farm, Key_FFAT, Pane_Farm,
Win_MapReduce — all must agree with the plain Win_Seq oracle on the same stream
(the reference's mp_test_cpu matrix property: every pattern computes the same windows)."""

import numpy as np
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.operators.win_seq import Win_Seq
from windflow_tpu.operators.win_seqffat import Win_SeqFFAT
from windflow_tpu.operators.win_patterns import (Win_Farm, Key_Farm, Key_FFAT,
                                                 Pane_Farm, Win_MapReduce)


def collect(total, K, op, batch_size=32):
    src = wf.Source(lambda i: {"v": (i // K).astype(jnp.float32)},
                    total=total, num_keys=K)
    results = []

    def cb(view):
        if view is None:
            return
        for k, w, r in zip(view["key"].tolist(), view["id"].tolist(),
                           np.asarray(view["payload"]).tolist()):
            results.append((k, w, round(float(r), 3)))

    wf.Pipeline(src, [op], wf.Sink(cb), batch_size=batch_size).run()
    return sorted(results)


def winseq_oracle(total, K, spec, **kw):
    return collect(total, K, Win_Seq(lambda wid, it: it.sum("v"), spec,
                                     num_keys=K, **kw))


def test_key_farm_matches_win_seq():
    spec = WindowSpec(6, 2, win_type_t.CB)
    kf = Key_Farm(lambda wid, it: it.sum("v"), spec, parallelism=4, num_keys=3)
    assert collect(150, 3, kf) == winseq_oracle(150, 3, spec)


def test_win_farm_keyless():
    spec = WindowSpec(8, 4, win_type_t.CB)
    wfarm = Win_Farm(lambda wid, it: it.sum("v"), spec, parallelism=4)
    got = collect(128, 1, wfarm)
    assert got == winseq_oracle(128, 1, spec)


def test_key_ffat_matches_win_seq_sum():
    spec = WindowSpec(6, 2, win_type_t.CB)
    ff = Key_FFAT(lambda t: t.v, jnp.add, spec=spec, num_keys=3)
    assert collect(150, 3, ff) == winseq_oracle(150, 3, spec)


def test_key_ffat_max_combine():
    spec = WindowSpec(4, 2, win_type_t.CB)
    ff = Key_FFAT(lambda t: t.v, jnp.maximum, spec=spec, identity=-1e30, num_keys=2)
    ws = Win_Seq(lambda wid, it: it.max("v"), spec, num_keys=2)
    assert collect(100, 2, ff) == collect(100, 2, ws)


def test_key_ffat_tb():
    spec = WindowSpec(8, 4, win_type_t.TB)
    ff = Key_FFAT(lambda t: t.v, jnp.add, spec=spec, num_keys=2)
    ws = Win_Seq(lambda wid, it: it.sum("v"), spec, num_keys=2)
    assert collect(120, 2, ff) == collect(120, 2, ws)


def test_pane_farm_matches_win_seq():
    spec = WindowSpec(6, 2, win_type_t.CB)   # pane_len = gcd(6,2) = 2
    pf = Pane_Farm(lambda pid, it: it.sum("v"), lambda wid, it: it.sum(), spec,
                   num_keys=3)
    assert collect(150, 3, pf) == winseq_oracle(150, 3, spec)


def test_win_mapreduce_matches_win_seq():
    spec = WindowSpec(8, 8, win_type_t.CB)
    wmr = Win_MapReduce(lambda wid, it: it.sum("v"), lambda wid, it: it.sum(),
                        spec, map_parallelism=4, num_keys=2)
    # WMR fires only complete windows; compare against non-flushed oracle subset
    got = collect(160, 2, wmr)
    oracle = winseq_oracle(160, 2, spec)
    assert got == oracle


def test_win_mapreduce_non_divisible():
    # win_len not a multiple of map_parallelism: round-robin leaves remainders
    spec = WindowSpec(10, 10, win_type_t.CB)
    wmr = Win_MapReduce(lambda wid, it: it.sum("v"), lambda wid, it: it.sum(),
                        spec, map_parallelism=3, num_keys=2)
    assert collect(200, 2, wmr) == winseq_oracle(200, 2, spec)


def test_win_mapreduce_tb():
    # TB windows: mask-aware round-robin partition over the archive row
    spec = WindowSpec(8, 8, win_type_t.TB)
    wmr = Win_MapReduce(lambda wid, it: it.sum("v"), lambda wid, it: it.sum(),
                        spec, map_parallelism=2, num_keys=2)
    assert collect(160, 2, wmr) == winseq_oracle(160, 2, spec)


def test_win_mapreduce_empty_partition_not_poisoning_reduce():
    # TB window with fewer tuples than map_parallelism: the empty partition's
    # identity partial (sum -> 0) must not enter a min-reduce
    spec = WindowSpec(2, 2, win_type_t.TB)
    wmr = Win_MapReduce(lambda wid, it: it.sum("v"), lambda wid, it: it.min(),
                        spec, map_parallelism=3, num_keys=1)
    src = wf.Source(lambda i: {"v": (i + 1).astype(jnp.float32)}, total=8,
                    num_keys=1)
    got = []

    def cb(view):
        if view is None:
            return
        got.extend((int(w), float(r)) for w, r in
                   zip(view["id"].tolist(), np.asarray(view["payload"]).tolist()))

    wf.Pipeline(src, [wmr], wf.Sink(cb), batch_size=8).run()
    # windows {1,2},{3,4},{5,6},{7,8}: each has 2 tuples over 3 partitions; the
    # min over non-empty partials is the smaller value, never the empty 0.0
    assert sorted(got) == [(0, 1.0), (1, 3.0), (2, 5.0), (3, 7.0)]


def test_win_mapreduce_sliding():
    spec = WindowSpec(8, 4, win_type_t.CB)
    wmr = Win_MapReduce(lambda wid, it: it.sum("v"), lambda wid, it: it.sum(),
                        spec, map_parallelism=4, num_keys=2)
    assert collect(160, 2, wmr) == winseq_oracle(160, 2, spec)


# ---- nesting: WF+PF, WF+WMR, KF+PF, KF+WMR (the reference's mp_test nested matrix)

def _pf(spec, K):
    return Pane_Farm(lambda pid, it: it.sum("v"), lambda wid, it: it.sum(), spec,
                     num_keys=K)


def _wmr(spec, K, M=2):
    return Win_MapReduce(lambda wid, it: it.sum("v"), lambda wid, it: it.sum(),
                         spec, map_parallelism=M, num_keys=K)


def test_nested_wf_pf():
    spec = WindowSpec(6, 2, win_type_t.CB)
    op = Win_Farm(_pf(spec, 3), parallelism=4)
    assert op.shard_axis == "window"
    assert collect(150, 3, op) == winseq_oracle(150, 3, spec)


def test_nested_kf_pf_tb():
    spec = WindowSpec(8, 4, win_type_t.TB)
    op = Key_Farm(_pf(spec, 2), parallelism=2)
    assert op.shard_axis == "key"
    assert collect(160, 2, op) == winseq_oracle(160, 2, spec)


def test_nested_wf_wmr():
    spec = WindowSpec(8, 4, win_type_t.CB)
    op = Win_Farm(_wmr(spec, 2, M=4), parallelism=2)
    assert collect(160, 2, op) == winseq_oracle(160, 2, spec)


def test_nested_kf_wmr_builder():
    from windflow_tpu.runtime.builders import (KeyFarm_Builder, WinMapReduce_Builder)
    spec_args = (6, 3)
    inner = (WinMapReduce_Builder(lambda wid, it: it.sum("v"),
                                  lambda wid, it: it.sum())
             .withCBWindows(*spec_args).withMapParallelism(3).withKeys(2).build())
    op = KeyFarm_Builder(inner).withParallelism(2).build()
    spec = WindowSpec(*spec_args, win_type_t.CB)
    assert collect(150, 2, op) == winseq_oracle(150, 2, spec)


def test_fuzz_patterns_match_win_seq_random_geometry():
    """Randomized specs x patterns vs the Win_Seq oracle: every parallel
    pattern must compute the identical window set for arbitrary (win, slide),
    CB and TB, sliding and tumbling, at random batch sizes."""
    rng = np.random.default_rng(11)
    for trial in range(6):
        wt = win_type_t.CB if trial % 2 == 0 else win_type_t.TB
        slide = int(rng.integers(2, 8))
        win = slide * int(rng.integers(1, 4))        # multiple: legal for panes
        K = int(rng.integers(1, 4))
        total = int(rng.integers(60, 200))
        bs = int(rng.integers(16, 64))
        spec = WindowSpec(win, slide, wt)
        oracle = collect(total, K, Win_Seq(lambda wid, it: it.sum("v"), spec,
                                           num_keys=K), batch_size=bs)
        pats = [Key_Farm(lambda wid, it: it.sum("v"), spec, parallelism=2,
                         num_keys=K),
                Key_FFAT(lambda t: t.v, jnp.add, spec=spec, num_keys=K),
                Win_Farm(lambda wid, it: it.sum("v"), spec, parallelism=3,
                         num_keys=K)]
        if win > slide:
            pats.append(Pane_Farm(lambda pid, it: it.sum("v"),
                                  lambda wid, it: it.sum(), spec, num_keys=K))
        if wt == win_type_t.CB or win == slide:
            pats.append(Win_MapReduce(lambda wid, it: it.sum("v"),
                                      lambda wid, it: it.sum(), spec,
                                      map_parallelism=2, num_keys=K))
        for p in pats:
            got = collect(total, K, p, batch_size=bs)
            assert got == oracle, (
                f"trial {trial}: {type(p).__name__} diverges at "
                f"spec=({win},{slide},{wt}) K={K} total={total} bs={bs}")
