"""Randomized fuzz of the device scan/compaction/segment primitives against
numpy oracles — counterpart of the reference's fuzz loop re-running its CUDA
scan test at random sizes (src/individual_test_gpu/mass_cudascan_test.py:1-16).
30 random (size, keys, fan-out, occupancy) configurations per primitive."""

import numpy as np
import jax.numpy as jnp
import pytest

from windflow_tpu.ops.compaction import (exclusive_scan, compact_indices,
                                         partition_by_destination,
                                         scatter_compact)
from windflow_tpu.ops.segment import segment_rank, segment_reduce

RNG = np.random.default_rng(2026)
CONFIGS = [(int(RNG.integers(1, 2049)), int(RNG.integers(1, 33)),
            int(RNG.integers(2, 9)), float(RNG.uniform(0.05, 1.0)))
           for _ in range(30)]


@pytest.mark.parametrize("n,k,f,occ", CONFIGS[:10])
def test_fuzz_exclusive_scan_and_compact(n, k, f, occ):
    valid = RNG.random(n) < occ
    x = valid.astype(np.int32)
    np.testing.assert_array_equal(
        np.asarray(exclusive_scan(jnp.asarray(x))),
        np.concatenate([[0], np.cumsum(x)[:-1]]))
    idx, ovalid = compact_indices(jnp.asarray(valid))
    count = int(np.asarray(ovalid).sum())
    assert count == valid.sum()
    live = np.flatnonzero(valid)
    np.testing.assert_array_equal(np.asarray(idx)[:count], live)


@pytest.mark.parametrize("n,k,f,occ", CONFIGS[10:20])
def test_fuzz_partition_by_destination(n, k, f, occ):
    valid = RNG.random(n) < occ
    dest = RNG.integers(0, f, n).astype(np.int32)
    cap = max(int(valid.sum()), 1)
    gidx, ovalid = partition_by_destination(jnp.asarray(dest), jnp.asarray(valid),
                                            f, cap)
    gidx, ovalid = np.asarray(gidx), np.asarray(ovalid)
    vals = np.arange(n, dtype=np.int64)
    for d in range(f):
        want = vals[valid & (dest == d)]
        got = np.sort(vals[gidx[d]][ovalid[d]])
        np.testing.assert_array_equal(got, np.sort(want))


@pytest.mark.parametrize("n,k,f,occ", CONFIGS[20:30])
def test_fuzz_segment_rank_and_reduce(n, k, f, occ):
    valid = RNG.random(n) < occ
    keys = RNG.integers(0, k, n).astype(np.int32)
    vals = RNG.random(n).astype(np.float32)

    rank = np.asarray(segment_rank(jnp.asarray(keys), jnp.asarray(valid)))
    seen = {}
    for i in range(n):
        if valid[i]:
            assert rank[i] == seen.get(keys[i], 0)
            seen[keys[i]] = seen.get(keys[i], 0) + 1

    red = np.asarray(segment_reduce(jnp.asarray(vals), jnp.asarray(keys),
                                    jnp.asarray(valid), k))
    want = np.zeros(k, np.float32)
    np.add.at(want, keys[valid], vals[valid])
    np.testing.assert_allclose(red, want, rtol=1e-5)


def test_fuzz_scatter_compact_roundtrip():
    for n, k, f, occ in CONFIGS[:8]:
        valid = RNG.random(n) < occ
        vals = RNG.integers(0, 1000, n).astype(np.int32)
        out, ovalid = scatter_compact({"v": jnp.asarray(vals)}, jnp.asarray(valid))
        out, ovalid = np.asarray(out["v"]), np.asarray(ovalid)
        np.testing.assert_array_equal(out[ovalid], vals[valid])
        assert ovalid.sum() == valid.sum()
        assert ovalid[:int(valid.sum())].all()       # stable front-packing


@pytest.mark.parametrize("n,k,f,occ", CONFIGS[10:20])
def test_fuzz_partition_onehot_matches_sort(n, k, f, occ):
    """The sort-free one-hot partition must agree with the sort-based one
    exactly — same stable within-destination order, same validity — including
    under capacity truncation."""
    from windflow_tpu.ops.compaction import partition_by_destination_onehot
    valid = RNG.random(n) < occ
    dest = RNG.integers(0, f, n).astype(np.int32)
    for cap in (max(int(valid.sum()), 1), max(int(valid.sum()) // (2 * f), 1)):
        a_idx, a_val = partition_by_destination(jnp.asarray(dest),
                                                jnp.asarray(valid), f, cap)
        b_idx, b_val = partition_by_destination_onehot(jnp.asarray(dest),
                                                       jnp.asarray(valid), f, cap)
        np.testing.assert_array_equal(np.asarray(a_val), np.asarray(b_val))
        np.testing.assert_array_equal(
            np.asarray(a_idx)[np.asarray(a_val)],
            np.asarray(b_idx)[np.asarray(b_val)])


def test_partition_onehot_drops_out_of_range_like_sort():
    """A routing_func may return dest outside [0, n_dest); both variants must
    DROP such lanes (sort maps them to the discarded n_dest bucket) rather
    than overwrite a legitimate lane's slot."""
    from windflow_tpu.ops.compaction import partition_by_destination_onehot
    dest = jnp.asarray(np.array([2, 5, 0, -1, 2, 1], np.int32))
    valid = jnp.ones(6, bool)
    a_idx, a_val = partition_by_destination(dest, valid, 3, 2)
    b_idx, b_val = partition_by_destination_onehot(dest, valid, 3, 2)
    np.testing.assert_array_equal(np.asarray(a_val), np.asarray(b_val))
    np.testing.assert_array_equal(np.asarray(a_idx)[np.asarray(a_val)],
                                  np.asarray(b_idx)[np.asarray(b_val)])
