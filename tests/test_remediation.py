"""Self-driving remediation (PR 17): policy grammar + resolution, the live
Reporter-tick engine (cooldown/budget/damping/gating/advisory actuators),
the deterministic commit-barrier engine (windows, damping, state
round-trip), supervised integration (byte-identical replay with remediation
active; arbitration against auto-reshard), actuator edge cases (rate change
mid-held-batch, re-climb during settle blackout), the WF118 validator, the
wf_slo/wf_top remediation surfaces, and the closed-loop chaos acceptance."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.batch import Batch
from windflow_tpu.control import (AdmissionController, CapacityAutotuner,
                                  ControlConfig, TokenBucket)
from windflow_tpu.control import _state as control_state
from windflow_tpu.control import remediation as rem
from windflow_tpu.observability import (MonitoringConfig, set_journal,
                                        journal as journal_mod)
from windflow_tpu.observability.journal import EventJournal
from windflow_tpu.observability.names import (CONTROL_COUNTERS,
                                              CONTROL_GAUGES, JOURNAL_EVENTS)
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.runtime.faults import FaultInjector, FaultPlan, FaultSpec
from windflow_tpu.runtime.supervisor import SupervisedPipeline

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_state():
    control_state.reset()
    yield
    control_state.reset()
    set_journal(None)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _mkbatch(n, start=0, ts=None):
    i = np.arange(start, start + n, dtype=np.int32)
    return Batch(key=jnp.asarray(i % 4), id=jnp.asarray(i),
                 ts=jnp.asarray(ts if ts is not None else i),
                 payload={"v": jnp.asarray(i, jnp.float32)},
                 valid=jnp.ones(n, bool))


def _page_snap(slo="lat", burn=3.0, code=2, **extra):
    snap = {"slo": {slo: {"state": {2: "page", 1: "warn", 0: "ok"}[code],
                          "code": code, "burn_fast": burn,
                          "burn_slow": burn}}}
    snap.update(extra)
    return snap


def _action(**kw):
    base = dict(name="a", slo="lat", actuator="admission_rate")
    base.update(kw)
    return rem.RemediationAction(**base)


def _collect(acc):
    def cb(view):
        if view is None:
            return
        acc.extend(zip(view["key"].tolist(), view["id"].tolist(),
                       np.asarray(view["payload"]).tolist()))
    return cb


def _src(total, num_keys):
    return wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
                     total=total, num_keys=num_keys)


def _op(num_keys):
    return wf.Win_Seq(lambda wid, it: it.sum("v"),
                      WindowSpec(10, 10, win_type_t.TB), num_keys=num_keys)


# ------------------------------------------------------- registry lockstep


def test_remediation_names_registered():
    for ev in ("remediation_apply", "remediation_skip", "tuning_reclimb"):
        assert ev in JOURNAL_EVENTS
    for c in ("remediation_actions", "remediation_skips"):
        assert c in CONTROL_COUNTERS
    for g in ("bucket_rate", "remediation_hot_capacity",
              "remediation_recommended_delay"):
        assert g in CONTROL_GAUGES


# --------------------------------------------------------- policy grammar


def test_resolve_policy_forms(tmp_path):
    assert rem.resolve_policy(None) is None
    assert rem.resolve_policy(False) is None
    assert rem.resolve_policy("0") is None
    assert rem.resolve_policy("") is None
    for on in (True, 1, "1"):
        p = rem.resolve_policy(on)
        assert [a.name for a in p.actions] == [a.name for a in
                                               rem.default_policy().actions]
    d = {"name": "x", "slo": "lat", "actuator": "admission_rate",
         "factor": 0.5}
    assert rem.resolve_policy([d]).actions[0].factor == 0.5
    assert rem.resolve_policy({"actions": [d]}).actions[0].name == "x"
    inline = json.dumps([d])
    assert rem.resolve_policy(inline).actions[0].name == "x"
    f = tmp_path / "pol.json"
    f.write_text(inline)
    assert rem.resolve_policy(str(f)).actions[0].name == "x"
    existing = rem.default_policy()
    assert rem.resolve_policy(existing) is existing


def test_resolve_policy_rejects_garbage():
    with pytest.raises(ValueError):
        rem.resolve_policy("{not json")
    with pytest.raises(ValueError):
        rem.resolve_policy([{"name": "x", "slo": "lat",
                             "actuator": "warp_drive"}])
    with pytest.raises(ValueError):
        rem.resolve_policy([{"name": "x", "slo": "lat",
                             "actuator": "admission_rate",
                             "flavor": "sour"}])      # unknown field
    with pytest.raises(ValueError):
        rem.RemediationPolicy((_action(factor=0.0),))
    with pytest.raises(ValueError):
        rem.RemediationPolicy((_action(gate="dispatch_ratio!!0.5"),))
    with pytest.raises(ValueError):          # duplicate action names
        rem.RemediationPolicy((_action(name="dup"), _action(name="dup")))


def test_policy_problems_checks_spec_names():
    p = rem.RemediationPolicy((_action(slo="lat"),))
    assert rem.policy_problems(p, spec_names=["lat"]) == []
    probs = rem.policy_problems(p, spec_names=["other"])
    assert probs and "lat" in probs[0]


def test_resolve_barrier_policy_ownership():
    p = rem.resolve_barrier_policy(True, admission=True, shards=1)
    assert [a.actuator for a in p.actions] == ["admission_rate"]
    p = rem.resolve_barrier_policy(True, admission=True, shards=4)
    assert sorted(a.actuator for a in p.actions) == ["admission_rate",
                                                     "reshard"]
    p = rem.resolve_barrier_policy(True, admission=False, shards=4)
    assert [a.actuator for a in p.actions] == ["reshard"]
    with pytest.raises(ValueError):          # nothing owned
        rem.resolve_barrier_policy(True, admission=False, shards=1)
    with pytest.raises(ValueError):          # not barrier-actionable
        rem.resolve_barrier_policy(
            [{"name": "x", "slo": "lat", "actuator": "autotune_reclimb"}],
            admission=True, shards=1)
    with pytest.raises(ValueError):          # reshard without shards
        rem.resolve_barrier_policy(
            [{"name": "x", "slo": "shards", "actuator": "reshard"}],
            admission=True, shards=1)
    assert rem.resolve_barrier_policy(None, admission=True, shards=1) is None


# ------------------------------------------------------- live engine (unit)


def test_live_engine_fires_on_page_only():
    clk = FakeClock()
    eng = rem.RemediationEngine(rem.RemediationPolicy((_action(),)),
                                cooldown_s=1.0, clock=clk)
    calls = []
    eng.bind("admission_rate", lambda a: calls.append(a.name) or {})
    eng.on_verdicts(_page_snap(code=0))
    eng.on_verdicts(_page_snap(code=1))
    assert calls == [] and eng.applied == 0
    snap = _page_snap(code=2)
    eng.on_verdicts(snap)
    assert calls == ["a"] and eng.applied == 1
    assert snap["remediation"]["applied"] == 1    # section folded in place
    assert snap["remediation"]["ledger"][-1]["action"] == "a"
    assert snap["remediation"]["bound"] == ["admission_rate"]


def test_live_engine_cooldown_budget_and_damping():
    clk = FakeClock()
    eng = rem.RemediationEngine(
        rem.RemediationPolicy((_action(max_applies=4),)),
        cooldown_s=10.0, max_actions=8, clock=clk)
    eng.bind("admission_rate", lambda a: {})
    eng.on_verdicts(_page_snap(burn=4.0))
    assert eng.applied == 1
    eng.on_verdicts(_page_snap(burn=4.0))         # inside cooldown
    assert eng.applied == 1
    assert eng._per["a"]["last_skip"] == "cooldown"
    clk.advance(11.0)
    # burn improved by >10% -> fires again
    eng.on_verdicts(_page_snap(burn=2.0))
    assert eng.applied == 2
    clk.advance(11.0)
    # burn NOT improved (>= 0.9 * prev) -> damped, permanently stopped
    eng.on_verdicts(_page_snap(burn=1.9))
    assert eng.applied == 2
    assert eng._per["a"]["stopped"]
    clk.advance(11.0)
    eng.on_verdicts(_page_snap(burn=0.1))         # even a huge improvement
    assert eng.applied == 2                        # stays stopped


def test_live_engine_run_budget():
    clk = FakeClock()
    acts = tuple(_action(name=f"a{k}", max_applies=9) for k in range(3))
    eng = rem.RemediationEngine(rem.RemediationPolicy(acts),
                                cooldown_s=0.0, max_actions=2, clock=clk)
    eng.bind("admission_rate", lambda a: {})
    eng.on_verdicts(_page_snap())
    assert eng.applied == 2                        # run budget caps the tick
    assert eng._per["a2"]["last_skip"] == "run_budget"


def test_live_engine_unbound_and_gate():
    clk = FakeClock()
    eng = rem.RemediationEngine(
        rem.RemediationPolicy((
            _action(name="loose", actuator="autotune_reclimb"),
            _action(name="gated", gate="dispatch_ratio>=0.5"),)),
        cooldown_s=0.0, clock=clk)
    eng.bind("admission_rate", lambda a: {})
    eng.on_verdicts(_page_snap())                  # no health section at all
    assert eng._per["loose"]["last_skip"] == "unbound"
    assert eng._per["gated"]["last_skip"] == "gate_unobserved"
    eng.on_verdicts(_page_snap(
        health={"device_time": {"s0": {"dispatch_ratio": 0.2}}}))
    assert eng._per["gated"]["last_skip"] == "gate"
    assert eng.applied == 0
    eng.on_verdicts(_page_snap(
        health={"device_time": {"s0": {"dispatch_ratio": 0.8}}}))
    assert eng.applied == 1                        # gate satisfied -> fires


def test_live_engine_advisory_hot_capacity_sets_gauge():
    clk = FakeClock()
    eng = rem.RemediationEngine(
        rem.RemediationPolicy((_action(
            name="grow", actuator="hot_capacity", factor=0.5, floor=1.0),)),
        cooldown_s=0.0, clock=clk)
    # nothing observable to scale a recommendation from
    eng.on_verdicts(_page_snap())
    assert eng._per["grow"]["last_skip"] == "unobserved"
    eng.on_verdicts(_page_snap(
        control={"gauges": {"hot_capacity": 64.0}}))
    assert eng.applied == 1
    last = eng._ledger[-1]
    assert last["recommended"] == 128.0            # ceil(64 / 0.5)
    assert last["advisory"] is True
    assert control_state.gauges()["remediation_hot_capacity"] == 128.0


def test_live_engine_skip_journals_on_transitions_only(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    set_journal(EventJournal(path))
    clk = FakeClock()
    eng = rem.RemediationEngine(
        rem.RemediationPolicy((_action(actuator="autotune_reclimb"),)),
        cooldown_s=0.0, clock=clk)
    for _ in range(5):
        eng.on_verdicts(_page_snap())              # same reason every tick
    journal_mod.get_active().close()
    evs = [e for e in journal_mod.read_journal(path)
           if e["event"] == "remediation_skip"]
    assert len(evs) == 1 and evs[0]["reason"] == "unbound"
    assert eng.skipped == 5                        # counted every time
    assert control_state.counters()["remediation_skips"] == 5


def test_live_engine_actuator_exception_is_contained():
    clk = FakeClock()
    eng = rem.RemediationEngine(rem.RemediationPolicy((_action(),)),
                                cooldown_s=0.0, clock=clk)

    def boom(a):
        raise RuntimeError("knob fell off")

    eng.bind("admission_rate", boom)
    eng.on_verdicts(_page_snap())                  # must not raise
    assert eng.applied == 0
    assert eng._per["a"]["last_skip"] == "actuator_error:RuntimeError"


# -------------------------------------------------- barrier engine (unit)


def _barrier_eng(**kw):
    base = dict(cooldown_barriers=2, max_actions=8)
    base.update(kw)
    pol = rem.RemediationPolicy((_action(
        name="shed", slo="drops", actuator="admission_rate",
        target=0.1, window=3, max_applies=4),))
    return rem.BarrierRemediation(pol, **base)


def test_barrier_window_and_fire():
    eng = _barrier_eng()
    decisions = []
    for pos in range(5):
        decisions.extend(eng.on_barrier(pos, {"drop_ratio": 0.5}))
    fired = [d for d in decisions if d.get("applied")]
    assert len(fired) == 1 and fired[0]["pos"] == 2    # 3rd violating barrier
    assert fired[0]["actuator"] == "admission_rate"
    assert fired[0]["factor"] == 0.7 and fired[0]["floor"] == 1.0


def test_barrier_missing_signal_freezes_window():
    eng = _barrier_eng()
    eng.on_barrier(0, {"drop_ratio": 0.5})
    eng.on_barrier(1, {})                          # empty interval: frozen
    eng.on_barrier(2, {"drop_ratio": 0.5})
    assert eng.on_barrier(3, {"drop_ratio": 0.5})[0]["applied"]
    # a clean value below target, by contrast, DOES reset the window
    eng2 = _barrier_eng()
    eng2.on_barrier(0, {"drop_ratio": 0.5})
    eng2.on_barrier(1, {"drop_ratio": 0.0})
    eng2.on_barrier(2, {"drop_ratio": 0.5})
    assert not eng2.on_barrier(3, {"drop_ratio": 0.5})


def test_barrier_damping_emits_skip_decision():
    eng = _barrier_eng(cooldown_barriers=1)
    out = []
    for pos in range(12):
        out.extend(eng.on_barrier(pos, {"drop_ratio": 0.5}))
    applies = [d for d in out if d.get("applied")]
    damped = [d for d in out if d.get("reason") == "damped"]
    assert len(applies) == 1                       # no improvement -> damped
    assert damped and eng.state()["per"]["shed"]["stopped"]


def test_barrier_state_roundtrip_determinism():
    sigs = [{"drop_ratio": v} for v in
            (0.5, 0.5, 0.0, 0.5, 0.5, 0.5, 0.2, 0.5, 0.5, 0.5)]
    eng1 = _barrier_eng()
    out1 = [eng1.on_barrier(p, s) for p, s in enumerate(sigs)]
    # replay: checkpoint the state at barrier 4, restore into a fresh
    # engine, and continue — decisions and final state must be identical
    eng2 = _barrier_eng()
    for p, s in enumerate(sigs[:4]):
        eng2.on_barrier(p, s)
    st = json.loads(json.dumps(eng2.state()))      # survives serialization
    eng3 = _barrier_eng()
    eng3.set_state(st)
    out3 = [eng3.on_barrier(p + 4, s) for p, s in enumerate(sigs[4:])]
    assert [d for o in out1[4:] for d in o] == [d for o in out3 for d in o]
    assert eng1.state() == eng3.state()


# -------------------------------------------- actuator edge cases (unit)


def test_rate_change_mid_held_batch_drop_oldest_ts():
    """scale_rate while the drop_oldest_ts hold queue is non-empty: held
    batches are untouched by the rate change and release in ts order at
    the NEW rate; the shed/admit accounting never double-counts."""
    clk = FakeClock()
    adm = AdmissionController(TokenBucket(rate=0.0, burst=10.0, clock=clk),
                              "drop_oldest_ts", hold_max=4)
    b0, b1, b2 = (_mkbatch(10, 100 * k) for k in range(3))
    assert adm.offer(b0) == [b0]                   # burst covers the first
    assert adm.offer(b1) == [] and adm.offer(b2) == []
    assert len(adm.held) == 2
    delta = adm.scale_rate(0.5, floor=40.0)        # mid-hold: floor wins
    assert delta == {"rate": 40.0, "prev_rate": 0.0}
    assert len(adm.held) == 2                      # holds untouched
    assert control_state.gauges()["bucket_rate"] == 40.0
    clk.advance(0.25)                              # +10 tokens at the new rate
    out = adm.offer(_mkbatch(10, 300))
    # FIFO: the oldest HELD batch releases first, the fresh offer queues
    assert [int(np.asarray(b.id)[0]) for b in out] == [100]
    assert [int(np.asarray(b.id)[0]) for b, *_ in adm.held] == [200, 300]
    clk.advance(0.25)
    out = adm.offer(_mkbatch(10, 400))
    assert [int(np.asarray(b.id)[0]) for b in out] == [200]
    drained = adm.drain()                          # EOS admits the tail
    assert [int(np.asarray(b.id)[0]) for b in drained] == [300, 400]
    assert adm.admitted == 5 and adm.shed == 0     # nothing double-counted
    # bucket snapshots stay tokens-only: a remediation-scaled rate must
    # never leak into checkpoint state (it rides the snapshot's
    # "remediation" key instead)
    assert set(adm.state()["bucket"]) == {"tokens"}


def test_reclimb_noop_during_settle_blackout(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    set_journal(EventJournal(path))
    clk = FakeClock()
    rates = {16: 1000.0, 32: 3000.0, 64: 2000.0}
    tuner = CapacityAutotuner([16, 32, 64], start_capacity=16,
                              decide_every=2, settle_batches=3, clock=clk)
    for _ in range(50):                            # drive to the first switch
        cap = tuner.capacity
        clk.advance(cap / rates[cap])
        tuner.on_batch(cap)
        if tuner.capacity != 16:
            break
    assert tuner.capacity == 32                    # mid-climb, in blackout
    assert tuner._settle > 0 and not tuner.converged
    phase_before = tuner._phase
    tuner.request_reclimb()
    clk.advance(0.001)
    tuner.on_batch(tuner.capacity)                 # consumes the event...
    # ...but the climb in progress IS the re-climb: nothing clobbered
    assert not tuner.converged
    assert tuner._phase == phase_before
    assert tuner.reclimb() is False                # still a no-op
    journal_mod.get_active().close()
    evs = journal_mod.read_journal(path)
    assert not [e for e in evs if e["event"] == "tuning_reclimb"]


def test_reclimb_after_convergence_journals_and_reexplores(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    set_journal(EventJournal(path))
    clk = FakeClock()
    rates = {16: 1000.0, 32: 4000.0, 64: 2000.0}
    tuner = CapacityAutotuner([16, 32, 64], start_capacity=16,
                              decide_every=2, settle_batches=1, clock=clk)
    for _ in range(300):
        cap = tuner.capacity
        clk.advance(cap / rates[cap])
        tuner.on_batch(cap)
        if tuner.converged:
            break
    assert tuner.converged and tuner.capacity == 32
    tuner.request_reclimb()
    clk.advance(0.001)
    tuner.on_batch(tuner.capacity)
    assert not tuner.converged                     # re-exploring the ladder
    journal_mod.get_active().close()
    evs = journal_mod.read_journal(path)
    assert [e for e in evs if e["event"] == "tuning_reclimb"]


# ------------------------------------------------ supervised integration


def _sup_run(total=400, batch=20, faults=None, remediation=True):
    got = []
    p = SupervisedPipeline(
        _src(total, 4), [_op(4)], wf.Sink(_collect(got)),
        # checkpoint_every=2: with refill = cost/2 the bucket admits every
        # other batch, so a 2-batch interval sheds at a steady 0.5 ratio —
        # 5 consecutive violating barriers arm shed_harder's window
        batch_size=batch, checkpoint_every=2, max_restarts=16,
        backoff_base=0.001, backoff_cap=0.01, faults=faults,
        remediation=remediation,
        control=ControlConfig(autotune=False, backpressure=False,
                              admission=True,
                              refill_per_batch=0.5 * batch,
                              burst_tuples=2 * batch))
    p.run()
    return sorted(got), p


def test_supervised_remediation_fires_and_replays_byte_identical():
    base, p_base = _sup_run()
    st = p_base._remediation.state()
    assert st["applied"] >= 1                      # shed_harder fired
    chaos, p_chaos = _sup_run(
        faults=FaultInjector(FaultPlan(
            [FaultSpec("chain.step", p=0.15)], seed=7)))
    assert chaos == base                           # byte-identical replay
    assert p_chaos._remediation.state() == st      # identical decisions


def test_supervised_remediation_journals_applies(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    set_journal(EventJournal(path))
    _sup_run()
    journal_mod.get_active().close()
    set_journal(None)
    applies = [e for e in journal_mod.read_journal(path)
               if e["event"] == "remediation_apply"]
    assert applies and applies[0]["actuator"] == "admission_rate"
    assert applies[0]["action"] == "shed_harder"
    assert "pos" in applies[0]                     # barrier coordinate
    assert "rate" in applies[0] and "prev_rate" in applies[0]
    assert control_state.counters()["remediation_actions"] >= 1


def test_supervised_remediation_off_is_inert():
    _, p = _sup_run(remediation=None)
    assert p._remediation is None
    # the admission snapshot never grows a remediation key when off — the
    # checkpoint stays byte-for-byte the pre-PR shape
    assert set(p._admission.state()) == {"bucket", "admitted", "shed"}
    assert set(p._admission.state()["bucket"]) == {"tokens"}


def test_supervised_construction_rejects_unusable_config():
    with pytest.raises(ValueError):                # nothing owned
        SupervisedPipeline(_src(100, 4), [_op(4)], wf.Sink(lambda v: None),
                           batch_size=20, remediation=True)
    with pytest.raises(ValueError, match="WF118"):  # not barrier-actionable
        SupervisedPipeline(
            _src(100, 4), [_op(4)], wf.Sink(lambda v: None), batch_size=20,
            remediation=[{"name": "x", "slo": "lat",
                          "actuator": "widen_delay"}],
            control=ControlConfig(autotune=False, admission=True,
                                  refill_per_batch=16.0))


def test_remediation_vs_auto_reshard_arbitration(tmp_path):
    """Both engines want the same barrier: the armed auto-reshard governor
    owns it and remediation defers with a journaled 'arbitration' skip —
    outputs stay byte-identical to the remediation-free run, and the
    decision sequence is identical across runs."""
    # reshard-only policy over a persistently skewed key space: num_keys=3
    # across 2 shards puts two keys on one shard (hot fraction ~2/3)
    pol = [{"name": "split", "slo": "shards", "actuator": "reshard",
            "target": 0.55, "window": 1, "max_applies": 2}]

    def run(name, remediation):
        path = str(tmp_path / f"{name}.jsonl")
        set_journal(EventJournal(path))
        got = []
        SupervisedPipeline(
            _src(300, 3), [_op(3)], wf.Sink(_collect(got)),
            batch_size=20, checkpoint_every=1, max_restarts=4,
            backoff_base=0.001, backoff_cap=0.01,
            shards=2, reshard="auto", remediation=remediation).run()
        journal_mod.get_active().close()
        set_journal(None)
        evs = journal_mod.read_journal(path)
        return sorted(got), [
            {k: e.get(k) for k in ("event", "action", "reason", "pos")}
            for e in evs if e["event"].startswith("remediation_")]

    out_rem, evs1 = run("arb1", pol)
    out_rem2, evs2 = run("arb2", pol)
    out_off, _ = run("arb3", None)
    assert out_rem == out_off                # arbitration never diverges
    assert (out_rem2, evs2) == (out_rem, evs1)    # deterministic decisions
    skips = [e for e in evs1 if e["event"] == "remediation_skip"]
    assert skips and all(e["reason"] == "arbitration" for e in skips)
    assert not [e for e in evs1 if e["event"] == "remediation_apply"]


# ------------------------------------------------------------- validator


def test_wf118_live_ownership_and_clean():
    from windflow_tpu.analysis.validate import validate
    mon = MonitoringConfig(slo=True, remediation=True)
    p = wf.Pipeline(_src(100, 4), [_op(4)], batch_size=50, monitoring=mon)
    codes = [d.code for d in validate(p).diagnostics]
    # the default policy's two actions are both unowned without control=
    assert codes.count("WF118") == 2
    p2 = wf.Pipeline(_src(100, 4), [_op(4)], batch_size=50, monitoring=mon,
                     control=ControlConfig(admission=True, rate_tps=1e9))
    assert "WF118" not in [d.code for d in validate(p2).diagnostics]


def test_wf118_remediation_without_slo():
    from windflow_tpu.analysis.validate import validate
    with pytest.raises(ValueError, match="WF118"):
        MonitoringConfig.resolve(MonitoringConfig(remediation=True))
    p = wf.Pipeline(_src(100, 4), [_op(4)], batch_size=50)
    p._monitoring_arg = MonitoringConfig(remediation=True)
    assert "WF118" in [d.code for d in validate(p).diagnostics]


def test_wf118_sub_tick_cooldown():
    from windflow_tpu.analysis.validate import validate
    p = wf.Pipeline(_src(100, 4), [_op(4)], batch_size=50)
    p._monitoring_arg = MonitoringConfig(slo=True, remediation=True,
                                         remediation_cooldown_s=0.1,
                                         interval_s=1.0)
    assert "WF118" in [d.code for d in validate(p).diagnostics]


def test_wf118_supervised_surface_clean():
    from windflow_tpu.analysis.validate import validate
    p = SupervisedPipeline(
        _src(100, 4), [_op(4)], wf.Sink(lambda v: None), batch_size=20,
        remediation=True,
        control=ControlConfig(autotune=False, admission=True,
                              refill_per_batch=16.0))
    assert "WF118" not in [d.code for d in validate(p).diagnostics]


def test_wf118_registered_in_lint_rules():
    from windflow_tpu.analysis.lint import RULES
    assert "WF118" in RULES


# ----------------------------------------------------------- CLI surfaces


def _synthetic_rem_dir(tmp_path):
    """The ci.sh recovered-series shape: 8 burning ticks then 8 healthy
    ones, the engine section on the final snapshot, one apply + one skip
    in the journal."""
    d = tmp_path / "mon"
    d.mkdir()

    def snap(p99_ms):
        return {"graph": "t", "operators": [],
                "e2e_latency_us": {"p99": p99_ms * 1e3,
                                   "p99_tick": p99_ms * 1e3,
                                   "samples": 8, "samples_tick": 8}}

    snaps = [snap(50.0) for _ in range(8)] + [snap(0.5) for _ in range(8)]
    snaps[-1]["remediation"] = {
        "enabled": True, "applied": 1, "skipped": 2,
        "bound": ["admission_rate"], "actions": ["shed_harder"],
        "ledger": [{"action": "shed_harder", "actuator": "admission_rate",
                    "slo": "lat", "burn": 2.5, "applied": True,
                    "rate": 100.0, "prev_rate": 200.0}]}
    snaps[-1]["control"] = {"counters": {"remediation_actions": 1,
                                         "remediation_skips": 2},
                            "gauges": {"bucket_rate": 100.0}}
    with open(d / "snapshots.jsonl", "w") as f:
        for s in snaps:
            f.write(json.dumps(s) + "\n")
    with open(d / "events.jsonl", "w") as f:
        f.write(json.dumps({"t": 1.0, "wall": 1.0,
                            "event": "remediation_apply",
                            "action": "shed_harder",
                            "actuator": "admission_rate", "slo": "lat",
                            "burn": 2.5, "applied": True,
                            "rate": 100.0, "prev_rate": 200.0}) + "\n")
        f.write(json.dumps({"t": 2.0, "wall": 2.0,
                            "event": "remediation_skip",
                            "action": "shed_harder",
                            "actuator": "admission_rate", "slo": "lat",
                            "burn": 2.4, "applied": False,
                            "reason": "damped"}) + "\n")
    spec = tmp_path / "spec.json"
    spec.write_text(json.dumps(
        [{"name": "lat", "signal": "e2e_p99_ms", "target": 10.0,
          "objective": 0.5, "fast_window": 2, "slow_window": 4}]))
    return str(d), str(spec)


def _poisoned_env(tmp_path):
    d = tmp_path / "nojax"
    d.mkdir(exist_ok=True)
    (d / "jax.py").write_text(
        "raise ImportError('stdlib CLIs must not import jax')\n")
    env = {k: v for k, v in os.environ.items() if not k.startswith("WF_")}
    env["PYTHONPATH"] = str(d)
    return env


def test_wf_slo_remediation_section_and_exit_contract(tmp_path):
    mon, spec = _synthetic_rem_dir(tmp_path)
    env = _poisoned_env(tmp_path)
    cli = os.path.join(REPO, "scripts", "wf_slo.py")
    r = subprocess.run(
        [sys.executable, cli, "--monitoring-dir", mon, "--specs", spec,
         "--report", "remediation"],
        capture_output=True, text=True, env=env)
    # the recovered tail ends OK: the remediation section must never
    # perturb the 0/1/2 exit contract
    assert r.returncode == 0, r.stdout + r.stderr
    assert "APPLY" in r.stdout and "shed_harder" in r.stdout
    assert "reason=damped" in r.stdout
    assert "applied=1" in r.stdout
    r2 = subprocess.run(
        [sys.executable, cli, "--monitoring-dir", mon, "--specs", spec,
         "--json"],
        capture_output=True, text=True, env=env)
    payload = json.loads(r2.stdout)["remediation"]
    assert payload["recorded"]["applied"] == 1
    assert [e["event"] for e in payload["events"]] == [
        "remediation_apply", "remediation_skip"]


def test_wf_top_remediation_panel(tmp_path):
    mon, _spec = _synthetic_rem_dir(tmp_path)
    env = _poisoned_env(tmp_path)
    cli = os.path.join(REPO, "scripts", "wf_top.py")
    r = subprocess.run(
        [sys.executable, cli, "--monitoring-dir", mon, "--once"],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "== remediation ==" in r.stdout
    assert "APPLY shed_harder" in r.stdout
    assert "admission tps=100" in r.stdout         # setpoint gauge line


# --------------------------------------------- closed-loop chaos acceptance


def test_chaos_sweep_remediate_closed_loop():
    """The headline acceptance, tier-1 sized: supervised byte-identity with
    remediation active + the live threaded OK -> PAGE -> actuate ->
    recover-to-OK loop with the incident bundle recording the actions."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaos_sweep.py"),
         "--seeds", "1", "--total", "200", "--batch", "20", "--remediate"],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "[closed-loop] threaded:" in r.stdout
    assert "remediation action(s), OK" in r.stdout
