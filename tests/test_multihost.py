"""Multi-host helpers (parallel/multihost.py) — single-process degradation on the
8-device CPU mesh: the same program text must run with the DCN axis collapsed to 1."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from windflow_tpu.parallel import multihost
from windflow_tpu.parallel.collective import keyed_all_to_all


def test_initialize_is_noop_single_process():
    assert multihost.initialize() is False
    assert jax.process_count() == 1


def test_dcn_ici_mesh_single_process_shapes():
    mesh = multihost.make_dcn_ici_mesh(dcn_axis="dp", ici_axes=("key",))
    assert mesh.axis_names == ("dp", "key")
    assert mesh.shape["dp"] == 1 and mesh.shape["key"] == 8

    mesh2 = multihost.make_dcn_ici_mesh(dcn_axis="dp", ici_axes=("key", "win"))
    assert mesh2.axis_names == ("dp", "key", "win")
    assert mesh2.shape["dp"] == 1
    assert mesh2.shape["key"] * mesh2.shape["win"] == 8


def test_collective_over_ici_axis_of_hybrid_mesh():
    # keyed all_to_all over the ICI axis of the 2-level mesh (dp collapsed to 1)
    mesh = multihost.make_dcn_ici_mesh(dcn_axis="dp", ici_axes=("key",))
    C = 64 * 8
    keys = jnp.arange(C, dtype=jnp.int32) % 23
    valid = jnp.ones(C, bool)
    pay = {"v": jnp.arange(C, dtype=jnp.float32)}
    sh = NamedSharding(mesh, P("key"))
    args = jax.tree.map(lambda a: jax.device_put(a, sh), (keys, valid, pay))
    rk, rv, rp, _ = jax.jit(keyed_all_to_all(mesh, axis="key"))(*args)
    rk, rv = np.asarray(rk), np.asarray(rv).ravel()
    per_dev = rk.shape[0] // 8
    for d in range(8):
        live = rk[d * per_dev:(d + 1) * per_dev][rv[d * per_dev:(d + 1) * per_dev]]
        assert np.all(live % 8 == d)


def test_process_local_batch_range_single_process():
    lo, hi = multihost.process_local_batch_range(1000, 128)
    assert (lo, hi) == (0, 1000)
