"""Remaining reference DAG shapes not covered by test_pipegraph.py:

- ``test_split_5.cpp``: a split whose branch contains a NESTED windowed pattern
  (Key_Farm over Pane_Farm) ending in its own sink, while the sibling branch is a
  plain map -> sink.
- ``test_merge_4.cpp``: merging a BARE source pipe (no operators) with processed
  pipes, with a filter after the merge.
- ``test_split_3.cpp``: a split inside a split branch (nested), with a FlatMap on
  one leaf (1->N fanout through the topology).

Oracle as in the reference: sink totals must equal the host-computed expectation and
be invariant under batch size (the parallelism-invariance property, SURVEY §4)."""

import numpy as np
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.operators.win_patterns import Key_Farm, Pane_Farm
from windflow_tpu.runtime.pipegraph import PipeGraph

TOTAL, K = 360, 3


def _split5(batch_size):
    """split -> [map -> sink | KF(PF) windowed -> sink] (test_split_5.cpp shape)."""
    g = PipeGraph("split5", batch_size=batch_size)
    src = wf.Source(lambda i: {"v": (i % 11).astype(jnp.float32)},
                    total=TOTAL, num_keys=K)
    mp = g.add_source(src)
    mp.split(lambda t: (t.v % 2).astype(jnp.int32), 2)
    mp.select(0).chain(wf.Map(lambda t: {"v": t.v * 2.0})).add(
        wf.ReduceSink(lambda t: t.v, name="branch_map"))
    nested = Key_Farm(
        Pane_Farm(lambda pid, it: it.sum("v"), lambda wid, it: it.sum(),
                  WindowSpec(12, 4, win_type_t.CB), num_keys=K), parallelism=2)
    win_out = []

    def cb(view):
        if view is None:
            return
        win_out.extend((int(k), int(w), float(r)) for k, w, r in
                       zip(view["key"].tolist(), view["id"].tolist(),
                           np.asarray(view["payload"]).tolist()))

    mp.select(1).add(nested).add_sink(wf.Sink(cb, name="branch_win"))
    res = g.run()
    return float(res["branch_map"]), sorted(win_out)


@pytest.mark.parametrize("batch_size", [48, 120])
def test_split_branch_with_nested_windowed_pattern(batch_size):
    map_total, wins = _split5(batch_size)
    vals = [i % 11 for i in range(TOTAL)]
    assert map_total == sum(v * 2.0 for v in vals if v % 2 == 0)
    assert wins, "windowed branch emitted nothing"
    # invariance: both outputs identical across batch sizes
    map2, wins2 = _split5(72)
    assert map2 == map_total and wins2 == wins
    # dense oracle for the windowed branch: odd-valued tuples, per key, CB(12,4)
    per_key = {}
    for i in range(TOTAL):
        v = i % 11
        if v % 2 == 1:
            per_key.setdefault(i % K, []).append(float(v))
    want = []
    for k, seq in per_key.items():
        w = 0
        while w * 4 + 12 <= len(seq):
            want.append((k, w, sum(seq[w * 4:w * 4 + 12])))
            w += 1
    # flushed partial windows also emit; the complete ones must match exactly
    got = {(k, w): r for k, w, r in wins}
    for k, w, r in want:
        assert abs(got[(k, w)] - r) < 1e-4, ((k, w), got.get((k, w)), r)


@pytest.mark.parametrize("batch_size", [40, 100])
def test_merge_bare_source_with_processed_pipes(batch_size):
    """test_merge_4.cpp: S | (S -> M) | (S -> M) merged -> F -> M -> sink."""
    g = PipeGraph("merge4", batch_size=batch_size)
    s1 = wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=100, name="s1")
    s2 = wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=80, name="s2")
    s3 = wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=60, name="s3")
    p1 = g.add_source(s1)                                   # bare: no operators
    p2 = g.add_source(s2).chain(wf.Map(lambda t: {"v": t.v + 1}))
    p3 = g.add_source(s3).chain(wf.Map(lambda t: {"v": t.v * 2}))
    m = p1.merge(p2, p3)
    m.chain(wf.Filter(lambda t: t.v % 3 == 0)).chain(
        wf.Map(lambda t: {"v": t.v + 10})).add(
        wf.ReduceSink(lambda t: t.v, name="out"))
    res = g.run()
    stream = ([i for i in range(100)] + [i + 1 for i in range(80)]
              + [i * 2 for i in range(60)])
    assert int(res["out"]) == sum(v + 10 for v in stream if v % 3 == 0)


@pytest.mark.parametrize("batch_size", [36, 90])
def test_nested_split_with_flatmap_leaf(batch_size):
    """test_split_3.cpp: split; one branch splits again; a leaf has FlatMap 1->2."""
    g = PipeGraph("split3", batch_size=batch_size)
    src = wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=120)
    mp = g.add_source(src)
    mp.split(lambda t: (t.v % 2).astype(jnp.int32), 2)
    inner = mp.select(0).chain(wf.Map(lambda t: {"v": t.v + 1}))
    inner.split(lambda t: (t.v % 3 == 0).astype(jnp.int32), 2)
    inner.select(0).add(wf.ReduceSink(lambda t: t.v, name="l0"))
    fm = wf.FlatMap(lambda t, ship: (ship.push({"v": t.v}),
                                     ship.push({"v": -t.v}))[0],
                    max_fanout=2)
    inner.select(1).chain(fm).add(wf.ReduceSink(lambda t: jnp.ones((), jnp.int32),
                                                name="l1_count"))
    mp.select(1).add(wf.ReduceSink(lambda t: t.v, name="r"))
    res = g.run()
    evens_plus1 = [i + 1 for i in range(120) if i % 2 == 0]
    assert int(res["l0"]) == sum(v for v in evens_plus1 if v % 3 != 0)
    assert int(res["l1_count"]) == 2 * len([v for v in evens_plus1 if v % 3 == 0])
    assert int(res["r"]) == sum(i for i in range(120) if i % 2 == 1)
