"""Shard-local supervision (``runtime/supervisor.py`` ``ShardSupervisor`` /
``ShardedSupervisor``): shard-count invariance (1 vs 4 vs a mid-run 4 -> 8
live reshard) across both supervised drivers and the Nexmark query set,
kill-one-of-4 chaos with the no-global-restart journal pin, sharded-and-
parallel checkpoints (per-shard lineage + per-shard fallback), deterministic
re-sharding under torn-handoff / mid-handoff-checkpoint chaos, the governor's
reshard planner, per-shard health reporting + host-tagged fleet folding, and
the WF115 validator pins."""

import glob
import os

import numpy as np
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import Mode, win_type_t
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.parallel.sharding import (ReshardPlan, ShardAssignment,
                                            affected_shards, make_splitter,
                                            resolve_shards)
from windflow_tpu.runtime import checkpoint as ckpt
from windflow_tpu.runtime.faults import FaultInjector, FaultPlan, FaultSpec
from windflow_tpu.runtime.supervisor import (ShardedSupervisor,
                                             SupervisedPipeline,
                                             _fresh_states)

TOTAL, K = 400, 4


def build(sink_cb, **kw):
    src = wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
                    total=TOTAL, num_keys=K)
    op = wf.Win_Seq(lambda wid, it: it.sum("v"),
                    WindowSpec(10, 10, win_type_t.TB), num_keys=K)
    return SupervisedPipeline(src, [op], wf.Sink(sink_cb), batch_size=50,
                              backoff_base=0.0, **kw)


def collect(results):
    def cb(view):
        if view is None:
            return
        results.extend(zip(view["key"].tolist(), view["id"].tolist(),
                           np.asarray(view["payload"]).tolist()))
    return cb


def run_build(**kw):
    got = []
    p = build(collect(got), **kw)
    p.run()
    return sorted(got), p


# ------------------------------------------------------------- assignment


def test_assignment_owner_and_moves():
    a = ShardAssignment(4)
    assert [a.owner(k) for k in range(8)] == [0, 1, 2, 3, 0, 1, 2, 3]
    m = ShardAssignment(4, ((5, 0), (2, 3)))
    assert m.owner(5) == 0 and m.owner(2) == 3 and m.owner(6) == 2
    rt = ShardAssignment.from_meta(m.to_meta())
    assert rt == m
    with pytest.raises(ValueError, match="nonexistent shard"):
        ShardAssignment(4, ((1, 7),))
    # duplicate key slots would make owner() and the traced owner_of()
    # disagree — rejected at construction
    with pytest.raises(ValueError, match="more than one move"):
        ShardAssignment(4, ((3, 1), (3, 2)))


def test_doubling_splits_each_shard_in_two():
    # key % 2N is congruent to key % N (mod N): a 4 -> 8 reshard only ever
    # SPLITS a shard — no key moves between surviving pairs
    a4, a8 = ShardAssignment(4), ShardAssignment(8)
    for k in range(64):
        assert a8.owner(k) % 4 == a4.owner(k)


def test_split_covers_input_exactly():
    a = ShardAssignment(3)
    b = wf.Batch.of({"v": jnp.arange(32, dtype=jnp.float32)},
                    key=jnp.arange(32, dtype=jnp.int32) * 7 % 11,
                    valid=jnp.arange(32) % 5 != 0)
    subs = a.split(b)
    masks = np.stack([np.asarray(s.valid) for s in subs])
    # disjoint and complete: each live input lane lives in EXACTLY one shard
    assert (masks.sum(axis=0) == np.asarray(b.valid).astype(int)).all()
    for s in subs:
        np.testing.assert_array_equal(np.asarray(s.key), np.asarray(b.key))


def test_affected_shards():
    a4 = ShardAssignment(4)
    assert affected_shards(a4, ShardAssignment(8)) == set(range(8))
    moved = ShardAssignment(4, ((5, 0),))
    assert affected_shards(a4, moved) == {0, 1}      # donor 1, recipient 0
    assert affected_shards(moved, moved) == set()


def test_resolve_shards_and_plan(monkeypatch):
    assert resolve_shards(None) == 1
    monkeypatch.setenv("WF_SHARDS", "4")
    assert resolve_shards(None) == 4
    # '0' means OFF (the documented ENV_FLAGS contract), never an error
    monkeypatch.setenv("WF_SHARDS", "0")
    assert resolve_shards(None) == 1
    assert resolve_shards(0) == 1
    with pytest.raises(ValueError):
        resolve_shards(-2)
    monkeypatch.setenv("WF_SHARDS", "4")
    monkeypatch.setenv("WF_RESHARD", "8")
    plan = ReshardPlan.resolve(None)
    assert plan.new_shards == 8
    assert ReshardPlan.resolve('{"at_pos": 3, "moves": [[5, 0]]}').moves \
        == ((5, 0),)
    assert ReshardPlan.resolve("auto") == "auto"
    assert ReshardPlan.resolve(False) is None


# ------------------------------------------------- off-path / invariance


def test_off_path_is_single_domain():
    got, p = run_build()
    assert p._shards == 1 and p._sharded is None
    assert p.shard_report() == {}


def test_shard_count_invariance_1_vs_4_vs_live_reshard():
    oracle, _ = run_build()
    got4, p4 = run_build(shards=4, checkpoint_every=3)
    assert got4 == oracle
    rep = p4.shard_report()
    assert sorted(rep) == [0, 1, 2, 3]
    assert sum(r["occupancy_tuples"] for r in rep.values()) == TOTAL
    # mid-run live 4 -> 8 reshard: byte-identical result multiset, zero
    # dropped/duplicated keys, every unit re-admitted once
    got8, p8 = run_build(shards=4, checkpoint_every=3,
                         reshard={"new_shards": 8, "at_pos": 4})
    assert got8 == oracle
    rep8 = p8.shard_report()
    assert sorted(rep8) == list(range(8))
    assert all(r["reshard_moves"] == 1 for r in rep8.values())
    assert p8._sharded.reshard_count == 1


def test_targeted_move_rebuilds_only_donor_and_recipient():
    oracle, _ = run_build()
    got, p = run_build(shards=4, checkpoint_every=3,
                       reshard={"moves": [[3, 0]], "at_pos": 4})
    assert got == oracle
    rep = p.shard_report()
    # key 3 moved from shard 3 to shard 0: only those two units re-admitted
    assert rep[0]["reshard_moves"] == 1 and rep[3]["reshard_moves"] == 1
    assert rep[1]["reshard_moves"] == 0 and rep[2]["reshard_moves"] == 0


# ------------------------------------------------------- chaos / recovery


def test_kill_one_of_four_journal_timeline(tmp_path):
    """THE acceptance drill: kill one shard's step; surviving shards emit
    continuously (journal shows shard_restore for the killed shard and NO
    global restore span), the failed shard replays only its own extent, and
    the output is byte-identical to the fault-free run."""
    from windflow_tpu.observability import (EventJournal, read_journal,
                                            set_journal)
    oracle, _ = run_build()
    path = str(tmp_path / "events.jsonl")
    j = EventJournal(path)
    set_journal(j)
    try:
        got, p = run_build(
            shards=4, checkpoint_every=3, max_restarts=4,
            faults=FaultInjector(FaultPlan(
                [FaultSpec("shard.kill", where={"shard": 2}, max_fires=2)],
                seed=1)))
    finally:
        set_journal(None)
        j.close()
    assert got == oracle
    rep = p.shard_report()
    assert rep[2]["restarts"] == 2
    assert all(rep[k]["restarts"] == 0 for k in (0, 1, 3))
    assert rep[2]["last_recovery_s"] > 0.0
    events = read_journal(path)
    restores = [e for e in events if e.get("event") == "shard_restore"]
    assert len(restores) == 2
    assert all(e["shard"] == 2 for e in restores)
    assert all("replay_from" in e for e in restores)
    # NO whole-domain restore: the "restore" span never opened (global
    # restarts would journal it), and commits continued across the kills
    assert not [e for e in events if e.get("event") == "restore"]
    ckpts = [e for e in events if e.get("event") == "checkpoint"
             and e.get("phase") == "begin"]
    assert ckpts and all(c.get("shards") == 4 for c in ckpts)


def test_plan_past_eos_is_journaled_not_silent(tmp_path):
    """A reshard plan whose barrier never arrives (at_pos past the stream)
    must leave an aborted journal record — a silently dropped re-layout
    would look like a healthy run."""
    from windflow_tpu.observability import (EventJournal, read_journal,
                                            set_journal)
    path = str(tmp_path / "e.jsonl")
    j = EventJournal(path)
    set_journal(j)
    try:
        got, p = run_build(shards=2, checkpoint_every=3,
                           reshard={"new_shards": 4, "at_pos": 10_000})
    finally:
        set_journal(None)
        j.close()
    assert len(p.shard_report()) == 2        # never applied
    ev = [e for e in read_journal(path) if e.get("event") == "reshard"]
    assert ev and ev[-1].get("aborted") and "stream ended" in ev[-1]["error"]


def test_shard_restart_budget_exhausts_locally():
    with pytest.raises(wf.RestartExhausted, match="shard 1"):
        run_build(shards=2, checkpoint_every=4, max_restarts=1,
                  faults=FaultInjector(FaultPlan(
                      [FaultSpec("shard.kill", where={"shard": 1})],
                      seed=0)))


def test_shard_poison_quarantine_dead_letters_exact_sub_batch():
    from windflow_tpu.runtime.faults import DeadLetterQueue
    oracle, _ = run_build()
    dlq = DeadLetterQueue()
    got, p = run_build(
        shards=4, checkpoint_every=3, max_restarts=6, dead_letter=dlq,
        poison_threshold=2,
        faults=FaultInjector(FaultPlan(
            [FaultSpec("shard.kill", where={"shard": 1, "pos": 3})],
            seed=0)))
    # shard 1's sub-batch at pos 3 was quarantined; every other (shard,
    # pos) cell — including the OTHER shards' slices of pos 3 — delivered
    assert len(dlq) == 1
    entry = dlq.entries[0]
    assert entry["pos"] == 3 and entry["driver"].endswith("shard1")
    assert p.shard_report()[1]["dead_letters"] == 1
    lost = set(oracle) - set(got)
    assert lost and not set(got) - set(oracle)
    # lost results all belong to shard 1's key range (key % 4 == 1)
    assert {k % 4 for k, _i, _v in lost} == {1}


def test_global_fault_falls_back_to_whole_domain_restore():
    oracle, _ = run_build()
    got, p = run_build(shards=4, checkpoint_every=3, max_restarts=3,
                       faults=FaultInjector(FaultPlan(
                           [FaultSpec("source.next", at=[5])], seed=0)))
    assert got == oracle
    assert p.restarts >= 1


def test_torn_handoff_discarded_and_rederived(tmp_path):
    oracle, _ = run_build()
    path = str(tmp_path / "ck.npz")
    got, p = run_build(
        shards=4, checkpoint_every=2, spill_path=path, max_restarts=4,
        reshard={"new_shards": 8, "at_pos": 3},
        faults=FaultInjector(FaultPlan(
            [FaultSpec("reshard.handoff", kind="torn", max_fires=1)],
            seed=5)))
    assert got == oracle
    assert len(p.shard_report()) == 8
    assert not glob.glob(str(tmp_path / "ck.handoff*")), "seal debris left"


def test_checkpoint_lands_mid_handoff_rederives_move(tmp_path):
    """A checkpoint.save fault during the post-reshard barrier commit: the
    restore discards the in-flight handoff manifests, replay re-derives the
    move at the same barrier, results stay byte-identical."""
    oracle, _ = run_build()
    path = str(tmp_path / "ck.npz")
    shard5 = ckpt.shard_stem(path, 5) + ".npz"
    got, p = run_build(
        shards=4, checkpoint_every=2, spill_path=path, max_restarts=4,
        reshard={"new_shards": 8, "at_pos": 3},
        faults=FaultInjector(FaultPlan(
            [FaultSpec("checkpoint.save", where={"path": shard5},
                       max_fires=1)], seed=6)))
    assert got == oracle
    assert len(p.shard_report()) == 8
    assert not glob.glob(str(tmp_path / "ck.handoff*"))


# ------------------------------------------------- sharded checkpoints


def test_sharded_checkpoint_files_and_roundtrip(tmp_path):
    path = str(tmp_path / "ck.npz")
    got, p = run_build(shards=4, checkpoint_every=2, spill_path=path,
                       checkpoint_keep=3)
    # one lineage per shard + the shards manifest
    for k in range(4):
        assert os.path.exists(ckpt.manifest_path(ckpt.shard_stem(path, k)))
    states, layout, meta = ckpt.load_sharded(_fresh_states(p.chain), path)
    assert sorted(states) == [0, 1, 2, 3]
    assert layout == {"num_shards": 4, "moves": []}
    assert meta["batches_done"] == TOTAL // 50
    # the restored per-shard states match the final supervised snapshots
    import jax
    for k, s in enumerate(p._sharded.shards):
        got_leaves = [np.asarray(x) for st in states[k]
                      for x in jax.tree.leaves(st)]
        want_leaves = [np.asarray(x) for st in s.snap
                       for x in jax.tree.leaves(st)]
        assert len(got_leaves) == len(want_leaves)
        for ga, wa in zip(got_leaves, want_leaves):
            np.testing.assert_array_equal(ga, wa)


def test_per_shard_lineage_fallback(tmp_path):
    """Corrupting ONE shard's newest lineage file degrades THAT shard to
    its previous commit (checkpoint_fallback) without touching peers."""
    path = str(tmp_path / "ck.npz")
    _got, p = run_build(shards=4, checkpoint_every=2, spill_path=path,
                        checkpoint_keep=3)
    man = ckpt._read_manifest(ckpt.manifest_path(ckpt.shard_stem(path, 2)))
    newest = os.path.join(str(tmp_path), man["entries"][-1]["file"])
    with open(newest, "wb") as f:
        f.write(b"torn!")
    states, _layout, _meta = ckpt.load_sharded(_fresh_states(p.chain), path)
    assert sorted(states) == [0, 1, 2, 3]    # shard 2 fell back, peers fine


def test_save_sharded_is_committed_by_manifest(tmp_path):
    """Shard files not named by a fully-written shards manifest are
    invisible to load_sharded (the crash-mid-fan-out rule)."""
    path = str(tmp_path / "ck.npz")
    with pytest.raises(ckpt.CheckpointCorrupt, match="manifest"):
        ckpt.load_sharded([], path)


# ------------------------------------------------------- nexmark + graph


from test_nexmark import ROW_FNS, run_query  # noqa: E402


def _run_nexmark_sharded(name, shards, reshard=None, total=400):
    from windflow_tpu.nexmark import make_query
    src, ops = make_query(name, total)
    rows = []
    rowfn = ROW_FNS[name]

    def cb(view):
        if view is None:
            return
        rows.extend(rowfn(view))
    # q5 re-keys by bidder (KeyBy): ownership must follow the session key
    key_fn = (lambda t: t.bidder) if name == "q5_session" else None
    wf.SupervisedPipeline(src, ops, wf.Sink(cb), batch_size=50,
                          checkpoint_every=3, backoff_base=0.0,
                          shards=shards, reshard=reshard,
                          shard_key=key_fn).run()
    return sorted(rows)


@pytest.mark.parametrize("name", sorted(ROW_FNS))
def test_nexmark_shard_count_invariance(name):
    base = sorted(run_query(name, 50, "supervised"))
    assert _run_nexmark_sharded(name, 4) == base


@pytest.mark.parametrize("name", ["q3_enrich_join", "q5_session"])
def test_nexmark_live_reshard_4_to_8(name):
    base = sorted(run_query(name, 50, "supervised"))
    got = _run_nexmark_sharded(name, 4,
                               reshard={"new_shards": 8, "at_pos": 4})
    assert got == base


def test_topn_shard_invariance():
    from windflow_tpu.nexmark import make_query

    def run(shards):
        src, ops = make_query("q6_topn", TOTAL)
        final = {}

        def cb(view):
            if view is None:
                return
            for k, r, i, s in zip(
                    view["key"].tolist(),
                    np.asarray(view["payload"]["rank"]).tolist(),
                    view["id"].tolist(),
                    np.asarray(view["payload"]["score"]).tolist()):
                final[(k, r)] = (i, s)
        wf.SupervisedPipeline(src, ops, wf.Sink(cb), batch_size=50,
                              checkpoint_every=3, backoff_base=0.0,
                              shards=shards).run()
        return sorted((k, r, i, s) for (k, r), (i, s) in final.items())
    assert run(4) == run(1)


def _graph_run(shards=1, faults=None, reshard=None, mode=Mode.DEFAULT):
    got = []
    g = wf.PipeGraph("shtest", batch_size=20, mode=mode)
    a = g.add_source(wf.Source(lambda i: {"v": (i % 9).astype(jnp.float32)},
                               total=200, num_keys=3, name="a"))
    b = g.add_source(wf.Source(lambda i: {"v": (i % 7).astype(jnp.float32)},
                               total=100, num_keys=3, name="b"))
    (a.merge(b)
     .add(wf.Win_Seq(lambda wid, it: it.sum("v"),
                     WindowSpec(12, 12, win_type_t.CB), num_keys=3))
     .add_sink(wf.Sink(collect(got))))
    g.run_supervised(checkpoint_every=3, max_restarts=6, backoff_base=0.0,
                     backoff_cap=0.01, faults=faults, shards=shards,
                     reshard=reshard)
    return sorted(got), g


def test_graph_shard_invariance_and_kill():
    base, _ = _graph_run()
    got, g = _graph_run(shards=3)
    assert got == base
    assert sorted(g._shard_report) == [0, 1, 2]
    killed, g2 = _graph_run(
        shards=3,
        faults=FaultInjector(FaultPlan(
            [FaultSpec("shard.kill", where={"shard": 1}, max_fires=2)],
            seed=3)))
    assert killed == base
    assert g2._shard_report[1]["restarts"] == 2
    assert g2._shard_report[0]["restarts"] == 0


def test_graph_deterministic_merge_sharded():
    base, _ = _graph_run(mode=Mode.DETERMINISTIC)
    got, _g = _graph_run(shards=2, mode=Mode.DETERMINISTIC)
    assert got == base


def test_graph_live_reshard():
    base, _ = _graph_run()
    got, g = _graph_run(shards=2, reshard={"new_shards": 4, "at_pos": 3})
    assert got == base
    assert sorted(g._shard_report) == [0, 1, 2, 3]


# ------------------------------------------------- multi-host slice


def test_process_shard_slice_union_is_exact():
    from windflow_tpu.parallel import multihost
    lo, hi = multihost.process_shard_slice(4)
    assert (lo, hi) == (0, 4)                # single-process: all shards
    oracle, _ = run_build()
    a, _pa = run_build(shards=4, shard_range=(0, 2))
    b, _pb = run_build(shards=4, shard_range=(2, 4))
    merged = sorted(a + b)
    assert merged == oracle                  # no key lost, none duplicated
    assert a and b


def test_shard_range_requires_sharding_on():
    """shard_range= with shards resolving to 1 must be LOUD: a host that
    silently supervised the whole stream would duplicate every output
    across the fleet (the graph-driver rejection, mirrored)."""
    with pytest.raises(ValueError, match="shard_range"):
        build(lambda v: None, shard_range=(0, 1))


def test_shard_range_rejects_reshard():
    with pytest.raises(ValueError, match="shard_range"):
        run_build(shards=4, shard_range=(0, 2),
                  reshard={"new_shards": 8, "at_pos": 2})


# ------------------------------------------------- composition guards


def test_shards_reject_dispatch_fusion():
    with pytest.raises(ValueError, match="scan dispatch"):
        run_build(shards=4, dispatch=4)


def test_shard_key_follows_rekeyed_stream():
    """A KeyBy re-key under sharding: ownership must follow the KeyBy's
    key (shard_key=), and the validator errors without it."""
    from windflow_tpu.analysis import validate

    def mk(**kw):
        src = wf.Source(lambda i: {"u": (i * 3 % 7).astype(jnp.int32),
                                   "v": (i % 13).astype(jnp.float32)},
                        total=TOTAL, num_keys=16)
        ops = [wf.KeyBy(lambda t: t.u, 7),
               wf.Win_Seq(lambda wid, it: it.sum("v"),
                          WindowSpec(10, 10, win_type_t.TB), num_keys=7)]
        got = []
        p = SupervisedPipeline(src, ops, wf.Sink(collect(got)),
                               batch_size=50, backoff_base=0.0, **kw)
        return p, got
    p1, got1 = mk()
    p1.run()
    p4, got4 = mk(shards=4, shard_key=lambda t: t.u)
    p4.run()
    assert sorted(got4) == sorted(got1)
    bad, _ = mk(shards=4)                    # no shard_key: WF115 error
    r = validate(bad)
    assert any(d.code == "WF115" and "KeyBy" in d.message for d in r.errors)


# --------------------------------------------- governor / auto-reshard


def test_recommend_reshard_planner():
    from windflow_tpu.control.governor import recommend_reshard
    a = ShardAssignment(4)
    assert recommend_reshard({0: 10, 1: 10, 2: 10, 3: 10}, a) is None
    plan = recommend_reshard({0: 100, 1: 5, 2: 5, 3: 5}, a)
    assert plan is not None and plan.new_shards == 8
    assert recommend_reshard({0: 100, 1: 5}, a, max_shards=4) is None
    assert recommend_reshard({}, a) is None
    assert recommend_reshard({0: 0.0, 1: 0.0}, a) is None
    # scale-free trigger: two active keys spread over 8 shards is NOT skew
    # (a max/mean ratio of 4 would have mis-fired here)
    assert recommend_reshard({i: (50 if i in (1, 5) else 0)
                              for i in range(8)}, ShardAssignment(8)) is None


def test_auto_reshard_doubles_under_skew():
    """reshard='auto': the governor's planner sees the committed per-shard
    load (shard 0 carries ~85% of traffic under a hot key) and doubles the
    layout at a barrier — results stay exact."""
    def mk(**kw):
        # key 0 carries ~85% of traffic; under shards=4 shard 0's load is
        # > 2x the mean, which trips the planner's doubling rule
        src = wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
                        total=TOTAL, num_keys=4,
                        key_fn=lambda i: ((i % 20 >= 17) *
                                          (1 + i % 3)).astype(jnp.int32))
        op = wf.Win_Seq(lambda wid, it: it.sum("v"),
                        WindowSpec(10, 10, win_type_t.TB), num_keys=4)
        got = []
        p = SupervisedPipeline(src, [op], wf.Sink(collect(got)),
                               batch_size=50, checkpoint_every=2,
                               backoff_base=0.0, **kw)
        p.run()
        return sorted(got), p
    base, _ = mk()
    got, p = mk(shards=4, reshard="auto")
    assert got == base
    assert p._sharded.reshard_count >= 1
    assert len(p.shard_report()) >= 8


def test_auto_reshard_stops_when_doubling_cannot_help():
    """A single hot key slot: ``key % 2N`` cannot split it, so after one
    futile doubling the governor's per-epoch skew ratio does not improve
    and auto-resharding STOPS instead of cascading to max_shards."""
    def mk(**kw):
        src = wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
                        total=2 * TOTAL, num_keys=4,
                        key_fn=lambda i: (i * 0).astype(jnp.int32))
        op = wf.Win_Seq(lambda wid, it: it.sum("v"),
                        WindowSpec(10, 10, win_type_t.TB), num_keys=4)
        got = []
        p = SupervisedPipeline(src, [op], wf.Sink(collect(got)),
                               batch_size=50, checkpoint_every=2,
                               backoff_base=0.0, **kw)
        p.run()
        return sorted(got), p
    base, _ = mk()
    got, p = mk(shards=4, reshard="auto")
    assert got == base
    assert p._sharded.reshard_count == 1     # one doubling, then damped
    assert len(p.shard_report()) == 8
    assert p._sharded._auto_stopped


def test_graph_drain_failure_recovers_without_double_apply():
    """A fault during the EOS drain: the shard restores to its last commit
    and replays its buffer — the replayed state must NOT stack on top of
    the stale pre-drain capture (the double-apply bug: uncommitted batches
    counted twice in a ReduceSink)."""
    from windflow_tpu.operators.sink import ReduceSink

    def run(shards, fail_drain=False):
        g = wf.PipeGraph("drain", batch_size=20)
        mp = g.add_source(wf.Source(
            lambda i: {"v": (i % 13).astype(jnp.float32)},
            total=190, num_keys=4, name="s"))
        mp.add(wf.Map(lambda t: {"v": t.v * 2.0}))
        mp.add(ReduceSink(lambda t: t.v, name="total"))
        if fail_drain:
            orig = g._topo_order
            hits = {"n": 0}

            def flaky():
                hits["n"] += 1
                if hits["n"] == 1:        # first drain call only
                    raise RuntimeError("injected drain fault")
                return orig()
            g._topo_order = flaky
        res = g.run_supervised(checkpoint_every=3, max_restarts=4,
                               backoff_base=0.0, shards=shards)
        return float(np.asarray(res["total"]))
    oracle = run(1)
    assert run(2) == oracle
    assert run(2, fail_drain=True) == oracle


def test_surplus_host_empty_slice_idles():
    """A fleet larger than the shard count: the surplus host's empty slice
    supervises zero shards (idles through the stream) instead of crashing;
    the owning hosts' union is still exact."""
    oracle, _ = run_build()
    a, _pa = run_build(shards=2, shard_range=(0, 1))
    b, _pb = run_build(shards=2, shard_range=(1, 2))
    c, pc = run_build(shards=2, shard_range=(2, 2))     # surplus host
    assert c == [] and pc.shard_report() == {}
    assert sorted(a + b) == oracle


def test_multihost_slice_manifests_do_not_clobber(tmp_path):
    """Two hosts spilling slices of one layout to a shared stem: per-slice
    manifests coexist (no last-writer-wins), load_sharded merges them, and
    a missing slice is a LOUD CheckpointCorrupt, never a silent partial
    restore."""
    path = str(tmp_path / "fleet.npz")
    _a, pa = run_build(shards=4, shard_range=(0, 2), checkpoint_every=2,
                       spill_path=path)
    _b, pb = run_build(shards=4, shard_range=(2, 4), checkpoint_every=2,
                       spill_path=path)
    tmpl = _fresh_states(pa.chain)
    states, layout, _meta = ckpt.load_sharded(tmpl, path)
    assert sorted(states) == [0, 1, 2, 3] and layout["num_shards"] == 4
    # drop host B's slice manifest: the restore must refuse, naming the gap
    os.unlink(str(tmp_path / "fleet.shards.s2-3.json"))
    for f in glob.glob(str(tmp_path / "fleet.shard2*")) \
            + glob.glob(str(tmp_path / "fleet.shard3*")):
        os.unlink(f)
    with pytest.raises(ckpt.CheckpointCorrupt, match=r"\[2, 3\] missing"):
        ckpt.load_sharded(tmpl, path)


def test_stale_slice_manifest_never_overrides_newer_full_save(tmp_path):
    """Deployment-shape switch: per-slice manifests left behind must not
    override a NEWER full save's entries (per shard, the newest generation
    wins the merge)."""
    path = str(tmp_path / "sw.npz")
    # phase 1: two-host slices at batches_done=8
    _a, pa = run_build(shards=4, shard_range=(0, 2), checkpoint_every=4,
                       spill_path=path)
    _b, _pb = run_build(shards=4, shard_range=(2, 4), checkpoint_every=4,
                        spill_path=path)
    # phase 2: single-host full save of a LONGER run (batches_done bumped
    # by hand to model a later generation under the same layout)
    import json as _json
    _c, pc = run_build(shards=4, checkpoint_every=4, spill_path=path)
    mf = str(tmp_path / "sw.shards.json")
    man = _json.loads(open(mf).read())
    man["meta"]["batches_done"] = 16
    for k in range(4):
        smf = ckpt.manifest_path(ckpt.shard_stem(path, k))
        # keep=1: no per-stem lineage; rewrite the shard files' meta via a
        # fresh save_states at the newer generation
        ckpt.save_states(pc._sharded.shards[k].snap, ckpt.shard_stem(path, k),
                         meta={"batches_done": 16, "shard": k,
                               "num_shards": 4})
        assert not ckpt._read_manifest(smf)
    open(mf, "w").write(_json.dumps(man))
    _states, _layout, meta = ckpt.load_sharded(_fresh_states(pa.chain), path)
    # the full (newest) manifest won for every shard despite the stale
    # slice manifests sorting first lexicographically
    assert all(m["batches_done"] == 16 for m in meta["shard_meta"].values())


def test_wf115_env_reshard_parity(monkeypatch):
    """WF_RESHARD alone must get the same WF115 legality checks as an
    explicit reshard= (the drivers resolve the env; so must the gate)."""
    from windflow_tpu.analysis import validate
    monkeypatch.setenv("WF_RESHARD", '{"moves": [[3, 99]]}')
    p = build(lambda v: None, shards=4)
    r = validate(p)
    assert any(d.code == "WF115" and "does not exist" in d.message
               for d in r.errors), r
    monkeypatch.setenv("WF_RESHARD", "not-json{")
    assert any(d.code == "WF115" for d in validate(
        build(lambda v: None, shards=4)).errors)
    monkeypatch.setenv("WF_RESHARD", "8")
    p1 = build(lambda v: None)               # shards off: can-never-apply
    assert any(d.code == "WF115" for d in validate(p1).warnings)


def test_empty_slice_reduce_sink_returns_identity():
    from windflow_tpu.operators.sink import ReduceSink
    src = wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
                    total=100, num_keys=4)
    ops = [ReduceSink(lambda t: t.v, name="total")]
    p = SupervisedPipeline(src, ops, None, batch_size=50, backoff_base=0.0,
                           shards=2, shard_range=(2, 2))
    res = p.run()
    assert float(np.asarray(res["total"])) == 0.0    # identity, never None


def test_wf115_graph_env_shards_and_shard_key_passthrough(monkeypatch):
    """WF_SHARDS alone must give a supervised graph the WF115 coverage
    (the run resolves the env, so must the validator), and validate's
    shard_key= passthrough silences the KeyBy error for a correctly
    configured run."""
    from windflow_tpu.analysis import validate

    def mk_graph():
        g = wf.PipeGraph("env", batch_size=20)
        mp = g.add_source(wf.Source(
            lambda i: {"u": (i * 3 % 7).astype(jnp.int32),
                       "v": (i % 13).astype(jnp.float32)},
            total=100, num_keys=16))
        mp.add(wf.KeyBy(lambda t: t.u, 7))
        mp.add(wf.Win_Seq(lambda wid, it: it.sum("v"),
                          WindowSpec(10, 10, win_type_t.TB), num_keys=7))
        mp.add_sink(wf.Sink(lambda v: None))
        return g
    monkeypatch.setenv("WF_SHARDS", "4")
    r = validate(mk_graph(), supervised=True)
    assert any(d.code == "WF115" and "KeyBy" in d.message for d in r.errors)
    r = validate(mk_graph(), supervised=True, shard_key=lambda t: t.u)
    assert "WF115" not in [d.code for d in r.errors]
    monkeypatch.delenv("WF_SHARDS")
    # env off: no WF115 findings on the same graph
    assert "WF115" not in validate(mk_graph(), supervised=True).codes()


def test_graph_driver_rejects_shard_range():
    g = wf.PipeGraph("r", batch_size=20)
    g.add_source(wf.Source(lambda i: {"v": (i % 9).astype(jnp.float32)},
                           total=40, num_keys=3)).add_sink(
        wf.Sink(lambda v: None))
    with pytest.raises(ValueError, match="shard_range"):
        g.run_supervised(shards=2, shard_range=(0, 1))


def test_auto_reshard_replans_after_real_improvement():
    """The damping guard compares only the FIRST post-reshard epoch: a
    doubling that genuinely splits the hot pair keeps auto mode alive, and
    a NEW hot spot later in the stream triggers a second reshard (the
    stale-ratio bug permanently disabled auto after any first success)."""
    def mk(**kw):
        # phase 1: keys {1, 5} hot (both -> shard 1 of 4; a doubling
        # splits them); phase 2: keys {2, 10} hot (both -> shard 2 of 8;
        # a second doubling splits them)
        src = wf.Source(
            lambda i: {"v": (i % 13).astype(jnp.float32)},
            total=800, num_keys=16,
            key_fn=lambda i: jnp.where(
                i < 400,
                jnp.where(i % 2 == 0, 1, 5),
                jnp.where(i % 2 == 0, 2, 10)).astype(jnp.int32))
        op = wf.Win_Seq(lambda wid, it: it.sum("v"),
                        WindowSpec(10, 10, win_type_t.TB), num_keys=16)
        got = []
        p = SupervisedPipeline(src, [op], wf.Sink(collect(got)),
                               batch_size=50, checkpoint_every=2,
                               backoff_base=0.0, **kw)
        p.run()
        return sorted(got), p
    base, _ = mk()
    got, p = mk(shards=4, reshard="auto")
    assert got == base
    assert p._sharded.reshard_count == 2, p._sharded.reshard_count
    assert not p._sharded._auto_stopped
    assert len(p.shard_report()) == 16


def test_poison_batch_survives_a_reshard():
    """A sub-batch the live run already quarantined must not kill the
    reshard's prefix replay: the rebuild dead-letters it inline and the
    run completes (previously: RestartExhausted at the barrier)."""
    from windflow_tpu.runtime.faults import DeadLetterQueue
    oracle, _ = run_build()
    dlq = DeadLetterQueue()
    got, p = run_build(
        shards=4, checkpoint_every=3, max_restarts=6, dead_letter=dlq,
        poison_threshold=2, reshard={"new_shards": 8, "at_pos": 5},
        # shard 1's slice of pos 3 is deterministically poison: it fails
        # in the live run (quarantined) AND in the rebuild replay
        faults=FaultInjector(FaultPlan(
            [FaultSpec("shard.kill", where={"shard": 1, "pos": 3})],
            seed=0)))
    assert len(p.shard_report()) == 8        # the reshard went through
    lost = set(oracle) - set(got)
    assert lost and not set(got) - set(oracle)
    assert {k % 4 for k, _i, _v in lost} == {1}


def test_sharded_manifest_detects_torn_keep1_fanout(tmp_path):
    """keep=1 + crash between the shard fan-out and the manifest rewrite:
    shard files are one generation AHEAD of the manifest (the committed
    bytes were overwritten in place) — load_sharded must refuse loudly and
    point at checkpoint_keep >= 2, never mix generations silently."""
    path = str(tmp_path / "g1.npz")
    _got, p = run_build(shards=2, checkpoint_every=4, spill_path=path)
    man_file = str(tmp_path / "g1.shards.json")
    stale = open(man_file).read().replace(
        '"batches_done": 8', '"batches_done": 4')
    open(man_file, "w").write(stale)         # manifest one commit behind
    with pytest.raises(ckpt.CheckpointCorrupt, match="AHEAD"):
        ckpt.load_sharded(_fresh_states(p.chain), path)


# ------------------------------------------------- health / reporting


def test_shard_report_gauges_registered():
    from windflow_tpu.observability.names import SHARD_GAUGES
    _got, p = run_build(shards=2, checkpoint_every=3)
    for row in p.shard_report().values():
        assert set(row) == set(SHARD_GAUGES)


def test_metrics_snapshot_shards_section_and_fleet_merge():
    from windflow_tpu.observability.device_health import merge_snapshots
    from windflow_tpu.observability.metrics import MetricsRegistry
    reg = MetricsRegistry("shtest")
    reg.attach_shards(lambda: {0: {"occupancy_tuples": 5, "restarts": 1},
                               1: {"occupancy_tuples": 9, "restarts": 0}})
    snap = reg.snapshot()
    assert snap["shards"]["1"]["occupancy_tuples"] == 9
    other = dict(snap)
    other["shards"] = {"0": {"occupancy_tuples": 50, "restarts": 2}}
    merged = merge_snapshots([snap, other], hosts=["hostA", "hostB"])
    # host-tagged, never summed: the fleet view names WHICH shard is hot
    assert merged["shards"]["hostA/1"]["occupancy_tuples"] == 9
    assert merged["shards"]["hostB/0"]["occupancy_tuples"] == 50
    assert len(merged["shards"]) == 3


def test_wf_state_and_wf_health_render_shards(tmp_path, capsys):
    import importlib.util
    import json as _json
    mon = tmp_path / "mon"
    mon.mkdir()
    snap = {"graph": "g", "shards": {
        "0": {"occupancy_tuples": 5, "restarts": 1, "last_recovery_s": 0.01,
              "dead_letters": 0, "reshard_moves": 0, "committed_pos": 8},
        "1": {"occupancy_tuples": 99, "restarts": 0, "last_recovery_s": 0.0,
              "dead_letters": 0, "reshard_moves": 1, "committed_pos": 8}}}
    (mon / "snapshot.json").write_text(_json.dumps(snap))
    (mon / "events.jsonl").write_text(
        _json.dumps({"event": "shard_restore", "shard": 0, "at_batch": 3,
                     "replay_from": 2, "error": "InjectedFault"}) + "\n"
        # a reshard SPAN: begin+end records — the CLIs must count/print it
        # once, not twice
        + _json.dumps({"event": "reshard", "phase": "begin",
                       "from_shards": 2, "to_shards": 4, "at_pos": 6,
                       "moves": 0}) + "\n"
        + _json.dumps({"event": "reshard", "phase": "end",
                       "from_shards": 2, "to_shards": 4, "at_pos": 6,
                       "moves": 0}) + "\n")
    for script in ("wf_state", "wf_health"):
        spec = importlib.util.spec_from_file_location(
            f"{script}_t", os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "scripts", f"{script}.py"))
        m = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(m)
        rc = m.main(["--monitoring-dir", str(mon), "--report", "shards"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "shard" in out and "[HOT]" in out, (script, out)
        # one reshard rendered once (span begin+end != two events)
        assert out.count("2->4") <= 1, (script, out)
        rc = m.main(["--monitoring-dir", str(mon), "--json"])
        out = capsys.readouterr().out
        assert rc == 0 and _json.loads(out)["shards"]["1"]["reshard_moves"] \
            == 1


# --------------------------------------------------------- WF115 pins


def test_wf115_pins():
    from windflow_tpu.analysis import validate
    from windflow_tpu.control import ControlConfig

    def mk(**kw):
        src = wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
                        total=100, num_keys=K)
        op = wf.Win_Seq(lambda wid, it: it.sum("v"),
                        WindowSpec(10, 10, win_type_t.TB), num_keys=K)
        return SupervisedPipeline(src, [op], wf.Sink(lambda v: None),
                                  batch_size=50, **kw)
    assert "WF115" not in validate(mk(shards=4)).codes()
    # shards > key space: empty shards, error
    errs = validate(mk(shards=8)).errors
    assert any(d.code == "WF115" and "key space" in d.message for d in errs)
    # indivisible: warning
    assert any(d.code == "WF115"
               for d in validate(mk(shards=3)).warnings)
    # reshard to a nonexistent shard: error
    errs = validate(mk(shards=4),
                    reshard={"new_shards": 4, "moves": [[2, 9]]}).errors
    assert any(d.code == "WF115" and "does not exist" in d.message
               for d in errs)
    # dispatch K>1 under shards: error
    errs = validate(mk(shards=4), dispatch=4).errors
    assert any(d.code == "WF115" and "scan dispatch" in d.message
               for d in errs)
    # wall-clock admission under shards: error (the WF105 mirror)
    errs = validate(mk(shards=4),
                    control=ControlConfig(autotune=False, admission=True,
                                          rate_tps=100.0)).errors
    assert any(d.code == "WF115" and "wall-clock" in d.message
               for d in errs)
    # shard fault sites while shards resolve to 1: can-never-fire warning
    warns = validate(mk(), faults=FaultPlan(
        [FaultSpec("shard.kill")])).warnings
    assert any(d.code == "WF115" for d in warns)
    # reshard plan with shards=1: can-never-apply warning
    warns = validate(mk(), reshard=8).warnings
    assert any(d.code == "WF115" for d in warns)
    # graph form: pass shards/reshard explicitly
    g = wf.PipeGraph("v", batch_size=20)
    g.add_source(wf.Source(lambda i: {"v": (i % 9).astype(jnp.float32)},
                           total=100, num_keys=3)).add_sink(
        wf.Sink(lambda v: None))
    r = validate(g, supervised=True, shards=4,
                 reshard={"new_shards": 4, "moves": [[1, 7]]})
    assert any(d.code == "WF115" and "does not exist" in d.message
               for d in r.errors)


def test_shards_site_map_in_wf103():
    """The new sites are registered for the supervised driver (WF103 stays
    accurate): scheduling them under 'supervised' produces no WF103."""
    from windflow_tpu.analysis import validate
    p = build(lambda v: None, shards=4)
    r = validate(p, faults=FaultPlan([FaultSpec("shard.kill"),
                                      FaultSpec("reshard.handoff")]))
    assert "WF103" not in r.codes()
