"""Per-backend kernel registry (ops/registry.py): selection precedence, env
subsumption (incl. the deprecated WF_*_IMPL aliases), TuningCache
warm-starts, WF109 stale-executable detection — and the interpret-mode
parity matrix: every registered kernel family byte-identical to its XLA
reference on CPU, including masked/padded-lane edge cases (the ``_bmask`` /
OLD-straggler-mask conventions of the fold call sites)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from windflow_tpu.ops import bitonic, registry
from windflow_tpu.ops.lookup import join_probe
from windflow_tpu.ops.segment import segment_fold, segment_reduce
from windflow_tpu.observability.names import KERNELS


# ------------------------------------------------------------ selection


def _mini_registry():
    r = registry.KernelRegistry()
    r.register_kernel("histogram", "xla", reference=True, default=True)
    r.register_kernel("histogram", "pallas")
    r.register_kernel("lookup", "xla", reference=True, default=True)
    r.register_kernel("lookup", "pallas")
    return r


def test_default_is_reference(monkeypatch):
    monkeypatch.delenv("WF_KERNEL_IMPL", raising=False)
    monkeypatch.delenv("WF_HISTOGRAM_IMPL", raising=False)
    r = _mini_registry()
    assert r.resolve_impl("histogram") == "xla"
    assert r.reference_impl("histogram") == "xla"


def test_explicit_impl_wins_over_env(monkeypatch):
    monkeypatch.setenv("WF_KERNEL_IMPL", "histogram=pallas")
    r = _mini_registry()
    assert r.resolve_impl("histogram", impl="xla") == "xla"


def test_env_per_kernel_beats_global(monkeypatch):
    monkeypatch.setenv("WF_KERNEL_IMPL", "pallas,histogram=xla")
    r = _mini_registry()
    assert r.resolve_impl("histogram") == "xla"
    assert r.resolve_impl("lookup") == "pallas"


def test_env_off_values_mean_no_override(monkeypatch):
    for off in ("", "0"):
        monkeypatch.setenv("WF_KERNEL_IMPL", off)
        assert _mini_registry().resolve_impl("histogram") == "xla"


def test_deprecated_alias_still_honored(monkeypatch):
    monkeypatch.delenv("WF_KERNEL_IMPL", raising=False)
    monkeypatch.setenv("WF_HISTOGRAM_IMPL", "pallas")
    r = _mini_registry()
    assert r.resolve_impl("histogram") == "pallas"
    # WF_KERNEL_IMPL outranks the alias
    monkeypatch.setenv("WF_KERNEL_IMPL", "histogram=xla")
    assert r.resolve_impl("histogram") == "xla"
    # ''/'0' = no override for the aliases too (the repo off convention —
    # a stale WF_HISTOGRAM_IMPL=0 must not crash a pipeline at trace time)
    monkeypatch.delenv("WF_KERNEL_IMPL", raising=False)
    for off in ("", "0"):
        monkeypatch.setenv("WF_HISTOGRAM_IMPL", off)
        assert r.resolve_impl("histogram") == "xla"


def test_unknown_kernel_and_impl_raise():
    r = _mini_registry()
    with pytest.raises(ValueError, match="unknown kernel"):
        r.resolve_impl("typo_kernel")
    with pytest.raises(ValueError, match="no impl"):
        r.resolve_impl("histogram", impl="cuda")


def test_tuning_cache_warm_start(tmp_path, monkeypatch):
    """persist_winner -> a FRESH registry attached to the same cache
    resolves the winner without any env (the PR 3 second-run property, for
    kernels)."""
    from windflow_tpu.control.autotune import TuningCache
    monkeypatch.delenv("WF_KERNEL_IMPL", raising=False)
    monkeypatch.delenv("WF_HISTOGRAM_IMPL", raising=False)
    cache = TuningCache(str(tmp_path / "tuning.json"))
    r = _mini_registry()
    r.attach_tuning_cache(cache)
    r.persist_winner("histogram", "C1024", "pallas", tps=1e8)
    r2 = _mini_registry()
    r2.attach_tuning_cache(cache)
    assert r2.resolve_impl("histogram", spec_key="C1024") == "pallas"
    # other spec keys are unaffected; env still outranks the cache
    assert r2.resolve_impl("histogram", spec_key="C2048") == "xla"
    monkeypatch.setenv("WF_KERNEL_IMPL", "histogram=xla")
    assert r2.resolve_impl("histogram", spec_key="C1024") == "xla"


def test_wf109_stale_selection_surfaces_in_validate(monkeypatch):
    """Resolve under one env, flip the env, validate(): the report carries a
    WF109 naming the kernel — and none after the env is restored."""
    import windflow_tpu as wf
    from windflow_tpu.analysis import validate

    monkeypatch.delenv("WF_KERNEL_IMPL", raising=False)
    monkeypatch.delenv("WF_HISTOGRAM_IMPL", raising=False)
    src = wf.Source(lambda i: {"v": (i % 7).astype(jnp.float32)},
                    total=64, num_keys=2)
    p = wf.Pipeline(src, [wf.Map(lambda t: {"v": t.v + 1.0})],
                    wf.Sink(lambda view: None), batch_size=32)
    registry.REGISTRY.reset_records()   # drop leftovers from earlier tests
    try:
        registry.REGISTRY.resolve_impl("histogram", spec_key="wf109-test")
        monkeypatch.setenv("WF_KERNEL_IMPL", "histogram=pallas")
        report = validate(p)
        hits = [d for d in report.diagnostics if d.code == "WF109"]
        assert hits and "histogram" in hits[0].where, str(report)
        assert report.ok            # warning severity: stale, not broken
        monkeypatch.delenv("WF_KERNEL_IMPL")
        assert "WF109" not in validate(p).codes()
    finally:
        registry.REGISTRY.reset_records()


def test_explicit_impl_not_recorded():
    r = _mini_registry()
    r.resolve_impl("histogram", spec_key="s", impl="pallas")
    assert r.trace_records() == {}
    r.resolve_impl("histogram", spec_key="s")
    assert list(r.trace_records().values()) == [frozenset({"xla"})]


def test_wf109_not_silenced_by_re_resolution(monkeypatch):
    """Records accumulate ALL impls per key: a fresh trace AFTER an env flip
    must not overwrite the pre-flip record — the executable compiled under
    the old impl is still cached, so it stays reported as stale."""
    monkeypatch.delenv("WF_KERNEL_IMPL", raising=False)
    monkeypatch.delenv("WF_HISTOGRAM_IMPL", raising=False)
    r = _mini_registry()
    r.resolve_impl("histogram", spec_key="s")              # records 'xla'
    monkeypatch.setenv("WF_KERNEL_IMPL", "histogram=pallas")
    r.resolve_impl("histogram", spec_key="s")              # re-records
    [rec] = r.stale_selections()
    assert rec["recorded"] == "xla" and rec["current"] == "pallas"


def test_global_registry_covers_names_registry():
    """Every kernel family in names.py::KERNELS is registered (with its
    reference impl) once the ops package is imported — the WF250/lint and
    perf-gate coverage contract."""
    import windflow_tpu.ops  # noqa: F401 — registration side effect
    for k in KERNELS:
        assert k in registry.REGISTRY.kernels()
        assert registry.REGISTRY.reference_impl(k) is not None


# -------------------------------------------------- parity: ordering merge


def _rand_keys(rng, n, lo=0, hi=1 << 20):
    return rng.integers(lo, hi, n).astype(np.int32)


def test_merge_network_parity_fuzz():
    """Pallas merge kernel byte-identical to the XLA network on bitonic
    inputs (ascending ++ descending), across sizes incl. the invalid-lane
    +max padding the ordering pool uses."""
    rng = np.random.default_rng(11)
    big = np.iinfo(np.int32).max
    for n in (4, 64, 1024, 8192):
        h = n // 2
        asc = np.sort(_rand_keys(rng, h))
        # descending side with +max "invalid lane" padding at the front
        # (after the [::-1] reversal the pads sit at the sequence tail, the
        # merge must sink them last like _push_core's ext() padding)
        desc = np.sort(_rand_keys(rng, h))[::-1].copy()
        desc[: max(1, h // 8)] = big
        prim = np.concatenate([asc, desc])
        sec = _rand_keys(rng, n, 0, 4)
        chan = _rand_keys(rng, n, 0, 3)
        idx = np.arange(n, dtype=np.int32)
        args = [jnp.asarray(a) for a in (prim, sec, chan, idx)]
        a = bitonic.merge_network(*args)
        b = bitonic.merge_network_pallas(*args, interpret=True)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        assert np.all(np.diff(np.asarray(a[0]).astype(np.int64)) >= 0)


def test_sort_network_parity_vs_lexsort():
    """The full sort network (both impls) equals the stable lexsort the
    ordering _sort_batch reference uses — the byte-identical-impls property
    the registry promises."""
    rng = np.random.default_rng(12)
    for n in (2, 16, 512, 4096):
        prim = _rand_keys(rng, n, 0, 50)          # heavy ties
        sec = _rand_keys(rng, n, 0, 3)
        chan = _rand_keys(rng, n, 0, 2)
        idx = np.arange(n, dtype=np.int32)
        args = [jnp.asarray(a) for a in (prim, sec, chan, idx)]
        want = np.lexsort((chan, sec, prim)).astype(np.int32)
        got_x = bitonic.sort_network(*args)
        got_p = bitonic.sort_network_pallas(*args, interpret=True)
        np.testing.assert_array_equal(np.asarray(got_x[3]), want)
        for x, y in zip(got_x, got_p):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_ordering_node_pallas_stream_identical(monkeypatch):
    """End-to-end Ordering_Node: the released stream under
    merge_impl='pallas' is byte-identical to the default ('xla') node, push
    by push, including watermark gating and the invalid-lane tail."""
    from windflow_tpu.basic import ordering_mode_t
    from windflow_tpu.batch import Batch

    def mk_batch(rng, base, cap=64):
        ts = np.sort(base + rng.integers(0, 40, cap)).astype(np.int32)
        ids = (base * 100 + np.arange(cap)).astype(np.int32)
        valid = rng.random(cap) < 0.8
        return Batch(key=jnp.asarray(ids % 5), id=jnp.asarray(ids),
                     ts=jnp.asarray(ts),
                     payload={"v": jnp.asarray(ts.astype(np.float32))},
                     valid=jnp.asarray(valid))

    def run(merge_impl):
        from windflow_tpu.parallel.ordering import Ordering_Node
        rng = np.random.default_rng(3)
        node = Ordering_Node(2, ordering_mode_t.TS, merge_impl=merge_impl)
        out = []

        def grab(b):
            if b is None:
                return
            ok = np.asarray(b.valid)
            out.append((np.asarray(b.ts)[ok], np.asarray(b.id)[ok],
                        np.asarray(b.payload["v"])[ok]))
        for step in range(6):
            grab(node.push(step % 2, mk_batch(rng, base=step * 25)))
        for ch in (0, 1):
            grab(node.close_channel(ch))
        grab(node.flush())
        return out

    a, b = run("xla"), run("pallas")
    assert len(a) == len(b)
    for (ta, ia, va), (tb, ib, vb) in zip(a, b):
        np.testing.assert_array_equal(ta, tb)
        np.testing.assert_array_equal(ia, ib)
        np.testing.assert_array_equal(va, vb)


# ------------------------------------------------- parity: segment fold


def test_segment_fold_parity_masked_and_padded():
    """Pallas fold byte-identical to the segment_sum reference: random
    masks, fully-dead chunks, out-of-range sentinel ids (the K*P 'invalid
    lane' convention of win_seqffat's fold), and the S not divisible by the
    tile width case."""
    rng = np.random.default_rng(21)
    for C, S in ((1024, 16), (4096, 300), (8192, 4096), (2048, 513)):
        v = rng.integers(-1000, 1000, C).astype(np.int32)
        seg = rng.integers(0, S + 1, C).astype(np.int32)   # S = sentinel
        valid = rng.random(C) < 0.7
        valid[:256] = False                                # dead head chunk
        a = segment_fold(jnp.asarray(v), jnp.asarray(seg),
                         jnp.asarray(valid), S, impl="xla")
        b = segment_fold(jnp.asarray(v), jnp.asarray(seg),
                         jnp.asarray(valid), S, impl="pallas",
                         interpret=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_segment_fold_full_int32_domain_exact():
    """The limb-split kernel is byte-identical to segment_sum over the FULL
    int32 domain — huge magnitudes, hot segments whose true sums overflow
    int32 (both impls wrap mod 2^32), and narrow dtypes that wrap earlier."""
    rng = np.random.default_rng(24)
    C, S = 2048, 32
    v = rng.integers(-(1 << 31), 1 << 31, C, dtype=np.int64).astype(np.int32)
    seg = rng.integers(0, S, C).astype(np.int32)
    seg[:512] = 7                                  # hot segment -> overflow
    valid = rng.random(C) < 0.9
    for dt in (np.int32, np.int16, np.int8):
        vv = v.astype(dt)
        a = segment_fold(jnp.asarray(vv), jnp.asarray(seg),
                         jnp.asarray(valid), S, impl="xla")
        b = segment_fold(jnp.asarray(vv), jnp.asarray(seg),
                         jnp.asarray(valid), S, impl="pallas",
                         interpret=True)
        assert a.dtype == b.dtype == dt
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(dt))


def test_segment_fold_float_routes_to_reference():
    """Float values are outside the Pallas exactness envelope — impl=pallas
    must still return the reference result (in-call fallback)."""
    rng = np.random.default_rng(22)
    C, S = 2048, 64
    v = rng.normal(size=C).astype(np.float32)
    seg = rng.integers(0, S, C).astype(np.int32)
    valid = rng.random(C) < 0.5
    a = segment_fold(jnp.asarray(v), jnp.asarray(seg), jnp.asarray(valid), S,
                     impl="xla")
    b = segment_fold(jnp.asarray(v), jnp.asarray(seg), jnp.asarray(valid), S,
                     impl="pallas")
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_segment_reduce_routes_through_fold(monkeypatch):
    """The Win_SeqFFAT fold call site: segment_reduce's default-add path
    under WF_KERNEL_IMPL=segment_fold=pallas equals the reference — through
    the registry, no code change at the call site."""
    rng = np.random.default_rng(23)
    C, S = 2048, 128
    v = rng.integers(0, 50, C).astype(np.int32)
    keys = rng.integers(0, S, C).astype(np.int32)
    valid = rng.random(C) < 0.8
    base = segment_reduce(jnp.asarray(v), jnp.asarray(keys),
                          jnp.asarray(valid), S)
    monkeypatch.setenv("WF_KERNEL_IMPL", "segment_fold=pallas")
    try:
        got = segment_reduce(jnp.asarray(v), jnp.asarray(keys),
                             jnp.asarray(valid), S)
    finally:
        from windflow_tpu.ops.registry import REGISTRY
        REGISTRY.reset_records()
    np.testing.assert_array_equal(np.asarray(base), np.asarray(got))


# -------------------------------------------------- parity: join probe


def test_join_probe_parity_hits_misses_masks():
    rng = np.random.default_rng(31)
    for C, K in ((1024, 16), (8192, 512), (2048, 2048)):
        tk = rng.permutation(1 << 16)[:K].astype(np.int32)
        tv = rng.integers(-(1 << 20), 1 << 20, K).astype(np.int32)
        # half the probes hit, half miss; some lanes invalid
        probe = np.where(rng.random(C) < 0.5, rng.choice(tk, C),
                         (1 << 17) + rng.integers(0, 1000, C)).astype(np.int32)
        valid = rng.random(C) < 0.8
        va, ha = join_probe(jnp.asarray(tk), jnp.asarray(tv),
                            jnp.asarray(probe), jnp.asarray(valid),
                            impl="xla")
        vb, hb = join_probe(jnp.asarray(tk), jnp.asarray(tv),
                            jnp.asarray(probe), jnp.asarray(valid),
                            impl="pallas", interpret=True)
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
        np.testing.assert_array_equal(np.asarray(ha), np.asarray(hb))
        # oracle
        lut = {int(k): int(x) for k, x in zip(tk, tv)}
        for i in range(0, C, 97):
            if valid[i] and int(probe[i]) in lut:
                assert bool(np.asarray(ha)[i])
                assert int(np.asarray(va)[i]) == lut[int(probe[i])]
            else:
                assert not bool(np.asarray(ha)[i])
                assert int(np.asarray(va)[i]) == 0


def test_join_probe_float_values_exact():
    """Float value tables: at most one match per lane, so the select-reduce
    is exact — impls byte-identical in f32 too."""
    rng = np.random.default_rng(32)
    C, K = 1024, 128
    tk = rng.permutation(1 << 12)[:K].astype(np.int32)
    tv = rng.normal(size=K).astype(np.float32)
    probe = rng.choice(tk, C).astype(np.int32)
    valid = np.ones(C, bool)
    va, ha = join_probe(jnp.asarray(tk), jnp.asarray(tv), jnp.asarray(probe),
                        jnp.asarray(valid), impl="xla")
    vb, hb = join_probe(jnp.asarray(tk), jnp.asarray(tv), jnp.asarray(probe),
                        jnp.asarray(valid), impl="pallas", interpret=True)
    np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
    assert bool(np.asarray(ha).all()) and bool(np.asarray(hb).all())


def test_join_probe_oversized_table_falls_back():
    """K beyond the kernel's VMEM envelope: impl='pallas' silently takes the
    reference path (selection is an optimization, never a semantics
    change)."""
    from windflow_tpu.ops.lookup import JOIN_PROBE_MAX_ROWS
    rng = np.random.default_rng(33)
    K = JOIN_PROBE_MAX_ROWS + 8
    C = 256
    tk = rng.permutation(1 << 18)[:K].astype(np.int32)
    tv = rng.integers(0, 100, K).astype(np.int32)
    probe = rng.choice(tk, C).astype(np.int32)
    valid = np.ones(C, bool)
    va, ha = join_probe(jnp.asarray(tk), jnp.asarray(tv), jnp.asarray(probe),
                        jnp.asarray(valid), impl="pallas")
    vb, hb = join_probe(jnp.asarray(tk), jnp.asarray(tv), jnp.asarray(probe),
                        jnp.asarray(valid), impl="xla")
    np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
    np.testing.assert_array_equal(np.asarray(ha), np.asarray(hb))


# ------------------------------------- parity: histogram/lookup via registry


def test_histogram_parity_through_registry(monkeypatch):
    """The pre-existing kernels selected THROUGH the registry env: fresh
    shapes force a fresh trace, results byte-identical to the reference."""
    from windflow_tpu.ops.histogram import keyed_pane_histogram
    from tests.test_histogram_lookup import ref_hist
    rng = np.random.default_rng(41)
    C, K, P = 3072, 9, 64
    key = rng.integers(0, K, C).astype(np.int32)
    pane = (np.arange(C) // 600).astype(np.int32) + 3
    valid = rng.random(C) < 0.75
    want = ref_hist(key, pane, valid, K, P)
    for impl_env in ("xla", "pallas", "pallas_mm"):
        monkeypatch.setenv("WF_KERNEL_IMPL", f"histogram={impl_env}")
        got = keyed_pane_histogram(jnp.asarray(key), jnp.asarray(pane),
                                   jnp.asarray(valid), K, P)
        np.testing.assert_array_equal(np.asarray(got), want,
                                      err_msg=impl_env)
    from windflow_tpu.ops.registry import REGISTRY
    REGISTRY.reset_records()


def test_lookup_parity_through_registry(monkeypatch):
    from windflow_tpu.ops.lookup import table_lookup
    rng = np.random.default_rng(42)
    K, C = 700, 1024
    table = jnp.asarray(rng.integers(0, 1 << 12, K).astype(np.int32))
    idx = jnp.asarray(rng.integers(0, K, C).astype(np.int32))
    want = np.asarray(table)[np.asarray(idx)]
    for impl_env in ("xla", "pallas"):
        monkeypatch.setenv("WF_KERNEL_IMPL", f"lookup={impl_env}")
        got = table_lookup(table, idx)
        np.testing.assert_array_equal(np.asarray(got), want,
                                      err_msg=impl_env)
    from windflow_tpu.ops.registry import REGISTRY
    REGISTRY.reset_records()
