"""Cross-chip window parallelism (Win_Farm): the fired-window [W] axis partitions
over the mesh while archives replicate — the WF_Emitter multicast + round-robin
window ownership (wf/wf_nodes.hpp:157-204, wf/win_farm.hpp:165-175) as sharding
rules. Oracle: results identical to single-device; evidence: addressable shards
of the output batch cover W/p rows on each of the 8 virtual devices."""

import numpy as np
import jax
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.batch import Batch
from windflow_tpu.operators.win_patterns import Win_Farm, Pane_Farm
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.parallel import make_mesh, ShardedChain
from windflow_tpu.runtime.pipeline import CompiledChain


def _batches(total, C):
    out = []
    for s in range(0, total, C):
        n = min(C, total - s)
        ids = np.arange(s, s + C, dtype=np.int32)
        out.append(Batch(
            key=jnp.zeros(C, jnp.int32),
            id=jnp.asarray(ids), ts=jnp.asarray(ids),
            payload={"v": jnp.asarray((ids % 11).astype(np.float32))},
            valid=jnp.asarray(np.arange(C) < n)))
    return out


def _collect(outs):
    acc = []
    for o in outs:
        o = jax.tree.map(np.asarray, o)
        v = o.valid
        acc.extend(zip(o.key[v].tolist(), o.id[v].tolist(),
                       np.asarray(jax.tree.leaves(o.payload)[0])[v].tolist()))
    return sorted(acc)


def _run(factory, batches, sharded):
    spec = {"v": jax.ShapeDtypeStruct((), jnp.float32)}
    chain = CompiledChain(factory(), spec, batch_capacity=batches[0].capacity)
    if sharded:
        sc = ShardedChain(chain, make_mesh(8))
        outs = [sc.push(b) for b in batches]
        outs += sc.flush()
        return _collect(outs), outs
    outs = [chain.push(b) for b in batches]
    outs += chain.flush()
    return _collect(outs), outs


def test_win_farm_window_axis_sharded_matches_oracle():
    factory = lambda: [Win_Farm(lambda wid, it: it.sum("v"),
                                WindowSpec(16, 8, win_type_t.CB),
                                parallelism=8, max_wins=32)]
    batches = _batches(512, 128)
    single, _ = _run(factory, batches, sharded=False)
    multi, outs = _run(factory, batches, sharded=True)
    assert single == multi and len(single) > 0

    # W axis verifiably partitioned: 8 addressable shards, each W/8 rows
    out = outs[0]
    shards = out.key.addressable_shards
    assert len(shards) == 8
    W = out.key.shape[0]
    assert all(s.data.shape[0] == W // 8 for s in shards)
    assert len({s.device for s in shards}) == 8


def test_win_farm_tb_window_axis_sharded_matches_oracle():
    factory = lambda: [Win_Farm(lambda wid, it: it.max("v"),
                                WindowSpec(20, 10, win_type_t.TB),
                                parallelism=8, max_wins=32, tb_capacity=256)]
    batches = _batches(400, 80)
    single, _ = _run(factory, batches, sharded=False)
    multi, _ = _run(factory, batches, sharded=True)
    assert single == multi and len(single) > 0


def test_nested_win_farm_pane_farm_sharded():
    def factory():
        inner = Pane_Farm(lambda wid, it: it.sum("v"),
                          lambda wid, it: it.sum(),
                          WindowSpec(16, 8, win_type_t.CB), num_keys=1,
                          max_wins=64)
        return [Win_Farm(inner, parallelism=8)]
    batches = _batches(384, 128)
    single, _ = _run(factory, batches, sharded=False)
    multi, _ = _run(factory, batches, sharded=True)
    assert single == multi and len(single) > 0


def _keyed_batches(total, C, K):
    out = []
    for s in range(0, total, C):
        n = min(C, total - s)
        ids = np.arange(s, s + C, dtype=np.int32)
        out.append(Batch(
            key=jnp.asarray(ids % K), id=jnp.asarray(ids), ts=jnp.asarray(ids),
            payload={"v": jnp.asarray((ids % 11).astype(np.float32))},
            valid=jnp.asarray(np.arange(C) < n)))
    return out


def test_key_x_win_mesh_shards_archive_and_windows():
    """Keyed Win_Farm on a 2-D key x win mesh (VERDICT r03 #9): the [K, ...]
    archive partitions over the key axis (the reference's hash(key)%p
    distribution, wf/wf_nodes.hpp:157-204 — full replication wastes HBM at
    large K) while the fired-window [W] rows partition over the win axis.
    Oracle-identical to the single-device run."""
    from windflow_tpu.parallel import make_mesh_2d
    K = 8
    spec = WindowSpec(16, 8, win_type_t.CB)
    batches = _keyed_batches(384, 96, K)
    payload_spec = {"v": jax.ShapeDtypeStruct((), jnp.float32)}

    def build():
        return CompiledChain(
            [Win_Farm(lambda wid, it: it.sum("v"), spec, num_keys=K,
                      max_wins=32)],
            payload_spec, batch_capacity=96)

    chain = build()
    single = _collect([chain.push(b) for b in batches] + chain.flush())

    mesh = make_mesh_2d((4, 2), axes=("key", "win"))
    chain2 = build()
    sc = ShardedChain(chain2, mesh, axis="key", win_axis="win",
                      key_axis="key")
    multi = _collect([sc.push(b) for b in batches] + sc.flush())
    assert single == multi and len(single) > 0

    # BOTH axes really partitioned: a [K, A, ...] archive leaf splits 4-way on
    # key (replicated over win)...
    arch = [l for l in jax.tree.leaves(chain2.states[0])
            if getattr(l, "ndim", 0) >= 2 and l.shape[0] == K]
    assert arch, "no [K, ...] archive leaves found"
    shards = arch[0].addressable_shards
    assert len(shards) == 8
    assert all(s.data.shape[0] == K // 4 for s in shards)
    # ...and per-key scalar state ([K]) splits the same way
    scalars = [l for l in jax.tree.leaves(chain2.states[0])
               if getattr(l, "ndim", 0) == 1 and l.shape[0] == K]
    assert scalars and all(
        s.data.shape[0] == K // 4 for s in scalars[0].addressable_shards)


def test_key_x_win_replicates_archive_without_explicit_key_axis():
    """Without an explicit key_axis the keyed farm's archive keeps the
    WF-multicast replication rule (1-D meshes unchanged)."""
    K = 8
    spec = WindowSpec(16, 8, win_type_t.CB)
    batches = _keyed_batches(192, 96, K)
    payload_spec = {"v": jax.ShapeDtypeStruct((), jnp.float32)}
    chain = CompiledChain(
        [Win_Farm(lambda wid, it: it.sum("v"), spec, num_keys=K, max_wins=32)],
        payload_spec, batch_capacity=96)
    sc = ShardedChain(chain, make_mesh(8, axis="win"), axis="win")
    _ = [sc.push(b) for b in batches]
    arch = [l for l in jax.tree.leaves(chain.states[0])
            if getattr(l, "ndim", 0) >= 2 and l.shape[0] == K]
    assert all(s.data.shape[0] == K for s in arch[0].addressable_shards)
