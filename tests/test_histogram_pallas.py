"""Pallas keyed-pane histogram (ops/histogram.py::keyed_pane_histogram_pallas):
exactness against the scatter oracle in interpret mode (CPU), under the fast
path's locality precondition, including ring wrap-around via the spill-column
fold and partially-invalid lanes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from windflow_tpu.ops.histogram import (DEFAULT_CHUNK, keyed_pane_histogram,
                                        keyed_pane_histogram_pallas)
from tests.test_histogram_lookup import ref_hist


def _call(key, pane, valid, K, P, placement="ds"):
    return keyed_pane_histogram_pallas(
        jnp.asarray(key), jnp.asarray(pane), jnp.asarray(valid), K, P,
        placement=placement, interpret=True)


@pytest.mark.parametrize("placement", ["ds", "mm"])
def test_pallas_hist_placements_agree(placement):
    C, K, P = 4096, 13, 48
    rng = np.random.default_rng(7)
    key = rng.integers(0, K, C).astype(np.int32)
    pane = (np.arange(C) // 700 + P - 2).astype(np.int32)   # wraps the ring
    valid = rng.random(C) < 0.8
    got = _call(key, pane, valid, K, P, placement=placement)
    np.testing.assert_array_equal(np.asarray(got),
                                  ref_hist(key, pane, valid, K, P))


@pytest.mark.parametrize("C,K,P", [(4096, 7, 64), (8192, 100, 256)])
def test_pallas_hist_sorted_ts(C, K, P):
    rng = np.random.default_rng(0)
    key = rng.integers(0, K, C).astype(np.int32)
    # nondecreasing panes, < locality(8) distinct panes per 1024-lane chunk
    pane = (np.arange(C) // 157).astype(np.int32) + 5
    valid = rng.random(C) < 0.7
    got = _call(key, pane, valid, K, P)
    np.testing.assert_array_equal(np.asarray(got),
                                  ref_hist(key, pane, valid, K, P))


def test_pallas_hist_wraparound():
    C, K, P = 4096, 5, 32
    rng = np.random.default_rng(1)
    key = rng.integers(0, K, C).astype(np.int32)
    pane = (np.arange(C) // 600 + P - 2).astype(np.int32)  # crosses ring edge
    valid = np.ones(C, bool)
    got = _call(key, pane, valid, K, P)
    np.testing.assert_array_equal(np.asarray(got),
                                  ref_hist(key, pane, valid, K, P))


def test_pallas_hist_empty_chunks():
    C, K, P = 4096, 3, 16
    key = np.zeros(C, np.int32)
    pane = np.zeros(C, np.int32)
    valid = np.zeros(C, bool)
    valid[2048:2100] = True          # chunks 0,1,3 fully invalid
    got = _call(key, pane, valid, K, P)
    np.testing.assert_array_equal(np.asarray(got),
                                  ref_hist(key, pane, valid, K, P))


def test_pallas_matches_xla_fast_path():
    """Same inputs through both fast-path implementations."""
    C, K, P = 8192, 100, 2100        # YSB-like ring geometry
    rng = np.random.default_rng(2)
    key = rng.integers(0, K, C).astype(np.int32)
    pane = (np.arange(C) // 200).astype(np.int32) + 1000
    valid = rng.random(C) < 0.9
    a = keyed_pane_histogram(jnp.asarray(key), jnp.asarray(pane),
                             jnp.asarray(valid), K, P)
    b = _call(key, pane, valid, K, P)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_integrated_impl_pallas_cond_paths():
    """keyed_pane_histogram(impl='pallas'): the locality cond routes in-bounds
    batches through the kernel and unordered batches through the exact scatter
    fallback — identical results either way."""
    C, K, P = 4096, 11, 64
    rng = np.random.default_rng(4)
    key = rng.integers(0, K, C).astype(np.int32)
    valid = rng.random(C) < 0.6
    for pane in ((np.arange(C) // 600).astype(np.int32),       # in-bounds
                 rng.integers(0, 1000, C).astype(np.int32)):   # violates -> scatter
        got = jax.jit(lambda *a: keyed_pane_histogram(*a, K, P, impl="pallas"))(
            jnp.asarray(key), jnp.asarray(pane), jnp.asarray(valid))
        np.testing.assert_array_equal(np.asarray(got),
                                      ref_hist(key, pane, valid, K, P))


def test_ysb_chain_equal_under_impl(monkeypatch):
    """Full YSB chain output is bit-identical under WF_HISTOGRAM_IMPL=pallas."""
    from windflow_tpu.benchmarks import ysb

    def run():
        res = ysb.make_pipeline(8192, batch_size=2048).run()
        return int(res["ysb_windows_total"])

    base = run()
    monkeypatch.setenv("WF_HISTOGRAM_IMPL", "pallas")
    assert run() == base == ysb.oracle_totals(8192)


@pytest.mark.parametrize("K,C", [(1000, 8192), (300, 512), (5000, 16384)])
def test_pallas_factored_lookup(K, C):
    from windflow_tpu.ops.lookup import _pallas_factored_lookup, table_lookup

    rng = np.random.default_rng(5)
    table = jnp.asarray(rng.integers(0, 1 << 12, K).astype(np.int32))
    idx = jnp.asarray(rng.integers(0, K, C).astype(np.int32))
    want = np.asarray(table)[np.asarray(idx)]
    got = jax.jit(lambda t, i: _pallas_factored_lookup(t, i, interpret=True))(
        table, idx)
    np.testing.assert_array_equal(np.asarray(got), want)
    # routed through table_lookup's impl switch
    got2 = jax.jit(lambda t, i: table_lookup(t, i, impl="pallas"))(table, idx)
    np.testing.assert_array_equal(np.asarray(got2), want)


def test_pallas_lookup_unblockable_capacity_falls_back():
    """C not a multiple of 128 -> the impl switch silently uses the XLA form."""
    from windflow_tpu.ops.lookup import table_lookup

    rng = np.random.default_rng(6)
    K, C = 1000, 1000
    table = jnp.asarray(rng.integers(0, 1 << 12, K).astype(np.int32))
    idx = jnp.asarray(rng.integers(0, K, C).astype(np.int32))
    got = jax.jit(lambda t, i: table_lookup(t, i, impl="pallas"))(table, idx)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(table)[np.asarray(idx)])


def test_pallas_hist_fuzz_geometry():
    """Randomized geometry x validity x placement fuzz against the scatter
    oracle (interpret mode), inputs constructed to satisfy the fast path's
    per-chunk locality precondition — the adoption gate for on-chip use."""
    from windflow_tpu.ops.histogram import DEFAULT_L

    rng = np.random.default_rng(42)
    for trial in range(12):
        chunk = DEFAULT_CHUNK
        C = chunk * int(rng.integers(2, 9))
        K = int(rng.integers(2, 300))
        P = int(rng.integers(8, 4096))
        L = DEFAULT_L
        key = rng.integers(0, K, C).astype(np.int32)
        # per-chunk pane base: arbitrary nondecreasing jumps (ring wraps many
        # times); within-chunk offsets < L
        bases = np.cumsum(rng.integers(0, 3 * P, C // chunk))
        pane = (np.repeat(bases, chunk)
                + rng.integers(0, L, C)).astype(np.int32)
        valid = rng.random(C) < rng.random()
        placement = ("ds", "mm")[trial % 2]
        got = _call(key, pane, valid, K, P, placement=placement)
        np.testing.assert_array_equal(
            np.asarray(got), ref_hist(key, pane, valid, K, P),
            err_msg=f"trial={trial} C={C} K={K} P={P} placement={placement}")


def test_pallas_lookup_fuzz_geometry():
    from windflow_tpu.ops.lookup import _pallas_block, _pallas_factored_lookup

    rng = np.random.default_rng(43)
    for trial in range(10):
        K = int(rng.integers(129, 20000))
        C = int(rng.choice([128, 256, 1024, 8192, 16384, 24576]))
        assert _pallas_block(C), C
        table = jnp.asarray(rng.integers(-(1 << 20), 1 << 20, K)
                            .astype(np.int32))
        idx = jnp.asarray(rng.integers(0, K, C).astype(np.int32))
        got = _pallas_factored_lookup(table, idx, interpret=True)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(table)[np.asarray(idx)],
            err_msg=f"trial={trial} K={K} C={C}")


def test_pallas_odd_capacity_falls_back():
    """Non-chunk-multiple capacities route to the exact scatter path."""
    C, K, P = 1000, 3, 16
    rng = np.random.default_rng(3)
    key = rng.integers(0, K, C).astype(np.int32)
    pane = rng.integers(0, 100, C).astype(np.int32)
    valid = rng.random(C) < 0.5
    got = _call(key, pane, valid, K, P)
    np.testing.assert_array_equal(np.asarray(got),
                                  ref_hist(key, pane, valid, K, P))


def test_pallas_small_ring_routes_to_scatter():
    """ring < locality: the kernel's single-fold wrap is shape-mismatched, so
    the call must route to the exact scatter path (ADVICE r05 #2)."""
    C, K, P = 2048, 5, 4                     # P=4 < locality=8
    rng = np.random.default_rng(3)
    key = rng.integers(0, K, C).astype(np.int32)
    pane = rng.integers(0, 64, C).astype(np.int32)
    valid = rng.random(C) < 0.9
    got = _call(key, pane, valid, K, P)
    np.testing.assert_array_equal(np.asarray(got),
                                  ref_hist(key, pane, valid, K, P))
    # the integrated entry point with impl="pallas" takes the same route
    got2 = keyed_pane_histogram(jnp.asarray(key), jnp.asarray(pane),
                                jnp.asarray(valid), K, P, impl="pallas")
    np.testing.assert_array_equal(np.asarray(got2),
                                  ref_hist(key, pane, valid, K, P))


def test_histogram_force_fast_env_zero_means_off(monkeypatch):
    """WF_HISTOGRAM_FORCE_FAST='0'/'' must DISABLE the diagnostic bypass (the
    WF_ORDERING_SKIP_SORTED convention, ADVICE r05 #1): with the locality cond
    active, a locality-violating batch still takes the exact scatter branch."""
    C, K, P = 2048, 4, 32
    rng = np.random.default_rng(9)
    key = rng.integers(0, K, C).astype(np.int32)
    pane = rng.integers(0, 10_000, C).astype(np.int32)   # wildly out of locality
    valid = np.ones(C, bool)
    oracle = ref_hist(key, pane, valid, K, P)
    for off in ("0", ""):
        monkeypatch.setenv("WF_HISTOGRAM_FORCE_FAST", off)
        got = keyed_pane_histogram(jnp.asarray(key), jnp.asarray(pane),
                                   jnp.asarray(valid), K, P)
        np.testing.assert_array_equal(np.asarray(got), oracle)
    # '1' still engages the bypass (wrong on this input — that is its contract)
    monkeypatch.setenv("WF_HISTOGRAM_FORCE_FAST", "1")
    forced = keyed_pane_histogram(jnp.asarray(key), jnp.asarray(pane),
                                  jnp.asarray(valid), K, P)
    assert not np.array_equal(np.asarray(forced), oracle)
