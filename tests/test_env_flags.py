"""Static-analysis gate: every ``WF_*`` environment flag read anywhere in the
tree must be documented in ``docs/ENV_FLAGS.md`` — including *when* it is read
(the ADVICE round-5 footgun: trace-time reads are baked into cached
executables, so an undocumented flag toggled mid-process silently does
nothing). A new env read without a docs row fails tier-1."""

import os
import re

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(ROOT, "docs", "ENV_FLAGS.md")

#: a line is an env READ when it touches the environment (os.environ /
#: getenv) or defines the default env-var name a reader resolves later
#: (``var: str = "WF_FAULT_PLAN"`` in FaultPlan.from_env)
_READ_LINE = re.compile(r"environ|getenv|var\s*:\s*str\s*=\s*\"WF_")
_FLAG = re.compile(r"WF_[A-Z][A-Z0-9_]*")


def _py_files():
    scan_dirs = [os.path.join(ROOT, "windflow_tpu"),
                 os.path.join(ROOT, "scripts")]
    files = [os.path.join(ROOT, "bench.py")]
    for d in scan_dirs:
        for dirpath, _dirs, names in os.walk(d):
            files += [os.path.join(dirpath, n) for n in names
                      if n.endswith(".py")]
    return files


def _flags_read():
    found = {}                       # flag -> first "file:line" seen
    for path in _py_files():
        rel = os.path.relpath(path, ROOT)
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                if not _READ_LINE.search(line):
                    continue
                for flag in _FLAG.findall(line):
                    found.setdefault(flag, f"{rel}:{lineno}")
    return found


def _documented():
    """Parse the ENV_FLAGS.md table: {flag: read-at cell}."""
    rows = {}
    with open(DOC) as f:
        for line in f:
            m = re.match(r"\|\s*`(WF_[A-Z0-9_]+)`\s*\|([^|]*)\|", line)
            if m:
                rows[m.group(1)] = m.group(2).strip()
    return rows


def test_every_env_flag_read_is_documented():
    read = _flags_read()
    assert read, "the scanner found no WF_* env reads at all — it is broken"
    docs = _documented()
    missing = {f: where for f, where in read.items() if f not in docs}
    assert not missing, (
        f"WF_* env flags read in the tree but missing from docs/ENV_FLAGS.md "
        f"(add a table row incl. the read-at column): {missing}")


def test_every_documented_flag_states_read_time():
    docs = _documented()
    assert docs, "docs/ENV_FLAGS.md has no flag table rows"
    bad = {f: cell for f, cell in docs.items()
           if not re.search(r"trace|run time|process start|start", cell,
                            re.I)}
    assert not bad, (
        f"ENV_FLAGS.md rows whose 'read at' cell does not state WHEN the "
        f"flag is read (trace time vs run time vs process start): {bad}")


def test_known_trace_time_flags_marked():
    """The four flags read inside jitted code paths must carry the trace-time
    marking — the footgun the inventory exists to prevent."""
    docs = _documented()
    for flag in ("WF_LOOKUP_IMPL", "WF_HISTOGRAM_IMPL",
                 "WF_HISTOGRAM_FORCE_FAST", "WF_ORDERING_SKIP_SORTED"):
        assert flag in docs, f"{flag} missing from ENV_FLAGS.md"
        assert "trace" in docs[flag].lower(), (
            f"{flag} is read at trace time but ENV_FLAGS.md does not say so")
