"""Env-flag inventory gate — every ``WF_*`` environment variable read
anywhere in the tree must be documented in ``docs/ENV_FLAGS.md`` including
*when* it is read (the ADVICE round-5 footgun: trace-time reads are baked
into cached executables, so an undocumented flag toggled mid-process silently
does nothing).

The scanner itself now lives in the invariant linter
(``windflow_tpu/analysis/lint.py`` — rules WF201/WF202), so the CLI, the
tier-1 lint gate (``tests/test_lint_clean.py``), and this focused test all
share ONE source of truth. This file keeps the inventory's contract pinned
directly: the rule finds real reads, and the known trace-time flags stay
marked."""

import os

from windflow_tpu.analysis import lint

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CFG = lint.LintConfig(root=ROOT)


def test_scanner_sees_env_reads_at_all():
    """Guard against a silently-broken scanner (regex drift would make the
    gate vacuously green)."""
    read = lint.env_flags_read(ROOT, CFG)
    assert read, "the scanner found no WF_* env reads at all — it is broken"
    # a representative spread: package run-time flag, default-name idiom
    # (FaultPlan.from_env), trace-time flag, and the linter's own override
    for flag in ("WF_MONITORING", "WF_FAULT_PLAN", "WF_LOOKUP_IMPL",
                 "WF_LINT_BASELINE"):
        assert flag in read, f"{flag} read site not found by the scanner"


def test_every_env_flag_read_is_documented_with_read_time():
    """Rules WF201 (undocumented read) + WF202 (row missing the read-time
    cell) over the live tree — add the ENV_FLAGS.md row in the same commit
    that introduces a flag."""
    findings = lint.rule_env_flags(CFG)
    assert not findings, "\n".join(x.render() for x in findings)


def test_known_trace_time_flags_marked():
    """The four flags read inside jitted code paths must carry the
    trace-time marking — the footgun the inventory exists to prevent."""
    docs = lint.parse_env_doc(os.path.join(ROOT, CFG.env_doc))
    for flag in ("WF_LOOKUP_IMPL", "WF_HISTOGRAM_IMPL",
                 "WF_HISTOGRAM_FORCE_FAST", "WF_ORDERING_SKIP_SORTED"):
        assert flag in docs, f"{flag} missing from ENV_FLAGS.md"
        _lineno, cell = docs[flag]
        assert "trace" in cell.lower(), (
            f"{flag} is read at trace time but ENV_FLAGS.md does not say so")
