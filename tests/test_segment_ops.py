"""Unit tests for segmented ops and compaction — the device-side keyed-routing layer.

Oracle: plain numpy per-key loops (the reference checks result invariance against a
sequential run, src/graph_test/test_graph_1.cpp:77-87; same idea at the op level)."""

import numpy as np
import jax
import jax.numpy as jnp

from windflow_tpu.ops import segment, compaction


def _random_batch(rng, c=257, k=7):
    keys = rng.integers(0, k, size=c).astype(np.int32)
    vals = rng.normal(size=c).astype(np.float32)
    valid = rng.random(c) < 0.8
    return keys, vals, valid


def test_segment_reduce_sum_matches_numpy():
    rng = np.random.default_rng(0)
    keys, vals, valid = _random_batch(rng)
    out = segment.segment_reduce(vals, jnp.asarray(keys), jnp.asarray(valid), 7)
    expect = np.zeros(7, np.float32)
    for k, v, ok in zip(keys, vals, valid):
        if ok:
            expect[k] += v
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-4, atol=1e-5)


def test_segment_reduce_custom_combine_max():
    rng = np.random.default_rng(1)
    keys, vals, valid = _random_batch(rng)
    out = segment.segment_reduce(vals, jnp.asarray(keys), jnp.asarray(valid), 7,
                                 combine=jnp.maximum, identity=-1e30)
    expect = np.full(7, -1e30, np.float32)
    for k, v, ok in zip(keys, vals, valid):
        if ok:
            expect[k] = max(expect[k], v)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-4, atol=1e-5)


def test_segment_prefix_scan_stream_order():
    rng = np.random.default_rng(2)
    keys, vals, valid = _random_batch(rng, c=101, k=5)
    out = segment.segment_prefix_scan(jnp.asarray(vals), jnp.asarray(keys),
                                      jnp.asarray(valid), jnp.add, 0)
    run = {}
    for i, (k, v, ok) in enumerate(zip(keys, vals, valid)):
        if ok:
            run[k] = run.get(k, 0.0) + v
            np.testing.assert_allclose(np.asarray(out)[i], run[k], rtol=1e-4, atol=1e-5)


def test_segment_prefix_scan_with_carry():
    rng = np.random.default_rng(3)
    keys, vals, valid = _random_batch(rng, c=64, k=4)
    carry = np.arange(4, dtype=np.float32) * 100
    out = segment.segment_prefix_scan(jnp.asarray(vals), jnp.asarray(keys),
                                      jnp.asarray(valid), jnp.add, 0,
                                      carry_in=jnp.asarray(carry))
    run = dict(enumerate(carry))
    for i, (k, v, ok) in enumerate(zip(keys, vals, valid)):
        if ok:
            run[k] = run[k] + v
            np.testing.assert_allclose(np.asarray(out)[i], run[k], rtol=1e-4, atol=1e-5)


def test_segment_rank():
    rng = np.random.default_rng(4)
    keys, _, valid = _random_batch(rng, c=50, k=3)
    rank = np.asarray(segment.segment_rank(jnp.asarray(keys), jnp.asarray(valid)))
    seen = {}
    for i, (k, ok) in enumerate(zip(keys, valid)):
        if ok:
            assert rank[i] == seen.get(k, 0)
            seen[k] = seen.get(k, 0) + 1


def test_scatter_compact():
    valid = jnp.asarray(np.array([1, 0, 1, 1, 0, 1], bool))
    vals = jnp.arange(6, dtype=jnp.float32)
    out, out_valid = compaction.scatter_compact(vals, valid)
    np.testing.assert_array_equal(np.asarray(out)[:4], [0, 2, 3, 5])
    np.testing.assert_array_equal(np.asarray(out_valid), [1, 1, 1, 1, 0, 0])


def test_partition_by_destination():
    dest = jnp.asarray(np.array([2, 0, 1, 0, 2, 2, 1], np.int32))
    valid = jnp.asarray(np.array([1, 1, 1, 1, 0, 1, 1], bool))
    vals = np.array([10, 20, 30, 40, 50, 60, 70], np.float32)
    idx, out_valid = compaction.partition_by_destination(dest, valid, 3, 4)
    got = np.asarray(jnp.take(jnp.asarray(vals), idx))
    ov = np.asarray(out_valid)
    assert sorted(got[0][ov[0]].tolist()) == [20, 40]
    assert sorted(got[1][ov[1]].tolist()) == [30, 70]
    assert sorted(got[2][ov[2]].tolist()) == [10, 60]


def test_compact_under_jit():
    @jax.jit
    def f(vals, valid):
        return compaction.scatter_compact(vals, valid)
    out, ov = f(jnp.arange(8, dtype=jnp.float32), jnp.arange(8) % 2 == 0)
    np.testing.assert_array_equal(np.asarray(out)[:4], [0, 2, 4, 6])
