"""Double-buffered host ingest (prefetch thread + overlapped device_put —
the reference GPU path's pinned-buffer cudaMemcpyAsync protocol,
wf/map_gpu_node.hpp:224-340, at the source boundary)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.operators.source import GeneratorSource, prefetch_to_device


def _src(total=300, chunk=64):
    def it():
        for s in range(0, total, chunk):
            n = min(chunk, total - s)
            i = np.arange(s, s + n, dtype=np.int32)
            yield ({"v": (i % 7).astype(np.float32)}, i % 4, i)
    return GeneratorSource(it, {"v": jax.ShapeDtypeStruct((), jnp.float32)},
                           name="gen")


def _collect(batches):
    acc = []
    for b in batches:
        b = jax.tree.map(np.asarray, b)
        v = b.valid
        acc.extend(zip(b.key[v].tolist(), b.id[v].tolist(), b.ts[v].tolist(),
                       b.payload["v"][v].tolist()))
    return acc


def test_prefetched_batches_equal_plain_batches():
    plain = _collect(_src().batches(64))
    pref = _collect(_src().batches_prefetched(64, depth=3))
    assert pref == plain and len(plain) == 300


def test_prefetch_worker_exception_propagates():
    def bad():
        yield {"v": np.zeros(4, np.float32)}
        raise RuntimeError("source died")
    src = GeneratorSource(bad, {"v": jax.ShapeDtypeStruct((), jnp.float32)})
    it = src.batches_prefetched(8, depth=2)
    next(it)
    with pytest.raises(RuntimeError, match="source died"):
        list(it)


def test_prefetch_early_close_stops_worker():
    import threading
    before = {t.name for t in threading.enumerate()}
    it = _src(total=10000, chunk=50).batches_prefetched(50, depth=2)
    next(it)
    it.close()                      # abandon mid-stream
    deadline = 20
    import time
    while deadline and any(t.name == "wf-prefetch" and t.is_alive()
                           and t.name not in before
                           for t in threading.enumerate()):
        time.sleep(0.1)
        deadline -= 1
    leaked = [t for t in threading.enumerate()
              if t.name == "wf-prefetch" and t.is_alive()]
    assert not leaked, f"prefetch worker leaked: {leaked}"


def test_pipeline_with_prefetch_matches_without():
    def run(prefetch):
        out = []
        p = wf.Pipeline(_src(), [wf.Map(lambda t: {"v": t.v * 2})],
                        wf.Sink(lambda v: v is not None and out.extend(
                            np.asarray(v["payload"]["v"]).tolist())),
                        batch_size=64, prefetch=prefetch)
        p.run()
        return out
    assert run(0) == run(3)
