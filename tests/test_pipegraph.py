"""PipeGraph DAG tests — the graph_test/merge_test/split_test suites' semantics:
split/merge topologies with randomized geometry, self-checking via sink sums
(src/graph_test/test_graph_1.cpp ASCII-art topologies + global_sum oracle)."""

import numpy as np
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.runtime.pipegraph import PipeGraph
from windflow_tpu.runtime.builders import (Source_Builder, Map_Builder,
                                           Filter_Builder, Sink_Builder,
                                           ReduceSink_Builder, KeyFarm_Builder)


def test_linear_graph_with_builders():
    total = 500
    src = (Source_Builder(lambda i: {"v": i.astype(jnp.int32)})
           .withName("src").withTotal(total).withKeys(4).build())
    m = Map_Builder(lambda t: {"v": t.v * 3}).withName("triple").build()
    f = Filter_Builder(lambda t: t.v % 2 == 0).withName("evens").build()
    rs = ReduceSink_Builder(lambda t: t.v).withName("total").build()

    g = PipeGraph("linear", batch_size=128)
    g.add_source(src).chain(m).chain(f).add(rs)
    res = g.run()
    expect = sum(i * 3 for i in range(total) if (i * 3) % 2 == 0)
    assert int(res["total"]) == expect


def test_split_two_branches():
    """Split by predicate; each branch applies a different map; sums must partition."""
    total = 400
    g = PipeGraph("split", batch_size=64)
    src = wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=total)
    mp = g.add_source(src)
    mp.split(lambda t: (t.v % 2).astype(jnp.int32), 2)
    b0 = mp.select(0).add(wf.ReduceSink(lambda t: t.v, name="evens"))
    b1 = mp.select(1).add(wf.ReduceSink(lambda t: t.v, name="odds"))
    res = g.run()
    assert int(res["evens"]) == sum(i for i in range(total) if i % 2 == 0)
    assert int(res["odds"]) == sum(i for i in range(total) if i % 2 == 1)


def test_split_multicast_mask():
    """Splitting function returning a boolean mask multicasts tuples to branches."""
    total = 100
    g = PipeGraph("mcast", batch_size=32)
    src = wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=total)
    mp = g.add_source(src)
    mp.split(lambda t: jnp.stack([t.v % 2 == 0, t.v % 3 == 0]), 2)
    mp.select(0).add(wf.ReduceSink(lambda t: jnp.ones((), jnp.int32), name="n2"))
    mp.select(1).add(wf.ReduceSink(lambda t: jnp.ones((), jnp.int32), name="n3"))
    res = g.run()
    assert int(res["n2"]) == len([i for i in range(total) if i % 2 == 0])
    assert int(res["n3"]) == len([i for i in range(total) if i % 3 == 0])


def test_merge_independent_sources():
    """merge-ind case (wf/pipegraph.hpp:860-889): two root pipes merged into one."""
    g = PipeGraph("merge", batch_size=50)
    s1 = wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=100, name="s1")
    s2 = wf.Source(lambda i: {"v": (i + 1000).astype(jnp.int32)}, total=100, name="s2")
    mp1 = g.add_source(s1)
    mp2 = g.add_source(s2)
    merged = mp1.merge(mp2)
    merged.add(wf.ReduceSink(lambda t: t.v, name="sum"))
    res = g.run()
    assert int(res["sum"]) == sum(range(100)) + sum(range(1000, 1100))


def test_split_then_merge_diamond():
    """Diamond: source -> split -> two maps -> merge -> sink (graph_test shape)."""
    total = 200
    g = PipeGraph("diamond", batch_size=64)
    src = wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=total)
    mp = g.add_source(src)
    mp.split(lambda t: (t.v % 2).astype(jnp.int32), 2)
    b0 = mp.select(0).add(wf.Map(lambda t: {"v": t.v * 10}, name="m0"))
    b1 = mp.select(1).add(wf.Map(lambda t: {"v": t.v * 100}, name="m1"))
    merged = b0.merge(b1)
    merged.add(wf.ReduceSink(lambda t: t.v, name="sum"))
    res = g.run()
    expect = sum(i * 10 for i in range(total) if i % 2 == 0) + \
        sum(i * 100 for i in range(total) if i % 2 == 1)
    assert int(res["sum"]) == expect


def test_windowed_op_in_graph_with_flush():
    """Windowed operator inside a PipeGraph: EOS flush cascades to the sink."""
    total, K = 120, 2
    g = PipeGraph("win", batch_size=40)
    src = wf.Source(lambda i: {"v": (i // K).astype(jnp.float32)},
                    total=total, num_keys=K)
    kf = (KeyFarm_Builder(lambda wid, it: it.sum("v"))
          .withCBWindows(10, 10).withKeys(K).withName("kf").build())
    got = []

    def cb(view):
        if view is None:
            return
        got.extend(zip(view["key"].tolist(), view["id"].tolist(),
                       np.asarray(view["payload"]).tolist()))

    g.add_source(src).add(kf).add_sink(wf.Sink(cb, name="sink"))
    g.run()
    expect = []
    for k in range(K):
        vals = [float(i // K) for i in range(total) if i % K == k]
        for w in range((len(vals) - 1) // 10 + 1):
            expect.append((k, w, sum(vals[w * 10:(w + 1) * 10])))
    assert sorted(got) == sorted(expect)


def test_dot_dump_and_introspection():
    g = PipeGraph("dotg", batch_size=32)
    src = wf.Source(lambda i: {"v": i * 1.0}, total=64, name="gen")
    mp = g.add_source(src)
    mp.add(wf.Map(lambda t: {"v": t.v}, name="id"))
    mp.add_sink(wf.Sink(lambda v: None, name="sk"))
    dot = g.dump_DOTGraph()
    assert "digraph PipeGraph" in dot and "gen" in dot
    assert len(g.listOperators()) == 3
    assert g.getNumThreads() == 3


# ---- graph_test DAG-shape suite (src/graph_test/test_graph_{1..9}.cpp shapes)

def test_merge_then_split():
    """graph_1 shape: two source pipes -> merge -> filter -> split -> two sinks."""
    g = PipeGraph("g1", batch_size=64)
    s1 = wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=120, name="s1")
    s2 = wf.Source(lambda i: {"v": (i + 1000).astype(jnp.int32)}, total=120, name="s2")
    merged = g.add_source(s1).merge(g.add_source(s2))
    merged.add(wf.Filter(lambda t: t.v % 2 == 0))
    merged.split(lambda t: (t.v >= 1000).astype(jnp.int32), 2)
    merged.select(0).add(wf.ReduceSink(lambda t: t.v, name="low"))
    merged.select(1).add(wf.ReduceSink(lambda t: t.v, name="high"))
    res = g.run()
    assert int(res["low"]) == sum(i for i in range(120) if i % 2 == 0)
    assert int(res["high"]) == sum(i for i in range(1000, 1120) if i % 2 == 0)


def test_nested_split():
    """graph_4 shape: a split branch splits again (3 leaf sinks)."""
    total = 300
    g = PipeGraph("g4", batch_size=64)
    mp = g.add_source(wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=total))
    mp.split(lambda t: (t.v % 3 == 0).astype(jnp.int32), 2)
    b_rest = mp.select(0)          # v % 3 != 0
    b_mul3 = mp.select(1)          # v % 3 == 0
    b_rest.split(lambda t: (t.v % 3 - 1).astype(jnp.int32), 2)
    b_rest.select(0).add(wf.ReduceSink(lambda t: t.v, name="r1"))
    b_rest.select(1).add(wf.ReduceSink(lambda t: t.v, name="r2"))
    b_mul3.add(wf.ReduceSink(lambda t: t.v, name="r0"))
    res = g.run()
    assert int(res["r0"]) == sum(i for i in range(total) if i % 3 == 0)
    assert int(res["r1"]) == sum(i for i in range(total) if i % 3 == 1)
    assert int(res["r2"]) == sum(i for i in range(total) if i % 3 == 2)


def test_merge_split_branch_with_independent_pipe_rejected():
    """The reference REJECTS merging one split branch with an independent pipe
    (get_MergedNodes1 requires the whole subtree or siblings;
    wf/pipegraph.hpp:963-965). The legal recomposition — merge the whole split
    subtree with the independent pipe — must still work."""
    import pytest
    g = PipeGraph("g3", batch_size=64)
    mp = g.add_source(wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=200,
                                name="sa"))
    mp.split(lambda t: (t.v % 2).astype(jnp.int32), 2)
    b0, b1 = mp.select(0), mp.select(1)
    ind = g.add_source(wf.Source(lambda i: {"v": (i + 5000).astype(jnp.int32)},
                                 total=50, name="sb"))
    with pytest.raises(RuntimeError, match="not supported"):
        b1.merge(ind)
    merged = b0.merge(b1, ind)       # whole subtree + root: legal (full + ind)
    merged.add(wf.ReduceSink(lambda t: t.v, name="m"))
    res = g.run()
    assert int(res["m"]) == sum(range(200)) + sum(range(5000, 5050))


def test_two_disjoint_graphs():
    """graph_5 shape: two unconnected pipelines inside one PipeGraph."""
    g = PipeGraph("g5", batch_size=32)
    g.add_source(wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=80,
                           name="sA")).add(
        wf.Map(lambda t: {"v": t.v * 2})).add(
        wf.ReduceSink(lambda t: t.v, name="a"))
    g.add_source(wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=60,
                           name="sB")).add(
        wf.Filter(lambda t: t.v < 30)).add(
        wf.ReduceSink(lambda t: t.v, name="b"))
    res = g.run()
    assert int(res["a"]) == sum(2 * i for i in range(80))
    assert int(res["b"]) == sum(range(30))


def test_merge_three_pipes():
    """3-way merge (graph_6/7 family): two split branches + independent pipe in one
    merge call."""
    g = PipeGraph("g6", batch_size=64)
    mp = g.add_source(wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=90,
                                name="sa"))
    mp.split(lambda t: (t.v % 2).astype(jnp.int32), 2)
    b0, b1 = mp.select(0), mp.select(1)
    ind = g.add_source(wf.Source(lambda i: {"v": (i + 700).astype(jnp.int32)},
                                 total=10, name="sb"))
    merged = b0.merge(b1, ind)
    merged.add(wf.ReduceSink(lambda t: t.v, name="all"))
    res = g.run()
    assert int(res["all"]) == sum(range(90)) + sum(range(700, 710))


def test_closing_function_runs_per_replica_at_teardown():
    """withClosingFunction (reference closing_func at svc_end): runs once per
    replica with that replica's RuntimeContext, after EOS."""
    calls = []
    m = (Map_Builder(lambda t: {"v": t.v * 2})
         .withName("m").withParallelism(3)
         .withClosingFunction(lambda ctx: calls.append(
             (ctx.getReplicaIndex(), ctx.getParallelism()))).build())
    src = (Source_Builder(lambda i: {"v": i.astype(jnp.int32)})
           .withName("s").withTotal(64).build())
    g = PipeGraph("closing", batch_size=32)
    g.add_source(src).chain(m).add(
        ReduceSink_Builder(lambda t: t.v).withName("out").build())
    g.run()
    assert sorted(calls) == [(0, 3), (1, 3), (2, 3)]


def test_split_branches_recombined_then_extended():
    """graph_2 shape: S->M, split 2 (branch 0: F->M, branch 1: F), merge the
    two branches back, M, sink."""
    total = 200
    g = PipeGraph("g2", batch_size=64)
    mp = g.add_source(wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=total))
    mp.chain(wf.Map(lambda t: {"v": t.v + 1}))              # v in 1..total
    mp.split(lambda t: (t.v % 2).astype(jnp.int32), 2)
    b0 = (mp.select(0).chain(wf.Filter(lambda t: t.v % 3 != 0))
          .chain(wf.Map(lambda t: {"v": t.v * 10})))
    b1 = mp.select(1).chain(wf.Filter(lambda t: t.v % 5 != 0))
    merged = b0.merge(b1)
    merged.chain(wf.Map(lambda t: {"v": t.v + 7}))
    merged.add(wf.ReduceSink(lambda t: t.v, name="out"))
    res = g.run()
    evens = [v * 10 for v in range(1, total + 1) if v % 2 == 0 and v % 3 != 0]
    odds = [v for v in range(1, total + 1) if v % 2 == 1 and v % 5 != 0]
    assert int(res["out"]) == sum(v + 7 for v in evens + odds)


def test_merged_branches_merged_again_with_sibling():
    """graph_8 shape: S->M, MULTICAST split 3 ({0} | {1} | {1,2}), each branch
    F->M; merge(branch1, branch0) (sibling order swapped), two chained maps,
    then merge the merged pipe with the remaining sibling branch 2, sink."""
    total = 240
    g = PipeGraph("g8", batch_size=48)
    mp = g.add_source(wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=total))
    mp.chain(wf.Map(lambda t: {"v": t.v + 1}))              # v in 1..total
    mp.split(lambda t: jnp.stack([t.v % 2 == 1,             # odd  -> {0}
                                  t.v % 2 == 0,             # even -> {1} (+2 below)
                                  (t.v % 2 == 0) & (t.v % 3 != 0)]), 3)
    b0 = (mp.select(0).chain(wf.Filter(lambda t: t.v % 5 != 0))
          .chain(wf.Map(lambda t: {"v": t.v * 10})))
    b1 = (mp.select(1).chain(wf.Filter(lambda t: t.v % 7 != 0))
          .chain(wf.Map(lambda t: {"v": t.v * 100})))
    b2 = (mp.select(2).chain(wf.Filter(lambda t: t.v > 20))
          .chain(wf.Map(lambda t: {"v": t.v + 3})))
    m01 = b1.merge(b0)
    m01.chain(wf.Map(lambda t: {"v": t.v + 1}))
    m01.chain(wf.Map(lambda t: {"v": t.v + 2}))
    final = m01.merge(b2)
    final.add(wf.ReduceSink(lambda t: t.v, name="out"))
    res = g.run()
    vs = range(1, total + 1)
    path0 = [v * 10 for v in vs if v % 2 == 1 and v % 5 != 0]
    path1 = [v * 100 for v in vs if v % 2 == 0 and v % 7 != 0]
    path2 = [v + 3 for v in vs if v % 2 == 0 and v % 3 != 0 and v > 20]
    assert int(res["out"]) == sum(v + 3 for v in path0 + path1) + sum(path2)


def test_cross_level_merge_with_sunk_sibling():
    """graph_9 shape: S->M, split 3; branch 2 ends in its OWN sink; branch 1
    splits again into two map leaves; merge(branch0, leaf0, leaf1) — a
    cross-level merge where the nested split's whole subtree collapses into
    branch 1, leaving contiguous siblings — then sink."""
    total = 300
    g = PipeGraph("g9", batch_size=60)
    mp = g.add_source(wf.Source(lambda i: {"v": i.astype(jnp.int32)}, total=total))
    mp.chain(wf.Map(lambda t: {"v": t.v + 1}))              # v in 1..total
    mp.split(lambda t: jnp.where(t.v % 2 == 1, 0,
                                 jnp.where(t.v % 3 == 0, 1, 2)).astype(jnp.int32), 3)
    b0 = (mp.select(0).chain(wf.Filter(lambda t: t.v % 5 != 0))
          .chain(wf.Map(lambda t: {"v": t.v * 10})))
    b1 = (mp.select(1).chain(wf.Filter(lambda t: t.v > 6))
          .chain(wf.Map(lambda t: {"v": t.v + 100})))
    b2 = mp.select(2).chain(wf.Filter(lambda t: t.v < 50))
    b2.add(wf.ReduceSink(lambda t: t.v, name="solo"))
    b1.split(lambda t: (t.v % 4 >= 2).astype(jnp.int32), 2)
    leaf0 = b1.select(0).chain(wf.Map(lambda t: {"v": t.v * 2}))
    leaf1 = b1.select(1).chain(wf.Map(lambda t: {"v": t.v * 3}))
    final = b0.merge(leaf0, leaf1)
    final.chain(wf.Map(lambda t: {"v": t.v + 1}))
    final.add(wf.ReduceSink(lambda t: t.v, name="out"))
    res = g.run()
    vs = range(1, total + 1)
    path0 = [v * 10 for v in vs if v % 2 == 1 and v % 5 != 0]
    b1_vals = [v + 100 for v in vs if v % 2 == 0 and v % 3 == 0 and v > 6]
    leaves = [v * 2 if v % 4 < 2 else v * 3 for v in b1_vals]
    path2 = [v for v in vs if v % 2 == 0 and v % 3 != 0 and v < 50]
    assert int(res["solo"]) == sum(path2)
    assert int(res["out"]) == sum(v + 1 for v in path0 + leaves)
