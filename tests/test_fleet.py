"""Fleet telemetry plane (observability/fleet.py + scripts/wf_fleet.py /
wf_top.py): wire framing, the drop-oldest agent outbox, agent→aggregator
loopback, the 3-host live-fleet acceptance loop (queue.stall chaos on ONE
host driving the FLEET SLO OK→WARN→PAGE→OK with exactly one correlated
fleet incident bundle), aggregator-death tick-cadence independence,
telemetry-off hermeticity, WF117 validator pins, snapshot schema
provenance, and the stdlib CLI exit contracts."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.nexmark import make_query
from windflow_tpu.observability import (MonitoringConfig, device_health as
                                        dh, fleet, metrics as metrics_mod,
                                        names, slo as slomod)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HOST_DRIVER = os.path.join(REPO, "tests", "fleet_host_driver.py")
WF_FLEET_CLI = os.path.join(REPO, "scripts", "wf_fleet.py")
WF_TOP_CLI = os.path.join(REPO, "scripts", "wf_top.py")
WF_SLO_CLI = os.path.join(REPO, "scripts", "wf_slo.py")

LAT_SPEC = [{"name": "latency", "signal": "e2e_p99_ms", "target": 30.0,
             "objective": 0.5, "fast_window": 3, "slow_window": 6,
             "warn_burn": 1.0, "page_burn": 2.0}]


def _poisoned_jax_dir(tmp_path):
    d = tmp_path / "nojax"
    d.mkdir(exist_ok=True)
    (d / "jax.py").write_text("raise ImportError('fleet CLIs must not "
                              "import jax')\n")
    return str(d)


def _snap(tick, host="h", graph="t", **over):
    s = {"graph": graph, "schema": dh.SNAPSHOT_SCHEMA,
         "wall_time": 1000.0 + tick, "uptime_s": float(tick),
         "operators": [{"name": "map", "role": "map",
                        "outputs_sent": 32 * (tick + 1),
                        "service_time_us": {"p50": 10.0}}],
         "totals": {"outputs_sent": 32 * (tick + 1)},
         "e2e_latency_us": {"p50": 100.0, "p99": 200.0},
         "queues": {"a->b": tick % 3}, "ordering": {}, "recovery": {},
         "control": {"counters": {}}}
    s.update(over)
    return s


# ------------------------------------------------------------ wire framing


def test_frame_roundtrip():
    frames = [{"kind": "snap", "host": f"h{i}", "snap": _snap(i)}
              for i in range(3)]
    dec = fleet.FrameDecoder()
    out = dec.feed(b"".join(fleet.encode_frame(f) for f in frames))
    assert out == frames
    assert dec.frames_torn == 0 and dec.frames_decoded == 3


def test_frame_split_feed():
    """Byte-dribbled input (TCP segmentation) decodes identically."""
    blob = fleet.encode_frame({"kind": "snap", "host": "h", "seq": 1})
    dec = fleet.FrameDecoder()
    out = []
    for i in range(len(blob)):
        out += dec.feed(blob[i:i + 1])
    assert out == [{"kind": "snap", "host": "h", "seq": 1}]


def test_frame_torn_resync():
    """A torn frame (mid-write disconnect) is skipped at the next magic —
    counted, never fatal, and the NEXT frame decodes."""
    good = fleet.encode_frame({"kind": "snap", "host": "ok"})
    dec = fleet.FrameDecoder()
    out = dec.feed(b"garbage-prefix" + good[7:] + good)
    assert [f["host"] for f in out] == ["ok"]
    assert dec.frames_torn >= 1
    # a corrupt length field resyncs too
    dec2 = fleet.FrameDecoder()
    bad = fleet.MAGIC + b"zzzzzzzz\n" + b"{}\n"
    assert dec2.feed(bad + good) == [{"kind": "snap", "host": "ok"}]
    assert dec2.frames_torn >= 1


def test_frame_oversize_refused():
    with pytest.raises(ValueError):
        fleet.encode_frame({"blob": "x" * (fleet.MAX_FRAME_BYTES + 1)})


@pytest.mark.parametrize("ep,want", [
    ("tcp://127.0.0.1:9900", ("tcp", "127.0.0.1", 9900)),
    ("127.0.0.1:0", ("tcp", "127.0.0.1", 0)),
    ("tcp://[::1]:80", ("tcp", "::1", 80)),
    ("unix:///tmp/x.sock", ("unix", "/tmp/x.sock")),
    ("unix:/tmp/y.sock", ("unix", "/tmp/y.sock")),
])
def test_parse_endpoint(ep, want):
    assert fleet.parse_endpoint(ep) == want


@pytest.mark.parametrize("bad", ["", "nohost", "tcp://:12", "tcp://h:xx",
                                 "tcp://h:99999", "unix://"])
def test_parse_endpoint_rejects(bad):
    with pytest.raises(ValueError):
        fleet.parse_endpoint(bad)


# ------------------------------------------------------------ agent outbox


def test_outbox_drop_oldest():
    """The outbox is a bounded drop-OLDEST deque: the reporter side never
    blocks and the newest snapshot always survives."""
    agent = fleet.TelemetryAgent("127.0.0.1:1", host="h", outbox=3)
    # never start()ed: nothing drains, so offers age out of the deque
    for i in range(5):
        agent.offer(_snap(i))
    st = agent.stats()
    assert st["frames_dropped"] == 2
    assert st["outbox_depth"] == 3
    assert st["frames_sent"] == 0 and st["connected"] == 0
    agent.close(flush_s=0.0)


def test_agent_rejects_unhonorable_config():
    """The WF117 problems raise at construction — loudly, the WF116/slo
    model, never a silently dead plane."""
    with pytest.raises(ValueError):
        fleet.TelemetryAgent("127.0.0.1:1", host="h", outbox=0)
    with pytest.raises(ValueError):
        fleet.TelemetryAgent("not-an-endpoint", host="h")


# ----------------------------------------------------- name registries


def test_telemetry_gauge_names_lockstep():
    assert set(names.TELEMETRY_GAUGES) == set(metrics_mod._TELEMETRY_HELP)
    assert set(names.FLEET_GAUGES) == set(fleet._FLEET_HELP)


def test_fleet_journal_events_registered():
    for ev in ("telemetry_connect", "telemetry_lost", "fleet_host_join",
               "fleet_host_leave"):
        assert ev in names.JOURNAL_EVENTS, ev


def test_snapshot_schema_stamp():
    """Every registry snapshot carries the schema version — the merge
    fold's provenance source."""
    reg = metrics_mod.MetricsRegistry("t")
    assert reg.snapshot()["schema"] == dh.SNAPSHOT_SCHEMA


# ------------------------------------------------------- schema provenance


def test_merge_flags_mixed_schema():
    """A mixed-schema fleet is FLAGGED, never silently folded: the merged
    view keeps the newest schema + the per-host map."""
    a, b = _snap(1), _snap(1)
    b["schema"] = dh.SNAPSHOT_SCHEMA + 1
    out = dh.merge_snapshots([a, b], hosts=["h0", "h1"])
    assert out["schema"] == dh.SNAPSHOT_SCHEMA + 1
    assert out["schema_mismatch"] == {"h0": dh.SNAPSHOT_SCHEMA,
                                      "h1": dh.SNAPSHOT_SCHEMA + 1}
    same = dh.merge_snapshots([_snap(1), _snap(1)], hosts=["h0", "h1"])
    assert "schema_mismatch" not in same
    assert same["schema"] == dh.SNAPSHOT_SCHEMA


# ------------------------------------------------------------ loopback


def test_agent_aggregator_loopback(tmp_path):
    """One agent, one aggregator, loopback TCP: frames land, the fleet dir
    is Reporter-schema (load_snapshots/load_journal read it unchanged),
    and nothing drops against a live aggregator."""
    out = str(tmp_path / "fleet")
    agg = fleet.FleetAggregator("127.0.0.1:0", out, max_skew_s=0.2)
    agg.start()
    agent = fleet.TelemetryAgent(agg.endpoint, host="h0", outbox=8)
    agent.start()
    try:
        for i in range(5):
            agent.offer(_snap(i))
            time.sleep(0.05)
        deadline = time.monotonic() + 5.0
        while (agg.stats()["frames_received"] < 5
               and time.monotonic() < deadline):
            time.sleep(0.02)
    finally:
        st = agent.stats()
        agent.close()
        agg.stop()
    assert st["frames_sent"] == 5 and st["frames_dropped"] == 0
    assert st["connected"] == 1
    latest, series = dh.load_snapshots(out)
    assert latest["merged_from"] == 1
    assert latest["fleet"]["frames_received"] == 5
    assert latest["fleet"]["frames_torn"] == 0
    assert latest["queues"]["a->b"] == 4 % 3
    assert len(series) == agg.stats()["ticks"]
    events = [e["event"] for e in dh.load_journal(out)]
    assert "fleet_host_join" in events and "fleet_host_leave" in events
    assert os.path.exists(os.path.join(out, "metrics.prom"))
    prom = open(os.path.join(out, "metrics.prom")).read()
    assert "windflow_fleet_hosts_seen" in prom
    assert "windflow_fleet_frames_received" in prom


def test_aggregator_survives_torn_and_garbage(tmp_path):
    """A client that sends garbage then dies must not wedge the
    aggregator; a subsequent well-formed host still aggregates."""
    import socket as socket_mod
    out = str(tmp_path / "fleet")
    agg = fleet.FleetAggregator("127.0.0.1:0", out, max_skew_s=0.2)
    agg.start()
    try:
        _, host, port = fleet.parse_endpoint(agg.endpoint)
        sk = socket_mod.create_connection((host, port), timeout=2)
        sk.sendall(b"NOT A FRAME AT ALL\n" * 4)
        sk.close()
        agent = fleet.TelemetryAgent(agg.endpoint, host="h0", outbox=8)
        agent.start()
        agent.offer(_snap(0))
        deadline = time.monotonic() + 5.0
        while (agg.stats()["frames_received"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.02)
        agent.close()
    finally:
        agg.stop()
    assert agg.stats()["frames_received"] == 1
    latest, _series = dh.load_snapshots(out)
    assert latest["merged_from"] == 1


# ------------------------------------------------- live-fleet acceptance


def _spawn_host(endpoint, tag, mon, faults):
    return subprocess.Popen(
        [sys.executable, HOST_DRIVER, endpoint, tag, mon,
         "1" if faults else "0"],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, env=dict(os.environ, JAX_PLATFORMS="cpu"))


def test_live_fleet_acceptance(tmp_path):
    """THE fleet acceptance loop: 3 real host processes stream their
    Reporter ticks to one in-test aggregator; queue.stall chaos on ONE
    host drives the FLEET latency SLO OK→WARN→PAGE→OK over the merged
    view; exactly one manifest-committed fleet bundle lands whose
    correlation.json blames exactly that host; wf_slo.py honors its
    1-on-burning / 0-after-recovery contract over the aggregator's own
    artifacts; and no host drops a frame against a live aggregator."""
    agg_dir = str(tmp_path / "fleet")
    agg = fleet.FleetAggregator("127.0.0.1:0", agg_dir,
                                specs=slomod.resolve_specs(LAT_SPEC),
                                max_skew_s=0.3, cooldown_s=60.0)
    agg.start()
    procs = []
    try:
        for i in range(3):
            procs.append(_spawn_host(agg.endpoint, f"host{i}",
                                     str(tmp_path / f"mon{i}"), i == 0))
        outs = [p.communicate(timeout=240) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        agg.stop()
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, err[-2000:]
        ok = [ln for ln in out.splitlines() if ln.startswith("FLEET-HOST-OK")]
        assert ok, out
        fields = dict(kv.split("=") for kv in ok[0].split()[1:])
        assert fields["rows"] == "420"          # chaos never loses a batch
        assert fields["dropped"] == "0"         # live aggregator: no drops
        assert int(fields["sent"]) >= 3

    # the merged fleet SLO walked OK -> WARN -> PAGE -> OK
    series = [json.loads(ln) for ln in
              open(os.path.join(agg_dir, "snapshots.jsonl"))]
    codes = [s.get("slo", {}).get("latency", {}).get("code")
             for s in series]
    walk = [c for i, c in enumerate(codes) if i == 0 or codes[i - 1] != c]
    assert 2 in walk, walk                      # paged
    assert walk[-1] == 0, walk                  # recovered
    assert series[-1]["slo"]["latency"]["pages"] == 1
    assert series[-1]["merged_from"] >= 1
    assert series[-1]["fleet"]["hosts_seen"] == 3
    assert series[-1]["fleet"]["frames_torn"] == 0

    # exactly ONE committed fleet bundle, correlating exactly host0
    bundles, torn = slomod.list_incidents(agg_dir)
    assert len(bundles) == 1 and not torn
    man = bundles[0]
    assert man["slo"] == "latency" and not man.get("missing")
    assert "correlation.json" in man["files"]
    corr = json.load(open(os.path.join(man["path"], "correlation.json")))
    assert corr["fleet_slo"] == "latency"
    assert corr["worst_host"] == "host0"
    by_host = {h["host"]: h for h in corr["hosts"]}
    assert set(by_host) == {"host0", "host1", "host2"}
    assert by_host["host0"]["correlated"] is True
    assert by_host["host1"]["correlated"] is False
    assert by_host["host2"]["correlated"] is False
    # the fleet bundle POINTS at each host's own artifacts
    assert by_host["host0"]["mon_dir"].endswith("mon0")

    # host journal records were re-emitted host-tagged into the fleet
    # events file: host0's page is visible at the fleet, named
    fleet_events = dh.load_journal(agg_dir)
    host_pages = [e for e in fleet_events
                  if e.get("event") == "slo_page" and e.get("host")]
    assert host_pages and all(e["host"] == "host0" for e in host_pages)
    joins = {e.get("host") for e in fleet_events
             if e.get("event") == "fleet_host_join"}
    assert joins == {"host0", "host1", "host2"}

    # wf_slo.py exit contract OVER THE AGGREGATOR DIR: the burn prefix
    # (through the first PAGE tick) exits 1; the full recovered series
    # exits 0 — the fleet dir is a plain monitoring dir to the CLI
    first_page = codes.index(2)
    prefix = tmp_path / "prefix"
    prefix.mkdir()
    with open(prefix / "snapshots.jsonl", "w") as f:
        for s in series[:first_page + 1]:
            f.write(json.dumps(s) + "\n")
    specf = tmp_path / "spec.json"
    specf.write_text(json.dumps(LAT_SPEC))
    env = dict(os.environ, PYTHONPATH=_poisoned_jax_dir(tmp_path))
    r = subprocess.run([sys.executable, WF_SLO_CLI, "--monitoring-dir",
                        str(prefix), "--specs", str(specf)],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 1, r.stdout + r.stderr
    r = subprocess.run([sys.executable, WF_SLO_CLI, "--monitoring-dir",
                        agg_dir, "--specs", str(specf)],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    # ... and the incident ledger renders the FLEET bundle
    assert "correlation.json" not in r.stdout   # ledger names, not files
    assert "latency" in r.stdout

    # wf_top renders the aggregator dir (CI mode), fleet line included
    r = subprocess.run([sys.executable, WF_TOP_CLI, "--monitoring-dir",
                        agg_dir, "--once"],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    assert "fleet:" in r.stdout and "SLOs" in r.stdout


def test_aggregator_death_leaves_tick_cadence_alone(tmp_path):
    """Kill the aggregator mid-run: the host's Reporter keeps its cadence
    (the offer is a deque append, never a socket wait), the run completes,
    and the host's own artifacts land whole."""
    agg_dir = str(tmp_path / "fleet")
    mon = str(tmp_path / "mon")
    agg = fleet.FleetAggregator("127.0.0.1:0", agg_dir, max_skew_s=0.3)
    agg.start()
    p = _spawn_host(agg.endpoint, "host0", mon, faults=True)
    try:
        deadline = time.monotonic() + 120.0
        while (agg.stats()["frames_received"] < 2
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert agg.stats()["frames_received"] >= 2
    finally:
        agg.stop()                       # mid-run kill
    out, err = p.communicate(timeout=240)
    assert p.returncode == 0, err[-2000:]
    ok = [ln for ln in out.splitlines() if ln.startswith("FLEET-HOST-OK")]
    assert ok and "rows=420" in ok[0]
    # the host's own monitoring kept ticking after the aggregator died
    snap = json.load(open(os.path.join(mon, "snapshot.json")))
    host_series = [json.loads(ln) for ln in
                   open(os.path.join(mon, "snapshots.jsonl"))]
    assert len(host_series) >= 10       # the chaos phase alone spans ~50
    tel = snap["telemetry"]
    assert tel["frames_sent"] >= 2
    # frames offered after the death were counted, never waited on
    assert tel["frames_sent"] + tel["frames_dropped"] < len(host_series) + 2


# ------------------------------------------------ off-path hermeticity


def _run_q3(driver, monitoring=False):
    src, ops = make_query("q3_enrich_join", 512)
    rows = []

    def cb(view):
        if view is None:
            return
        rows.append((np.asarray(view["key"]).tolist(),
                     np.asarray(view["id"]).tolist(),
                     np.asarray(view["ts"]).tolist()))
    sink = wf.Sink(cb)
    if driver == "plain":
        wf.Pipeline(src, ops, sink, batch_size=64,
                    monitoring=monitoring).run()
    else:
        g = wf.PipeGraph(batch_size=64, monitoring=monitoring)
        mp = g.add_source(src)
        for op in ops:
            mp.add(op)
        mp.add_sink(sink)
        if driver == "graph":
            g.run()
        elif driver == "graph-threaded":
            g.run(threaded=True)
        elif driver == "graph-supervised":
            g.run_supervised(checkpoint_every=2, backoff_base=0.001,
                             backoff_cap=0.01)
    return rows


@pytest.mark.parametrize("driver", ["plain", "graph", "graph-threaded",
                                    "graph-supervised"])
def test_telemetry_on_results_byte_identical(tmp_path, driver):
    """telemetry= on (streaming to a LIVE loopback aggregator) must not
    change a single result byte through any of the four drivers — the
    plane is Reporter-thread work only."""
    base = _run_q3(driver)
    agg = fleet.FleetAggregator("127.0.0.1:0",
                                str(tmp_path / f"fleet-{driver}"),
                                max_skew_s=0.2)
    agg.start()
    try:
        cfg = MonitoringConfig(out_dir=str(tmp_path / f"m-{driver}"),
                               interval_s=30.0, telemetry=agg.endpoint)
        on = _run_q3(driver, monitoring=cfg)
    finally:
        agg.stop()
    assert on == base
    # the run's final emit streamed at least one frame
    snap = json.load(open(os.path.join(str(tmp_path / f"m-{driver}"),
                                       "snapshot.json")))
    assert "telemetry" in snap


# WF_TELEMETRY's program-identity pin (formerly an ad-hoc HLO-text
# comparison here) lives in the shared toggle-OFF fingerprint gate:
# tests/test_program_fingerprint.py, TOGGLES["telemetry"].


# ------------------------------------------------------------ WF117 pins


def _plain_pipeline(**kw):
    src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=256,
                    num_keys=4)
    return wf.Pipeline(src, [wf.Map(lambda t: {"v": t.v})],
                       wf.Sink(lambda v: None), batch_size=64, **kw)


def test_wf117_env_on_monitoring_off(monkeypatch):
    from windflow_tpu.analysis import validate
    monkeypatch.setenv("WF_TELEMETRY", "1")
    r = validate(_plain_pipeline())
    assert "WF117" in r.codes() and r.errors
    monkeypatch.setenv("WF_MONITORING", "1")
    monkeypatch.setenv("WF_TELEMETRY_ENDPOINT", "127.0.0.1:9")
    r = validate(_plain_pipeline())
    assert "WF117" not in r.codes()


@pytest.mark.parametrize("cfg_kw,frag", [
    (dict(telemetry="not-an-endpoint"), "does not parse"),
    (dict(telemetry=True), "does not parse"),     # True + no endpoint env
    (dict(telemetry="127.0.0.1:9", telemetry_outbox=0), "outbox"),
])
def test_wf117_bad_configs(tmp_path, cfg_kw, frag):
    from windflow_tpu.analysis import validate
    cfg = MonitoringConfig(out_dir=str(tmp_path / "m"), **cfg_kw)
    r = validate(_plain_pipeline(monitoring=cfg))
    msgs = [d.message for d in r.diagnostics if d.code == "WF117"]
    assert msgs and any(frag in m for m in msgs), msgs


def test_wf117_in_explain_rules():
    from windflow_tpu.analysis.lint import RULES
    assert "WF117" in RULES and RULES["WF117"][0] == "error"


def test_monitor_raises_on_unhonorable_telemetry(tmp_path):
    """The runtime mirror of WF117: Monitor construction raises loudly
    instead of starting a silently dead plane."""
    from windflow_tpu.observability import Monitor
    cfg = MonitoringConfig(out_dir=str(tmp_path / "m"),
                           telemetry="not-an-endpoint")
    with pytest.raises(ValueError):
        Monitor(cfg)


# ------------------------------------------------------------ CLI pins


def test_wf_fleet_cli_contracts(tmp_path):
    env = dict(os.environ, PYTHONPATH=_poisoned_jax_dir(tmp_path))
    r = subprocess.run([sys.executable, WF_FLEET_CLI, "status",
                        "--monitoring-dir", str(tmp_path / "nope")],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 2
    assert "cannot load snapshots" in r.stderr
    r = subprocess.run([sys.executable, WF_FLEET_CLI, "serve",
                        "--listen", "not-an-endpoint",
                        "--out", str(tmp_path / "f")],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 2
    assert "bad --listen endpoint" in r.stderr
    # the loopback selftest is the CI smoke: exit 0, artifacts land
    out = str(tmp_path / "fleet")
    r = subprocess.run([sys.executable, WF_FLEET_CLI, "selftest",
                        "--out", out, "--ticks", "3"],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout
    r = subprocess.run([sys.executable, WF_FLEET_CLI, "status",
                        "--monitoring-dir", out, "--json"],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    data = json.loads(r.stdout)
    assert data["fleet"]["frames_torn"] == 0
    assert data["merged_from"] == 2


def test_wf_top_cli_contracts(tmp_path):
    env = dict(os.environ, PYTHONPATH=_poisoned_jax_dir(tmp_path))
    r = subprocess.run([sys.executable, WF_TOP_CLI, "--monitoring-dir",
                        str(tmp_path / "nope"), "--once"],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 2
    assert "cannot load snapshots" in r.stderr
    # renders a plain (non-fleet) monitoring dir too
    mon = tmp_path / "m"
    mon.mkdir()
    with open(mon / "snapshots.jsonl", "w") as f:
        for i in range(3):
            f.write(json.dumps(_snap(i, over={})) + "\n")
    r = subprocess.run([sys.executable, WF_TOP_CLI, "--monitoring-dir",
                        str(mon), "--once"],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    assert "stages" in r.stdout and "queues" in r.stdout


def test_wf_slo_merge_mode(tmp_path):
    """--merge evaluates the spec set over the offline fleet fold with the
    same exit contract, and flags mixed-schema hosts."""
    env = dict(os.environ, PYTHONPATH=_poisoned_jax_dir(tmp_path))
    dirs = []
    for h, p99 in (("a", 200.0), ("b", 50e3)):   # host b burns
        d = tmp_path / h
        d.mkdir()
        with open(d / "snapshots.jsonl", "w") as f:
            for i in range(8):
                s = _snap(i)
                s["e2e_latency_us"] = {"p99": p99, "p99_tick": p99,
                                       "samples": 8, "samples_tick": 8}
                f.write(json.dumps(s) + "\n")
        dirs.append(str(d))
    specf = tmp_path / "spec.json"
    specf.write_text(json.dumps(LAT_SPEC))
    r = subprocess.run([sys.executable, WF_SLO_CLI, "--merge", *dirs,
                        "--specs", str(specf)],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 1, r.stdout + r.stderr   # merged view burns
    assert "merged 2 host(s)" in r.stdout
    # mixed schema across the merged hosts is flagged in the output
    with open(tmp_path / "a" / "snapshots.jsonl") as f:
        lines = [json.loads(ln) for ln in f]
    for ln in lines:
        ln.pop("schema", None)                      # seed-era host
    with open(tmp_path / "a" / "snapshots.jsonl", "w") as f:
        for ln in lines:
            f.write(json.dumps(ln) + "\n")
    r = subprocess.run([sys.executable, WF_SLO_CLI, "--merge", *dirs,
                        "--specs", str(specf), "--json"],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 1, r.stdout + r.stderr
    data = json.loads(r.stdout)
    assert data["schema_mismatch"] == {"a": 0, "b": dh.SNAPSHOT_SCHEMA}
