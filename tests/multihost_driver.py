"""Per-process driver for the 2-process multihost smoke test (CPU backend).

Run as: ``python tests/multihost_driver.py <coordinator> <num_procs> <proc_id>``
from the repo root (cwd provides the windflow_tpu import — PYTHONPATH must stay
unset in this environment). Each process gets 4 virtual CPU devices; together
they form the DCN×ICI mesh (key axis across processes, dp axis inside).

Two parts, in order:

1. **Shard-local supervision across the process boundary** (always runs):
   each process supervises ITS slice of a 4-shard ``ShardedSupervisor``
   layout over the same logical stream — per-shard recovery domains with a
   shard-kill drill, NO cross-process collectives (that is the point of
   shard-local recovery), so this is a real multi-process code path even on
   platforms whose CPU backend cannot run cross-process computations.
   Prints ``SHARD-OK <n_results> <digest> range=<lo>:<hi> restarts=<n>``.

2. **keyed_all_to_all over DCN** (platform-dependent): the collective
   exchange across the process boundary. On jaxlib builds where
   multiprocess CPU computations are unimplemented this prints
   ``COLLECTIVES-UNSUPPORTED <reason>`` and exits 0 — part 1 already
   exercised the multi-process path, so the test no longer skips.
   Prints ``MULTIHOST-OK <n_received>`` / ``LOSSLESS-OK ...`` when it runs.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

coordinator, num_procs, proc_id = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

from windflow_tpu.parallel import multihost  # noqa: E402 (after platform pin)

# initialize() must run BEFORE any backend query — it probes the distributed
# client handle, not jax.process_count()
assert multihost.initialize(coordinator_address=coordinator,
                            num_processes=num_procs, process_id=proc_id), \
    "initialize() returned False for an explicit multi-process call"

assert jax.process_count() == num_procs, jax.process_count()
assert jax.device_count() == num_procs * 4, jax.device_count()
assert jax.local_device_count() == 4

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

# ---- part 1: shard-local supervision across the process boundary ---------
# This process supervises shards [lo, hi) of the 4-shard layout over the
# SAME logical stream as its peer — per-shard restart budgets, outboxes,
# and a shard-kill drill on the first local shard, all without a single
# cross-process collective (the shard-local recovery contract). The parent
# test unions both processes' result multisets against an unsharded oracle.
import windflow_tpu as wf  # noqa: E402
from windflow_tpu.basic import win_type_t  # noqa: E402
from windflow_tpu.operators.window import WindowSpec  # noqa: E402
from windflow_tpu.runtime.faults import (FaultInjector, FaultPlan,  # noqa: E402
                                         FaultSpec)
from windflow_tpu.runtime.supervisor import SupervisedPipeline  # noqa: E402

SH_TOTAL, SH_KEYS, SH_SHARDS = 240, 8, 4
lo, hi = multihost.process_shard_slice(SH_SHARDS)
assert hi - lo == SH_SHARDS // num_procs, (lo, hi)

got = []


def _collect(view):
    if view is None:
        return
    got.extend(zip(view["key"].tolist(), view["id"].tolist(),
                   np.asarray(view["payload"]).tolist()))


kill = FaultInjector(FaultPlan(
    [FaultSpec("shard.kill", where={"shard": lo}, max_fires=1)], seed=11))
sp = SupervisedPipeline(
    wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
              total=SH_TOTAL, num_keys=SH_KEYS),
    [wf.Win_Seq(lambda wid, it: it.sum("v"),
                WindowSpec(10, 10, win_type_t.TB), num_keys=SH_KEYS)],
    wf.Sink(_collect), batch_size=30, checkpoint_every=2, max_restarts=4,
    backoff_base=0.0, shards=SH_SHARDS, shard_range=(lo, hi), faults=kill)
sp.run()
rep = sp.shard_report()
assert rep[lo]["restarts"] == 1, rep          # the drill recovered locally
assert all(r["restarts"] == 0 for k, r in rep.items() if k != lo), rep
digest = sum((k + 1) * 1_000_003 + (i + 1) * 31 + int(v * 7)
             for k, i, v in got) % (1 << 31)
print(f"SHARD-OK {len(got)} {digest} range={lo}:{hi} "
      f"restarts={rep[lo]['restarts']}")

# ---- part 2: collectives over DCN (platform-dependent) -------------------
#: stderr/exception signatures of a CPU backend that cannot run
#: cross-process computations at all — part 2 then reports unsupported and
#: exits 0 (part 1 already proved the multi-process path)
_COLLECTIVE_UNSUPPORTED = (
    # the ONE precise jaxlib signature — a broad "not implemented" match
    # would let a genuine collectives regression masquerade as a platform
    # gap (the PR 10 quarantine-hardening lesson)
    "Multiprocess computations aren't implemented",
)


def _unsupported(e) -> bool:
    msg = str(e)
    return any(sig.lower() in msg.lower() for sig in _COLLECTIVE_UNSUPPORTED)


from windflow_tpu.parallel.collective import keyed_all_to_all  # noqa: E402

# key axis spans the two hosts over DCN (documented-legal: the keyed exchange
# then rides DCN); dp spans each host's 4 local chips over ICI
mesh = multihost.make_dcn_ici_mesh(dcn_axis="key", ici_axes=("dp",))
assert mesh.devices.shape == (num_procs, 4), mesh.devices.shape
assert mesh.axis_names == ("key", "dp")
# outer axis really spans processes: every column of row i lives on process i
for krow in range(num_procs):
    procs = {d.process_index for d in mesh.devices[krow].flat}
    assert len(procs) == 1, f"DCN row {krow} spans processes {procs}"

def _collectives():
    C = 64                                   # global rows, sharded over the key axis
    exchange = keyed_all_to_all(mesh, axis="key", capacity=C)

    gen = jax.jit(lambda: (jnp.arange(C, dtype=jnp.int32) * 7 % 13,
                           jnp.ones((C,), jnp.bool_),
                           {"v": jnp.arange(C, dtype=jnp.float32)}),
                  out_shardings=(NamedSharding(mesh, P("key")),
                                 NamedSharding(mesh, P("key")),
                                 NamedSharding(mesh, P("key"))))
    keys, valid, payload = gen()
    out_keys, out_valid, out_pay, n_left = exchange(keys, valid, payload)
    # capacity C: complete exchange (n_left is global — read this process's shards)
    assert all(int(np.asarray(s.data).sum()) == 0
               for s in n_left.addressable_shards)

    # every row landed on the key-axis shard that owns its key (owner = key % 2),
    # with its payload riding along
    n_local = 0
    for shard_k, shard_v, shard_p in zip(out_keys.addressable_shards,
                                         out_valid.addressable_shards,
                                         out_pay["v"].addressable_shards):
        coord = np.argwhere(mesh.devices == shard_k.device)
        assert coord.shape == (1, 2), coord
        key_coord = int(coord[0][0])
        kv = np.asarray(shard_k.data)
        vv = np.asarray(shard_v.data)
        pv = np.asarray(shard_p.data)
        assert np.all(kv[vv] % num_procs == key_coord), (key_coord, kv[vv])
        assert np.all(pv[vv] * 7 % 13 == kv[vv])       # payload stayed with its key
        n_local += int(vv.sum())

    # no row lost in the exchange: global count over both processes == C
    from jax.experimental import multihost_utils  # noqa: E402
    total = int(multihost_utils.process_allgather(jnp.asarray(n_local)).sum())
    # every dp member holds a replicated copy of its host's received rows
    assert total == C * 4, (total, C * 4)

    print(f"MULTIHOST-OK {n_local}")

    # -- lossless variant across the same process boundary --------------------------
    # Skewed keys: every row targets owner 1 while the per-(src,dst) lane budget is
    # capacity=2, so each source can ship only 2 of its 8 rows per round and the
    # exchange MUST take multiple rounds — the blocking-bounded-queue semantics
    # (r05: overflow is lossless or loud, never silent) over a real DCN boundary.
    from windflow_tpu.parallel.collective import keyed_all_to_all_lossless  # noqa: E402

    SMALL = 16
    lossless = keyed_all_to_all_lossless(mesh, axis="key", capacity=2)
    gen2 = jax.jit(lambda: (jnp.full((SMALL,), 1, jnp.int32),
                            jnp.ones((SMALL,), jnp.bool_),
                            {"v": jnp.arange(SMALL, dtype=jnp.float32)}),
                   out_shardings=(NamedSharding(mesh, P("key")),
                                  NamedSharding(mesh, P("key")),
                                  NamedSharding(mesh, P("key"))))
    k2, v2, p2 = gen2()
    lk, lv, lp, n_rounds = lossless(k2, v2, p2)
    assert n_rounds > 1, f"skew did not overflow (rounds={n_rounds})"
    # The multi-round concatenation may leave the output partially replicated
    # (documented in keyed_all_to_all_lossless), so per-shard layout asserts are
    # invalid here; validate with LOGICAL global reductions instead — replicated
    # results, identical on both processes, independent of XLA's layout choice.
    chk = jax.jit(lambda k, v, p: (
        jnp.sum(v.astype(jnp.int32)),                  # rows delivered (once each)
        jnp.sum(jnp.where(v, p["v"], 0.0)),            # payload sum rides along
        jnp.all(jnp.where(v, k == 1, True))))          # every live row has key 1
    n_delivered, v_sum, keys_ok = (int(x) if x.ndim == 0 else x
                                   for x in map(np.asarray, chk(lk, lv, lp)))
    assert n_delivered == SMALL, (n_delivered, SMALL)
    assert v_sum == sum(range(SMALL)), v_sum
    assert keys_ok

    print(f"LOSSLESS-OK {n_delivered} rounds={n_rounds}")


try:
    _collectives()
except SystemExit:
    raise
except Exception as e:  # noqa: BLE001 — platform capability probe
    if _unsupported(e):
        line = str(e).splitlines()[0][:160]
        print(f"COLLECTIVES-UNSUPPORTED {line}")
        sys.exit(0)
    raise
