"""Per-process driver for the 2-process multihost smoke test (CPU backend).

Run as: ``python tests/multihost_driver.py <coordinator> <num_procs> <proc_id>``
from the repo root (cwd provides the windflow_tpu import — PYTHONPATH must stay
unset in this environment). Each process gets 4 virtual CPU devices; together
they form the DCN×ICI mesh (key axis across processes, dp axis inside) and run
``keyed_all_to_all`` across the process boundary.

Prints ``MULTIHOST-OK <n_received>`` on success.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

coordinator, num_procs, proc_id = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

from windflow_tpu.parallel import multihost  # noqa: E402 (after platform pin)

# initialize() must run BEFORE any backend query — it probes the distributed
# client handle, not jax.process_count()
assert multihost.initialize(coordinator_address=coordinator,
                            num_processes=num_procs, process_id=proc_id), \
    "initialize() returned False for an explicit multi-process call"

assert jax.process_count() == num_procs, jax.process_count()
assert jax.device_count() == num_procs * 4, jax.device_count()
assert jax.local_device_count() == 4

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from windflow_tpu.parallel.collective import keyed_all_to_all  # noqa: E402

# key axis spans the two hosts over DCN (documented-legal: the keyed exchange
# then rides DCN); dp spans each host's 4 local chips over ICI
mesh = multihost.make_dcn_ici_mesh(dcn_axis="key", ici_axes=("dp",))
assert mesh.devices.shape == (num_procs, 4), mesh.devices.shape
assert mesh.axis_names == ("key", "dp")
# outer axis really spans processes: every column of row i lives on process i
for krow in range(num_procs):
    procs = {d.process_index for d in mesh.devices[krow].flat}
    assert len(procs) == 1, f"DCN row {krow} spans processes {procs}"

C = 64                                   # global rows, sharded over the key axis
exchange = keyed_all_to_all(mesh, axis="key", capacity=C)

gen = jax.jit(lambda: (jnp.arange(C, dtype=jnp.int32) * 7 % 13,
                       jnp.ones((C,), jnp.bool_),
                       {"v": jnp.arange(C, dtype=jnp.float32)}),
              out_shardings=(NamedSharding(mesh, P("key")),
                             NamedSharding(mesh, P("key")),
                             NamedSharding(mesh, P("key"))))
keys, valid, payload = gen()
out_keys, out_valid, out_pay, n_left = exchange(keys, valid, payload)
# capacity C: complete exchange (n_left is global — read this process's shards)
assert all(int(np.asarray(s.data).sum()) == 0
           for s in n_left.addressable_shards)

# every row landed on the key-axis shard that owns its key (owner = key % 2),
# with its payload riding along
n_local = 0
for shard_k, shard_v, shard_p in zip(out_keys.addressable_shards,
                                     out_valid.addressable_shards,
                                     out_pay["v"].addressable_shards):
    coord = np.argwhere(mesh.devices == shard_k.device)
    assert coord.shape == (1, 2), coord
    key_coord = int(coord[0][0])
    kv = np.asarray(shard_k.data)
    vv = np.asarray(shard_v.data)
    pv = np.asarray(shard_p.data)
    assert np.all(kv[vv] % num_procs == key_coord), (key_coord, kv[vv])
    assert np.all(pv[vv] * 7 % 13 == kv[vv])       # payload stayed with its key
    n_local += int(vv.sum())

# no row lost in the exchange: global count over both processes == C
from jax.experimental import multihost_utils  # noqa: E402
total = int(multihost_utils.process_allgather(jnp.asarray(n_local)).sum())
# every dp member holds a replicated copy of its host's received rows
assert total == C * 4, (total, C * 4)

print(f"MULTIHOST-OK {n_local}")
