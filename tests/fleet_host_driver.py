"""Per-process host driver for the live-fleet acceptance test.

Run as::

    python tests/fleet_host_driver.py <endpoint> <host_tag> <mon_dir> <faults>

from the repo root. Runs the test_slo.py chaos geometry through the
monitored ThreadedPipeline with the telemetry plane on, streaming every
Reporter tick to the parent test's in-process FleetAggregator at
``<endpoint>``. ``<faults>`` = 1 injects the queue.stall chaos plan (the
stalled phase that saturates both burn windows, then the healthy tail the
fast window recovers on); 0 runs clean.

Prints ``FLEET-HOST-OK rows=<n> sent=<s> dropped=<d>`` on success — the
parent parses the sentinel and additionally reads this host's own
monitoring artifacts (the telemetry plane must never perturb them).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"

endpoint, host_tag, mon_dir, faults = (sys.argv[1], sys.argv[2],
                                       sys.argv[3], sys.argv[4] == "1")

os.environ["WF_TELEMETRY_HOST"] = host_tag

import json  # noqa: E402

import jax.numpy as jnp  # noqa: E402

import windflow_tpu as wf  # noqa: E402
from windflow_tpu.observability import MonitoringConfig  # noqa: E402
from windflow_tpu.runtime.faults import FaultPlan, FaultSpec  # noqa: E402

spec = [{"name": "latency", "signal": "e2e_p99_ms", "target": 30.0,
         "objective": 0.5, "fast_window": 3, "slow_window": 6,
         "warn_burn": 1.0, "page_burn": 2.0}]
cfg = MonitoringConfig(out_dir=mon_dir, interval_s=0.02, slo=spec,
                       e2e_sample_every=1, telemetry=endpoint)

plan = None
if faults:
    plan = FaultPlan([
        FaultSpec("queue.stall", kind="stall", stall_s=0.05,
                  at=list(range(6, 60))),
        FaultSpec("queue.stall", kind="stall", stall_s=0.002,
                  at=list(range(60, 500))),
    ], seed=3)

src = wf.Source(lambda i: {"v": i.astype(jnp.float32)},
                total=420 * 32, num_keys=4)
rows = []
tp = wf.ThreadedPipeline(
    src, [[wf.Map(lambda t: {"v": t.v * 2})]],
    wf.Sink(lambda v: rows.append(0) if v is not None else None),
    batch_size=32, queue_capacity=2, faults=plan, monitoring=cfg)
tp.run()

with open(os.path.join(mon_dir, "snapshot.json")) as f:
    snap = json.load(f)
tel = snap.get("telemetry") or {}
print(f"FLEET-HOST-OK rows={len(rows)} sent={tel.get('frames_sent', 0)} "
      f"dropped={tel.get('frames_dropped', 0)}", flush=True)
