"""Request-scoped serving observability (PR 20): the wire-to-sink tracing
stamp (t_send/span meta keys, unknown-meta-key forward compat in BOTH
directions, SocketSource wire coordinates), per-tenant latency histograms +
the tenant_e2e_p99_ms SLO signal + fleet federation fold, profile-on-page
(ProfileOnPage through the ONE xprof session guard, engine commit-before-
manifest, config resolution + the WF120 validator), THE loopback acceptance
(a wire-stalled noisy tenant drives its tenant-labelled latency SLO
OK -> WARN -> PAGE -> OK with exactly one profile-bearing bundle while the
quiet tenant never leaves OK), and the four-driver byte-identity pin with
tracing + latency + profile armed."""

import json
import os
import time

import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.analysis import validate
from windflow_tpu.nexmark import make_query
from windflow_tpu.observability import (MetricsRegistry, MonitoringConfig,
                                        TraceConfig, set_journal,
                                        device_health as dh, profiling,
                                        slo as slo_mod, tracing)
from windflow_tpu.serving import (RecordClient, RecordFrameDecoder,
                                  ServingRuntime, SocketSource,
                                  encode_record_frame)
from windflow_tpu.serving import framing as framing_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BATCH = 32
DT = np.dtype([("key", np.int32), ("ts", np.int64), ("v", np.float32)])

_PROFILE_ENVS = ("WF_PROFILE", "WF_PROFILE_WINDOW_MS",
                 "WF_PROFILE_MAX_CAPTURES", "WF_MONITORING", "WF_SLO")


@pytest.fixture(autouse=True)
def _clean_hooks():
    yield
    dh.set_active(None)
    set_journal(None)


def _chunks(n, base=0.0, batch=BATCH):
    out = []
    for i in range(n):
        rec = np.zeros(batch, dtype=DT)
        rec["key"] = np.arange(batch) % 4
        rec["ts"] = np.arange(i * batch, (i + 1) * batch)
        rec["v"] = base + np.arange(i * batch, (i + 1) * batch,
                                    dtype=np.float32)
        out.append(rec)
    return out


def _ops():
    return [wf.Map(lambda t: {"v": t.v * 2.0 + 1.0})]


def _collect(acc):
    def cb(view):
        if view is not None:
            acc.extend(zip(view["id"].tolist(),
                           np.asarray(view["payload"]["v"]).tolist()))
    return cb


# ------------------------------------------------- wire stamp + forward compat


def test_frame_stamp_roundtrip_and_unknown_meta_forward_compat():
    """The no-flag-day pin, both directions: a stamped (new-client) frame
    decodes on any server with the stamp in meta; a frame from a FUTURE
    client carrying meta keys this decoder has never heard of stays fully
    valid; an unstamped (old-client) frame carries NO stamp keys at all."""
    rec = b"r" * 24
    dec = RecordFrameDecoder()
    wire = encode_record_frame(rec, tenant="a", seq=3,
                               t_send=123.25, span="a/3")
    (meta, blob), = dec.feed(wire)
    assert meta["t_send"] == 123.25 and meta["span"] == "a/3"
    assert meta["tenant"] == "a" and meta["seq"] == 3 and blob == rec
    # new-client -> old-server stood in by a future client here: unknown
    # meta keys pass through untouched, never torn
    fut = {"tenant": "a", "seq": 4, "kind": "data", "nbytes": len(rec),
           "t_send": 1.0, "span": "a/4", "hop_count": 3,
           "compression": "none"}
    payload = json.dumps(fut).encode() + b"\n" + rec
    raw = framing_mod.MAGIC + b"%08x" % len(payload) + b"\n" + payload + b"\n"
    (meta2, blob2), = dec.feed(raw)
    assert meta2["hop_count"] == 3 and meta2["compression"] == "none"
    assert blob2 == rec
    # old-client -> new-server: pre-stamp frames have neither key
    (meta3, _), = dec.feed(encode_record_frame(rec, tenant="a", seq=5))
    assert "t_send" not in meta3 and "span" not in meta3
    assert dec.frames_decoded == 3 and dec.frames_torn == 0
    # the client-side kill switch reproduces pre-stamp clients exactly
    assert RecordClient("tcp://127.0.0.1:1").stamp is True
    assert RecordClient("tcp://127.0.0.1:1", stamp=False).stamp is False


def test_socket_source_records_wire_coordinates():
    """Receipt stamping: a stamped client's frame surfaces
    ``last_wire = {seq, t_send, t_recv, span}`` at drive pickup with
    client-before-server wall ordering; an unstamped client still gets the
    receipt half (t_recv) so queue time stays attributable."""
    chunks = _chunks(1)
    for stamp in (True, False):
        src = SocketSource("tcp://127.0.0.1:0", DT, key_field="key",
                           ts_field="ts", num_keys=4).start()
        client = RecordClient(src.endpoint, stamp=stamp)
        t_before = time.time()  # wf-lint: allow[wall-clock] cross-process wire timing needs wall time
        client.send(chunks[0].tobytes(), tenant="a")
        client.send_eos("a")
        client.close()
        wires = []
        for _b in src.batches(BATCH):
            wires.append(dict(src.last_wire or {}))
        src.close()
        assert len(wires) == 1
        w = wires[0]
        t_after = time.time()  # wf-lint: allow[wall-clock] cross-process wire timing needs wall time
        assert w["seq"] == 0
        assert t_before <= w["t_recv"] <= t_after
        if stamp:
            assert w["span"] == "a/0"
            assert t_before <= w["t_send"] <= w["t_recv"]
        else:
            assert w["t_send"] is None and w["span"] is None


# ------------------------------------------------------ per-tenant latency


def test_registry_tenant_latency_rows_and_prometheus():
    reg = MetricsRegistry("g")
    reg.attach_serving(lambda: {"graph": "v1", "swaps_applied": 0,
                                "tenants": {"a": {"offered": 2, "shed": 0}}})
    # latency never sampled: the tenant row keeps its exact PR 18 shape
    snap0 = reg.snapshot()
    assert "e2e_p99_ms" not in snap0["serving"]["tenants"]["a"]
    reg.record_tenant_e2e("a", 0.050, exemplar=0x123)
    snap1 = reg.snapshot()
    row = snap1["serving"]["tenants"]["a"]
    assert row["e2e_samples"] == 1 and row["offered"] == 2
    assert 20.0 < row["e2e_p99_ms"] < 150.0      # log-bucket tolerance, 50 ms
    assert row["e2e_p50_ms"] <= row["e2e_p95_ms"] <= row["e2e_p99_ms"]
    assert row["e2e_p99_exemplar"] == 0x123
    assert "e2e_samples_tick" not in row          # no previous tick yet
    reg.record_tenant_e2e("a", 0.001)
    snap2 = reg.snapshot()
    row2 = snap2["serving"]["tenants"]["a"]
    assert row2["e2e_samples"] == 2 and row2["e2e_samples_tick"] == 1
    # the windowed p99 sees only the fast tail sample — the recovery signal
    assert row2["e2e_p99_tick_ms"] < row2["e2e_p99_ms"]
    text = reg.to_prometheus(snap2)
    assert 'windflow_tenant_e2e_p99_ms{graph="g",tenant="a"}' in text
    assert 'windflow_tenant_e2e_samples{graph="g",tenant="a"} 2' in text


def test_merge_snapshots_folds_tenant_latency():
    """Fleet federation: percentiles fold MAX (a fleet p99 can only be as
    good as its worst host), sample counts sum, the p99 exemplar follows
    the worst host, and rate keeps its MIN sense."""
    def host(p99, samples, ex, offered):
        return {"graph": "g", "operators": [],
                "serving": {"graph": "v1", "tenants": {"a": {
                    "offered": offered, "shed": 0, "shed_tuples": 0,
                    "e2e_p50_ms": p99 / 4, "e2e_p95_ms": p99 / 2,
                    "e2e_p99_ms": p99, "e2e_samples": samples,
                    "e2e_p99_exemplar": ex, "e2e_samples_tick": samples,
                    "e2e_p99_tick_ms": p99, "rate": 8.0}}}}
    m = dh.merge_snapshots([host(20.0, 10, 111, 6), host(50.0, 3, 222, 4)],
                           hosts=["h0", "h1"])
    row = m["serving"]["tenants"]["a"]
    assert row["e2e_p99_ms"] == 50.0 and row["e2e_p99_tick_ms"] == 50.0
    assert row["e2e_p50_ms"] == 12.5 and row["e2e_p95_ms"] == 25.0
    assert row["e2e_samples"] == 13 and row["e2e_samples_tick"] == 13
    assert row["e2e_p99_exemplar"] == 222         # the worst host's exemplar
    assert row["offered"] == 10 and row["rate"] == 8.0


def test_tenant_e2e_signal_windowed_then_cumulative():
    fn, mode = slo_mod.TENANT_SIGNALS["tenant_e2e_p99_ms"]
    assert mode == "max"

    def snap(row):
        return {"serving": {"tenants": {"a": row}}}
    # windowed form preferred once a previous tick exists
    assert fn(snap({"e2e_samples": 9, "e2e_p99_ms": 500.0,
                    "e2e_samples_tick": 3, "e2e_p99_tick_ms": 4.0}),
              {}, "a") == 4.0
    # no traffic this tick: None — the burn windows hold, neither
    # violating nor clearing
    assert fn(snap({"e2e_samples": 9, "e2e_p99_ms": 500.0,
                    "e2e_samples_tick": 0, "e2e_p99_tick_ms": 0.0}),
              {}, "a") is None
    # first tick: cumulative fallback
    assert fn(snap({"e2e_samples": 9, "e2e_p99_ms": 500.0}), {}, "a") == 500.0
    # latency sampling off / ghost tenant
    assert fn(snap({"offered": 3}), {}, "a") is None
    assert fn(snap({"e2e_samples": 9, "e2e_p99_ms": 1.0}), {}, "ghost") is None
    # the signal rides the tenant-spec grammar (tenant= required)
    ok = slo_mod.SLOSpec("lat", "tenant_e2e_p99_ms", target=30.0, tenant="a")
    assert slo_mod.spec_problems(ok) == []
    bad = slo_mod.SLOSpec("lat", "tenant_e2e_p99_ms", target=30.0)
    assert any("tenant" in p for p in slo_mod.spec_problems(bad))


# --------------------------------------------------------- profile-on-page


def _snap_p99(p99_ms, samples=5):
    return {"graph": "t", "operators": [],
            "e2e_latency_us": {"p99": p99_ms * 1e3, "p99_tick": p99_ms * 1e3,
                               "samples": samples, "samples_tick": samples}}


def _lat_spec():
    return slo_mod.SLOSpec(name="latency", signal="e2e_p99_ms", target=30.0,
                           objective=0.5, fast_window=2, slow_window=4,
                           warn_burn=1.0, page_burn=2.0)


def test_engine_commits_profiler_evidence_before_manifest(tmp_path):
    """The SLOEngine.profiler hook: its return value lands as profile.json
    INSIDE the committed bundle (listed in the manifest, which stays LAST);
    a hook that raises degrades to a recorded skip reason, never a failed
    tick or a torn bundle."""
    eng = slo_mod.SLOEngine([_lat_spec()], str(tmp_path / "a"),
                            journal=False, clock=lambda: 0.0)
    seen = []
    eng.profiler = lambda d: (seen.append(d),
                              {"window_ms": 1.0, "logdir": d,
                               "files": [{"name": "x.pb", "bytes": 3}]})[1]
    for _ in range(4):
        eng.observe(_snap_p99(500.0))
    bundles, torn = slo_mod.list_incidents(str(tmp_path / "a"))
    assert len(bundles) == 1 and not torn
    man = bundles[0]
    assert "profile.json" in man["files"] and not man["missing"]
    prof = profiling.load_profile(man["path"])
    assert prof["files"][0]["name"] == "x.pb"
    # the capture target lives INSIDE the bundle directory
    assert seen == [os.path.join(man["path"], "profile")]

    class _Boom:
        def __call__(self, d):
            raise RuntimeError("device went away")
    eng2 = slo_mod.SLOEngine([_lat_spec()], str(tmp_path / "b"),
                             journal=False, clock=lambda: 0.0)
    eng2.profiler = _Boom()
    for _ in range(4):
        eng2.observe(_snap_p99(500.0))
    bundles2, torn2 = slo_mod.list_incidents(str(tmp_path / "b"))
    assert len(bundles2) == 1 and not torn2
    prof2 = profiling.load_profile(bundles2[0]["path"])
    assert "device went away" in prof2["profile_skipped"]


def test_profile_on_page_respects_the_one_session_guard(tmp_path):
    """The one-session-guard satellite: a held ``stats.xprof_trace`` is a
    recorded skip reason (naming the holder) out of ProfileOnPage, and a
    raised RuntimeError out of the programmatic ``profile_window``; skipped
    attempts still count against max_captures (a backend that refuses must
    not be retried on every subsequent page)."""
    from windflow_tpu.stats import xprof_trace
    outer = str(tmp_path / "outer")
    hook = profiling.ProfileOnPage(
        profiling.ProfileConfig(window_ms=1.0, max_captures=2))
    with xprof_trace(outer):
        prof = hook(str(tmp_path / "p1"))
        assert "profile_skipped" in prof
        assert "outer" in prof["profile_skipped"]      # names the holder
        with pytest.raises(RuntimeError, match="already"):
            profiling.profile_window(str(tmp_path / "p2"), window_ms=1.0)
    assert hook.captures == 1
    hook(str(tmp_path / "p3"))                         # attempt 2 of 2
    prof3 = hook(str(tmp_path / "p4"))
    assert prof3["profile_skipped"].startswith("max captures")
    assert hook.captures == 2                          # attempt not spent


def test_profile_config_resolution(monkeypatch, tmp_path):
    for env in _PROFILE_ENVS:
        monkeypatch.delenv(env, raising=False)
    assert profiling.resolve_profile(None) is None
    assert profiling.resolve_profile(False) is None
    assert profiling.resolve_profile(True).window_ms == \
        profiling.DEFAULT_WINDOW_MS
    monkeypatch.setenv("WF_PROFILE", "1")
    monkeypatch.setenv("WF_PROFILE_WINDOW_MS", "7.5")
    monkeypatch.setenv("WF_PROFILE_MAX_CAPTURES", "5")
    cfg = profiling.resolve_profile(None)
    assert cfg.window_ms == 7.5 and cfg.max_captures == 5
    monkeypatch.setenv("WF_PROFILE", "0")
    assert profiling.resolve_profile(None) is None
    with pytest.raises(ValueError):
        profiling.ProfileConfig(window_ms=0.0)
    with pytest.raises(ValueError):
        profiling.ProfileConfig(max_captures=0)
    # structural misconfigurations raise at resolve (the WF118 discipline):
    # profile without the SLO engine, and a window reaching the interval
    for env in ("WF_PROFILE", "WF_PROFILE_WINDOW_MS",
                "WF_PROFILE_MAX_CAPTURES"):
        monkeypatch.delenv(env, raising=False)
    with pytest.raises(ValueError, match="WF120"):
        MonitoringConfig.resolve(MonitoringConfig(
            out_dir=str(tmp_path / "m1"), profile=True))
    with pytest.raises(ValueError, match="WF120"):
        MonitoringConfig.resolve(MonitoringConfig(
            out_dir=str(tmp_path / "m2"), interval_s=0.1, slo=True,
            profile=profiling.ProfileConfig(window_ms=250.0)))
    ok = MonitoringConfig.resolve(MonitoringConfig(
        out_dir=str(tmp_path / "m3"), slo=True,
        profile=profiling.ProfileConfig(window_ms=5.0)))
    assert ok.profile.window_ms == 5.0


def test_validator_reports_wf120(monkeypatch):
    for env in _PROFILE_ENVS:
        monkeypatch.delenv(env, raising=False)
    chunks = _chunks(2)

    def mk():
        return wf.Pipeline(
            wf.RecordSource(lambda: iter(chunks), DT, key_field="key",
                            ts_field="ts", num_keys=4),
            _ops(), wf.Sink(lambda v: None), batch_size=BATCH)
    p = mk()                         # built with a clean env: the validator
    #                                  resolves the CURRENT env at run time
    # WF_PROFILE set while monitoring itself resolves off: dead toggle
    monkeypatch.setenv("WF_PROFILE", "1")
    assert "WF120" in validate(p).codes()
    # monitoring on but the SLO engine off: the config cannot resolve —
    # the validator reports it, and the constructor mirrors it (the WF118
    # discipline: a pipeline built under the bad env refuses loudly)
    monkeypatch.setenv("WF_MONITORING", "1")
    assert "WF120" in validate(p).codes()
    with pytest.raises(ValueError, match="WF120"):
        mk()
    # fully armed (slo on, window under the interval, jax importable): clean
    monkeypatch.setenv("WF_SLO", "1")
    report = validate(mk())
    assert "WF120" not in report.codes()


# ------------------------------------------- THE loopback acceptance loop


def test_acceptance_wire_stalled_tenant_pages_with_profile(tmp_path):
    """THE acceptance loop, wire-to-sink edition: the noisy tenant's frames
    arrive stamped 250 ms in the past (a deterministic wire stall — no
    sleeps), driving ITS tenant-labelled latency SLO OK -> WARN -> PAGE;
    the stall lifting recovers it to OK; exactly one cooldown-limited
    bundle commits WITH the profile artifact; the quiet tenant never leaves
    OK and never sheds; and the flight-recorder report attributes the
    noisy tenant's time to the WIRE segment."""
    mon = str(tmp_path / "mon")
    trace_dir = str(tmp_path / "trace")
    stall_s = 0.25
    spec = dict(signal="tenant_e2e_p99_ms", target=30.0, objective=0.5,
                fast_window=3, slow_window=6, warn_burn=1.0, page_burn=2.0)
    cfg = MonitoringConfig(
        out_dir=mon, interval_s=0.05, e2e_sample_every=1,
        slo=[dict(spec, name="lat-noisy", tenant="noisy"),
             dict(spec, name="lat-quiet", tenant="quiet")],
        profile=profiling.ProfileConfig(window_ms=5.0, max_captures=1))
    got = []
    src = SocketSource("tcp://127.0.0.1:0", DT, key_field="key",
                       ts_field="ts", num_keys=4, replay=128)
    rt = ServingRuntime(
        src, _ops(), wf.Sink(_collect(got)), batch_size=BATCH,
        serving={"tenants": [{"id": "quiet"}, {"id": "noisy"}]},
        monitoring=cfg)
    tracer = tracing.Tracer(TraceConfig(out_dir=trace_dir), "serve").start()
    src.start()
    thread = rt.run_background()
    quiet_client = RecordClient(src.endpoint)
    noisy_sock = framing_mod.connect(src.endpoint)
    quiet_chunks = _chunks(28, base=10_000.0)
    noisy_chunks = _chunks(28, base=0.0)
    try:
        for i in range(28):
            quiet_client.send(quiet_chunks[i].tobytes(), tenant="quiet")
            # first 10 frames: stamped in the PAST — the wire segment
            # carries the stall; then the stall lifts
            t_send = time.time() - (stall_s if i < 10 else 0.0)  # wf-lint: allow[wall-clock] cross-process wire timing needs wall time
            noisy_sock.sendall(encode_record_frame(
                noisy_chunks[i].tobytes(), tenant="noisy", seq=i,
                t_send=t_send, span=f"noisy/{i}"))
            time.sleep(0.06)
        quiet_client.send_eos("quiet")
    finally:
        quiet_client.close()
        noisy_sock.close()
    thread.join(timeout=120.0)
    assert not thread.is_alive()
    if rt.background_error is not None:
        raise rt.background_error
    tracer.finish()

    # every record delivered, transformed, nobody shed
    want = sorted(2.0 * v + 1.0
                  for c in quiet_chunks + noisy_chunks
                  for v in c["v"].tolist())
    assert sorted(v for _, v in got) == want
    rows = rt.serving_section()["tenants"]
    assert rows["quiet"]["shed"] == 0 and rows["noisy"]["shed"] == 0

    series = [json.loads(l)
              for l in open(os.path.join(mon, "snapshots.jsonl"))]
    noisy_states = [s["slo"]["lat-noisy"]["state"]
                    for s in series if "slo" in s]
    quiet_states = [s["slo"]["lat-quiet"]["state"]
                    for s in series if "slo" in s]
    # the tenant label rides the SLO row into every snapshot
    tagged = next(s["slo"]["lat-noisy"] for s in series if "slo" in s)
    assert tagged["tenant"] == "noisy"
    # noisy: strictly OK -> WARN -> PAGE -> OK, no re-page after recovery
    assert noisy_states[0] == "ok"
    i_warn = noisy_states.index("warn")
    i_page = noisy_states.index("page")
    assert i_warn < i_page
    assert noisy_states[-1] == "ok"
    i_ok = noisy_states.index("ok", i_page)
    assert "page" not in noisy_states[i_ok:]
    # quiet: never leaves OK while its neighbor burns
    assert set(quiet_states) == {"ok"}

    # exactly ONE committed bundle, carrying the profile artifact
    bundles, torn = slo_mod.list_incidents(mon)
    assert len(bundles) == 1 and not torn
    man = bundles[0]
    assert man["slo"] == "lat-noisy" and "profile.json" in man["files"]
    prof = profiling.load_profile(man["path"])
    # a CPU/TPU box captures for real; a box whose backend refuses records
    # why — either way the bundle carries the evidence
    assert prof.get("files") or "profile_skipped" in prof

    # the per-tenant latency rows landed in the final snapshot
    snap = json.load(open(os.path.join(mon, "snapshot.json")))
    trow = snap["serving"]["tenants"]
    assert trow["noisy"]["e2e_samples"] > 0
    assert trow["noisy"]["e2e_p99_ms"] > 100.0      # the stall dominates
    assert trow["quiet"]["e2e_p99_ms"] < trow["noisy"]["e2e_p99_ms"]
    assert "e2e_p99_exemplar" in trow["noisy"]

    # wire-to-sink attribution: the report blames the WIRE segment for the
    # noisy tenant, with per-request coordinates joined
    records, meta = tracing.load_flight(trace_dir)
    report = tracing.critical_path_report(records, [], snap, meta)
    assert "per-tenant wire-to-sink attribution" in report
    lines = report.splitlines()
    i = next(idx for idx, l in enumerate(lines) if "tenant 'noisy'" in l)
    block = "\n".join(lines[i:i + 7])
    assert "slowest segment: wire" in block
    assert "seq=" in block
    assert any("tenant 'quiet'" in l for l in lines)


# ------------------------------------------------ four-driver byte identity


def _run_q3(driver, monitoring=False, trace=None):
    src, ops = make_query("q3_enrich_join", 300)
    rows = []

    def cb(view):
        if view is None:
            return
        rows.append((np.asarray(view["key"]).tolist(),
                     np.asarray(view["id"]).tolist(),
                     np.asarray(view["ts"]).tolist()))
    sink = wf.Sink(cb)
    kw = dict(monitoring=monitoring)
    if trace is not None:
        kw["trace"] = trace
    if driver == "plain":
        wf.Pipeline(src, ops, sink, batch_size=64, **kw).run()
    else:
        g = wf.PipeGraph(batch_size=64, **kw)
        mp = g.add_source(src)
        for op in ops:
            mp.add(op)
        mp.add_sink(sink)
        if driver == "graph":
            g.run()
        elif driver == "graph-threaded":
            g.run(threaded=True)
        elif driver == "graph-supervised":
            g.run_supervised(checkpoint_every=2, backoff_base=0.001,
                             backoff_cap=0.01)
    return rows


@pytest.mark.parametrize("driver", ["plain", "graph", "graph-threaded",
                                    "graph-supervised"])
def test_tracing_latency_profile_on_results_byte_identical(tmp_path, driver):
    """tracing + per-request latency sampling + an armed (never-firing)
    profile hook must not change a single result byte through any of the
    four drivers — the whole observability stack is host-side work."""
    base = _run_q3(driver)
    cfg = MonitoringConfig(
        out_dir=str(tmp_path / f"m-{driver}"), interval_s=30.0,
        e2e_sample_every=1,
        slo=[{"name": "lat", "signal": "e2e_p99_ms", "target": 1e9}],
        profile=profiling.ProfileConfig(window_ms=5.0))
    on = _run_q3(driver, monitoring=cfg,
                 trace=TraceConfig(out_dir=str(tmp_path / f"t-{driver}")))
    assert on == base
