"""Seeded chaos suite (runtime/faults.py): deterministic fault plans injected at
every named site across SupervisedPipeline, run_graph_supervised, and
ThreadedPipeline must leave results byte-identical to the fault-free run
(exactly-once under injection), poison batches must dead-letter instead of
exhausting the restart budget, torn checkpoints must fall back to the newest
valid lineage entry, and hangs must surface through the watchdogs."""

import json
import threading

import numpy as np
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import win_type_t
from windflow_tpu.operators.window import WindowSpec
from windflow_tpu.runtime import faults as faults_mod
from windflow_tpu.runtime.faults import (DeadLetterQueue, FaultInjector,
                                         FaultPlan, FaultSpec, InjectedFault)
from windflow_tpu.runtime.pipegraph import PipeGraph
from windflow_tpu.runtime.supervisor import SupervisedPipeline
from windflow_tpu.runtime.threaded import ThreadedPipeline

pytestmark = pytest.mark.chaos

TOTAL, BATCH, K = 200, 25, 4


@pytest.fixture(autouse=True)
def _clean_faults():
    faults_mod.set_active(None)
    faults_mod.reset_counters()
    yield
    faults_mod.set_active(None)


def collect(acc):
    def cb(view):
        if view is None:
            return
        acc.extend(zip(view["id"].tolist(),
                       np.asarray(view["payload"]["v"]).tolist()))
    return cb


def build_map(sink_cb, **kw):
    src = wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
                    total=TOTAL, num_keys=K)
    return SupervisedPipeline(src, [wf.Map(lambda t: {"v": t.v * 2})],
                              wf.Sink(sink_cb), batch_size=BATCH,
                              backoff_base=0.001, backoff_cap=0.02, **kw)


def build_win(sink_cb, **kw):
    src = wf.Source(lambda i: {"v": (i % 13).astype(jnp.float32)},
                    total=TOTAL, num_keys=K)
    op = wf.Win_Seq(lambda wid, it: it.sum("v"),
                    WindowSpec(10, 10, win_type_t.TB), num_keys=K)

    def cb(view):
        if view is None:
            return
        sink_cb.extend(zip(view["key"].tolist(), view["id"].tolist(),
                           np.asarray(view["payload"]).tolist()))
    return SupervisedPipeline(src, [op], wf.Sink(cb), batch_size=BATCH,
                              backoff_base=0.001, backoff_cap=0.02, **kw)


# ---------------------------------------------------------------- plan basics

def test_plan_json_roundtrip_and_env(tmp_path, monkeypatch):
    plan = FaultPlan([FaultSpec("chain.step", at=[3]),
                      FaultSpec("queue.stall", kind="stall", stall_s=0.2,
                                where={"stage": "seg0"})], seed=11)
    clone = FaultPlan.from_json(plan.to_json())
    assert clone.seed == 11
    assert [f.site for f in clone.faults] == ["chain.step", "queue.stall"]
    assert clone.faults[0].at == (3,)
    assert clone.faults[1].where == {"stage": "seg0"}
    # env: inline JSON
    monkeypatch.setenv("WF_FAULT_PLAN", plan.to_json())
    assert [f.site for f in FaultPlan.from_env().faults] == \
        [f.site for f in plan.faults]
    # env: a file path
    p = tmp_path / "plan.json"
    p.write_text(plan.to_json())
    monkeypatch.setenv("WF_FAULT_PLAN", str(p))
    assert FaultPlan.from_env().seed == 11
    monkeypatch.setenv("WF_FAULT_PLAN", "")
    assert FaultPlan.from_env() is None


def test_unknown_site_and_kind_rejected():
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultSpec("not.a.site")
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec("chain.step", kind="meteor")


def test_seeded_probability_is_deterministic():
    plan = FaultPlan([FaultSpec("chain.step", p=0.3, max_fires=None)], seed=7)

    def occurrences(pl):
        inj = FaultInjector(pl)
        fired = []
        for i in range(200):
            try:
                inj.fire("chain.step", pos=i)
            except InjectedFault:
                fired.append(i)
        return fired

    a, b = occurrences(plan), occurrences(FaultPlan.from_json(plan.to_json()))
    assert a == b and 20 < len(a) < 120
    c = occurrences(FaultPlan([FaultSpec("chain.step", p=0.3)], seed=8))
    assert c != a


def test_backoff_decorrelated_jitter_bounds():
    import random
    rng = random.Random(3)
    prev, seen = 0.001, []
    for i in range(5):
        prev = faults_mod.backoff_sleep(rng, prev, 0.001, 0.01, attempt=i)
        seen.append(prev)
    assert all(0.001 <= s <= 0.01 for s in seen)
    ctr = faults_mod.counters()
    assert ctr["backoff_sleeps"] == 5
    assert abs(ctr["backoff_seconds"] - sum(seen)) < 1e-9
    assert faults_mod.backoff_sleep(rng, 1.0, 0.0, 1.0) == 0.0  # disabled


# ------------------------------------------------- SupervisedPipeline chaos

def test_pipeline_chaos_every_site_exactly_once(tmp_path):
    oracle = []
    build_map(collect(oracle)).run()

    got = []
    spill = str(tmp_path / "ckpt.npz")
    plan = FaultPlan([
        FaultSpec("source.next", at=[3]),
        FaultSpec("chain.step", at=[6]),
        FaultSpec("sink.consume", at=[2]),
        FaultSpec("checkpoint.save", at=[3]),
    ], seed=1)
    inj = FaultInjector(plan)
    p = build_map(collect(got), checkpoint_every=2, max_restarts=2,
                  spill_path=spill, faults=inj)
    p.run()
    assert sorted(got) == sorted(oracle), "results lost/duplicated under chaos"
    assert {s for s, *_ in inj.fired} == {"source.next", "chain.step",
                                          "sink.consume", "checkpoint.save"}
    assert p.restarts == 4
    assert faults_mod.counters()["faults_injected"] == 4


def test_pipeline_chaos_windowed_sites_fired(tmp_path):
    oracle = []
    build_win(oracle).run()

    got = []
    plan = FaultPlan([FaultSpec("source.next", at=[5]),
                      FaultSpec("chain.step", at=[2, 9])], seed=2)
    inj = FaultInjector(plan)
    p = build_win(got, checkpoint_every=3, max_restarts=3, faults=inj)
    p.run()
    assert sorted(got) == sorted(oracle)
    assert {s for s, *_ in inj.fired} == {"source.next", "chain.step"}
    assert len(inj.fired) == 3 and p.restarts == 3


def test_pipeline_watchdog_converts_hang_into_recovery():
    oracle = []
    build_map(collect(oracle)).run()

    got = []
    plan = FaultPlan([FaultSpec("chain.step", kind="stall", at=[4],
                                stall_s=0.6)])
    p = build_map(collect(got), checkpoint_every=2, max_restarts=2,
                  step_timeout=0.15, faults=plan)
    p.run()
    assert sorted(got) == sorted(oracle)
    assert p.restarts == 1
    assert faults_mod.counters()["watchdog_timeouts"] == 1


def test_pipeline_poison_batch_quarantined_not_exhausted(tmp_path):
    oracle = []
    build_map(collect(oracle)).run()

    got = []
    spill = str(tmp_path / "dead.jsonl")
    dlq = DeadLetterQueue(spill_path=spill)
    # batch position 5 fails EVERY replay — a deterministic poison batch
    plan = FaultPlan([FaultSpec("chain.step", where={"pos": 5})])
    p = build_map(collect(got), checkpoint_every=4, max_restarts=3,
                  dead_letter=dlq, poison_threshold=3, faults=plan)
    p.run()                                  # must NOT raise RestartExhausted
    poisoned = set(range(5 * BATCH, 6 * BATCH))
    assert sorted(got) == sorted(t for t in oracle if t[0] not in poisoned)
    assert len(dlq) == 1
    entry = dlq.entries[0]
    assert entry["pos"] == 5 and entry["n_valid"] == BATCH
    assert entry["ids"][0] == 5 * BATCH
    assert "InjectedFault" in entry["error"]
    with open(spill) as f:
        lines = [json.loads(l) for l in f if l.strip()]
    assert len(lines) == 1 and lines[0]["pos"] == 5
    assert faults_mod.counters()["dead_letters"] == 1


def test_pipeline_torn_checkpoint_retried_and_lineage_restores(tmp_path):
    oracle = []
    build_win(oracle).run()

    got = []
    spill = str(tmp_path / "lineage.npz")
    plan = FaultPlan([FaultSpec("checkpoint.save", kind="torn", at=[2])])
    p = build_win(got, checkpoint_every=3, max_restarts=2, spill_path=spill,
                  checkpoint_keep=3, faults=plan)
    p.run()
    assert sorted(got) == sorted(oracle)
    assert p.restarts == 1                   # the torn write was retried
    # the lineage holds valid checkpoints; the torn file never made the
    # manifest, so a fresh restore gets the final committed state
    q = build_win([])
    meta = wf.load_chain(q.chain, spill)
    assert meta["batches_done"] == TOTAL // BATCH
    from windflow_tpu.runtime.checkpoint import manifest_path, _read_manifest
    man = _read_manifest(manifest_path(spill))
    assert man is not None and 1 <= len(man["entries"]) <= 3


def test_checkpoint_load_site_fires(tmp_path):
    got = []
    p = build_map(collect(got), checkpoint_every=4,
                  spill_path=str(tmp_path / "c.npz"))
    p.run()
    plan = FaultPlan([FaultSpec("checkpoint.load", at=[1])])
    with faults_mod.activate(FaultInjector(plan)):
        with pytest.raises(InjectedFault):
            wf.load_chain(p.chain, str(tmp_path / "c.npz"))


def test_chaos_run_is_journaled(tmp_path):
    from windflow_tpu.observability import (EventJournal, read_journal,
                                            set_journal)
    path = str(tmp_path / "events.jsonl")
    j = EventJournal(path)
    set_journal(j)
    try:
        got = []
        dlq = DeadLetterQueue()
        plan = FaultPlan([FaultSpec("chain.step", at=[3]),
                          FaultSpec("chain.step", where={"pos": 6})])
        p = build_map(collect(got), checkpoint_every=4, max_restarts=3,
                      dead_letter=dlq, poison_threshold=3, faults=plan)
        p.run()
    finally:
        set_journal(None)
        j.close()
    events = read_journal(path)
    names = {e["event"] for e in events}
    assert {"fault_injected", "restore", "checkpoint", "backoff",
            "dead_letter"} <= names
    injected = [e for e in events if e["event"] == "fault_injected"]
    assert all(e["site"] == "chain.step" for e in injected)
    restores = [e for e in events if e["event"] == "restore"
                and e.get("phase") == "end"]
    assert len(restores) == p.restarts


def test_unreadable_position_exhausts_instead_of_livelocking():
    """A quarantined position whose READ genuinely fails on every replay must
    exhaust the restart budget loudly (RestartExhausted with the source error
    as __cause__) — only the failure that ARMS the quarantine is budget-free,
    so a deterministic error can never livelock the restore loop."""
    from windflow_tpu.operators.source import GeneratorSource
    from windflow_tpu.runtime.supervisor import RestartExhausted

    def factory():
        def gen():
            for s in range(0, 400, 50):
                if s == 100:                 # chunk 2 unreadable, EVERY replay
                    raise ValueError("corrupt record at offset 100")
                ids = np.arange(s, s + 50, dtype=np.int32)
                yield ({"v": (ids % 13).astype(np.float32)}, ids % 4, ids)
        return gen()

    src = GeneratorSource(factory, {"v": jnp.zeros((), jnp.float32)})
    p = SupervisedPipeline(src, [wf.Map(lambda t: {"v": t.v})],
                           wf.Sink(lambda v: None), batch_size=50,
                           checkpoint_every=2, max_restarts=2,
                           dead_letter=DeadLetterQueue(), poison_threshold=2,
                           backoff_base=0.0)
    with pytest.raises(RestartExhausted) as ei:
        p.run()
    assert isinstance(ei.value.__cause__, ValueError)
    assert p.restarts <= 2 + 2 + 1, "restart loop must be bounded"


# --------------------------------------------------- graph-supervised chaos

def build_graph(win_sink, plain_sink, **kw):
    g = PipeGraph("chaos", batch_size=40)
    a = g.add_source(wf.Source(lambda i: {"v": (i % 9).astype(jnp.float32)},
                               total=240, num_keys=3, name="a"))
    b = g.add_source(wf.Source(lambda i: {"v": (i % 7).astype(jnp.float32)},
                               total=120, num_keys=3, name="b",
                               ts_fn=lambda i: i * 2))
    m = a.merge(b).split(lambda t: t.v % 2 == 0, 2)
    (m.select(1).add(wf.Win_Seq(lambda wid, it: it.sum("v"),
                                WindowSpec(12, 12, win_type_t.CB), num_keys=3))
     .add_sink(wf.Sink(win_sink)))
    m.select(0).add_sink(wf.Sink(plain_sink))
    return g


def graph_collectors():
    wins, plains = [], []

    def win_cb(view):
        if view is None:
            return
        wins.extend(zip(view["key"].tolist(), view["id"].tolist(),
                        np.asarray(view["payload"]).tolist()))

    def plain_cb(view):
        if view is None:
            return
        plains.extend(zip(view["id"].tolist(),
                          np.asarray(view["payload"]["v"]).tolist()))
    return wins, plains, win_cb, plain_cb


def test_graph_chaos_every_site_exactly_once():
    w0, p0, wc0, pc0 = graph_collectors()
    build_graph(wc0, pc0).run()

    w1, p1, wc1, pc1 = graph_collectors()
    g = build_graph(wc1, pc1)
    inj = FaultInjector(FaultPlan([
        FaultSpec("source.next", at=[5]),
        FaultSpec("chain.step", at=[9]),
        FaultSpec("sink.consume", at=[2]),
    ], seed=4))
    g.run_supervised(checkpoint_every=3, max_restarts=2,
                     backoff_base=0.001, backoff_cap=0.02, faults=inj)
    assert g.supervised_restarts == 3
    assert sorted(w1) == sorted(w0) and sorted(p1) == sorted(p0)
    assert {s for s, *_ in inj.fired} == \
        {"source.next", "chain.step", "sink.consume"}
    assert faults_mod.counters()["backoff_sleeps"] >= 3


def test_graph_poison_batch_dead_lettered():
    total, bs = 300, 30
    def mk(sink_cb):
        g = PipeGraph("poison", batch_size=bs)
        src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=total)
        g.add_source(src).add(wf.Map(lambda t: {"v": t.v + 1})) \
            .add_sink(wf.Sink(sink_cb))
        return g

    oracle = []
    mk(collect(oracle)).run()

    got = []
    dlq = DeadLetterQueue()
    g = mk(collect(got))
    g.run_supervised(checkpoint_every=4, max_restarts=3,
                     backoff_base=0.0, dead_letter=dlq, poison_threshold=3,
                     faults=FaultPlan([FaultSpec("chain.step",
                                                 where={"pos": 4})]))
    poisoned = set(range(4 * bs, 5 * bs))
    assert sorted(got) == sorted(t for t in oracle if t[0] not in poisoned)
    assert len(dlq) == 1 and dlq.entries[0]["pos"] == 4
    assert g.supervised_restarts == 3


def test_graph_watchdog_step_timeout_recovers():
    total, bs = 300, 30

    def mk(sink_cb):
        g = PipeGraph("wdg", batch_size=bs)
        src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=total)
        g.add_source(src).add(wf.Map(lambda t: {"v": t.v * 5})) \
            .add_sink(wf.Sink(sink_cb))
        return g

    oracle = []
    mk(collect(oracle)).run()

    got = []
    g = mk(collect(got))
    # the stall dwarfs the timeout; a legitimate step (compile included) must
    # stay far under it, so only the injected hang trips the watchdog — but a
    # slow-CI spurious trip is recovered like any fault, hence >= asserts
    g.run_supervised(checkpoint_every=3, max_restarts=3,
                     backoff_base=0.001, backoff_cap=0.02, step_timeout=2.0,
                     faults=FaultPlan([FaultSpec("chain.step", kind="stall",
                                                 at=[6], stall_s=6.0)]))
    assert g.supervised_restarts >= 1
    assert sorted(got) == sorted(oracle)
    assert faults_mod.counters()["watchdog_timeouts"] >= 1


# --------------------------------------------------------- threaded chaos

def build_threaded(sink_cb, **kw):
    src = wf.Source(lambda i: {"v": i.astype(jnp.float32)}, total=480)
    return ThreadedPipeline(
        src, [[wf.Map(lambda t: {"v": t.v * 3})],
              [wf.Map(lambda t: {"v": t.v + 1})]],
        wf.Sink(sink_cb), batch_size=16, pin=False, **kw)


def test_threaded_stall_detected_results_identical():
    oracle = []
    build_threaded(collect(oracle)).run()

    got = []
    plan = FaultPlan([FaultSpec("queue.stall", kind="stall", stall_s=0.4,
                                where={"stage": "seg0", "pos": 3})])
    tp = build_threaded(collect(got), heartbeat_timeout=0.1, faults=plan)
    tp.run()
    assert sorted(got) == sorted(oracle), "stall must delay, never drop"
    assert "seg0" in tp.watchdog_stale
    assert faults_mod.counters()["watchdog_timeouts"] >= 1


def test_threaded_failing_segment_drains_upstream_and_closes():
    """A dying segment must NOT wedge the source on a full SPSC ring: the
    error path drains to EOS, run() re-raises AFTER closing every operator.
    With queue_capacity=2 and 30 source batches the pre-fix code deadlocked
    here (source blocked in push, join never returned)."""
    closed = []
    got = []
    tp = build_threaded(collect(got), queue_capacity=2,
                        faults=FaultPlan([FaultSpec(
                            "chain.step", where={"stage": "seg0", "pos": 1})]))
    tp.source.close = lambda: closed.append("source")
    tp.sink.close = lambda: closed.append("sink")

    box = {}

    def runner():
        try:
            tp.run()
            box["ok"] = True
        except BaseException as e:          # noqa: BLE001
            box["err"] = e

    t = threading.Thread(target=runner)
    t.start()
    t.join(60)
    assert not t.is_alive(), "threaded run wedged on a failing segment"
    assert isinstance(box.get("err"), InjectedFault)
    assert closed == ["source", "sink"], "close skipped on the failure path"


# ------------------------------------------------------- metrics integration

def test_recovery_counters_flow_into_metrics_and_prometheus():
    got, oracle = [], []
    build_map(collect(oracle)).run()
    p = build_map(collect(got), checkpoint_every=4, max_restarts=2,
                  faults=FaultPlan([FaultSpec("chain.step", at=[3])]))
    p.run()
    assert sorted(got) == sorted(oracle)
    reg = wf.MetricsRegistry("chaos")
    snap = reg.snapshot()
    assert snap["recovery"]["restarts"] == 1
    assert snap["recovery"]["faults_injected"] == 1
    prom = reg.to_prometheus(snap)
    assert 'windflow_recovery_restarts_total{graph="chaos"} 1' in prom
    assert "windflow_recovery_backoff_sleeps_total" in prom
