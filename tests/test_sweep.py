"""Smoke test of the sweep harness (tiny shapes on CPU): every workload runs,
rows are well-formed, markdown renders."""

from windflow_tpu.benchmarks.sweep import render_markdown, run_sweep


def test_sweep_smoke():
    rows = run_sweep(batches=(256,), keyset=(1, 16), steps=3)
    assert len(rows) == 8
    for name, batch, keys, tps in rows:
        assert batch == 256 and keys in (1, 16) and tps > 0
    md = render_markdown(rows, "cpu-test")
    assert md.count("\n| ") == 9 and "map_stateful" in md   # header + 8 rows
