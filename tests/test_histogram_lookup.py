"""MXU histogram (ops/histogram.py) and factored table lookup (ops/lookup.py):
exactness against the scatter/gather reference on random data, including the
locality-violation fallback and ring wrap-around."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from windflow_tpu.ops.histogram import keyed_pane_histogram, _scatter_hist
from windflow_tpu.ops.lookup import table_lookup, _factored_lookup


def ref_hist(key, pane, valid, K, P):
    out = np.zeros((K, P), np.int32)
    for k, p, v in zip(key, pane, valid):
        if v:
            out[k, p % P] += 1
    return out


@pytest.mark.parametrize("C,K,P", [(4096, 7, 64), (8192, 100, 256)])
def test_hist_sorted_ts(C, K, P):
    rng = np.random.default_rng(0)
    key = rng.integers(0, K, C).astype(np.int32)
    # locally-clustered panes: nondecreasing ts
    pane = (np.arange(C) // 97).astype(np.int32) + 5
    valid = rng.random(C) < 0.7
    got = jax.jit(lambda *a: keyed_pane_histogram(*a, K, P))(
        jnp.asarray(key), jnp.asarray(pane), jnp.asarray(valid))
    np.testing.assert_array_equal(np.asarray(got), ref_hist(key, pane, valid, K, P))


def test_hist_wraparound():
    C, K, P = 4096, 5, 32
    rng = np.random.default_rng(1)
    key = rng.integers(0, K, C).astype(np.int32)
    pane = (np.arange(C) // 130 + P - 3).astype(np.int32)   # crosses the ring edge
    valid = np.ones(C, bool)
    got = jax.jit(lambda *a: keyed_pane_histogram(*a, K, P))(
        jnp.asarray(key), jnp.asarray(pane), jnp.asarray(valid))
    np.testing.assert_array_equal(np.asarray(got), ref_hist(key, pane, valid, K, P))


def test_hist_fallback_unordered():
    """Panes scattered randomly violate chunk locality -> scatter fallback, same
    result."""
    C, K, P = 4096, 11, 64
    rng = np.random.default_rng(2)
    key = rng.integers(0, K, C).astype(np.int32)
    pane = rng.integers(0, 1000, C).astype(np.int32)
    valid = rng.random(C) < 0.5
    got = jax.jit(lambda *a: keyed_pane_histogram(*a, K, P))(
        jnp.asarray(key), jnp.asarray(pane), jnp.asarray(valid))
    np.testing.assert_array_equal(np.asarray(got), ref_hist(key, pane, valid, K, P))


def test_hist_odd_capacity_and_empty():
    C, K, P = 1000, 3, 16          # C not a multiple of the chunk -> scatter path
    key = np.zeros(C, np.int32)
    pane = np.zeros(C, np.int32)
    valid = np.zeros(C, bool)
    got = keyed_pane_histogram(jnp.asarray(key), jnp.asarray(pane),
                               jnp.asarray(valid), K, P)
    assert int(jnp.sum(got)) == 0


@pytest.mark.parametrize("K", [100, 1000, 4000])
def test_factored_lookup_int(K):
    rng = np.random.default_rng(3)
    tbl = rng.integers(0, 1 << 20, K).astype(np.int32)
    idx = rng.integers(0, K, 2048).astype(np.int32)
    got = table_lookup(jnp.asarray(tbl), jnp.asarray(idx))
    np.testing.assert_array_equal(np.asarray(got), tbl[idx])


def test_factored_lookup_float():
    rng = np.random.default_rng(4)
    tbl = rng.standard_normal(777).astype(np.float32)
    idx = rng.integers(0, 777, 512).astype(np.int32)
    got = _factored_lookup(jnp.asarray(tbl), jnp.asarray(idx))
    np.testing.assert_array_equal(np.asarray(got), tbl[idx])  # bit-exact selection


def test_lookup_large_int_values_fall_back():
    """Values >= 2^24 are not f32-exact: must take the gather path and stay exact."""
    tbl = np.array([0, (1 << 24) + 1, 5, 7] * 300, np.int32)
    idx = np.array([1, 2, 1199], np.int32)
    got = table_lookup(jnp.asarray(tbl), jnp.asarray(idx))
    np.testing.assert_array_equal(np.asarray(got), tbl[idx])


def test_count_lift_autodetect():
    from windflow_tpu.operators.win_seqffat import _detect_count_lift
    from windflow_tpu.batch import Batch

    b = Batch(key=jnp.zeros(8, jnp.int32), id=jnp.zeros(8, jnp.int32),
              ts=jnp.zeros(8, jnp.int32),
              payload={"v": jnp.zeros(8, jnp.int32)}, valid=jnp.ones(8, bool))
    assert _detect_count_lift(lambda t: jnp.ones((), jnp.int32), b)
    assert not _detect_count_lift(lambda t: t.data["v"], b)
    assert not _detect_count_lift(lambda t: jnp.zeros((), jnp.int32), b)
    assert not _detect_count_lift(lambda t: {"a": jnp.ones(()), "b": jnp.ones(())}, b)


def test_lookup_inf_float_table_falls_back():
    """inf sentinels (running-max identities) must not NaN-poison other rows."""
    tbl = np.full(1024, -np.inf, np.float32)
    tbl[3] = 3.0
    idx = np.array([3, 5], np.int32)
    got = table_lookup(jnp.asarray(tbl), jnp.asarray(idx))
    assert float(got[0]) == 3.0 and np.isneginf(float(got[1]))


def test_hist_many_keys_tiled():
    C, K, P = 4096, 1500, 64          # K > K_TILE exercises key-axis tiling
    rng = np.random.default_rng(5)
    key = rng.integers(0, K, C).astype(np.int32)
    pane = (np.arange(C) // 511).astype(np.int32)
    valid = rng.random(C) < 0.9
    got = jax.jit(lambda *a: keyed_pane_histogram(*a, K, P))(
        jnp.asarray(key), jnp.asarray(pane), jnp.asarray(valid))
    np.testing.assert_array_equal(np.asarray(got), ref_hist(key, pane, valid, K, P))
