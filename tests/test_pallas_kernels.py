"""Pallas kernel tests (interpret mode on the CPU test mesh)."""

import numpy as np
import jax.numpy as jnp

from windflow_tpu.ops.pallas_kernels import masked_window_reduce, ROW_TILE


def test_masked_window_reduce_matches_numpy():
    rng = np.random.default_rng(0)
    W, L = ROW_TILE * 2, 256
    vals = rng.normal(size=(W, L)).astype(np.float32)
    mask = rng.random((W, L)) < 0.5
    got = np.asarray(masked_window_reduce(jnp.asarray(vals), jnp.asarray(mask),
                                          interpret=True))
    expect = np.where(mask, vals, 0).sum(axis=1)
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-4)


def test_masked_window_reduce_fallback_shapes():
    # non-tile-aligned shapes take the XLA fallback path
    vals = jnp.ones((10, 7), jnp.float32)
    mask = jnp.ones((10, 7), bool)
    got = np.asarray(masked_window_reduce(vals, mask))
    np.testing.assert_allclose(got, np.full(10, 7.0))


def test_masked_window_reduce_safe_under_enclosing_jit():
    # Called under an enclosing trace, a Mosaic compile error would surface at
    # the OUTER jit (past the eager try/except) and the trace-time success
    # line would poison _pallas_ok — traced calls must route to XLA and leave
    # the cache untouched.
    import jax
    from windflow_tpu.ops import pallas_kernels as pk

    vals = jnp.ones((ROW_TILE * 2, 128), jnp.float32)
    mask = jnp.ones_like(vals, bool)
    before = dict(pk._pallas_ok)
    got = np.asarray(jax.jit(lambda v, m: masked_window_reduce(v, m))(vals, mask))
    np.testing.assert_allclose(got, np.full(ROW_TILE * 2, 128.0))
    assert pk._pallas_ok == before
