"""Pallas kernel tests (interpret mode on the CPU test mesh)."""

import numpy as np
import jax.numpy as jnp

from windflow_tpu.ops.pallas_kernels import masked_window_reduce, ROW_TILE


def test_masked_window_reduce_matches_numpy():
    rng = np.random.default_rng(0)
    W, L = ROW_TILE * 2, 256
    vals = rng.normal(size=(W, L)).astype(np.float32)
    mask = rng.random((W, L)) < 0.5
    got = np.asarray(masked_window_reduce(jnp.asarray(vals), jnp.asarray(mask),
                                          interpret=True))
    expect = np.where(mask, vals, 0).sum(axis=1)
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-4)


def test_masked_window_reduce_fallback_shapes():
    # non-tile-aligned shapes take the XLA fallback path
    vals = jnp.ones((10, 7), jnp.float32)
    mask = jnp.ones((10, 7), bool)
    got = np.asarray(masked_window_reduce(vals, mask))
    np.testing.assert_allclose(got, np.full(10, 7.0))
