"""Hermetic perf gate (analysis/perfgate.py + scripts/wf_perfgate.py):
the repo gate is green against the checked-in cost pins, the ratchet-down
compare semantics (regression AND stale pins fail), the 0/1/2 CLI exit
contract, proxy coverage over every registered kernel, and the per-stage
cost rows bench.py attaches to captures. Device-free by construction —
everything here runs on the CPU backend."""

import copy
import importlib.util
import json
import os
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from windflow_tpu.analysis import perfgate  # noqa: E402


@pytest.fixture(scope="module")
def measurement():
    """ONE AOT measurement shared by the module (compiles both workloads;
    proxy reps kept minimal for CI wall time)."""
    return perfgate.measure(reps=1)


def _cli_main(argv):
    """scripts/wf_perfgate.py main() in-process (no subprocess: one jax
    import per tier-1 run, not one per exit-code case)."""
    path = os.path.join(ROOT, "scripts", "wf_perfgate.py")
    spec = importlib.util.spec_from_file_location("wf_perfgate_cli", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["wf_perfgate_cli"] = mod
    spec.loader.exec_module(mod)
    return mod.main(argv)


# ------------------------------------------------------------ the repo gate


def test_repo_gate_green_against_checked_in_baseline(measurement):
    """THE tier-1 perf gate: current cost-analysis of the compiled YSB +
    mp-matrix chains matches the pinned baseline within rtol — a fusion
    break / dtype promotion / gather blowup fails here with zero device
    access."""
    findings = perfgate.compare(
        measurement, perfgate.load_baseline(perfgate.baseline_path(ROOT)))
    assert findings == [], json.dumps(findings, indent=1)


def test_measurement_shape(measurement):
    for name in perfgate.WORKLOADS:
        row = measurement["workloads"][name]
        assert row["flops"] > 0 and row["bytes_accessed"] > 0
        assert row["capacity"] == perfgate.WORKLOAD_CAPACITY[name]


def test_proxy_covers_every_registered_kernel(measurement):
    """CPU-proxy microbenchmarks exist (and measured a positive time) for
    every kernel family in names.py::KERNELS — a newly registered kernel
    without a proxy row fails the gate's coverage finding too."""
    from windflow_tpu.observability.names import KERNELS
    for k in KERNELS:
        assert k in measurement["proxy"], k
        assert measurement["proxy"][k]["ns_per_elem"] > 0
    assert perfgate.compare(measurement, {"workloads":
                                          measurement["workloads"],
                                          "proxy": measurement["proxy"]}
                            ) == []


# -------------------------------------------------- compare() semantics


def _synth():
    current = {"workloads": {"ysb": {"flops": 1000.0,
                                     "bytes_accessed": 500.0,
                                     "capacity": 2048}}}
    baseline = copy.deepcopy(current)
    return current, baseline


def test_compare_clean_within_rtol():
    current, baseline = _synth()
    current["workloads"]["ysb"]["flops"] *= 1.01      # inside rtol=0.02
    assert perfgate.compare(current, baseline) == []


def test_compare_regression_fails():
    current, baseline = _synth()
    current["workloads"]["ysb"]["flops"] *= 1.10
    [f] = perfgate.compare(current, baseline)
    assert f["kind"] == "regression" and f["metric"] == "flops"


def test_compare_stale_pin_fails_ratchet_down():
    """An IMPROVEMENT beyond rtol is also a finding: the better number must
    be banked with --update-baseline or the gate would let it erode back."""
    current, baseline = _synth()
    current["workloads"]["ysb"]["bytes_accessed"] *= 0.80
    [f] = perfgate.compare(current, baseline)
    assert f["kind"] == "stale-pin" and "update-baseline" in f["message"]


def test_compare_unpinned_and_stale_workloads_fail():
    current, baseline = _synth()
    current["workloads"]["nexmark"] = {"flops": 1.0, "bytes_accessed": 1.0,
                                       "capacity": 64}
    del baseline["workloads"]["ysb"]
    baseline["workloads"]["retired"] = {"flops": 2.0, "bytes_accessed": 2.0,
                                        "capacity": 64}
    kinds = sorted(f["kind"] for f in perfgate.compare(current, baseline))
    assert kinds == ["stale-workload", "unpinned", "unpinned"]


def test_compare_capacity_drift_fails():
    current, baseline = _synth()
    current["workloads"]["ysb"]["capacity"] = 4096
    [f] = perfgate.compare(current, baseline)
    assert f["kind"] == "capacity-drift"


def test_compare_no_baseline_means_unpinned():
    current, _ = _synth()
    [f] = perfgate.compare(current, None)
    assert f["kind"] == "unpinned"


def test_compare_proxy_advisory_vs_strict():
    current, baseline = _synth()
    from windflow_tpu.observability.names import KERNELS, PERF_PROXY_FAMILIES
    current["proxy"] = {k: {"ns_per_elem": 100.0, "elems": 1}
                        for k in KERNELS + PERF_PROXY_FAMILIES}
    baseline["proxy"] = {"histogram": {"ns_per_elem": 10.0}}
    # default: proxy timings never fail the gate (noisy CI boxes)
    assert perfgate.compare(current, baseline) == []
    strict = perfgate.compare(current, baseline, strict_proxy=True)
    assert [f["kind"] for f in strict] == ["proxy-regression"]


# --------------------------------------------------------- CLI contract


def test_cli_update_baseline_then_green_then_regression(tmp_path,
                                                        monkeypatch,
                                                        capsys):
    """Exit-code contract on a scratch baseline: --update-baseline (0) ->
    clean gate (0) -> doctored pin (1)."""
    bpath = tmp_path / "perfgate_baseline.json"
    monkeypatch.setenv("WF_PERFGATE_BASELINE", str(bpath))
    assert _cli_main(["--update-baseline", "--skip-proxy", "--reps", "1"]) \
        == 0
    assert _cli_main(["--skip-proxy", "--reps", "1"]) == 0
    doc = json.loads(bpath.read_text())
    for row in doc["workloads"].values():
        row["flops"] *= 0.5               # current is now a 2x "regression"
    bpath.write_text(json.dumps(doc))
    assert _cli_main(["--skip-proxy", "--reps", "1"]) == 1
    out = capsys.readouterr().out
    assert "regression" in out


def test_cli_exit_2_on_missing_explicit_baseline(tmp_path, monkeypatch,
                                                 capsys):
    """An explicit WF_PERFGATE_BASELINE pointing nowhere is a BROKEN gate
    (exit 2) — never 'no baseline yet' (the wf_lint.py contract)."""
    monkeypatch.setenv("WF_PERFGATE_BASELINE", str(tmp_path / "typo.json"))
    assert _cli_main(["--skip-proxy"]) == 2
    assert "internal error" in capsys.readouterr().err


# ------------------------------------------------------ per-stage costs


def test_stage_costs_rows_per_operator():
    """analysis/perfgate.py::stage_costs — the rows bench.py attaches next
    to each capture's metrics snapshot: one row per op, flops/bytes
    present, capacities flowed through out_capacity."""
    chain, _step, cap = perfgate.WORKLOADS["mp_matrix"]()
    rows = perfgate.stage_costs(chain, cap)
    assert len(rows) == len(chain.ops)
    for row in rows:
        assert "error" not in row, row
        assert row["flops"] >= 0 and row["bytes_accessed"] > 0
    assert rows[0]["capacity"] == cap


# ------------------------------------------------------- scan dispatch


def test_scan_workload_pinned_and_in_measurement(measurement):
    """The ysb_scan_k8 workload (the K-fused _scan_fn program AOT-lowered)
    is measured and pinned beside the per-batch steps, carrying its K."""
    row = measurement["workloads"]["ysb_scan_k8"]
    assert row["flops"] > 0 and row["bytes_accessed"] > 0
    assert row["k"] == 8 and row["capacity"] == 2048
    pinned = perfgate.load_baseline(perfgate.baseline_path(ROOT))
    assert "ysb_scan_k8" in pinned["workloads"]


def test_scan_body_cost_parity_no_per_step_regression(measurement):
    """XLA's cost model counts a lax.scan body ONCE, so the scanned
    program's flops must match the single chain step's within tolerance —
    fusing K steps into one program must not bloat the per-step program
    (a fusion break inside the scan body fails here)."""
    scan = measurement["workloads"]["ysb_scan_k8"]
    single = perfgate.chain_step_cost("ysb")
    assert scan["flops"] <= single["flops"] * 1.05
    assert scan["flops"] >= single["flops"] * 0.5    # it IS the same body


def test_scan_k_drift_is_a_finding():
    cur = {"workloads": {"ysb_scan_k8": {"flops": 10.0,
                                         "bytes_accessed": 5.0,
                                         "capacity": 2048, "k": 16}}}
    base = {"workloads": {"ysb_scan_k8": {"flops": 10.0,
                                          "bytes_accessed": 5.0,
                                          "capacity": 2048, "k": 8}}}
    [f] = perfgate.compare(cur, base)
    assert f["kind"] == "capacity-drift" and "K changed" in f["message"]


def test_dispatch_proxy_row_and_coverage(measurement):
    """The 'dispatch' proxy family (names.py::PERF_PROXY_FAMILIES) is
    measured — and dropping it is a coverage finding, the KERNELS
    convention."""
    row = measurement["proxy"]["dispatch"]
    assert row["ns_per_elem"] > 0
    assert row["launches"] * row["k"] >= row["batches"]
    pruned = {"workloads": measurement["workloads"],
              "proxy": {k: v for k, v in measurement["proxy"].items()
                        if k != "dispatch"}}
    findings = perfgate.compare(pruned, pruned)
    assert any(f["kind"] == "proxy-coverage" and f["workload"] == "dispatch"
               for f in findings)


def test_dispatch_launch_counts_amortization():
    """push_many issues ONE executable call per K batches (partial tail
    included): launches == ceil(batches / K), measured at the jit boundary
    by wrapping the chain's cached executables — the >= Kx
    fewer-invocations-per-batch claim of the scan dispatcher."""
    import math
    for k, n in ((8, 20), (4, 16), (3, 7)):
        row = perfgate.dispatch_launch_counts(k=k, capacity=256, n_batches=n)
        assert row["batches"] == n
        assert row["launches"] == math.ceil(n / k), row
    # the K=1 degenerate rung is exactly today's per-batch dispatch
    row = perfgate.dispatch_launch_counts(k=1, capacity=256, n_batches=5)
    assert row["launches"] == row["batches"] == 5
