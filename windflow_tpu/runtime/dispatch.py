"""Scan dispatch — device-resident drive loop configuration + accumulator.

The drivers amortize per-tuple overhead by micro-batching, but still pay one
Python-loop dispatch per BATCH: at the projected YSB headline the host loop,
not the chip, is the ceiling (the GPU-First argument of arXiv:2306.11686 —
move the sequential control loop onto the accelerator; the fusion-amortization
argument of arXiv:1305.1183 applied to *dispatch* instead of kernels). Scan
dispatch fuses K consecutive batch steps into ONE compiled device program:
``CompiledChain.push_many`` stacks K same-capacity batches
(``batch.stack_batches``) and runs ``lax.scan`` over the existing per-op
``apply`` step with operator states as carry — one trace and one executable
per (K, capacity), one host dispatch per K batches, byte-identical outputs to
K sequential ``push`` calls.

Two pieces here, both host-side:

- :class:`DispatchConfig` — the ``dispatch=`` argument resolved (the
  ``monitoring=``/``control=``/``faults=`` convention: ``None`` consults
  ``WF_DISPATCH``, off by default; ``WF_DISPATCH_K`` overrides K whenever
  dispatch is on, like ``WF_TRACE_SAMPLE``).
- :class:`MicrobatchAccumulator` — gathers up to K same-capacity batches at a
  driver's ingest boundary. A capacity change flushes the current group first
  (a scanned executable is traced for one (K, capacity) shape), and a bounded
  wall-clock *linger* caps how long a partial group may wait in the pull-free
  drivers (``ThreadedPipeline`` polls ``expired()`` when its input ring runs
  dry) so latency-sensitive runs are not penalized. The pull drivers
  (``Pipeline``/``PipeGraph``/supervised) never wait — the source is
  synchronous, so a partial group only exists at EOS (``drain()``), at a
  capacity switch, or at a supervised checkpoint boundary (the supervised
  driver flushes the accumulator before every commit so the snapshot reflects
  every read position; it ignores ``linger_s`` — wall-clock must not steer
  the replayed stream).

K = 1 is the degenerate pass-through: every group has one batch and the
drivers call today's ``push`` path unchanged.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import List, Optional, Union

from ..control import _state as _cstate


@dataclasses.dataclass
class DispatchConfig:
    """Resolved scan-dispatch settings for one driver run."""

    #: batches fused per device program (1 = today's per-batch dispatch)
    k: int = 8
    #: max wall-clock seconds a PARTIAL group may linger in a pull-free
    #: driver before it is dispatched short (0 = dispatch as soon as the
    #: input ring runs dry). Ignored by the supervised drivers (count-based
    #: flush only — wall-clock must not steer the replayed stream).
    linger_s: float = 0.002
    #: grow the autotuner ladder with a K dimension when the control plane's
    #: autotune is also on (winner persisted in the same TuningCache)
    autotune_k: bool = True
    #: pre-compile the scanned executable for every K rung up front (the
    #: ``CompiledChain.warm`` discipline) so switches never pay a trace
    prewarm: bool = True

    def __post_init__(self):
        if int(self.k) < 1:
            raise ValueError(f"dispatch k must be >= 1, got {self.k}")
        if float(self.linger_s) < 0:
            raise ValueError(
                f"dispatch linger_s must be >= 0, got {self.linger_s}")

    @classmethod
    def resolve(cls, dispatch: Union[None, bool, int, str, dict,
                                     "DispatchConfig"],
                ) -> Optional["DispatchConfig"]:
        """Normalize the user-facing ``dispatch=`` argument; None when off.
        ``None`` consults ``WF_DISPATCH`` (``''``/``'0'`` = off, ``'1'`` =
        defaults, an integer = K, inline JSON / a JSON file path = field
        overrides); ``False``/``0`` force off (every off-spelling agrees);
        ``True`` = defaults; an int = K; a dict = field overrides; a config
        passes through. ``WF_DISPATCH_K`` overrides ``k`` whenever dispatch
        is on."""
        cfg = None
        if dispatch is False:
            return None
        if isinstance(dispatch, DispatchConfig):
            cfg = dispatch
        elif isinstance(dispatch, bool):          # True (False returned above)
            cfg = cls()
        elif isinstance(dispatch, int):
            if dispatch == 0:       # the WF_DISPATCH='0' / False spelling
                return None
            cfg = cls(k=dispatch)
        elif isinstance(dispatch, dict):
            cfg = cls(**dispatch)
        elif isinstance(dispatch, str):
            cfg = cls._from_text(dispatch)
        else:                                     # None: env-driven
            env = os.environ.get("WF_DISPATCH", "")
            if env in ("", "0"):
                return None
            cfg = cls._from_text(env)
        k_env = os.environ.get("WF_DISPATCH_K", "")
        if k_env:
            cfg = dataclasses.replace(cfg, k=int(k_env))
        return cfg

    @classmethod
    def _from_text(cls, text: str) -> "DispatchConfig":
        text = text.strip()
        if text in ("1", "true"):
            return cls()
        if text.isdigit():
            return cls(k=int(text))
        if text and text[0] == "{":
            return cls(**json.loads(text))
        with open(text) as f:                 # a path to a JSON config file
            return cls(**json.load(f))


def fused_push(chain, group: List, label: str) -> List:
    """Run one dispatch group through ``chain`` with per-batch trace spans
    synthesized from the one launch — THE fused-group execution sequence
    every non-supervised driver shares (the supervised drivers keep their own
    variant: spans must open on the driver thread BEFORE the step-watchdog
    worker runs the push). A singleton group delegates to the per-batch
    ``push`` executable (the K=1 degenerate — same trace, same sampling
    path); outputs return in batch order for the caller to deliver."""
    from ..observability import tracing as _tracing
    # K>1: mark every member span with the group size so the trace report
    # apportions the one fused launch across the K trace ids (wf_trace.py's
    # per-batch drill-down stays honest under WF_DISPATCH)
    spans = [_tracing.service(b, label, k=len(group)) for b in group]
    outs = (chain.push_many(group) if len(group) > 1
            else [chain.push(group[0])])
    for b, out, span in zip(group, outs, spans):
        if span is not None:
            span.done()
            _tracing.carry(b, out)
    return outs


def build_k_ladder(k_max: int) -> List[int]:
    """Power-of-two K rungs up to (and always including) ``k_max``,
    ascending with 1 first — the degenerate rung IS today's per-batch push,
    so the tuner can conclude fusion does not pay on this chain."""
    k_max = int(k_max)
    if k_max < 1:
        raise ValueError(f"k_max must be >= 1, got {k_max}")
    rungs = {1, k_max}
    c = 2
    while c < k_max:
        rungs.add(c)
        c *= 2
    return sorted(rungs)


# each accumulator instance is owned by exactly ONE thread — a driver's
# ingest loop, or one segment/pipe body of the threaded drivers — and its
# buffer is plain unlocked state on that basis; the feed/flush surface
# carries thread-role annotations so WF261 fails the gate if any other
# role (reporter, watchdog monitor, pool worker, JAX callback) ever
# reaches a flush path
class MicrobatchAccumulator:  # wf-lint: single-writer[driver, stage]
    """Gather up to K same-capacity batches into dispatch groups.

    ``feed`` returns the groups that became ready (zero, one, or — after a
    capacity change flushed the previous partial group — two). ``expired()``
    + ``take()`` serve the linger path of polling drivers; ``drain()`` the
    EOS / checkpoint-boundary tail; ``clear()`` the supervised restore path
    (replay re-feeds the dropped batches). ``set_k`` actuates an autotuner
    decision at the next group boundary.

    OWNING-THREAD ONLY — statically checked: every group-forming/flushing
    method below is annotated ``thread-role[driver, stage]`` (the step-
    timeout watchdog worker counts as the driver: it runs the step on loan
    while the driver blocks in join, see ``faults.call_with_timeout``)."""

    def __init__(self, k: int, linger_s: float = 0.0, clock=time.monotonic,
                 publish_gauge: bool = True):
        self.k = max(1, int(k))
        self.linger_s = float(linger_s)
        self.clock = clock
        #: whether this accumulator publishes the process-global
        #: dispatch_linger_depth gauge — the single-driver-thread ingest
        #: accumulators do; the per-segment/per-pipe accumulators of the
        #: threaded drivers do NOT (N threads stomping one gauge would report
        #: a random thread's depth, not anything meaningful)
        self.publish_gauge = bool(publish_gauge)
        self._buf: List = []
        self._t0: Optional[float] = None

    def __len__(self) -> int:
        return len(self._buf)

    def set_k(self, k: int) -> None:
        """New group size; takes effect for groups formed from now on (an
        already-buffered partial group completes at whichever bound it hits
        first)."""
        self.k = max(1, int(k))

    def _take(self) -> List:
        group, self._buf = self._buf, []
        self._t0 = None
        if self.publish_gauge:
            _cstate.set_gauge("dispatch_linger_depth", 0)
        return group

    def feed(self, batch) -> List[List]:  # wf-lint: thread-role[driver, stage]
        """One batch in; the list of groups now ready to dispatch."""
        out: List[List] = []
        if self._buf and self._buf[0].capacity != batch.capacity:
            # scanned executables are per-(K, capacity): a capacity switch
            # (rebatcher rung change, EOS-flush odd shapes) dispatches the
            # buffered run short rather than mixing shapes
            out.append(self._take())
        self._buf.append(batch)
        if self._t0 is None:
            self._t0 = self.clock()
        if self.publish_gauge:
            _cstate.set_gauge("dispatch_linger_depth", len(self._buf))
        if len(self._buf) >= self.k:
            out.append(self._take())
        return out

    def expired(self) -> bool:  # wf-lint: thread-role[driver, stage]
        """True when a partial group has lingered past ``linger_s`` (polling
        drivers dispatch it short rather than hold latency hostage)."""
        return (bool(self._buf) and self._t0 is not None
                and self.clock() - self._t0 >= self.linger_s)

    def take(self) -> List:  # wf-lint: thread-role[driver, stage]
        """Pop the current partial group (linger flush)."""
        return self._take()

    def drain(self) -> List:  # wf-lint: thread-role[driver, stage]
        """EOS / checkpoint boundary: the partial tail (< K), possibly []."""
        return self._take() if self._buf else []

    def clear(self) -> None:  # wf-lint: thread-role[driver, stage]
        """Supervised restore: drop buffered batches — replay from the
        committed position re-feeds them."""
        self._buf = []
        self._t0 = None
        if self.publish_gauge:
            _cstate.set_gauge("dispatch_linger_depth", 0)
