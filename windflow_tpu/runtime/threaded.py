"""Threaded pipeline-parallel scheduler over the native SPSC runtime.

The reference runs ONE OS THREAD PER NODE connected by FastFlow lock-free queues
(``ff_pipeline::run()``, ``wf/pipegraph.hpp:1522-1533``); on TPU the per-*operator*
thread model would serialize on the single device queue, so the threaded scheduler
parallelizes at the *segment* level: each pipeline segment (a compiled chain) gets a
host thread that pops micro-batch handles from its input SPSC ring, dispatches its
device program (async — the device pipelines across segments), and pushes the output
handle downstream. The source thread generates/uploads batches; the sink thread
consumes results. Host threads overlap Python dispatch of stage i+1 with device
execution of stage i — the ``was_batch_started`` double-buffering of the reference GPU
nodes (``wf/map_gpu_node.hpp:224-340``) generalized to the whole pipeline.

Thread pinning mirrors the reference default mapping (one core per stage,
disable like NO_DEFAULT_MAPPING with ``pin=False``).
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence

from ..basic import DEFAULT_BATCH_SIZE
from ..native import SPSCQueue, pin_thread
from ..operators.sink import Sink
from ..operators.source import SourceBase
from .pipeline import CompiledChain

_EOS = object()


class ThreadedPipeline:
    """Source -> [segment chains...] -> sink, one host thread per stage."""

    def __init__(self, source: SourceBase, segments: Sequence[Sequence],
                 sink: Optional[Sink] = None, *,
                 batch_size: int = DEFAULT_BATCH_SIZE,
                 queue_capacity: int = 8, pin: bool = True):
        self.source = source
        self.sink = sink
        self.batch_size = batch_size
        self.pin = pin
        spec = source.payload_spec()
        self.chains: List[CompiledChain] = []
        cap = getattr(source, "out_capacity", lambda b: b)(batch_size)
        for seg in segments:
            chain = CompiledChain(list(seg), spec, batch_capacity=cap)
            spec = chain.out_spec
            for op in chain.ops:
                cap = op.out_capacity(cap)
            self.chains.append(chain)
        # queue i feeds chain i; last queue feeds the sink thread
        self.queues = [SPSCQueue(queue_capacity) for _ in range(len(self.chains) + 1)]
        self._errors: List[BaseException] = []

    # -- stage bodies -----------------------------------------------------------------

    def _source_body(self, core: int):
        if self.pin:
            pin_thread(core)
        from .pipeline import record_source_launch
        try:
            for batch in self.source.batches(self.batch_size):
                record_source_launch(self.source, batch)
                self.queues[0].push(batch)
        except BaseException as e:          # noqa: BLE001 — propagated to join
            self._errors.append(e)
        finally:
            self.queues[0].push(_EOS)

    def _segment_body(self, i: int, core: int):
        if self.pin:
            pin_thread(core)
        chain, q_in, q_out = self.chains[i], self.queues[i], self.queues[i + 1]
        try:
            while True:
                ok, item = q_in.pop()
                if not ok:
                    continue
                if item is _EOS:
                    for out in chain.flush():
                        q_out.push(out)
                    break
                q_out.push(chain.push(item))
        except BaseException as e:          # noqa: BLE001
            self._errors.append(e)
        finally:
            q_out.push(_EOS)

    def _sink_body(self, core: int):
        if self.pin:
            pin_thread(core)
        q = self.queues[-1]
        try:
            while True:
                ok, item = q.pop()
                if not ok:
                    continue
                if item is _EOS:
                    break
                if self.sink is not None:
                    self.sink.consume(item)
            if self.sink is not None:
                self.sink.consume(None)
        except BaseException as e:          # noqa: BLE001
            self._errors.append(e)

    # -- run --------------------------------------------------------------------------

    def run(self):
        threads = [threading.Thread(target=self._source_body, args=(0,),
                                    name="wf-source")]
        for i in range(len(self.chains)):
            threads.append(threading.Thread(target=self._segment_body,
                                            args=(i, i + 1), name=f"wf-seg{i}"))
        threads.append(threading.Thread(target=self._sink_body,
                                        args=(len(self.chains) + 1,),
                                        name="wf-sink"))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if self._errors:
            raise self._errors[0]
        for c in self.chains:
            for op in c.ops:
                op.close()            # closing_func per replica (svc_end parity)
        self.source.close()
        if self.sink is not None:
            self.sink.close()
        res = {}
        for c in self.chains:
            res.update(c.result())
        return res
