"""Threaded pipeline-parallel scheduler over the native SPSC runtime.

The reference runs ONE OS THREAD PER NODE connected by FastFlow lock-free queues
(``ff_pipeline::run()``, ``wf/pipegraph.hpp:1522-1533``); on TPU the per-*operator*
thread model would serialize on the single device queue, so the threaded scheduler
parallelizes at the *segment* level: each pipeline segment (a compiled chain) gets a
host thread that pops micro-batch handles from its input SPSC ring, dispatches its
device program (async — the device pipelines across segments), and pushes the output
handle downstream. The source thread generates/uploads batches; the sink thread
consumes results. Host threads overlap Python dispatch of stage i+1 with device
execution of stage i — the ``was_batch_started`` double-buffering of the reference GPU
nodes (``wf/map_gpu_node.hpp:224-340``) generalized to the whole pipeline.

Thread pinning mirrors the reference default mapping (one core per stage,
disable like NO_DEFAULT_MAPPING with ``pin=False``).

Failure hardening (chaos-harness findings):

- a failing stage **drains its input ring to EOS** before exiting, so an
  upstream producer can never block forever on a full ring behind a dead
  consumer (the deadlock the seed code had);
- ``run()`` closes source/ops/sink even when a stage failed, then re-raises
  the first stage error;
- ``heartbeat_timeout`` starts a watchdog thread over per-stage heartbeats: a
  stage that stops beating (hung device step, stalled queue) is journaled as
  ``watchdog_stale`` and counted — a hang becomes a detectable fault instead
  of a silent wedge. Detection only: the threaded driver has no replay
  machinery, supervision lives in ``SupervisedPipeline``. Attribution caveat:
  a stage blocked *pushing* into a full ring behind the stalled stage also
  stops beating, so ``watchdog_stale`` lists the whole blocked chain — the
  root cause is the furthest-downstream stale stage.

Fault-injection sites (``runtime/faults.py``): ``source.next`` per source
batch, ``queue.stall`` per popped item (stall kind = the latency fault the
watchdog must notice), ``chain.step`` per segment push, ``sink.consume`` per
sink delivery.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional, Sequence

from ..basic import DEFAULT_BATCH_SIZE
from ..native import SPSCQueue, pin_thread
from ..observability import journal as _journal
from ..observability import tracing as _tracing
from ..operators.sink import Sink
from ..operators.source import SourceBase
from . import faults as _faults
from .pipeline import CompiledChain

_EOS = object()

#: how long a failed stage keeps draining its input waiting for the upstream
#: EOS marker before giving up (the upstream's ``finally`` always sends one,
#: so this only bounds pathological cases like a killed producer thread)
_DRAIN_TIMEOUT_S = 30.0


def _resolve_edge_capacity(spec, name: str, index: int, default: int = 8) -> int:
    """Per-edge SPSC ring capacity: ``spec`` is one int for every edge (the
    historical behavior), a dict keyed by edge name or index (missing edges
    fall back to the default), or a callable ``(name, index) -> int``."""
    if callable(spec):
        cap = spec(name, index)
    elif isinstance(spec, dict):
        cap = spec.get(name, spec.get(index, default))
    else:
        cap = spec
    cap = int(cap)
    if cap < 1:
        raise ValueError(f"edge {name!r}: queue capacity must be >= 1, got {cap}")
    return cap


class ThreadedPipeline:
    """Source -> [segment chains...] -> sink, one host thread per stage."""

    def __init__(self, source: SourceBase, segments: Sequence[Sequence],
                 sink: Optional[Sink] = None, *,
                 batch_size: int = DEFAULT_BATCH_SIZE,
                 queue_capacity=8, pin: bool = True,
                 heartbeat_timeout: Optional[float] = None, faults=None,
                 prefetch: int = 0, control=None, trace=None, dispatch=None,
                 monitoring=None):
        self.source = source
        self.sink = sink
        #: telemetry opt-in (monitoring= kwarg or WF_MONITORING env — the
        #: Pipeline/PipeGraph convention, previously missing on this
        #: driver): segment chains + SPSC ring-depth gauges registered, e2e
        #: latency sampled source-framing -> sink-receipt across the stage
        #: threads, and the SLO engine riding the Reporter tick
        self._monitoring_arg = monitoring
        # created in run() BEFORE the stage threads start (happens-before
        # via Thread.start); stage bodies only read the reference
        self._monitor = None                # wf-lint: single-writer[driver]
        # (enqueue seq, perf_counter) stamps of SAMPLED source batches: the
        # source stage appends, the sink stage pops its matching receipt —
        # SPSC rings preserve order, so receipt m pairs with enqueue m;
        # deque append/popleft are GIL-atomic, and the two writers never
        # touch the same end
        self._e2e_stamps = collections.deque()  # wf-lint: single-writer[driver, stage]
        #: per-batch causal tracing opt-in (trace= kwarg or WF_TRACE env)
        self._trace_arg = trace
        self._tracer = None
        #: scan dispatch opt-in (dispatch= kwarg or WF_DISPATCH env); each
        #: segment thread gathers up to K popped batches — flushing short on
        #: the bounded linger when its input ring runs dry — and runs them as
        #: ONE compiled scan
        self._dispatch_arg = dispatch
        # resolved in run() BEFORE the stage threads start (happens-before
        # via Thread.start); stages only read
        self._dispatch = None               # wf-lint: single-writer[driver]
        self.batch_size = batch_size
        self.pin = pin
        self.heartbeat_timeout = heartbeat_timeout
        self._faults_arg = faults
        self.prefetch = int(prefetch)   # >0: prefetched (overlapped H2D) ingest
        spec = source.payload_spec()
        self.chains: List[CompiledChain] = []
        cap = getattr(source, "out_capacity", lambda b: b)(batch_size)
        # event-time sub-toggle (WF_MONITORING/WF_MONITORING_EVENT_TIME —
        # this driver has no monitoring= kwarg): geometry-binding, resolved
        # once before the segment chains build their operator states
        from ..observability import event_time_enabled
        et = event_time_enabled(None)
        for seg in segments:
            chain = CompiledChain(list(seg), spec, batch_capacity=cap,
                                  event_time=et)
            # health-ledger stage label (compile journal + device-time
            # attribution): the same per-segment name the flight recorder
            # and ring edges use, so dispatch-bound rows line up with traces
            chain.label = f"seg{len(self.chains)}"
            spec = chain.out_spec
            for op in chain.ops:
                cap = op.out_capacity(cap)
            self.chains.append(chain)
        # queue i feeds chain i; last queue feeds the sink thread. Edges are
        # named so hot edges can be sized independently: ``queue_capacity``
        # is one int (every edge, the historical default), a dict keyed by
        # edge name or index, or a callable ``(name, index) -> int``.
        n = len(self.chains)
        self.edge_names = [("src->seg0" if n else "src->sink")] + \
            [f"seg{i}->" + (f"seg{i + 1}" if i + 1 < n else "sink")
             for i in range(n)]
        self.edge_capacities = {
            name: _resolve_edge_capacity(queue_capacity, name, i)
            for i, name in enumerate(self.edge_names)}
        self.queues = [SPSCQueue(self.edge_capacities[name])
                       for name in self.edge_names]
        #: adaptive control plane (off by default): backpressure governor over
        #: the rings + admission control at the source. Autotuning does not
        #: apply here — each segment chain's capacity is its queue contract.
        from ..control import ControlConfig
        self._control = ControlConfig.resolve(control)
        # governor/_admission are built in run() BEFORE the stage threads
        # start; stage bodies only read the references
        self.governor = None                # wf-lint: single-writer[driver]
        self._admission = None              # wf-lint: single-writer[driver]
        # stage threads append, the driver reads AFTER join() — the join is
        # the memory barrier, list appends are GIL-atomic
        self._errors: List[BaseException] = []  # wf-lint: single-writer[stage]
        # per-stage slot, each written by its own stage thread only; the
        # watchdog reads and tolerates a stale beat (it re-polls)
        self._beats = {}                    # wf-lint: single-writer[stage]
        # set.add per exiting stage; watchdog membership checks are
        # GIL-atomic and a late observation only delays the stale flag
        self._done = set()                  # wf-lint: single-writer[stage]
        self.watchdog_stale: List[str] = [] # stages the watchdog flagged

    def queue_depths(self) -> dict:
        """Live ring depth per edge name (the backpressure signal)."""
        return {name: q.size()
                for name, q in zip(self.edge_names, self.queues)}

    # -- failure path -----------------------------------------------------------------

    def _drain_to_eos(self, q) -> bool:
        """A failed consumer keeps popping its input until the upstream's EOS
        marker arrives — the upstream producer is blocked on a full ring
        otherwise (SPSC ``push`` spins until space) and would never reach its
        own EOS/exit. Returns False only on drain timeout."""
        return _faults.drain_queue_to_sentinel(q, _EOS,
                                               timeout_s=_DRAIN_TIMEOUT_S)

    # -- stage bodies -----------------------------------------------------------------

    def _source_body(self, core: int):
        if self.pin:
            pin_thread(core)
        from .pipeline import record_source_launch
        stage = "source"
        self._beats[stage] = time.monotonic()
        gov, adm = self.governor, self._admission
        try:
            if self.prefetch:
                batches = self.source.batches_prefetched(
                    self.batch_size, self.prefetch,
                    pause_event=gov.pause_event if gov is not None else None)
            else:
                batches = self.source.batches(self.batch_size)
            mon = self._monitor
            n = 0
            n_enq = 0
            for batch in batches:
                self._beats[stage] = time.monotonic()
                _faults.fire("source.next", stage=stage, pos=n)
                record_source_launch(self.source, batch)
                _tracing.ingest(batch, n)
                admitted = (batch,) if adm is None else adm.offer(batch, pos=n)
                for ab in admitted:
                    if gov is not None:
                        # a throttle wait beats the heartbeat: backpressure is
                        # intentional, not a hang the watchdog should flag
                        gov.throttle(heartbeat=lambda: self._beats.__setitem__(
                            stage, time.monotonic()))
                        self._beats[stage] = time.monotonic()
                    if (mon is not None and self.sink is not None
                            and mon.config.should_sample_e2e(n_enq)):
                        # e2e sample: stamp the ENQUEUE index (post-
                        # admission), matched by receipt order at the sink
                        self._e2e_stamps.append((n_enq, time.perf_counter()))
                    _tracing.event(ab, self.edge_names[0], "enq")
                    self.queues[0].push(ab)
                    n_enq += 1
                n += 1
            if adm is not None:
                for ab in adm.drain():      # bounded held tail (drop_oldest)
                    if gov is not None:
                        gov.throttle(heartbeat=lambda: self._beats.__setitem__(
                            stage, time.monotonic()))
                        self._beats[stage] = time.monotonic()
                    self.queues[0].push(ab)
        except BaseException as e:          # noqa: BLE001 — propagated to join
            self._errors.append(e)
        finally:
            self._done.add(stage)
            self.queues[0].push(_EOS)

    def _segment_body(self, i: int, core: int):
        if self.pin:
            pin_thread(core)
        chain, q_in, q_out = self.chains[i], self.queues[i], self.queues[i + 1]
        edge_in, edge_out = self.edge_names[i], self.edge_names[i + 1]
        stage = f"seg{i}"
        self._beats[stage] = time.monotonic()
        eos_seen = False
        dcfg = self._dispatch
        acc = None
        if dcfg is not None and dcfg.k > 1:
            from .dispatch import MicrobatchAccumulator
            # per-segment accumulator: the global linger gauge stays with
            # the single-threaded ingest accumulators (N segment threads
            # stomping one gauge would report a random thread's depth)
            acc = MicrobatchAccumulator(dcfg.k, dcfg.linger_s,
                                        publish_gauge=False)
        from .dispatch import fused_push

        def run_group(group):
            # K popped batches, ONE scan dispatch; per-batch spans + ring
            # records synthesized from the one launch, in pop order
            outs = fused_push(chain, group, stage)
            for out in outs:
                _tracing.event(out, edge_out, "enq")   # no-op untraced
                q_out.push(out)

        try:
            n = 0
            while True:
                self._beats[stage] = time.monotonic()
                ok, item = q_in.pop(spin=256, max_yields=1024)
                if not ok:
                    # input ring ran dry: a lingering partial group goes out
                    # short rather than hold latency hostage
                    if acc is not None and acc.expired():
                        run_group(acc.take())
                    continue
                if item is _EOS:
                    eos_seen = True
                    if acc is not None:
                        tail = acc.drain()      # partial tail < K at EOS
                        if tail:
                            run_group(tail)
                    for out in chain.flush():
                        q_out.push(out)
                    break
                _faults.fire("queue.stall", stage=stage, pos=n)
                _faults.fire("chain.step", stage=stage, pos=n)
                _tracing.event(item, edge_in, "deq")
                if acc is None:
                    run_group([item])
                else:
                    for group in acc.feed(item):
                        run_group(group)
                n += 1
        except BaseException as e:          # noqa: BLE001
            self._errors.append(e)
            if self.governor is not None:
                self.governor.stop()        # a throttled source must not wait
                                            # on a ring this stage will drain
            if not eos_seen:
                self._drain_to_eos(q_in)    # unwedge the upstream producer
        finally:
            self._done.add(stage)
            q_out.push(_EOS)

    def _sink_body(self, core: int):
        if self.pin:
            pin_thread(core)
        q = self.queues[-1]
        stage = "sink"
        self._beats[stage] = time.monotonic()
        eos_seen = False
        try:
            n = 0
            while True:
                self._beats[stage] = time.monotonic()
                ok, item = q.pop(spin=256, max_yields=1024)
                if not ok:
                    continue
                if item is _EOS:
                    eos_seen = True
                    break
                _faults.fire("sink.consume", stage=stage, pos=n)
                _tracing.event(item, self.edge_names[-1], "deq")
                span = _tracing.service(item, stage)
                if self.sink is not None:
                    self.sink.consume(item)
                if span is not None:
                    span.done()
                stamps = self._e2e_stamps
                if stamps and stamps[0][0] == n:
                    # the stamped enqueue reached its receipt: a true
                    # source-framing -> host-receipt sample through every
                    # ring + segment (consume materialized the batch)
                    _seq, t0 = stamps.popleft()
                    self._monitor.registry.record_e2e(
                        time.perf_counter() - t0,
                        exemplar=_tracing.tid_of(item))
                n += 1
            if self.sink is not None:
                self.sink.consume(None)
        except BaseException as e:          # noqa: BLE001
            self._errors.append(e)
            if self.governor is not None:
                self.governor.stop()
            if not eos_seen:
                self._drain_to_eos(q)       # unwedge the upstream producer
        finally:
            self._done.add(stage)

    # -- watchdog ---------------------------------------------------------------------

    def _watchdog_body(self, stop: threading.Event):
        t = self.heartbeat_timeout
        while not stop.wait(min(t / 4.0, 0.05)):
            now = time.monotonic()
            for stage, last in list(self._beats.items()):
                if stage in self._done or stage in self.watchdog_stale:
                    continue
                if now - last > t:
                    self.watchdog_stale.append(stage)
                    _faults.bump("watchdog_timeouts")
                    _journal.record("watchdog_stale", stage=stage,
                                    stalled_s=round(now - last, 3),
                                    timeout_s=t)

    # -- run --------------------------------------------------------------------------

    def run(self):
        injector = _faults.resolve(self._faults_arg)
        from .dispatch import DispatchConfig
        self._dispatch = DispatchConfig.resolve(self._dispatch_arg)
        from ..observability import Monitor, MonitoringConfig, TraceConfig, \
            Tracer
        mcfg = MonitoringConfig.resolve(self._monitoring_arg)
        self._e2e_stamps.clear()            # receipt indices restart at 0
        if mcfg is not None and self._monitor is None:
            self._monitor = Monitor(mcfg,
                                    self.source.getName() + "-threaded")
            reg = self._monitor.registry
            reg.register_operator(self.source)
            for chain in self.chains:
                reg.register_chain(chain.label, chain)
            if self.sink is not None:
                reg.register_operator(self.sink)
            for name, q in zip(self.edge_names, self.queues):
                reg.attach_queue_gauge(name, q.size,
                                       capacity=self.edge_capacities[name])
            self._monitor.start()
        tcfg = TraceConfig.resolve(self._trace_arg)
        if tcfg is not None and self._tracer is None:
            self._tracer = Tracer(tcfg,
                                  self.source.getName() + "-threaded").start()
        cfg = self._control
        if cfg is not None:
            from ..control import admission_from_config, governor_from_config
            self.governor = governor_from_config(cfg)
            if self.governor is not None:
                for name, q in zip(self.edge_names, self.queues):
                    self.governor.watch(name, q.size,
                                        self.edge_capacities[name])
            self._admission = admission_from_config(
                cfg, getattr(self.source, "out_capacity",
                             lambda b: b)(self.batch_size),
                driver="threaded")
        if (self._monitor is not None
                and self._monitor.remediation is not None
                and self._admission is not None):
            # bind the actuators THIS run owns — remediation actions whose
            # actuator stays unbound skip loudly (remediation_skip
            # reason=unbound) instead of guessing.  scale_rate takes the
            # bucket lock, so the Reporter-thread actuation is atomic
            # w.r.t. the source thread's offer()
            adm = self._admission
            self._monitor.remediation.bind(
                "admission_rate",
                lambda a: adm.scale_rate(a.factor, a.floor))
        with _faults.activate(injector):
            try:
                return self._run()
            finally:
                if self._monitor is not None:
                    # final snapshot + journal close; no topology target —
                    # the export models Pipeline/PipeGraph shapes
                    self._monitor.finish()
                if self._tracer is not None:
                    self._tracer.finish()
                if self.governor is not None:
                    # never leave a source wedged in a throttle wait past
                    # teardown (the object stays readable for post-run stats)
                    self.governor.stop()

    def _run(self):
        threads = [threading.Thread(  # wf-lint: thread-role[stage]
            target=self._source_body, args=(0,), name="wf-source")]
        for i in range(len(self.chains)):
            threads.append(threading.Thread(  # wf-lint: thread-role[stage]
                target=self._segment_body, args=(i, i + 1),
                name=f"wf-seg{i}"))
        threads.append(threading.Thread(  # wf-lint: thread-role[stage]
            target=self._sink_body, args=(len(self.chains) + 1,),
            name="wf-sink"))
        stop_watchdog = threading.Event()
        watchdog = None
        if self.heartbeat_timeout:
            watchdog = threading.Thread(  # wf-lint: thread-role[watchdog]
                target=self._watchdog_body,
                args=(stop_watchdog,), daemon=True,
                name="wf-watchdog")
            watchdog.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if watchdog is not None:
            stop_watchdog.set()
            watchdog.join()
        err = self._errors[0] if self._errors else None
        # close EVERYTHING before re-raising (closing_func / svc_end parity
        # must run on the failure path too — the seed skipped close entirely
        # when a stage had failed); a close error surfaces only on clean runs
        for c in self.chains:
            for op in c.ops:
                try:
                    op.close()
                except Exception as ce:     # noqa: BLE001
                    err = err or ce
        try:
            self.source.close()
        except Exception as ce:             # noqa: BLE001
            err = err or ce
        if self.sink is not None:
            try:
                self.sink.close()
            except Exception as ce:         # noqa: BLE001
                err = err or ce
        if err is not None:
            raise err
        res = {}
        for c in self.chains:
            res.update(c.result())
        return res
