"""Checkpoint / resume of pipeline state.

The reference has NO checkpointing (SURVEY §5: all operator state — keyMaps, archives,
FlatFATs — is in-memory and lost at exit). Here every operator's state is a pytree of
device arrays threaded through the compiled step, so checkpointing is structural:
``save_chain`` snapshots each operator's state (plus stream-position metadata) to an
``.npz``; ``load_chain`` restores it. Works for any CompiledChain (and therefore any
Pipeline / PipeGraph segment).
"""

from __future__ import annotations

import json
from typing import Any, Dict

import jax
import numpy as np

from .pipeline import CompiledChain


def _flatten(states) -> Dict[str, np.ndarray]:
    out = {}
    for i, st in enumerate(states):
        leaves, _ = jax.tree.flatten(st)
        for j, leaf in enumerate(leaves):
            out[f"op{i}_leaf{j}"] = np.asarray(leaf)
    return out


def save_chain(chain: CompiledChain, path: str, *, meta: dict = None) -> None:
    arrays = _flatten(chain.states)
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta or {}).encode(), dtype=np.uint8)
    np.savez(path, **arrays)


def load_chain(chain: CompiledChain, path: str) -> dict:
    """Restore states in place; returns the saved metadata dict.

    Legacy compatibility: a checkpoint written before a state dataclass grew a
    trailing field (e.g. Win_SeqFFAT's ``dropped_old`` counter) is short by
    those leaves — registered dataclasses flatten in field order, so the
    missing keys are exactly the tail. Absent leaves keep the chain's
    freshly-initialized value (zeros for counters) instead of raising — the
    same stance as the supervisor's legacy-``wm`` mapping."""
    data = np.load(path)
    present = set(getattr(data, "files", []))
    new_states = []
    for i, st in enumerate(chain.states):
        leaves, treedef = jax.tree.flatten(st)
        have = [f"op{i}_leaf{j}" in present for j in range(len(leaves))]
        # only a missing TRAILING suffix of a present state is the legacy
        # grown-field case; a gap (missing leaf followed by a present one) or
        # an op whose state is entirely absent means a mismatched or truncated
        # checkpoint — keep the loud KeyError for those
        n_present = sum(have)
        if leaves and n_present == 0:
            raise KeyError(
                f"checkpoint {path!r} has no op{i}_leaf* keys for a stateful "
                f"operator — mismatched chain or truncated file")
        if have[n_present:] != [False] * (len(leaves) - n_present):
            j_bad = have.index(False)
            raise KeyError(
                f"checkpoint {path!r} is missing op{i}_leaf{j_bad} but has "
                f"later leaves of op{i} — mismatched chain or truncated file")
        restored = [jax.numpy.asarray(data[f"op{i}_leaf{j}"]) if have[j]
                    else leaves[j] for j in range(len(leaves))]
        new_states.append(jax.tree.unflatten(treedef, restored))
    chain.states = new_states
    raw = data.get("__meta__")
    return json.loads(bytes(raw).decode()) if raw is not None else {}
