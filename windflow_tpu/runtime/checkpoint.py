"""Checkpoint / resume of pipeline state, with durable lineage.

The reference has NO checkpointing (SURVEY §5: all operator state — keyMaps, archives,
FlatFATs — is in-memory and lost at exit). Here every operator's state is a pytree of
device arrays threaded through the compiled step, so checkpointing is structural:
``save_chain`` snapshots each operator's state (plus stream-position metadata) to an
``.npz``; ``load_chain`` restores it. Works for any CompiledChain (and therefore any
Pipeline / PipeGraph segment).

Durability hardening (the chaos-harness findings):

- **Atomic writes**: the ``.npz`` is written to a temp file in the target
  directory and ``os.replace``-d into place — a crash mid-write can never
  leave a torn file under the checkpoint's name.
- **Checksums**: ``__meta__`` carries a per-array sha256 map; ``load_chain``
  verifies every present array before touching the chain (bit-rot and
  tampering fail loudly as :class:`CheckpointCorrupt`, never a silent
  partial restore). Pre-checksum checkpoints load without verification.
- **Lineage** (``keep > 1``): successive saves rotate through
  ``<stem>.<seq>.npz`` files tracked by a ``<stem>.manifest.json`` (atomic,
  with whole-file sha256 per entry, pruned to the last ``keep``);
  ``load_chain`` walks the manifest newest→oldest and restores the newest
  *valid* checkpoint, so one torn/corrupt file degrades to the previous
  commit instead of losing the state entirely.
- ``path`` is resolved ONCE (``.npz`` appended when missing) and used for both
  save and load — ``save_chain("ckpt")`` / ``load_chain("ckpt")`` now agree.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
from typing import Dict, Optional

import jax
import numpy as np

from . import faults as _faults
from ..observability import journal as _journal
from .pipeline import CompiledChain

#: reserved __meta__ keys (stripped from the dict load_chain returns)
_META_SHA = "__sha256__"
_META_SEQ = "__seq__"
#: per-op state-leaf KEY PATHS (jax.tree_util.keystr), written by every
#: save: restore matches leaves BY PATH, so a state layout that grew
#: interleaved fields (the tiered-state lap/ocnt/okey/... keys sort into
#: the middle of the dict flatten order) restores old leaves into the
#: right fields instead of positionally misassigning them
_META_PATHS = "__leafpaths__"


class CheckpointCorrupt(RuntimeError):
    """A checkpoint file is torn, truncated, or fails checksum verification
    (and, for a lineage, no older entry is valid either)."""


def resolve_path(path: str) -> str:
    """THE path normalization, shared by save and load: ``np.savez`` appends
    ``.npz`` when the suffix is missing, so resolve it once up front."""
    path = str(path)
    return path if path.endswith(".npz") else path + ".npz"


def manifest_path(path: str) -> str:
    return resolve_path(path)[:-len(".npz")] + ".manifest.json"


def _flatten(states) -> Dict[str, np.ndarray]:
    out = {}
    for i, st in enumerate(states):
        leaves, _ = jax.tree.flatten(st)
        for j, leaf in enumerate(leaves):
            out[f"op{i}_leaf{j}"] = np.asarray(leaf)
    return out


def _leaf_paths(states) -> Dict[str, list]:
    """``{"op<i>": [keystr, ...]}`` of every state leaf, in flatten order."""
    out = {}
    for i, st in enumerate(states):
        kl, _ = jax.tree_util.tree_flatten_with_path(st)
        out[f"op{i}"] = [jax.tree_util.keystr(p) for p, _leaf in kl]
    return out


def _digest_map(arrays: Dict[str, np.ndarray]) -> Dict[str, str]:
    """Per-array sha256 (dtype + shape + bytes) — per-array so the legacy
    grown-field tolerance (a checkpoint missing TRAILING leaves of a state
    that later grew) keeps working: only present arrays are verified."""
    out = {}
    for k in sorted(arrays):
        h = hashlib.sha256()
        a = np.ascontiguousarray(arrays[k])
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
        out[k] = h.hexdigest()
    return out


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _serialize(arrays: Dict[str, np.ndarray], meta: dict) -> Dict[str, np.ndarray]:
    out = dict(arrays)
    out["__meta__"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    return out


def _to_npz_bytes(arrays: Dict[str, np.ndarray]) -> bytes:
    """Serialize once to memory — the same bytes feed the atomic write AND the
    manifest's whole-file sha256, so a lineage save never re-reads the file it
    just wrote."""
    import io
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _atomic_write_bytes(path: str, raw: bytes) -> None:
    """Write to a temp file in the target directory, then ``os.replace`` —
    readers see the old file or the new file, never a torn one (the
    pre-hardening ``np.savez(path)`` could be interrupted mid-write)."""
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(raw)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _write_torn(path: str, raw: bytes, spec) -> None:
    """Injected torn write: leave HALF the serialized bytes under the real
    checkpoint name (simulating a crashed non-atomic writer / bit rot), then
    raise — what `load_chain` must survive via the lineage fallback."""
    with open(path, "wb") as f:
        f.write(raw[:max(1, len(raw) // 2)])
    raise _faults.InjectedFault(
        spec.message or f"injected torn checkpoint write at {path}")


def _read_manifest(mpath: str) -> Optional[dict]:
    try:
        with open(mpath) as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except (json.JSONDecodeError, OSError):
        return None      # a torn manifest degrades to single-file behavior


def _write_manifest(mpath: str, man: dict) -> None:
    d = os.path.dirname(mpath) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(mpath) + ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(man, f, indent=1)
        os.replace(tmp, mpath)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def save_chain(chain: CompiledChain, path: str, *, meta: dict = None,
               keep: int = 1) -> str:
    """Snapshot ``chain.states`` (+ ``meta``) to ``path`` atomically; returns
    the file actually written.

    ``keep > 1`` enables lineage mode: each save writes a new
    ``<stem>.<seq>.npz`` and updates ``<stem>.manifest.json`` (entries carry a
    whole-file sha256; pruned to the last ``keep`` files). ``load_chain`` on
    the same ``path`` then restores the newest valid entry."""
    return save_states(chain.states, path, meta=meta, keep=keep,
                       extra_arrays=_extra_chain_arrays(chain))


def _extra_chain_arrays(chain: CompiledChain) -> Dict[str, np.ndarray]:
    chain.tier_settle()
    return chain.tier_manifests()


def save_states(states, path: str, *, meta: dict = None, keep: int = 1,
                extra_arrays: Optional[Dict[str, np.ndarray]] = None) -> str:
    """The states-level core of :func:`save_chain` — also the per-shard save
    of :func:`save_sharded` (each shard's state list rides the SAME atomic
    write + per-array sha256 + ``keep=K`` lineage machinery under its own
    file stem, so per-shard lineages fall back independently)."""
    path = resolve_path(path)
    arrays = _flatten(states)
    if extra_arrays:
        arrays.update(extra_arrays)
    full_meta = dict(meta or {})
    full_meta[_META_SHA] = _digest_map(arrays)
    full_meta[_META_PATHS] = _leaf_paths(states)
    spec = _faults.decision("checkpoint.save", path=path)
    if keep <= 1:
        raw = _to_npz_bytes(_serialize(arrays, full_meta))
        if spec is not None:
            if spec.kind == "torn":
                _write_torn(path, raw, spec)
            raise _faults.InjectedFault(
                spec.message or f"injected checkpoint.save fault at {path}")
        _atomic_write_bytes(path, raw)
        _faults.bump("checkpoint_saves")
        return path
    # -- lineage mode ------------------------------------------------------
    mpath = manifest_path(path)
    man = _read_manifest(mpath) or {"version": 1, "stem": os.path.basename(path),
                                    "entries": []}
    entries = man["entries"]
    seq = (entries[-1]["seq"] + 1) if entries else 0
    full_meta[_META_SEQ] = seq
    file = f"{path[:-len('.npz')]}.{seq:06d}.npz"
    raw = _to_npz_bytes(_serialize(arrays, full_meta))
    if spec is not None:
        if spec.kind == "torn":
            # crash mid-write: the torn file exists but never reaches the
            # manifest — exactly the artifact restore must tolerate
            _write_torn(file, raw, spec)
        raise _faults.InjectedFault(
            spec.message or f"injected checkpoint.save fault at {file}")
    _atomic_write_bytes(file, raw)
    entries.append({"file": os.path.basename(file), "seq": seq,
                    "sha256": hashlib.sha256(raw).hexdigest(),
                    # lineage metadata only — never read back on the replay
                    # path, so a wall timestamp cannot skew recovery
                    "wall": time.time(),      # wf-lint: allow[wall-clock]
                    "meta": {k: v for k, v in (meta or {}).items()}})
    while len(entries) > keep:
        old = entries.pop(0)
        try:
            os.unlink(os.path.join(os.path.dirname(path) or ".", old["file"]))
        except OSError:
            pass
    _write_manifest(mpath, man)
    _faults.bump("checkpoint_saves")
    return file


def _restore_file(chain: CompiledChain, path: str,
                  expect_file_sha: Optional[str] = None) -> dict:
    """Verify + restore one checkpoint file in place; returns the user meta."""
    new_states, meta, extra = _load_states_file(
        chain.states, path, expect_file_sha=expect_file_sha,
        tier_ops=getattr(chain, "_tier_ops", ()))
    chain.states = new_states
    # tiered cold tiers: restore from the tier* namespace (a pre-tiering
    # checkpoint has none — the fresh empty store stands, and any in-flight
    # spill copies of the failed attempt are discarded either way)
    chain.tier_restore_manifests(
        {k: v for k, v in extra.items() if k.startswith("tier")})
    return meta


def _load_states_file(states, path: str,
                      expect_file_sha: Optional[str] = None,
                      tier_ops=()) -> tuple:
    """Verify one checkpoint file against a states template and return
    ``(new_states, user_meta, extra_arrays)`` — the states-level core of
    :func:`_restore_file`, shared with the per-shard loads of
    :func:`load_sharded` (``extra_arrays`` carries every non-state array,
    e.g. the ``tier*`` cold-tier manifests).

    Legacy compatibility: a checkpoint written before a state dataclass grew a
    trailing field (e.g. Win_SeqFFAT's ``dropped_old`` counter) is short by
    those leaves — registered dataclasses flatten in field order, so the
    missing keys are exactly the tail. Absent leaves keep the template's
    freshly-initialized value (zeros for counters) instead of raising — the
    same stance as the supervisor's legacy-``wm`` mapping."""
    if expect_file_sha is not None and _file_sha256(path) != expect_file_sha:
        raise CheckpointCorrupt(
            f"checkpoint {path!r} fails its manifest sha256 — torn or corrupt")
    try:
        data = np.load(path)
    except Exception as e:                 # noqa: BLE001 — torn zip/npz
        raise CheckpointCorrupt(
            f"checkpoint {path!r} is unreadable ({type(e).__name__}: {e})"
        ) from e
    raw = data.get("__meta__")
    meta = json.loads(bytes(raw).decode()) if raw is not None else {}
    sha_map = meta.pop(_META_SHA, None)
    paths_map = meta.pop(_META_PATHS, None)
    meta.pop(_META_SEQ, None)
    present = set(getattr(data, "files", []))
    if sha_map:
        for k in sorted(present - {"__meta__"}):
            want = sha_map.get(k)
            if want is not None and _digest_map({k: data[k]})[k] != want:
                raise CheckpointCorrupt(
                    f"checkpoint {path!r}: array {k} fails its sha256 — "
                    f"corrupt data, refusing a silent partial restore")
    new_states = []
    for i, st in enumerate(states):
        leaves, treedef = jax.tree.flatten(st)
        saved_paths = (paths_map or {}).get(f"op{i}")
        if saved_paths is not None:
            # path-aware restore (every modern save): match leaves BY KEY
            # PATH, so a layout that grew interleaved fields (tiered-state
            # lap/okey/... sort into the middle of the dict flatten order)
            # restores each saved leaf into its true field. Fields absent
            # from the file keep their fresh init (tier fields restoring a
            # pre-tiering save); saved fields the chain lacks are skipped
            # (the legacy trailing-leaf tolerance, by name: an event_time-
            # off or untiered chain restoring a richer save keeps exactly
            # the fields it has)
            kl, _ = jax.tree_util.tree_flatten_with_path(st)
            cur = [jax.tree_util.keystr(p) for p, _leaf in kl]
            idx = {p: j for j, p in enumerate(saved_paths)}
            # the positional branch's trailing-tolerance, kept by saved
            # index: a missing TRAILING run of saved arrays is the legacy
            # grown-field case (those fields keep their init); a GAP is a
            # mismatched/tampered file and stays a loud error
            have = [f"op{i}_leaf{j}" in present
                    for j in range(len(saved_paths))]
            n_present = sum(have)
            if have[n_present:] != [False] * (len(saved_paths) - n_present):
                j_bad = have.index(False)
                raise KeyError(
                    f"checkpoint {path!r} is missing op{i}_leaf{j_bad} "
                    f"({saved_paths[j_bad]}) but has later leaves of "
                    f"op{i} — mismatched chain or truncated file")
            restored = [
                jax.numpy.asarray(data[f"op{i}_leaf{idx[p]}"])
                if p in idx and have[idx[p]] else leaves[j]
                for j, p in enumerate(cur)]
            new_states.append(jax.tree.unflatten(treedef, restored))
            continue
        # legacy file (no path map): positional restore. Refuse it for a
        # tiered operator — the tier fields interleave into the flatten
        # order, so positional matching would silently misassign arrays
        if any(j == i for j in tier_ops):
            raise KeyError(
                f"checkpoint {path!r} predates leaf-path metadata and "
                f"op{i} has tiered state — a positional restore would "
                f"misassign fields; re-save the checkpoint (or restore "
                f"into an untiered chain first)")
        have = [f"op{i}_leaf{j}" in present for j in range(len(leaves))]
        # only a missing TRAILING suffix of a present state is the legacy
        # grown-field case; a gap (missing leaf followed by a present one) or
        # an op whose state is entirely absent means a mismatched or truncated
        # checkpoint — keep the loud KeyError for those
        n_present = sum(have)
        if leaves and n_present == 0:
            raise KeyError(
                f"checkpoint {path!r} has no op{i}_leaf* keys for a stateful "
                f"operator — mismatched chain or truncated file")
        if have[n_present:] != [False] * (len(leaves) - n_present):
            j_bad = have.index(False)
            raise KeyError(
                f"checkpoint {path!r} is missing op{i}_leaf{j_bad} but has "
                f"later leaves of op{i} — mismatched chain or truncated file")
        restored = [jax.numpy.asarray(data[f"op{i}_leaf{j}"]) if have[j]
                    else leaves[j] for j in range(len(leaves))]
        new_states.append(jax.tree.unflatten(treedef, restored))
    extra = {k: data[k] for k in present
             if k != "__meta__" and not k.startswith("op")}
    return new_states, meta, extra


def _walk_lineage(path: str, restore_one):
    """THE newest-valid-entry fallback protocol, shared by
    :func:`load_chain` and :func:`load_states`: fire the ``checkpoint.load``
    site, then — when ``path`` has a lineage manifest — try
    ``restore_one(file, expect_sha)`` newest→oldest, journaling skipped
    entries (``checkpoint_invalid``) and the fallback
    (``checkpoint_fallback``); without a manifest, one direct
    ``restore_one(path, None)``."""
    path = resolve_path(path)
    _faults.fire("checkpoint.load", path=path)
    man = _read_manifest(manifest_path(path))
    if man and man.get("entries"):
        d = os.path.dirname(path) or "."
        last_err: Optional[Exception] = None
        skipped = []
        for ent in reversed(man["entries"]):
            f = os.path.join(d, ent["file"])
            try:
                result = restore_one(f, ent.get("sha256"))
            except (CheckpointCorrupt, KeyError, OSError) as e:
                last_err = e
                skipped.append(ent["file"])
                _faults.bump("checkpoint_corrupt_skipped")
                _journal.record("checkpoint_invalid", path=f,
                                error=type(e).__name__)
                continue
            if skipped:
                _faults.bump("checkpoint_fallbacks")
                _journal.record("checkpoint_fallback", restored=ent["file"],
                                skipped=skipped)
            return result
        raise CheckpointCorrupt(
            f"no valid checkpoint in lineage {path!r} "
            f"({len(man['entries'])} entries, all torn/corrupt)") from last_err
    return restore_one(path, None)


def load_states(states, path: str) -> tuple:
    """States-level :func:`load_chain`: restore against a template states
    list, returning ``(new_states, meta)`` with the same lineage-manifest
    newest-valid fallback — each sharded-checkpoint shard walks its OWN
    lineage here, so one shard's torn latest file degrades that shard to its
    previous commit without touching its peers."""
    def restore_one(f, sha):
        new_states, meta, _extra = _load_states_file(states, f,
                                                     expect_file_sha=sha)
        return new_states, meta
    return _walk_lineage(path, restore_one)


# ------------------------------------------------------- sharded checkpoints

#: shards-manifest schema version
_SHARDS_VERSION = 1


def shard_stem(path: str, shard: int) -> str:
    """File stem of one shard's checkpoint (its own atomic-write + lineage
    namespace): ``<stem>.shard<k>`` beside the unsharded ``<stem>.npz``."""
    return resolve_path(path)[:-len(".npz")] + f".shard{int(shard)}"


def shards_manifest_path(path: str, shard_ids=None) -> str:
    """The sharded-checkpoint manifest name. A FULL save (all shards) owns
    ``<stem>.shards.json``; a multi-host SLICE owns a deterministic
    per-slice name (``<stem>.shards.s2-3.json``) so concurrent hosts on a
    shared filesystem can never clobber each other's manifests —
    :func:`load_sharded` merges every ``<stem>.shards*.json`` and verifies
    the union covers the layout."""
    stem = resolve_path(path)[:-len(".npz")]
    if shard_ids is None:
        return stem + ".shards.json"
    ids = sorted(int(i) for i in shard_ids)
    return stem + f".shards.s{ids[0]}-{ids[-1]}.json"


def save_sharded(shard_states, path: str, *, layout: dict,
                 meta: dict = None, keep: int = 1,
                 parallel: bool = True, shard_ids=None) -> dict:
    """Sharded-and-parallel checkpoint: one file (or ``keep=K`` lineage) PER
    SHARD over the existing atomic-write + per-array sha256 machinery, the
    saves fanned out across a thread pool, committed by an atomic
    ``<stem>.shards.json`` manifest written LAST — readers only ever see
    shard files named by a fully-written manifest, so a crash mid-fan-out
    degrades to the previous sharded commit.

    ``layout`` is the serialized :class:`~windflow_tpu.parallel.sharding.
    ShardAssignment` (``to_meta()``) — the layout epoch a restore re-derives
    shard ownership from. Returns the manifest dict written."""
    shard_states = list(shard_states)
    ids = (list(range(len(shard_states))) if shard_ids is None
           else [int(i) for i in shard_ids])
    n = int(layout.get("num_shards", len(shard_states)))
    meta = dict(meta or {})

    def save_one(j):
        return save_states(shard_states[j], shard_stem(path, ids[j]),
                           meta={**meta, "shard": ids[j], "num_shards": n},
                           keep=keep)
    if parallel and len(ids) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=min(len(ids), 8)) as ex:
            files = list(ex.map(save_one, range(len(ids))))
    else:
        files = [save_one(j) for j in range(len(ids))]
    man = {"version": _SHARDS_VERSION, "num_shards": n, "layout": dict(layout),
           "meta": meta,
           "shards": [{"shard": k, "file": os.path.basename(f)}
                      for k, f in zip(ids, files)]}
    # a multi-host slice writes only ITS shards' entries, under a PER-SLICE
    # manifest name — two hosts sharing a filesystem can never clobber each
    # other (last-writer-wins on one file would silently lose half the key
    # space); the full single-host save owns the plain .shards.json
    full = ids == list(range(n))
    _write_manifest(shards_manifest_path(path, None if full else ids), man)
    return man


def load_sharded(template_states, path: str) -> tuple:
    """Restore a :func:`save_sharded` checkpoint: reads the shards manifest,
    then restores each shard against ``template_states`` (fresh per-op init
    values) — verification and lineage fallback run PER SHARD, so one
    shard's corrupt file never forces a global fallback. Returns
    ``(list_of_states_per_shard, layout, meta)``; raises
    :class:`CheckpointCorrupt` when the manifest is missing/torn."""
    import glob
    stem = resolve_path(path)[:-len(".npz")]
    mans = []
    for mp_ in sorted(glob.glob(stem + ".shards*.json")):
        man = _read_manifest(mp_)
        if man and "num_shards" in man:
            mans.append(man)
    if not mans:
        raise CheckpointCorrupt(
            f"no sharded-checkpoint manifest at "
            f"{shards_manifest_path(path)!r} (or any per-slice "
            f"{os.path.basename(stem)}.shards.s*-*.json beside it)")
    n = int(mans[0]["num_shards"])
    layout = dict(mans[0].get("layout", {}))
    for man in mans[1:]:
        if int(man["num_shards"]) != n or dict(man.get("layout", {})) \
                != layout:
            raise CheckpointCorrupt(
                f"sharded-checkpoint manifests under {stem!r} disagree on "
                f"the layout epoch — mixed-generation slices; clear the "
                f"stale manifests and re-save")
    # NEWEST generation first (batches_done, missing -> oldest): a stale
    # per-slice manifest left behind by a deployment-shape switch (slices
    # -> full save, or back) must never override a fresher manifest's
    # entries for the same shards — per shard, the first (newest) manifest
    # naming it wins
    mans.sort(key=lambda m: -(m.get("meta", {}).get("batches_done")
                              if isinstance(m.get("meta", {})
                                            .get("batches_done"), int)
                              else -1))
    entries = {}
    for man in mans:
        for ent in man.get("shards", []):
            entries.setdefault(int(ent["shard"]),
                               (ent, dict(man.get("meta", {}))))
    missing = sorted(set(range(n)) - set(entries))
    if missing:
        raise CheckpointCorrupt(
            f"sharded checkpoint {stem!r} covers only shards "
            f"{sorted(entries)} of {n} — shard(s) {missing} missing "
            f"(a host's slice never committed); refusing a silent "
            f"partial restore")
    d = os.path.dirname(resolve_path(path)) or "."
    out = {}
    shard_meta = {}
    for k in sorted(entries):
        ent, man_meta = entries[k]
        # restore the MANIFEST-NAMED file — the manifest is the commit
        # point, so a shard whose lineage already advanced past it (saves
        # fanned out, crash before the manifest rewrite) must restore the
        # committed generation, not its newest file; only a torn committed
        # file falls back to the shard's own lineage walk
        try:
            states, meta_k, _extra = _load_states_file(
                template_states, os.path.join(d, ent["file"]))
        except (CheckpointCorrupt, KeyError, OSError):
            states, meta_k = load_states(template_states,
                                         shard_stem(path, k))
        meta_k = {kk: v for kk, v in meta_k.items()
                  if kk not in ("shard", "num_shards")}
        # generation cross-check: a shard AHEAD of its manifest is the
        # torn keep=1 fan-out (the overwritten file is the only copy of
        # the new generation and the old one is gone) — loud, with the
        # fix; a shard BEHIND is the legitimate per-shard lineage
        # fallback, surfaced via meta["shard_meta"] for reconciliation
        if (meta_k.get("batches_done") is not None
                and man_meta.get("batches_done") is not None
                and meta_k["batches_done"] > man_meta["batches_done"]):
            raise CheckpointCorrupt(
                f"sharded checkpoint {stem!r}: shard {k} is at "
                f"batches_done={meta_k['batches_done']}, AHEAD of its "
                f"manifest ({man_meta['batches_done']}) — a crash between "
                f"the shard fan-out and the manifest rewrite overwrote "
                f"the committed generation; save with checkpoint_keep >= "
                f"2 so the manifest-named lineage entry survives the "
                f"next fan-out")
        out[k] = states
        shard_meta[k] = meta_k
    meta = dict(mans[0].get("meta", {}))
    meta["shard_meta"] = shard_meta
    return out, layout, meta


# ------------------------------------------------- re-sharding handoff seal

def handoff_path(path: str, shard: int) -> str:
    return resolve_path(path)[:-len(".npz")] + f".handoff{int(shard)}.npz"


def seal_handoff(shard_states, path: str, *, layout: dict,
                 at_pos: int) -> list:
    """Phase 1 of the re-sharding handoff: seal every retiring shard's
    drained state to a ``<stem>.handoff<k>.npz`` manifest (atomic + sha256,
    the HostStore-manifest wire format: plain named arrays). The seal is
    NOT a commit — the sharded-checkpoint manifest still names the old
    layout, so a crash between seal and the new layout's first commit
    leaves only orphan handoff files for :func:`discard_handoffs`."""
    files = []
    for k, states in enumerate(shard_states):
        spec = _faults.decision("reshard.handoff", shard=k, at_pos=at_pos)
        f = handoff_path(path, k)
        arrays = _flatten(states)
        hmeta = {"layout": dict(layout), "at_pos": int(at_pos), "shard": k,
                 _META_SHA: _digest_map(arrays),
                 _META_PATHS: _leaf_paths(states)}
        raw = _to_npz_bytes(_serialize(arrays, hmeta))
        if spec is not None:
            if spec.kind == "torn":
                _write_torn(f, raw, spec)
            raise _faults.InjectedFault(
                spec.message or f"injected reshard.handoff fault at {f}")
        _atomic_write_bytes(f, raw)
        files.append(f)
    return files


def discard_handoffs(path: str) -> list:
    """Drop every in-flight handoff manifest under ``path`` (the restore
    rule: a checkpoint that lands mid-handoff discards the seal — replay
    re-derives the move deterministically at the same barrier). Returns the
    discarded file names."""
    import glob
    stem = resolve_path(path)[:-len(".npz")]
    dropped = []
    for f in sorted(glob.glob(stem + ".handoff*.npz")):
        try:
            os.unlink(f)
            dropped.append(os.path.basename(f))
        except OSError:
            pass
    return dropped


def load_chain(chain: CompiledChain, path: str) -> dict:
    """Restore states in place; returns the saved metadata dict.

    When ``path`` has a lineage manifest (``save_chain(..., keep=K)``), walks
    the entries newest→oldest and restores the newest checkpoint that passes
    verification — a torn or corrupt latest file falls back to the previous
    commit (journaled as ``checkpoint_fallback``) instead of failing the
    restore. Without a manifest, a single invalid file raises
    :class:`CheckpointCorrupt` (or ``KeyError`` for a chain mismatch)."""
    return _walk_lineage(
        path, lambda f, sha: _restore_file(chain, f, expect_file_sha=sha))
