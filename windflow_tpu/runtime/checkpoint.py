"""Checkpoint / resume of pipeline state.

The reference has NO checkpointing (SURVEY §5: all operator state — keyMaps, archives,
FlatFATs — is in-memory and lost at exit). Here every operator's state is a pytree of
device arrays threaded through the compiled step, so checkpointing is structural:
``save_chain`` snapshots each operator's state (plus stream-position metadata) to an
``.npz``; ``load_chain`` restores it. Works for any CompiledChain (and therefore any
Pipeline / PipeGraph segment).
"""

from __future__ import annotations

import json
from typing import Any, Dict

import jax
import numpy as np

from .pipeline import CompiledChain


def _flatten(states) -> Dict[str, np.ndarray]:
    out = {}
    for i, st in enumerate(states):
        leaves, _ = jax.tree.flatten(st)
        for j, leaf in enumerate(leaves):
            out[f"op{i}_leaf{j}"] = np.asarray(leaf)
    return out


def save_chain(chain: CompiledChain, path: str, *, meta: dict = None) -> None:
    arrays = _flatten(chain.states)
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta or {}).encode(), dtype=np.uint8)
    np.savez(path, **arrays)


def load_chain(chain: CompiledChain, path: str) -> dict:
    """Restore states in place; returns the saved metadata dict."""
    data = np.load(path)
    new_states = []
    for i, st in enumerate(chain.states):
        leaves, treedef = jax.tree.flatten(st)
        restored = [jax.numpy.asarray(data[f"op{i}_leaf{j}"])
                    for j in range(len(leaves))]
        new_states.append(jax.tree.unflatten(treedef, restored))
    chain.states = new_states
    raw = data.get("__meta__")
    return json.loads(bytes(raw).decode()) if raw is not None else {}
