"""Checkpoint / resume of pipeline state, with durable lineage.

The reference has NO checkpointing (SURVEY §5: all operator state — keyMaps, archives,
FlatFATs — is in-memory and lost at exit). Here every operator's state is a pytree of
device arrays threaded through the compiled step, so checkpointing is structural:
``save_chain`` snapshots each operator's state (plus stream-position metadata) to an
``.npz``; ``load_chain`` restores it. Works for any CompiledChain (and therefore any
Pipeline / PipeGraph segment).

Durability hardening (the chaos-harness findings):

- **Atomic writes**: the ``.npz`` is written to a temp file in the target
  directory and ``os.replace``-d into place — a crash mid-write can never
  leave a torn file under the checkpoint's name.
- **Checksums**: ``__meta__`` carries a per-array sha256 map; ``load_chain``
  verifies every present array before touching the chain (bit-rot and
  tampering fail loudly as :class:`CheckpointCorrupt`, never a silent
  partial restore). Pre-checksum checkpoints load without verification.
- **Lineage** (``keep > 1``): successive saves rotate through
  ``<stem>.<seq>.npz`` files tracked by a ``<stem>.manifest.json`` (atomic,
  with whole-file sha256 per entry, pruned to the last ``keep``);
  ``load_chain`` walks the manifest newest→oldest and restores the newest
  *valid* checkpoint, so one torn/corrupt file degrades to the previous
  commit instead of losing the state entirely.
- ``path`` is resolved ONCE (``.npz`` appended when missing) and used for both
  save and load — ``save_chain("ckpt")`` / ``load_chain("ckpt")`` now agree.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
from typing import Dict, Optional

import jax
import numpy as np

from . import faults as _faults
from ..observability import journal as _journal
from .pipeline import CompiledChain

#: reserved __meta__ keys (stripped from the dict load_chain returns)
_META_SHA = "__sha256__"
_META_SEQ = "__seq__"
#: per-op state-leaf KEY PATHS (jax.tree_util.keystr), written by every
#: save: restore matches leaves BY PATH, so a state layout that grew
#: interleaved fields (the tiered-state lap/ocnt/okey/... keys sort into
#: the middle of the dict flatten order) restores old leaves into the
#: right fields instead of positionally misassigning them
_META_PATHS = "__leafpaths__"


class CheckpointCorrupt(RuntimeError):
    """A checkpoint file is torn, truncated, or fails checksum verification
    (and, for a lineage, no older entry is valid either)."""


def resolve_path(path: str) -> str:
    """THE path normalization, shared by save and load: ``np.savez`` appends
    ``.npz`` when the suffix is missing, so resolve it once up front."""
    path = str(path)
    return path if path.endswith(".npz") else path + ".npz"


def manifest_path(path: str) -> str:
    return resolve_path(path)[:-len(".npz")] + ".manifest.json"


def _flatten(states) -> Dict[str, np.ndarray]:
    out = {}
    for i, st in enumerate(states):
        leaves, _ = jax.tree.flatten(st)
        for j, leaf in enumerate(leaves):
            out[f"op{i}_leaf{j}"] = np.asarray(leaf)
    return out


def _chain_arrays(chain: CompiledChain) -> Dict[str, np.ndarray]:
    """Device states + (for tiered operators) the settled cold-tier
    manifests — ONE array namespace, so the per-array sha256 map and the
    atomic write cover the host stores exactly like device state."""
    chain.tier_settle()
    out = _flatten(chain.states)
    out.update(chain.tier_manifests())
    return out


def _leaf_paths(states) -> Dict[str, list]:
    """``{"op<i>": [keystr, ...]}`` of every state leaf, in flatten order."""
    out = {}
    for i, st in enumerate(states):
        kl, _ = jax.tree_util.tree_flatten_with_path(st)
        out[f"op{i}"] = [jax.tree_util.keystr(p) for p, _leaf in kl]
    return out


def _digest_map(arrays: Dict[str, np.ndarray]) -> Dict[str, str]:
    """Per-array sha256 (dtype + shape + bytes) — per-array so the legacy
    grown-field tolerance (a checkpoint missing TRAILING leaves of a state
    that later grew) keeps working: only present arrays are verified."""
    out = {}
    for k in sorted(arrays):
        h = hashlib.sha256()
        a = np.ascontiguousarray(arrays[k])
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
        out[k] = h.hexdigest()
    return out


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _serialize(arrays: Dict[str, np.ndarray], meta: dict) -> Dict[str, np.ndarray]:
    out = dict(arrays)
    out["__meta__"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    return out


def _to_npz_bytes(arrays: Dict[str, np.ndarray]) -> bytes:
    """Serialize once to memory — the same bytes feed the atomic write AND the
    manifest's whole-file sha256, so a lineage save never re-reads the file it
    just wrote."""
    import io
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _atomic_write_bytes(path: str, raw: bytes) -> None:
    """Write to a temp file in the target directory, then ``os.replace`` —
    readers see the old file or the new file, never a torn one (the
    pre-hardening ``np.savez(path)`` could be interrupted mid-write)."""
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(raw)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _write_torn(path: str, raw: bytes, spec) -> None:
    """Injected torn write: leave HALF the serialized bytes under the real
    checkpoint name (simulating a crashed non-atomic writer / bit rot), then
    raise — what `load_chain` must survive via the lineage fallback."""
    with open(path, "wb") as f:
        f.write(raw[:max(1, len(raw) // 2)])
    raise _faults.InjectedFault(
        spec.message or f"injected torn checkpoint write at {path}")


def _read_manifest(mpath: str) -> Optional[dict]:
    try:
        with open(mpath) as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except (json.JSONDecodeError, OSError):
        return None      # a torn manifest degrades to single-file behavior


def _write_manifest(mpath: str, man: dict) -> None:
    d = os.path.dirname(mpath) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(mpath) + ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(man, f, indent=1)
        os.replace(tmp, mpath)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def save_chain(chain: CompiledChain, path: str, *, meta: dict = None,
               keep: int = 1) -> str:
    """Snapshot ``chain.states`` (+ ``meta``) to ``path`` atomically; returns
    the file actually written.

    ``keep > 1`` enables lineage mode: each save writes a new
    ``<stem>.<seq>.npz`` and updates ``<stem>.manifest.json`` (entries carry a
    whole-file sha256; pruned to the last ``keep`` files). ``load_chain`` on
    the same ``path`` then restores the newest valid entry."""
    path = resolve_path(path)
    arrays = _chain_arrays(chain)
    full_meta = dict(meta or {})
    full_meta[_META_SHA] = _digest_map(arrays)
    full_meta[_META_PATHS] = _leaf_paths(chain.states)
    spec = _faults.decision("checkpoint.save", path=path)
    if keep <= 1:
        raw = _to_npz_bytes(_serialize(arrays, full_meta))
        if spec is not None:
            if spec.kind == "torn":
                _write_torn(path, raw, spec)
            raise _faults.InjectedFault(
                spec.message or f"injected checkpoint.save fault at {path}")
        _atomic_write_bytes(path, raw)
        _faults.bump("checkpoint_saves")
        return path
    # -- lineage mode ------------------------------------------------------
    mpath = manifest_path(path)
    man = _read_manifest(mpath) or {"version": 1, "stem": os.path.basename(path),
                                    "entries": []}
    entries = man["entries"]
    seq = (entries[-1]["seq"] + 1) if entries else 0
    full_meta[_META_SEQ] = seq
    file = f"{path[:-len('.npz')]}.{seq:06d}.npz"
    raw = _to_npz_bytes(_serialize(arrays, full_meta))
    if spec is not None:
        if spec.kind == "torn":
            # crash mid-write: the torn file exists but never reaches the
            # manifest — exactly the artifact restore must tolerate
            _write_torn(file, raw, spec)
        raise _faults.InjectedFault(
            spec.message or f"injected checkpoint.save fault at {file}")
    _atomic_write_bytes(file, raw)
    entries.append({"file": os.path.basename(file), "seq": seq,
                    "sha256": hashlib.sha256(raw).hexdigest(),
                    # lineage metadata only — never read back on the replay
                    # path, so a wall timestamp cannot skew recovery
                    "wall": time.time(),      # wf-lint: allow[wall-clock]
                    "meta": {k: v for k, v in (meta or {}).items()}})
    while len(entries) > keep:
        old = entries.pop(0)
        try:
            os.unlink(os.path.join(os.path.dirname(path) or ".", old["file"]))
        except OSError:
            pass
    _write_manifest(mpath, man)
    _faults.bump("checkpoint_saves")
    return file


def _restore_file(chain: CompiledChain, path: str,
                  expect_file_sha: Optional[str] = None) -> dict:
    """Verify + restore one checkpoint file in place; returns the user meta.

    Legacy compatibility: a checkpoint written before a state dataclass grew a
    trailing field (e.g. Win_SeqFFAT's ``dropped_old`` counter) is short by
    those leaves — registered dataclasses flatten in field order, so the
    missing keys are exactly the tail. Absent leaves keep the chain's
    freshly-initialized value (zeros for counters) instead of raising — the
    same stance as the supervisor's legacy-``wm`` mapping."""
    if expect_file_sha is not None and _file_sha256(path) != expect_file_sha:
        raise CheckpointCorrupt(
            f"checkpoint {path!r} fails its manifest sha256 — torn or corrupt")
    try:
        data = np.load(path)
    except Exception as e:                 # noqa: BLE001 — torn zip/npz
        raise CheckpointCorrupt(
            f"checkpoint {path!r} is unreadable ({type(e).__name__}: {e})"
        ) from e
    raw = data.get("__meta__")
    meta = json.loads(bytes(raw).decode()) if raw is not None else {}
    sha_map = meta.pop(_META_SHA, None)
    paths_map = meta.pop(_META_PATHS, None)
    meta.pop(_META_SEQ, None)
    present = set(getattr(data, "files", []))
    if sha_map:
        for k in sorted(present - {"__meta__"}):
            want = sha_map.get(k)
            if want is not None and _digest_map({k: data[k]})[k] != want:
                raise CheckpointCorrupt(
                    f"checkpoint {path!r}: array {k} fails its sha256 — "
                    f"corrupt data, refusing a silent partial restore")
    new_states = []
    for i, st in enumerate(chain.states):
        leaves, treedef = jax.tree.flatten(st)
        saved_paths = (paths_map or {}).get(f"op{i}")
        if saved_paths is not None:
            # path-aware restore (every modern save): match leaves BY KEY
            # PATH, so a layout that grew interleaved fields (tiered-state
            # lap/okey/... sort into the middle of the dict flatten order)
            # restores each saved leaf into its true field. Fields absent
            # from the file keep their fresh init (tier fields restoring a
            # pre-tiering save); saved fields the chain lacks are skipped
            # (the legacy trailing-leaf tolerance, by name: an event_time-
            # off or untiered chain restoring a richer save keeps exactly
            # the fields it has)
            kl, _ = jax.tree_util.tree_flatten_with_path(st)
            cur = [jax.tree_util.keystr(p) for p, _leaf in kl]
            idx = {p: j for j, p in enumerate(saved_paths)}
            # the positional branch's trailing-tolerance, kept by saved
            # index: a missing TRAILING run of saved arrays is the legacy
            # grown-field case (those fields keep their init); a GAP is a
            # mismatched/tampered file and stays a loud error
            have = [f"op{i}_leaf{j}" in present
                    for j in range(len(saved_paths))]
            n_present = sum(have)
            if have[n_present:] != [False] * (len(saved_paths) - n_present):
                j_bad = have.index(False)
                raise KeyError(
                    f"checkpoint {path!r} is missing op{i}_leaf{j_bad} "
                    f"({saved_paths[j_bad]}) but has later leaves of "
                    f"op{i} — mismatched chain or truncated file")
            restored = [
                jax.numpy.asarray(data[f"op{i}_leaf{idx[p]}"])
                if p in idx and have[idx[p]] else leaves[j]
                for j, p in enumerate(cur)]
            new_states.append(jax.tree.unflatten(treedef, restored))
            continue
        # legacy file (no path map): positional restore. Refuse it for a
        # tiered operator — the tier fields interleave into the flatten
        # order, so positional matching would silently misassign arrays
        if any(j == i for j in getattr(chain, "_tier_ops", ())):
            raise KeyError(
                f"checkpoint {path!r} predates leaf-path metadata and "
                f"op{i} has tiered state — a positional restore would "
                f"misassign fields; re-save the checkpoint (or restore "
                f"into an untiered chain first)")
        have = [f"op{i}_leaf{j}" in present for j in range(len(leaves))]
        # only a missing TRAILING suffix of a present state is the legacy
        # grown-field case; a gap (missing leaf followed by a present one) or
        # an op whose state is entirely absent means a mismatched or truncated
        # checkpoint — keep the loud KeyError for those
        n_present = sum(have)
        if leaves and n_present == 0:
            raise KeyError(
                f"checkpoint {path!r} has no op{i}_leaf* keys for a stateful "
                f"operator — mismatched chain or truncated file")
        if have[n_present:] != [False] * (len(leaves) - n_present):
            j_bad = have.index(False)
            raise KeyError(
                f"checkpoint {path!r} is missing op{i}_leaf{j_bad} but has "
                f"later leaves of op{i} — mismatched chain or truncated file")
        restored = [jax.numpy.asarray(data[f"op{i}_leaf{j}"]) if have[j]
                    else leaves[j] for j in range(len(leaves))]
        new_states.append(jax.tree.unflatten(treedef, restored))
    chain.states = new_states
    # tiered cold tiers: restore from the tier* namespace (a pre-tiering
    # checkpoint has none — the fresh empty store stands, and any in-flight
    # spill copies of the failed attempt are discarded either way)
    chain.tier_restore_manifests(
        {k: data[k] for k in present if k.startswith("tier")})
    return meta


def load_chain(chain: CompiledChain, path: str) -> dict:
    """Restore states in place; returns the saved metadata dict.

    When ``path`` has a lineage manifest (``save_chain(..., keep=K)``), walks
    the entries newest→oldest and restores the newest checkpoint that passes
    verification — a torn or corrupt latest file falls back to the previous
    commit (journaled as ``checkpoint_fallback``) instead of failing the
    restore. Without a manifest, a single invalid file raises
    :class:`CheckpointCorrupt` (or ``KeyError`` for a chain mismatch)."""
    path = resolve_path(path)
    _faults.fire("checkpoint.load", path=path)
    man = _read_manifest(manifest_path(path))
    if man and man.get("entries"):
        d = os.path.dirname(path) or "."
        last_err: Optional[Exception] = None
        skipped = []
        for ent in reversed(man["entries"]):
            f = os.path.join(d, ent["file"])
            try:
                meta = _restore_file(chain, f,
                                     expect_file_sha=ent.get("sha256"))
            except (CheckpointCorrupt, KeyError, OSError) as e:
                last_err = e
                skipped.append(ent["file"])
                _faults.bump("checkpoint_corrupt_skipped")
                _journal.record("checkpoint_invalid", path=f,
                                error=type(e).__name__)
                continue
            if skipped:
                _faults.bump("checkpoint_fallbacks")
                _journal.record("checkpoint_fallback", restored=ent["file"],
                                skipped=skipped)
            return meta
        raise CheckpointCorrupt(
            f"no valid checkpoint in lineage {path!r} "
            f"({len(man['entries'])} entries, all torn/corrupt)") from last_err
    return _restore_file(chain, path)
