"""Asynchronous device->host result shipping — the latency-critical sink path.

The reference's sink receives each window result over an in-memory queue and
timestamps receipt per result (YSB latency vector,
``src/yahoo_test_cpu/ysb_nodes.hpp:200-216``). On TPU the equivalent boundary is a
device->host transfer, and a *synchronous* fetch costs a full host<->device round
trip per batch (measured ~67 ms over a tunneled dev chip; ~100 us on a local PJRT
host) — paying it inline would gate the whole stream on the slowest link.

:class:`AsyncResultShipper` instead starts a non-blocking device->host copy the
moment a result batch is produced (``jax.Array.copy_to_host_async``) and harvests
completed copies later, so result transfer overlaps both device compute and other
transfers. Receipt latency becomes ``step_time + transfer_time + one round trip``
amortized across everything in flight, instead of one blocking round trip per
batch. This is the same overlap discipline as the reference GPU operators' D2H
``cudaMemcpyAsync`` + next-batch-flush protocol (``wf/win_seq_gpu.hpp:243-260,524``),
applied to the sink boundary.

Usage (see ``bench.py::bench_latency_curve``)::

    shipper = AsyncResultShipper(depth=4)
    for i, batch in enumerate(stream):
        out = step(batch)                       # async dispatch
        shipper.ship(out, tag=i)                # starts D2H copy, never blocks
        for rec in shipper.harvest():           # completed older results
            sink(rec.value, latency=rec.receipt_time - rec.ship_time)
    for rec in shipper.drain():                 # EOS
        sink(rec.value, ...)
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Iterator, List, Optional

import jax
import numpy as np


@dataclasses.dataclass
class ShippedResult:
    tag: Any              # caller's identifier (e.g. step index)
    value: Any            # pytree of np.ndarray, on host
    ship_time: float      # perf_counter at ship() (device result was available)
    receipt_time: float   # perf_counter when the host copy completed


class AsyncResultShipper:
    """Overlapped device->host shipping of small result batches.

    ``depth``: harvest() leaves this many newest results in flight (their copies
    may still be running); drain() collects everything.
    """

    def __init__(self, depth: int = 4):
        self.depth = int(depth)
        self._inflight: deque = deque()

    def ship(self, arrays: Any, tag: Any = None) -> None:
        """Start a non-blocking device->host copy of ``arrays`` (a pytree of
        jax.Array). Returns immediately."""
        for leaf in jax.tree.leaves(arrays):
            copy_async = getattr(leaf, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        self._inflight.append((time.perf_counter(), tag, arrays))

    def harvest(self, keep_inflight: Optional[int] = None) -> List[ShippedResult]:
        """Collect results older than the in-flight window. The copies of
        harvested results have had ``depth`` ship() calls of wall time to finish,
        so the final np.asarray is (amortized) a cheap completed-copy read."""
        keep = self.depth if keep_inflight is None else keep_inflight
        out: List[ShippedResult] = []
        while len(self._inflight) > keep:
            ship_t, tag, arrays = self._inflight.popleft()
            host = jax.tree.map(np.asarray, arrays)
            out.append(ShippedResult(tag=tag, value=host, ship_time=ship_t,
                                     receipt_time=time.perf_counter()))
        return out

    def drain(self) -> List[ShippedResult]:
        """EOS: collect everything still in flight."""
        return self.harvest(keep_inflight=0)

    def __len__(self) -> int:
        return len(self._inflight)
