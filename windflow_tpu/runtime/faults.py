"""Deterministic fault injection + the supervision hardening primitives.

The reference's entire failure model is ``exit(EXIT_FAILURE)`` (SURVEY §5);
the supervisor (``runtime/supervisor.py``) goes far beyond it — but a recovery
path that is never exercised is an assumption, not a capability. This module
makes failure a first-class, *testable* input to every driver:

- :class:`FaultPlan` / :class:`FaultInjector`: a seeded, fully deterministic
  schedule of faults at **named injection sites** threaded through the runtime
  (``source.next``, ``chain.step``, ``sink.consume``, ``checkpoint.save``,
  ``checkpoint.load``, ``queue.stall``). Programmatic (``faults=`` kwarg on the
  supervised/threaded drivers) or via the ``WF_FAULT_PLAN`` env (inline JSON or
  a path to a JSON file). Every injected fault is journaled through the
  observability EventJournal (``fault_injected`` events) together with the
  recovery it triggered, so a chaos run's artifact shows the full sequence.
- :func:`call_with_timeout`: the step watchdog — converts a hung device step
  into a detectable :class:`WatchdogTimeout` the supervisor recovers from.
- :func:`backoff_sleep`: exponential backoff with decorrelated jitter between
  restart attempts (sleep ~ ``U(base, 3*prev)`` capped), so a flapping device
  cannot be hammered in a tight restart loop.
- :class:`DeadLetterQueue`: the poison-batch quarantine target — a malformed
  input that keeps failing replay is routed here (in-memory, optional JSONL
  spill) and skipped instead of exhausting the restart budget.
- process-wide recovery counters (:func:`counters`) that flow into the
  observability ``MetricsRegistry`` snapshot and Prometheus exposition.

Injection sites cost one module-attribute load + ``None`` check when no
injector is active — the same stance as the event journal.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..observability import journal as _journal
from ..observability.names import RECOVERY_COUNTERS

#: the named injection sites threaded through the runtime drivers.
#: ``shard.kill`` fires before every per-shard push of the sharded
#: supervisors (ctx: shard=, pos= — ``where={"shard": 2}`` kills exactly
#: one shard's steps, the kill-one-of-N chaos drill); ``reshard.handoff``
#: fires inside the two-phase re-sharding handoff (kind="torn" leaves the
#: half-sealed handoff manifest behind, then raises — what restore must
#: discard and replay must re-derive).
SITES = ("source.next", "chain.step", "sink.consume",
         "checkpoint.save", "checkpoint.load", "queue.stall",
         "shard.kill", "reshard.handoff")

#: fault kinds: raise an InjectedFault / sleep stall_s (watchdog + queue-stall
#: exercise) / leave a half-written checkpoint behind, then raise (torn write)
KINDS = ("error", "stall", "torn")


class InjectedFault(RuntimeError):
    """Raised at an injection site by an active :class:`FaultInjector`."""


class WatchdogTimeout(RuntimeError):
    """A supervised step (or threaded stage) exceeded its watchdog timeout —
    a hang converted into a detectable, recoverable fault."""


@dataclasses.dataclass
class FaultSpec:
    """One scheduled fault. Matching conditions AND together:

    - ``at``: 1-based per-site occurrence indices (deterministic single shots);
    - ``where``: equality constraints on the call-site context (e.g.
      ``{"pos": 5}`` — fires **every** time batch position 5 is processed,
      which is how a deterministic poison batch is modelled);
    - ``p``: per-occurrence probability drawn from the plan's seeded RNG
      (chaos sweeps).

    With none of the three, the spec fires on the first occurrence only.
    ``max_fires`` bounds total fires (default: unlimited for ``where``/``p``
    specs, ``len(at)`` for ``at`` specs, 1 otherwise).
    """

    site: str
    kind: str = "error"
    at: Optional[Sequence[int]] = None
    where: Optional[Dict[str, Any]] = None
    p: float = 0.0
    stall_s: float = 0.05
    max_fires: Optional[int] = None
    message: str = ""

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(f"unknown fault site {self.site!r} "
                             f"(sites: {', '.join(SITES)})")
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(kinds: {', '.join(KINDS)})")
        if self.at is not None:
            self.at = tuple(int(a) for a in self.at)

    def _fire_bound(self) -> Optional[int]:
        if self.max_fires is not None:
            return int(self.max_fires)
        if self.at is not None:
            return len(self.at)
        if self.where is not None or self.p > 0.0:
            return None                      # unlimited
        return 1


class FaultPlan:
    """An ordered, seeded set of :class:`FaultSpec`. JSON round-trippable:

    ``{"seed": 7, "faults": [{"site": "chain.step", "at": [3]}, ...]}``
    """

    def __init__(self, faults: Sequence[FaultSpec] = (), *, seed: int = 0):
        self.faults = [f if isinstance(f, FaultSpec) else FaultSpec(**f)
                       for f in faults]
        self.seed = int(seed)

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "faults": [{k: v for k, v in dataclasses.asdict(f).items()
                        if v not in (None, "", 0.0) or k in ("site", "kind")}
                       for f in self.faults]})

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        obj = json.loads(text)
        if isinstance(obj, list):            # bare fault list shorthand
            obj = {"faults": obj}
        return cls(obj.get("faults", ()), seed=obj.get("seed", 0))

    @classmethod
    def from_env(cls, var: str = "WF_FAULT_PLAN") -> Optional["FaultPlan"]:
        """``WF_FAULT_PLAN`` = inline JSON (starts with ``{``/``[``) or a path
        to a JSON file; empty/unset = no plan."""
        raw = os.environ.get(var, "").strip()
        if not raw:
            return None
        if raw[0] in "[{":
            return cls.from_json(raw)
        with open(raw) as f:
            return cls.from_json(f.read())


class FaultInjector:
    """Evaluates a :class:`FaultPlan` at the runtime's injection sites.

    Deterministic: per-site occurrence counters plus one ``random.Random``
    seeded per spec from ``plan.seed`` — the same plan against the same
    (single-threaded) driver fires at the same occurrences every run.
    Thread-safe (the threaded driver fires from several stage threads).
    ``fired`` records every fire: ``(site, occurrence, kind, ctx)``.
    """

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.counts: Dict[str, int] = {}
        self.fired: List[Tuple[str, int, str, dict]] = []
        self._spec_fires = [0] * len(plan.faults)
        self._rngs = [random.Random(f"{plan.seed}/{i}/{s.site}")
                      for i, s in enumerate(plan.faults)]
        self._lock = threading.Lock()

    def decision(self, site: str, **ctx) -> Optional[FaultSpec]:
        """Count one occurrence of ``site`` and return the matching spec (or
        None) WITHOUT acting on it — call sites with special semantics (torn
        checkpoint writes) implement the fault themselves."""
        with self._lock:
            n = self.counts.get(site, 0) + 1
            self.counts[site] = n
            for i, spec in enumerate(self.plan.faults):
                if spec.site != site:
                    continue
                bound = spec._fire_bound()
                if bound is not None and self._spec_fires[i] >= bound:
                    continue
                if spec.at is not None and n not in spec.at:
                    continue
                if spec.where is not None and not all(
                        ctx.get(k) == v for k, v in spec.where.items()):
                    continue
                if spec.p > 0.0 and self._rngs[i].random() >= spec.p:
                    continue
                self._spec_fires[i] += 1
                self.fired.append((site, n, spec.kind, dict(ctx)))
                bump("faults_injected")
                _journal.record("fault_injected", site=site, occurrence=n,
                                kind=spec.kind, **ctx)
                return spec
        return None

    def fire(self, site: str, **ctx) -> None:
        """Count one occurrence; act on a match: ``error`` raises
        :class:`InjectedFault`, ``stall`` sleeps ``stall_s`` (the hang the
        watchdog must catch), ``torn`` raises (call sites that can leave a
        torn artifact behind use :meth:`decision` instead)."""
        spec = self.decision(site, **ctx)
        if spec is None:
            return
        if spec.kind == "stall":
            time.sleep(spec.stall_s)
            return
        with self._lock:
            # under the lock: another stage thread may be bumping this
            # site's counter concurrently (surfaced by the WF260 lint); the
            # message's occurrence number may still trail the decision by
            # design — it is diagnostic text, never replay state
            occurrence = self.counts[site]
        raise InjectedFault(
            spec.message or f"injected {spec.kind} fault at {site} "
            f"(occurrence {occurrence}, ctx {ctx})")


# ------------------------------------------------------------- active injector

_active: Optional[FaultInjector] = None


def set_active(inj: Optional[FaultInjector]) -> None:
    global _active
    _active = inj


def get_active() -> Optional[FaultInjector]:
    return _active


def resolve(arg) -> Optional[FaultInjector]:
    """Normalize a driver's ``faults=`` argument: None consults
    ``WF_FAULT_PLAN``; False forces off; a plan/injector passes through."""
    if arg is False:
        return None
    if isinstance(arg, FaultInjector):
        return arg
    if isinstance(arg, FaultPlan):
        return FaultInjector(arg)
    if isinstance(arg, str):
        return FaultInjector(FaultPlan.from_json(arg))
    plan = FaultPlan.from_env()
    return FaultInjector(plan) if plan is not None else None


@contextlib.contextmanager
def activate(inj: Optional[FaultInjector]):
    """Install ``inj`` as the active injector for the block; None leaves the
    current (possibly externally installed) injector untouched."""
    if inj is None:
        yield None
        return
    prev = get_active()
    set_active(inj)
    try:
        yield inj
    finally:
        set_active(prev)


def fire(site: str, **ctx) -> None:
    """Module-level injection site: one attribute load + None check when no
    injector is active — safe in per-batch paths."""
    inj = _active
    if inj is not None:
        inj.fire(site, **ctx)


def decision(site: str, **ctx) -> Optional[FaultSpec]:
    inj = _active
    if inj is not None:
        return inj.decision(site, **ctx)
    return None


# --------------------------------------------------------- recovery counters

#: canonical counter names live in the observability registry so the static
#: linter can check every ``bump("...")`` call site against one source of truth
_COUNTER_NAMES = RECOVERY_COUNTERS
_counters: Dict[str, float] = {k: 0 for k in _COUNTER_NAMES}
_counters_lock = threading.Lock()


def bump(name: str, n: float = 1) -> None:
    """Increment a process-wide recovery counter (surfaces in the metrics
    registry snapshot under ``recovery`` and as
    ``windflow_recovery_<name>_total`` in the Prometheus exposition)."""
    with _counters_lock:
        _counters[name] = _counters.get(name, 0) + n


def counters() -> Dict[str, float]:
    with _counters_lock:
        return dict(_counters)


def reset_counters() -> None:
    with _counters_lock:
        for k in list(_counters):
            _counters[k] = 0


# ------------------------------------------------------------- step watchdog

def call_with_timeout(fn, timeout: Optional[float], *, stage: str = "step",
                      pre=None):
    """Run ``pre()`` (the injection point — stall faults sleep there) then
    ``fn()``, enforcing ``timeout`` seconds wall-clock when set.

    With a timeout the call runs in a transient worker thread; if it does not
    finish in time the worker is *abandoned* (flagged so it will not run ``fn``
    after waking from a pre-step stall — a late mutation of restored state
    would corrupt recovery) and :class:`WatchdogTimeout` is raised — the
    supervisor treats it like any other step fault and replays. A step hung
    *inside* the device program cannot be interrupted, only detected; the
    abandoned thread is a daemon.

    The raised :class:`WatchdogTimeout` carries the abandoned thread as
    ``.worker``: callers that restore shared state afterwards MUST join it
    with a grace period first (the supervisors join for ``timeout`` more
    seconds) — a slow-but-alive step then lands its mutation BEFORE the
    restore overwrites it, instead of racing the replay. A genuinely hung
    step never returns from the device and so never mutates."""
    if not timeout:
        if pre is not None:
            pre()
        return fn()
    box: dict = {}
    abandoned = threading.Event()

    def worker():
        try:
            if pre is not None:
                pre()
            if abandoned.is_set():
                return                     # watchdog gave up: leave state alone
            box["value"] = fn()
        except BaseException as e:         # noqa: BLE001 — re-raised below
            box["error"] = e

    # role DRIVER, not watchdog: the step worker runs the supervised step ON
    # LOAN from the driver, which blocks in join() below until it finishes
    # or is abandoned — and an abandoned worker is flagged to never run fn,
    # then joined with a grace period before any restore (the protocol
    # callers must follow, see join_abandoned_worker).  Driver-thread-only
    # APIs (Ordering_Node.settle, TieredTable maintenance) are therefore
    # legal inside a supervised step.
    t = threading.Thread(target=worker, daemon=True,  # wf-lint: thread-role[driver]
                         name=f"wf-watchdog-{stage}")
    t.start()
    t.join(timeout)
    if t.is_alive():
        abandoned.set()
        bump("watchdog_timeouts")
        _journal.record("watchdog_timeout", stage=stage, timeout_s=timeout)
        err = WatchdogTimeout(
            f"{stage} exceeded the {timeout}s watchdog timeout")
        err.worker = t
        raise err
    if "error" in box:
        raise box["error"]
    return box.get("value")


def join_abandoned_worker(exc, grace: Optional[float]) -> None:
    """Before restoring state after a :class:`WatchdogTimeout`, give the
    abandoned worker ``grace`` seconds to finish: a transiently slow (not
    hung) step then completes its state mutation BEFORE the restore, so the
    replay never races a late writer. No-op for other exceptions."""
    w = getattr(exc, "worker", None)
    if w is not None and grace:
        w.join(grace)


def drain_queue_to_sentinel(q, sentinel, timeout_s: float = 30.0,
                            poll_s: float = 0.0005) -> bool:
    """Keep popping ``q``, discarding data items, until ``sentinel`` arrives —
    THE failure-path protocol of the threaded drivers: a dead consumer must
    drain its input ring so the upstream producer (blocked on a full SPSC
    ring) can finish and send its own EOS. The producer's ``finally`` always
    sends the sentinel, so ``timeout_s`` only bounds pathological cases
    (a killed producer thread). Returns False on timeout."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        ok, item = q.pop(spin=64, max_yields=0)
        if ok:
            if item is sentinel:
                return True
            continue
        time.sleep(poll_s)
    return False


# ------------------------------------------------- backoff with decorrelated jitter

def backoff_sleep(rng: random.Random, prev: float, base: float,
                  cap: float, *, attempt: int = 0) -> float:
    """One decorrelated-jitter backoff step: sleep ``min(cap, U(base,
    3*prev))`` and return the slept duration (feed it back as ``prev``).
    ``base <= 0`` disables (returns 0 without sleeping) — restart storms
    against a flapping device are throttled, deterministic tests opt out."""
    if base <= 0 or cap <= 0:
        return 0.0
    s = min(cap, rng.uniform(base, max(base, prev * 3.0)))
    bump("backoff_sleeps")
    bump("backoff_seconds", s)
    _journal.record("backoff", sleep_s=round(s, 6), attempt=attempt)
    time.sleep(s)
    return s


# ------------------------------------------------------------ dead letters

class DeadLetterQueue:
    """Quarantine target for poison batches: when supervised replay keeps
    failing at the same committed position, the offending batch lands here
    (host copies — bounded by ``max_entries``) and the stream moves on.
    ``spill_path`` appends one JSON summary line per entry (ids + error, not
    the array payload) so a long-running service keeps a durable record."""

    def __init__(self, spill_path: Optional[str] = None,
                 max_entries: int = 1024):
        self.spill_path = spill_path
        self.max_entries = int(max_entries)
        self.entries: List[dict] = []      # wf-lint: guarded-by[_lock]
        self.dropped = 0                   # entries evicted past max_entries
        self._lock = threading.Lock()

    def put(self, batch, *, pos, error=None, driver: str = "") -> dict:
        import numpy as np
        entry = {"pos": pos, "driver": driver, "wall": time.time(),
                 "error": (f"{type(error).__name__}: {error}"[:500]
                           if error is not None else None)}
        if batch is not None:
            try:
                import jax
                host = jax.tree.map(np.asarray, batch)
                v = np.asarray(host.valid)
                entry["n_valid"] = int(v.sum())
                entry["ids"] = np.asarray(host.id)[v][:32].tolist()
                entry["batch"] = host
            except Exception:              # noqa: BLE001 — never lose the record
                entry["n_valid"] = None
        with self._lock:
            self.entries.append(entry)
            if len(self.entries) > self.max_entries:
                self.entries.pop(0)
                self.dropped += 1
            if self.spill_path:
                summary = {k: v for k, v in entry.items() if k != "batch"}
                with open(self.spill_path, "a") as f:
                    f.write(json.dumps(summary, default=str) + "\n")
        return entry

    def __len__(self) -> int:
        with self._lock:
            return len(self.entries)
